"""On-device resharding primitives for the inter-stage handoff.

The device-resident edge contract (:mod:`rnb_tpu.handoff`) re-homes a
committed producer array onto the consumer's device/sharding without
ever materializing host memory. This module owns the *how*:

* :func:`reshard` — the one entry the edge calls: ``jax.device_put``
  onto the target device or ``NamedSharding`` (ICI on real hardware,
  a buffer copy on the virtual-CPU mesh), with a remote-DMA fast path
  engaged when (a) the platform is a real TPU and (b) the move is a
  pure ring shift of a one-axis-sharded array across its mesh — the
  stage-boundary pattern of a stage-partitioned pipeline, where stage
  i's cores hand their shard to stage i+1's neighboring cores.
* :func:`ring_shift` — the underlying collective, in two bodies with
  one contract: a **Pallas** ``make_async_remote_copy`` kernel (each
  core DMAs its whole local shard straight into its neighbor's HBM —
  no gather, no host, no XLA collective scheduling) gated to real TPU
  hardware, and a ``shard_map`` + ``lax.ppermute`` **CPU-testable
  twin** that compiles on the 8-virtual-device harness so tier-1 can
  pin the semantics (``ring_shift(x, k)`` == ``jnp.roll`` by ``k``
  shards along the sharded axis) without touching a TPU.
* :func:`ring_shift_amount` — the pattern detector: given source and
  target shardings, the shift ``k`` that turns one placement into the
  other, or ``None`` when the move is not a ring shift (then
  ``device_put`` is the honest path).
* :func:`ring_all_gather` / :func:`ring_psum_scatter` — the intra-stage
  sharding collectives (rnb_tpu.parallel.shardplan): both are built on
  the SAME one-step ring movement as :func:`ring_shift` — n-1 neighbor
  hops, each hop the Pallas remote-DMA kernel on real TPU or the
  ``lax.ppermute`` twin everywhere else — composed with local
  slice/update (gather) or slice/add (reduce-scatter) arithmetic.
  The all-gather is pure data movement (chunk placement), so its
  result is BITWISE the concatenation of the shards — the property
  the sharded stage forward's logit bit-parity rests on. The
  reduce-scatter adds in ring order, which is a *different* float
  summation order than a tree psum; it is shipped for the TPU
  reduction path and pinned against a jnp reference on exactly
  representable values (tests/test_handoff.py), never used where
  bit-parity against an unsharded forward is claimed.

Kernel lineage: the Pallas distributed right-permute exemplar
(SNIPPETS.md [1]/[3]; jax.dev pallas/tpu/distributed) — semaphore
pair in scratch, ``memory_space=ANY`` refs, ``DeviceIdType.MESH``
neighbor addressing.
"""

from __future__ import annotations

from typing import Optional, Tuple

from rnb_tpu.utils.lazy_jax import jax_numpy as _jax_numpy


def dma_available() -> bool:
    """Is the Pallas remote-DMA path usable? Real TPU backends only —
    interpret mode cannot emulate cross-device semaphores, and the
    CPU twin exists precisely so everything else stays testable."""
    jax, _ = _jax_numpy()
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def _mesh_axis(mesh) -> Optional[str]:
    """The mesh's single axis name, or None for multi-axis meshes
    (the ring-shift pattern is defined over one ring)."""
    names = tuple(mesh.axis_names)
    return names[0] if len(names) == 1 else None


def ring_shift_amount(src_sharding, dst_sharding) -> Optional[int]:
    """The ring shift ``k`` (in device positions, 1 <= k < n) that
    maps the source placement onto the target placement, or None when
    the move is not a pure ring shift.

    Pattern: both are ``NamedSharding`` s with equal specs over
    single-axis meshes of the same size, and the target mesh's device
    ring is the source's rotated by ``k`` — then "reshard src→dst"
    moves every shard to the device ``k`` positions along the ring,
    which is exactly one neighbor-DMA per core.
    """
    import numpy as np
    for s in (src_sharding, dst_sharding):
        if s is None or not hasattr(s, "mesh") or not hasattr(s, "spec"):
            return None
    src_mesh, dst_mesh = src_sharding.mesh, dst_sharding.mesh
    axis = _mesh_axis(src_mesh)
    if axis is None or _mesh_axis(dst_mesh) != axis:
        return None
    if tuple(src_sharding.spec) != tuple(dst_sharding.spec):
        return None
    src_devs = list(np.ravel(src_mesh.devices))
    dst_devs = list(np.ravel(dst_mesh.devices))
    n = len(src_devs)
    if n < 2 or len(dst_devs) != n:
        return None
    for k in range(1, n):
        if dst_devs == src_devs[k:] + src_devs[:k]:
            return k
    return None


def _pallas_shift_body(axis_name: str, n: int, shift: int):
    """The Pallas remote-copy body for one core: DMA the whole local
    shard into the neighbor ``shift`` positions along the ring. Gated
    to real TPU by the caller (``dma_available``)."""
    import jax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    from jax import lax

    def kernel(input_ref, output_ref, send_sem, recv_sem):
        my_id = lax.axis_index(axis_name)
        neighbor = lax.rem(my_id + shift, n)
        copy = pltpu.make_async_remote_copy(
            src_ref=input_ref,
            dst_ref=output_ref,
            send_sem=send_sem,
            recv_sem=recv_sem,
            device_id=(neighbor,),
            device_id_type=pltpu.DeviceIdType.MESH,
        )
        copy.start()
        copy.wait()

    def body(x_shard):
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=0,
            in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
            out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
            scratch_shapes=[pltpu.SemaphoreType.DMA] * 2,
        )
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct(x_shard.shape, x_shard.dtype),
            grid_spec=grid_spec,
        )(x_shard)

    return body


def _ppermute_shift_body(axis_name: str, n: int, shift: int):
    """The CPU-testable twin: the identical shard movement spelled as
    a ``lax.ppermute`` collective, compiled by the stock CPU backend
    so tier-1 pins the contract the TPU kernel must honor."""
    from jax import lax

    perm = [(i, (i + shift) % n) for i in range(n)]

    def body(x_shard):
        return lax.ppermute(x_shard, axis_name, perm)

    return body


def _one_step_shift_body(axis_name: str, n: int, use_pallas: bool):
    """The shared ring primitive both collectives below ride: move
    every core's buffer to its +1 neighbor — the Pallas remote-DMA
    kernel on real TPU, the ppermute twin everywhere else."""
    return (_pallas_shift_body(axis_name, n, 1) if use_pallas
            else _ppermute_shift_body(axis_name, n, 1))


def ring_all_gather_body(axis_name: str, n: int, axis: int = -1,
                         use_pallas: bool = False):
    """Per-core body (usable inside an enclosing ``shard_map``): local
    shard -> the full concatenation along ``axis``, assembled by n-1
    one-step ring hops. Pure movement — each global chunk lands at
    ``chunk_index * chunk`` exactly once, so the result is bitwise the
    unsharded array on every core."""
    from jax import lax
    import jax.numpy as jnp

    shift = _one_step_shift_body(axis_name, n, use_pallas)

    def body(x_shard):
        if n == 1:
            return x_shard
        ax = axis % x_shard.ndim
        chunk = x_shard.shape[ax]
        idx = lax.axis_index(axis_name)
        full = list(x_shard.shape)
        full[ax] = chunk * n
        out = lax.dynamic_update_slice_in_dim(
            jnp.zeros(full, x_shard.dtype), x_shard, idx * chunk,
            axis=ax)
        buf = x_shard
        for s in range(1, n):
            buf = shift(buf)
            # after s hops this core holds the shard that started on
            # core (idx - s) mod n — place it at that chunk's offset
            src = lax.rem(idx - s + n, n)
            out = lax.dynamic_update_slice_in_dim(out, buf, src * chunk,
                                                  axis=ax)
        return out

    return body


def ring_psum_scatter_body(axis_name: str, n: int, axis: int = -1,
                           use_pallas: bool = False):
    """Per-core body: full-width local operand -> this core's chunk of
    the cross-core elementwise sum (``lax.psum_scatter`` semantics),
    as n-1 one-step ring hops each followed by one local chunk add.
    Ring order sums left-to-right around the ring — a different float
    association than a tree reduction (see module docstring)."""
    from jax import lax

    shift = _one_step_shift_body(axis_name, n, use_pallas)

    def body(x_local):
        ax = axis % x_local.ndim
        width = x_local.shape[ax]
        if width % n:
            raise ValueError(
                "ring_psum_scatter: axis %d extent %d not divisible "
                "by %d ring members" % (ax, width, n))
        if n == 1:
            return x_local
        chunk = width // n
        idx = lax.axis_index(axis_name)

        def piece(m):
            return lax.dynamic_slice_in_dim(x_local, m * chunk, chunk,
                                            axis=ax)

        # the accumulator seeded on core j ends on core j+n-1 carrying
        # chunk (j-1) mod n the whole way: core j seeds chunk j-1, and
        # at hop s adds chunk (j-1-s) mod n to the partial it received
        acc = piece(lax.rem(idx - 1 + n, n))
        for s in range(1, n):
            acc = shift(acc)
            acc = acc + piece(lax.rem(idx - 1 - s + 2 * n, n))
        return acc

    return body


def ring_all_gather(x, mesh, axis_name: Optional[str] = None,
                    axis: int = -1, use_pallas: Optional[bool] = None):
    """Standalone entry: ``x`` sharded along ``axis`` over the mesh
    ring -> the same *value* fully replicated on every core (bitwise
    the unsharded array). ``use_pallas`` defaults to
    :func:`dma_available`."""
    jax, _ = _jax_numpy()
    try:
        from jax.experimental.shard_map import shard_map
    except ImportError:
        shard_map = jax.shard_map
    from jax.sharding import PartitionSpec

    if axis_name is None:
        axis_name = _mesh_axis(mesh)
        if axis_name is None:
            raise ValueError("ring_all_gather needs a single-axis mesh "
                             "or an explicit axis_name")
    n = int(mesh.shape[axis_name])
    ax = axis % x.ndim
    if x.shape[ax] % n:
        raise ValueError(
            "ring_all_gather: axis %d extent %d not divisible by %d "
            "ring members" % (ax, x.shape[ax], n))
    if use_pallas is None:
        use_pallas = dma_available()
    in_spec = [None] * x.ndim
    in_spec[ax] = axis_name
    fn = shard_map(ring_all_gather_body(axis_name, n, axis=ax,
                                        use_pallas=use_pallas),
                   mesh=mesh, in_specs=PartitionSpec(*in_spec),
                   out_specs=PartitionSpec(), check_rep=False)
    return jax.jit(fn)(x)


def ring_psum_scatter(x, mesh, axis_name: Optional[str] = None,
                      axis: int = -1,
                      use_pallas: Optional[bool] = None):
    """Standalone entry: ``x`` carries one full-width operand per core
    stacked on axis 0 (global shape ``(n, ...)``); returns the
    cross-core elementwise sum scattered along ``axis`` of the operand
    — core i holds chunk i, i.e. ``lax.psum_scatter`` over the ring.
    The returned global array is the concatenation of those chunks
    (== the full sum)."""
    jax, _ = _jax_numpy()
    try:
        from jax.experimental.shard_map import shard_map
    except ImportError:
        shard_map = jax.shard_map
    from jax.sharding import PartitionSpec

    if axis_name is None:
        axis_name = _mesh_axis(mesh)
        if axis_name is None:
            raise ValueError("ring_psum_scatter needs a single-axis "
                             "mesh or an explicit axis_name")
    n = int(mesh.shape[axis_name])
    if x.shape[0] != n:
        raise ValueError(
            "ring_psum_scatter: leading axis %d must equal the %d ring "
            "members (one operand per core)" % (x.shape[0], n))
    op_axis = (axis % (x.ndim - 1)) + 1  # operand axis in the stacked x
    if x.shape[op_axis] % n:
        raise ValueError(
            "ring_psum_scatter: axis %d extent %d not divisible by %d "
            "ring members" % (op_axis - 1, x.shape[op_axis], n))
    if use_pallas is None:
        use_pallas = dma_available()
    inner = ring_psum_scatter_body(axis_name, n, axis=axis,
                                   use_pallas=use_pallas)

    def body(x_stack):  # local (1, ...) slab -> this core's sum chunk
        return inner(x_stack[0])

    out_spec = [None] * (x.ndim - 1)
    out_spec[op_axis - 1] = axis_name
    fn = shard_map(body, mesh=mesh,
                   in_specs=PartitionSpec(axis_name),
                   out_specs=PartitionSpec(*out_spec), check_rep=False)
    return jax.jit(fn)(x)


def ring_shift(x, mesh, axis_name: Optional[str] = None, shift: int = 1,
               use_pallas: Optional[bool] = None):
    """Move every device's shard of ``x`` to the device ``shift``
    positions along the mesh ring; value-wise this is ``jnp.roll`` by
    ``shift`` shards along the sharded axis. ``use_pallas`` defaults
    to :func:`dma_available` — the remote-DMA kernel on real TPU, the
    ppermute twin everywhere else."""
    jax, _ = _jax_numpy()
    try:
        from jax.experimental.shard_map import shard_map
    except ImportError:  # newer jax spells it jax.shard_map
        shard_map = jax.shard_map
    from jax.sharding import PartitionSpec

    if axis_name is None:
        axis_name = _mesh_axis(mesh)
        if axis_name is None:
            raise ValueError("ring_shift needs a single-axis mesh or an "
                             "explicit axis_name")
    n = int(mesh.shape[axis_name])
    shift = int(shift) % n
    if shift == 0:
        return x
    if use_pallas is None:
        use_pallas = dma_available()
    body = (_pallas_shift_body(axis_name, n, shift) if use_pallas
            else _ppermute_shift_body(axis_name, n, shift))
    spec = PartitionSpec(axis_name)
    fn = shard_map(body, mesh=mesh, in_specs=spec, out_specs=spec,
                   check_rep=False)
    return jax.jit(fn)(x)


def reshard(data, target):
    """Re-home ``data`` onto ``target`` (a device or a sharding)
    without host materialization. On real TPU, a move matching the
    ring-shift pattern routes through the remote-DMA kernel (one
    neighbor copy per core, overlappable with compute); everything
    else — including the whole virtual-CPU harness — is one
    ``jax.device_put``, which the runtime executes device-to-device
    for committed ``jax.Array`` inputs."""
    jax, _ = _jax_numpy()
    if hasattr(target, "device_set") and dma_available():
        shift = ring_shift_amount(getattr(data, "sharding", None),
                                  target)
        if shift is not None:
            src_mesh = data.sharding.mesh
            shifted = ring_shift(data, src_mesh, shift=shift,
                                 use_pallas=True)
            # every shard now sits on its target device (src device
            # i+k holds global shard i, which is exactly where the
            # rotated target mesh wants it); wrap the in-place buffers
            # under the target sharding — no further movement. NB the
            # shifted Array's *value* reads rotated under the source
            # sharding; under the target sharding the same buffers
            # spell the original value, which is what a reshard means.
            shards = [s.data for s in shifted.addressable_shards]
            return jax.make_array_from_single_device_arrays(
                data.shape, target, shards)
    return jax.device_put(data, target)
