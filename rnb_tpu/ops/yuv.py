"""On-device 4:2:0 ingest: packed YUV planes -> normalized bfloat16.

The ``yuv420`` pixel path moves the per-pixel colourspace work off the
host (the benchmark host's single CPU core is the throughput ceiling —
see RESULTS.md) and onto the accelerator, where it fuses with the
ingest normalization into one XLA kernel:

    host:   y4m payload --pure byte gathers--> packed 4:2:0 planes
    wire:   1.5 bytes/pixel  (vs 3 for RGB u8, 6 for bf16 frames)
    device: nearest chroma upsample -> BT.601 -> clip/quantize ->
            normalize -> network   (all inside the stage's jit)

The reference did this balance the opposite way — NVVL's NVDEC decoded
on the GPU *because the GPU had a video ASIC* (reference
README.md:42-110). A TPU has none, so the split that minimizes host
work and wire bytes is: gather on host, arithmetic on device.

Packed layout per frame (geometry must be even): ``Y`` (H*W bytes),
then ``U`` and ``V`` ((H/2)*(W/2) bytes each) — ``packed_frame_bytes``
total, flattened on the trailing axis so clip batches are
``(N, F, packed)`` and row bucketing/fusing work unchanged.

Numerics contract: luma uses the RGB path's exact nearest index map;
chroma keeps its own nearest map at half output resolution (standard
4:2:0 subsampling), so the two pixel paths may differ by one source
pixel in chroma. Within the yuv420 path, the numpy and native backends
are bit-exact; this device converter mirrors the numpy float32 op
order, with XLA FMA contraction allowed (±1 u8 LSB — asserted in
tests/test_yuv.py).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from rnb_tpu.ops.preprocess import normalize_u8


def packed_frame_bytes(height: int, width: int) -> int:
    """Bytes of one packed 4:2:0 frame; geometry must be even."""
    if height % 2 or width % 2:
        raise ValueError("packed 4:2:0 needs even geometry, got %dx%d"
                         % (height, width))
    return height * width * 3 // 2


def yuv420_to_rgb_u8(x, height: int, width: int):
    """Packed u8 planes ``(..., packed)`` -> RGB u8 ``(..., H, W, 3)``.

    jnp mirror of the numpy oracle (decode.yuv420_to_rgb_numpy): nearest
    2x chroma upsample, full-range BT.601, clip, truncate to u8.
    """
    hw = height * width
    q = (height // 2) * (width // 2)
    lead = x.shape[:-1]
    y = x[..., :hw].reshape(lead + (height, width)).astype(jnp.float32)
    u = x[..., hw:hw + q].reshape(lead + (height // 2, width // 2))
    v = x[..., hw + q:].reshape(lead + (height // 2, width // 2))
    u = jnp.repeat(jnp.repeat(u, 2, axis=-2), 2, axis=-1)
    v = jnp.repeat(jnp.repeat(v, 2, axis=-2), 2, axis=-1)
    uf = u.astype(jnp.float32) - 128.0
    vf = v.astype(jnp.float32) - 128.0
    rgb = jnp.stack([
        y + 1.402 * vf,
        y - 0.344136 * uf - 0.714136 * vf,
        y + 1.772 * uf,
    ], axis=-1)
    return jnp.clip(rgb, 0.0, 255.0).astype(jnp.uint8)


def normalize_yuv420(x, height: int = 112, width: int = 112,
                     dtype=jnp.bfloat16):
    """Packed u8 planes -> ``dtype`` NDHWC frames in [-1, 1].

    The u8 quantization step between conversion and normalization is
    kept deliberately: it makes the network's input identical to what
    a host-side converter would have produced, so accuracy is a
    property of the pixel path, not of where it runs.
    """
    return normalize_u8(yuv420_to_rgb_u8(x, height, width), dtype=dtype)


def yuv420_to_rgb_numpy(x: np.ndarray, height: int,
                        width: int) -> np.ndarray:
    """The numpy oracle for :func:`yuv420_to_rgb_u8` (tests only)."""
    hw = height * width
    q = (height // 2) * (width // 2)
    lead = x.shape[:-1]
    y = x[..., :hw].reshape(lead + (height, width)).astype(np.float32)
    u = x[..., hw:hw + q].reshape(lead + (height // 2, width // 2))
    v = x[..., hw + q:].reshape(lead + (height // 2, width // 2))
    u = u.repeat(2, axis=-2).repeat(2, axis=-1).astype(np.float32) - 128.0
    v = v.repeat(2, axis=-2).repeat(2, axis=-1).astype(np.float32) - 128.0
    rgb = np.stack([
        y + 1.402 * v,
        y - 0.344136 * u - 0.714136 * v,
        y + 1.772 * u,
    ], axis=-1)
    return np.clip(rgb, 0.0, 255.0).astype(np.uint8)
