"""Length-prefixed, checksummed frame protocol for the cross-host
ingest edge (rnb_tpu.netedge).

One frame = a fixed 28-byte little-endian header followed by
``length`` payload bytes:

    u32  length    payload byte count (not counting the header)
    u8   type      REQ | ACK | DATA | BEAT | DISPOSE | EOS
    u8   flags     reserved (0)
    u16  depth     sender's in-flight request count at send time —
                   the per-lane depth signal the health board consumes,
                   piggybacked on EVERY frame so acks and beats both
                   refresh it
    u64  seq       sender-assigned sequence number of the REQ this
                   frame belongs to (0 on BEAT/EOS); ACK/DATA/DISPOSE
                   echo it, and both sides' dedup ledgers key on it
    f64  deadline  the request's absolute ``deadline_s`` stamp (0.0
                   when no deadline is set) — in the HEADER so expiry
                   shedding can fire on either side of the edge
                   without decoding the payload
    u32  crc       CRC32 over the 24 preceding header bytes + payload

Payloads are JSON (REQ/DISPOSE), empty (ACK/BEAT/EOS), or JSON meta +
raw row bytes (DATA). DATA ships ONLY the ``valid`` leading rows of
the batch — for the packed DCT pixel path that is exactly
``dct_frame_elems`` int16 elements per frame (9 408 B at the default
budget, the wire format PR 12 built for this edge); the receiver
re-pads to the static shipped shape with zeros, which is what the pad
rows contain by construction.

Error classification (the PR 1 taxonomy, see rnb_tpu.faults):

    CRC mismatch              -> NetCorruptFrameError   (permanent)
    EOF inside a frame        -> NetPartialFrameError   (transient)
    EOF at a frame boundary   -> NetResetError          (transient)
    ECONNRESET / EPIPE        -> NetResetError          (transient)
    socket timeout            -> NetTimeoutError        (transient)
    dial refused              -> NetRefusedError        (transient)
"""

from __future__ import annotations

import json
import socket
import struct
import zlib
from collections import OrderedDict
from typing import Any, Optional, Tuple

import numpy as np

from rnb_tpu.faults import (NetCorruptFrameError, NetPartialFrameError,
                            NetRefusedError, NetResetError,
                            NetTimeoutError)

#: frame types
REQ = 1       # main -> peer: one request (path + serialized TimeCard)
ACK = 2       # peer -> main: REQ accepted (resend suppression + depth)
DATA = 3      # peer -> main: the stage's output rows for one REQ
BEAT = 4      # peer -> main: liveness heartbeat (depth piggybacked)
DISPOSE = 5   # peer -> main: terminal non-output outcome (failed/shed)
EOS = 6       # main -> peer: no more REQs; drain and exit

FRAME_NAMES = {REQ: "REQ", ACK: "ACK", DATA: "DATA", BEAT: "BEAT",
               DISPOSE: "DISPOSE", EOS: "EOS"}

#: header minus the trailing crc, and the crc tail
_HEAD = struct.Struct("<IBBHQd")
_CRC = struct.Struct("<I")
HEADER_SIZE = _HEAD.size + _CRC.size


def encode_frame(ftype: int, payload: bytes = b"", seq: int = 0,
                 deadline: float = 0.0, depth: int = 0,
                 flags: int = 0) -> bytes:
    """One wire-ready frame. ``depth`` saturates at u16 max rather
    than wrapping — a depth gauge that lies small under pathological
    backlog would mask exactly the overload it exists to show."""
    head = _HEAD.pack(len(payload), ftype, flags, min(depth, 0xffff),
                      seq, deadline)
    crc = zlib.crc32(payload, zlib.crc32(head)) & 0xffffffff
    return head + _CRC.pack(crc) + payload


def classify_io_error(exc: BaseException) -> Optional[Exception]:
    """Map a raw socket exception onto the net taxonomy, or None if it
    is not a recognized network failure (caller re-raises those)."""
    if isinstance(exc, socket.timeout):
        return NetTimeoutError(str(exc) or "socket timeout")
    if isinstance(exc, ConnectionRefusedError):
        return NetRefusedError(str(exc))
    if isinstance(exc, (ConnectionResetError, BrokenPipeError,
                        ConnectionAbortedError)):
        return NetResetError(str(exc))
    return None


def recv_exact(sock: socket.socket, n: int, *,
               mid_frame: bool) -> bytes:
    """Exactly ``n`` bytes off ``sock`` or a classified net error.

    EOF before the first byte of a frame header is a dead connection
    (:class:`NetResetError`); EOF anywhere else — including between
    the header and its payload — is a short frame
    (:class:`NetPartialFrameError`): framing is lost either way, but
    the distinction feeds separate per-class counters so a chaos
    plan's ``net_partial_frame`` injections are visible as themselves.
    """
    if sock.gettimeout() is None:
        # an unbounded blocking recv hangs the receiver forever on a
        # silently dead peer — the transport's whole fault taxonomy
        # depends on this read surfacing as net_timeout instead
        raise ValueError("recv_exact needs a socket with a configured "
                         "timeout (sock.settimeout)")
    chunks = []
    got = 0
    while got < n:
        try:
            chunk = sock.recv(n - got)
        except Exception as exc:  # noqa: BLE001 - classified below
            net = classify_io_error(exc)
            if net is not None:
                raise net from exc
            raise
        if not chunk:
            if mid_frame or got:
                raise NetPartialFrameError(
                    "stream ended %d bytes into a %d-byte read"
                    % (got, n))
            raise NetResetError("connection closed by peer")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def read_frame(sock: socket.socket
               ) -> Tuple[int, int, int, int, float, bytes]:
    """-> (type, flags, depth, seq, deadline, payload) or a classified
    net error. The CRC check covers header and payload together, so a
    flipped byte anywhere in the frame surfaces as
    :class:`NetCorruptFrameError` — but only AFTER the full frame was
    consumed, so framing stays in sync and the connection survives a
    corrupt frame (the request it carried does not)."""
    head = recv_exact(sock, _HEAD.size, mid_frame=False)
    (crc_stored,) = _CRC.unpack(
        recv_exact(sock, _CRC.size, mid_frame=True))
    length, ftype, flags, depth, seq, deadline = _HEAD.unpack(head)
    payload = recv_exact(sock, length, mid_frame=True) if length \
        else b""
    crc = zlib.crc32(payload, zlib.crc32(head)) & 0xffffffff
    if crc != crc_stored:
        exc = NetCorruptFrameError(
            "crc mismatch on %s frame seq=%d (%08x != %08x)"
            % (FRAME_NAMES.get(ftype, ftype), seq, crc, crc_stored))
        exc.seq = seq  # receiver dead-letters exactly this request
        raise exc
    return ftype, flags, depth, seq, deadline, payload


def send_frame(sock: socket.socket, frame: bytes) -> None:
    """sendall with the same classification as the receive side."""
    try:
        sock.sendall(frame)
    except Exception as exc:  # noqa: BLE001 - classified below
        net = classify_io_error(exc)
        if net is not None:
            raise net from exc
        raise


# -- TimeCard serialization -------------------------------------------

def card_to_wire(card) -> dict:
    """JSON-safe dict carrying EVERYTHING a TimeCard owns: identity,
    the ordered timing stamps, the device trail, the outcome fields,
    and every declared content stamp that is set (absent stamps stay
    absent — presence is part of the telemetry schema; fabricating a
    default would corrupt e.g. deadline-off accounting)."""
    from rnb_tpu.telemetry import CONTENT_STAMPS
    stamps = {}
    for attr in CONTENT_STAMPS:
        if hasattr(card, attr):
            stamps[attr] = getattr(card, attr)
    return {"id": card.id, "sub_id": card.sub_id,
            "timings": [[k, t] for k, t in card.timings.items()],
            "devices": [list(d) for d in card.devices],
            "status": card.status,
            "failure_reason": card.failure_reason,
            "stamps": stamps}


def card_from_wire(d: dict):
    """Inverse of :func:`card_to_wire`."""
    from rnb_tpu.telemetry import TimeCard
    card = TimeCard(int(d["id"]))
    card.sub_id = d.get("sub_id")
    card.timings = OrderedDict((k, float(t)) for k, t in d["timings"])
    card.devices = [tuple(dev) for dev in d.get("devices", [])]
    card.status = d.get("status", "ok")
    card.failure_reason = d.get("failure_reason")
    for attr, value in d.get("stamps", {}).items():
        setattr(card, attr, value)
    return card


# -- REQ / DISPOSE payloads -------------------------------------------

def encode_req(path: str, card) -> bytes:
    return json.dumps({"path": path, "card": card_to_wire(card)},
                      sort_keys=True).encode("utf-8")


def decode_req(payload: bytes) -> Tuple[str, Any]:
    d = json.loads(payload.decode("utf-8"))
    return d["path"], card_from_wire(d["card"])


def encode_dispose(outcome: str, reason: str, card) -> bytes:
    """``outcome`` is "failed" (peer dead-lettered the request) or
    "shed" (peer shed it at its receive boundary)."""
    return json.dumps({"outcome": outcome, "reason": reason,
                       "card": card_to_wire(card)},
                      sort_keys=True).encode("utf-8")


def decode_dispose(payload: bytes) -> Tuple[str, str, Any]:
    d = json.loads(payload.decode("utf-8"))
    return d["outcome"], d["reason"], card_from_wire(d["card"])


# -- DATA payload (batch rows + meta) ---------------------------------

def encode_data(batch, non_tensors, card) -> bytes:
    """u32 meta length + JSON meta + the raw bytes of the VALID rows.

    Only single-request emissions are wire-able (seq <-> request is
    1:1; that is what makes the exactly-once ledger sound), so fusing
    loaders stay in-process — enforced here, loudly.
    """
    from rnb_tpu.stage import RaggedBatch
    if not hasattr(card, "timings"):
        raise ValueError(
            "netedge wire carries single-request emissions only "
            "(got %s — fusing loaders are not wire-able)"
            % type(card).__name__)
    data = np.asarray(batch.data)
    valid = int(batch.valid)
    rows = np.ascontiguousarray(data[:valid])
    meta = {"kind": ("ragged" if isinstance(batch, RaggedBatch)
                     else "padded"),
            "shape": list(data.shape), "dtype": data.dtype.name,
            "valid": valid,
            "offsets": (list(batch.segment_offsets)
                        if isinstance(batch, RaggedBatch) else None),
            "non_tensors": non_tensors,
            "card": card_to_wire(card)}
    mj = json.dumps(meta, sort_keys=True).encode("utf-8")
    return struct.pack("<I", len(mj)) + mj + rows.tobytes()


def decode_data(payload: bytes) -> Tuple[Any, Any, Any, int]:
    """-> (batch, non_tensors, card, row_bytes). The receiver side
    re-pads to the static shipped shape with zeros — bit-identical to
    what the in-process loader emits, because pad rows ARE zeros."""
    from rnb_tpu.stage import PaddedBatch, RaggedBatch
    (mlen,) = struct.unpack_from("<I", payload, 0)
    meta = json.loads(payload[4:4 + mlen].decode("utf-8"))
    raw = payload[4 + mlen:]
    shape = tuple(int(s) for s in meta["shape"])
    dtype = np.dtype(meta["dtype"])
    valid = int(meta["valid"])
    rows = np.frombuffer(raw, dtype=dtype).reshape(
        (valid,) + shape[1:]) if valid else \
        np.zeros((0,) + shape[1:], dtype=dtype)
    data = np.zeros(shape, dtype=dtype)
    if valid:
        data[:valid] = rows
    if meta["kind"] == "ragged":
        batch = RaggedBatch(data, valid,
                            tuple(meta["offsets"] or (0, 0)))
    else:
        batch = PaddedBatch(data, valid)
    return batch, meta["non_tensors"], card_from_wire(meta["card"]), \
        len(raw)
