"""Ragged row-pool dispatch: one compiled shape, zero padding FLOPs.

Row bucketing (PR 4/5 era) made batch shapes *bounded* — every
emission pads up to the next warmed bucket — but each bucket is still
one XLA executable (a warmup matrix of one compile per (bucket,
dtype)), every pad row still burns FLOPs in the consuming stage, and
the autotune controller is quantized to the pre-warmed set. Following
Ragged Paged Attention (PAPERS.md), this module provides the ragged
alternative: stages dispatch a **flat row pool of fixed capacity**
``(pool_rows, ...)`` — ONE compiled shape for the stage's whole life —
plus a scalar ``rows_valid`` and a per-request ``segment_offsets``
table carried on :class:`rnb_tpu.stage.RaggedBatch`. The forward
primitive masks/skips rows past ``rows_valid``:

* **TPU**: a Pallas kernel over a ``PrefetchScalarGridSpec`` —
  ``rows_valid`` is scalar-prefetched into SMEM and the grid's row
  programs use ``pl.when(row < rows_valid)`` so pad-row blocks execute
  a zero-store only, no arithmetic — zero padding FLOPs;
* **CPU / fallback**: a masked ``jnp`` formulation with the identical
  contract (valid rows bit-identical to the bucketed path's
  ``normalize_u8``; pad rows exactly zero), so the tier-1 harness
  exercises the same semantics the TPU kernel compiles;
* **interpret mode**: the Pallas kernel body itself runs on CPU via
  ``interpret=True`` (tests assert it matches the jnp fallback
  bit-for-bit).

The scalar is *traced*, never static: any ``rows_valid`` in
``[0, pool_rows]`` dispatches through the same executable, which is
what deletes the warmup matrix and frees the autotune controller from
the warmed-bucket restriction (decisions become continuous).

Numerics contract: rows ``< rows_valid`` are bit-identical to the
bucketed path applied to the same rows; rows ``>= rows_valid`` are
exactly zero out of the masking primitives (the network consumes the
pool at its one shape and per-row outputs are independent of other
rows, so valid-row logits stay bit-identical to the bucketed path's —
asserted in tests/test_ragged.py on both pixel paths).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple

@dataclasses.dataclass(frozen=True)
class RaggedSettings:
    """Validated, defaulted view of the ``ragged`` root config key.

    ``pool_rows`` is the one dispatch shape's row capacity; ``None``
    defers to each participating stage's declared max rows (the
    common case — the pool IS the stage's max shape, so ring sizing
    and declared wire shapes are unchanged).
    """

    pool_rows: Optional[int] = None

    @staticmethod
    def from_config(raw: Optional[dict]) -> Optional["RaggedSettings"]:
        """Settings from the (schema-validated) config dict, or None
        when ragged is absent or ``enabled`` is false."""
        if not raw or not raw.get("enabled", True):
            return None
        pool_rows = raw.get("pool_rows")
        return RaggedSettings(
            pool_rows=int(pool_rows) if pool_rows is not None else None)


def resolve_pool_rows(pool_rows: Optional[int], declared_max: int,
                      what: str) -> int:
    """The one pool-capacity rule every ragged stage shares: an
    explicit ``ragged.pool_rows`` must EQUAL the stage's declared max
    row axis — the pool is the stage's one compiled shape, so a
    different capacity would silently change every declared wire
    shape, ring size and warmup signature (rnb-lint RNB-G009 rejects
    the mismatch statically; this is the runtime backstop)."""
    declared_max = int(declared_max)
    if pool_rows is None:
        return declared_max
    pool_rows = int(pool_rows)
    if pool_rows != declared_max:
        raise ValueError(
            "ragged.pool_rows=%d does not match %s=%d — the pool is "
            "the stage's one compiled shape, so its capacity must "
            "equal the declared max row axis" % (pool_rows, what,
                                                 declared_max))
    return pool_rows


def segment_offsets_of(counts: Sequence[int]) -> Tuple[int, ...]:
    """The cumulative segment table for per-request row ``counts``:
    ``(0, counts[0], counts[0]+counts[1], ...)`` — request i owns rows
    ``[offsets[i], offsets[i+1])``."""
    offsets = [0]
    for n in counts:
        offsets.append(offsets[-1] + int(n))
    return tuple(offsets)


def check_segment_offsets(offsets: Sequence[int], valid: int) -> None:
    """Assert a segment table partitions ``[0, valid)``: offsets are
    nondecreasing, start at 0 and end exactly at ``valid`` — request i
    owns rows ``[offsets[i], offsets[i+1])``. The executor applies
    this to every RaggedBatch it publishes (rnb_tpu.runner
    validate_payload), so a broken fill can never silently ship."""
    offsets = tuple(int(o) for o in offsets)
    if len(offsets) < 2:
        raise ValueError("segment_offsets needs >= 2 entries "
                         "(got %r)" % (offsets,))
    if offsets[0] != 0:
        raise ValueError("segment_offsets must start at 0, got %r"
                         % (offsets,))
    if any(b < a for a, b in zip(offsets, offsets[1:])):
        raise ValueError("segment_offsets must be nondecreasing, "
                         "got %r" % (offsets,))
    if offsets[-1] != int(valid):
        raise ValueError(
            "segment_offsets %r end at %d but rows_valid=%d — the "
            "segment table must partition the valid rows"
            % (offsets, offsets[-1], int(valid)))


# -- the masking/forward primitives -----------------------------------
#
# jax imports stay inside the functions: rnb-lint and config parsing
# import this module for RaggedSettings without touching a backend.

def _row_mask(pool, rows_valid):
    """Boolean (R, 1, 1, ...) row mask broadcastable over the pool."""
    import jax.numpy as jnp
    rows = pool.shape[0]
    idx = jnp.arange(rows).reshape((rows,) + (1,) * (pool.ndim - 1))
    return idx < rows_valid


def ragged_mask_rows(pool, rows_valid):
    """Zero every row ``>= rows_valid`` of ``pool`` (same dtype/shape).

    The minimal ragged primitive: turns a pool whose pad tail may hold
    garbage (a staging slot mid-recycle, an un-zeroed fill) into the
    exact bytes the bucketed path would have shipped for its pad rows
    (zeros) — inside the consuming jit, at the one compiled shape.
    """
    import jax.numpy as jnp
    return jnp.where(_row_mask(pool, rows_valid), pool,
                     jnp.zeros((), pool.dtype))


#: lane width of the TPU VPU — the Pallas kernel tiles each pool row
#: to (sublanes, LANES); rows whose byte count is not lane-divisible
#: fall back to the masked jnp formulation
LANES = 128
#: sublane rows per grid step (uint8 min tile is 32; a healthy
#: multiple keeps grid overhead low while staying far under VMEM)
BLOCK_SUBLANES = 512


def _ragged_normalize_kernel(rows_valid_ref, x_ref, o_ref):
    """One (pool-row, sublane-chunk) program: normalize when the row
    is valid, store zeros otherwise — pad programs execute no
    arithmetic (the ``pl.when`` predicate skips the whole body)."""
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    row = pl.program_id(0)

    @pl.when(row < rows_valid_ref[0])
    def _valid():
        # Mosaic has no direct uint8->bf16 cast; widen via int32/f32.
        # Same FMA-proof formulation as ops.preprocess.normalize_u8.
        x = x_ref[:].astype(jnp.int32).astype(jnp.float32)
        o_ref[:] = ((x * 2.0 - 255.0)
                    * jnp.float32(1.0 / 255.0)).astype(o_ref.dtype)

    @pl.when(row >= rows_valid_ref[0])
    def _pad():
        o_ref[:] = jnp.zeros_like(o_ref)


def _ragged_normalize_pallas(pool, rows_valid, dtype, interpret: bool):
    """Pallas ragged normalize over ``(R, per_row)`` lanes: grid =
    (pool rows, sublane chunks); ``rows_valid`` is scalar-prefetched
    so every program's predicate is resolved before its body runs."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    rows = pool.shape[0]
    per_row = int(np.prod(pool.shape[1:]))
    sublanes = per_row // LANES
    flat = pool.reshape(rows, sublanes, LANES)
    block = min(BLOCK_SUBLANES, sublanes)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(rows, pl.cdiv(sublanes, block)),
        in_specs=[pl.BlockSpec((1, block, LANES),
                               lambda i, j, rv: (i, j, 0))],
        out_specs=pl.BlockSpec((1, block, LANES),
                               lambda i, j, rv: (i, j, 0)),
    )
    out = pl.pallas_call(
        _ragged_normalize_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((rows, sublanes, LANES), dtype),
        interpret=interpret,
    )(jnp.asarray(rows_valid, jnp.int32).reshape(1), flat)
    return out.reshape(pool.shape)


def _on_tpu() -> bool:
    import jax
    try:
        return jax.default_backend() == "tpu"
    except RuntimeError:
        return False


def ragged_normalize_u8(pool, rows_valid, dtype=None,
                        interpret: bool = False):
    """uint8 row pool -> normalized ``dtype`` pool; pad rows zeroed.

    The ragged twin of ``ops.preprocess.normalize_u8``: valid rows are
    bit-identical to the bucketed preprocess applied to the same rows
    (same FMA-proof formulation); rows ``>= rows_valid`` come out
    exactly zero without being read by any arithmetic. Dispatches to
    the Pallas grid-skip kernel on TPU (or under ``interpret=True``
    anywhere, for tests); the masked jnp formulation otherwise.
    """
    import jax.numpy as jnp
    import numpy as np

    from rnb_tpu.ops.preprocess import normalize_u8_reference

    if dtype is None:
        dtype = jnp.bfloat16
    per_row = int(np.prod(pool.shape[1:])) if pool.ndim > 1 else 0
    if (pool.dtype == jnp.uint8 and per_row > 0
            and per_row % LANES == 0 and (interpret or _on_tpu())):
        return _ragged_normalize_pallas(pool, rows_valid, dtype,
                                        interpret)
    return jnp.where(_row_mask(pool, rows_valid),
                     normalize_u8_reference(pool, dtype=dtype),
                     jnp.zeros((), dtype))


def ragged_normalize_yuv420(pool, rows_valid, height: int, width: int,
                            dtype=None):
    """Packed 4:2:0 u8 row pool -> normalized NDHWC frames; rows past
    ``rows_valid`` enter the converter as zero bytes — exactly the
    bytes the bucketed path ships for its pad rows — so valid-row
    outputs are bit-identical to the bucketed fused ingest and pad
    rows are deterministic regardless of what the pool tail held.
    The mask runs at the u8 level (1.5 bytes/pixel), before the
    converter widens to f32."""
    import jax.numpy as jnp

    from rnb_tpu.ops.yuv import normalize_yuv420

    if dtype is None:
        dtype = jnp.bfloat16
    return normalize_yuv420(ragged_mask_rows(pool, rows_valid),
                            height, width, dtype=dtype)
