"""Custom TPU ops (Pallas kernels) with portable jnp fallbacks.

The compute path of this framework is almost entirely XLA-compiled
Flax/jnp code — XLA already fuses elementwise work into the conv/matmul
HLOs that dominate R(2+1)D. The ops package holds the few hand-written
Pallas kernels for boundaries XLA cannot see across, currently the
host->device ingest preprocess (uint8 decode output -> normalized
bfloat16 activations) that every video batch crosses exactly once
(reference analog: the uint8->float cast + permute after NVVL decode,
reference models/r2p1d/model.py:149-151).

Every op exposes one public entry point that dispatches to the Pallas
kernel on TPU backends and to an identical jnp formulation elsewhere
(CPU tests, interpret mode), so numerics are defined once.
"""

from rnb_tpu.ops.preprocess import normalize_u8  # noqa: F401
