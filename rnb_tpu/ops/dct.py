"""DCT-domain ingest: packed dequantized coefficients -> normalized
bfloat16 frames, fused on-device.

The ``dct`` pixel path moves the LAST per-pixel host stage of the MJPEG
pipeline onto the accelerator. The host decoder stops at
entropy-decoded, **dequantized** 8x8 DCT coefficients (the exact cut
point before ``Idct8x8`` in native/decode.cpp) and ships them in a
sparse packed row format; the consuming network stage runs

    IDCT  ->  2x nearest chroma upsample  ->  BT.601 YUV->RGB
          ->  u8 quantize  ->  normalize to [-1, 1]

as ONE fused step ahead of conv1 — a Pallas kernel on TPU (grid-skip
over ``rows_valid`` exactly like rnb_tpu/ops/ragged.py), a bit-identical
masked-jnp twin on CPU, and the kernel body itself under
``interpret=True`` in tests. This both *deletes* host IDCT work (the
dominant per-pixel term of MJPEG decode) and cuts wire bytes again on
top of YUV 4:2:0's 2x: quantized-then-dequantized coefficients are
sparse, so the packed format ships ~half the bytes of the packed-plane
yuv420 path at the default budget.

Wire row format (``dct_frame_elems`` int16 elements per frame; one clip
row is ``(consecutive_frames, elems)``), for even H, W with
``H % 16 == W % 16 == 0`` (one MCU = 16x16 luma under 4:2:0):

    [0 : NB)            per-block nonzero coefficient counts
    [NB : NB+C)         dequantized coefficient values (int16),
                        concatenated per block in block order,
                        ascending zigzag order within a block
    [NB+C : NB+2C)      the zigzag index (0..63) of each value

where ``NB = num_dct_blocks(H, W)`` (Y blocks in raster order, then U,
then V) and ``C = coeffs`` is the per-frame coefficient budget
(``default_dct_coeffs`` picks the largest C that keeps the frame at
half the packed-yuv420 byte count). Unused value/position slots are
zero. A frame whose nonzero count exceeds ``C`` cannot ship losslessly
and the decoder raises a *classified permanent* error instead of
silently truncating spectrum (see README "DCT-domain ingest" for when
yuv420 stays preferable).

The device unpack (counts -> per-entry block ids via searchsorted ->
one static-shape scatter) is plain jnp inside the same jit and is
garbage-tolerant: out-of-range counts/positions are clamped/dropped so
an uninitialized ragged pool tail can never corrupt valid rows or trap.

Numerics contract: the host AAN IDCT (native/decode.cpp) and this
on-device direct-basis IDCT are both float32 implementations of the
same transform, so reconstructed u8 planes agree within +-1 LSB at
round boundaries (tests bound this against the yuv420 pixel path); the
Pallas kernel and the jnp twin share one frame-conversion function and
are asserted BIT-identical. Pad rows (``>= rows_valid``) come out
exactly zero from both.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

#: zigzag scan: position k in the scan -> natural (row-major u*8+v)
#: coefficient index. Identical to kZigzag in native/decode.cpp.
ZIGZAG_NATURAL = np.array([
    0,  1,  8,  16, 9,  2,  3,  10, 17, 24, 32, 25, 18, 11, 4,  5,
    12, 19, 26, 33, 40, 48, 41, 34, 27, 20, 13, 6,  7,  14, 21, 28,
    35, 42, 49, 56, 57, 50, 43, 36, 29, 22, 15, 23, 30, 37, 44, 51,
    58, 59, 52, 45, 38, 31, 39, 46, 53, 60, 61, 54, 47, 55, 62, 63],
    dtype=np.int32)


def _check_geometry(height: int, width: int) -> None:
    if height % 16 or width % 16:
        raise ValueError(
            "the dct pixel path needs H and W divisible by 16 (one "
            "4:2:0 MCU is 16x16 luma), got %dx%d" % (height, width))


def num_dct_blocks(height: int, width: int) -> int:
    """8x8 blocks per frame at 4:2:0: Y (H/8 * W/8) + U + V (quarter
    resolution each)."""
    _check_geometry(height, width)
    return (height // 8) * (width // 8) + 2 * (height // 16) * (width // 16)


def default_dct_coeffs(height: int, width: int) -> int:
    """Default per-frame coefficient budget: the largest C for which
    the packed frame (int16) costs no more than HALF the packed
    yuv420 frame — the wire-byte headline this path ships by default
    (raise ``dct_coeffs_per_frame`` for high-entropy content at the
    cost of some of the reduction)."""
    _check_geometry(height, width)
    packed_yuv = height * width * 3 // 2      # bytes, u8 planes
    max_elems = (packed_yuv // 2) // 2        # int16 elems in half that
    coeffs = (max_elems - num_dct_blocks(height, width)) // 2
    if coeffs < 1:
        raise ValueError("geometry %dx%d too small for the dct wire "
                         "format" % (height, width))
    return coeffs


def dct_frame_elems(height: int, width: int,
                    coeffs: Optional[int] = None) -> int:
    """int16 elements of one packed coefficient frame."""
    nb = num_dct_blocks(height, width)
    if coeffs is None:
        coeffs = default_dct_coeffs(height, width)
    coeffs = int(coeffs)
    if coeffs < 1:
        raise ValueError("dct coefficient budget must be >= 1, got %r"
                         % (coeffs,))
    return nb + 2 * coeffs


def coeffs_from_elems(height: int, width: int, elems: int) -> int:
    """Recover the coefficient budget C from a wire row's trailing
    axis (the inverse of :func:`dct_frame_elems`)."""
    nb = num_dct_blocks(height, width)
    coeffs, rem = divmod(int(elems) - nb, 2)
    if rem or coeffs < 1:
        raise ValueError(
            "%d is not a valid dct frame length for %dx%d (expected "
            "num_blocks=%d + 2*C)" % (elems, height, width, nb))
    return coeffs


def pack_frame_dct(zz: np.ndarray, height: int, width: int,
                   coeffs: Optional[int] = None) -> np.ndarray:
    """Pack one frame's dense zigzag-order coefficients into the wire
    format.

    ``zz`` is ``(num_blocks, 64)`` int16 — dequantized coefficients in
    zigzag scan order per block, blocks in Y-raster/U-raster/V-raster
    order. Raises ValueError when the nonzero count exceeds the
    budget (callers classify it permanent: re-decoding cannot shrink
    the spectrum).
    """
    nb = num_dct_blocks(height, width)
    if coeffs is None:
        coeffs = default_dct_coeffs(height, width)
    coeffs = int(coeffs)
    zz = np.asarray(zz, dtype=np.int16)
    if zz.shape != (nb, 64):
        raise ValueError("expected (%d, 64) zigzag coefficients for "
                         "%dx%d, got %r" % (nb, height, width, zz.shape))
    block_idx, pos_idx = np.nonzero(zz)   # row-major: block-then-zigzag
    total = block_idx.size
    if total > coeffs:
        raise ValueError(
            "frame has %d nonzero DCT coefficients but the wire "
            "budget is %d — raise dct_coeffs_per_frame (or use "
            "pixel_path yuv420 for this content)" % (total, coeffs))
    out = np.zeros(nb + 2 * coeffs, dtype=np.int16)
    counts = np.bincount(block_idx, minlength=nb)
    out[:nb] = counts.astype(np.int16)
    out[nb:nb + total] = zz[block_idx, pos_idx]
    out[nb + coeffs:nb + coeffs + total] = pos_idx.astype(np.int16)
    return out


def unpack_frame_dct_numpy(wire: np.ndarray, height: int,
                           width: int) -> np.ndarray:
    """Wire frame -> dense ``(num_blocks, 64)`` zigzag coefficients
    (numpy; the host-side inverse of :func:`pack_frame_dct`, for
    tests and oracles)."""
    nb = num_dct_blocks(height, width)
    coeffs = coeffs_from_elems(height, width, wire.shape[-1])
    wire = np.asarray(wire, dtype=np.int64)
    counts = np.clip(wire[:nb], 0, 64)
    total = min(int(counts.sum()), coeffs)
    block = np.repeat(np.arange(nb), counts)[:total]
    vals = wire[nb:nb + total]
    poss = np.clip(wire[nb + coeffs:nb + coeffs + total], 0, 63)
    zz = np.zeros((nb, 64), dtype=np.int16)
    zz[block, poss] = vals[: block.size].astype(np.int16)
    return zz


# -- IDCT bases (host-built constants) --------------------------------

def _idct_basis8() -> np.ndarray:
    """M[y, u] = c(u)/2 * cos((2y+1) u pi / 16) — one 1-D 8-point
    inverse DCT pass; the 2-D block IDCT is M @ C @ M^T."""
    y, u = np.meshgrid(np.arange(8), np.arange(8), indexing="ij")
    m = 0.5 * np.cos((2 * y + 1) * u * np.pi / 16.0)
    m[:, 0] *= 1.0 / np.sqrt(2.0)
    return m.astype(np.float32)


def _plane_bases(height: int, width: int):
    """The four constant matrices of the fused frame conversion:

    * ``ly (H, H)`` / ``lyt (W, W)``: block-diagonal ``I ⊗ M8`` so the
      WHOLE luma plane's IDCT is two dense matmuls over the block-tiled
      coefficient matrix — MXU-shaped work instead of 8x8 batches;
    * ``lcr (H, H/2)`` / ``lcct (W/2, W)``: the same for chroma with
      the 2x nearest upsample folded in (rows duplicated — replication
      commutes with the later rounding, so this is exactly the
      "round the half-res plane, then repeat" host semantics).
    """
    m = _idct_basis8()
    ly = np.kron(np.eye(height // 8, dtype=np.float32), m)
    lyt = np.kron(np.eye(width // 8, dtype=np.float32), m).T
    cb_r = np.kron(np.eye(height // 16, dtype=np.float32), m)
    cb_c = np.kron(np.eye(width // 16, dtype=np.float32), m)
    lcr = np.repeat(cb_r, 2, axis=0)
    lcct = np.repeat(cb_c, 2, axis=0).T
    return (np.ascontiguousarray(ly), np.ascontiguousarray(lyt),
            np.ascontiguousarray(lcr), np.ascontiguousarray(lcct))


# -- device unpack (jnp, inside the consuming jit) --------------------

def unpack_dct_rows(x, height: int, width: int):
    """Packed wire rows ``(..., F, elems)`` int16 -> block-tiled dense
    coefficient planes ``(ycoef (..., F, H, W), ucoef/vcoef (..., F,
    H/2, W/2))`` as int32.

    Block-tiled layout: the 8x8 tile of ``ycoef`` at block (i, j)
    holds that block's natural-order coefficients, so the plane IDCT
    is ``ly @ ycoef @ lyt``. Garbage-tolerant by construction (clamped
    counts/positions, out-of-range entries dropped into a dump slot):
    an uninitialized pool tail decodes to SOMETHING deterministic and
    is then masked by the caller, never trapping.
    """
    import jax
    import jax.numpy as jnp

    nb = num_dct_blocks(height, width)
    coeffs = coeffs_from_elems(height, width, x.shape[-1])
    lead = x.shape[:-1]
    flat = x.reshape((-1, x.shape[-1]))
    counts = jnp.clip(flat[:, :nb].astype(jnp.int32), 0, 64)
    cum = jnp.cumsum(counts, axis=-1)                    # inclusive
    total = jnp.minimum(cum[:, -1], coeffs)
    vals = flat[:, nb:nb + coeffs].astype(jnp.int32)
    poss = jnp.clip(flat[:, nb + coeffs:nb + 2 * coeffs]
                    .astype(jnp.int32), 0, 63)
    entry = jnp.arange(coeffs, dtype=jnp.int32)
    block = jax.vmap(
        lambda c: jnp.searchsorted(c, entry, side="right"))(cum)
    natural = jnp.asarray(ZIGZAG_NATURAL)[poss]
    ok = (entry[None, :] < total[:, None]) & (block < nb)
    # one extra dump slot swallows every invalid entry
    target = jnp.where(ok, block * 64 + natural, nb * 64)
    dense = jax.vmap(
        lambda t, v: jnp.zeros(nb * 64 + 1, jnp.int32).at[t].set(v)
    )(target, jnp.where(ok, vals, 0))[:, : nb * 64]

    ny = (height // 8) * (width // 8)
    nc = (height // 16) * (width // 16)

    def tiled(blocks, bh, bw):
        # (B, bh*bw, 8, 8) -> block-tiled (B, bh*8, bw*8)
        t = blocks.reshape((-1, bh, bw, 8, 8))
        return t.transpose((0, 1, 3, 2, 4)).reshape(
            (-1, bh * 8, bw * 8))

    ycoef = tiled(dense[:, : ny * 64], height // 8, width // 8)
    ucoef = tiled(dense[:, ny * 64:(ny + nc) * 64],
                  height // 16, width // 16)
    vcoef = tiled(dense[:, (ny + nc) * 64:], height // 16, width // 16)
    return (ycoef.reshape(lead + ycoef.shape[1:]),
            ucoef.reshape(lead + ucoef.shape[1:]),
            vcoef.reshape(lead + vcoef.shape[1:]))


# -- the fused frame conversion (shared by kernel, twin, interpret) ---

def _frame_rgb_normalized(cy, cu, cv, ly, lyt, lcr, lcct, dtype):
    """Block-tiled coefficient planes ``(..., H, W)`` -> normalized
    ``(..., H, W, 3)``. The SINGLE function both the Pallas kernel
    body (one 2-D frame per grid program) and the jnp twin (all
    frames batched over the leading dims — ``jnp.matmul`` broadcasts)
    call, so the two are structurally identical op for op; the
    bit-parity contract tier-1 asserts batched-vs-per-frame matmul
    rounding agreement on this backend.

    Stages mirror the host pixel pipeline exactly: IDCT (+128 level
    shift), per-plane round-half-up u8 quantize (native Idct8x8's
    ``ClipByte(px + 0.5)``), BT.601 in the same op order as
    rnb_tpu/ops/yuv.py, clip, truncate to u8, then the FMA-proof
    normalize formulation of ops/preprocess.normalize_u8_reference.
    """
    import jax.numpy as jnp

    f32 = jnp.float32

    def plane(coef, left, right):
        c = coef.astype(jnp.int32).astype(f32)
        p = jnp.matmul(left, jnp.matmul(c, right,
                                        preferred_element_type=f32),
                       preferred_element_type=f32)
        # level shift + the host decoder's round-half-up u8 quantize
        return jnp.clip(jnp.floor(p + (128.0 + 0.5)), 0.0, 255.0)

    y = plane(cy, ly, lyt)
    u = plane(cu, lcr, lcct)
    v = plane(cv, lcr, lcct)
    uf = u - 128.0
    vf = v - 128.0
    rgb = jnp.stack([
        y + 1.402 * vf,
        y - 0.344136 * uf - 0.714136 * vf,
        y + 1.772 * uf,
    ], axis=-1)
    # the yuv420 path's u8 quantization step (clip + truncate), kept in
    # f32, then the single-rounding normalize
    rgbq = jnp.floor(jnp.clip(rgb, 0.0, 255.0))
    return ((rgbq * 2.0 - 255.0) * f32(1.0 / 255.0)).astype(dtype)


def _dct_kernel(rows_valid_ref, cy_ref, cu_ref, cv_ref, ly_ref,
                lyt_ref, lcr_ref, lcct_ref, o_ref):
    """One (pool-row, frame) program: full fused conversion when the
    row is valid, a zero store otherwise — pad programs run no
    IDCT/convert arithmetic (the ``pl.when`` predicate skips the whole
    body, rnb_tpu/ops/ragged.py discipline)."""
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    row = pl.program_id(0)

    @pl.when(row < rows_valid_ref[0])
    def _valid():
        out = _frame_rgb_normalized(
            cy_ref[0, 0], cu_ref[0, 0], cv_ref[0, 0], ly_ref[:],
            lyt_ref[:], lcr_ref[:], lcct_ref[:], o_ref.dtype)
        o_ref[:] = out[None, None]

    @pl.when(row >= rows_valid_ref[0])
    def _pad():
        o_ref[:] = jnp.zeros_like(o_ref)


def _dct_convert_pallas(ycoef, ucoef, vcoef, rows_valid, height: int,
                        width: int, dtype, interpret: bool):
    """Pallas dispatch over (pool rows, frames): ``rows_valid`` is
    scalar-prefetched so every program's predicate resolves before its
    body; the IDCT bases ride as whole-array inputs every program
    reads."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    rows, frames = ycoef.shape[0], ycoef.shape[1]
    h2, w2 = height // 2, width // 2
    ly, lyt, lcr, lcct = _plane_bases(height, width)
    const = lambda shape: pl.BlockSpec(  # noqa: E731 — local spec rule
        shape, lambda i, j, rv: tuple(0 for _ in shape))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(rows, frames),
        in_specs=[
            pl.BlockSpec((1, 1, height, width),
                         lambda i, j, rv: (i, j, 0, 0)),
            pl.BlockSpec((1, 1, h2, w2), lambda i, j, rv: (i, j, 0, 0)),
            pl.BlockSpec((1, 1, h2, w2), lambda i, j, rv: (i, j, 0, 0)),
            const(ly.shape), const(lyt.shape), const(lcr.shape),
            const(lcct.shape),
        ],
        out_specs=pl.BlockSpec((1, 1, height, width, 3),
                               lambda i, j, rv: (i, j, 0, 0, 0)),
    )
    out = pl.pallas_call(
        _dct_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(
            (rows, frames, height, width, 3), dtype),
        interpret=interpret,
    )(jnp.asarray(rows_valid, jnp.int32).reshape(1), ycoef, ucoef,
      vcoef, jnp.asarray(ly), jnp.asarray(lyt), jnp.asarray(lcr),
      jnp.asarray(lcct))
    return out


def _dct_convert_jnp(ycoef, ucoef, vcoef, height: int, width: int,
                     dtype):
    """The jnp twin's conversion over ``(rows, frames)`` planes: ONE
    call of the SAME function the kernel body runs, with the plane
    matmuls batched over the leading dims (XLA CPU's batched GEMM
    runs the identical per-frame contraction — bit-equality with the
    interpret-mode kernel is asserted in tests/test_dct.py)."""
    import jax.numpy as jnp

    ly, lyt, lcr, lcct = _plane_bases(height, width)
    return _frame_rgb_normalized(
        ycoef, ucoef, vcoef, jnp.asarray(ly), jnp.asarray(lyt),
        jnp.asarray(lcr), jnp.asarray(lcct), dtype)


def _on_tpu() -> bool:
    import jax
    try:
        return jax.default_backend() == "tpu"
    except RuntimeError:
        return False


def normalize_dct(pool, height: int, width: int, dtype=None,
                  interpret: bool = False):
    """Packed coefficient rows ``(N, F, elems)`` int16 -> normalized
    ``dtype`` NDHWC frames — the bucketed-path ingest (every row
    converted; pad rows are zero wire bytes, which decode to a
    deterministic flat mid-gray frame — zero coefficients -> all
    planes 128. Deterministic-pad is the shared contract with the
    yuv420 path; the pad frame VALUE differs per pixel path, and
    per-row network outputs never depend on pad rows either way)."""
    import jax.numpy as jnp

    if dtype is None:
        dtype = jnp.bfloat16
    ycoef, ucoef, vcoef = unpack_dct_rows(pool, height, width)
    if interpret or _on_tpu():
        return _dct_convert_pallas(ycoef, ucoef, vcoef,
                                   pool.shape[0], height, width,
                                   dtype, interpret)
    return _dct_convert_jnp(ycoef, ucoef, vcoef, height, width, dtype)


def ragged_normalize_dct(pool, rows_valid, height: int, width: int,
                         dtype=None, interpret: bool = False):
    """The ragged seam replacing ``ragged_normalize_yuv420`` on the
    dct pixel path: packed coefficient row pool + traced ``rows_valid``
    -> normalized NDHWC pool whose rows ``>= rows_valid`` are exactly
    zero. On TPU (or under ``interpret=True``) the Pallas grid skips
    pad (row, frame) programs outright — no IDCT, no conversion
    arithmetic on rows nobody reads; the jnp twin masks the converted
    output with the identical result. The unpack stays garbage-
    tolerant, so an uninitialized pool tail is safe on both paths."""
    import jax.numpy as jnp

    if dtype is None:
        dtype = jnp.bfloat16
    ycoef, ucoef, vcoef = unpack_dct_rows(pool, height, width)
    if interpret or _on_tpu():
        return _dct_convert_pallas(ycoef, ucoef, vcoef, rows_valid,
                                   height, width, dtype, interpret)
    out = _dct_convert_jnp(ycoef, ucoef, vcoef, height, width, dtype)
    rows = pool.shape[0]
    mask = jnp.arange(rows).reshape((rows, 1, 1, 1, 1)) < rows_valid
    return jnp.where(mask, out, jnp.zeros((), out.dtype))


# -- numpy oracle (tests only) ----------------------------------------

def dct_rows_to_rgb_numpy(wire: np.ndarray, height: int,
                          width: int) -> np.ndarray:
    """Packed wire rows ``(..., elems)`` -> u8 RGB ``(..., H, W, 3)``:
    the pure-numpy mirror of the fused conversion minus the final
    normalize, for comparing against the pixel decode backends."""
    ly, lyt, lcr, lcct = _plane_bases(height, width)
    nb = num_dct_blocks(height, width)
    lead = wire.shape[:-1]
    flat = wire.reshape((-1, wire.shape[-1]))
    out = np.empty((flat.shape[0], height, width, 3), np.uint8)
    ny = (height // 8) * (width // 8)
    nc = (height // 16) * (width // 16)
    nat = np.zeros(64, dtype=np.int64)
    nat[:] = ZIGZAG_NATURAL

    def tiled(blocks, bh, bw):
        return blocks.reshape(bh, bw, 8, 8).transpose(0, 2, 1, 3) \
            .reshape(bh * 8, bw * 8)

    for i in range(flat.shape[0]):
        zz = unpack_frame_dct_numpy(flat[i], height, width)
        dense = np.zeros((nb, 64), np.float32)
        dense[np.arange(nb)[:, None], nat[None, :]] = zz
        cy = tiled(dense[:ny], height // 8, width // 8)
        cu = tiled(dense[ny:ny + nc], height // 16, width // 16)
        cv = tiled(dense[ny + nc:], height // 16, width // 16)

        def plane(c, left, right):
            p = left.astype(np.float64) @ c.astype(np.float64) \
                @ right.astype(np.float64)
            return np.clip(np.floor(p + 128.5), 0, 255)

        y = plane(cy, ly, lyt)
        u = plane(cu, lcr, lcct)
        v = plane(cv, lcr, lcct)
        rgb = np.stack([
            y + 1.402 * (v - 128.0),
            y - 0.344136 * (u - 128.0) - 0.714136 * (v - 128.0),
            y + 1.772 * (u - 128.0),
        ], axis=-1)
        out[i] = np.floor(np.clip(rgb, 0, 255)).astype(np.uint8)
    return out.reshape(lead + (height, width, 3))
