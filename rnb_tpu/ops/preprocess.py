"""Ingest preprocess kernel: uint8 frames -> normalized bfloat16.

This is the one op every video batch crosses on its way from the host
decoder into the network (the TPU-native analog of the reference's
post-NVVL ``.float()`` cast, reference models/r2p1d/model.py:149-151):

    y = x.astype(bf16) * (2/255) - 1        # [0,255] -> [-1,1]

XLA would fuse this into the consuming conv when it can; the Pallas
kernel makes the ingest cost explicit and keeps the uint8->bf16
widening on the VPU with lane-aligned tiles, independent of what the
consumer looks like (it may live behind a ``device_put`` boundary in
the pipelined runtime, where there is no consumer to fuse into).

Layout strategy: the logical clip shape ``(N, F, H, W, 3)`` is
irrelevant to an elementwise op, so the wrapper flattens to
``(M, 128)`` lanes and grids over row blocks; Pallas masks the ragged
final block. Inputs whose element count is not lane-divisible (never
the case for the 112x112x3 production geometry) take the jnp path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

LANES = 128
#: uint8 min sublane tile is 32; use a healthy multiple for fewer grid
#: steps while staying far under VMEM (2 x 512 x 128 x ~3B per step).
BLOCK_ROWS = 512


def normalize_u8_reference(x, dtype=jnp.bfloat16):
    """The jnp formulation (also the numerics contract for the kernel).

    Written as ``(2x - 255) * (1/255)``: the inner term is exact
    integer arithmetic in f32 (|2x-255| <= 255), leaving a single
    rounding multiply — no mul+add pair a compiler could contract into
    an FMA — so every backend (XLA CPU/TPU, Mosaic, interpret mode)
    produces bit-identical f32, rounded to ``dtype`` exactly once.
    """
    xf = x.astype(jnp.float32)
    return ((xf * 2.0 - 255.0) * jnp.float32(1.0 / 255.0)).astype(dtype)


def _normalize_kernel(x_ref, o_ref):
    # Mosaic has no direct uint8->bf16 cast; widen via int32/f32 on the
    # VPU. Same FMA-proof formulation as normalize_u8_reference.
    x = x_ref[:].astype(jnp.int32).astype(jnp.float32)
    o_ref[:] = ((x * 2.0 - 255.0)
                * jnp.float32(1.0 / 255.0)).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("dtype",))
def _normalize_u8_pallas(x, dtype=jnp.bfloat16):
    from jax.experimental import pallas as pl

    flat = x.reshape(-1, LANES)
    rows = flat.shape[0]
    block = min(BLOCK_ROWS, rows)
    out = pl.pallas_call(
        _normalize_kernel,
        out_shape=jax.ShapeDtypeStruct((rows, LANES), dtype),
        grid=(pl.cdiv(rows, block),),
        in_specs=[pl.BlockSpec((block, LANES), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((block, LANES), lambda i: (i, 0)),
    )(flat)
    return out.reshape(x.shape)


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except RuntimeError:
        return False


def normalize_u8(x, dtype=jnp.bfloat16):
    """uint8 [0,255] frames -> ``dtype`` in [-1, 1].

    The single normalization every ingest path shares (pipeline loader
    preprocess, sharded mesh step). Dispatches to the Pallas kernel on
    TPU when the element count is lane-divisible, else to jnp.
    """
    if x.dtype == jnp.uint8 and x.size > 0 and x.size % LANES == 0 \
            and _on_tpu():
        return _normalize_u8_pallas(x, dtype=dtype)
    return normalize_u8_reference(x, dtype=dtype)
