"""Request-scoped software tracing: per-request event timelines.

Every request travelling through the pipeline carries a TimeCard; each
stage stamps named events on it (``runner{i}_start``, ``inference{i}_start``,
``inference{i}_finish``) together with a trail of the devices it visited.
Segment-parallel execution forks a card per segment and the aggregation
stage merges the siblings back into one card whose post-fork events carry
``-{sub_id}`` suffixes.

Capability parity with the reference's rnb_logging.py (TimeCard
rnb_logging.py:22-123, TimeCardList :126-142, TimeCardSummary :145-214,
log path helpers :6-19), re-designed for the TPU runtime: device trails
are arbitrary string labels ("tpu:3", "cpu:0", "host") instead of GPU
integers, and log filenames use a device-label scheme.
"""

from __future__ import annotations

import os
import time
from collections import OrderedDict, namedtuple
from typing import IO, Iterable, List, Optional, Sequence


def logroot(job_id: str, base: str = "logs") -> str:
    """Directory holding every artifact of one benchmark job."""
    path = os.path.join(base, str(job_id))
    os.makedirs(path, exist_ok=True)
    return path


def logmeta(job_id: str, base: str = "logs") -> str:
    """Path of the job metadata file (args, wall time, termination code)."""
    return os.path.join(logroot(job_id, base), "log-meta.txt")


def logname(job_id: str, device_label: str, group_idx: int, instance_idx: int,
            base: str = "logs") -> str:
    """Path of the per-final-instance timing table.

    Mirrors the reference's ``g{gpu}-group{group}-{instance}.txt`` scheme
    (rnb_logging.py:17-19) with a device label usable for TPU cores.
    """
    safe = str(device_label).replace(":", "").replace("/", "-")
    return os.path.join(
        logroot(job_id, base),
        "%s-group%d-%d.txt" % (safe, group_idx, instance_idx))


def latency_percentiles(latencies_ms: Sequence[float],
                        percentiles=(50.0, 99.0)):
    """{percentile: value_ms} over a latency sample; {} when empty.

    The one percentile convention shared by per-instance summaries and
    the controller's cross-instance aggregation (rnb_tpu.benchmark).
    """
    import numpy as np
    if not latencies_ms:
        return {}
    return {p: float(np.percentile(latencies_ms, p)) for p in percentiles}


#: per-request content stamps set by the loader that must survive
#: fork/merge: clip count (routing, MFU accounting) and the cache
#: outcome (rnb_tpu.cache: True=hit, False=miss; cache_coalesced marks
#: a request that shared another request's in-flight decode)
CONTENT_STAMPS = ("num_clips", "cache_hit", "cache_coalesced",
                  # True when the request was answered from feature
                  # pages (rnb_tpu.pager): the stage forward never ran,
                  # so MFU accounting counts its rows 0 — the honesty
                  # policy twin of cache_coalesced. (The feature_plan /
                  # feature_insert carriers live in TRANSIENT_STAMPS
                  # below instead: they hold live page pins / insert
                  # obligations a fork would double-own.)
                  "feature_hit",
                  # pad rows the emission carrying this request shipped
                  # (attributed to the emission's first constituent so
                  # sums stay exact; 0 on every other card and on every
                  # ragged emission — the ragged kernel computes no pad
                  # rows)
                  "pad_rows",
                  # absolute wall-clock deadline (rnb_tpu.health,
                  # root 'deadline' config key): stamped by the client
                  # at enqueue; every stage boundary sheds the request
                  # once it passes — absent on deadline-off runs
                  "deadline_s",
                  # times this request was drained off an evicted
                  # replica lane and re-enqueued onto a healthy
                  # sibling (rnb_tpu.health lane eviction)
                  "redispatched",
                  # True on the CLONE card of a hedged re-dispatch
                  # (rnb_tpu.health.HedgeGovernor) — the claim site
                  # reads it to attribute the win to the hedge or the
                  # original copy
                  "hedge_copy",
                  # set once a copy claimed WINNER: later disposal of
                  # the SAME copy must not claim again (it owns the
                  # rid's terminal outcome; a re-claim would consume
                  # the sibling copy's LOSER slot)
                  "hedge_resolved")

#: card-riding carriers that are DELIBERATELY not content stamps: they
#: must NOT survive fork/merge. Each holds single-owner live state —
#: copying it onto a hedge clone would double-own it. The schema
#: checker (RNB-T007) accepts stamp sites for these names but the
#: fork/merge copy loop above never touches them; both plans are
#: released idempotently by the loader's failure/shed sweeps so a
#: dropped card cannot strand a page pin.
TRANSIENT_STAMPS = (
    # rnb_tpu.pager.GatherPlan for a feature-page hit: pins live pages
    # until the runner's logit gather releases them — exactly-once
    # consumption, popped (set back to None) by the consuming stage
    "feature_plan",
    # (content_key, row_start, rows) insert obligation: must fire
    # exactly once AFTER the forward succeeds; surviving a fork would
    # double-insert the same rows
    "feature_insert")


# -- the declared telemetry schema ------------------------------------
#
# PRs 1-2 each extended the TimeCard/report schema by hand in three
# places (stamp sites, scripts/parse_utils.py, README) — exactly the
# silent drift a stamp registry exists to stop. Every timing-stamp
# pattern, log-meta line and report trailer the tree may write is
# DECLARED here; the static schema checker
# (rnb_tpu.analysis.schema, gated in tier-1) cross-checks these
# declarations against the actual stamp/write sites AND against what
# scripts/parse_utils.py parses, so a stamp can neither appear
# unregistered nor silently vanish from reports.
# ``python scripts/parse_utils.py --stamps`` prints the generated
# reference.

#: one declared telemetry element: ``pattern`` uses ``{step}`` for the
#: pipeline-step index (stamp sites format it with ``%d``); merged
#: segment cards additionally suffix post-fork stamps with
#: ``-{sub_id}`` (TimeCard.merge)
StampSpec = namedtuple("StampSpec", ("pattern", "producer", "description"))

#: every TimeCard timing-stamp pattern any code path may record
STAMP_REGISTRY = (
    StampSpec("enqueue_filename", "rnb_tpu/client.py",
              "client created the request and enqueued its video path"),
    StampSpec("runner{step}_start", "rnb_tpu/runner.py",
              "stage executor popped the request off its input queue"),
    StampSpec("inference{step}_start", "rnb_tpu/runner.py",
              "model call (or prefetched-decode completion) began"),
    StampSpec("inference{step}_finish", "rnb_tpu/runner.py",
              "stage output ready (device-synced unless async_dispatch)"),
    # -- phase-refinement stamps (rnb_tpu.trace): recorded ONLY when
    # the job's `trace` config key enables tracing, so trace-off runs
    # stay byte-stable with the pre-trace schema. They split the
    # loader's inference{step} span into decode/hold/transfer/drain
    # for per-request attribution (parse_utils --attribute).
    StampSpec("decode{step}_done", "rnb_tpu/models/r2p1d/model.py",
              "this request's clip decode completed (trace mode only; "
              "a cache hit records a zero-length decode phase)"),
    StampSpec("transfer{step}_start", "rnb_tpu/models/r2p1d/model.py",
              "the emission holding this request closed and its "
              "host->device transfer began (trace mode only)"),
    StampSpec("transfer{step}_done", "rnb_tpu/models/r2p1d/model.py",
              "host->device transfer dispatched/confirmed; the gap to "
              "inference{step}_finish is publish drain (trace mode "
              "only)"),
)

#: every ``<Prefix>:``-keyed line rnb_tpu/benchmark.py may write into
#: ``logs/<job>/log-meta.txt`` (plus one bare ``<start> <end>``
#: timestamp line carrying no prefix)
META_LINE_REGISTRY = (
    StampSpec("Args:", "rnb_tpu/benchmark.py",
              "argparse-style repr of the launch arguments"),
    StampSpec("Termination flag:", "rnb_tpu/benchmark.py",
              "job termination reason code (TerminationFlag)"),
    StampSpec("Faults:", "rnb_tpu/benchmark.py",
              "job-wide num_failed/num_shed/num_retries counters"),
    StampSpec("Failure reasons:", "rnb_tpu/benchmark.py",
              "JSON per-reason contained-failure counts"),
    StampSpec("Shed sites:", "rnb_tpu/benchmark.py",
              "JSON per-site shed counts"),
    StampSpec("Queue overflows:", "rnb_tpu/benchmark.py",
              "JSON per-edge abort-policy queue-overflow counts"),
    StampSpec("Cache:", "rnb_tpu/benchmark.py",
              "clip-cache counters (cache-enabled runs only)"),
    StampSpec("Staging:", "rnb_tpu/benchmark.py",
              "zero-copy decode-staging pool counters "
              "(staging-enabled runs only)"),
    StampSpec("Pages:", "rnb_tpu/benchmark.py",
              "paged device-memory counters (rnb_tpu.pager): arena/"
              "page occupancy (live/limbo/bytes), page allocs/frees/"
              "alloc_fails, gather dispatches + rows split clip vs "
              "feature plane, feature-cache lookups/hits/inserts/"
              "evictions/bytes_saved, and emissions that shipped "
              "zero host->device bytes (pager-enabled runs only; "
              "--check holds allocs == frees + live at teardown, "
              "feature_hits <= feature_lookups, and gather_rows <= "
              "the ragged cache_hit_rows they serve)"),
    StampSpec("Autotune:", "rnb_tpu/benchmark.py",
              "load-adaptive batching controller counters "
              "(autotune-enabled runs only)"),
    StampSpec("Autotune buckets:", "rnb_tpu/benchmark.py",
              "JSON per-chosen-bucket emission counts "
              "(autotune-enabled runs only)"),
    StampSpec("Ragged:", "rnb_tpu/benchmark.py",
              "ragged row-pool dispatch counters: pool capacity, "
              "emissions, valid rows, pad rows the bucketed rule "
              "would have shipped (ragged-enabled runs only)"),
    StampSpec("Shard:", "rnb_tpu/benchmark.py",
              "intra-stage shard counters: declared-degree steps, max "
              "degree, logits-path merge gathers, their summed "
              "host-timed microseconds, valid rows crossing sharded "
              "stages (declared-shard runs only; --check holds "
              "degree x replicas <= the device budget and "
              "collective_us <= the inference span sum)"),
    StampSpec("Shard steps:", "rnb_tpu/benchmark.py",
              "JSON per-step shard detail: degree/axis, merge-gather "
              "counters, projected vs budget per-device MiB, min "
              "feasible degree (declared-shard runs only)"),
    StampSpec("Padding:", "rnb_tpu/benchmark.py",
              "bucketed-path padding waste: pad rows / total shipped "
              "rows / emissions summed over batching stages"),
    StampSpec("Compiles:", "rnb_tpu/benchmark.py",
              "JSON per-step jit-entry signature counts "
              "{step: {warmup, steady_new, steady_calls}} — "
              "steady_new > 0 means a mid-run recompile"),
    StampSpec("Warmup:", "rnb_tpu/benchmark.py",
              "JSON per-step stage-construction wall seconds "
              "(weights + warmup compiles)"),
    StampSpec("Handoff:", "rnb_tpu/benchmark.py",
              "device-resident handoff counters: edge takes split "
              "d2d vs host with bytes each class moved "
              "(handoff-enabled runs only; d2d+host == edges, "
              "host_bytes == 0 on device-resident edges)"),
    StampSpec("Handoff edges:", "rnb_tpu/benchmark.py",
              "JSON per-edge-label handoff counters "
              "(handoff-enabled runs only)"),
    StampSpec("Placement:", "rnb_tpu/benchmark.py",
              "JSON measured-cost placement report: per-step dispatch "
              "costs, predicted occupancy, recommended replica plan "
              "(placement-enabled runs only; --check holds the "
              "prediction to the traced busy fraction)"),
    StampSpec("Health:", "rnb_tpu/benchmark.py",
              "lane health/circuit-breaker counters: lanes, state "
              "transitions, circuit opens, evictions, half-open "
              "probes, redispatched items, routes to open lanes "
              "(health-enabled replica runs only; --check holds "
              "routes_after_open to 0 and replays every lane's "
              "transition path against the legal automaton)"),
    StampSpec("Health lanes:", "rnb_tpu/benchmark.py",
              "JSON per-lane health detail: final state, transition "
              "path, redispatched-from count "
              "(health-enabled replica runs only)"),
    StampSpec("Deadline:", "rnb_tpu/benchmark.py",
              "deadline-propagation counters: configured budget_ms "
              "and requests shed as deadline_expired "
              "(deadline-enabled runs only; per-site sheds must sum "
              "to the total)"),
    StampSpec("Deadline sites:", "rnb_tpu/benchmark.py",
              "JSON per-check-site deadline_expired shed counts "
              "(deadline-enabled runs only)"),
    StampSpec("Hedge:", "rnb_tpu/benchmark.py",
              "hedged re-dispatch counters: hedges fired, won by the "
              "hedge copy, lost (original resolved first), and the "
              "losers' wasted service milliseconds (hedge_ms runs "
              "only; won + lost == fired always — hedge compute is "
              "overhead, never throughput)"),
    StampSpec("Trace:", "rnb_tpu/benchmark.py",
              "trace-export counters: events written to trace.json, "
              "events dropped at the max_events cap "
              "(trace-enabled runs only)"),
    StampSpec("Metrics:", "rnb_tpu/benchmark.py",
              "live-metrics plane counters: interval snapshots "
              "appended to metrics.jsonl, distinct series, flight-"
              "recorder dumps written and triggers observed "
              "(metrics-enabled runs only; --check holds the final "
              "snapshot's counters to the Faults:/Cache:/Deadline:/"
              "Hedge: ledgers exactly)"),
    StampSpec("Slo:", "rnb_tpu/benchmark.py",
              "live SLO-layer counters: completions tracked / within "
              "deadline / missed, plus the run's peak burn rate in "
              "milli-units (burn 1000 = consuming the error budget "
              "exactly; metrics-enabled runs only)"),
    StampSpec("Phases:", "rnb_tpu/benchmark.py",
              "JSON per-phase latency attribution "
              "{phase: {mean_ms, p99_ms, count}} over steady-state "
              "completions (trace-enabled runs only)"),
    StampSpec("Compute:", "rnb_tpu/benchmark.py",
              "device-compute plane counters (rnb_tpu.devobs): "
              "flops-bearing stages, dispatches, valid rows, total "
              "achieved FLOPs, measured window, job tflops/mfu in "
              "bench.py's exact rounding (tflops_milli / mfu_e4; "
              "mfu_e4=-1 when the device peak is unknown), capture "
              "windows taken (devobs-enabled runs only; --check "
              "cross-foots flops against per-row counts x rows, "
              "recomputes tflops_milli, and bounds the mfu)"),
    StampSpec("Compute stages:", "rnb_tpu/benchmark.py",
              "JSON per-stage roofline detail: rows, dispatches, "
              "flops_per_row, busy_us, achieved tflops_busy, "
              "mfu_busy vs the device peak, arithmetic intensity "
              "from XLA cost_analysis bytes "
              "(devobs-enabled runs only)"),
    StampSpec("Memory:", "rnb_tpu/benchmark.py",
              "HBM footprint ledger totals (rnb_tpu.memledger): "
              "declared owners, devices, resident/peak bytes, "
              "watermark threshold and crossings, backend "
              "live-buffer bytes and the reconciliation verdict "
              "(devobs-enabled runs only; --check asserts owner "
              "rows sum to the total and peak >= final)"),
    StampSpec("Memory owners:", "rnb_tpu/benchmark.py",
              "JSON per-owner footprint detail {owner: {bytes, "
              "peak_bytes}} — owners are declared in "
              "memledger.MEM_OWNER_REGISTRY "
              "(devobs-enabled runs only)"),
    StampSpec("Critpath:", "rnb_tpu/benchmark.py",
              "critical-path extraction counters (rnb_tpu.critpath): "
              "requests whose blocking chain was recovered, chain "
              "segments, worst per-request partition residual in "
              "microseconds, hedge-won and redispatched completions, "
              "and the binding stage's critical-path throughput "
              "bound (bound_step / bound_vps_milli) "
              "(critpath-enabled runs only; --check re-derives every "
              "field from the timing tables and holds the partition "
              "residual under 1 ms per request)"),
    StampSpec("Critpath stages:", "rnb_tpu/benchmark.py",
              "JSON per-stage blocking attribution: lanes, per-"
              "(class) blocked totals/means over steady completions, "
              "occupied ms and the stage's critical-path throughput "
              "bound (critpath-enabled runs only)"),
    StampSpec("Whatif:", "rnb_tpu/benchmark.py",
              "calibrated queueing-model counters (rnb_tpu.whatif): "
              "stages calibrated from the metrics plane, whether "
              "calibration succeeded, the model's self-predicted "
              "throughput in milli-vps and its bottleneck step "
              "(whatif-enabled runs only; --check recomputes the "
              "prediction from metrics.jsonl + the config copy alone "
              "and holds it to +-1 milli-vps)"),
    StampSpec("Operator:", "rnb_tpu/benchmark.py",
              "operator-plane request ledger (rnb_tpu.statusz): GET "
              "scrapes served, POST actions accepted, POST actions "
              "denied by the allow_actions gate, request errors "
              "(operator-enabled runs only; --check holds the line "
              "to the logs/<job>/operator.json artifact both ways)"),
    StampSpec("Stacks:", "rnb_tpu/benchmark.py",
              "wall-clock stack sampler counters "
              "(rnb_tpu.stacksampler): sampling ticks, distinct "
              "thread roles, distinct folded stacks, total per-"
              "thread samples (operator runs with sample_hz > 0 "
              "only; --check re-sums stacks.folded to total and "
              "holds ticks to sample_hz x wall within tolerance)"),
    StampSpec("Net:", "rnb_tpu/benchmark.py",
              "cross-host ingest edge counters (rnb_tpu.netedge): "
              "frames sent/acked, resends + resent_pending at "
              "teardown, heartbeats seen, reconnect cycles, "
              "remote vs local-fallback dispatch split, dedup drops "
              "vs duplicate arrivals, wire/frame byte totals, "
              "window strands, opened-before-timeout flag (netedge-"
              "enabled runs only; --check holds "
              "frames_sent == frames_acked + resent_pending and "
              "dedup_drops == dup_arrivals)"),
    StampSpec("Net errors:", "rnb_tpu/benchmark.py",
              "per-class network fault counts off the PR 1 taxonomy "
              "(refused/reset/timeout/partial_frame/corrupt); "
              "--check re-sums the classes to total"),
    StampSpec("Locks:", "rnb_tpu/benchmark.py",
              "lock-order witness ledger (rnb_tpu.lockwitness, root "
              "`lint.lock_witness` config key): witnessed locks, "
              "total acquisitions, distinct acquisition-order edges, "
              "discipline violations (order inversions + non-LIFO "
              "releases + require() failures) — witness-enabled runs "
              "only; --check holds violations to zero and the "
              "Lock edges: detail to edges/violations counts"),
    StampSpec("Lock edges:", "rnb_tpu/benchmark.py",
              "JSON detail for the Locks: line: the observed "
              "acquisition-order edges and any violation records; "
              "--check holds every observed edge to the static "
              "RNB-C lock-order graph (observed subset-of declared, "
              "so a runtime order the analyzer never blessed fails "
              "offline)"),
)

#: every ``# <kind> ...`` trailer a per-instance timing table may carry
#: (TimeCardSummary.save_full_report)
TABLE_TRAILER_REGISTRY = (
    StampSpec("faults", "rnb_tpu/telemetry.py",
              "per-instance failed/shed/retry counts + reasons"),
    StampSpec("cache", "rnb_tpu/telemetry.py",
              "per-instance completed-request cache attribution"),
    StampSpec("phases", "rnb_tpu/telemetry.py",
              "per-instance per-phase latency attribution "
              "(mean/p99 microseconds; trace-enabled runs only)"),
    StampSpec("padding", "rnb_tpu/telemetry.py",
              "per-instance pad rows shipped with completed requests "
              "(0 under ragged dispatch)"),
    StampSpec("critpath", "rnb_tpu/telemetry.py",
              "per-instance blocking-chain totals: microseconds "
              "blocked per (class, step) segment over steady "
              "completions (critpath-enabled runs only)"),
)


#: every span/instant/counter name the tracing layer (rnb_tpu.trace)
#: may emit into logs/<job>/trace.json — ``{step}`` stands for the
#: pipeline-step or queue index, formatted at the ``trace.name`` call
#: site. The static schema checker (rnb_tpu.analysis.schema,
#: RNB-T008) cross-checks these declarations against the actual
#: instrumentation sites, so a trace event can neither appear
#: unregistered nor linger registered after its site is deleted.
TRACE_EVENT_REGISTRY = (
    StampSpec("client.enqueue", "rnb_tpu/client.py",
              "instant: client created + enqueued one request (flow "
              "anchor for the request id)"),
    StampSpec("client.enqueued", "rnb_tpu/client.py",
              "counter: cumulative requests the client has emitted"),
    StampSpec("client.shed", "rnb_tpu/client.py",
              "instant: client dropped a request at the full filename "
              "queue (overload_policy shed)"),
    StampSpec("exec{step}.queue_get", "rnb_tpu/runner.py",
              "span: executor blocked on its input queue (starvation)"),
    StampSpec("exec{step}.hold_wait", "rnb_tpu/runner.py",
              "span: executor blocked while its stage holds work "
              "(batch-fill wait, not starvation)"),
    StampSpec("exec{step}.swallow", "rnb_tpu/runner.py",
              "instant: one request admitted into the stage"),
    StampSpec("exec{step}.model_call", "rnb_tpu/runner.py",
              "span: the stage model call for one dispatch"),
    StampSpec("exec{step}.device_sync", "rnb_tpu/runner.py",
              "span: blocking on device output readiness "
              "(sync_outputs)"),
    StampSpec("exec{step}.publish", "rnb_tpu/runner.py",
              "span: route + ring write + downstream enqueue"),
    StampSpec("exec{step}.handoff", "rnb_tpu/runner.py",
              "span: the edge contract's payload take — adopt or "
              "reshard the committed upstream arrays onto this "
              "consumer (handoff-enabled runs only)"),
    StampSpec("exec{step}.redispatch", "rnb_tpu/runner.py",
              "span: an evicted replica lane's executor re-enqueues "
              "one queued-but-undispatched item onto a healthy "
              "sibling lane (health-enabled chaos runs only)"),
    StampSpec("exec{step}.collective", "rnb_tpu/models/r2p1d/model.py",
              "span: the sharded stage's cross-shard logits merge "
              "gather, host-timed around the separate merge jit "
              "(declared shard_degree > 1 only; nested inside the "
              "step's model_call span — the collective tax, never "
              "extra wall)"),
    StampSpec("health.lane_state", "rnb_tpu/health.py",
              "instant: a replica lane's health state transition "
              "(args: lane, from, to, why) — the timeline face of "
              "the Health lanes: path log"),
    StampSpec("loader.decode_submit", "rnb_tpu/models/r2p1d/model.py",
              "instant: one request's decode submitted to the pool"),
    StampSpec("loader.decode", "rnb_tpu/models/r2p1d/model.py",
              "span: fallback-pool decode body (rnb-decode threads; "
              "native-pool decodes run in C++ and are delimited by "
              "the submit/ready instants instead)"),
    StampSpec("loader.decode_ready", "rnb_tpu/models/r2p1d/model.py",
              "instant: one request's decode observed complete"),
    StampSpec("loader.emit", "rnb_tpu/models/r2p1d/model.py",
              "span: fused-batch take/assemble/handoff"),
    StampSpec("loader.transfer", "rnb_tpu/models/r2p1d/model.py",
              "span: host->device device_put (+ confirm/preprocess "
              "dispatch) — executor thread or transfer worker"),
    StampSpec("loader.s{step}.inflight", "rnb_tpu/models/r2p1d/model.py",
              "counter (sampled): decodes in flight + decoded-but-"
              "unemitted requests held by the loader"),
    StampSpec("staging.s{step}.free", "rnb_tpu/models/r2p1d/model.py",
              "counter (sampled): free staging slots in the loader's "
              "pool"),
    StampSpec("staging.acquire_wait", "rnb_tpu/staging.py",
              "span: blocked acquiring a staging slot (exhaustion "
              "backpressure)"),
    StampSpec("transfer.job", "rnb_tpu/staging.py",
              "span: one queued job on the transfer worker thread"),
    StampSpec("batcher.emit", "rnb_tpu/batcher.py",
              "instant: the Batcher fused + emitted one batch "
              "(args: requests, rows)"),
    StampSpec("autotune.decision", "rnb_tpu/autotune.py",
              "instant: one BatchController decision (args: verdict, "
              "target_rows, hold_ms)"),
    StampSpec("queue.filename.depth", "rnb_tpu/benchmark.py",
              "counter (sampled): client filename queue depth"),
    StampSpec("queue.e{step}.depth", "rnb_tpu/benchmark.py",
              "counter (sampled): inter-stage queue depth, keyed by "
              "queue index"),
)


#: one declared live-metric series (rnb_tpu.metrics): ``pattern`` uses
#: ``{step}`` like the other registries; ``kind`` is the series type
#: (counter | gauge | rate | histogram); ``source`` says where samples
#: come from — ``site`` (a ``metrics.counter/gauge/observe/mark/name``
#: call site, which rnb-lint RNB-T009 requires to exist), ``bridge``
#: (fed from same-named rnb_tpu.trace events through the SpanBridge —
#: no metrics call site exists by design), ``poll`` (read from a
#: subsystem's snapshot() each flusher tick) or ``derived`` (computed
#: inside the registry, e.g. the SLO burn gauge).
MetricSpec = namedtuple("MetricSpec",
                        ("pattern", "kind", "source", "description"))

#: every live-metric series name the tree may emit
#: (``logs/<job>/metrics.jsonl`` + the Prometheus exposition file) —
#: rnb-lint RNB-T009 cross-checks call sites against this, and the
#: runtime registry rejects undeclared names outright
METRIC_REGISTRY = (
    # -- client (site-sourced) ----------------------------------------
    MetricSpec("client.arrivals", "rate", "site",
               "windowed request arrival rate at the client"),
    MetricSpec("client.requests", "counter", "site",
               "requests the client has created"),
    MetricSpec("client.shed", "counter", "site",
               "requests the client dropped at the full filename "
               "queue"),
    # -- executor hot loop (bridged from trace spans) -----------------
    MetricSpec("exec{step}.queue_get", "histogram", "bridge",
               "executor input-queue starvation wait (ms)"),
    MetricSpec("exec{step}.hold_wait", "histogram", "bridge",
               "executor batch-fill hold wait (ms)"),
    MetricSpec("exec{step}.model_call", "histogram", "bridge",
               "stage model-call service time (ms)"),
    MetricSpec("exec{step}.device_sync", "histogram", "bridge",
               "device output readiness wait (ms)"),
    MetricSpec("exec{step}.publish", "histogram", "bridge",
               "route + ring write + downstream enqueue (ms)"),
    MetricSpec("exec{step}.collective", "histogram", "bridge",
               "sharded-stage cross-shard logits merge gather (ms)"),
    MetricSpec("loader.emit", "histogram", "bridge",
               "fused-batch take/assemble/handoff (ms)"),
    MetricSpec("loader.transfer", "histogram", "bridge",
               "host->device transfer span (ms)"),
    MetricSpec("staging.acquire_wait", "histogram", "bridge",
               "staging-slot exhaustion backpressure wait (ms)"),
    MetricSpec("batcher.emit", "counter", "bridge",
               "Batcher fused emissions"),
    MetricSpec("autotune.decision", "counter", "bridge",
               "BatchController decisions"),
    MetricSpec("health.lane_state", "counter", "bridge",
               "lane health state transitions"),
    # -- queue occupancy (probed each flusher tick) -------------------
    MetricSpec("queue.filename.depth", "gauge", "site",
               "client filename queue depth (saturation-armed)"),
    MetricSpec("queue.e{step}.depth", "gauge", "site",
               "inter-stage queue depth by edge ordinal "
               "(saturation-armed)"),
    # -- autotune controller (site-sourced gauges) --------------------
    MetricSpec("autotune.arrival_hz", "gauge", "site",
               "controller arrival-rate EWMA at the last decision"),
    MetricSpec("autotune.target_rows", "gauge", "site",
               "controller target row count at the last decision"),
    # -- ledgers (polled from the shared stats objects) ---------------
    MetricSpec("faults.num_failed", "counter", "poll",
               "dead-lettered requests (FaultStats ledger)"),
    MetricSpec("faults.num_shed", "counter", "poll",
               "shed requests (FaultStats ledger)"),
    MetricSpec("faults.num_retries", "counter", "poll",
               "transient retry attempts (FaultStats ledger)"),
    MetricSpec("faults.sheds", "rate", "site",
               "windowed shed rate (shed-spike flight trigger)"),
    MetricSpec("deadline.expired", "counter", "poll",
               "requests shed as deadline_expired (DeadlineStats "
               "ledger)"),
    MetricSpec("hedge.fired", "counter", "poll",
               "hedged re-dispatches fired (HedgeGovernor ledger)"),
    MetricSpec("hedge.won", "counter", "poll",
               "hedges the clone copy won"),
    MetricSpec("hedge.lost", "counter", "poll",
               "hedges the original copy won"),
    MetricSpec("health.transitions", "counter", "poll",
               "lane state-machine hops (LaneHealthBoard)"),
    MetricSpec("health.opens", "counter", "poll",
               "lane circuit opens"),
    MetricSpec("health.evictions", "counter", "poll",
               "permanently dead lanes"),
    MetricSpec("health.probes", "counter", "poll",
               "half-open recovery probes"),
    MetricSpec("health.redispatches", "counter", "poll",
               "items drained off evicted lanes onto siblings"),
    MetricSpec("net.frames_sent", "counter", "poll",
               "REQ frames shipped across the ingest edge"),
    MetricSpec("net.frames_acked", "counter", "poll",
               "REQ frames the peer acknowledged (unique seqs)"),
    MetricSpec("net.resends", "counter", "poll",
               "REQ frames re-shipped after reconnect or ack loss"),
    MetricSpec("net.beats", "counter", "poll",
               "peer heartbeat frames received"),
    MetricSpec("net.reconnects", "counter", "poll",
               "successful re-dials after a connection died"),
    MetricSpec("net.remote", "counter", "poll",
               "requests dispatched across the wire"),
    MetricSpec("net.local", "counter", "poll",
               "requests routed to the in-process fallback"),
    MetricSpec("net.dedup_drops", "counter", "poll",
               "duplicate DATA/DISPOSE frames dropped by the "
               "receiver-side ledger (exactly-once guard)"),
    MetricSpec("net.dup_arrivals", "counter", "poll",
               "frames that arrived for an already-settled seq"),
    MetricSpec("net.wire_bytes", "counter", "poll",
               "total bytes received off the wire"),
    MetricSpec("net.frame_bytes", "counter", "poll",
               "DATA row-payload bytes received (valid rows only)"),
    MetricSpec("net.err_total", "counter", "poll",
               "classified network faults observed (all classes)"),
    MetricSpec("net.peer_depth", "gauge", "poll",
               "peer-reported in-flight depth (piggybacked on "
               "every ack/beat frame)"),
    # -- stage-owned subsystems (polled via metrics.register_stage) ---
    MetricSpec("cache.hits", "counter", "poll",
               "clip-cache lookup hits"),
    MetricSpec("cache.misses", "counter", "poll",
               "clip-cache lookup misses"),
    MetricSpec("cache.inserts", "counter", "poll",
               "clip-cache inserts"),
    MetricSpec("cache.evictions", "counter", "poll",
               "clip-cache LRU evictions"),
    MetricSpec("cache.coalesced", "counter", "poll",
               "requests that shared an in-flight decode"),
    MetricSpec("cache.oversize", "counter", "poll",
               "entries skipped as larger than the whole budget"),
    MetricSpec("cache.bytes_resident", "gauge", "poll",
               "resident cache bytes (shrinks on eviction)"),
    MetricSpec("cache.entries", "gauge", "poll",
               "resident cache entries"),
    MetricSpec("staging.acquires", "counter", "poll",
               "staging-slot acquires"),
    MetricSpec("staging.acquire_waits", "counter", "poll",
               "staging-slot exhaustion waits"),
    MetricSpec("staging.staged_batches", "counter", "poll",
               "zero-copy staged emissions"),
    MetricSpec("staging.copied_batches", "counter", "poll",
               "copy-fallback emissions"),
    MetricSpec("staging.reallocs", "counter", "poll",
               "alias-forced slot-buffer replacements"),
    MetricSpec("pages.allocs", "counter", "poll",
               "pages popped off arena free lists (rnb_tpu.pager)"),
    MetricSpec("pages.frees", "counter", "poll",
               "pages returned to arena free lists (incl. limbo "
               "releases at unpin)"),
    MetricSpec("pages.alloc_fails", "counter", "poll",
               "page allocations refused for lack of free pages "
               "(the caller evicts-and-retries or skips)"),
    MetricSpec("pages.gathers", "counter", "poll",
               "clip-arena gather kernels dispatched (one per "
               "emission with paged hit rows)"),
    MetricSpec("pages.gather_rows", "counter", "poll",
               "rows overlaid from clip pages onto emission pools "
               "(zero host bytes each)"),
    MetricSpec("pages.feature_lookups", "counter", "poll",
               "feature-cache probes at request admission"),
    MetricSpec("pages.feature_hits", "counter", "poll",
               "feature-cache hits (the request skips decode, "
               "transfer and the stage forward)"),
    MetricSpec("pages.feature_inserts", "counter", "poll",
               "feature entries written after a successful forward "
               "(insert-after-success only)"),
    MetricSpec("pages.feature_evictions", "counter", "poll",
               "LRU feature entries evicted to fit an insert"),
    MetricSpec("pages.feature_gathers", "counter", "poll",
               "feature-arena gather kernels dispatched (one per "
               "feature-hit emission)"),
    MetricSpec("pages.feature_gather_rows", "counter", "poll",
               "output rows gathered from feature pages"),
    MetricSpec("pages.feature_bytes_saved", "counter", "poll",
               "wire bytes feature hits did not ship host->device"),
    MetricSpec("pages.live", "gauge", "poll",
               "pages off the free lists (entry-held + limbo) across "
               "arenas"),
    MetricSpec("pages.limbo", "gauge", "poll",
               "evicted-but-still-pinned pages awaiting unpin"),
    MetricSpec("pages.bytes", "gauge", "poll",
               "total arena slab bytes (the page_pool HBM claim)"),
    MetricSpec("staging.slots", "gauge", "poll",
               "allocated staging slots"),
    MetricSpec("handoff.d2d_edges", "counter", "poll",
               "device-resident edge takes"),
    MetricSpec("handoff.host_edges", "counter", "poll",
               "host-round-trip edge takes"),
    MetricSpec("handoff.d2d_bytes", "counter", "poll",
               "bytes adopted/resharded on-device"),
    MetricSpec("handoff.host_bytes", "counter", "poll",
               "bytes moved through host memory"),
    # -- device observability plane (polled from rnb_tpu.devobs) ------
    MetricSpec("compute.s{step}.rows", "counter", "poll",
               "valid rows a flops-bearing stage dispatched"),
    MetricSpec("compute.s{step}.dispatches", "counter", "poll",
               "model-call dispatches the compute meter observed"),
    MetricSpec("compute.s{step}.tflops", "gauge", "poll",
               "achieved TFLOP/s over the stage's busy time "
               "(declared per-row FLOPs x rows / busy seconds)"),
    MetricSpec("compute.s{step}.mfu", "gauge", "poll",
               "busy-time MFU vs the device peak (absent when the "
               "platform has no known peak — never guessed)"),
    MetricSpec("memory.total_bytes", "gauge", "poll",
               "HBM footprint ledger total across declared owners"),
    MetricSpec("memory.peak_bytes", "gauge", "poll",
               "ledger high-water mark (monotone)"),
    MetricSpec("memory.params_bytes", "gauge", "poll",
               "device-resident network parameter bytes (deduped "
               "across replicas sharing one copy)"),
    MetricSpec("memory.cache_bytes", "gauge", "poll",
               "clip-cache resident bytes as a ledger owner"),
    MetricSpec("memory.staging_bytes", "gauge", "poll",
               "staging-slot slab bytes as a ledger owner"),
    MetricSpec("memory.ragged_pool_bytes", "gauge", "poll",
               "ragged pool dispatch-shape bytes as a ledger owner"),
    MetricSpec("memory.page_pool_bytes", "gauge", "poll",
               "page-allocator arena slab + shared-pool bytes "
               "(memledger page_pool owner, rnb_tpu.pager)"),
    MetricSpec("memory.handoff_bytes", "gauge", "poll",
               "bytes resident from the latest edge adoptions"),
    # -- the live SLO layer (derived inside the registry) -------------
    MetricSpec("slo.good", "rate", "derived",
               "windowed within-deadline completions"),
    MetricSpec("slo.miss", "rate", "site",
               "windowed SLO violations: late completions + "
               "shed/failed requests"),
    MetricSpec("slo.tracked", "counter", "derived",
               "completions the SLO layer observed"),
    MetricSpec("slo.within", "counter", "derived",
               "completions inside their deadline/budget"),
    MetricSpec("slo.missed", "counter", "derived",
               "completions outside their deadline/budget"),
    MetricSpec("slo.goodput_vps", "gauge", "derived",
               "windowed within-deadline goodput (completions/s)"),
    MetricSpec("slo.burn_rate", "gauge", "derived",
               "windowed miss fraction / error budget (1.0 = "
               "consuming the budget exactly)"),
)


class TimeCard:
    """An ordered event->timestamp record that rides along with a request.

    Reference behavior: rnb_logging.py:22-123. Supports single-level
    fork (one child per parallel segment) and merge (recombine siblings:
    pre-fork events kept once, post-fork events suffixed ``-{sub_id}``,
    device trails merged positionally).
    """

    def __init__(self, id: int):
        self.timings: "OrderedDict[str, float]" = OrderedDict()
        self.id = id
        self.sub_id: Optional[int] = None
        self.num_parent_timings: Optional[int] = None
        # One entry per pipeline step traversed; each entry is a tuple of
        # device labels (singleton until a merge combines segments that ran
        # on different devices).
        self.devices: List[tuple] = []
        # request outcome: "ok" until the containment layer stamps the
        # card "failed" (dead-lettered) or "shed" (dropped under the
        # "shed" overload policy) — rnb_tpu.runner / rnb_tpu.client
        self.status: str = "ok"
        self.failure_reason: Optional[str] = None

    def mark_failed(self, reason: str) -> None:
        """Stamp this request permanently failed (dead-letter path)."""
        self.status = "failed"
        self.failure_reason = str(reason)

    def mark_shed(self, site: str) -> None:
        """Stamp this request dropped by the overload policy."""
        self.status = "shed"
        self.failure_reason = str(site)

    def record(self, key: str, at: Optional[float] = None) -> None:
        """Stamp event ``key`` with the current wall-clock time (or a
        caller-supplied instant, for events shared across cards)."""
        self.timings[key] = time.time() if at is None else at

    def add_device(self, device_label: str) -> None:
        """Append a pipeline-step device visit to the trail."""
        self.devices.append((device_label,))

    def fork(self, sub_id: int) -> "TimeCard":
        """Clone this card for one parallel segment.

        The clone keeps the same id and a copy of all timings; the fork
        point is remembered so merge() knows which events are shared.
        Two-level forking is rejected — merge before forking again
        (reference invariant, rnb_logging.py:56-62).
        """
        if self.sub_id is not None:
            raise RuntimeError(
                "cannot fork TimeCard(id=%s) twice: it is already a fork "
                "with sub_id=%s; merge first" % (self.id, self.sub_id))
        child = TimeCard(self.id)
        child.timings = OrderedDict(self.timings)
        child.sub_id = sub_id
        child.num_parent_timings = len(self.timings)
        for attr in CONTENT_STAMPS:
            # content stamps (loader's num_clips / cache outcome) ride
            # along with every segment so routing, clip accounting and
            # cache attribution survive the fork
            if hasattr(self, attr):
                setattr(child, attr, getattr(self, attr))
        child.devices = list(self.devices)
        child.status = self.status
        child.failure_reason = self.failure_reason
        return child

    @staticmethod
    def merge(time_cards: Sequence["TimeCard"]) -> "TimeCard":
        """Recombine sibling forks into one card.

        All inputs must share id-independent structure: identical timing
        keys and identical fork points. Events recorded before the fork
        are emitted once; events after the fork are emitted per sibling
        with a ``-{sub_id}`` suffix, ordered by sub_id. Device trails are
        zipped positionally: a step where every sibling used the same
        device collapses to a singleton, otherwise the full tuple is kept
        (reference behavior, rnb_logging.py:72-123).
        """
        if not time_cards:
            raise ValueError("merge() needs at least one TimeCard")
        first = time_cards[0]
        keys = list(first.timings.keys())
        fork_point = first.num_parent_timings
        seen_sub_ids = set()
        for tc in time_cards:
            if tc.sub_id is None:
                raise RuntimeError(
                    "cannot merge TimeCard(id=%s): not a fork (sub_id is "
                    "None); only sibling forks can be merged" % tc.id)
            if tc.sub_id in seen_sub_ids:
                raise RuntimeError(
                    "cannot merge TimeCards with duplicate sub_id=%s"
                    % tc.sub_id)
            seen_sub_ids.add(tc.sub_id)
        for tc in time_cards[1:]:
            if list(tc.timings.keys()) != keys:
                raise RuntimeError(
                    "cannot merge TimeCards with different timing keys: "
                    "%s != %s" % (keys, list(tc.timings.keys())))
            if tc.num_parent_timings != fork_point:
                raise RuntimeError(
                    "cannot merge TimeCards forked at different points: "
                    "%s != %s" % (fork_point, tc.num_parent_timings))
        ordered = sorted(time_cards, key=lambda tc: tc.sub_id)

        merged = TimeCard(first.id)
        for key_idx, key in enumerate(keys):
            if fork_point is not None and key_idx < fork_point:
                merged.timings[key] = ordered[0].timings[key]
            else:
                for tc in ordered:
                    merged.timings["%s-%s" % (key, tc.sub_id)] = tc.timings[key]

        for step_devices in zip(*[tc.devices for tc in ordered]):
            flat = tuple(d for tpl in step_devices for d in tpl)
            if len(set(flat)) == 1:
                merged.devices.append((flat[0],))
            else:
                merged.devices.append(flat)
        for attr in CONTENT_STAMPS:
            # content stamps are per-request, identical on every
            # sibling fork — keep them once
            if hasattr(ordered[0], attr):
                setattr(merged, attr, getattr(ordered[0], attr))
        for tc in ordered:
            # one failed segment fails the merged request
            if tc.status != "ok":
                merged.status = tc.status
                merged.failure_reason = tc.failure_reason
                break
        return merged


class TimeCardList:
    """Broadcast wrapper over the cards of a dynamically-batched request.

    Produced by the Batcher stage so that one fused inference still stamps
    events on every constituent request's card (reference
    rnb_logging.py:126-142). Forking a batched card is not meaningful.
    """

    def __init__(self, time_cards: List[TimeCard]):
        self.time_cards = time_cards

    def record(self, key: str, at: Optional[float] = None) -> None:
        # one event, one instant: every constituent of a fused batch
        # gets the SAME stamp (per-card time.time() calls would drift
        # by microseconds, breaking offline dispatch-grouping — one
        # fused jit call IS one event for all its constituents)
        at = time.time() if at is None else at
        for tc in self.time_cards:
            tc.record(key, at=at)

    def add_device(self, device_label: str) -> None:
        for tc in self.time_cards:
            tc.add_device(device_label)

    def fork(self, sub_id: int) -> "TimeCard":
        raise NotImplementedError("TimeCardLists cannot be forked")

    def __len__(self) -> int:
        return len(self.time_cards)


class TimeCardSummary:
    """Columnar accumulator over completed requests' TimeCards.

    Assumes every registered card carries the identical event-key sequence
    (true per final-step instance because the pipeline topology is fixed);
    prints mean inter-event gaps and persists a whitespace table with one
    row per request plus per-step device columns (split per segment when a
    step ran on several devices). Reference: rnb_logging.py:145-214.
    """

    def __init__(self):
        self.summary: "OrderedDict[str, List[float]]" = OrderedDict()
        self.keys: List[str] = []
        self.devices_per_inference: List[List[tuple]] = []
        # per-record clip counts (0 when the pipeline never stamped
        # num_clips) — feeds clips/sec and MFU accounting in bench.py
        self.clip_counts: List[int] = []
        # fault accounting (rnb_tpu.runner containment): failed/shed
        # requests never enter the columnar timing data, so latency
        # percentiles stay success-only; the counters keep the summary
        # honest about what the instance dropped along the way.
        # num_shed is part of the schema for symmetry with the
        # controller's FaultStats but is structurally 0 in current
        # topologies: sheds happen at the client and at producing
        # (non-final) stages, while a summary exists only on final-step
        # instances — job-level shed counts live in FaultStats/log-meta
        self.num_failed: int = 0
        self.num_shed: int = 0
        self.num_retries: int = 0
        self.failure_reasons: "OrderedDict[str, int]" = OrderedDict()
        # decoded-clip cache attribution (rnb_tpu.cache): registered
        # completions whose card carries a cache_hit stamp. tracked=0
        # means the pipeline ran cacheless and the report stays
        # byte-stable with the pre-cache schema.
        self.num_cache_hits: int = 0
        self.num_cache_coalesced: int = 0
        self.num_cache_tracked: int = 0
        # padding-waste attribution: pad rows the emissions carrying
        # the registered completions shipped (stamped on each
        # emission's first constituent card by the batching stages;
        # tracked=0 keeps pre-padding-era reports byte-stable)
        self.num_pad_rows: int = 0
        self.num_pad_tracked: int = 0
        # per-request phase attribution (rnb_tpu.trace): surfaced as a
        # `# phases` trailer + the job-wide `Phases:` line ONLY when
        # the executor opts this summary in (trace-enabled runs) —
        # trace-off reports stay byte-stable with the earlier schema
        self.track_phases: bool = False
        self.phase_num_skips: int = 0
        # blocking-chain extraction (rnb_tpu.critpath): the hedge/
        # redispatch content stamps are captured per completion
        # unconditionally (cheap ints, like clip_counts) so the
        # chain aggregation stays hedge-aware, but the `# critpath`
        # trailer is written only when the executor opts this summary
        # in (root 'critpath' config key) — earlier reports stay
        # byte-stable
        self.track_critpath: bool = False
        self.critpath_num_skips: int = 0
        self.hedge_flags: List[bool] = []
        self.redispatch_counts: List[int] = []

    def note_failure(self, reason: str, n: int = 1) -> None:
        """Count a contained permanent failure (excluded from timings)."""
        self.num_failed += n
        self.failure_reasons[reason] = \
            self.failure_reasons.get(reason, 0) + n

    def note_shed(self, n: int = 1) -> None:
        self.num_shed += n

    def note_retries(self, n: int = 1) -> None:
        self.num_retries += n

    def register(self, time_card: TimeCard) -> None:
        if not self.summary:
            self.keys = list(time_card.timings.keys())
            for key in self.keys:
                self.summary[key] = []
        if self.keys != list(time_card.timings.keys()):
            raise AssertionError(
                "TimeCard key sequence changed mid-run: %s != %s"
                % (self.keys, list(time_card.timings.keys())))
        for key, ts in time_card.timings.items():
            self.summary[key].append(ts)
        self.devices_per_inference.append(time_card.devices)
        # clip_counts feeds clips/sec and MFU — DEVICE-WORK accounting.
        # A coalesced follower's rows were computed once, on the
        # leader's card; counting them again would inflate the device
        # utilization the honesty policy protects, so followers
        # contribute 0 here (their num_clips stamp remains on the card
        # for routing/request-level analysis).
        coalesced = getattr(time_card, "cache_coalesced", False)
        self.clip_counts.append(
            0 if coalesced else int(getattr(time_card, "num_clips", 0)))
        hit = getattr(time_card, "cache_hit", None)
        if hit is not None:
            self.num_cache_tracked += 1
            if hit:
                self.num_cache_hits += 1
        if getattr(time_card, "cache_coalesced", False):
            self.num_cache_coalesced += 1
        pad = getattr(time_card, "pad_rows", None)
        if pad is not None:
            self.num_pad_tracked += 1
            self.num_pad_rows += int(pad)
        # claim-ledger stamps (rnb_tpu.health): did the hedge clone
        # win this completion, and how often was it drained off an
        # evicted lane — the critical-path aggregation reports both
        self.hedge_flags.append(
            bool(getattr(time_card, "hedge_copy", False)))
        self.redispatch_counts.append(
            int(getattr(time_card, "redispatched", 0)))

    def total_clips(self) -> int:
        """Sum of registered records' ``num_clips`` stamps."""
        return sum(self.clip_counts)

    def num_records(self) -> int:
        return len(self.summary[self.keys[0]]) if self.keys else 0

    def mean_gaps_ms(self, num_skips: int = 0):
        """[(prev_key, next_key, mean_ms)] over records after `num_skips`."""
        import numpy as np
        out = []
        for prv, nxt in zip(self.keys[:-1], self.keys[1:]):
            if len(self.summary[prv]) <= num_skips:
                return out
            gap = np.mean(
                (np.asarray(self.summary[nxt][num_skips:])
                 - np.asarray(self.summary[prv][num_skips:])) * 1000.0)
            out.append((prv, nxt, float(gap)))
        return out

    def latencies_ms(self, num_skips: int = 0):
        """Per-record end-to-end latency (first event -> last event) in
        ms over records after ``num_skips``."""
        import numpy as np
        if not self.keys or len(self.keys) < 2:
            return []
        first = np.asarray(self.summary[self.keys[0]][num_skips:])
        last = np.asarray(self.summary[self.keys[-1]][num_skips:])
        return ((last - first) * 1000.0).tolist()

    def latency_percentiles_ms(self, num_skips: int = 0,
                               percentiles=(50.0, 99.0)):
        """End-to-end latency percentiles in ms; {} when there are not
        enough records."""
        return latency_percentiles(self.latencies_ms(num_skips),
                                   percentiles)

    def print_summary(self, num_skips: int) -> None:
        gaps = self.mean_gaps_ms(num_skips)
        if not gaps and self.keys:
            print("Not enough log entries (%d records) to print summary!"
                  % self.num_records())
        for prv, nxt, ms in gaps:
            print("Average time between %s and %s: %f ms" % (prv, nxt, ms))
        if self.num_failed or self.num_shed or self.num_retries:
            print("Contained faults: %d failed, %d shed, %d retries (%s)"
                  % (self.num_failed, self.num_shed, self.num_retries,
                     ", ".join("%s=%d" % kv
                               for kv in self.failure_reasons.items())
                     or "no failures"))
        if self.num_cache_tracked:
            print("Clip cache: %d/%d completions were hits, %d coalesced"
                  % (self.num_cache_hits, self.num_cache_tracked,
                     self.num_cache_coalesced))

    def faults_line(self) -> Optional[str]:
        """The ``# faults ...`` trailer of the full report, or None when
        every request succeeded (keeping fault-free reports byte-stable
        with the pre-containment schema)."""
        if not (self.num_failed or self.num_shed or self.num_retries):
            return None
        parts = ["# faults num_failed=%d num_shed=%d num_retries=%d"
                 % (self.num_failed, self.num_shed, self.num_retries)]
        parts.extend("reason:%s=%d" % kv
                     for kv in self.failure_reasons.items())
        return " ".join(parts)

    def cache_line(self) -> Optional[str]:
        """The ``# cache ...`` trailer, or None for cacheless runs
        (keeping their reports byte-stable with the pre-cache schema).
        Written even when hits=0 on a cache-enabled run — a zero
        hit-rate is a result, not an absence of data."""
        if not self.num_cache_tracked:
            return None
        return ("# cache num_hits=%d num_coalesced=%d num_tracked=%d"
                % (self.num_cache_hits, self.num_cache_coalesced,
                   self.num_cache_tracked))

    def padding_line(self) -> Optional[str]:
        """The ``# padding ...`` trailer, or None when no registered
        card carried a ``pad_rows`` stamp (pre-padding-era pipelines
        keep their byte-stable reports). pad_rows=0 on a tracked run
        is a result — exactly what a ragged arm should show."""
        if not self.num_pad_tracked:
            return None
        return ("# padding pad_rows=%d num_tracked=%d"
                % (self.num_pad_rows, self.num_pad_tracked))

    def steady_rows(self, num_skips: int = 0):
        """Yield ``(timings, hedged, redispatched)`` per record after
        ``num_skips`` — the critical-path aggregation's input
        (rnb_tpu.critpath.aggregate): each row's stamp mapping plus
        the claim-ledger content stamps captured at register()."""
        if not self.keys or len(self.keys) < 2:
            return
        columns = [self.summary[key][num_skips:] for key in self.keys]
        hedges = self.hedge_flags[num_skips:]
        redisps = self.redispatch_counts[num_skips:]
        for idx, row in enumerate(zip(*columns)):
            yield (dict(zip(self.keys, row)),
                   hedges[idx] if idx < len(hedges) else False,
                   redisps[idx] if idx < len(redisps) else 0)

    def critpath_line(self) -> Optional[str]:
        """The ``# critpath ...`` trailer, or None when extraction is
        off (critpath-disabled runs keep the earlier byte-stable
        schema) or no steady record decomposed. Microsecond integer
        totals per ``<class><step>`` segment so the generic
        ``key=value`` trailer parser reads it unchanged."""
        if not self.track_critpath:
            return None
        from rnb_tpu.critpath import trailer_totals
        n, totals = trailer_totals(
            timings for timings, _h, _r
            in self.steady_rows(self.critpath_num_skips))
        if not n:
            return None
        parts = ["# critpath n=%d" % n]
        parts.extend("%s_us=%d" % (key, totals[key])
                     for key in sorted(totals))
        return " ".join(parts)

    def phase_samples(self, num_skips: int = 0):
        """{phase: [per-request milliseconds]} over records after
        ``num_skips`` — the deterministic stamp-only decomposition
        (rnb_tpu.trace.attribute_phases) applied to this instance's
        columnar data. Phases partition each request's end-to-end
        span, so per-request sums equal latencies_ms() exactly."""
        from rnb_tpu.trace import attribute_phases
        samples: "OrderedDict[str, List[float]]" = OrderedDict()
        if not self.keys or len(self.keys) < 2:
            return samples
        columns = [self.summary[key][num_skips:] for key in self.keys]
        for row in zip(*columns):
            for phase, ms in attribute_phases(
                    dict(zip(self.keys, row))).items():
                samples.setdefault(phase, []).append(ms)
        return samples

    def phases_line(self) -> Optional[str]:
        """The ``# phases ...`` trailer, or None when phase tracking
        is off (trace-disabled runs keep the earlier byte-stable
        schema) or too few records exist. Microsecond integers so the
        generic ``key=value`` trailer parser reads it unchanged."""
        if not self.track_phases:
            return None
        from rnb_tpu.trace import phase_stats, sorted_phases
        stats = phase_stats(self.phase_samples(self.phase_num_skips))
        if not stats:
            return None
        count = max(s["count"] for s in stats.values())
        parts = ["# phases n=%d" % count]
        for phase in sorted_phases(stats):
            parts.append("%s_mean_us=%d"
                         % (phase, round(stats[phase]["mean_ms"] * 1000)))
            parts.append("%s_p99_us=%d"
                         % (phase, round(stats[phase]["p99_ms"] * 1000)))
        return " ".join(parts)

    def save_full_report(self, fp: IO[str]) -> None:
        # Per-step device-column widths can differ across records (a merge
        # collapses segments that happened to share a device); size each
        # step's columns to the widest record and pad narrower rows with
        # '-' so the whitespace table stays rectangular.
        num_steps = max((len(d) for d in self.devices_per_inference),
                        default=0)
        widths = [0] * num_steps
        for devices_per_step in self.devices_per_inference:
            for step_idx, step_devices in enumerate(devices_per_step):
                widths[step_idx] = max(widths[step_idx], len(step_devices))

        fp.write(" ".join(self.keys))
        for step_idx, width in enumerate(widths):
            if width > 1:
                for sub_id in range(width):
                    fp.write(" device%d-%d" % (step_idx, sub_id))
            else:
                fp.write(" device%d" % step_idx)
        fp.write("\n")
        for row, devices_per_step in zip(zip(*self.summary.values()),
                                         self.devices_per_inference):
            fp.write(" ".join(map(str, row)))
            for step_idx, width in enumerate(widths):
                step_devices = (devices_per_step[step_idx]
                                if step_idx < len(devices_per_step) else ())
                for col in range(width):
                    fp.write(" %s" % (step_devices[col]
                                      if col < len(step_devices) else "-"))
            fp.write("\n")
        faults = self.faults_line()
        if faults is not None:
            fp.write(faults + "\n")
        cache = self.cache_line()
        if cache is not None:
            fp.write(cache + "\n")
        padding = self.padding_line()
        if padding is not None:
            fp.write(padding + "\n")
        phases = self.phases_line()
        if phases is not None:
            fp.write(phases + "\n")
        critpath = self.critpath_line()
        if critpath is not None:
            fp.write(critpath + "\n")
