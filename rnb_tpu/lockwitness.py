"""Runtime lock-order witness: the checked-not-trusted face of the
static concurrency contracts (rnb_tpu.analysis.concurrency).

FreeBSD WITNESS in miniature: participating modules construct their
locks through :func:`lock` with a stable name (``"ClassName.attr"`` —
the same ``(class, attr)`` identity the static analyzer uses). When
the witness is **disabled** (the default), :func:`lock` returns a
plain ``threading.Lock``/``RLock`` — zero wrapper, zero overhead, and
runs produce byte-identical output to a build without this module.
When **enabled** (config ``lint: {lock_witness: true}``, or tests),
each acquisition records:

* the **order edge** (top of the acquiring thread's held stack ->
  the acquired lock) — at teardown the observed edge set must be a
  subset of the static acquisition-order graph
  (``parse_utils --check``);
* **violations**: an acquisition inverting an already-observed edge
  (the two-thread interleaving that deadlocks), releasing a lock the
  thread does not hold, and :func:`require` assertions — the runtime
  face of the ``*_locked`` naming convention — failing.

The summary feeds the ``Locks:`` / ``Lock edges:`` log-meta lines
(META_LINE_REGISTRY), so the static model and observed reality
cross-foot exactly like every other telemetry plane.
"""

from __future__ import annotations

import json
import threading
from typing import Dict, List, Optional, Set, Tuple

_enabled = False
_state = threading.local()          # per-thread held stack + tally
_reg_lock = threading.Lock()        # guards the module tallies below
_locks_created = 0
_tallies: List[List[int]] = []      # per-thread acquire counts
_tally_gen = 0                      # bumped by reset(): stale tallies
                                    # re-register instead of resurrect
_edges: Set[Tuple[str, str]] = set()
_violations: List[str] = []

#: cap so a pathological run cannot grow the violation list unbounded
MAX_VIOLATIONS = 100


def enabled() -> bool:
    return _enabled


def enable() -> None:
    """Turn the witness on for locks constructed from now on (call
    before the pipeline builds, i.e. before any participating class's
    ``__init__``)."""
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def reset() -> None:
    """Clear tallies (test isolation; enable/disable is separate)."""
    global _locks_created, _tally_gen
    with _reg_lock:
        _locks_created = 0
        _tally_gen += 1
        del _tallies[:]
        _edges.clear()
        del _violations[:]


def _held() -> List[str]:
    stack = getattr(_state, "held", None)
    if stack is None:
        stack = _state.held = []
    return stack


def _tally() -> List[int]:
    """This thread's acquire counter. Registered once per thread per
    reset() generation, so the hot acquire path is an uncontended
    list increment — never the registry lock (a witnessed suite must
    not serialize every lock in the process through one global)."""
    if getattr(_state, "tally_gen", None) == _tally_gen:
        return _state.tally
    t = [0]
    with _reg_lock:
        _state.tally = t
        _state.tally_gen = _tally_gen
        _tallies.append(t)
    return t


def _violation(msg: str) -> None:
    with _reg_lock:
        if len(_violations) < MAX_VIOLATIONS:
            _violations.append(msg)


class WitnessLock:
    """A named lock that records acquisition-order edges and order
    inversions. Context-manager and acquire/release compatible, so
    ``threading.Condition`` built on it works unchanged."""

    __slots__ = ("name", "_inner")

    def __init__(self, name: str, inner):
        self.name = name
        self._inner = inner

    def acquire(self, blocking: bool = True,
                timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._note_acquired()
        return got

    def _note_acquired(self) -> None:
        held = _held()
        _tally()[0] += 1
        if held and held[-1] != self.name and self.name not in held:
            edge = (held[-1], self.name)
            # GIL-safe racy pre-check: repeat edges (the steady state)
            # never touch the registry lock
            if edge not in _edges:
                with _reg_lock:
                    if edge not in _edges:
                        if (edge[1], edge[0]) in _edges \
                                and len(_violations) < MAX_VIOLATIONS:
                            _violations.append(
                                "order inversion: acquired %s while "
                                "holding %s, but %s -> %s was already "
                                "observed" % (self.name, held[-1],
                                              self.name, held[-1]))
                        _edges.add(edge)
        held.append(self.name)

    def release(self) -> None:
        held = _held()
        if self.name not in held:
            _violation("released %s on a thread that does not hold it"
                       % self.name)
        else:
            # remove the innermost hold (reentrant stacks pop LIFO)
            for i in range(len(held) - 1, -1, -1):
                if held[i] == self.name:
                    del held[i]
                    break
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self) -> bool:
        return self._inner.locked()

    def __repr__(self):
        return "<WitnessLock %s %r>" % (self.name, self._inner)


def lock(name: str, factory=threading.Lock):
    """The one construction seam: a plain ``factory()`` lock when the
    witness is off (byte-identical no-op path), a named
    :class:`WitnessLock` around it when on."""
    if not _enabled:
        return factory()
    global _locks_created
    with _reg_lock:
        _locks_created += 1
    return WitnessLock(name, factory())


def require(name: str) -> None:
    """Runtime assert of the ``*_locked`` convention: records a
    violation when the calling thread does not hold ``name``. Free
    when the witness is off."""
    if not _enabled:
        return
    if name not in _held():
        _violation("%s required but not held (a *_locked callee ran "
                   "without its caller's lock)" % name)


def holds(name: str) -> bool:
    return name in _held()


def summary() -> Optional[Dict[str, object]]:
    """Teardown snapshot for the ``Locks:`` meta line, or None when
    the witness never ran (keeps witness-off logs byte-stable)."""
    if not _enabled:
        return None
    with _reg_lock:
        return {
            "locks": _locks_created,
            "acquires": sum(t[0] for t in _tallies),
            "edges": sorted(_edges),
            "violations": list(_violations),
        }


def format_edges(snap: Dict[str, object]) -> str:
    """The ``Lock edges:`` JSON detail payload."""
    return json.dumps({
        "edges": [list(e) for e in snap["edges"]],
        "violations": snap["violations"],
    }, sort_keys=True)
