"""Unified pipeline tracing: spans, counters, Perfetto export, phases.

PRs 1-5 left the runtime with rich but fragmented telemetry: TimeCard
stamps answer "when did request N pass milestone X", hostprof prefix
sums answer "which section eats the host core", and the log-meta
counter lines answer "how many". None of them can answer "where did
request #417's 9 ms go" or "what was the staging pool doing while the
executor starved". This module unifies the signals into two artifacts:

* **A per-job timeline** (``logs/<job>/trace.json``): named spans from
  every thread role (client, stage executors, decode workers, the
  transfer worker), counter tracks sampled at a low background rate
  (queue depths, staging-slot occupancy, in-flight decodes), and flow
  links chaining one request's spans across stages — a standard Chrome
  trace loadable in ``ui.perfetto.dev`` untouched. Enabled per job via
  the root config key ``trace: {enabled, sample_hz, max_events}``.
* **A per-request cost breakdown** (:func:`attribute_phases`): a
  deterministic decomposition of each request's end-to-end latency
  into named phases — ``client_queue -> decode -> hold -> transfer ->
  inference{i} -> inter_stage_queue -> drain`` — derived from TimeCard
  stamps alone, so it works on any past log directory (coarser there:
  without the trace-mode refinement stamps the loader span reports as
  one ``decode`` phase). Phases partition [first stamp, last stamp] by
  construction, so they always sum to the end-to-end latency.

Cost discipline: like :mod:`rnb_tpu.hostprof`, the disabled path of
every instrumentation call is one module-global ``None`` test and no
allocation — ``trace.span(name)`` returns a shared no-op context
manager when no tracer is active. Event names are DECLARED in
``rnb_tpu.telemetry.TRACE_EVENT_REGISTRY`` and cross-checked by the
static schema checker (rnb_tpu.analysis.schema, RNB-T008): an
undeclared event name is a tier-1 lint failure.
"""

from __future__ import annotations

import bisect
import json
import threading
import time
from typing import Callable, Dict, List, Mapping, Optional, Tuple

#: the active per-job tracer, installed/cleared by rnb_tpu.benchmark
#: around the measured run (module-global like hostprof's accumulator:
#: jobs run one at a time per process)
ACTIVE: Optional["Tracer"] = None

#: default background counter-sampling rate (Hz); 0 disables the
#: sampler thread while keeping spans/instants/explicit counters
DEFAULT_SAMPLE_HZ = 20.0
#: default event-buffer cap — beyond it events are counted as dropped,
#: never grown (a runaway trace must not OOM the bench host)
DEFAULT_MAX_EVENTS = 200000


class _NullSpan:
    """Shared no-op context manager: the disabled path costs one
    function call, one global read, and no allocation."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL = _NullSpan()


def name(pattern: str, *args) -> str:
    """Format a registered event-name pattern once, ahead of a hot
    loop (``trace.name("exec%d.model_call", step)``). Call sites keep
    the literal pattern here so the static schema checker (RNB-T008)
    can see every name the tree may emit; the hot loop then passes the
    prebuilt string to :func:`span`/:func:`instant` with zero
    formatting cost per event."""
    return pattern % args if args else pattern


def span(event_name: str, rid: Optional[int] = None):
    """Context manager timing one named span on the current thread.

    ``rid`` correlates the span with a request id: the exporter chains
    all events of one rid into a Perfetto flow. Disabled path: shared
    no-op, no allocation."""
    t = ACTIVE
    if t is None:
        return _NULL
    return t.span(event_name, rid)


def instant(event_name: str, rid: Optional[int] = None,
            args: Optional[dict] = None) -> None:
    """A zero-duration event on the current thread's track."""
    t = ACTIVE
    if t is None:
        return
    t.add_event(event_name, "i", time.time(), 0.0, rid, args)


def counter(event_name: str, value) -> None:
    """An explicit counter sample (event-driven counter track)."""
    t = ACTIVE
    if t is None:
        return
    t.add_event(event_name, "C", time.time(), 0.0, None,
                {"value": value})


class TraceSettings:
    """Validated per-job tracing knobs (root config key ``trace``)."""

    __slots__ = ("enabled", "sample_hz", "max_events")

    def __init__(self, enabled: bool = True,
                 sample_hz: float = DEFAULT_SAMPLE_HZ,
                 max_events: int = DEFAULT_MAX_EVENTS):
        self.enabled = bool(enabled)
        self.sample_hz = float(sample_hz)
        self.max_events = int(max_events)

    @staticmethod
    def from_config(raw: Optional[dict]) -> Optional["TraceSettings"]:
        """Settings from the validated config dict, or None when the
        key is absent or ``enabled`` is false (tracing fully off: no
        tracer, no refinement stamps, byte-stable logs)."""
        if raw is None:
            return None
        settings = TraceSettings(
            enabled=raw.get("enabled", True),
            sample_hz=raw.get("sample_hz", DEFAULT_SAMPLE_HZ),
            max_events=raw.get("max_events", DEFAULT_MAX_EVENTS))
        return settings if settings.enabled else None


class _Span:
    """One live enabled-mode span (allocated only while tracing)."""

    __slots__ = ("tracer", "name", "rid", "t0")

    def __init__(self, tracer: "Tracer", event_name: str,
                 rid: Optional[int]):
        self.tracer = tracer
        self.name = event_name
        self.rid = rid
        self.t0 = time.time()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        t1 = time.time()
        self.tracer.add_event(self.name, "X", self.t0, t1 - self.t0,
                              self.rid, None)
        return False


class Tracer:
    """Bounded, thread-safe event collector + background sampler.

    Events are (name, ph, t_epoch_s, dur_s, thread_name, rid, args)
    tuples appended under one lock; the export step normalizes them
    into Chrome-trace JSON (microsecond timestamps relative to the
    earliest event, one ``tid`` per thread role, counter tracks, and
    synthesized flow chains per request id)."""

    GUARDED_BY = {
        "_events": "_lock",
        "_counter_sources": "_lock",
        "dropped": "_lock",
    }

    UNGUARDED_OK = {
        "_sampler": "controller-thread lifecycle "
                    "(start_sampler/stop_sampler)",
    }

    def __init__(self, settings: Optional[TraceSettings] = None):
        self.settings = settings or TraceSettings()
        self._lock = threading.Lock()
        self._events: List[Tuple] = []
        self.dropped = 0
        #: (name, callable) pairs the sampler polls; callables must be
        #: cheap and thread-safe (queue qsize, pool availability)
        self._counter_sources: List[Tuple[str, Callable[[], float]]] = []
        self._sampler: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- collection ---------------------------------------------------

    def span(self, event_name: str, rid: Optional[int] = None) -> _Span:
        return _Span(self, event_name, rid)

    def add_event(self, event_name: str, ph: str, t0: float,
                  dur: float, rid: Optional[int],
                  args: Optional[dict]) -> None:
        thread_name = threading.current_thread().name
        with self._lock:
            if len(self._events) >= self.settings.max_events:
                self.dropped += 1
                return
            self._events.append(
                (event_name, ph, t0, dur, thread_name, rid, args))

    def num_events(self) -> int:
        with self._lock:
            return len(self._events)

    def snapshot_events(self) -> List[Tuple]:
        """Copy of the collected event tuples — the devobs merge reads
        the ``model_call`` spans here to rid-correlate device ops."""
        with self._lock:
            return list(self._events)

    def extend(self, events: List[Tuple]) -> int:
        """Append externally-built event tuples (``(name, ph, t0,
        dur_s, thread_name, rid, args)`` — the collection schema) with
        the same ``max_events`` bound as live collection; returns how
        many were admitted. Used by rnb_tpu.devobs to merge captured
        device-op intervals as ``device:<plane>`` tracks after the run
        drained (never on the hot path)."""
        added = 0
        with self._lock:
            for event in events:
                if len(self._events) >= self.settings.max_events:
                    self.dropped += 1
                    continue
                self._events.append(tuple(event))
                added += 1
        return added

    # -- background occupancy sampler ---------------------------------

    def add_counter_source(self, event_name: str,
                           fn: Callable[[], float]) -> None:
        """Register a queue-depth/occupancy probe for the sampler."""
        with self._lock:
            self._counter_sources.append((event_name, fn))

    def start_sampler(self) -> None:
        if self.settings.sample_hz <= 0 or self._sampler is not None:
            return
        self._sampler = threading.Thread(target=self._sample_loop,
                                         name="trace-sampler",
                                         daemon=True)
        self._sampler.start()

    def stop_sampler(self, timeout: float = 2.0) -> None:
        self._stop.set()
        if self._sampler is not None:
            self._sampler.join(timeout=timeout)
            self._sampler = None

    def _sample_loop(self) -> None:
        period = 1.0 / self.settings.sample_hz
        while not self._stop.wait(timeout=period):
            with self._lock:
                sources = list(self._counter_sources)
            now = time.time()
            for event_name, fn in sources:
                try:
                    value = fn()
                except Exception:
                    continue  # a dying probe must not kill the sampler
                self.add_event(event_name, "C", now, 0.0, None,
                               {"value": value})

    # -- export -------------------------------------------------------

    def export(self, path: str, job_id: str = "") -> int:
        """Write the collected events as Chrome-trace JSON; returns
        the number of trace events written (excluding metadata)."""
        with self._lock:
            events = list(self._events)
            dropped = self.dropped
        return export_events(events, dropped, path, job_id)


def export_events(events: List[Tuple], dropped: int, path: str,
                  job_id: str = "",
                  extra: Optional[dict] = None) -> int:
    """Export one event list — ``(name, ph, t0, dur_s, thread_name,
    rid, args)`` tuples, the :class:`Tracer` collection schema — as
    Chrome-trace JSON. Shared by :meth:`Tracer.export` and the flight
    recorder (rnb_tpu.metrics), whose bounded ring dumps MUST render
    in Perfetto and pass :func:`validate_trace` exactly like a full
    trace; ``extra`` keys land in ``otherData`` (the flight dump
    carries its trigger + metric window there)."""
    events = sorted(events, key=lambda e: e[2])
    t_base = events[0][2] if events else 0.0
    tids: Dict[str, int] = {}
    out: List[dict] = []
    #: rid -> mutable [ts_us, tid, record] flow points
    by_rid: Dict[int, List[list]] = {}
    #: tid -> unrounded (start_us, end_us) of every duration slice
    slice_ivals: Dict[int, List[Tuple[float, float]]] = {}

    def tid_of(thread_name: str) -> int:
        tid = tids.get(thread_name)
        if tid is None:
            tid = len(tids) + 1
            tids[thread_name] = tid
        return tid

    for event_name, ph, t0, dur, thread_name, rid, args in events:
        tid = tid_of(thread_name)
        ts = (t0 - t_base) * 1e6
        record = {"name": event_name, "ph": ph, "pid": 1,
                  "tid": tid, "ts": round(ts, 3)}
        if ph == "X":
            dur_us = max(0.0, dur) * 1e6
            record["dur"] = round(dur_us, 3)
            slice_ivals.setdefault(tid, []).append(
                (ts, ts + dur_us))
        record_args = dict(args) if args else {}
        if rid is not None:
            record_args["rid"] = rid
            by_rid.setdefault(rid, []).append([ts, tid, record])
        if record_args:
            record["args"] = record_args
        out.append(record)

    # -- flow anchoring ------------------------------------------
    # Perfetto/Chrome bind a legacy s/t/f flow event to the
    # duration slice enclosing its ts on (pid, tid); an anchor
    # outside every slice is silently dropped at import, which
    # would amputate the chain ends living on instant-only tracks
    # (client.enqueue, the swallow markers). Promote every
    # unenclosed rid-instant to a thin anchor slice (<= 1 us,
    # clamped so it cannot overlap the next slice or anchor on its
    # track) and bind the flow at its midpoint.
    starts_by_tid: Dict[int, List[float]] = {}
    maxend_by_tid: Dict[int, List[float]] = {}
    for tid, ivals in slice_ivals.items():
        ivals.sort()
        running, maxend = float("-inf"), []
        for _start, end in ivals:
            running = max(running, end)
            maxend.append(running)
        starts_by_tid[tid] = [start for start, _end in ivals]
        maxend_by_tid[tid] = maxend

    def _enclosed(tid: int, ts: float) -> bool:
        starts = starts_by_tid.get(tid)
        if not starts:
            return False
        idx = bisect.bisect_right(starts, ts) - 1
        return idx >= 0 and maxend_by_tid[tid][idx] > ts

    def _next_slice_start(tid: int, ts: float) -> Optional[float]:
        starts = starts_by_tid.get(tid)
        if not starts:
            return None
        idx = bisect.bisect_right(starts, ts)
        return starts[idx] if idx < len(starts) else None

    all_points = sorted((p for pts in by_rid.values() for p in pts),
                        key=lambda p: (p[1], p[0]))
    last_anchor: Dict[int, Tuple[float, float, dict, list]] = {}
    for point in all_points:
        ts, tid, record = point
        if record["ph"] != "i" or _enclosed(tid, ts):
            continue
        nxt = _next_slice_start(tid, ts)
        dur = 1.0 if nxt is None else min(1.0, nxt - ts)
        prev = last_anchor.get(tid)
        if prev is not None and ts < prev[0] + prev[1]:
            # shrink the previous anchor up to this one's start
            p_ts, _p_dur, p_record, p_point = prev
            p_dur = max(0.0, ts - p_ts)
            p_record["dur"] = round(p_dur, 3)
            p_point[0] = p_ts + p_dur / 2.0
        record["ph"] = "X"
        record["dur"] = round(dur, 3)
        point[0] = ts + dur / 2.0
        last_anchor[tid] = (ts, dur, record, point)

    # flow chains: every rid with >= 2 correlated events gets a
    # start -> step... -> finish chain binding its spans across
    # thread tracks (Perfetto draws the arrows)
    num_flows = 0
    for rid in sorted(by_rid):
        points = sorted(by_rid[rid], key=lambda p: (p[0], p[1]))
        if len(points) < 2:
            continue
        num_flows += 1
        last = len(points) - 1
        for idx, (ts, tid, record) in enumerate(points):
            ph = "s" if idx == 0 else ("f" if idx == last else "t")
            flow = {"name": "request", "cat": "request", "ph": ph,
                    "id": rid, "pid": 1, "tid": tid,
                    "ts": round(ts, 3)}
            if ph == "f":
                flow["bp"] = "e"
            out.append(flow)
    meta = [{"name": "process_name", "ph": "M", "pid": 1, "tid": 0,
             "ts": 0, "args": {"name": "rnb-tpu %s" % job_id}}]
    for thread_name, tid in sorted(tids.items(), key=lambda kv: kv[1]):
        meta.append({"name": "thread_name", "ph": "M", "pid": 1,
                     "tid": tid, "ts": 0,
                     "args": {"name": thread_name}})
    other = {"job_id": job_id,
             "num_events": len(events),
             "num_flows": num_flows,
             "dropped_events": dropped,
             "t_base_epoch_s": t_base}
    if extra:
        other.update(extra)
    doc = {"traceEvents": meta + out,
           "displayTimeUnit": "ms",
           "otherData": other}
    with open(path, "w") as f:
        json.dump(doc, f)
    return len(events)


def validate_trace(path: str) -> List[str]:
    """Structural checks over one exported ``trace.json``; returns a
    list of human-readable problems (empty = valid). Held to the same
    bar as ``parse_utils --check``: every event carries ts/tid/ph (and
    dur for complete spans), and every flow id resolves start-to-
    finish."""
    problems: List[str] = []
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        return ["trace unreadable: %s" % e]
    events = doc.get("traceEvents")
    if not isinstance(events, list) or not events:
        return ["traceEvents missing or empty"]
    flow_starts: Dict[int, int] = {}
    flow_ends: Dict[int, int] = {}
    slices: Dict[Tuple[int, int], List[Tuple[float, float]]] = {}
    flow_points: List[Tuple[int, dict]] = []
    for idx, ev in enumerate(events):
        for key in ("ph", "ts", "tid", "pid"):
            if key not in ev:
                problems.append("event %d (%r) missing %r"
                                % (idx, ev.get("name"), key))
                break
        ph = ev.get("ph")
        if ph == "X":
            if "dur" not in ev or ev["dur"] < 0:
                problems.append("span %d (%r) missing/negative dur"
                                % (idx, ev.get("name")))
            else:
                slices.setdefault(
                    (ev.get("pid"), ev.get("tid")), []).append(
                        (ev["ts"], ev["ts"] + ev["dur"]))
        elif ph in ("s", "t", "f"):
            flow_points.append((idx, ev))
            if ph == "s":
                flow_starts[ev.get("id")] = \
                    flow_starts.get(ev.get("id"), 0) + 1
            elif ph == "f":
                flow_ends[ev.get("id")] = \
                    flow_ends.get(ev.get("id"), 0) + 1
    for rid, n in flow_starts.items():
        if flow_ends.get(rid, 0) != n:
            problems.append("flow id %r: %d start(s) but %d finish(es)"
                            % (rid, n, flow_ends.get(rid, 0)))
    for rid in flow_ends:
        if rid not in flow_starts:
            problems.append("flow id %r finishes without a start" % rid)
    # Perfetto binds a legacy flow event to the duration slice
    # enclosing its ts on (pid, tid) and silently DROPS unbound ones —
    # an arrow endpoint missing from the rendered timeline with the
    # JSON still "valid". Hold the exporter to renderability, not just
    # structure (closed interval: thin promoted anchors count). Same
    # bisect index the exporter uses, so a max_events-sized trace
    # validates in O(n log n), not O(flow_points x slices).
    starts_by_track: Dict[Tuple[int, int], List[float]] = {}
    maxend_by_track: Dict[Tuple[int, int], List[float]] = {}
    for track, ivals in slices.items():
        ivals.sort()
        running, maxend = float("-inf"), []
        for _start, end in ivals:
            running = max(running, end)
            maxend.append(running)
        starts_by_track[track] = [start for start, _end in ivals]
        maxend_by_track[track] = maxend
    for idx, ev in flow_points:
        ts = ev.get("ts")
        track = (ev.get("pid"), ev.get("tid"))
        starts = starts_by_track.get(track)
        pos = bisect.bisect_right(starts, ts) - 1 if starts else -1
        if pos < 0 or maxend_by_track[track][pos] < ts:
            problems.append(
                "flow event %d (id %r, ph %r) has no enclosing slice "
                "on tid %r at ts %r — Perfetto would drop this arrow"
                % (idx, ev.get("id"), ev.get("ph"), ev.get("tid"), ts))
    return problems


def track_names(path: str) -> List[str]:
    """The distinct named thread tracks of one exported trace (the
    acceptance criterion counts these sources)."""
    with open(path) as f:
        doc = json.load(f)
    return sorted(ev.get("args", {}).get("name", "")
                  for ev in doc.get("traceEvents", [])
                  if ev.get("ph") == "M"
                  and ev.get("name") == "thread_name")


# -- deterministic phase attribution ----------------------------------
#
# The decomposition consumes ONLY TimeCard stamps — the columnar data
# every past per-instance timing table already holds — so it can be
# applied offline to any log directory (scripts/parse_utils.py
# --attribute). Stamps recorded under tracing refine the loader span
# into decode/hold/transfer/drain; without them the whole loader span
# reports as one `decode` phase (the STANDARD_COMPONENTS name for it).

#: canonical phase print order (phases absent from a request's stamps
#: are simply absent from its decomposition)
PHASE_ORDER = ("client_queue", "decode", "hold", "transfer", "drain",
               "inference", "inter_stage_queue")


def _strip_suffix(key: str) -> str:
    """Merged segment cards suffix post-fork stamps with ``-{sub_id}``
    (telemetry.TimeCard.merge); classification ignores the suffix."""
    base, dash, tail = key.rpartition("-")
    if dash and tail.isdigit():
        return base
    return key


def _step_of(base: str, prefix: str, suffix: str) -> Optional[int]:
    if base.startswith(prefix) and base.endswith(suffix):
        digits = base[len(prefix):len(base) - len(suffix)]
        if digits.isdigit():
            return int(digits)
    return None


def phase_of(prev_key: str, next_key: str) -> str:
    """The phase name of the gap between two adjacent stamps.

    Every gap maps to exactly one phase, so per-request phases
    partition [first stamp, last stamp] and sum to the end-to-end
    latency by construction. Unrecognized gaps (segment-sibling skew,
    future stamps) fall into ``drain`` rather than being dropped —
    attribution must account for every microsecond or it lies.
    """
    prev_base = _strip_suffix(prev_key)
    next_base = _strip_suffix(next_key)
    step = _step_of(next_base, "runner", "_start")
    if step is not None:
        return "client_queue" if step == 0 else "inter_stage_queue"
    step = _step_of(next_base, "decode", "_done")
    if step is not None:
        return "decode"
    step = _step_of(next_base, "transfer", "_start")
    if step is not None:
        return "hold"
    step = _step_of(next_base, "transfer", "_done")
    if step is not None:
        return "transfer"
    step = _step_of(next_base, "inference", "_start")
    if step is not None:
        return "client_queue" if step == 0 else "inter_stage_queue"
    step = _step_of(next_base, "inference", "_finish")
    if step is not None:
        if _step_of(prev_base, "transfer", "_done") == step:
            return "drain"  # transfer complete -> publish pickup
        if step == 0:
            # the un-refined loader span: decode(+transfer) in one —
            # the STANDARD_COMPONENTS name for inference0 on past logs
            return "decode"
        return "inference%d" % step
    return "drain"


def attribute_phases(timings: Mapping[str, float]
                     ) -> "Dict[str, float]":
    """Per-request phase decomposition in milliseconds.

    ``timings`` is one TimeCard's stamp mapping (or one timing-table
    row): event key -> epoch seconds. Stamps are ordered by time (a
    merged segment card's sibling stamps interleave), adjacent gaps
    are classified by :func:`phase_of`, and same-named gaps accumulate.
    The values always sum to ``(last - first) * 1000`` exactly (up to
    float rounding), which ``parse_utils --check`` asserts per request.
    """
    stamps = [(float(t), key) for key, t in timings.items()
              if t == t]  # drop NaNs from union-schema frames
    stamps.sort(key=lambda p: p[0])
    phases: Dict[str, float] = {}
    for (t_prev, k_prev), (t_next, k_next) in zip(stamps, stamps[1:]):
        phase = phase_of(k_prev, k_next)
        phases[phase] = phases.get(phase, 0.0) \
            + (t_next - t_prev) * 1000.0
    return phases


def _phase_sort_key(phase: str) -> Tuple[int, str]:
    for idx, prefix in enumerate(PHASE_ORDER):
        if phase == prefix or (prefix == "inference"
                               and phase.startswith("inference")):
            return (idx, phase)
    return (len(PHASE_ORDER), phase)


def sorted_phases(names) -> List[str]:
    """Phase names in the canonical display order."""
    return sorted(names, key=_phase_sort_key)


def phase_stats(samples: Mapping[str, List[float]]
                ) -> "Dict[str, Dict[str, float]]":
    """{phase: {mean_ms, p99_ms, count}} over per-request samples —
    the one aggregation rule shared by the ``Phases:`` log-meta line,
    the ``# phases`` table trailer, and ``parse_utils --attribute``."""
    import numpy as np
    out: Dict[str, Dict[str, float]] = {}
    for phase, values in samples.items():
        if not values:
            continue
        arr = np.asarray(values, dtype=float)
        out[phase] = {"mean_ms": float(arr.mean()),
                      "p99_ms": float(np.percentile(arr, 99.0)),
                      "count": len(values)}
    return out
