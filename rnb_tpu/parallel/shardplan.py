"""Intra-stage tensor parallelism: shard one R(2+1)D stage over a ring.

PR 9's scale-out replicates whole stages, so a stage can never exceed
one device's HBM or FLOPs. This module is the other axis (ROADMAP item
4): partition the stage's *channel* dimensions over a ``shard_degree``-
sized mesh axis via ``shard_map``, the Gemma-on-TPU serving protocol
(PAPERS.md) applied to the R(2+1)D backbone — shard the filter axes,
keep ONE executable, measure the collective tax honestly.

What is sharded (and why the result is bit-identical):

* every **temporal** conv kernel's output-channel axis and the
  classification head's column axis live SHARDED at rest — each mesh
  member holds ``1/degree`` of those bytes, which is where degree k
  buys its per-device HBM headroom — and are ring-all-gathered to
  full width right before their op (``nn.map_variables`` swaps the
  gathered kernel in). The op then runs at FULL width, so the
  activation path is op-for-op the unsharded program: a gather is
  pure data movement, and the gathered kernel is bitwise the
  unsharded one. This weight-gathered form is deliberate — slicing
  the *compute* per member (``features // k`` output channels each)
  is mathematically exact but NOT bitwise under XLA's bf16
  excess-precision fusion: changing the op graph changes which
  intermediate roundings are elided, a measured 1-ulp drift on the
  CPU twins. Only a structurally identical compute graph survives.
* the **spatial** convs, BatchNorms, shortcuts and pooling stay
  replicated: the factorization's ``mid`` widths (83/230/921...) are
  not divisible by 2/4. By the (2+1)D parameter-parity construction
  the temporal half carries ~half the stage's parameters, so degree
  k drops per-device *sharded* bytes by 1/k while the replicated
  half stays — the HBM sizing rule README "Intra-stage sharding"
  documents. Compute is NOT divided — sharding here is parameter
  residency (FSDP-style serving), and the planner's cost model says
  so (collective tax measured, compute invariant).

The kernel reassembly is
:func:`rnb_tpu.ops.handoff_dma.ring_all_gather_body` — n-1 one-step
ring hops riding the same scaffolding as the handoff's remote-DMA
``ring_shift``, pure data movement, so parity survives. A head stage
(``end == NUM_LAYERS``) computes full-width logits, keeps only its
own column block (a slice — pure movement), and leaves its logits
*channel-sharded* out of the forward jit; the one merge gather is a
SEPARATE jitted collective the stage times on the host
(``exec{i}.collective``) — the collective tax is a measured number in
the logs, never an assumption buried in a fused program.

Config surface: step key ``shard: {degree, axis, hbm_budget_mb}``
(rnb_tpu.config validates; ``_expand_shard`` moves the lane's device
list into ``shard_devices`` extras). ``hbm_budget_mb`` arms the
launch-time feasibility gate: a projected per-device footprint
(replicated params + sharded params / degree + the ragged pool) over
budget REJECTS the launch — the honest "this stage does not fit at
this degree" failure the headline shard config demonstrates at degree
1 (memledger owns the live accounting; this gate owns the projection).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np


def is_sharded_param(path: Sequence[str]) -> bool:
    """Is the variables-tree leaf at ``path`` (key names, root first)
    partitioned on its output-channel axis? Exactly the temporal conv
    kernels and the classification head — the axes
    network.SpatioTemporalConv/R2Plus1DClassifier declare as
    ``features // shards`` wide."""
    names = tuple(str(p) for p in path)
    if len(names) >= 2 and names[-2] == "temporal" \
            and names[-1] == "kernel":
        return True
    if len(names) >= 2 and names[-2] == "linear" \
            and names[-1] in ("kernel", "bias"):
        return True
    return False


def _tree_paths(tree, prefix=()):
    """[(path tuple, leaf)] over a nested dict tree (flax variables)."""
    out = []
    if isinstance(tree, dict):
        for key in sorted(tree):
            out.extend(_tree_paths(tree[key], prefix + (str(key),)))
    else:
        out.append((prefix, tree))
    return out


def shard_param_specs(variables, axis_name: str = "tp"):
    """A ``PartitionSpec`` tree matching ``variables``: sharded leaves
    (see :func:`is_sharded_param`) partition their LAST axis over
    ``axis_name``; everything else is replicated."""
    import jax
    from jax.sharding import PartitionSpec as P

    def spec_for(path, leaf):
        names = tuple(str(getattr(p, "key", p)) for p in path)
        if is_sharded_param(names):
            ndim = int(np.ndim(leaf))
            return P(*([None] * (ndim - 1) + [axis_name]))
        return P()

    return jax.tree_util.tree_map_with_path(spec_for, variables)


def _leaf_nbytes(leaf) -> int:
    nbytes = getattr(leaf, "nbytes", None)
    if nbytes is not None:
        return int(nbytes)
    # abstract leaves (jax.eval_shape's ShapeDtypeStruct) size from
    # shape x dtype — the projection never needs materialized weights
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is not None and dtype is not None:
        size = 1
        for extent in shape:
            size *= int(extent)
        return size * int(np.dtype(dtype).itemsize)
    return int(np.asarray(leaf).nbytes)


def split_param_bytes(variables) -> Tuple[int, int]:
    """(replicated_bytes, sharded_bytes) of one stage's variables —
    the two halves of the per-device HBM projection: replicated bytes
    land whole on every shard member, sharded bytes divide by the
    degree. Works on concrete arrays and on abstract
    ``jax.eval_shape`` trees alike, so feasibility is computable
    before any weight is materialized."""
    replicated = sharded = 0
    for path, leaf in _tree_paths(variables):
        nbytes = _leaf_nbytes(leaf)
        if is_sharded_param(path):
            sharded += nbytes
        else:
            replicated += nbytes
    return replicated, sharded


def projected_device_mb(replicated_bytes: int, sharded_bytes: int,
                        pool_bytes: int, degree: int) -> float:
    """Per-device HBM projection (MiB) at ``degree``: the feasibility
    number the launch gate and the planner both use — one formula, so
    they can never disagree."""
    degree = max(1, int(degree))
    return (float(replicated_bytes) + float(sharded_bytes) / degree
            + float(pool_bytes)) / (1 << 20)


def min_feasible_degree(replicated_bytes: int, sharded_bytes: int,
                        pool_bytes: int, budget_mb: float,
                        candidates: Sequence[int] = (1, 2, 4, 8)
                        ) -> Optional[int]:
    """The smallest candidate degree whose projection fits the budget,
    or None when even the largest candidate does not fit (the
    replicated half alone can exceed a small budget — sharding cannot
    save a stage whose *unshardable* bytes are too big)."""
    for degree in sorted(int(d) for d in candidates):
        if projected_device_mb(replicated_bytes, sharded_bytes,
                               pool_bytes, degree) <= float(budget_mb):
            return degree
    return None


def shardable_widths(start: int, end: int, num_classes: int) -> List[int]:
    """The declared output-channel widths sharding slices for a
    [start..end] stage — every temporal conv's feature count plus the
    head when the range ends the network. The shard degree must divide
    ALL of them (validated at construction and statically by rnb-lint
    RNB-G010)."""
    from rnb_tpu.models.r2p1d.network import LAYER_FEATURES, NUM_LAYERS
    widths: List[int] = []
    for layer in range(int(start), int(end) + 1):
        widths.append(64 if layer == 1 else LAYER_FEATURES[layer])
    if int(end) == NUM_LAYERS:
        widths.append(int(num_classes))
    return widths


def validate_degree(degree: int, start: int, end: int,
                    num_classes: int) -> None:
    """Raise ValueError unless ``degree`` divides every width
    :func:`shardable_widths` declares for the range."""
    degree = int(degree)
    if degree < 1:
        raise ValueError("shard degree must be >= 1, got %d" % degree)
    for width in shardable_widths(start, end, num_classes):
        if width % degree:
            raise ValueError(
                "shard degree %d does not divide the declared channel "
                "width %d of layers [%d..%d] (num_classes=%d)"
                % (degree, width, start, end, num_classes))


def build_shard_mesh(devices: Sequence, degree: int,
                     axis_name: str = "tp"):
    """One lane's shard sub-mesh: a single-axis ring of exactly
    ``degree`` resolved devices."""
    from rnb_tpu.parallel.mesh import build_mesh
    devices = list(devices)
    if len(devices) != int(degree):
        raise ValueError(
            "shard mesh wants exactly degree=%d devices, got %d"
            % (degree, len(devices)))
    return build_mesh(devices, axes={axis_name: int(degree)})


def shard_variables(variables, mesh, axis_name: str = "tp"):
    """Place a host variables tree onto the shard mesh: sharded leaves
    split their last axis over the ring, the rest replicate."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    def place(path, leaf):
        names = tuple(str(getattr(p, "key", p)) for p in path)
        if is_sharded_param(names):
            spec = P(*([None] * (np.ndim(leaf) - 1) + [axis_name]))
        else:
            spec = P()
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map_with_path(place, variables)


def make_sharded_apply(start: int, end: int, num_classes: int,
                       layer_sizes: tuple, mesh,
                       factored_shortcut: bool = False,
                       pixel_path: str = "rgb", ragged: bool = False,
                       axis_name: str = "tp"):
    """The sharded twin of model._shared_apply: ONE jit whose ingest
    (identical HLO to the unsharded applier's) runs replicated, then a
    ``shard_map`` network body over the ring. A head range returns
    logits still CHANNEL-SHARDED on the class axis (merge them with
    :func:`make_merge` — the host-timed collective); a mid-pipeline
    range's output is already full-width (the last temporal gather
    reassembled it) and comes back replicated."""
    import jax
    from jax.sharding import PartitionSpec as P
    try:
        from jax.experimental.shard_map import shard_map
    except ImportError:
        shard_map = jax.shard_map
    from rnb_tpu.models.r2p1d.network import (NUM_LAYERS,
                                              R2Plus1DClassifier)

    degree = int(mesh.shape[axis_name])
    validate_degree(degree, start, end, num_classes)
    model = R2Plus1DClassifier(start=start, end=end,
                               num_classes=num_classes,
                               layer_sizes=tuple(layer_sizes),
                               factored_shortcut=bool(factored_shortcut),
                               shards=degree, shard_axis=axis_name)
    head = (int(end) == NUM_LAYERS)

    if pixel_path == "yuv420":
        from rnb_tpu.models.r2p1d.model import FRAME_HW
        if ragged:
            from rnb_tpu.ops.ragged import ragged_normalize_yuv420

            def ingest(x, rows_valid):
                return ragged_normalize_yuv420(x, rows_valid, FRAME_HW,
                                               FRAME_HW)
        else:
            from rnb_tpu.ops.yuv import normalize_yuv420

            def ingest(x, rows_valid):
                del rows_valid
                return normalize_yuv420(x, FRAME_HW, FRAME_HW)
    elif pixel_path == "dct":
        from rnb_tpu.models.r2p1d.model import FRAME_HW
        if ragged:
            from rnb_tpu.ops.dct import ragged_normalize_dct

            def ingest(x, rows_valid):
                return ragged_normalize_dct(x, rows_valid, FRAME_HW,
                                            FRAME_HW)
        else:
            from rnb_tpu.ops.dct import normalize_dct

            def ingest(x, rows_valid):
                del rows_valid
                return normalize_dct(x, FRAME_HW, FRAME_HW)
    else:
        def ingest(x, rows_valid):
            del rows_valid
            return x

    def network(variables, xin):
        return model.apply(variables, xin, train=False)

    def build(variables_specs):
        body = shard_map(
            network, mesh=mesh,
            in_specs=(variables_specs, P()),
            out_specs=(P(None, axis_name) if head else P()),
            check_rep=False)

        if ragged:
            def apply(variables, x, rows_valid):
                return body(variables, ingest(x, rows_valid))
        else:
            def apply(variables, x):
                return body(variables, ingest(x, None))
        return jax.jit(apply)

    def applier_for(variables):
        return build(shard_param_specs(variables, axis_name))

    return applier_for


def make_merge(mesh, axis_name: str = "tp"):
    """The head stage's one merge collective: channel-sharded logits ->
    the full-width value, replicated, via the ring all-gather. Jitted
    separately from the forward ON PURPOSE: the stage host-times this
    call as ``exec{i}.collective``, so the collective tax is a span in
    the trace and a histogram in metrics.jsonl — the calibration
    source whatif's ``shard_degree`` vocabulary scales from."""
    import jax
    from jax.sharding import PartitionSpec as P
    try:
        from jax.experimental.shard_map import shard_map
    except ImportError:
        shard_map = jax.shard_map
    from rnb_tpu.ops.handoff_dma import ring_all_gather_body

    degree = int(mesh.shape[axis_name])
    fn = shard_map(ring_all_gather_body(axis_name, degree, axis=-1),
                   mesh=mesh, in_specs=P(None, axis_name),
                   out_specs=P(), check_rep=False)
    return jax.jit(fn)
