"""Multi-host runtime initialization (DCN-scale distribution).

The reference's "distributed backend" was single-node
torch.multiprocessing: spawn-mode processes, pickle queues, CUDA-IPC
tensors (SURVEY.md §2.4). The TPU-native equivalent splits cleanly in
two:

* **intra-slice (ICI)**: invisible to user code — XLA collectives
  inserted by sharding annotations (see :mod:`rnb_tpu.parallel.sharded`);
* **inter-host (DCN)**: ``jax.distributed`` — one controller process
  per host, all hosts participating in every jitted collective over the
  global mesh. This module wraps its initialization behind environment
  variables so single-host runs (and the CPU test mesh) need no setup.

Env contract (all optional; absence = single-process mode):
  RNB_TPU_COORDINATOR   "host:port" of process 0
  RNB_TPU_NUM_PROCESSES total process count
  RNB_TPU_PROCESS_ID    this process's index
"""

from __future__ import annotations

import os
from typing import Optional

_initialized = False


def maybe_initialize(coordinator: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> bool:
    """Initialize ``jax.distributed`` when multi-host env/args are set.

    Returns True when running distributed (after initialization), False
    for single-process mode. Idempotent.
    """
    global _initialized
    if _initialized:
        return True
    coordinator = coordinator or os.environ.get("RNB_TPU_COORDINATOR")
    if num_processes is None:
        num_processes = int(os.environ.get("RNB_TPU_NUM_PROCESSES", "0")) \
            or None
    if process_id is None:
        pid = os.environ.get("RNB_TPU_PROCESS_ID")
        process_id = int(pid) if pid is not None else None
    if coordinator is None:
        if num_processes is not None or process_id is not None:
            raise RuntimeError(
                "RNB_TPU_NUM_PROCESSES/RNB_TPU_PROCESS_ID are set but "
                "RNB_TPU_COORDINATOR is not — refusing to fall back to "
                "single-process mode in a partially-configured "
                "multi-host launch")
        return False
    import jax
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id)
    _initialized = True
    return True


def process_count() -> int:
    import jax
    return jax.process_count()


def process_index() -> int:
    import jax
    return jax.process_index()


def is_primary() -> bool:
    """True on the process that should write logs / print summaries."""
    return process_index() == 0


def global_mesh(axis_names=("dp", "sp"), axes=None):
    """A mesh over every device of every participating host.

    With multiple hosts the returned mesh spans hosts; shardings over it
    make XLA route collectives over ICI within a slice and DCN across
    slices — no NCCL/MPI-style plumbing in user code.
    """
    import jax
    from rnb_tpu.parallel.mesh import build_mesh
    return build_mesh(list(jax.devices()), axes=axes,
                      axis_names=axis_names)
