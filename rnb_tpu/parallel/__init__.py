"""Multi-chip parallel execution: meshes, sharded steps, collectives.

The reference scaled by *processes*: one OS process per GPU, replicas
competing on queues, batches hand-split into segments and re-merged by a
CPU aggregator (SURVEY.md §2.3). On TPU the idiomatic scaling unit is
the **device mesh**: a stage runs once, jitted over a
``jax.sharding.Mesh``, with XLA inserting ICI collectives where the
sharding demands them. This package provides:

* :mod:`rnb_tpu.parallel.mesh` — mesh construction and axis factoring;
* :mod:`rnb_tpu.parallel.sharded` — the sharded inference step: videos
  sharded over ``dp``, clips over ``sp`` with an on-device ``psum``
  replacing the reference's host-side logit aggregator
  (models/r2p1d/model.py:238-285);
* :mod:`rnb_tpu.parallel.distributed` — multi-host (DCN) runtime
  initialization, the capability slot the reference filled with
  single-node torch.multiprocessing (benchmark.py:130-132).
"""

from rnb_tpu.parallel.mesh import (MeshSpec, build_mesh, factor_devices,
                                   submeshes)
from rnb_tpu.parallel.sharded import (ShardedInference,
                                      make_sharded_inference)

__all__ = [
    "MeshSpec", "build_mesh", "factor_devices", "submeshes",
    "ShardedInference", "make_sharded_inference",
]
