"""Mesh construction and device-axis factoring.

Pipeline configs name devices by index; parallel stages name *axes*
(``dp`` — data/video replication, ``sp`` — clip/segment sharding).
These helpers turn "this group owns devices [0..k)" into a
``jax.sharding.Mesh`` with the requested axis split, and carve a global
device list into disjoint per-stage sub-meshes (the TPU analog of the
reference pinning each pipeline step to its own GPU set,
reference benchmark.py:230-271).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


class MeshSpec:
    """A declarative mesh request: ordered {axis_name: size}.

    Size ``-1`` on at most one axis means "whatever is left" after the
    explicit axes divide the device count (mirrors reshape's -1).
    """

    def __init__(self, axes: Dict[str, int]):
        if not axes:
            raise ValueError("MeshSpec needs at least one axis")
        wildcards = [a for a, s in axes.items() if s == -1]
        if len(wildcards) > 1:
            raise ValueError("at most one mesh axis may be -1, got %r"
                             % (axes,))
        for a, s in axes.items():
            if s != -1 and s < 1:
                raise ValueError("mesh axis %r has invalid size %d" % (a, s))
        self.axes = dict(axes)

    def resolve(self, num_devices: int) -> Dict[str, int]:
        """Concrete axis sizes for ``num_devices`` devices."""
        sizes = dict(self.axes)
        explicit = 1
        wildcard = None
        for a, s in sizes.items():
            if s == -1:
                wildcard = a
            else:
                explicit *= s
        if wildcard is not None:
            if num_devices % explicit != 0:
                raise ValueError(
                    "cannot fill axis %r: %d devices not divisible by %d"
                    % (wildcard, num_devices, explicit))
            sizes[wildcard] = num_devices // explicit
        elif explicit != num_devices:
            raise ValueError(
                "mesh %r wants %d devices but group has %d"
                % (self.axes, explicit, num_devices))
        return sizes

    def __repr__(self):
        return "MeshSpec(%r)" % (self.axes,)


def factor_devices(num_devices: int,
                   axis_names: Sequence[str]) -> Dict[str, int]:
    """Factor ``num_devices`` across ``axis_names`` as evenly as
    possible, biasing larger factors toward the *earlier* axes.

    Used when a caller asks for "a dp×sp mesh over n devices" without
    caring about the exact split — e.g. ``dryrun_multichip``. 8 devices
    over ("dp", "sp") -> {dp: 4, sp: 2}; over ("pp", "dp", "sp") ->
    {pp: 2, dp: 2, sp: 2}; a prime count puts everything on the first
    axis.
    """
    if num_devices < 1:
        raise ValueError("need at least one device")
    names = list(axis_names)
    sizes = {a: 1 for a in names}
    factors: List[int] = []
    n = int(num_devices)
    p = 2
    while p * p <= n:
        while n % p == 0:
            factors.append(p)
            n //= p
        p += 1
    if n > 1:
        factors.append(n)
    # LPT greedy: place prime factors largest-first onto the currently
    # smallest axis — keeps the split as even as the factorization allows
    for f in sorted(factors, reverse=True):
        smallest = min(range(len(names)), key=lambda i: sizes[names[i]])
        sizes[names[smallest]] *= f
    # sort sizes descending onto the axis order so earlier axes are larger
    ordered = sorted((sizes[a] for a in names), reverse=True)
    return dict(zip(names, ordered))


def build_mesh(devices: Optional[Sequence] = None,
               axes: Optional[Dict[str, int]] = None,
               axis_names: Sequence[str] = ("dp", "sp")):
    """Build a ``jax.sharding.Mesh``.

    ``devices`` defaults to all visible accelerator devices. ``axes``
    gives explicit {name: size} (``-1`` allowed once); without it the
    device count is auto-factored over ``axis_names``.
    """
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = list(jax.devices())
    devices = list(devices)
    if axes is not None:
        sizes = MeshSpec(axes).resolve(len(devices))
    else:
        sizes = factor_devices(len(devices), axis_names)
    names = tuple(sizes.keys())
    shape = tuple(sizes[a] for a in names)
    grid = np.asarray(devices, dtype=object).reshape(shape)
    return Mesh(grid, names)


def carve_replicas(devices: Sequence, replicas: int) -> List[list]:
    """Carve a step's device list into ``replicas`` disjoint equal
    sub-meshes, in order — the replica expansion's placement rule
    (rnb_tpu.config ``replicas: N`` / placement apply): replica i owns
    ``devices[i*k:(i+1)*k]`` with ``k = len(devices)//replicas``, so
    contiguous device ranges (adjacent cores on real topologies) stay
    together inside one replica. Works on raw config indices or
    resolved devices alike."""
    devices = list(devices)
    replicas = int(replicas)
    if replicas < 1:
        raise ValueError("need at least one replica, got %d" % replicas)
    if not devices or len(devices) % replicas:
        raise ValueError(
            "%d device(s) cannot split into %d equal replica "
            "sub-meshes" % (len(devices), replicas))
    chunk = len(devices) // replicas
    return [devices[i * chunk:(i + 1) * chunk]
            for i in range(replicas)]


def submeshes(devices: Sequence, stage_sizes: Sequence[int],
              axes_per_stage: Sequence[Optional[Dict[str, int]]] = None):
    """Carve ``devices`` into disjoint consecutive sub-meshes.

    ``stage_sizes[i]`` devices go to stage i (the pipeline-parallel
    split: each stage owns its own cores and hand-off between stages is
    an ICI re-shard, the analog of the reference's per-step GPU lists).
    Returns a list of Meshes.
    """
    devices = list(devices)
    if sum(stage_sizes) > len(devices):
        raise ValueError("stage sizes %r exceed %d devices"
                         % (list(stage_sizes), len(devices)))
    if axes_per_stage is None:
        axes_per_stage = [None] * len(stage_sizes)
    out = []
    cursor = 0
    for size, axes in zip(stage_sizes, axes_per_stage):
        chunk = devices[cursor: cursor + size]
        cursor += size
        out.append(build_mesh(chunk, axes=axes))
    return out
