"""The sharded inference step: one jit over a dp×sp device mesh.

This is the TPU-native generalization of the reference's scaling
mechanics (SURVEY.md §2.3):

* **dp** (data parallel) shards the *video* axis — what the reference
  did with replica processes competing on one queue
  (reference benchmark.py:248-271);
* **sp** (segment parallel) shards the *clip* axis — what the
  reference did with ``num_segments`` row-splitting, forked TimeCards
  and a host-side aggregator summing logits per request
  (reference runner.py:138-173, models/r2p1d/model.py:238-285). Here
  the split, the compute and the merge all live inside one compiled
  program: every ``sp`` member computes logits for its clip shard and a
  ``psum`` over the ``sp`` axis reduces them on-chip over ICI — no host
  round-trip, no queue hop, no aggregator stage.

Variable clip counts (1..max_clips per video) are handled the same way
the rest of the framework handles them: fixed max-shape batches plus a
validity mask (reference control.py:34-39 kept as the shape idiom), so
XLA compiles exactly once.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional, Sequence, Tuple

import numpy as np

from rnb_tpu.models.r2p1d import checkpoint as ckpt
from rnb_tpu.models.r2p1d.network import (KINETICS_CLASSES, NUM_LAYERS,
                                          R18_LAYER_SIZES,
                                          R2Plus1DClassifier, normalize_u8)


class ShardedInference:
    """Full R(2+1)D inference jitted once over a ``dp × sp`` mesh.

    ``run(videos_u8, clip_mask)`` takes a uint8 batch of shape
    ``(videos, max_clips, frames, H, W, 3)`` and a float mask
    ``(videos, max_clips)`` (1.0 = valid clip) and returns per-video
    aggregated logits ``(videos, num_classes)`` — already summed over
    each video's valid clips and psum-reduced across the ``sp`` axis.

    The mesh's ``dp`` size must divide the video axis. The clip axis
    needs no divisibility: when ``sp`` does not divide ``max_clips`` the
    step pads the clip axis up to the next multiple *inside* the
    compiled program — the padded rows carry a zero mask, so they cost
    one slice of dead MXU work and change no result. That is what lets
    e.g. ``sp=8`` serve ``max_clips=15`` (15 -> 16) and use every core
    of an 8-device mesh instead of idling three (the reference's
    segment parallelism had the same constraint and simply required
    divisibility).
    """

    def __init__(self, mesh, max_clips: int = 15,
                 consecutive_frames: int = 8,
                 frame_hw: int = 112,
                 num_classes: int = KINETICS_CLASSES,
                 layer_sizes: Sequence[int] = R18_LAYER_SIZES,
                 dtype: Any = None,
                 ckpt_path: Optional[str] = None,
                 dp_axis: str = "dp", sp_axis: str = "sp",
                 variables: Optional[Any] = None,
                 factored_shortcut: bool = False,
                 pixel_path: str = "rgb"):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        if dp_axis not in mesh.axis_names or sp_axis not in mesh.axis_names:
            raise ValueError("mesh %r lacks axis %r/%r"
                             % (mesh.axis_names, dp_axis, sp_axis))
        if pixel_path not in ("rgb", "yuv420"):
            raise ValueError("pixel_path must be 'rgb' or 'yuv420', "
                             "got %r" % (pixel_path,))
        self.mesh = mesh
        self.max_clips = int(max_clips)
        self.consecutive_frames = int(consecutive_frames)
        self.frame_hw = int(frame_hw)
        self.num_classes = int(num_classes)
        self.dp_axis = dp_axis
        self.sp_axis = sp_axis
        self.pixel_path = pixel_path
        dtype = dtype or jnp.bfloat16
        layer_sizes = tuple(layer_sizes)

        sp_size = mesh.shape[sp_axis]
        self.sp_size = sp_size
        #: internal clip-axis extent: max_clips rounded up to a multiple
        #: of sp so every sp member gets an equal shard
        self.padded_clips = -(-self.max_clips // sp_size) * sp_size

        model = R2Plus1DClassifier(start=1, end=NUM_LAYERS,
                                   num_classes=num_classes,
                                   layer_sizes=layer_sizes, dtype=dtype,
                                   factored_shortcut=factored_shortcut)

        if variables is None:
            variables = ckpt.load_or_init(
                1, NUM_LAYERS, num_classes, layer_sizes, ckpt_path,
                factored_shortcut=factored_shortcut)
        replicated = NamedSharding(mesh, P())
        self.variables = jax.device_put(variables, replicated)

        clip_pad = self.padded_clips - self.max_clips
        # External arrays always carry max_clips clip rows. With no
        # padding the clip axis is sharded straight over sp (each core
        # receives only its shard on transfer); with padding the input
        # arrives dp-sharded/sp-replicated and the jitted step pads +
        # slices it — the broadcast is the price of using every core
        # when sp does not divide max_clips.
        if clip_pad == 0:
            self.batch_sharding = NamedSharding(mesh, P(dp_axis, sp_axis))
        else:
            self.batch_sharding = NamedSharding(mesh, P(dp_axis))
        self.logit_sharding = NamedSharding(mesh, P(dp_axis))

        try:
            from jax import shard_map
        except ImportError:  # older jax
            from jax.experimental.shard_map import shard_map

        hw = self.frame_hw

        def step(variables, vids, mask):
            # local shapes: vids (v, c, F, H, W, 3) for rgb or
            # (v, c, F, packed) for yuv420; mask (v, c)
            v, c = vids.shape[0], vids.shape[1]
            flat = vids.reshape((v * c,) + vids.shape[2:])
            if pixel_path == "yuv420":
                # the same fused on-device ingest the single-chip
                # network stage runs (rnb_tpu/ops/yuv.py), here inside
                # the sharded program so it shards with the clip axis
                from rnb_tpu.ops.yuv import normalize_yuv420
                x = normalize_yuv420(flat, hw, hw, dtype)
            else:
                x = normalize_u8(flat, dtype)
            logits = model.apply(variables, x, train=False)
            logits = logits.reshape(v, c, self.num_classes)
            per_video = (logits * mask[..., None]).sum(axis=1)
            return jax.lax.psum(per_video, sp_axis)

        sharded = shard_map(
            step, mesh=mesh,
            in_specs=(P(), P(dp_axis, sp_axis), P(dp_axis, sp_axis)),
            out_specs=P(dp_axis))
        if clip_pad == 0:
            self._run = jax.jit(sharded)
        else:
            def padded(variables, vids, mask):
                # rank differs per pixel path — pad only the clip axis
                vids = jnp.pad(
                    vids, ((0, 0), (0, clip_pad))
                    + ((0, 0),) * (vids.ndim - 2))
                mask = jnp.pad(mask, ((0, 0), (0, clip_pad)))
                return sharded(variables, vids, mask)
            self._run = jax.jit(padded)

    def batch_shape(self, num_videos: int) -> Tuple[int, ...]:
        if self.pixel_path == "yuv420":
            from rnb_tpu.ops.yuv import packed_frame_bytes
            return (num_videos, self.max_clips, self.consecutive_frames,
                    packed_frame_bytes(self.frame_hw, self.frame_hw))
        return (num_videos, self.max_clips, self.consecutive_frames,
                self.frame_hw, self.frame_hw, 3)

    def place_mask(self, valid_clips: Sequence[int]):
        """The one clip-validity mask convention: float32 (videos,
        max_clips), 1.0 = valid row, sharded like the batch."""
        import jax
        mask = np.zeros((len(valid_clips), self.max_clips), np.float32)
        for i, n in enumerate(valid_clips):
            mask[i, : int(n)] = 1.0
        return jax.device_put(mask, self.batch_sharding)

    def place(self, videos_u8: np.ndarray, valid_clips: Sequence[int]):
        """Device-put a host batch + derive its mask, both sharded."""
        import jax
        vids = jax.device_put(videos_u8, self.batch_sharding)
        return vids, self.place_mask(valid_clips)

    def run(self, vids, mask):
        """-> per-video aggregated logits (videos, num_classes), fp32."""
        return self._run(self.variables, vids, mask)

    def predict(self, videos_u8: np.ndarray,
                valid_clips: Sequence[int]) -> np.ndarray:
        """Host convenience: class ids for one padded uint8 batch."""
        vids, mask = self.place(videos_u8, valid_clips)
        logits = self.run(vids, mask)
        return np.asarray(logits).argmax(axis=-1)


def make_sharded_inference(mesh=None, num_devices: Optional[int] = None,
                           **kwargs) -> ShardedInference:
    """Build a :class:`ShardedInference` over ``mesh`` (or an
    auto-factored dp×sp mesh over ``num_devices`` / all devices)."""
    if mesh is None:
        import jax
        from rnb_tpu.parallel.mesh import build_mesh
        devices = list(jax.devices())
        if num_devices is not None:
            if num_devices > len(devices):
                raise ValueError(
                    "asked for %d devices but only %d are visible"
                    % (num_devices, len(devices)))
            devices = devices[:num_devices]
        mesh = build_mesh(devices, axis_names=("dp", "sp"))
    return ShardedInference(mesh, **kwargs)
