"""Measured-cost placement planner: replication plans from live costs.

The pipeline's replica counts used to be whatever the config author
guessed. Following AoiZora (PAPERS.md: choose the replication /
partition plan from topology plus *measured* per-stage costs), this
module closes the loop: the executors measure every stage's dispatch
cost over the run's wall window, the planner turns those costs into a
replication plan over the visible device budget, and ``parse_utils
--check`` holds the plan's occupancy *prediction* to the occupancy the
trace timeline actually recorded — a plan whose model drifts from
reality fails the check instead of silently misplacing the next run.

Cost model (deliberately the queueing-free first-order one — the
per-stage numbers it needs are exactly what the runtime already
measures):

* per-dispatch service ``c_i`` = measured busy seconds / dispatches —
  *busy* is the executor's dispatch span (injected fault-plan latency
  + model call + device sync), the same spans the trace timeline
  records as ``exec{i}.model_call``/``exec{i}.device_sync``, so the
  offline check compares like with like;
* offered load ``L_i = rate_i * c_i`` device-seconds per second, with
  ``rate_i`` = dispatches / wall;
* predicted occupancy at ``n`` replicas: ``L_i / n`` — for the
  *executed* plan (``n`` = configured instances) this must land within
  tolerance of the traced busy fraction (the model-consistency check);
  for the *recommendation* the same per-dispatch costs extrapolate.

Recommendation: allocate the device budget greedily — every step gets
one device, then each remaining device goes to the step with the
highest predicted occupancy (ties: lowest step index) — minimizing the
predicted bottleneck occupancy. First-order by design: it ignores
queueing variance and host-side coupling, which is why the prediction
is *checked*, not trusted.

Intra-stage sharding (PR 19) makes the plan two-dimensional: a step
may run at ``shard degree`` k (rnb_tpu.parallel.shardplan), consuming
k devices *per replica*. The planner's original model silently
assumed per-step service is invariant to the plan — true for replica
scaling (lanes run whole independent dispatches) but WRONG for
sharding, whose service includes a measured collective slice (the
``exec{i}.collective`` merge gather) that exists only because of the
degree. The corrected model, per step:

* **replicated** steps keep lane-parallel semantics: service is
  plan-invariant, occupancy at n replicas = ``L_i / n``;
* **sharded** steps decompose service into compute + collective. The
  compute slice is degree-invariant (weight-gathered sharding
  replicates the math; degree divides parameter *residency*, not
  FLOPs — see shardplan), and the collective slice scales with the
  ring-hop factor ``g(k) = (k-1)/k``, *calibrated from the measured
  collective fraction, never assumed*. With no measured collective
  (executed degree 1) there is nothing to calibrate from, so the
  planner refuses to extrapolate a degree>1 service — that
  counterfactual belongs to `whatif`'s ``shard_degree_step<i>``
  vocabulary, validated against an executed shard arm.

Joint recommendation (:func:`recommend_joint`): degree is bought for
per-device HBM feasibility, never for speed — on this cost model a
higher degree only adds collective tax — so each step's degree is the
smallest its memory floor (``min_degree``, from the stage's armed
feasibility gate) allows, with the calibrated compute-only service
when that drops the degree below the executed one; replicas then
spread greedily, each costing ``degree`` devices.

Config (root key, validated in rnb_tpu.config)::

    "placement": {"mode": "plan"}                         // report only
    "placement": {"mode": "apply", "plan": {"step1": 4}}  // auto-apply

``mode: "plan"`` emits the measured costs + recommendation as the
``Placement:`` log-meta JSON line. ``mode: "apply"`` additionally
applies the named replica counts at parse time — each ``step<i>``
entry becomes that step's ``replicas`` (unless the step already
declares one), going through the same replica expansion a hand-written
``replicas`` key does — and still emits the line, so an applied plan's
prediction is verified like any other.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

#: modes the root ``placement`` config key accepts
PLACEMENT_MODES = ("plan", "apply")


@dataclasses.dataclass(frozen=True)
class PlacementSettings:
    """Validated view of the ``placement`` root config key."""

    mode: str
    #: step index -> replica count to apply (apply mode only)
    plan: Tuple[Tuple[int, int], ...] = ()

    @staticmethod
    def from_config(raw: Optional[dict]) -> Optional["PlacementSettings"]:
        """Settings from the (schema-validated) config dict, or None
        when the key is absent or ``enabled`` is false."""
        if not raw or not raw.get("enabled", True):
            return None
        mode = raw.get("mode", "plan")
        plan = tuple(sorted(
            (int(key[4:]), int(val))
            for key, val in dict(raw.get("plan") or {}).items()))
        return PlacementSettings(mode=mode, plan=plan)


@dataclasses.dataclass(frozen=True)
class CostRecord:
    """One executor instance's measured dispatch cost, appended by the
    runner's teardown into the launcher's placement sink."""

    step_idx: int
    busy_s: float
    dispatches: int
    #: executed shard degree: 0 = the step declared no `shard` key,
    #: >= 1 = the declared degree (1 included, so an operator
    #: iterating degrees keeps a stable report shape)
    shard_degree: int = 0
    #: host-timed exec{i}.collective seconds (merge gathers), a slice
    #: OF busy_s — the calibration source for degree counterfactuals
    collective_s: float = 0.0
    #: smallest degree the stage's armed HBM feasibility gate admits
    #: (1 when no budget was declared — no documented memory floor)
    min_degree: int = 1


def aggregate_costs(records: Sequence) -> Dict[int, Dict[str, float]]:
    """Per-step sums over the executors' cost records:
    {step_idx: {instances, busy_s, dispatches, shard_degree,
    collective_s, min_degree}}."""
    out: Dict[int, Dict[str, float]] = {}
    for rec in records:
        step = out.setdefault(int(rec.step_idx),
                              {"instances": 0, "busy_s": 0.0,
                               "dispatches": 0, "shard_degree": 0,
                               "collective_s": 0.0, "min_degree": 1})
        step["instances"] += 1
        step["busy_s"] += float(rec.busy_s)
        step["dispatches"] += int(rec.dispatches)
        step["shard_degree"] = max(step["shard_degree"],
                                   int(getattr(rec, "shard_degree", 0)))
        step["collective_s"] += float(getattr(rec, "collective_s", 0.0))
        step["min_degree"] = max(step["min_degree"],
                                 int(getattr(rec, "min_degree", 1)))
    return out


def recommend(loads: Dict[int, float], device_budget: int
              ) -> Dict[int, int]:
    """Greedy replica allocation: minimize the predicted bottleneck
    occupancy ``max_i loads[i] / n_i`` subject to ``sum n_i <=
    device_budget`` and ``n_i >= 1``. Deterministic: ties go to the
    lowest step index."""
    steps = sorted(loads)
    if not steps:
        return {}
    n = {s: 1 for s in steps}
    spare = int(device_budget) - len(steps)
    while spare > 0:
        hottest = max(steps, key=lambda s: (loads[s] / n[s], -s))
        if loads[hottest] <= 0.0:
            break  # nothing left that predicts any occupancy
        n[hottest] += 1
        spare -= 1
    return n


def ring_hop_factor(degree: int) -> float:
    """``g(k) = (k-1)/k`` — the fraction of the gathered bytes a
    degree-k ring moves (k-1 one-step hops of 1/k-sized chunks).
    The collective slice of a sharded step's service scales with this
    factor; g(1) = 0 (no ring, no tax)."""
    degree = int(degree)
    return 0.0 if degree <= 1 else (degree - 1) / degree


def service_at_degree(service_s: float, collective_s: float,
                      degree0: int, degree: int) -> Optional[float]:
    """Per-dispatch service predicted at ``degree``, calibrated from
    the measurement at ``degree0``: the compute slice is invariant
    (weight-gathered sharding), the collective slice scales by
    ``g(degree)/g(degree0)``. Returns None when ``degree0 <= 1`` and
    ``degree > 1`` — a degree-1 run measured NO collective, and this
    module refuses to invent one (whatif documents the same limit on
    its ``shard_degree_step<i>`` vocabulary)."""
    degree0, degree = int(degree0), int(degree)
    if degree == degree0:
        return float(service_s)
    g0 = ring_hop_factor(degree0)
    if g0 <= 0.0:
        if degree <= 1:
            return float(service_s)
        return None
    compute = max(0.0, float(service_s) - float(collective_s))
    return compute + float(collective_s) * ring_hop_factor(degree) / g0


def recommend_joint(loads: Dict[int, float], device_budget: int,
                    degrees: Dict[int, int],
                    collective_loads: Dict[int, float],
                    min_degrees: Dict[int, int]) -> Dict[int, Dict]:
    """Greedy min-bottleneck plan over (replicas x shard degree) under
    ``sum_i n_i * k_i <= device_budget``.

    Degree choice is analytic on this cost model: a higher degree only
    ever *adds* collective tax (compute is degree-invariant under
    weight-gathered sharding) while costing more devices per replica,
    so each step takes the smallest degree its memory floor
    (``min_degrees``) admits — the executed degree when the floor
    binds, degree 1 (shedding the whole measured collective slice,
    a calibrated drop, not an assumed one) when it does not. Replicas
    then spread greedily exactly like :func:`recommend`, except each
    replica of step i costs ``k_i`` devices; a step whose ring no
    longer fits the spare budget is skipped for the next-hottest.

    Returns ``{step: {"replicas", "shard_degree", "load"}}``.
    """
    steps = sorted(loads)
    if not steps:
        return {}
    plan: Dict[int, Dict] = {}
    for s in steps:
        d0 = max(1, int(degrees.get(s, 1)))
        floor = max(1, int(min_degrees.get(s, 1)))
        d = d0 if floor > 1 else 1
        if d == d0:
            load = float(loads[s])
        else:
            # calibrated compute-only load at degree 1: shed the
            # measured collective slice
            load = max(0.0,
                       float(loads[s]) - float(collective_loads.get(
                           s, 0.0)))
        plan[s] = {"replicas": 1, "shard_degree": d, "load": load}
    spare = int(device_budget) - sum(p["shard_degree"]
                                     for p in plan.values())
    while spare > 0:
        order = sorted(
            steps,
            key=lambda s: (-(plan[s]["load"] / plan[s]["replicas"]), s))
        gave = False
        for s in order:
            p = plan[s]
            if p["load"] <= 0.0:
                break
            if p["shard_degree"] <= spare:
                p["replicas"] += 1
                spare -= p["shard_degree"]
                gave = True
                break
        if not gave:
            break
    return plan


def build_report(records: Sequence, wall_s: float, device_budget: int,
                 mode: str) -> Optional[Dict[str, object]]:
    """The ``Placement:`` log-meta payload for one finished run: the
    per-step measured costs, the executed plan's predicted occupancy,
    and the recommendation over the device budget. None when nothing
    was measured (no dispatches or no wall window)."""
    costs = aggregate_costs(records)
    if not costs or wall_s <= 0.0:
        return None
    steps: Dict[str, Dict[str, object]] = {}
    loads: Dict[int, float] = {}
    degrees: Dict[int, int] = {}
    collective_loads: Dict[int, float] = {}
    min_degrees: Dict[int, int] = {}
    sharded = False
    for step_idx in sorted(costs):
        c = costs[step_idx]
        dispatches = int(c["dispatches"])
        instances = int(c["instances"])
        busy = float(c["busy_s"])
        service_s = busy / dispatches if dispatches else 0.0
        rate_hz = dispatches / wall_s
        load = rate_hz * service_s
        loads[step_idx] = load
        row: Dict[str, object] = {
            "instances": instances,
            "dispatches": dispatches,
            "service_ms": round(service_s * 1000.0, 3),
            "rate_hz": round(rate_hz, 4),
            # the executed plan's prediction — what parse_utils
            # --check holds to the traced busy fraction
            "occupancy": round(load / instances if instances else 0.0,
                               4),
        }
        degree = int(c.get("shard_degree", 0))
        degrees[step_idx] = max(1, degree)
        min_degrees[step_idx] = int(c.get("min_degree", 1))
        coll_s = float(c.get("collective_s", 0.0))
        collective_loads[step_idx] = (coll_s / dispatches * rate_hz
                                      if dispatches else 0.0)
        if degree > 0:
            # shard-declared step: service_ms above already CONTAINS
            # the collective slice (the corrected service model), and
            # the slice is reported so the calibration is inspectable
            sharded = True
            row["shard_degree"] = degree
            row["collective_ms"] = round(
                (coll_s / dispatches if dispatches else 0.0) * 1000.0,
                3)
        steps["step%d" % step_idx] = row
    if sharded:
        joint = recommend_joint(loads, device_budget, degrees,
                                collective_loads, min_degrees)
        plan_out = {"step%d" % s: {
            "replicas": joint[s]["replicas"],
            "shard_degree": joint[s]["shard_degree"],
            "occupancy": round(joint[s]["load"]
                               / joint[s]["replicas"], 4)}
            for s in sorted(joint)}
    else:
        plan = recommend(loads, device_budget)
        plan_out = {"step%d" % s: {
            "replicas": plan[s],
            "occupancy": round(loads[s] / plan[s], 4)}
            for s in sorted(plan)}
    return {
        "mode": mode,
        "device_budget": int(device_budget),
        "steps": steps,
        "plan": plan_out,
    }
