"""Measured-cost placement planner: replication plans from live costs.

The pipeline's replica counts used to be whatever the config author
guessed. Following AoiZora (PAPERS.md: choose the replication /
partition plan from topology plus *measured* per-stage costs), this
module closes the loop: the executors measure every stage's dispatch
cost over the run's wall window, the planner turns those costs into a
replication plan over the visible device budget, and ``parse_utils
--check`` holds the plan's occupancy *prediction* to the occupancy the
trace timeline actually recorded — a plan whose model drifts from
reality fails the check instead of silently misplacing the next run.

Cost model (deliberately the queueing-free first-order one — the
per-stage numbers it needs are exactly what the runtime already
measures):

* per-dispatch service ``c_i`` = measured busy seconds / dispatches —
  *busy* is the executor's dispatch span (injected fault-plan latency
  + model call + device sync), the same spans the trace timeline
  records as ``exec{i}.model_call``/``exec{i}.device_sync``, so the
  offline check compares like with like;
* offered load ``L_i = rate_i * c_i`` device-seconds per second, with
  ``rate_i`` = dispatches / wall;
* predicted occupancy at ``n`` replicas: ``L_i / n`` — for the
  *executed* plan (``n`` = configured instances) this must land within
  tolerance of the traced busy fraction (the model-consistency check);
  for the *recommendation* the same per-dispatch costs extrapolate.

Recommendation: allocate the device budget greedily — every step gets
one device, then each remaining device goes to the step with the
highest predicted occupancy (ties: lowest step index) — minimizing the
predicted bottleneck occupancy. First-order by design: it ignores
queueing variance and host-side coupling, which is why the prediction
is *checked*, not trusted.

Config (root key, validated in rnb_tpu.config)::

    "placement": {"mode": "plan"}                         // report only
    "placement": {"mode": "apply", "plan": {"step1": 4}}  // auto-apply

``mode: "plan"`` emits the measured costs + recommendation as the
``Placement:`` log-meta JSON line. ``mode: "apply"`` additionally
applies the named replica counts at parse time — each ``step<i>``
entry becomes that step's ``replicas`` (unless the step already
declares one), going through the same replica expansion a hand-written
``replicas`` key does — and still emits the line, so an applied plan's
prediction is verified like any other.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

#: modes the root ``placement`` config key accepts
PLACEMENT_MODES = ("plan", "apply")


@dataclasses.dataclass(frozen=True)
class PlacementSettings:
    """Validated view of the ``placement`` root config key."""

    mode: str
    #: step index -> replica count to apply (apply mode only)
    plan: Tuple[Tuple[int, int], ...] = ()

    @staticmethod
    def from_config(raw: Optional[dict]) -> Optional["PlacementSettings"]:
        """Settings from the (schema-validated) config dict, or None
        when the key is absent or ``enabled`` is false."""
        if not raw or not raw.get("enabled", True):
            return None
        mode = raw.get("mode", "plan")
        plan = tuple(sorted(
            (int(key[4:]), int(val))
            for key, val in dict(raw.get("plan") or {}).items()))
        return PlacementSettings(mode=mode, plan=plan)


@dataclasses.dataclass(frozen=True)
class CostRecord:
    """One executor instance's measured dispatch cost, appended by the
    runner's teardown into the launcher's placement sink."""

    step_idx: int
    busy_s: float
    dispatches: int


def aggregate_costs(records: Sequence) -> Dict[int, Dict[str, float]]:
    """Per-step sums over the executors' cost records:
    {step_idx: {instances, busy_s, dispatches}}."""
    out: Dict[int, Dict[str, float]] = {}
    for rec in records:
        step = out.setdefault(int(rec.step_idx),
                              {"instances": 0, "busy_s": 0.0,
                               "dispatches": 0})
        step["instances"] += 1
        step["busy_s"] += float(rec.busy_s)
        step["dispatches"] += int(rec.dispatches)
    return out


def recommend(loads: Dict[int, float], device_budget: int
              ) -> Dict[int, int]:
    """Greedy replica allocation: minimize the predicted bottleneck
    occupancy ``max_i loads[i] / n_i`` subject to ``sum n_i <=
    device_budget`` and ``n_i >= 1``. Deterministic: ties go to the
    lowest step index."""
    steps = sorted(loads)
    if not steps:
        return {}
    n = {s: 1 for s in steps}
    spare = int(device_budget) - len(steps)
    while spare > 0:
        hottest = max(steps, key=lambda s: (loads[s] / n[s], -s))
        if loads[hottest] <= 0.0:
            break  # nothing left that predicts any occupancy
        n[hottest] += 1
        spare -= 1
    return n


def build_report(records: Sequence, wall_s: float, device_budget: int,
                 mode: str) -> Optional[Dict[str, object]]:
    """The ``Placement:`` log-meta payload for one finished run: the
    per-step measured costs, the executed plan's predicted occupancy,
    and the recommendation over the device budget. None when nothing
    was measured (no dispatches or no wall window)."""
    costs = aggregate_costs(records)
    if not costs or wall_s <= 0.0:
        return None
    steps: Dict[str, Dict[str, object]] = {}
    loads: Dict[int, float] = {}
    for step_idx in sorted(costs):
        c = costs[step_idx]
        dispatches = int(c["dispatches"])
        instances = int(c["instances"])
        busy = float(c["busy_s"])
        service_s = busy / dispatches if dispatches else 0.0
        rate_hz = dispatches / wall_s
        load = rate_hz * service_s
        loads[step_idx] = load
        steps["step%d" % step_idx] = {
            "instances": instances,
            "dispatches": dispatches,
            "service_ms": round(service_s * 1000.0, 3),
            "rate_hz": round(rate_hz, 4),
            # the executed plan's prediction — what parse_utils
            # --check holds to the traced busy fraction
            "occupancy": round(load / instances if instances else 0.0,
                               4),
        }
    plan = recommend(loads, device_budget)
    return {
        "mode": mode,
        "device_budget": int(device_budget),
        "steps": steps,
        "plan": {"step%d" % s: {
            "replicas": plan[s],
            "occupancy": round(loads[s] / plan[s], 4)}
            for s in sorted(plan)},
    }
