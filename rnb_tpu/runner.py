"""The stage executor: one thread per (step, group, device instance).

Capability parity with the reference's per-process hot loop
(runner.py:5-271), re-designed for a single-controller TPU runtime:

* stages are **threads**, not OS processes — JAX async dispatch plays
  the role the private per-process CUDA stream played (reference
  runner.py:41-44); device work from different stages overlaps while
  threads block on queues;
* the tensor hand-off is by reference: a Signal names a ring slot whose
  payload is a tuple of immutable device arrays; "copy-out" is the
  consuming stage's ``jax.device_put`` onto its own device (ICI on real
  hardware), after which the slot is released for reuse;
* segmentation splits the *valid* rows of each output row-wise
  (remainder spread from the front: 11 rows over 3 segments -> 4/4/3,
  reference runner.py:140-154), pads each segment back to the ring's
  static segment shape, and forks the TimeCard per segment;
* a crashed stage raises ``INTERNAL_ERROR`` instead of hanging the job
  (the reference had no failure path for this);
* **request-level fault containment** (rnb_tpu.faults taxonomy): an
  error escaping the model call is classified — *transient* errors are
  retried up to the step's ``max_retries`` with ``retry_backoff_ms``
  of sleep between attempts, *permanent* errors (and exhausted retry
  budgets) stamp the request's TimeCard ``failed`` and dead-letter it
  on the controller while the stream keeps flowing, and everything
  unclassified stays **fatal** exactly as before (stage-init failures
  and ring-protocol violations abort the job). Under the config's
  ``overload_policy: "shed"`` a full downstream queue drops the *new*
  request with a counted ``shed`` outcome instead of aborting with
  ``FRAME_QUEUE_FULL``. A configured :class:`rnb_tpu.faults.FaultPlan`
  is consulted at two hook points (stage stall before the inference
  span; raise/latency per model-call attempt) so chaos behavior is
  deterministic and reproducible.

Synchronization fidelity: by default the executor blocks until a
stage's device output is ready before stamping ``inference_finish`` and
publishing downstream — the analog of the reference's
``stream.synchronize()`` (runner.py:127-128), keeping latency
decompositions honest. Setting ``async_dispatch=True`` on a step
publishes as soon as XLA has the work queued; dataflow stays correct
(consumers wait on the arrays' futures) and throughput improves, but
``inference{i}`` spans then measure dispatch, not device compute.
"""

from __future__ import annotations

import math
import queue
import threading
import time
import traceback
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from rnb_tpu import devobs, hostprof, metrics, trace
from rnb_tpu.control import (NUM_EXIT_MARKERS, BufferRing, EdgeTracker,
                             FaultStats, InferenceCounter, Signal,
                             TerminationFlag, TerminationState,
                             dispose_requests, send_exit_markers)
from rnb_tpu.devices import DeviceSpec
from rnb_tpu.faults import (FATAL, TRANSIENT, LaneDeathError,
                            classify_error, fault_reason)
from rnb_tpu.health import (EVICTED, HEALTHY, LOSER, SUSPECT, WINNER,
                            DirectPayload, deadline_site)
from rnb_tpu.health import cards_of as health_cards_of
from rnb_tpu.health import expired as _deadline_expired
from rnb_tpu.ops.ragged import check_segment_offsets
from rnb_tpu.placement import CostRecord
from rnb_tpu.stage import PaddedBatch, RaggedBatch
from rnb_tpu.telemetry import TimeCardList, TimeCardSummary, logname
from rnb_tpu.utils.class_utils import load_class
from rnb_tpu.utils.lazy_jax import jax_numpy as _jax_numpy

NUM_SUMMARY_SKIPS = 10  # steady-state summaries skip warm records
QUEUE_POLL_S = 0.05
#: floor for deadline-driven poll timeouts: a zero/near-zero deadline
#: must still yield the GIL briefly instead of spinning
MIN_POLL_S = 0.001


def poll_plan(model):
    """``(timeout_s, holding)`` for an accumulator stage's queue poll:
    the stage's own next deadline (hold-timeout expiry / harvest tick
    — under autotune, the controller's next deadline), clamped to
    [MIN_POLL_S, QUEUE_POLL_S], plus whether the stage is actually
    holding work (drives the exec*.hold_wait/queue_get hostprof
    split: waiting to fill a batch is not starvation). Stages without
    deadlines poll at the coarse default. The round-5 frontier
    measured the fixed 50 ms poll as the light-load p99 floor
    (57-61 ms tails against a 5-8 ms configured hold) — emissions
    could only fire on a poll tick."""
    deadline = None
    next_deadline = getattr(model, "next_deadline_s", None)
    if next_deadline is not None:
        deadline = next_deadline()
    if deadline is None:
        return QUEUE_POLL_S, False
    return min(QUEUE_POLL_S, max(MIN_POLL_S, deadline)), True


def poll_timeout(model) -> float:
    """The timeout half of :func:`poll_plan` (kept as the stable
    public face the deadline tests exercise)."""
    return poll_plan(model)[0]
#: sentinel for "an idle poll produced an emission" in the hot loop
_IDLE_EMIT = object()


@dataclass
class RunnerContext:
    """Everything one stage-executor thread needs."""

    in_queue: "queue.Queue"
    out_queues: Optional[List["queue.Queue"]]
    queue_selector_path: str
    print_progress: bool
    job_id: str
    device: DeviceSpec
    group_idx: int
    instance_idx: int
    counter: InferenceCounter
    num_videos: int
    termination: TerminationState
    step_idx: int
    sta_bar: threading.Barrier
    fin_bar: threading.Barrier
    model_class_path: str
    num_segments: int
    input_rings: Optional[Dict[int, List[Optional[BufferRing]]]]
    output_ring: Optional[BufferRing]
    out_trackers: Optional[List[EdgeTracker]] = None
    sync_outputs: bool = True
    log_base: str = "logs"
    model_kwargs: Dict[str, Any] = field(default_factory=dict)
    # final-step instances append their TimeCardSummary here so the
    # controller can report aggregate latency percentiles
    summary_sink: Optional[List] = None
    # -- fault-containment knobs (rnb_tpu.faults / config schema) -----
    #: False = strict reference semantics: even classified errors abort
    containment: bool = True
    #: "abort" (full queue kills the job) | "shed" (drop new requests)
    overload_policy: str = "abort"
    #: transient-error retry budget for this step's model call
    max_retries: int = 0
    retry_backoff_ms: float = 10.0
    #: deterministic injection schedule (FaultPlan), or None
    fault_plan: Optional[Any] = None
    #: job-wide failed/shed/retry accounting shared with the controller
    fault_stats: Optional[FaultStats] = None
    #: stages owning a clip cache (rnb_tpu.cache: `cache_mb` on a
    #: loader step) append their final cache snapshot here so the
    #: controller can report job-wide hit/miss/eviction/coalesced
    #: counts (BenchmarkResult + log-meta `Cache:` line)
    cache_sink: Optional[List] = None
    #: stages owning a staging pool (rnb_tpu.staging: zero-copy decode
    #: staging on a loader step) append their final pool snapshot here
    #: (BenchmarkResult + log-meta `Staging:` line)
    staging_sink: Optional[List] = None
    #: load-adaptive batching (rnb_tpu.autotune): the job's
    #: AutotuneSettings when this step participates (root 'autotune'
    #: config key, per-step opt-out), or None. The executor calls
    #: model.enable_autotune() on supporting stages and feeds the
    #: controller's estimators from the hot loop.
    autotune: Optional[Any] = None
    #: controller-owning stages append their final decision/deadline
    #: counters here (BenchmarkResult + log-meta `Autotune:` line)
    autotune_sink: Optional[List] = None
    #: paged device memory (root 'pager' config key, rnb_tpu.pager):
    #: the job's one Pager when enabled, else None. The executor
    #: calls model.enable_pager() on SUPPORTS_PAGER stages before the
    #: start barrier — the loader switches its clip cache to page
    #: tables, the consuming stage attaches the feature-page arena
    pager: Optional[Any] = None
    #: every stage appends ``(step_idx, warmup_s, sigs-or-None)`` here:
    #: construction wall time plus — for stages owning a jit applier —
    #: the SignatureTracker snapshot (rnb_tpu.compilestats), feeding
    #: the `Compiles:`/`Warmup:` log-meta lines
    compile_sink: Optional[List] = None
    #: batching stages append their PadCounter snapshot here
    #: (BenchmarkResult pad_rows/total_rows + log-meta `Padding:` line)
    pad_sink: Optional[List] = None
    #: ragged stages (root 'ragged' config key) append their
    #: ragged_stats here (BenchmarkResult ragged_* + `Ragged:` line)
    ragged_sink: Optional[List] = None
    #: shard-declared stages (step `shard` key,
    #: rnb_tpu.parallel.shardplan) append ``(step_idx, shard_stats)``
    #: here (BenchmarkResult shard_* + `Shard:`/`Shard steps:` lines)
    shard_sink: Optional[List] = None
    #: per-job rnb_tpu.trace.Tracer when the config's `trace` key
    #: enabled tracing, else None. The executor emits hot-loop spans
    #: through the module-level trace hooks (one None test when off),
    #: calls model.enable_trace(tracer, step_idx) on stages that
    #: refine phase stamps / register occupancy sources, and opts the
    #: final-step summary into `# phases` trailers.
    tracer: Optional[Any] = None
    #: device-resident handoff (root 'handoff' config key,
    #: rnb_tpu.handoff): the job's HandoffSettings for consumer
    #: stages (input_rings present), else None. The executor builds
    #: one EdgeHandoff per instance and applies it to every ring
    #: payload take; snapshots land in handoff_sink.
    handoff_settings: Optional[Any] = None
    #: edge label for this consumer's handoff accounting
    #: ("step{i-1}->step{i}")
    handoff_edge: str = ""
    handoff_sink: Optional[List] = None
    #: measured-cost placement (root 'placement' config key,
    #: rnb_tpu.placement): when set, the executor accumulates its
    #: dispatch busy seconds (fault-plan latency + model call +
    #: device sync — the same work the trace timeline records) and
    #: appends a CostRecord here at teardown
    placement_sink: Optional[List] = None
    #: replica-lane depth counters (rnb_tpu.handoff.InflightDepths)
    #: when the NEXT step is replica-expanded: the producer increments
    #: its chosen lane per successful enqueue and hands the counters
    #: to its ReplicaSelector (least-loaded routing)
    out_depths: Optional[Any] = None
    #: config queue indices parallel to out_queues (lane addressing
    #: for out_depths; None when out_depths is None)
    out_queue_indices: Optional[List[int]] = None
    #: this consumer's side of the replica-lane depth counters: the
    #: executor decrements its lane once a popped item's processing
    #: completed (loop-top settlement), closing the in-flight window
    #: the producer's selector routes on
    in_depths: Optional[Any] = None
    in_queue_idx: Optional[int] = None
    # -- self-healing layer (rnb_tpu.health) --------------------------
    #: this consumer's replica step's LaneHealthBoard (root 'health'
    #: config key): the executor publishes a liveness beat per loop
    #: iteration, settles in-flight age windows, feeds dead-letter
    #: counts, and — on an injected lane death — evicts its lane
    health_board: Optional[Any] = None
    #: the NEXT step's board, handed to this producer's
    #: ReplicaSelector (bind_health) for circuit-gated routing
    out_health_board: Optional[Any] = None
    #: every lane queue of this consumer's replica step (queue idx ->
    #: Queue): the evicted-lane drain re-enqueues
    #: queued-but-undispatched work onto healthy siblings through
    #: these
    sibling_queues: Optional[Dict[int, "queue.Queue"]] = None
    #: deadline propagation (root 'deadline' key): settings + the
    #: job-wide expiry-shed ledger (both None = checks inert)
    deadline: Optional[Any] = None
    deadline_stats: Optional[Any] = None
    #: hedged re-dispatch governors (step key 'hedge_ms' on a
    #: replica step): out_hedges tracks/fires on the producer side of
    #: the edge; in_hedges claims exactly-once resolutions on the
    #: replica step itself
    out_hedges: Optional[Any] = None
    in_hedges: Optional[Any] = None
    #: critical-path extraction (root 'critpath' config key,
    #: rnb_tpu.critpath): when True, final-instance summaries opt
    #: into the `# critpath` table trailer (the job-wide Critpath:
    #: lines are the launcher's aggregation of the same rows) —
    #: False keeps reports byte-stable with the earlier schema
    critpath: bool = False


def split_segments(payload, num_segments: int):
    """Row-split each PaddedBatch's valid rows into ``num_segments``
    per-segment PaddedBatches padded to the segment max shape.

    Segment row counts follow the reference rule (runner.py:140-154):
    ``divmod`` quotient everywhere, remainder spread from the front
    (11 rows, 3 segments -> 4, 4, 3). Segments may be empty when the
    batch has fewer valid rows than segments.
    """
    _, jnp = _jax_numpy()

    if num_segments <= 1:
        return [payload]
    segments = []
    for seg_idx in range(num_segments):
        seg_payload = []
        for pb in payload:
            q, r = divmod(pb.valid, num_segments)
            start = q * seg_idx + min(seg_idx, r)
            end = q * (seg_idx + 1) + min(seg_idx + 1, r)
            seg_rows = end - start
            seg_max = math.ceil(pb.max_rows / num_segments)
            chunk = pb.data[start:start + seg_max]
            pad = seg_max - chunk.shape[0]
            if pad > 0:
                chunk = jnp.concatenate(
                    [chunk, jnp.zeros((pad,) + tuple(chunk.shape[1:]),
                                      chunk.dtype)], axis=0)
            seg_payload.append(PaddedBatch(chunk, seg_rows))
        segments.append(tuple(seg_payload))
    return segments


def _block_on(payload) -> None:
    # deliberate host sync: the executor's stream.synchronize() analog
    # (sync_outputs honesty) — baselined under RNB-H006
    jax, _ = _jax_numpy()
    jax.block_until_ready([pb.data for pb in payload])


def _eos_flush(model):
    """End-of-stream marker seen: the stage's pending partial batch (if
    it accumulates one) becomes the stream's last item. Returns the
    (tensors, non_tensors, time_card) to publish, or None."""
    flushed = model.flush() if hasattr(model, "flush") else None
    if flushed is None or flushed[2] is None:
        return None
    return flushed


def validate_payload(declared, payload, where: str) -> None:
    """Assert a stage's produced payload matches its declared
    ``output_shape_for``: same tensor count, same trailing dims, row
    axis no larger than the declared max (smaller is legal under row
    bucketing). Keeps shape metadata honest — a declaration nothing
    checks is dead metadata that silently rots.
    """
    payload = tuple(payload) if payload else ()
    if declared is None:
        if payload:
            raise ValueError(
                "%s declares no tensor outputs (output_shape None) but "
                "produced %d tensor(s)" % (where, len(payload)))
        return
    declared = tuple(map(tuple, declared))
    if len(payload) != len(declared):
        raise ValueError(
            "%s declares %d output tensor(s) %r but produced %d"
            % (where, len(declared), declared, len(payload)))
    for idx, (pb, want) in enumerate(zip(payload, declared)):
        got = tuple(int(d) for d in pb.data.shape)
        if (len(got) != len(want) or got[1:] != want[1:]
                or got[0] > want[0]):
            raise ValueError(
                "%s output %d has shape %r but declares %r (row axis may "
                "be smaller under bucketing, never larger; trailing dims "
                "must match exactly)" % (where, idx, got, want))
        if isinstance(pb, RaggedBatch):
            # ragged payloads additionally carry a per-request segment
            # table that must partition the valid rows — a broken pool
            # fill fails here, at the producing step, not as garbage
            # logits downstream
            try:
                check_segment_offsets(pb.segment_offsets, pb.valid)
            except ValueError as e:
                raise ValueError("%s output %d: %s" % (where, idx, e))


# the ONE fused-card unwrap rule, shared with the hedge governor's
# claim/key identity (rnb_tpu.health.cards_of) — two copies could
# silently diverge on what "the cards behind one item" means
_cards_of = health_cards_of


def _hedge_lost(ctx: RunnerContext, time_card) -> bool:
    """Exactly-once resolution at a hedged replica step: the FIRST
    disposal/completion event of a hedged dispatch claims the request
    id(s); the second copy's event is the loser — its result (or
    failure) is discarded with its burned service time counted as
    hedge waste, and the caller must drop the item without touching
    the counters (the rid already terminated through the winner).

    One COPY claims at most once: a copy that already claimed WINNER
    (marked ``hedge_resolved`` on its cards) owns the rid's terminal
    outcome — a later disposal of the same copy in the same iteration
    (e.g. its deadline expired between completion and publish)
    proceeds normally instead of consuming the sibling's LOSER slot,
    which would let the real sibling copy claim UNTRACKED and publish
    the rid a second time."""
    if ctx.in_hedges is None:
        return False
    cards = _cards_of(time_card)
    if any(getattr(tc, "hedge_resolved", False) for tc in cards):
        return False
    verdict = ctx.in_hedges.claim(time_card)
    if verdict == LOSER:
        ctx.in_hedges.discard(time_card)
        return True
    if verdict == WINNER:
        for tc in cards:
            tc.hedge_resolved = True
    return False


def _contain_failure(ctx: RunnerContext, time_card, reason: str,
                     summary) -> None:
    """Dead-letter one item's request(s): stamp the card(s) failed,
    record job-wide accounting, and count the disposal toward the run
    target so the job still terminates (a failed request will never
    produce the completion the target otherwise waits for)."""
    if _hedge_lost(ctx, time_card):
        return
    cards = _cards_of(time_card)
    for tc in cards:
        if tc.status == "ok":
            tc.mark_failed(reason)
    if ctx.fault_stats is not None:
        ctx.fault_stats.record_failure([tc.id for tc in cards],
                                       ctx.step_idx, reason)
    if ctx.health_board is not None:
        # the lane's dead-letter signal (one of the three circuit
        # inputs next to in-flight age and the liveness beat)
        ctx.health_board.note_failure(ctx.in_queue_idx)
    if summary is not None:
        summary.note_failure(reason, len(cards))
    dispose_requests(ctx.counter, ctx.num_videos, ctx.termination,
                     len(cards))


def _shed_item(ctx: RunnerContext, time_card, summary,
               lane: Optional[int] = None) -> None:
    """Drop one item under ``overload_policy: "shed"`` (downstream
    queue full): counted, stamped, disposed — never aborts the job.
    ``lane`` names the chosen replica lane queue when the full edge is
    replica-expanded, so shed-site accounting is per-lane."""
    if _hedge_lost(ctx, time_card):
        return
    site = ("step%d_out_queue.lane%d" % (ctx.step_idx, lane)
            if lane is not None
            else "step%d_out_queue" % ctx.step_idx)
    cards = _cards_of(time_card)
    for tc in cards:
        tc.mark_shed(site)
    if ctx.fault_stats is not None:
        ctx.fault_stats.record_shed(site, len(cards))
    if summary is not None:
        summary.note_shed(len(cards))
    dispose_requests(ctx.counter, ctx.num_videos, ctx.termination,
                     len(cards))


def _shed_deadline(ctx: RunnerContext, time_card, where: str,
                   summary) -> None:
    """Shed an item whose every constituent blew its absolute deadline
    (rnb_tpu.health, root 'deadline' key): the expiry rides the PR 1
    shed machinery — counted in FaultStats per site AND in the
    deadline ledger, which parse_utils --check cross-foots."""
    if _hedge_lost(ctx, time_card):
        return
    site = deadline_site(where)
    cards = _cards_of(time_card)
    for tc in cards:
        tc.mark_shed(site)
    if ctx.fault_stats is not None:
        ctx.fault_stats.record_shed(site, len(cards))
    if ctx.deadline_stats is not None:
        ctx.deadline_stats.record(site, len(cards))
    if summary is not None:
        summary.note_shed(len(cards))
    dispose_requests(ctx.counter, ctx.num_videos, ctx.termination,
                     len(cards))


def _sheddable_expired(ctx: RunnerContext, time_card) -> bool:
    """Deadline boundary check: expired AND legal to shed (forked
    segment cards never shed — dropping one segment would strand its
    aggregator siblings, same rule as the overload shed path)."""
    return (ctx.deadline is not None
            and getattr(time_card, "sub_id", None) is None
            and _deadline_expired(time_card))


def _pick_lane(depths, board, queue_indices,
               exclude: Optional[int] = None) -> Optional[int]:
    """Deterministic healthy-sibling choice for hedges and evicted-
    lane redispatch: healthy/suspect lanes first, non-evicted as the
    fallback, least-loaded wins with the lowest queue index as the
    stable tie-break. None when no candidate lane exists."""
    candidates = [q for q in queue_indices if q != exclude]
    if board is not None:
        live = [q for q in candidates
                if board.state(q) in (HEALTHY, SUSPECT)]
        if not live:
            live = [q for q in candidates
                    if board.state(q) != EVICTED]
        candidates = live
    if not candidates:
        return None
    if depths is None:
        return candidates[0]
    return min(candidates, key=lambda q: (depths.depth(q), q))


def _fire_hedges(ctx: RunnerContext) -> None:
    """Producer-side hedge tick: re-issue every dispatch outstanding
    past the governor's threshold onto the best healthy sibling lane.
    The hedge item carries its payload directly (DirectPayload) — the
    original still owns its ring slot — and a stamp-complete card
    clone, so whichever copy resolves first produces an identical
    summary row. A full sibling queue just defers the hedge to a
    later tick (hedging must never add backpressure)."""
    gov = ctx.out_hedges
    if gov is None or ctx.out_queues is None:
        return
    for entry in gov.poll():
        lane = _pick_lane(ctx.out_depths, ctx.out_health_board,
                          ctx.out_queue_indices, exclude=entry.lane)
        if lane is None:
            continue
        # commit BEFORE the enqueue: begin_fire re-checks under the
        # governor lock that the dispatch is still unresolved, so a
        # copy can never be fired for a request that already claimed
        # (the late copy would win a second time and double-publish)
        if not gov.begin_fire(entry):
            continue
        item = (DirectPayload(entry.payload), entry.non_tensors,
                entry.card)
        try:
            ctx.out_queues[ctx.out_queue_indices.index(lane)] \
                .put_nowait(item)
        except queue.Full:
            gov.cancel_fire(entry)
            continue
        if ctx.out_depths is not None:
            ctx.out_depths.inc(lane)
        if ctx.out_health_board is not None:
            ctx.out_health_board.note_enqueue(lane)


def _linger_for_hedges(ctx: RunnerContext) -> None:
    """Producer end-of-stream hook: the stream may end long before a
    wedged downstream dispatch exceeds its hedge threshold — exiting
    then would orphan exactly the tail dispatches hedging exists for.
    Keep ticking the governor until every tracked dispatch settled
    (consumers settle at their loop top, so this drains naturally) or
    the job terminates; the caller sends exit markers only after, so
    a late hedge can never arrive behind an end-of-stream marker."""
    gov = ctx.out_hedges
    if gov is None:
        return
    while not ctx.termination.terminated and gov.num_outstanding():
        _fire_hedges(ctx)
        time.sleep(QUEUE_POLL_S / 5.0)


def _die_lane(ctx: RunnerContext, exc: LaneDeathError,
              summary) -> None:
    """This replica lane's executor is dead (injected replica_crash /
    replica_stall): once the lane's LAST instance died, evict the
    lane so the upstream selector stops feeding it, then run a
    drain-and-redispatch pump until end-of-stream: every
    queued-but-undispatched item moves to a healthy sibling lane
    (``redispatched`` content stamp, in-flight windows reconciled on
    both lanes), so no request is ever stranded behind a dead lane.
    No model call happens after the death; the in-service dispatch
    was already dead-lettered by the caller."""
    if ctx.health_board is None:
        # no board: siblings have no end-of-stream linger, so a late
        # redispatch could land in a queue whose executor already
        # exited, and instance deaths cannot be coordinated — the
        # launcher rejects lane-death fault plans without the root
        # 'health' key, so this is only the defensive backstop
        return
    if ctx.health_board.instance_died(ctx.in_queue_idx) > 0:
        # a live sibling instance still consumes this lane's
        # queue — the lane serves on at reduced capacity, and
        # draining it would steal live work, not rescue it. (A
        # lane-addressed fault will kill that instance too on
        # its next matching dispatch; the LAST death drains.)
        return
    ctx.health_board.evict(ctx.in_queue_idx,
                           "replica-%s" % exc.fate)
    if ctx.sibling_queues is None:
        return
    targets = {q: sq for q, sq in ctx.sibling_queues.items()
               if q != ctx.in_queue_idx}
    if not targets:
        return
    tr_redispatch = trace.name("exec%d.redispatch", ctx.step_idx)
    try:
        _pump_dead_lane(ctx, targets, tr_redispatch)
    finally:
        # the dead lane's stream is over (its queue remainder moved to
        # siblings): release any sibling lingering on the drained
        # latch (rnb_tpu.health end-of-stream protocol)
        if ctx.health_board is not None:
            ctx.health_board.note_drained(ctx.in_queue_idx)


def _pump_dead_lane(ctx: RunnerContext, targets, tr_redispatch) -> None:
    while not ctx.termination.terminated:
        try:
            item = ctx.in_queue.get(timeout=QUEUE_POLL_S)
        except queue.Empty:
            continue
        if item is None:
            return  # end-of-stream: nothing more can strand here
        lane = _pick_lane(ctx.in_depths, ctx.health_board,
                          sorted(targets))
        if lane is None:
            lane = sorted(targets)[0]
        _sig, _nt, tc = item
        with trace.span(tr_redispatch):
            for c in _cards_of(tc):
                c.redispatched = getattr(c, "redispatched", 0) + 1
            # bounded put + liveness re-check: a dying pipeline must
            # not wedge the drain pump forever (RNB-H009 discipline)
            while not ctx.termination.terminated:
                try:
                    targets[lane].put(item, timeout=QUEUE_POLL_S)
                    break
                except queue.Full:
                    continue
            else:
                return
        if ctx.in_depths is not None:
            # reconcile the in-flight windows: the item leaves this
            # lane's count and joins the target's, so the selector's
            # depth view (and --check's settlement) still closes
            ctx.in_depths.dec(ctx.in_queue_idx)
            ctx.in_depths.inc(lane)
        if ctx.health_board is not None:
            ctx.health_board.note_settle(ctx.in_queue_idx)
            ctx.health_board.note_enqueue(lane)
            ctx.health_board.note_redispatch(ctx.in_queue_idx)
        # (a moved dispatch is still the ORIGINAL hedge copy, if one
        # was fired for it: its claim window keeps running and
        # resolves wherever it lands)


def _drain_stage_failures(ctx: RunnerContext, take_failed, take_retries,
                          summary) -> None:
    """Collect requests a stage contained *internally* (e.g. the fusing
    loader excluding a corrupt video from a fused batch): stages with
    intra-stage batching expose ``take_failed() -> [(card, reason)]``
    and the executor turns each entry into a normal dead-letter —
    unless containment is disabled, in which case a stage-contained
    failure still aborts the job (strict reference semantics must not
    depend on which code path an error took)."""
    if take_retries is not None:
        n = take_retries()
        if n:
            if ctx.fault_stats is not None:
                ctx.fault_stats.record_retries(n)
            if summary is not None:
                summary.note_retries(n)
    if take_failed is None:
        return
    for tc, reason in take_failed():
        if not ctx.containment:
            raise RuntimeError(
                "request %s failed in-stage (%s) with fault_containment "
                "disabled" % (getattr(tc, "id", "?"), reason))
        _contain_failure(ctx, tc, reason, summary)


def runner(ctx: RunnerContext) -> None:
    """Thread entry: init the stage, run the hot loop, drain cleanly."""
    summary = TimeCardSummary() if ctx.out_queues is None else None
    if summary is not None and ctx.tracer is not None:
        # trace-enabled runs opt the per-instance report into the
        # `# phases` trailer (same steady-state skip as the job-wide
        # Phases: line); trace-off reports stay byte-stable
        summary.track_phases = True
        summary.phase_num_skips = NUM_SUMMARY_SKIPS
    if summary is not None and ctx.critpath:
        # critpath-enabled runs opt the report into the `# critpath`
        # trailer (same steady-state skip as the job-wide Critpath:
        # lines); critpath-off reports stay byte-stable
        summary.track_critpath = True
        summary.critpath_num_skips = NUM_SUMMARY_SKIPS
    progress_bar = None
    declared_shapes = None
    controller = None
    handoff = None
    warmup_s = 0.0
    # measured-cost placement accounting (rnb_tpu.placement): busy =
    # this executor's dispatch spans (fault-plan latency + model call
    # + device sync), the same work the trace timeline records — the
    # planner's occupancy prediction is checked against the traced
    # busy fraction, so the two MUST measure the same thing
    stage_busy_s = 0.0
    stage_dispatches = 0
    # replica-lane in-flight settlement: items popped whose depth
    # decrement is owed at the next loop top (after their processing
    # completed) — rnb_tpu.handoff.InflightDepths
    depth_owed = 0
    try:
        model_class = load_class(ctx.model_class_path)
        # warmup wall time: weights + warmup compiles all happen in the
        # stage constructor, before the start barrier — the launch cost
        # the `Warmup:` accounting surfaces (ragged collapses the
        # per-bucket compile matrix here)
        t_construct = time.monotonic()
        model = model_class(ctx.device, **ctx.model_kwargs)
        warmup_s = time.monotonic() - t_construct
        declared_shapes = model_class.output_shape_for(**ctx.model_kwargs)

        selector = None
        if ctx.out_queues is not None:
            selector_class = load_class(ctx.queue_selector_path)
            selector = selector_class(len(ctx.out_queues))
            selector.bind_stage(model)
            if ctx.out_depths is not None \
                    and hasattr(selector, "bind_depths"):
                # replica-lane routing (rnb_tpu.selector
                # .ReplicaSelector): share the downstream step's
                # in-flight depth counters so routing is least-loaded
                selector.bind_depths(ctx.out_depths,
                                     ctx.out_queue_indices)
                if ctx.out_health_board is not None \
                        and hasattr(selector, "bind_health"):
                    # circuit-gated routing (rnb_tpu.health): open/
                    # evicted lanes leave the candidate set; half-open
                    # lanes get their single recovery probe
                    selector.bind_health(ctx.out_health_board)
        if ctx.health_board is not None:
            # lane-instance census (pre-barrier, so deaths can never
            # race registration): the LAST instance to die is the one
            # that drains the lane
            ctx.health_board.register_instance(ctx.in_queue_idx)
        if ctx.handoff_settings is not None \
                and ctx.input_rings is not None:
            # device-resident handoff (rnb_tpu.handoff): this
            # consumer's side of the edge contract, re-home target
            # refined by the stage's input_sharding() when declared
            from rnb_tpu.handoff import EdgeHandoff
            handoff = EdgeHandoff(
                ctx.handoff_settings, ctx.device, ctx.handoff_edge,
                model,
                # pager-owned shared pools (feature-hit stubs) are
                # footed under the page_pool ledger owner — exclude
                # them from this edge's residency claim
                external_owner=(ctx.pager.owns
                                if ctx.pager is not None else None))
        if ctx.autotune is not None \
                and getattr(model, "SUPPORTS_AUTOTUNE", False):
            # load-adaptive batching (rnb_tpu.autotune): the stage
            # builds its controller over its OWN warmed bucket set —
            # a bucket restriction it never warms is rejected here
            # (and statically by rnb-lint RNB-G006)
            controller = model.enable_autotune(ctx.autotune)
        if ctx.pager is not None \
                and getattr(model, "SUPPORTS_PAGER", False):
            # paged device memory (rnb_tpu.pager): arenas allocate and
            # register with the memory ledger here, pre-barrier, so
            # every Memory:/Pages: sample covers the full page pool
            model.enable_pager(ctx.pager)
        if ctx.tracer is not None and hasattr(model, "enable_trace"):
            # unified tracing (rnb_tpu.trace): stages that refine the
            # per-request phase stamps (decode/hold/transfer) and own
            # sampled occupancy sources wire themselves up here; the
            # executor's own spans need no stage support
            model.enable_trace(ctx.tracer, ctx.step_idx)
        if hasattr(model, "bind_shard_step"):
            # intra-stage sharding (rnb_tpu.parallel.shardplan): the
            # stage host-times its merge collective as
            # exec{i}.collective — unconditional (unlike enable_trace)
            # because hostprof and the Shard: accounting need the
            # step index even on trace-disabled runs
            model.bind_shard_step(ctx.step_idx)
        # live-metrics plane (rnb_tpu.metrics): stage-owned subsystems
        # (clip cache, staging pool, handoff edge) become poll sources
        # of the active registry — registered before the start barrier
        # so every flusher tick sees the full source set (no-op when
        # metrics are off)
        metrics.register_stage(model, handoff)
        # device observability plane (rnb_tpu.devobs): the stage's
        # declared compute profile becomes a per-step MFU meter and
        # its byte-owning subsystems (params, cache, staging, ragged
        # pool, handoff adoptions) become HBM-ledger sources — all
        # pre-barrier, so every sample covers the full source set
        # (no-op when devobs is off)
        devobs.register_stage(model, ctx.step_idx, ctx.device, handoff)
    except Exception:
        traceback.print_exc()
        ctx.termination.raise_flag(TerminationFlag.INTERNAL_ERROR)
        model = None

    try:
        ctx.sta_bar.wait()
    except threading.BrokenBarrierError:
        pass
    # the measured window opens here: any jit-entry signature the
    # stage's applier first sees from now on is a mid-run recompile
    # (surfaced as steady_new in the Compiles: accounting; parse_utils
    # --check fails on nonzero)
    compile_tracker = getattr(model, "compiles", None)
    if compile_tracker is not None:
        compile_tracker.freeze()

    if ctx.print_progress:
        try:
            from tqdm import tqdm
            progress_bar = tqdm(total=ctx.num_videos)
        except ImportError:
            progress_bar = None

    ring_counter = 0  # next output slot (reference runner.py:60-61)
    # accumulator stages expose poll() for the idle tick; resolve once
    idle_poll = getattr(model, "poll", None)
    # stages with intra-stage batching surface internally-contained
    # request failures through take_failed(); resolve once
    take_failed = getattr(model, "take_failed", None)
    take_retries = getattr(model, "take_retries", None)
    # stages with a pipelined transfer handoff (rnb_tpu.staging:
    # transfer_async on a fusing loader) surface completed emissions
    # through take_ready(); resolve once
    take_ready = getattr(model, "take_ready", None)
    # stages that hold work internally (loader accumulator, Batcher)
    # surface deadline-expired requests they shed at admission through
    # take_shed() -> [(card, where)]; resolve once
    take_shed = getattr(model, "take_shed", None)
    if model is not None and take_failed is not None and ctx.containment:
        # stages with internal containment retry transients themselves;
        # hand them the step's schema retry knobs (never model kwargs).
        # In strict mode the budget stays (0, 0): the stage parks the
        # failure unretried and the drain below aborts the job, matching
        # the executor path's first-attempt abort.
        model.fault_retry_budget = (ctx.max_retries, ctx.retry_backoff_ms)
    old_counter_value = 0
    # loop-invariant hostprof section names, formatted once
    sec_queue_get = "exec%d.queue_get" % ctx.step_idx
    sec_hold_wait = "exec%d.hold_wait" % ctx.step_idx
    sec_model_call = "exec%d.model_call" % ctx.step_idx
    sec_device_sync = "exec%d.device_sync" % ctx.step_idx
    sec_ring_publish = "exec%d.ring_publish" % ctx.step_idx
    sec_bookkeeping = "exec%d.bookkeeping" % ctx.step_idx
    sec_enqueue = "exec%d.route+enqueue" % ctx.step_idx
    sec_handoff = "exec%d.handoff" % ctx.step_idx
    # loop-invariant stamp keys the autotune service feed reads (these
    # are lookups of stamps the record() sites below write, not new
    # stamp sites)
    key_inf_start = "inference%d_start" % ctx.step_idx
    key_inf_finish = "inference%d_finish" % ctx.step_idx
    # loop-invariant trace event names (rnb_tpu.trace): formatted once
    # here so the hot loop's disabled path stays one None test with no
    # allocation (the trace.name literals are what RNB-T008 checks)
    tr_queue_get = trace.name("exec%d.queue_get", ctx.step_idx)
    tr_hold_wait = trace.name("exec%d.hold_wait", ctx.step_idx)
    tr_swallow = trace.name("exec%d.swallow", ctx.step_idx)
    tr_model_call = trace.name("exec%d.model_call", ctx.step_idx)
    tr_device_sync = trace.name("exec%d.device_sync", ctx.step_idx)
    tr_publish = trace.name("exec%d.publish", ctx.step_idx)
    tr_handoff = trace.name("exec%d.handoff", ctx.step_idx)
    # devobs compute meter (rnb_tpu.devobs): resolved once — None when
    # devobs is off or this stage declares no compute profile, so the
    # per-dispatch cost of the disabled path is one None test
    devobs_meter = devobs.meter_for(ctx.step_idx)

    # Prefetch (NVVL parity, reference README.md:46-110): a signal-free
    # first stage exposing submit()/complete() gets its next requests'
    # host work (decode) kicked off while the head request's device work
    # runs. Depth 0 (or any tensor-input stage) keeps the classic loop.
    prefetch_depth = 0
    if (model is not None and ctx.input_rings is None
            and hasattr(model, "submit") and hasattr(model, "complete")):
        prefetch_depth = int(getattr(model, "prefetch_depth", 0) or 0)
    pending = deque()  # (handle, non_tensors, time_card) submitted
    saw_marker = False
    # end-of-stream linger (health-enabled replica lanes): this lane
    # saw its exit marker but siblings may still redispatch stranded
    # work here — keep polling until the whole step drained
    marker_noted = False
    # all_drained was observed True once: one final timed sweep of the
    # queue runs before exiting (a pump's last put happens-before its
    # drained note, so one more poll after the observation closes the
    # Empty-then-put-then-drained ordering race)
    linger_final_sweep = False

    try:
        if model is not None:
            while not ctx.termination.terminated:
                if ctx.health_board is not None:
                    # explicit liveness beat: a wedged executor stops
                    # publishing these while its queue keeps aging —
                    # the circuit's missing-liveness signal
                    ctx.health_board.beat(ctx.in_queue_idx)
                if ctx.out_hedges is not None:
                    # producer-side hedge tick: re-issue dispatches
                    # outstanding past the threshold onto healthy
                    # siblings (rnb_tpu.health)
                    _fire_hedges(ctx)
                if depth_owed:
                    # the previous iteration's popped item(s) have
                    # fully processed: close their in-flight window so
                    # the upstream ReplicaSelector stops counting them
                    # against this lane
                    if ctx.in_depths is not None:
                        ctx.in_depths.dec(ctx.in_queue_idx, depth_owed)
                    if ctx.health_board is not None:
                        ctx.health_board.note_settle(ctx.in_queue_idx,
                                                     depth_owed)
                    depth_owed = 0
                # dead-letter requests the stage contained internally
                # during the previous iteration (fused-batch members
                # whose decode failed)
                _drain_stage_failures(ctx, take_failed, take_retries,
                                      summary)
                if take_shed is not None:
                    # requests the stage shed at admission because
                    # their deadline expired while it held work
                    for tc_shed, where in take_shed():
                        _shed_deadline(ctx, tc_shed,
                                       "step%d_%s" % (ctx.step_idx,
                                                      where), summary)
                handle = None
                # end-of-stream flush: a marker with an accumulating
                # stage (batcher) still holding a partial batch emits
                # that batch as one last item before draining, so the
                # final ``num_videos mod batch`` requests complete
                # instead of stranding the run
                flushed = None
                if take_ready is not None:
                    # publish handoff: a fused batch whose (possibly
                    # worker-side) transfer completed publishes BEFORE
                    # new input is admitted — bounded completion
                    # latency, and natural backpressure toward the
                    # input queue while transfers are behind
                    flushed = take_ready()
                if flushed is not None:
                    pass  # fall through to the publish path below
                elif saw_marker and prefetch_depth == 0:
                    # draining: the stage may hold MORE than one pending
                    # batch (e.g. a fusing loader's accumulator), so
                    # keep calling flush() until it runs dry instead of
                    # consuming one exit marker per flushed batch —
                    # markers are finite (NUM_EXIT_MARKERS) and running
                    # out would silently strand the tail requests
                    flushed = _eos_flush(model)
                    if flushed is None:
                        break
                elif prefetch_depth > 0:
                    while (not saw_marker
                           and len(pending) < prefetch_depth + 1):
                        try:
                            item = ctx.in_queue.get(block=not pending,
                                                    timeout=QUEUE_POLL_S)
                        except queue.Empty:
                            break
                        if item is None:
                            saw_marker = True
                            break
                        _sig, nt, tc = item
                        tc.add_device(ctx.device.label)
                        tc.record("runner%d_start" % ctx.step_idx)
                        if ctx.tracer is not None:
                            trace.instant(tr_swallow, rid=tc.id)
                        if _sheddable_expired(ctx, tc):
                            # expiry shed before the decode is even
                            # submitted — the whole point of deadline
                            # propagation is never decoding doomed work
                            _shed_deadline(ctx, tc,
                                           "step%d_take" % ctx.step_idx,
                                           summary)
                            continue
                        try:
                            pending.append((model.submit(nt, tc), nt, tc))
                        except Exception as exc:
                            # a submit-time decode error (corrupt
                            # header, vanished file) fails only this
                            # request; unclassified errors stay fatal
                            if classify_error(exc) is FATAL \
                                    or not ctx.containment:
                                raise
                            _contain_failure(ctx, tc, fault_reason(exc),
                                             summary)
                    if pending:
                        handle, non_tensors, time_card = pending.popleft()
                        signal, tensors = None, None
                    elif saw_marker:
                        flushed = _eos_flush(model)
                        if flushed is None:
                            break  # end-of-stream, all work drained
                    else:
                        continue
                else:
                    try:
                        if idle_poll is None:
                            with hostprof.section(sec_queue_get), \
                                    trace.span(tr_queue_get):
                                item = ctx.in_queue.get(
                                    timeout=QUEUE_POLL_S)
                        else:
                            # accumulator stages: the poll window
                            # shrinks to the stage's next deadline
                            # (under autotune, the controller's), and
                            # time spent blocked while the stage HOLDS
                            # work is batch-fill wait, not queue
                            # starvation — hostprof splits the two
                            timeout, holding = poll_plan(model)
                            with hostprof.section(
                                    sec_hold_wait if holding
                                    else sec_queue_get), \
                                    trace.span(tr_hold_wait if holding
                                               else tr_queue_get):
                                item = ctx.in_queue.get(timeout=timeout)
                    except queue.Empty:
                        if marker_noted \
                                and ctx.health_board.all_drained():
                            # lingering past our own end-of-stream and
                            # every sibling lane has now drained too.
                            # A pump's final put may have landed
                            # BETWEEN our Empty and this check (puts
                            # happen-before the drained note), so run
                            # exactly one more timed sweep before
                            # exiting — after all_drained, no NEW put
                            # can occur, so the second Empty is proof
                            if linger_final_sweep:
                                break
                            linger_final_sweep = True
                            continue
                        # idle tick: give accumulator stages (fusing
                        # loader) a chance to emit on hold-timeout —
                        # without this, a decoded request would wait
                        # for the NEXT arrival, paying a full
                        # inter-arrival gap instead of max_hold_ms
                        # (+<= QUEUE_POLL_S of poll granularity)
                        if idle_poll is None:
                            continue
                        flushed = idle_poll()
                        if flushed is None or flushed[2] is None:
                            continue
                        item = _IDLE_EMIT
                    if item is _IDLE_EMIT:
                        pass  # flushed already holds the emission
                    elif item is None:
                        if ctx.health_board is not None:
                            # end-of-stream LINGER (rnb_tpu.health):
                            # a lane evicted after this one finished
                            # redispatches its queue here — exiting
                            # on our own marker would strand that
                            # work in a queue nobody reads. Note our
                            # drain, keep polling, and exit only once
                            # every sibling lane drained too.
                            if not marker_noted:
                                ctx.health_board.note_drained(
                                    ctx.in_queue_idx)
                                marker_noted = True
                            flushed = _eos_flush(model)
                            if flushed is None:
                                if ctx.health_board.all_drained():
                                    # same one-more-sweep rule as the
                                    # Empty branch: a pump's final put
                                    # can precede its drained note
                                    if linger_final_sweep:
                                        saw_marker = True
                                        break
                                    linger_final_sweep = True
                                continue
                        else:
                            saw_marker = True
                            flushed = _eos_flush(model)
                            if flushed is None:
                                break  # end-of-stream marker
                    else:
                        signal, non_tensors, time_card = item
                        if ctx.in_depths is not None:
                            # settle at the NEXT loop top (processing
                            # complete), not here — depth must cover
                            # in-service time or the router's view
                            # collapses to queue length
                            depth_owed += 1
                        time_card.add_device(ctx.device.label)
                        time_card.record("runner%d_start" % ctx.step_idx)
                        if ctx.tracer is not None:
                            # request-id flow anchors: one admitted
                            # item may carry many cards (an upstream
                            # fused batch)
                            for _tc in _cards_of(time_card):
                                trace.instant(tr_swallow, rid=_tc.id)
                        if controller is not None:
                            # arrival-rate estimator: the client's
                            # enqueue stamps (pure host arithmetic,
                            # no clock call)
                            for tc in _cards_of(time_card):
                                t_enq = tc.timings.get("enqueue_filename")
                                if t_enq is not None:
                                    controller.observe_enqueue(t_enq)

                        if isinstance(signal, DirectPayload):
                            # a hedged re-dispatch (rnb_tpu.health):
                            # the payload rides inside the item — the
                            # ORIGINAL copy still owns its ring slot,
                            # so there is no slot to read or release
                            tensors = signal.payload
                            signal = None
                        elif signal is not None:
                            ring = ctx.input_rings[signal.group_idx][
                                signal.instance_idx]
                            slot = ring.slots[signal.tensor_idx]
                            tensors = slot.read()
                            if tensors is None:
                                # an abort-path release_all() cleared the
                                # slot between our queue pop and this
                                # read — exit (reference runner.py:96-100)
                                break
                            slot.release()
                        else:
                            tensors = None
                        if _sheddable_expired(ctx, time_card):
                            # queue-take expiry shed (root 'deadline'
                            # key): the request's budget is already
                            # blown — drop it HERE, before decode /
                            # reshard / model work burns anything on
                            # it (the ring slot above is released, so
                            # nothing upstream blocks)
                            _shed_deadline(ctx, time_card,
                                           "step%d_take" % ctx.step_idx,
                                           summary)
                            continue
                        if handoff is not None and tensors:
                            # the edge contract (rnb_tpu.handoff):
                            # adopt/reshard the committed payload
                            # onto this consumer — and account the
                            # move, so "zero host-hop bytes" is a
                            # log fact, not a claim
                            with hostprof.section(sec_handoff), \
                                    trace.span(tr_handoff):
                                tensors = handoff.take(tensors)

                if flushed is not None:
                    # constituents carry their own runner/inference start
                    # stamps from when the batcher swallowed them
                    tensors_out, non_tensors_out, time_card = flushed
                else:
                    in_card = time_card
                    rids = None
                    if ctx.fault_plan is not None:
                        # injection key: every constituent id (a fault
                        # matching ANY member of a fused batch affects
                        # the whole dispatch)
                        rids = [tc.id for tc in _cards_of(in_card)]
                        # 'stall' injection wedges the stage BEFORE the
                        # inference span: the delay surfaces downstream
                        # as queue wait while this stage's input queue
                        # backs up — a reproducible overload window
                        stall = ctx.fault_plan.stall_ms(
                            ctx.step_idx, rids, lane=ctx.in_queue_idx)
                        if stall > 0:
                            time.sleep(stall / 1000.0)
                    time_card.record("inference%d_start" % ctx.step_idx)
                    attempt = 0
                    failed_reason = None
                    lane_death = None
                    t_busy0 = (time.monotonic()
                               if ctx.placement_sink is not None
                               else None)
                    while True:
                        try:
                            with hostprof.section(sec_model_call), \
                                    trace.span(tr_model_call,
                                               getattr(in_card, "id",
                                                       None)):
                                if ctx.fault_plan is not None:
                                    # inside the model_call span:
                                    # injected 'latency' is emulated
                                    # stage service, and the trace
                                    # timeline / placement busy
                                    # accounting must agree on what
                                    # service means; the lane address
                                    # lets replica_crash/replica_stall
                                    # faults target ONE lane
                                    ctx.fault_plan.fire(
                                        ctx.step_idx, rids, attempt,
                                        lane=ctx.in_queue_idx)
                                if handle is not None and attempt == 0:
                                    tensors_out, non_tensors_out, \
                                        time_card = model.complete(
                                            handle, non_tensors, in_card)
                                else:
                                    # retries re-run the synchronous
                                    # path even for prefetched work: the
                                    # failed handle's decode cannot be
                                    # re-waited, only redone
                                    tensors_out, non_tensors_out, \
                                        time_card = model(
                                            tensors, non_tensors, in_card)
                            break
                        except Exception as exc:
                            if handle is not None:
                                # this request will never complete() the
                                # prefetched decode again (retries
                                # re-decode synchronously; injected
                                # errors may fire before complete ever
                                # ran): retire its pool tickets now or
                                # the decode buffers stay pinned in the
                                # native pool for the process's life
                                if hasattr(model, "discard"):
                                    model.discard(handle, non_tensors)
                                handle = None
                            if isinstance(exc, LaneDeathError) \
                                    and ctx.containment \
                                    and ctx.in_depths is not None:
                                # lane-scale death (chaos
                                # replica_crash/replica_stall), not a
                                # request fault: dead-letter the
                                # in-service dispatch below, then hand
                                # the lane to the eviction drain. On
                                # non-replica steps the error falls
                                # through to classify_error -> FATAL
                                # (a chaos plan aimed at a lane-less
                                # step is a config bug, not a
                                # containable fault).
                                lane_death = exc
                                failed_reason = fault_reason(exc)
                                break
                            kind = classify_error(exc)
                            if kind is FATAL or not ctx.containment:
                                raise  # job-fatal, exactly as before
                            if getattr(in_card, "sub_id", None) \
                                    is not None and not (
                                        kind is TRANSIENT
                                        and attempt < ctx.max_retries):
                                # a forked SEGMENT card: dead-lettering
                                # one segment would strand its siblings
                                # in the aggregator forever and count
                                # the request toward the target once
                                # per segment — segment-parallel steps
                                # stay fail-fast past the retry budget
                                raise
                            if kind is TRANSIENT \
                                    and attempt < ctx.max_retries:
                                attempt += 1
                                if ctx.fault_stats is not None:
                                    ctx.fault_stats.record_retries(1)
                                if summary is not None:
                                    summary.note_retries(1)
                                if ctx.retry_backoff_ms > 0:
                                    # backoff is idle wait, not
                                    # service: pause the placement
                                    # busy clock so the planner's
                                    # busy window keeps matching the
                                    # trace spans (which never see
                                    # the sleep) under chaos runs
                                    if t_busy0 is not None:
                                        stage_busy_s += \
                                            time.monotonic() - t_busy0
                                    time.sleep(
                                        ctx.retry_backoff_ms / 1000.0)
                                    if t_busy0 is not None:
                                        t_busy0 = time.monotonic()
                                continue
                            failed_reason = fault_reason(exc)
                            if kind is TRANSIENT:
                                failed_reason = ("retries-exhausted:"
                                                 + failed_reason)
                            break
                    if t_busy0 is not None:
                        stage_busy_s += time.monotonic() - t_busy0
                    if failed_reason is not None:
                        # permanent failure: dead-letter the request(s)
                        # and keep the stream flowing
                        _contain_failure(ctx, in_card, failed_reason,
                                         summary)
                        if lane_death is not None:
                            # this lane is dead: evict it, drain its
                            # queued work onto healthy siblings, then
                            # exit the hot loop for good (no model
                            # call ever runs here again)
                            _die_lane(ctx, lane_death, summary)
                            break
                        continue
                    if time_card is None:
                        # stage swallowed the item (accumulating batcher
                        # / aggregator) — nothing moves downstream
                        continue
                validate_payload(declared_shapes, tensors_out,
                                 "step %d %s" % (ctx.step_idx,
                                                 ctx.model_class_path))
                if ctx.sync_outputs and tensors_out:
                    t_sync0 = (time.monotonic()
                               if ctx.placement_sink is not None
                               else None)
                    with hostprof.section(sec_device_sync), \
                            trace.span(tr_device_sync):
                        _block_on(tensors_out)
                    if t_sync0 is not None:
                        stage_busy_s += time.monotonic() - t_sync0
                time_card.record("inference%d_finish" % ctx.step_idx)
                if ctx.placement_sink is not None:
                    stage_dispatches += 1
                if ctx.in_hedges is not None \
                        and _hedge_lost(ctx, time_card):
                    # first completion wins: a sibling copy already
                    # resolved this hedged dispatch — discard this
                    # result (service time lands in hedges_wasted_ms,
                    # nothing publishes, nothing double-counts)
                    continue
                if devobs_meter is not None and flushed is None:
                    # per-dispatch achieved-FLOPs feed — AFTER the
                    # hedge-lost discard above, so a loser copy's rows
                    # never inflate the meter (the same reason the
                    # autotune service feed sits past that check):
                    # valid rows are the constituents' num_clips
                    # stamps with coalesced followers counted 0 — the
                    # device-work rule clip_counts applies
                    # (telemetry.TimeCardSummary) — so the Compute:
                    # line cross-foots bench.py's clips_completed-
                    # based MFU exactly. The busy span is
                    # inference_start -> inference_finish (model call
                    # + device sync), the service-time semantics the
                    # autotune estimator uses.
                    cards_dv = _cards_of(time_card)
                    t_fin_dv = cards_dv[0].timings.get(key_inf_finish)
                    if t_fin_dv is not None:
                        # LAST constituent's start, like the autotune
                        # estimator: an accumulating stage's earlier
                        # members carry stale starts whose gap is
                        # batch-fill wait, not device busy time
                        t_sta_dv = max(
                            tc_dv.timings.get(key_inf_start, t_fin_dv)
                            for tc_dv in cards_dv)
                        rows_dv = 0
                        for tc_dv in cards_dv:
                            # coalesced rows share another request's
                            # dispatch and feature-hit rows skipped
                            # the forward entirely — neither ran
                            # FLOPs, so both count 0 (honesty policy:
                            # hits must never inflate MFU)
                            if not getattr(tc_dv, "cache_coalesced",
                                           False) \
                                    and not getattr(tc_dv,
                                                    "feature_hit",
                                                    False):
                                rows_dv += int(getattr(tc_dv,
                                                       "num_clips", 0))
                        devobs_meter.note(rows_dv,
                                          t_fin_dv - t_sta_dv)
                if controller is not None and tensors_out \
                        and flushed is None \
                        and not getattr(model, "AUTOTUNE_SELF_SERVICE",
                                        False):
                    # service-time estimator, per emitted row bucket:
                    # the LAST-swallowed constituent's start -> the
                    # emission finish. Accurate for stages where
                    # swallow and emit happen in the same call (the
                    # Batcher — earlier constituents' spans include
                    # their accumulate hold, which must not read as
                    # service). Stages whose emissions complete
                    # asynchronously (the fusing loader under
                    # transfer_async, where every emission surfaces
                    # via take_ready and `flushed` is never None)
                    # self-report their close->ready span instead and
                    # opt out via AUTOTUNE_SELF_SERVICE.
                    # Arrival-triggered dispatches only: on `flushed`
                    # emissions (idle-tick hold expiry, EOS flush,
                    # async-transfer drains) the last start predates
                    # the dispatch by up to the hold/poll gap, and
                    # feeding that span would inflate the EWMA until
                    # the controller stopped holding at all
                    cards = _cards_of(time_card)
                    t_fin = cards[0].timings.get(key_inf_finish)
                    if t_fin is not None:
                        t_sta = max(tc.timings.get(key_inf_start, t_fin)
                                    for tc in cards)
                        out_pb = tensors_out[0]
                        # ragged emissions always ship the pool shape;
                        # the controller's continuous candidates are
                        # keyed by the VALID rows the dispatch carried
                        rows_key = (out_pb.valid
                                    if isinstance(out_pb, RaggedBatch)
                                    else int(out_pb.data.shape[0]))
                        controller.observe_service(
                            rows_key, max(0.0, t_fin - t_sta))

                out_queue = None
                if ctx.out_queues is not None:
                    if _sheddable_expired(ctx, time_card):
                        # pre-ring-write expiry shed: the computed
                        # output is already too late — drop it before
                        # it occupies a ring slot or downstream queue
                        _shed_deadline(ctx, time_card,
                                       "step%d_publish" % ctx.step_idx,
                                       summary)
                        continue
                    # route BEFORE the ring publish so a shed decision
                    # can drop the item while no ring slot holds it (a
                    # written-but-never-signalled slot would deadlock
                    # the producer on the next wrap-around)
                    with hostprof.section(sec_enqueue):
                        out_idx = selector.select(tensors_out,
                                                  non_tensors_out,
                                                  time_card)
                    out_queue = ctx.out_queues[out_idx]
                    # forked segment cards are never shed (dropping one
                    # segment would strand its siblings in the
                    # aggregator and double-count the request): they
                    # fall through to the blocking-put backpressure path
                    if (ctx.overload_policy == "shed"
                            and out_queue.maxsize > 0
                            and getattr(time_card, "sub_id", None) is None
                            and out_queue.qsize() + ctx.num_segments
                            > out_queue.maxsize):
                        # on a replica-expanded edge the shed site is
                        # per-LANE: which lane's queue filled up is
                        # the signal (satellite of the health layer)
                        _shed_item(ctx, time_card, summary,
                                   lane=(ctx.out_queue_indices[out_idx]
                                         if ctx.out_depths is not None
                                         else None))
                        continue

                if ctx.output_ring is not None:
                    with hostprof.section(sec_ring_publish), \
                            trace.span(tr_publish):
                        segments = split_segments(tensors_out,
                                                  ctx.num_segments)
                        for seg_idx, seg_payload in enumerate(segments):
                            slot_idx = (ring_counter + seg_idx) \
                                % len(ctx.output_ring)
                            if not ctx.output_ring.wait_free(
                                    slot_idx, ctx.termination):
                                break
                            ctx.output_ring.slots[slot_idx].write(
                                seg_payload)
                    if ctx.termination.terminated:
                        break

                if ctx.out_queues is None:
                    # final step: count completions, detect the target.
                    # Register BEFORE any target-reached break: a
                    # completion added to the counter must appear in some
                    # timing table even when a sibling instance raised
                    # the flag while this one was mid-inference — the
                    # reference registered every completed record
                    # (reference runner.py:176-202)
                    with hostprof.section(sec_bookkeeping):
                        n = len(time_card) if isinstance(time_card,
                                                         TimeCardList) \
                            else 1
                        old, new = ctx.counter.add(n)
                        if progress_bar is not None \
                                and new > old_counter_value:
                            progress_bar.update(new - old_counter_value)
                            old_counter_value = new
                        cards = time_card.time_cards if isinstance(
                            time_card, TimeCardList) else [time_card]
                        for tc in cards:
                            summary.register(tc)
                        # live SLO feed (rnb_tpu.metrics): the same
                        # completions the summary registers stream
                        # into the windowed goodput/burn gauges (one
                        # None test when metrics are off)
                        metrics.completions(cards)
                    if new >= ctx.num_videos:
                        if old < ctx.num_videos:
                            ctx.termination.raise_flag(
                                TerminationFlag.TARGET_NUM_VIDEOS_REACHED)
                        else:
                            break  # someone else already hit the target
                else:
                    try:
                        with hostprof.section(sec_enqueue), \
                                trace.span(tr_publish):
                            for seg_idx in range(ctx.num_segments):
                                forked = time_card.fork(seg_idx) \
                                    if ctx.num_segments > 1 else time_card
                                if ctx.output_ring is not None:
                                    sig = Signal(ctx.group_idx,
                                                 ctx.instance_idx,
                                                 ring_counter)
                                    ring_counter = (ring_counter + 1) \
                                        % len(ctx.output_ring)
                                else:
                                    sig = None
                                item = (sig, non_tensors_out, forked)
                                if ctx.out_hedges is not None:
                                    # snapshot the hedge template
                                    # BEFORE the put: the card clone
                                    # must never race the consumer's
                                    # stamps, and the payload refs
                                    # (immutable arrays) outlive the
                                    # ring slot's reuse
                                    ctx.out_hedges.track(
                                        forked,
                                        ctx.out_queue_indices[out_idx],
                                        tensors_out, non_tensors_out)
                                enqueued = False
                                if ctx.overload_policy == "shed":
                                    # capacity raced away since the
                                    # pre-check (competing producer):
                                    # the ring slot is already written,
                                    # so block with termination polling
                                    # — bounded backpressure, not abort
                                    while not ctx.termination.terminated:
                                        try:
                                            out_queue.put(
                                                item,
                                                timeout=QUEUE_POLL_S)
                                            enqueued = True
                                            break
                                        except queue.Full:
                                            continue
                                else:
                                    out_queue.put_nowait(item)
                                    enqueued = True
                                if enqueued \
                                        and ctx.out_depths is not None:
                                    # open the item's in-flight window
                                    # on its chosen replica lane
                                    ctx.out_depths.inc(
                                        ctx.out_queue_indices[out_idx])
                                    if ctx.out_health_board is not None:
                                        ctx.out_health_board \
                                            .note_enqueue(
                                                ctx.out_queue_indices[
                                                    out_idx])
                    except queue.Full:
                        # counted telemetry, not a stray stdout line:
                        # the per-edge overflow count lands in
                        # BenchmarkResult.queue_overflows and the
                        # log-meta 'Queue overflows:' line; the
                        # termination flag still says the job aborted
                        if ctx.fault_stats is not None:
                            ctx.fault_stats.record_overflow(
                                "step%d->step%d"
                                % (ctx.step_idx, ctx.step_idx + 1))
                        ctx.termination.raise_flag(
                            TerminationFlag.FRAME_QUEUE_FULL)
                        break
                # a flushed item does NOT end the loop: the stage may
                # hold more (fusing loaders flush one batch per call);
                # the loop re-enters the drain branch until flush()
                # returns None
            # the final flush may have contained failures (or parked
            # deadline sheds) after the last loop-top drain ran
            _drain_stage_failures(ctx, take_failed, take_retries,
                                  summary)
            if take_shed is not None:
                for tc_shed, where in take_shed():
                    _shed_deadline(ctx, tc_shed,
                                   "step%d_%s" % (ctx.step_idx, where),
                                   summary)
    except Exception:
        traceback.print_exc()
        ctx.termination.raise_flag(TerminationFlag.INTERNAL_ERROR)
    finally:
        # abort/drain path: retire any prefetched decodes whose results
        # will never be used so native-pool tickets don't pin buffers
        if pending and hasattr(model, "discard"):
            for handle, nt, _tc in pending:
                model.discard(handle, nt)
        pending.clear()
        # same for a stage-internal accumulator (fusing loader): its
        # submitted decodes must be retired or the shared pool pins
        # their buffers for the process's life
        if model is not None and hasattr(model, "discard_pending"):
            try:
                model.discard_pending()
            except Exception:
                traceback.print_exc()
        # hedged edges: keep the governor ticking until every
        # outstanding downstream dispatch settled — hedges fired after
        # this producer's exit markers would strand behind them
        if ctx.out_hedges is not None:
            try:
                _linger_for_hedges(ctx)
            except Exception:
                traceback.print_exc()
        # drain: the LAST producer on each edge marks end-of-stream, so
        # markers can never overtake a slower sibling replica's real
        # items (improves on reference runner.py:238-245 which let any
        # replica enqueue markers immediately)
        if ctx.out_queues is not None:
            for q_idx, out_queue in enumerate(ctx.out_queues):
                tracker = (ctx.out_trackers[q_idx]
                           if ctx.out_trackers is not None else None)
                if tracker is None or tracker.producer_finished():
                    markers = (tracker.num_markers if tracker is not None
                               else NUM_EXIT_MARKERS)
                    send_exit_markers(out_queue, markers, ctx.termination)
        # on abort only: wake any upstream producer blocked on our input
        # rings (reference runner.py:247-253). On a clean end-of-stream
        # drain every upstream producer has already finished (markers
        # come only after the last one), and a sibling replica may still
        # hold an unread Signal — releasing here would clear its slot
        # under it.
        if ctx.input_rings is not None and ctx.termination.terminated:
            for rings in ctx.input_rings.values():
                for ring in rings:
                    if ring is not None:
                        ring.release_all()
        # async stages (mesh runner) drain outstanding device work
        # BEFORE the finish barrier so the measured window covers every
        # dispatched inference (the analog of the reference's final
        # stream.synchronize discipline)
        if model is not None and hasattr(model, "finalize"):
            try:
                model.finalize()
            except Exception:
                traceback.print_exc()
        # cache-owning stages report their final counters before the
        # finish barrier (all stage work is done by here), so the
        # controller's aggregation never races a live counter
        if (ctx.cache_sink is not None
                and getattr(model, "cache", None) is not None):
            try:
                ctx.cache_sink.append(model.cache.snapshot())
            except Exception:
                traceback.print_exc()
        # staging-owning stages likewise report their final pool
        # counters (discard_pending above already stopped any transfer
        # worker, so the snapshot is stable)
        if (ctx.staging_sink is not None
                and getattr(model, "staging", None) is not None):
            try:
                ctx.staging_sink.append(model.staging.snapshot())
            except Exception:
                traceback.print_exc()
        # controller-owning stages report their final decision counters
        # the same way (the stage is drained; counters are stable)
        if ctx.autotune_sink is not None and controller is not None:
            try:
                ctx.autotune_sink.append(controller.snapshot())
            except Exception:
                traceback.print_exc()
        # compile/warmup accounting: every stage reports construction
        # time; jit-owning stages add their signature snapshot
        if ctx.compile_sink is not None and model is not None:
            try:
                tracker = getattr(model, "compiles", None)
                ctx.compile_sink.append(
                    (ctx.step_idx, warmup_s,
                     tracker.snapshot() if tracker is not None
                     else None))
            except Exception:
                traceback.print_exc()
        # padding-waste counters (bucketed) / ragged pool counters
        if (ctx.pad_sink is not None
                and getattr(model, "padding", None) is not None):
            try:
                ctx.pad_sink.append(model.padding.snapshot())
            except Exception:
                traceback.print_exc()
        if (ctx.ragged_sink is not None
                and getattr(model, "ragged_stats", None) is not None):
            try:
                ctx.ragged_sink.append(dict(model.ragged_stats))
            except Exception:
                traceback.print_exc()
        # intra-stage shard accounting (rnb_tpu.parallel.shardplan):
        # stages with a declared `shard` key report degree, projected
        # footprint and the host-timed collective tax
        if (ctx.shard_sink is not None
                and getattr(model, "shard_stats", None) is not None):
            try:
                ctx.shard_sink.append((ctx.step_idx,
                                       dict(model.shard_stats)))
            except Exception:
                traceback.print_exc()
        # replica-lane settlement for an item still in service when
        # the loop exited (abort / target-reached break); the hedge
        # governor needs no twin here — claim() settles on every
        # resolution path, and unresolved abort-path dispatches are
        # released by the producer's termination-gated linger
        if depth_owed:
            if ctx.in_depths is not None:
                ctx.in_depths.dec(ctx.in_queue_idx, depth_owed)
            if ctx.health_board is not None:
                ctx.health_board.note_settle(ctx.in_queue_idx,
                                             depth_owed)
            depth_owed = 0
        # device-resident handoff accounting (rnb_tpu.handoff): the
        # stage is drained, counters are stable
        if ctx.handoff_sink is not None and handoff is not None:
            try:
                ctx.handoff_sink.append(handoff.snapshot())
            except Exception:
                traceback.print_exc()
        # measured dispatch costs for the placement planner
        # (rnb_tpu.placement) — every executor reports, planner-on runs
        # only (the sink gates it)
        if ctx.placement_sink is not None and model is not None:
            try:
                sstats = getattr(model, "shard_stats", None)
                if sstats is not None:
                    # sharded steps carry their degree, the host-timed
                    # collective slice and the feasibility floor so
                    # the planner's joint (replicas x degree) model
                    # calibrates from measurement, never assumption
                    ctx.placement_sink.append(
                        CostRecord(ctx.step_idx, stage_busy_s,
                                   stage_dispatches,
                                   shard_degree=int(sstats["degree"]),
                                   collective_s=float(
                                       sstats["collective_ms"]) / 1e3,
                                   min_degree=max(
                                       1, int(sstats["min_degree"]))))
                else:
                    ctx.placement_sink.append(
                        CostRecord(ctx.step_idx, stage_busy_s,
                                   stage_dispatches))
            except Exception:
                traceback.print_exc()
        try:
            ctx.fin_bar.wait()
        except threading.BrokenBarrierError:
            pass

        if summary is not None:
            if ctx.summary_sink is not None:
                ctx.summary_sink.append(summary)
            with open(logname(ctx.job_id, ctx.device.label, ctx.group_idx,
                              ctx.instance_idx, base=ctx.log_base),
                      "w") as f:
                summary.save_full_report(f)
            if ctx.print_progress:
                summary.print_summary(NUM_SUMMARY_SKIPS)
                if progress_bar is not None:
                    progress_bar.close()
