"""Dynamic batching stage.

Accumulates ``batch`` incoming requests and fuses them into one larger
batch so a downstream network stage amortizes its launch/compile cost —
the "Batch" half of Replicate & Batch. While accumulating, the stage
returns a None time_card, which tells the executor to propagate nothing
downstream (reference batcher.py:17-34, runner.py:130-134).

The fused output is one PaddedBatch holding the concatenated *valid*
rows of the constituents, re-padded to the stage's max shape, plus a
TimeCardList so one fused inference still stamps every constituent
request's card.
"""

from __future__ import annotations

import numpy as np

from rnb_tpu.stage import PaddedBatch, StageModel
from rnb_tpu.telemetry import TimeCardList

MAX_ROWS = 15  # max clips per fused batch, matches the loader's max


class Batcher(StageModel):
    """Accumulate `batch` requests, then emit one fused PaddedBatch."""

    def __init__(self, device, batch=1, shapes=None, **kwargs):
        super().__init__(device)
        del shapes  # consumed by output_shape_for; payloads carry shape
        self.batch = int(batch)
        self._tensors = []      # list of tuples of PaddedBatch
        self._time_cards = []

    def input_shape(self):
        # NDHWC, the layout every payload in this framework flows
        # (loader: models/r2p1d/model.py R2P1DLoader._batch_shape)
        return ((MAX_ROWS, 8, 112, 112, 3),)

    @staticmethod
    def output_shape():
        return ((MAX_ROWS, 8, 112, 112, 3),)

    @classmethod
    def output_shape_for(cls, shapes=None, max_rows: int = MAX_ROWS,
                         consecutive_frames: int = 8,
                         frame_hw: int = 112, **_kwargs):
        # the batcher is payload-agnostic — it re-packs whatever its
        # upstream emits — so non-flagship topologies declare the wire
        # shapes explicitly via a `shapes` config key
        if shapes:
            return tuple(tuple(int(d) for d in s) for s in shapes)
        return ((int(max_rows), int(consecutive_frames),
                 frame_hw, frame_hw, 3),)

    def __call__(self, tensors, non_tensors, time_card):
        if self.batch <= 1:
            return tensors, non_tensors, time_card

        # Validate before mutating state so an oversized request leaves the
        # accumulator intact and the stage recoverable.
        for pos, pb in enumerate(tensors):
            pending = sum(parts[pos].valid for parts in self._tensors)
            if pending + pb.valid > pb.max_rows:
                raise ValueError(
                    "fusing this request would reach %d rows, exceeding the "
                    "max shape %d; lower the `batch` config or raise the "
                    "stage max shape"
                    % (pending + pb.valid, pb.max_rows))

        self._tensors.append(tensors)
        self._time_cards.append(time_card)
        if len(self._time_cards) < self.batch:
            return None, None, None

        fused = []
        for parts in zip(*self._tensors):
            rows = np.concatenate(
                [np.asarray(pb.data)[: pb.valid] for pb in parts], axis=0)
            fused.append(PaddedBatch.from_rows(rows, parts[0].max_rows))

        cards = TimeCardList(self._time_cards)
        self._tensors = []
        self._time_cards = []
        # Per-request metadata cannot be attributed to a fused batch; emit
        # None rather than one arbitrary constituent's non_tensors
        # (reference batcher.py:34 does the same).
        return tuple(fused), None, cards
