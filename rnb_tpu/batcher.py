"""Dynamic batching stage.

Accumulates ``batch`` incoming requests and fuses them into one larger
batch so a downstream network stage amortizes its launch/compile cost —
the "Batch" half of Replicate & Batch. While accumulating, the stage
returns a None time_card, which tells the executor to propagate nothing
downstream (reference batcher.py:17-34, runner.py:130-134).

The fused output is one PaddedBatch holding the concatenated *valid*
rows of the constituents, re-padded to the stage's max shape, plus a
TimeCardList so one fused inference still stamps every constituent
request's card.
"""

from __future__ import annotations

import time

import numpy as np

from rnb_tpu import trace
from rnb_tpu.autotune import BatchController
from rnb_tpu.health import cards_of as _cards_of
from rnb_tpu.health import expired as _deadline_expired
from rnb_tpu.ops.ragged import resolve_pool_rows, segment_offsets_of
from rnb_tpu.stage import (PadCounter, PaddedBatch, RaggedBatch,
                           StageModel, normalize_row_buckets,
                           note_emission_accounting)
from rnb_tpu.telemetry import TimeCardList
from rnb_tpu.utils.lazy_jax import jax_numpy as _jax_numpy

MAX_ROWS = 15  # max clips per fused batch, matches the loader's max


class Batcher(StageModel):
    """Accumulate `batch` requests, then emit one fused PaddedBatch.

    ``row_buckets`` (optional) pads the fused batch to the smallest
    bucket holding its valid rows instead of all the way to the ring's
    max shape — e.g. 6 fused 1-clip videos dispatch as a 6-row batch,
    not a 15-row one — so the downstream network stage (warmed on the
    same buckets) spends MXU cycles on mostly-valid rows. ``flush()``
    emits any partial batch at end-of-stream so the last
    ``num_videos mod batch`` requests still complete (the reference's
    batcher simply stranded them, reference batcher.py:17-34).
    """

    # any upstream bucket set is acceptable: the batcher concatenates
    # valid rows and re-pads to its OWN bucket set / max shape
    REPACKS_ROWS = True

    #: the accumulate/emit decision and the pad bucket can be driven
    #: by the load-adaptive controller (rnb_tpu.autotune): under
    #: autotune the static `batch` count becomes a ceiling and the
    #: controller emits as soon as growing the window cannot meet the
    #: latency budget — with a hold deadline, which the static batcher
    #: never had (it waited for `batch` arrivals or end-of-stream)
    SUPPORTS_AUTOTUNE = True

    #: fused emissions can ship as a flat row pool at ONE shape with a
    #: rows_valid count + per-request segment offsets instead of
    #: padding to a bucket (root 'ragged' config key)
    SUPPORTS_RAGGED = True

    def __init__(self, device, batch=1, shapes=None, max_rows=MAX_ROWS,
                 consecutive_frames=8, frame_hw=112, row_buckets=None,
                 ragged=False, ragged_pool_rows=None,
                 **kwargs):
        super().__init__(device)
        self.batch = int(batch)
        # the fuse capacity comes from this stage's DECLARED output
        # shape, not from incoming payloads: under upstream row
        # bucketing an incoming batch's max_rows is its (small) bucket,
        # while the fused batch may legally grow to the ring shape
        self._declared_shapes = self.output_shape_for(
            shapes=shapes, max_rows=max_rows,
            consecutive_frames=consecutive_frames, frame_hw=frame_hw)
        self._declared_max = [int(s[0]) for s in self._declared_shapes]
        # same validation as the loader's bucketing: typo'd buckets
        # fail fast instead of silently padding to un-warmed shapes
        self.row_buckets = (normalize_row_buckets(
            row_buckets, self._declared_max[0], "stage max rows")
            if row_buckets else None)
        # ragged row-pool dispatch (rnb_tpu.ops.ragged): emissions ship
        # the full declared shape (the pool) with a rows_valid count +
        # segment offsets; row_buckets, if configured, become the
        # COUNTERFACTUAL pad rule the pad_rows_eliminated counter is
        # measured against, never a shipped shape
        self.ragged = bool(ragged)
        self.pool_rows = (resolve_pool_rows(
            ragged_pool_rows, self._declared_max[0], "stage max rows")
            if self.ragged else None)
        #: padding-waste accounting (always on; 0-pad under ragged)
        self.padding = PadCounter()
        #: ragged accounting, drained via the executor's ragged sink
        self.ragged_stats = ({"pool_rows": self.pool_rows,
                              "emissions": 0, "rows": 0,
                              "pad_rows_eliminated": 0,
                              "cache_hit_rows": 0}
                             if self.ragged else None)
        self._tensors = []      # list of tuples of PaddedBatch
        self._time_cards = []
        #: load-adaptive batching controller (rnb_tpu.autotune), set
        #: by the executor via enable_autotune(); None = static
        #: accumulate-to-`batch` semantics exactly as configured
        self.autotune = None
        #: deadline-expired requests dropped from the accumulator at
        #: emission time (rnb_tpu.health), parked for the executor's
        #: take_shed() drain — inert unless requests carry deadlines
        self._shed = []
        #: monotonic instant the oldest pending request joined the
        #: accumulator (None when empty) — the hold-deadline anchor
        self._t_oldest = None

    def enable_autotune(self, settings) -> BatchController:
        """Executor protocol (rnb_tpu.runner): drive this stage's
        accumulate/emit decision and pad bucket with a BatchController
        over the stage's own warmed bucket set — decisions can only
        name shapes the downstream stage warmed. Under ragged dispatch
        every row count is one dispatch of the same executable, so the
        candidate set is continuous (1..pool_rows) and decisions stop
        being bucket-quantized."""
        if self.ragged:
            self.autotune = BatchController.for_stage(
                settings, tuple(range(1, self.pool_rows + 1)),
                self.pool_rows)
            return self.autotune
        self.autotune = BatchController.for_stage(
            settings, self.row_buckets or (self._declared_max[0],),
            self._declared_max[0])
        return self.autotune

    def input_shape(self):
        # the batcher re-packs whatever it receives, so its input max
        # shapes ARE its declared output shapes — derived from the
        # constructor's shapes/max_rows/consecutive_frames/frame_hw,
        # never the flagship globals (a non-default topology's
        # declared-vs-actual payload validation depends on this)
        return self._declared_shapes

    @staticmethod
    def output_shape():
        return ((MAX_ROWS, 8, 112, 112, 3),)

    @classmethod
    def output_shape_for(cls, shapes=None, max_rows: int = MAX_ROWS,
                         consecutive_frames: int = 8,
                         frame_hw: int = 112, **_kwargs):
        # the batcher is payload-agnostic — it re-packs whatever its
        # upstream emits — so non-flagship topologies declare the wire
        # shapes explicitly via a `shapes` config key
        if shapes:
            return tuple(tuple(int(d) for d in s) for s in shapes)
        return ((int(max_rows), int(consecutive_frames),
                 frame_hw, frame_hw, 3),)

    @classmethod
    def input_shape_for(cls, **model_kwargs):
        # static counterpart of input_shape(): the batcher re-packs
        # whatever it receives, so its input max shapes ARE its
        # declared output shapes (same constructor-args derivation)
        return cls.output_shape_for(**model_kwargs)

    def __call__(self, tensors, non_tensors, time_card):
        if self.batch <= 1:
            return tensors, non_tensors, time_card

        # A single request bigger than the fuse capacity can never be
        # emitted — that is a topology error, fail fast and leave the
        # accumulator intact.
        for pos, pb in enumerate(tensors):
            if pb.valid > self._declared_max[pos]:
                raise ValueError(
                    "request carries %d rows, exceeding the stage max "
                    "shape %d; raise the stage max shape"
                    % (pb.valid, self._declared_max[pos]))

        # A request that no longer FITS with the pending ones closes
        # the window early: emit what is pending and start the next
        # batch with this request. Load-dependent early emission is
        # ordinary dynamic-batching behavior — aborting the run here
        # would let one mid-sized video kill the benchmark.
        early = None
        if self._tensors and any(
                sum(parts[pos].valid for parts in self._tensors)
                + pb.valid > self._declared_max[pos]
                for pos, pb in enumerate(tensors)):
            early = self._emit_fused()

        self._tensors.append(tensors)
        self._time_cards.append(time_card)
        if self._t_oldest is None:
            self._t_oldest = time.monotonic()
        if self.autotune is not None:
            # rows per CLIENT request, not per upstream emission: a
            # fused upstream delivers many requests' rows in one call,
            # and the runner feeds the inter-arrival EWMA per
            # constituent card — mixing per-emission rows with
            # per-request gaps would understate residual-fill time by
            # the upstream fuse factor and hold when growth cannot
            # meet the budget
            n_req = len(getattr(time_card, "time_cards", None) or (1,))
            self.autotune.observe_rows(tensors[0].valid / n_req)
        if early is not None:
            return early
        if len(self._time_cards) >= self.batch:
            # the static fuse count stays a hard ceiling under autotune
            return self._emit_fused()
        if self.autotune is not None:
            # controller-driven early emission: dispatch now when
            # growing the window cannot meet the latency budget (the
            # static batcher would wait for `batch` arrivals — at low
            # rate that wait is unbounded until end-of-stream)
            rows, waited, dec = self._decide()
            if rows >= dec.target_rows or waited >= dec.hold_s:
                return self._emit_fused()
        return None, None, None

    def _decide(self, peek=False):
        """``(rows_ready, oldest_wait_s, Decision)`` for the current
        accumulator state — the single place the controller's inputs
        are derived, so the emit check (__call__/poll) and the
        deadline the executor polls on (next_deadline_s) can never
        diverge. ``peek`` skips the controller's decision accounting
        (deadline queries happen every executor poll tick)."""
        rows = sum(parts[0].valid for parts in self._tensors)
        waited = time.monotonic() - self._t_oldest
        ask = self.autotune.peek if peek else self.autotune.decide
        return rows, waited, ask(len(self._time_cards), rows, waited)

    def next_deadline_s(self):
        """Seconds until the controller's hold deadline for the oldest
        pending request, or None when nothing is held (or autotune is
        off — the static batcher has no deadline: it waits for
        arrivals). The executor shrinks its queue-poll timeout to
        this (rnb_tpu.runner.poll_plan)."""
        if self.autotune is None or self._t_oldest is None:
            return None
        _, waited, dec = self._decide(peek=True)
        return max(0.0, dec.hold_s - waited)

    def poll(self):
        """Idle tick from the executor (no arrival within its queue
        poll window): emit the held partial batch once its controller
        hold deadline expired. Without this, a held batch could only
        emit on the NEXT arrival — exactly the unbounded low-rate wait
        autotune exists to remove. Static mode (autotune off) keeps
        the accumulate-to-`batch` semantics: always None."""
        if self.autotune is None or self._t_oldest is None:
            return None
        rows, waited, dec = self._decide()
        if rows >= dec.target_rows or waited >= dec.hold_s:
            return self._emit_fused()
        return None

    def _bucket_for(self, rows: int, max_rows: int) -> int:
        if self.autotune is not None:
            # restrict the pad bucket to the controller's candidate
            # set (warmed buckets, optionally narrowed by
            # autotune.buckets) so emissions land on the shapes the
            # decisions reason about; rows exceeding every candidate
            # fall back to the static rule (never pad short)
            bucket = self.autotune.bucket_for(rows)
            if rows <= bucket <= max_rows:
                return bucket
        if self.row_buckets:
            for bucket in self.row_buckets:
                if rows <= bucket <= max_rows:
                    return bucket
        return max_rows

    def _counterfactual_bucket(self, rows: int) -> int:
        """The rows the bucketed pad rule WOULD have shipped for this
        emission — what pad_rows_eliminated is measured against under
        ragged (max-shape padding when no row_buckets are named)."""
        if self.row_buckets:
            for bucket in self.row_buckets:
                if rows <= bucket:
                    return bucket
        return self._declared_max[0]

    def take_shed(self):
        """Executor hook (rnb_tpu.runner): requests this stage shed
        internally because their deadline expired while the batch
        accumulated -> [(card, where)] (drained each loop top)."""
        out, self._shed = self._shed, []
        return out

    def _drop_expired(self) -> None:
        """The 'Batcher emit' deadline boundary (rnb_tpu.health): a
        request whose absolute deadline passed while it waited in the
        accumulator is dropped BEFORE fusing — its rows never pad a
        dispatch, never burn downstream service. Inert when no card
        carries a deadline stamp."""
        if not any(getattr(tc, "deadline_s", None) is not None
                   for item in self._time_cards
                   for tc in _cards_of(item)):
            # no constituent card anywhere carries a deadline (the
            # unwrap matters: an upstream fusing loader delivers
            # TimeCardLists whose deadline stamps live on the
            # constituents, not the wrapper)
            return
        live_tensors, live_cards = [], []
        for tensors, card in zip(self._tensors, self._time_cards):
            # forked segment cards are never shed — same rule as every
            # other shed boundary (runner take/publish): dropping one
            # segment would strand its aggregator siblings forever and
            # count the request toward the target a second time
            forked = any(getattr(tc, "sub_id", None) is not None
                         for tc in _cards_of(card))
            if not forked and _deadline_expired(card):
                self._shed.append((card, "hold"))
            else:
                live_tensors.append(tensors)
                live_cards.append(card)
        self._tensors = live_tensors
        self._time_cards = live_cards

    def _emit_fused(self):
        self._drop_expired()
        if not self._time_cards:
            # every pending request expired: nothing to emit — the
            # executor's take_shed() drain disposes the parked cards
            self._tensors = []
            self._t_oldest = None
            return None, None, None
        if trace.ACTIVE is not None:
            # timeline marker per fused dispatch (args allocated only
            # while tracing): how many requests/rows this batch fused
            trace.instant("batcher.emit", args={
                "requests": len(self._time_cards),
                "rows": sum(parts[0].valid for parts in self._tensors)})
        fused = []
        for pos, parts in enumerate(zip(*self._tensors)):
            valid = sum(pb.valid for pb in parts)
            if self.ragged:
                # one compiled shape: the pool is the declared max;
                # the segment table partitions the valid rows per
                # constituent request
                bucket = self._declared_max[pos]
            else:
                bucket = self._bucket_for(valid, self._declared_max[pos])
            if pos == 0 and self.autotune is not None:
                self.autotune.note_emission(valid if self.ragged
                                            else bucket)
            if pos == 0:
                # the shared padding/ragged accounting rule
                # (rnb_tpu.stage.note_emission_accounting): pad count
                # stamped on the first constituent card; under ragged
                # the counterfactual bucket feeds pad_rows_eliminated
                note_emission_accounting(
                    self.padding, self.ragged_stats, self._time_cards,
                    valid, bucket,
                    self._counterfactual_bucket(valid) if self.ragged
                    else 0)
            pb = self._fuse_parts(parts, valid, bucket)
            if self.ragged and pos == 0:
                pb = RaggedBatch(pb.data, valid, segment_offsets_of(
                    part.valid for part in parts))
            fused.append(pb)

        cards = TimeCardList(self._time_cards)
        self._tensors = []
        self._time_cards = []
        self._t_oldest = None
        # Per-request metadata cannot be attributed to a fused batch; emit
        # None rather than one arbitrary constituent's non_tensors
        # (reference batcher.py:34 does the same).
        return tuple(fused), None, cards

    @staticmethod
    def _fuse_parts(parts, valid: int, bucket: int) -> PaddedBatch:
        """Concatenate the valid rows of ``parts`` padded to ``bucket``.

        Device arrays fuse ON DEVICE (lazy jnp slice+concat): the fused
        batch never round-trips through the host, which matters doubly
        on TPU — device_put/asarray bounces would serialize on transfer
        latency, and the async concat lets the executor thread move on.
        Host numpy payloads keep the numpy path.
        """
        jax, jnp = _jax_numpy()

        # "fusable on device" = identical placement: the seed rule
        # (every part on the SAME single device) OR — under the
        # device-resident edge contract (rnb_tpu.handoff), where
        # payloads may arrive mesh-sharded — equal shardings. Both
        # alternatives are needed: a NamedSharding over a 1-device
        # mesh and a SingleDeviceSharding on that device compare
        # unequal as objects yet fuse on device identically, and
        # falling to the host-numpy path for them would be the host
        # bounce the handoff exists to delete.
        all_jax = all(isinstance(pb.data, jax.Array) for pb in parts)
        same_placement = all_jax and (
            len({d for pb in parts for d in pb.data.devices()}) == 1
            or len({pb.data.sharding for pb in parts}) == 1)
        if same_placement:
            segments = [pb.data[: pb.valid] for pb in parts]
            pad = bucket - valid
            if pad > 0:
                segments.append(jnp.zeros(
                    (pad,) + tuple(parts[0].data.shape[1:]),
                    parts[0].data.dtype))
            return PaddedBatch(jnp.concatenate(segments, axis=0), valid)
        rows = np.concatenate(
            [np.asarray(pb.data)[: pb.valid] for pb in parts], axis=0)
        return PaddedBatch.from_rows(rows, bucket)

    def flush(self):
        """End-of-stream hook (called by the executor on the exit
        marker): emit whatever partial batch is pending, or None."""
        if not self._time_cards:
            return None
        return self._emit_fused()
