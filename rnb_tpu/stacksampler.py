"""Continuous wall-clock stack sampling over the pipeline threads.

hostprof answers "how much wall time did section X cost" — but only
for the sections somebody instrumented, and only as end-of-run sums.
This module is the always-on complement: a low-rate background sampler
over ``sys._current_frames()`` that records *where each named pipeline
thread actually is* at every tick, with zero per-sample cooperation
from the sampled code. Three surfaces come out of one sample stream:

* ``logs/<job>/stacks.folded`` — the classic flamegraph-folded format
  (``role;frame;frame;...;leaf count`` per line), loadable untouched
  by any FlameGraph/speedscope-style viewer;
* sampler tracks merged into ``trace.json`` — one ``stacks:<role>``
  track per thread role whose tiles are the role's *top frame* at each
  tick, so the Perfetto timeline shows what the host was executing in
  the gaps between instrumented spans;
* a ``Stacks:`` log-meta counter line (ticks, roles, distinct folded
  stacks, total per-thread samples) whose folded-stack counts
  ``parse_utils --check`` re-sums from the artifact, and whose tick
  count it holds to ``sample_hz x measured wall`` within tolerance.

Gating: the sampler rides the root ``operator`` config key
(``operator.sample_hz``; 0 disables it) — see :mod:`rnb_tpu.statusz`.
With the key absent nothing starts and no artifact or meta line is
written (byte-stable logs). Overhead: one ``sys._current_frames()``
call per tick walks every thread's frame chain under the GIL; at the
default 25 Hz over the handful of pipeline threads this is well under
1% of one core (the README "Operator plane" section carries the
expectation).
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

#: default sampling rate (Hz) — low enough to be invisible next to the
#: pipeline's own work, high enough that a few-second run still yields
#: hundreds of samples per thread
DEFAULT_SAMPLE_HZ = 25.0

#: thread-name prefixes that count as pipeline roles; everything else
#: (the controller MainThread, the samplers/flushers themselves,
#: jax-internal pools) is deliberately not sampled — the signal is
#: "where is the *pipeline* spending host time"
ROLE_PREFIXES = ("client", "runner-", "rnb-decode", "rnb-transfer")

#: frame-walk depth cap: a pathological recursion must cost bounded
#: work per tick, never a runaway folded key
MAX_STACK_DEPTH = 64

#: cap on per-sample timeline events kept for the trace merge (the
#: folded aggregation is unbounded-safe on its own: distinct stacks,
#: not samples); beyond the cap samples still fold, only the timeline
#: tiles stop growing
MAX_TRACE_SAMPLES = 100000


def role_of(thread_name: str) -> Optional[str]:
    """The sampled role of one thread name, or None when the thread is
    not a pipeline role. Pool workers collapse onto their pool's role
    (``rnb-decode_3`` -> ``rnb-decode``) so the aggregation reads as
    "the decode pool", not N anonymous lanes."""
    for prefix in ROLE_PREFIXES:
        if thread_name.startswith(prefix):
            if prefix in ("rnb-decode", "rnb-transfer"):
                return prefix
            return thread_name
    return None


def _frame_label(frame) -> str:
    """``file:function`` for one frame, semicolon/space-free so the
    folded format stays parseable."""
    code = frame.f_code
    base = os.path.basename(code.co_filename)
    if base.endswith(".py"):
        base = base[:-3]
    label = "%s:%s" % (base, code.co_name)
    return label.replace(";", "_").replace(" ", "_")


def walk_stack(frame) -> Tuple[str, ...]:
    """Root-first frame labels of one thread's live stack (the folded
    orientation: caller;...;leaf), depth-capped."""
    labels: List[str] = []
    while frame is not None and len(labels) < MAX_STACK_DEPTH:
        labels.append(_frame_label(frame))
        frame = frame.f_back
    labels.reverse()
    return tuple(labels)


class StackSampler:
    """Bounded, thread-safe wall-clock sampler.

    The real feed is ``sys._current_frames()`` + ``threading
    .enumerate()``; tests drive :meth:`record` directly with synthetic
    stacks (the folded math is pure aggregation over (role, stack)
    pairs), or inject ``frames_fn``/``names_fn``.
    """

    GUARDED_BY = {
        "_folded": "_lock",
        "_roles": "_lock",
        "_timeline": "_lock",
        "samples": "_lock",
        "timeline_dropped": "_lock",
    }

    UNGUARDED_OK = {
        "_thread": "controller-thread lifecycle (start/stop)",
    }

    def __init__(self, sample_hz: float = DEFAULT_SAMPLE_HZ,
                 frames_fn: Optional[Callable[[], Dict]] = None,
                 names_fn: Optional[Callable[[], Dict[int, str]]] = None):
        self.sample_hz = float(sample_hz)
        self._frames_fn = frames_fn or sys._current_frames
        self._names_fn = names_fn or self._live_thread_names
        self._lock = threading.Lock()
        #: (role, stack_tuple) -> sample count (the folded artifact)
        self._folded: Dict[Tuple, int] = {}
        #: distinct roles ever sampled
        self._roles: set = set()
        #: sampling ticks executed (the samples ~ hz x wall invariant)
        self.samples = 0
        #: per-sample (t_epoch_s, role, leaf_label) timeline tiles for
        #: the trace merge, bounded by MAX_TRACE_SAMPLES
        self._timeline: List[Tuple[float, str, str]] = []
        self.timeline_dropped = 0
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    @staticmethod
    def _live_thread_names() -> Dict[int, str]:
        return {t.ident: t.name for t in threading.enumerate()
                if t.ident is not None}

    # -- collection ---------------------------------------------------

    def record(self, role: str, stack: Tuple[str, ...],
               now: Optional[float] = None) -> None:
        """Fold one (role, stack) observation; ``stack`` is root-first
        frame labels. Public so tests feed synthetic stacks."""
        now = time.time() if now is None else now
        key = (role,) + tuple(stack)
        leaf = stack[-1] if stack else "?"
        with self._lock:
            self._folded[key] = self._folded.get(key, 0) + 1
            self._roles.add(role)
            if len(self._timeline) < MAX_TRACE_SAMPLES:
                self._timeline.append((now, role, leaf))
            else:
                self.timeline_dropped += 1

    def sample_once(self, now: Optional[float] = None) -> int:
        """One tick over every live pipeline thread; returns how many
        threads were sampled. Counted as one sample tick even when no
        pipeline thread is running (the hz x wall invariant covers the
        sampler's own cadence, not the pipeline's lifetime)."""
        now = time.time() if now is None else now
        with self._lock:
            self.samples += 1
        names = self._names_fn()
        sampled = 0
        for ident, frame in list(self._frames_fn().items()):
            name = names.get(ident)
            if name is None:
                continue
            role = role_of(name)
            if role is None:
                continue
            self.record(role, walk_stack(frame), now)
            sampled += 1
        return sampled

    # -- lifecycle ----------------------------------------------------

    def start(self) -> None:
        if self.sample_hz <= 0 or self._thread is not None:
            return
        self._thread = threading.Thread(target=self._loop,
                                        name="stack-sampler",
                                        daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        period = 1.0 / self.sample_hz
        while not self._stop.wait(timeout=period):
            try:
                self.sample_once()
            except Exception:
                continue  # a torn-down thread must not kill the sampler

    def stop(self, timeout: float = 2.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None

    # -- artifacts ----------------------------------------------------

    def folded_lines(self) -> List[str]:
        """The flamegraph-folded artifact body: one
        ``role;frame;...;leaf count`` line per distinct stack, sorted
        for deterministic output."""
        with self._lock:
            items = sorted(self._folded.items())
        return ["%s %d" % (";".join(key), count)
                for key, count in items]

    def write_folded(self, path: str) -> None:
        lines = self.folded_lines()
        with open(path, "w") as f:
            f.write("\n".join(lines))
            if lines:
                f.write("\n")

    def trace_events(self) -> List[Tuple]:
        """Per-sample timeline tiles as Tracer event tuples (the
        collection schema ``(name, ph, t0, dur_s, thread_name, rid,
        args)``) on synthetic ``stacks:<role>`` tracks — each tile is
        the role's top frame at that tick, one sampling period wide,
        so the merged trace.json shows the sampled execution ribbon
        under the instrumented spans."""
        period = 1.0 / self.sample_hz if self.sample_hz > 0 else 0.04
        with self._lock:
            timeline = list(self._timeline)
        return [(leaf, "X", t, period, "stacks:%s" % role, None, None)
                for t, role, leaf in timeline]

    def summary(self) -> Dict[str, int]:
        """The ``Stacks:`` log-meta line payload (and the ``stacks_*``
        BenchmarkResult fields): sampling ticks, distinct roles,
        distinct folded stacks, total per-thread samples — the folded
        artifact's counts sum to ``total`` exactly (--check re-sums
        them)."""
        with self._lock:
            return {
                "samples": self.samples,
                "threads": len(self._roles),
                "folded": len(self._folded),
                "total": sum(self._folded.values()),
            }
