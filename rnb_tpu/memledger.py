"""HBM footprint ledger: per-device byte accounting with declared owners.

ROADMAP item 3 (paged device memory) needs a page allocator sized from
what actually lives in HBM, and item 7 (push MFU past 55%) needs to
know when activation/cache growth starts stealing the bandwidth the
roofline assumes — but until this PR every byte-owning subsystem kept
its own private count (the clip cache's ``resident_bytes``, the
staging pool's slot slabs, the ragged pool's one dispatch shape, the
shared network parameters, the handoff edge's adopted payloads) and
nothing summed them, tracked a peak, or compared the claim against the
backend's own live-buffer list. This module is that unifying layer:

* **Declared owners** (:data:`MEM_OWNER_REGISTRY`): every byte source
  registers under one of the declared owner names — an undeclared
  owner raises at registration, the runtime twin of the metrics-plane
  rule (rnb_tpu.metrics) that undeclared series fail loudly.
* **Sources, not re-measurement**: each subsystem already tracks its
  own bytes; the ledger holds ``(owner, device, key) -> probe`` entries
  (a callable or a fixed byte count) and sums them on each
  :meth:`MemLedger.sample`. The ``key`` dedupes shared objects —
  replicas share one device parameter copy (``_shared_params``), so
  two stage instances registering the same variables count it once.
* **Peak high-water tracking** per owner and for the total, sampled by
  the devobs worker (rnb_tpu.devobs) and by every metrics flusher tick
  — the ``Memory:`` log-meta line's ``peak_bytes >= total_bytes``
  invariant (``parse_utils --check``) holds by construction.
* **Watermark**: a configurable byte threshold; crossing it (below ->
  at-or-above) warns once per episode, counts a ``watermark_hit``, and
  arms the PR 11 flight recorder (``metrics.trigger``) plus — through
  the registry's trigger hooks — a bounded devobs capture window, so
  the black box records what the device was doing when memory ran hot.
* **Reconciliation** (:meth:`reconcile`): on backends exposing
  ``jax.live_arrays()`` / ``jax.live_buffers()``, the ledger's
  *live-backed* claims (sources registered ``live=True`` — the device
  parameter copies, whose arrays provably persist) must not exceed the
  backend's own byte total. Checked, not trusted: a ledger claiming
  more live device bytes than the backend holds is lying.

Cost discipline: module-level hooks follow the house rule — the
disabled path (no ``devobs`` root config key) is one module-global
``None`` test, no registration happens, and every artifact stays
byte-identical to the pre-devobs schema.
"""

from __future__ import annotations

import sys
import threading
from collections import namedtuple
from typing import Callable, Dict, List, Optional, Tuple, Union

#: the active per-job ledger, installed/cleared by rnb_tpu.benchmark
#: around the measured run (module-global like trace.ACTIVE /
#: metrics.ACTIVE: jobs run one at a time per process)
ACTIVE: Optional["MemLedger"] = None

#: one declared footprint owner — same shape as the telemetry
#: registries (rnb_tpu.telemetry.StampSpec), surfaced by
#: ``parse_utils --stamps``
OwnerSpec = namedtuple("OwnerSpec", ("name", "producer", "description"))

#: every owner name a byte source may register under; the ``Memory
#: owners:`` log-meta line's keys are always a subset of these
MEM_OWNER_REGISTRY = (
    OwnerSpec("params", "rnb_tpu/models/r2p1d/model.py",
              "device-resident network parameter copies (deduped: "
              "replicas sharing one _shared_params copy count once)"),
    OwnerSpec("cache", "rnb_tpu/cache.py",
              "clip-cache resident bytes (padded device batches, or "
              "host row extents under ragged dispatch)"),
    OwnerSpec("staging", "rnb_tpu/staging.py",
              "pre-allocated host staging-slot slabs (the zero-copy "
              "decode targets)"),
    OwnerSpec("ragged_pool", "rnb_tpu/models/r2p1d/model.py",
              "one pool-shaped dispatch input per ragged stage (the "
              "stage's single compiled shape's footprint)"),
    OwnerSpec("handoff", "rnb_tpu/handoff.py",
              "payload bytes resident from the consumer's most recent "
              "edge adoption/reshard (rnb_tpu.handoff)"),
    OwnerSpec("page_pool", "rnb_tpu/pager.py",
              "page-allocator arena slabs (paged clip-cache rows and "
              "feature pages) plus the shared zero pools feature hits "
              "dispatch with (rnb_tpu.pager)"),
)

MEM_OWNERS = tuple(spec.name for spec in MEM_OWNER_REGISTRY)


def register(owner: str, device_label: str, key,
             source: Union[int, Callable[[], int]],
             live: bool = False) -> None:
    """Module-level registration hook: one ``None`` test when the
    ledger is off (no ``devobs`` config key), otherwise
    :meth:`MemLedger.register`."""
    ledger = ACTIVE
    if ledger is None:
        return
    ledger.register(owner, device_label, key, source, live=live)


class _Source:
    __slots__ = ("owner", "device", "fn", "live")

    def __init__(self, owner: str, device: str,
                 fn: Callable[[], int], live: bool):
        self.owner = owner
        self.device = device
        self.fn = fn
        self.live = live


class MemLedger:
    """Bounded, thread-safe per-device byte registry with peaks and a
    watermark. One instance per job, owned by the devobs plane
    (rnb_tpu.devobs); sampled by the devobs worker and by metrics
    flusher polls."""

    GUARDED_BY = {
        "_sources": "_lock",
        "_above_watermark": "_lock",
        "_last": "_lock",
        "_peak_by_owner": "_lock",
        "num_samples": "_lock",
        "peak_total": "_lock",
        "watermark_hits": "_lock",
    }

    def __init__(self, watermark_bytes: Optional[int] = None):
        self._lock = threading.Lock()
        #: (owner, key) -> _Source; the key dedupes shared objects
        self._sources: Dict[Tuple[str, object], _Source] = {}
        self.watermark_bytes = (int(watermark_bytes)
                                if watermark_bytes else 0)
        self.watermark_hits = 0
        self._above_watermark = False
        self.peak_total = 0
        self._peak_by_owner: Dict[str, int] = {}
        self._last: Optional[dict] = None
        self.num_samples = 0
        #: direct watermark observer (the devobs plane's capture
        #: arming) for runs WITHOUT a metrics registry — with metrics
        #: on, the flight-trigger hook path delivers the same event,
        #: and the observer is expected to dedupe (rnb_tpu.devobs
        #: checks metrics.ACTIVE)
        self.on_watermark: Optional[Callable[[int], None]] = None

    # -- registration --------------------------------------------------

    def register(self, owner: str, device_label: str, key,
                 source: Union[int, Callable[[], int]],
                 live: bool = False) -> None:
        """Register one byte source under a declared owner.

        ``key`` identifies the underlying object — a second
        registration with the same ``(owner, key)`` replaces rather
        than double-counts (replicas sharing one parameter copy).
        ``source`` is a fixed byte count or a zero-arg probe returning
        the current bytes; ``live=True`` marks sources whose bytes are
        provably backed by persistent device arrays (they enter the
        :meth:`reconcile` comparison).
        """
        if owner not in MEM_OWNERS:
            # runtime twin of the metrics-plane rule: an undeclared
            # owner fails loudly at registration, not as silent drift
            # in the Memory: footing
            raise ValueError(
                "memory owner %r is not declared in "
                "memledger.MEM_OWNER_REGISTRY — declare it or fix the "
                "registration site" % (owner,))
        if callable(source):
            fn = source
        else:
            nbytes = int(source)
            fn = lambda: nbytes  # noqa: E731 — fixed-count probe
        with self._lock:
            self._sources[(owner, key)] = _Source(
                owner, str(device_label), fn, bool(live))

    # -- sampling ------------------------------------------------------

    def sample(self) -> dict:
        """Probe every source, update peaks, evaluate the watermark.

        Returns ``{"total": int, "owners": {owner: bytes}, "devices":
        {device: bytes}}``. Crossing the watermark (below ->
        at-or-above) warns on stderr once per episode, counts one
        ``watermark_hit`` and arms the flight recorder
        (``metrics.trigger``) — trigger hooks then also arm a devobs
        capture window."""
        with self._lock:
            sources = list(self._sources.values())
        owners: Dict[str, int] = {}
        devices: Dict[str, int] = {}
        total = 0
        for src in sources:
            try:
                nbytes = int(src.fn())
            except Exception:
                continue  # a dying probe must not kill the sampler
            owners[src.owner] = owners.get(src.owner, 0) + nbytes
            devices[src.device] = devices.get(src.device, 0) + nbytes
            total += nbytes
        crossed = False
        with self._lock:
            self.num_samples += 1
            self.peak_total = max(self.peak_total, total)
            for owner, nbytes in owners.items():
                self._peak_by_owner[owner] = max(
                    self._peak_by_owner.get(owner, 0), nbytes)
            if self.watermark_bytes > 0:
                above = total >= self.watermark_bytes
                if above and not self._above_watermark:
                    crossed = True
                    self.watermark_hits += 1
                self._above_watermark = above
            record = {"total": total, "owners": owners,
                      "devices": devices}
            self._last = record
        if crossed:
            print("[rnb-tpu] WARNING: memory ledger total %d B crossed "
                  "the %d B watermark" % (total, self.watermark_bytes),
                  file=sys.stderr)
            from rnb_tpu import metrics
            metrics.trigger(metrics.TRIGGER_MEMORY_WATERMARK,
                            {"total_bytes": total,
                             "watermark_bytes": self.watermark_bytes})
            hook = self.on_watermark
            if hook is not None:
                try:
                    hook(total)
                except Exception:
                    pass  # an observer must not break the sampler
        return record

    def peek(self) -> "Optional[dict]":
        """The most recent :meth:`sample` record WITHOUT probing —
        no peak/num_samples updates, no watermark evaluation, no
        trigger side effects. The operator plane's read
        (rnb_tpu.statusz /statusz): an ungated GET must never mutate
        ledger state or fire actuation hooks. None until the devobs
        worker has sampled once."""
        with self._lock:
            return self._last

    # -- reconciliation ------------------------------------------------

    @staticmethod
    def _live_backend_bytes() -> int:
        """Total bytes of the backend's own live array list, or 0 when
        the introspection API is unavailable."""
        try:
            import jax
        except Exception:
            return 0
        arrays = None
        for attr in ("live_arrays", "live_buffers"):
            fn = getattr(jax, attr, None)
            if fn is None:
                continue
            try:
                arrays = fn()
                break
            except Exception:
                continue
        if arrays is None:
            return 0
        total = 0
        for arr in arrays:
            try:
                total += int(arr.nbytes)
            except Exception:
                continue
        return total

    def reconcile(self) -> Tuple[int, bool]:
        """-> ``(live_bytes, ok)``: the backend's live-buffer byte
        total and whether the ledger's live-backed claims fit inside
        it. ``live_bytes == 0`` means the backend exposes no live list
        (``ok`` is then vacuously False — "not reconciled", distinct
        from "reconciled and violated")."""
        live_bytes = self._live_backend_bytes()
        if live_bytes <= 0:
            return 0, False
        with self._lock:
            sources = list(self._sources.values())
        claimed = 0
        for src in sources:
            if not src.live:
                continue
            try:
                claimed += int(src.fn())
            except Exception:
                continue
        return live_bytes, claimed <= live_bytes

    # -- reporting -----------------------------------------------------

    def snapshot(self) -> dict:
        """Final footing record for the ``Memory:`` / ``Memory
        owners:`` log-meta lines: re-samples so the totals reflect the
        settled end-of-run state, then attaches peaks."""
        record = self.sample()
        with self._lock:
            owners_detail = {
                owner: {"bytes": record["owners"].get(owner, 0),
                        "peak_bytes": self._peak_by_owner.get(owner, 0)}
                for owner in sorted(set(record["owners"])
                                    | set(self._peak_by_owner))}
            return {
                "total_bytes": record["total"],
                "peak_bytes": self.peak_total,
                "owners": owners_detail,
                "devices": dict(record["devices"]),
                "watermark_bytes": self.watermark_bytes,
                "watermark_hits": self.watermark_hits,
            }
