"""The channel runtime: control queues, device buffer rings, termination.

The pipeline's communication fabric, re-designed for a single-controller
TPU runtime (capability parity with the reference's control.py:1-209):

* **Control messages** travel through bounded ``queue.Queue`` channels as
  ``(Signal|None, non_tensors, TimeCard)`` tuples — never bulk tensors.
  Queue overflow is a *failure signal*, not backpressure: the run aborts
  with a reason code (reference semantics, README/runner.py:230-234).
* **Bulk data** lives in per-instance :class:`BufferRing` s — a bounded
  pool of slots, each holding a tuple of immutable device arrays plus
  their valid-row counts. A slot's ``free`` event provides the
  producer/consumer ownership handoff the reference implemented with
  ``mp.Event`` over shared CUDA tensors (control.py:19-46). Because JAX
  arrays are immutable there is no data race to guard — the ring's job
  here is *backpressure*: a producer blocks when all its slots hold
  unconsumed outputs, bounding device memory exactly like the
  reference's pre-allocated tensor pool.
* **Coordination**: a :class:`TerminationState` any stage may raise
  (first writer wins), inspected at every loop top; threading barriers
  fence start/finish so init and teardown stay out of timing windows.

Stage hand-off across devices happens when the *consumer* re-homes the
arrays with ``jax.device_put`` onto its own device — on TPU hardware an
ICI transfer, the analog of the reference's cross-GPU ``copy_``
(runner.py:104-114).
"""

from __future__ import annotations

import enum
import math
import queue
import threading
from collections import namedtuple
from typing import Dict, List, Optional, Tuple

from rnb_tpu import metrics
from rnb_tpu.config import (  # DEFAULT_... re-exported for back-compat
    DEFAULT_NUM_SHARED_TENSORS, ConfigError, PipelineConfig)
from rnb_tpu.devices import DeviceSpec
from rnb_tpu.utils.class_utils import load_class

#: sentinel count marking end-of-stream on every edge (reference
#: client.py:9, runner.py:3)
NUM_EXIT_MARKERS = 10


class TerminationFlag(enum.IntEnum):
    """Job-wide termination reason codes (reference control.py:11-16;
    INTERNAL_ERROR is ours — the reference had no code for a crashed
    stage and could hang on one)."""

    UNSET = -1
    TARGET_NUM_VIDEOS_REACHED = 0
    FILENAME_QUEUE_FULL = 1
    FRAME_QUEUE_FULL = 2
    INTERNAL_ERROR = 3


class TerminationState:
    """A raise-once job termination flag shared by every stage thread.

    Any thread may raise it with a reason code; the first raise wins.
    Replaces the reference's lock-free shared ``Value`` write
    (runner.py:193) with an explicit first-writer-wins rule so the
    recorded reason is deterministic.
    """

    UNGUARDED_OK = {
        "_value": "first-writer-wins under _lock; bare reads observe "
                  "a monotone raise-once flag",
    }

    def __init__(self):
        self._value = TerminationFlag.UNSET
        self._lock = threading.Lock()

    @property
    def value(self) -> TerminationFlag:
        return self._value

    def raise_flag(self, code: TerminationFlag) -> None:
        with self._lock:
            if self._value == TerminationFlag.UNSET:
                self._value = TerminationFlag(code)

    @property
    def terminated(self) -> bool:
        return self._value != TerminationFlag.UNSET


class FaultStats:
    """Job-wide fault accounting shared by the client and every stage
    executor (rnb_tpu.runner containment layer).

    Counts contained permanent failures (with per-reason totals and a
    bounded dead-letter record of ``(request_id, step_idx, reason)``),
    shed requests per site, transient retries, and per-edge queue
    overflows (the abort-policy full-queue events that used to be an
    unparseable stdout warning — now a counter surfaced in
    BenchmarkResult and the log-meta ``Queue overflows:`` line). All
    exact counts; only the dead-letter *detail* list is capped so a
    pathological run cannot grow controller memory without bound.
    """

    MAX_DEAD_LETTERS = 1000

    GUARDED_BY = {
        "num_failed": "_lock",
        "num_shed": "_lock",
        "num_retries": "_lock",
        "failure_reasons": "_lock",
        "shed_sites": "_lock",
        "overflow_sites": "_lock",
        "dead_letters": "_lock",
    }

    def __init__(self):
        self._lock = threading.Lock()
        self.num_failed = 0
        self.num_shed = 0
        self.num_retries = 0
        self.failure_reasons: Dict[str, int] = {}
        self.shed_sites: Dict[str, int] = {}
        self.overflow_sites: Dict[str, int] = {}
        self.dead_letters: List[tuple] = []

    def record_failure(self, request_ids, step_idx: int,
                       reason: str) -> None:
        """Dead-letter one or more requests (a fused batch fails as a
        unit) with one reason at one step."""
        with self._lock:
            self.num_failed += len(request_ids)
            self.failure_reasons[reason] = \
                self.failure_reasons.get(reason, 0) + len(request_ids)
            for rid in request_ids:
                if len(self.dead_letters) < self.MAX_DEAD_LETTERS:
                    self.dead_letters.append((rid, step_idx, reason))
        # live SLO feed (rnb_tpu.metrics): a dead-lettered request is
        # an SLO violation the burn-rate window must see NOW, not at
        # exit (one None test when metrics are off; outside the ledger
        # lock so the two locks never nest)
        metrics.mark("slo.miss", len(request_ids))

    def record_shed(self, site: str, n: int = 1) -> None:
        with self._lock:
            self.num_shed += n
            self.shed_sites[site] = self.shed_sites.get(site, 0) + n
        # shed-spike flight trigger + SLO burn both window on these
        metrics.mark("faults.sheds", n)
        metrics.mark("slo.miss", n)

    def record_retries(self, n: int = 1) -> None:
        with self._lock:
            self.num_retries += n

    def record_overflow(self, edge: str, n: int = 1) -> None:
        """One inter-stage (or filename) queue hit capacity under the
        "abort" overload policy — counted per edge so the telemetry
        names WHERE the pipeline backed up, not just that it died."""
        with self._lock:
            self.overflow_sites[edge] = \
                self.overflow_sites.get(edge, 0) + n

    def snapshot(self) -> Dict[str, object]:
        """Point-in-time copy for reports (dead-letter detail included)."""
        with self._lock:
            return {
                "num_failed": self.num_failed,
                "num_shed": self.num_shed,
                "num_retries": self.num_retries,
                "failure_reasons": dict(self.failure_reasons),
                "shed_sites": dict(self.shed_sites),
                "overflow_sites": dict(self.overflow_sites),
                "dead_letters": list(self.dead_letters),
            }


class InferenceCounter:
    """Locked global disposed-request counter driving the progress
    display and the target-reached check (reference benchmark.py:199-205,
    runner.py:176-196). With the containment layer, *disposed* means
    completed, contained-failed, or shed — every request the pipeline
    will never owe further work on counts toward the target, so a run
    with contained failures still terminates instead of waiting forever
    for completions that cannot come."""

    UNGUARDED_OK = {
        "_value": "add() is atomic under _lock; bare value reads are "
                  "a progress gauge",
    }

    def __init__(self):
        self._value = 0
        self._lock = threading.Lock()

    @property
    def value(self) -> int:
        return self._value

    def add(self, n: int) -> Tuple[int, int]:
        """Add n; return (old, new) atomically."""
        with self._lock:
            old = self._value
            self._value = old + n
            return old, self._value


def dispose_requests(counter: InferenceCounter, num_videos: int,
                     termination: TerminationState,
                     n: int = 1) -> Tuple[int, int]:
    """Count n requests as disposed (failed/shed) and raise the
    target-reached flag when the count crosses the job target.

    The final step's success path keeps its own inline version (it also
    breaks its hot loop on the crossing); every *other* disposal site —
    a contained failure at any step, a shed at the client or between
    stages — funnels through here so the job still terminates when the
    last outstanding request dies instead of completing.
    """
    old, new = counter.add(n)
    if old < num_videos <= new:
        termination.raise_flag(TerminationFlag.TARGET_NUM_VIDEOS_REACHED)
    return old, new


def send_exit_markers(target_queue: "queue.Queue",
                      num_markers: int = NUM_EXIT_MARKERS,
                      termination: Optional["TerminationState"] = None,
                      timeout_s: float = 60.0) -> None:
    """Enqueue ``num_markers`` end-of-stream ``None`` markers.

    Markers must not be silently dropped: with the last-producer drain
    protocol each edge gets exactly one marker attempt, so a transiently
    full queue would otherwise lose the end-of-stream signal and hang
    every downstream consumer until the barrier timeout. Retries with a
    short blocking put until the queue drains, the job terminates, or a
    generous deadline passes (a dead pipeline with no consumers left).
    """
    import time as _time
    deadline = _time.monotonic() + timeout_s
    for _ in range(num_markers):
        while True:
            try:
                target_queue.put(None, timeout=0.05)
                break
            except queue.Full:
                if termination is not None and termination.terminated:
                    return
                if _time.monotonic() > deadline:
                    # markers could not be delivered — abort the job
                    # rather than leave downstream consumers polling an
                    # edge that will never see end-of-stream
                    print("[WARNING] end-of-stream markers undeliverable "
                          "for %.0fs; aborting" % timeout_s)
                    if termination is not None:
                        termination.raise_flag(TerminationFlag.INTERNAL_ERROR)
                    return


class EdgeTracker:
    """Producer countdown for one queue edge.

    Exit markers (``None``) must never overtake real items: with
    competing producer replicas feeding one queue, a fast replica that
    finished and enqueued its markers could starve a downstream consumer
    of a slower sibling's still-in-flight items (the consumer breaks on
    the first ``None`` it pops). The fix over the reference's
    fixed-10-markers heuristic (reference runner.py:238-245): every
    producer on the edge decrements this tracker when it is done, and
    only the *last* one enqueues the markers — by then every real item
    is already in the queue ahead of them.
    """

    GUARDED_BY = {"_remaining": "_lock"}

    def __init__(self, num_producers: int, num_markers: int):
        self._remaining = num_producers
        self._lock = threading.Lock()
        self.num_markers = num_markers

    def producer_finished(self) -> bool:
        """Record one producer's completion; True for the last one."""
        with self._lock:
            self._remaining -= 1
            return self._remaining == 0


#: Pointer passed through control queues instead of tensor payloads:
#: names the producer (group, instance) and the ring slot index
#: (reference control.py:209).
Signal = namedtuple("Signal", ("group_idx", "instance_idx", "tensor_idx"))


def get_segmented_shapes(shapes: Tuple[Tuple[int, ...], ...],
                         num_segments: int) -> Tuple[Tuple[int, ...], ...]:
    """Shrink per-output max shapes to one segment's worth of rows.

    A step with ``num_segments=k`` splits each output batch row-wise into
    k segments, so downstream buffers only ever hold ``ceil(rows/k)``
    rows (reference control.py:49-69).
    """
    if num_segments <= 1:
        return shapes
    out = []
    for shape in shapes:
        if not shape:
            raise ValueError(
                "cannot segment a scalar output shape %r" % (shape,))
        out.append((math.ceil(shape[0] / num_segments),) + tuple(shape[1:]))
    return tuple(out)


class RingSlot:
    """One credit of a BufferRing: free-event + the parked payload."""

    __slots__ = ("free", "payload")

    def __init__(self):
        self.free = threading.Event()
        self.free.set()  # set == free for reuse (reference control.py:23-33)
        self.payload: Optional[tuple] = None

    def write(self, payload: tuple) -> None:
        """Park a payload (tuple of PaddedBatch) and mark occupied."""
        self.payload = payload
        self.free.clear()

    def read(self) -> tuple:
        return self.payload

    def release(self) -> None:
        """Consumer is done with the slot; producer may reuse it."""
        self.payload = None
        self.free.set()


class BufferRing:
    """A bounded slot pool owned by one producer instance.

    The producer writes outputs round-robin into slots, blocking while
    the next slot is still held by a consumer — the same backpressure
    point as the reference's ``tensor_event.event.wait()``
    (runner.py:161-163). ``wait_free`` polls the termination flag so a
    dying pipeline can't deadlock a producer forever.
    """

    POLL_INTERVAL_S = 0.05

    def __init__(self, num_slots: int, device: DeviceSpec,
                 shapes: Tuple[Tuple[int, ...], ...]):
        if num_slots < 1:
            raise ValueError("BufferRing needs at least one slot")
        self.slots = [RingSlot() for _ in range(num_slots)]
        self.device = device
        self.shapes = shapes

    def __len__(self) -> int:
        return len(self.slots)

    def wait_free(self, slot_idx: int,
                  termination: TerminationState) -> bool:
        """Block until slot is free; False if the job died meanwhile."""
        slot = self.slots[slot_idx]
        while not slot.free.wait(timeout=self.POLL_INTERVAL_S):
            if termination.terminated:
                return False
        return True

    def release_all(self) -> None:
        """Free every slot so blocked producers wake during teardown
        (reference runner.py:247-253)."""
        for slot in self.slots:
            slot.release()


class ChannelFabric:
    """Builds and wires every queue and buffer ring of one pipeline.

    Equivalent of the reference's ``SharedQueuesAndTensors``
    (control.py:72-205): a filename queue feeding step 0, one bounded
    queue per declared out-queue index per step, and a
    [step][group][instance] ring pool for every non-final step whose
    stage model declares tensor outputs (``output_shape() is not None``;
    None means no ring is allocated — distinct from an empty tuple,
    reference runner_model.py:31-46). Ring shapes come from the stage
    class's config-aware ``output_shape_for(**model_kwargs)`` —
    evaluated per group, since group extras may override step extras —
    shrunk by the step's ``num_segments``.
    """

    def __init__(self, pipeline: PipelineConfig, queue_size: int):
        self.pipeline = pipeline
        self.queue_size = queue_size
        self.filename_queue: "queue.Queue" = queue.Queue(maxsize=queue_size)

        # queues[step_idx][queue_idx] -> Queue shared by that step's
        # producers and the next step's consumers
        self.queues: List[Dict[int, "queue.Queue"]] = []
        # trackers[step_idx][queue_idx] -> EdgeTracker for that edge
        self.trackers: List[Dict[int, EdgeTracker]] = []
        # rings[step_idx][group_idx][instance_idx] -> BufferRing | None
        self.rings: List[List[List[Optional[BufferRing]]]] = []

        #: the filename queue has exactly one producer (the client), so
        #: it needs no countdown — just enough markers for step 0
        self.filename_num_markers = max(
            NUM_EXIT_MARKERS,
            sum(len(g.devices) for g in pipeline.steps[0].groups))

        for step_idx, step in enumerate(pipeline.steps):
            is_final = step_idx == pipeline.num_steps - 1

            step_queues: Dict[int, "queue.Queue"] = {}
            step_trackers: Dict[int, EdgeTracker] = {}
            if not is_final:
                for group in step.groups:
                    for q_idx in group.out_queues:
                        if q_idx not in step_queues:
                            step_queues[q_idx] = queue.Queue(
                                maxsize=queue_size)
                for q_idx in step_queues:
                    num_producers = sum(
                        len(g.devices) for g in step.groups
                        if q_idx in g.out_queues)
                    num_consumers = sum(
                        len(g.devices)
                        for g in pipeline.steps[step_idx + 1].groups
                        if g.in_queue == q_idx)
                    step_trackers[q_idx] = EdgeTracker(
                        num_producers,
                        max(NUM_EXIT_MARKERS, num_consumers))
            self.queues.append(step_queues)
            self.trackers.append(step_trackers)

            step_rings: List[List[Optional[BufferRing]]] = []
            model_class = load_class(step.model) if not is_final else None
            num_slots = step.effective_shared_tensors
            for group_idx, group in enumerate(step.groups):
                shapes = None
                if model_class is not None:
                    shapes = model_class.output_shape_for(
                        **step.kwargs_for_group(group_idx))
                    if shapes is not None:
                        # authoritative deadlock guard (parse_config
                        # repeats it conservatively for configs that
                        # never reach fabric construction): a producer
                        # fills one slot per segment before publishing
                        # any Signal, so slots < segments hangs forever
                        if num_slots < step.num_segments:
                            raise ConfigError(
                                "step %d: ring of %d slots cannot hold "
                                "%d segments — the producer would "
                                "deadlock" % (step_idx, num_slots,
                                              step.num_segments))
                        shapes = get_segmented_shapes(
                            tuple(map(tuple, shapes)), step.num_segments)
                group_rings: List[Optional[BufferRing]] = []
                for device in group.devices:
                    if shapes is None:
                        group_rings.append(None)
                    else:
                        group_rings.append(
                            BufferRing(num_slots, device, shapes))
                step_rings.append(group_rings)
            self.rings.append(step_rings)

    # -- accessors ---------------------------------------------------

    def get_filename_queue(self) -> "queue.Queue":
        return self.filename_queue

    def get_queues(self, step_idx: int, group_idx: int):
        """(in_queue, out_queues) for one group's runner instances.

        Step 0 reads the filename queue; the final step has no out
        queues (None). Reference: control.py:167-180.
        """
        group = self.pipeline.steps[step_idx].groups[group_idx]
        if step_idx == 0:
            in_queue = self.filename_queue
        else:
            in_queue = self.queues[step_idx - 1][group.in_queue]
        if step_idx == self.pipeline.num_steps - 1:
            out_queues = None
        else:
            out_queues = [self.queues[step_idx][q] for q in group.out_queues]
        return in_queue, out_queues

    def get_out_trackers(self, step_idx: int,
                         group_idx: int) -> Optional[List[EdgeTracker]]:
        """EdgeTrackers parallel to ``get_queues()[1]`` (None for the
        final step)."""
        if step_idx == self.pipeline.num_steps - 1:
            return None
        group = self.pipeline.steps[step_idx].groups[group_idx]
        return [self.trackers[step_idx][q] for q in group.out_queues]

    def get_input_rings(self, step_idx: int,
                        group_idx: int) -> Optional[Dict[int, List[Optional[BufferRing]]]]:
        """Upstream rings a consumer may receive Signals into.

        For a consumer group at ``step_idx``, returns
        ``{upstream_group_idx: [ring per instance]}`` restricted to the
        previous step's groups whose out-queues include this group's
        in-queue; None for step 0 or when the upstream step allocates no
        rings (reference control.py:182-205).
        """
        if step_idx == 0:
            return None
        group = self.pipeline.steps[step_idx].groups[group_idx]
        upstream = self.pipeline.steps[step_idx - 1]
        result: Dict[int, List[Optional[BufferRing]]] = {}
        any_ring = False
        for up_idx, up_group in enumerate(upstream.groups):
            if group.in_queue in up_group.out_queues:
                rings = self.rings[step_idx - 1][up_idx]
                result[up_idx] = rings
                if any(r is not None for r in rings):
                    any_ring = True
        return result if any_ring else None

    def get_output_ring(self, step_idx: int, group_idx: int,
                        instance_idx: int) -> Optional[BufferRing]:
        return self.rings[step_idx][group_idx][instance_idx]

    def all_rings(self) -> List[BufferRing]:
        return [r for step in self.rings for group in step for r in group
                if r is not None]

