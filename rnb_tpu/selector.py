"""Pluggable routing policies: which downstream queue receives an output.

A stage group with several ``out_queues`` consults its
:class:`QueueSelector` per request; selectors may inspect the tensors,
the non-tensor payload, or the TimeCard (content-aware routing — the
"Replicate & Batch" placement idea routes rare large videos to a
dedicated lane, see models/r2p1d/model.py in this repo).

Reference parity: selector.py:1-18.
"""

from __future__ import annotations


class QueueSelector:
    """Base contract: pick an output-queue index in [0, num_queues)."""

    def __init__(self, num_queues: int):
        self.num_queues = num_queues

    def bind_stage(self, model) -> None:
        """Called once by the executor with the producing stage model,
        before the hot loop. Content-aware selectors read their
        thresholds from the stage's configuration here (e.g. the
        loader's configured clip population) instead of hardcoding
        module constants that silently diverge from the config."""

    def select(self, tensors, non_tensors, time_card) -> int:
        raise NotImplementedError


class RoundRobinSelector(QueueSelector):
    """Cycle through the output queues regardless of content."""

    def __init__(self, num_queues: int):
        super().__init__(num_queues)
        self._next = 0

    def select(self, tensors, non_tensors, time_card) -> int:
        choice = self._next
        self._next = (self._next + 1) % self.num_queues
        return choice


class ReplicaSelector(QueueSelector):
    """Least-loaded routing over replica lanes (PR 9 scale-out).

    A step declaring ``replicas: N`` expands into N queue groups, each
    with its own lane queue; the upstream producers' selector becomes
    this one (rnb_tpu.config swaps it in for the default). Routing is
    by **per-replica in-flight depth** — items enqueued minus items
    whose processing the replica finished, tracked by a shared
    :class:`rnb_tpu.handoff.InflightDepths` the executor binds via
    :meth:`bind_depths` — so a replica wedged on a slow batch stops
    receiving work, which a bare queue-length poll would miss (the
    popped-and-in-service item is invisible to ``qsize``).

    Deterministic: the minimum-depth lane wins, ties break to the
    lowest lane index — under a seeded workload the routing sequence
    is a pure function of the depth sequence. Without bound depths
    (hand-written configs naming this selector on a non-replica edge)
    it degrades to round-robin.

    With a bound :class:`rnb_tpu.health.LaneHealthBoard`
    (``bind_health``, root ``health`` config key), routing is
    additionally health-gated: open/evicted lanes leave the candidate
    set, a half-open lane due for its recovery probe receives exactly
    that one dispatch, and the lowest-lane tie-break skips excluded
    lanes **stably** — the surviving lanes keep their original
    relative order, so a seeded run replays the identical routing
    sequence across chaos arms whatever subset of lanes is alive
    (the regression test pins this for a seeded kill schedule).
    """

    def __init__(self, num_queues: int):
        super().__init__(num_queues)
        self._rr = 0
        self._depths = None          # rnb_tpu.handoff.InflightDepths
        self._queue_indices = None   # lane position -> queue index
        self._health = None          # rnb_tpu.health.LaneHealthBoard
        #: True when the last select() was a forced route (no healthy
        #: sibling existed) — the executor reads it for accounting
        self.last_route_forced = False

    def bind_depths(self, depths, queue_indices) -> None:
        """Executor protocol (rnb_tpu.runner): share the replica
        step's depth counters and this producer's out-queue index
        list (lane position -> config queue index)."""
        if len(queue_indices) != self.num_queues:
            raise ValueError(
                "ReplicaSelector routes over %d queue(s) but was bound "
                "to %d queue indices" % (self.num_queues,
                                         len(queue_indices)))
        self._depths = depths
        self._queue_indices = [int(q) for q in queue_indices]

    def bind_health(self, board) -> None:
        """Executor protocol: share the replica step's lane health
        board (rnb_tpu.health) so routing stops feeding open/evicted
        lanes and carries half-open recovery probes."""
        self._health = board

    def select(self, tensors, non_tensors, time_card) -> int:
        self.last_route_forced = False
        if self._depths is None:
            choice = self._rr
            self._rr = (self._rr + 1) % self.num_queues
            return choice
        candidates = self._queue_indices
        if self._health is not None:
            allowed, probe = self._health.route_filter(
                self._queue_indices)
            if probe is not None:
                # the single half-open recovery dispatch goes to the
                # probing lane, bypassing least-loaded entirely
                self._health.note_route(probe)
                return self._queue_indices.index(probe)
            if allowed:
                # STABLE exclusion: surviving lanes keep their
                # original relative order, so the deterministic
                # lowest-lane tie-break replays identically whatever
                # subset is alive (route_filter preserves the order
                # of the indices it was given)
                candidates = allowed
            if not allowed:
                # every lane open/evicted: route least-loaded over
                # whatever exists — deterministic, counted as forced
                self.last_route_forced = True
        best_q, best_depth = candidates[0], None
        for q_idx in candidates:
            depth = self._depths.depth(q_idx)
            if best_depth is None or depth < best_depth:
                best_q, best_depth = q_idx, depth
        if self._health is not None:
            self._health.note_route(best_q,
                                    forced=self.last_route_forced)
        return self._queue_indices.index(best_q)
