"""Pluggable routing policies: which downstream queue receives an output.

A stage group with several ``out_queues`` consults its
:class:`QueueSelector` per request; selectors may inspect the tensors,
the non-tensor payload, or the TimeCard (content-aware routing — the
"Replicate & Batch" placement idea routes rare large videos to a
dedicated lane, see models/r2p1d/model.py in this repo).

Reference parity: selector.py:1-18.
"""

from __future__ import annotations


class QueueSelector:
    """Base contract: pick an output-queue index in [0, num_queues)."""

    def __init__(self, num_queues: int):
        self.num_queues = num_queues

    def bind_stage(self, model) -> None:
        """Called once by the executor with the producing stage model,
        before the hot loop. Content-aware selectors read their
        thresholds from the stage's configuration here (e.g. the
        loader's configured clip population) instead of hardcoding
        module constants that silently diverge from the config."""

    def select(self, tensors, non_tensors, time_card) -> int:
        raise NotImplementedError


class RoundRobinSelector(QueueSelector):
    """Cycle through the output queues regardless of content."""

    def __init__(self, num_queues: int):
        super().__init__(num_queues)
        self._next = 0

    def select(self, tensors, non_tensors, time_card) -> int:
        choice = self._next
        self._next = (self._next + 1) % self.num_queues
        return choice
