"""Device-resident decoded-clip cache + in-flight request coalescing.

Real video-serving traffic is popularity-skewed: a small fraction of
videos receives most of the requests (the Zipf workload
``rnb_tpu.video_path_provider.ZipfPathIterator`` models). Round 5
measured the host core at 98% saturation with the two dominant terms
being ``device_put`` staging (49.3%) and decode-output assembly +
decode wait (22.1%) — both of which a cache hit skips entirely: the
cached value is the *already-padded on-device uint8 clip batch*
(post-``device_put``, pre-preprocess) plus its valid-row count, so a
hit feeds the existing jitted preprocess/network path unchanged and
produces bit-identical logits to a miss.

Design:

* **Content-addressed keys** (:func:`content_key`): (video path,
  file mtime_ns + size, decode-config fingerprint). The fingerprint
  covers everything that changes decoded bytes — sampler population/
  weights (clip starts are deterministic per video id given these),
  ``consecutive_frames``, frame geometry, pixel format, ``max_clips``
  and the row-bucket set (the padded shape is part of the value). A
  file replaced on disk gets a new key; a config change can never
  alias another config's entries.
* **Byte-accounted LRU** bounded by ``cache_mb``: every entry is
  charged its device-array ``nbytes``; inserts evict from the
  least-recently-used end until the new entry fits. An entry larger
  than the whole budget is skipped (counted ``oversize``), never
  inserted.
* **Insert-after-success only**: the loaders insert a value only once
  decode + transfer completed; failed or contained requests
  (rnb_tpu.faults taxonomy, including ``take_failed()`` inside fused
  assembly) never reach the insert path, so a corrupt video cannot
  poison later requests.
* **In-flight coalescing** (:class:`InflightTable`): concurrent
  requests for the same key share one decode. The loaders register
  the leader's in-flight record; followers park on it — in the fusing
  loader they ride the leader's fused emission through the existing
  TimeCardList fan-out, in the prefetching loader they share the
  leader's decoded host buffer. Either way the duplicate decode never
  happens, which is where the win is under Poisson+Zipf arrivals.

The cache is per loader-stage instance (all access happens on the one
executor thread that owns the stage), but every mutator takes the lock
anyway so a future shared deployment stays correct. Stats are exact
counters surfaced end-to-end: BenchmarkResult, ``log-meta.txt``
(``Cache:`` line), the ``# cache`` trailer on per-instance tables, and
``scripts/parse_utils.py``.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from rnb_tpu import lockwitness
from rnb_tpu.utils.lazy_jax import jax_numpy as _jax_numpy

#: stat signature for ids that are not files on disk (synth:// ids):
#: their content is deterministic per id, so a constant signature is
#: content-correct
_NO_STAT = (-1, -1)


def content_key(video: str, cfg_key: Any) -> tuple:
    """Content-addressed cache key for one request.

    ``cfg_key`` is the loader's decode-config fingerprint (hashable).
    For real files the file's (mtime_ns, size) joins the key so a
    video replaced on disk mid-run invalidates instead of serving
    stale clips; ids without a backing file (synthetic, vanished
    files — the decode layer resolves those deterministically) use a
    constant signature.
    """
    try:
        st = os.stat(video)
        sig = (st.st_mtime_ns, st.st_size)
    except (OSError, ValueError):
        sig = _NO_STAT
    return (video, sig, cfg_key)


class CacheEntry:
    """One cached clip batch: device-resident uint8, padded to its
    row bucket, plus the valid-row count."""

    __slots__ = ("batch", "valid", "nbytes")

    def __init__(self, batch, valid: int, nbytes: int):
        self.batch = batch      # jax.Array uint8, shape = bucket shape
        self.valid = int(valid)  # meaningful leading rows
        self.nbytes = int(nbytes)


class PagedEntry:
    """One paged cache entry: a page *reference list* into the clip
    arena's device slab (rnb_tpu.pager) instead of a contiguous blob —
    any free pages serve any entry (no fragmentation, no oversize
    skip) and eviction frees pages, not bytes."""

    __slots__ = ("pages", "valid", "nbytes")

    def __init__(self, pages: Tuple[int, ...], valid: int, nbytes: int):
        self.pages = pages
        self.valid = int(valid)
        self.nbytes = int(nbytes)


class ClipCache:
    """Bounded, byte-accounted LRU of device-resident clip batches."""

    #: declared concurrency contract (rnb-lint RNB-C001/C003): which
    #: lock guards which cross-thread attribute
    GUARDED_BY = {
        "_entries": "_lock",
        "_arena": "_lock",
        "capacity_bytes": "_lock",
        "resident_bytes": "_lock",
        "num_hits": "_lock",
        "num_misses": "_lock",
        "num_inserts": "_lock",
        "num_evictions": "_lock",
        "num_coalesced": "_lock",
        "num_oversize": "_lock",
    }

    def __init__(self, cache_mb: float, device=None):
        if cache_mb <= 0:
            raise ValueError("cache_mb must be > 0 to build a ClipCache "
                             "(got %r); omit the key to disable caching"
                             % (cache_mb,))
        self.capacity_bytes = int(float(cache_mb) * (1 << 20))
        self.device = device
        self._lock = lockwitness.lock("ClipCache._lock")
        self._entries: "OrderedDict[tuple, CacheEntry]" = OrderedDict()
        self.resident_bytes = 0
        # exact counters, surfaced end-to-end (benchmark/log-meta/parse)
        self.num_hits = 0
        self.num_misses = 0
        self.num_inserts = 0
        self.num_evictions = 0
        self.num_coalesced = 0
        self.num_oversize = 0
        #: paged mode (rnb_tpu.pager): entries become page reference
        #: lists in this arena's slab; None = blob mode (the seed
        #: semantics, byte-stable)
        self._arena = None

    def attach_arena(self, arena) -> None:
        """Switch this cache to paged mode: entries are page reference
        lists allocated from ``arena``; the arena budget replaces
        ``capacity_bytes`` as the byte bound (still reported, for the
        Cache: line's footing)."""
        with self._lock:
            if self._entries:
                raise RuntimeError("attach_arena on a non-empty cache: "
                                   "blob and paged entries must never "
                                   "coexist")
            self._arena = arena
            self.capacity_bytes = int(arena.nbytes)

    @property
    def paged(self) -> bool:
        with self._lock:
            return self._arena is not None

    def acquire(self, key: tuple):
        """Paged-mode hit path: counted lookup -> pinned
        ``rnb_tpu.pager.GatherPlan`` (flat slab rows for the entry's
        valid rows) or None. The caller overlays the rows on device at
        the consumption seam and releases the plan once its gather
        dispatched; pages evicted in between park in limbo, so the
        plan's rows can never be recycled under it."""
        from rnb_tpu.pager import GatherPlan
        with self._lock:
            arena = self._arena
            assert arena is not None, "acquire() is the paged hit path"
            entry = self._entries.get(key)
            if entry is None:
                self.num_misses += 1
                return None
            self._entries.move_to_end(key)
            self.num_hits += 1
            with arena.pager.lock:
                arena.pin_locked(entry.pages)
            return GatherPlan(arena, entry.pages,
                              arena.flat_rows(entry.pages, entry.valid),
                              entry.valid)

    def insert_pages(self, key: tuple, src_pool, row0: int,
                     valid: int) -> bool:
        """Paged-mode insert: allocate pages, publish ``valid`` rows of
        the already-transferred device pool (rows ``[row0, row0 +
        valid)``) into the arena slab via the donated page writer, and
        record the reference list. First writer wins; evicts LRU
        entries (freeing their pages) until the allocation fits; an
        entry needing more pages than the whole arena holds is counted
        ``oversize`` and skipped — the only size an entry can still
        exceed, since pages need not be contiguous."""
        valid = int(valid)
        if valid < 1:
            return False
        with self._lock:
            arena = self._arena
            assert arena is not None, \
                "insert_pages() is the paged insert"
            if key in self._entries:
                return False
            needed = arena.pages_needed(valid)
            if needed > arena.num_pages:
                self.num_oversize += 1
                return False
            with arena.pager.lock:
                pages = None
                while True:
                    pages = arena.alloc_locked(needed)
                    if pages is not None or not self._entries:
                        break
                    _, evicted = self._entries.popitem(last=False)
                    self.resident_bytes -= evicted.nbytes
                    self.num_evictions += 1
                    arena.free_locked(evicted.pages)
                if pages is None:
                    # every evictable page is out and the rest are
                    # pinned/limbo under live plans — skip, never block
                    return False
                arena.write_entry_locked(pages, src_pool, row0, valid)
            entry = PagedEntry(pages, valid, needed * arena.page_bytes)
            self._entries[key] = entry
            self.resident_bytes += entry.nbytes
            self.num_inserts += 1
            return True

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def lookup(self, key: tuple) -> Optional[CacheEntry]:
        """Counted hit/miss lookup; a hit refreshes LRU recency."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                self.num_misses += 1
                return None
            self._entries.move_to_end(key)
            self.num_hits += 1
            return entry

    def contains(self, key: tuple) -> bool:
        """Uncounted membership probe (insert-path dedup)."""
        with self._lock:
            return key in self._entries

    def note_coalesced(self, n: int = 1) -> None:
        with self._lock:
            self.num_coalesced += n

    def _insert(self, key: tuple, batch, valid: int,
                nbytes: int) -> bool:
        """The one locked insert body every flavor shares: first
        writer wins, oversize skipped (counted), LRU-evict until the
        entry fits."""
        with self._lock:
            if key in self._entries:
                return False
            if nbytes > self.capacity_bytes:
                self.num_oversize += 1
                return False
            while (self.resident_bytes + nbytes > self.capacity_bytes
                   and self._entries):
                _, evicted = self._entries.popitem(last=False)
                self.resident_bytes -= evicted.nbytes
                self.num_evictions += 1
            self._entries[key] = CacheEntry(batch, valid, nbytes)
            self.resident_bytes += nbytes
            self.num_inserts += 1
            return True

    def insert_device(self, key: tuple, device_batch, valid: int) -> bool:
        """Insert an already-transferred padded device batch.

        Returns False when the entry was skipped (oversize, or the key
        is already resident — first writer wins, the bytes are
        identical by content-addressing).
        """
        return self._insert(key, device_batch, valid,
                            int(device_batch.nbytes))

    def insert_host(self, key: tuple, clips, valid: int,
                    target_shape: Tuple[int, ...],
                    dtype=np.uint8) -> bool:
        """Pad host clips to ``target_shape`` and transfer, then insert.

        Used by the fusing loader, whose misses cross the wire inside a
        fused batch — there is no standalone padded device array to
        reuse, so the insert pays one extra transfer the first time a
        video is seen (amortized away by every later hit; the
        ``loader.cache_insert`` hostprof section accounts for it).

        Staging contract (rnb_tpu.staging): ``clips`` may be a view
        into a staging slot whose buffer is recycled after the fused
        emission's transfer confirms. This method COPIES the rows into
        its own freshly padded array before any transfer, so it must
        be called while the slot rows are still live (the fusing
        loader inserts during ``_emit``, strictly before the slot's
        transfer handoff) — after that, the cached device array owns
        independent bytes and can never observe a slot reuse.
        """
        dtype = np.dtype(dtype)
        with self._lock:
            # capacity_bytes is rebound by attach_arena — read it
            # under the same lock that guards the switch
            if int(np.prod(target_shape)) * dtype.itemsize \
                    > self.capacity_bytes:
                self.num_oversize += 1
                return False
        if self.contains(key):
            return False
        jax, _ = _jax_numpy()
        padded = np.zeros(target_shape, dtype=dtype)
        padded[:valid] = clips[:valid]
        device_batch = jax.device_put(padded, self.device)
        return self.insert_device(key, device_batch, valid)

    def insert_rows(self, key: tuple, clips, valid: int) -> bool:
        """Insert a **host row extent**: exactly ``valid`` decoded rows,
        no bucket padding, no device transfer (ragged dispatch mode,
        rnb_tpu.ops.ragged).

        Under ragged row-pool dispatch there is no per-request padded
        device batch to reuse — hit rows are *filled into the pool*
        alongside fresh decodes and ride the pool's single transfer —
        so the cached value is the minimal thing that skips the decode:
        the raw rows. Copies out of the caller's buffer (which may be a
        staging-slot view about to recycle, same contract as
        :meth:`insert_host`), and charges exactly ``valid`` rows of
        bytes — a 1-clip entry costs 1/15th of its bucket-padded
        equivalent. The rows keep the loader's wire dtype (uint8
        pixels/planes, int16 packed dct coefficients).
        """
        valid = int(valid)
        rows = np.array(np.asarray(clips)[:valid])
        return self._insert(key, rows, valid, int(rows.nbytes))

    def snapshot(self) -> Dict[str, int]:
        """Point-in-time counter copy for reports."""
        with self._lock:
            return {
                "hits": self.num_hits,
                "misses": self.num_misses,
                "inserts": self.num_inserts,
                "evictions": self.num_evictions,
                "coalesced": self.num_coalesced,
                "oversize": self.num_oversize,
                "bytes_resident": self.resident_bytes,
                "entries": len(self._entries),
                "capacity_bytes": self.capacity_bytes,
            }


def aggregate_snapshots(snapshots: List[Dict[str, int]]) -> Dict[str, int]:
    """Sum per-instance cache snapshots into one job-wide record
    (every counter is additive, including bytes_resident — each
    instance owns its own budget)."""
    total = {"hits": 0, "misses": 0, "inserts": 0, "evictions": 0,
             "coalesced": 0, "oversize": 0, "bytes_resident": 0,
             "entries": 0, "capacity_bytes": 0}
    for snap in snapshots:
        for k in total:
            total[k] += int(snap.get(k, 0))
    return total


class InflightTable:
    """Key -> opaque in-flight record, for request coalescing.

    The loaders register the record of a decode they just kicked off;
    a later request for the same key finds it and parks on it instead
    of re-decoding. Records are removed when the decode is finalized
    (emitted, failed, or discarded) — a removed key simply means the
    next request consults the cache (where a successful decode has
    landed by then) or decodes afresh.
    """

    GUARDED_BY = {"_records": "_lock"}

    def __init__(self):
        self._lock = lockwitness.lock("InflightTable._lock")
        self._records: Dict[tuple, Any] = {}

    def get(self, key: tuple) -> Optional[Any]:
        with self._lock:
            return self._records.get(key)

    def put(self, key: tuple, record: Any) -> None:
        with self._lock:
            self._records[key] = record

    def pop(self, key: Optional[tuple]) -> None:
        if key is None:
            return
        with self._lock:
            self._records.pop(key, None)

    def __len__(self) -> int:
        with self._lock:
            return len(self._records)
