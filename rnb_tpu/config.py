"""Pipeline configuration: JSON schema, parsing and validation.

A pipeline config names a video-path iterator plus an ordered list of
*steps*; each step names a stage-model class and a list of *queue
groups* placing replicas on devices and wiring them to numbered
inter-stage queues. Any step/group key outside the reserved schema is
forwarded verbatim to the stage constructor — the open kwargs
passthrough that makes every model parameter configurable from JSON.

Schema and validation parity with the reference (benchmark.py:23-125):
same step/group structure, same queue-wiring rule (the out-queue set of
step i must equal the in-queue set of step i+1), same last-step
constraints (no multi-segment, no shared output tensors), same reserved
keyword handling. TPU-first changes: the placement key is ``devices``
(``gpus`` accepted as an alias for drop-in use of reference configs),
-1 places a group on the host, and the availability probe inspects
`jax.devices()` instead of NVML.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, List, Optional

from rnb_tpu.devices import DeviceSpec

RESERVED_KEYWORDS = [
    "model", "queue_groups", "num_shared_tensors", "num_segments",
    "in_queue", "out_queues", "devices", "gpus", "queue_selector",
    "async_dispatch", "max_retries", "retry_backoff_ms", "autotune",
    "replicas", "hedge_ms", "shard",
]

#: keys a step-level 'shard' object may carry
#: (rnb_tpu.parallel.shardplan)
SHARD_KEYWORDS = ["degree", "axis", "hbm_budget_mb"]

#: root-level keys with meaning to the runtime (everything else at the
#: root is rejected to catch typos like "overload_polcy")
ROOT_KEYWORDS = [
    "video_path_iterator", "pipeline", "overload_policy",
    "fault_containment", "fault_plan", "popularity", "autotune",
    "trace", "ragged", "pager", "handoff", "placement", "health",
    "deadline",
    "metrics", "devobs", "critpath", "whatif", "operator", "netedge",
    "lint",
    "_comment",
]

#: keys a root 'popularity' object may carry
POPULARITY_KEYWORDS = ["dist", "s", "universe"]

#: keys a root 'autotune' object may carry (rnb_tpu.autotune)
AUTOTUNE_KEYWORDS = ["enabled", "slo_ms", "ewma_alpha", "min_hold_ms",
                     "max_hold_ms", "buckets"]

#: keys a root 'trace' object may carry (rnb_tpu.trace)
TRACE_KEYWORDS = ["enabled", "sample_hz", "max_events"]

#: keys a root 'ragged' object may carry (rnb_tpu.ops.ragged)
RAGGED_KEYWORDS = ["enabled", "pool_rows"]

#: keys a root 'pager' object may carry (rnb_tpu.pager)
PAGER_KEYWORDS = ["enabled", "page_rows", "pool_mb", "feature_cache"]

#: keys a root 'handoff' object may carry (rnb_tpu.handoff)
HANDOFF_KEYWORDS = ["enabled", "mode"]

#: keys a root 'placement' object may carry (rnb_tpu.placement)
PLACEMENT_KEYWORDS = ["enabled", "mode", "plan"]

#: keys a root 'health' object may carry (rnb_tpu.health)
HEALTH_KEYWORDS = ["enabled", "suspect_after_ms", "open_after_ms",
                   "probe_interval_ms"]

#: keys a root 'deadline' object may carry (rnb_tpu.health)
DEADLINE_KEYWORDS = ["enabled", "budget_ms"]

#: keys a root 'metrics' object may carry (rnb_tpu.metrics)
METRICS_KEYWORDS = ["enabled", "interval_ms", "flight_recorder"]

#: keys a 'metrics.flight_recorder' object may carry
FLIGHT_RECORDER_KEYWORDS = ["enabled", "ring_events", "max_dumps",
                            "burn_threshold", "shed_spike_per_s",
                            "queue_saturation", "cooldown_s"]

#: keys a root 'devobs' object may carry (rnb_tpu.devobs)
DEVOBS_KEYWORDS = ["enabled", "capture_window_ms", "capture_on_trigger",
                   "max_captures", "capture_max_ops", "watermark_mb",
                   "sample_hz"]

#: keys a root 'critpath' object may carry (rnb_tpu.critpath)
CRITPATH_KEYWORDS = ["enabled"]

#: keys a root 'whatif' object may carry (rnb_tpu.whatif)
WHATIF_KEYWORDS = ["enabled"]

#: keys a root 'operator' object may carry (rnb_tpu.statusz)
OPERATOR_KEYWORDS = ["enabled", "port", "allow_actions", "sample_hz"]

#: keys a root 'lint' object may carry (runtime arms of the
#: rnb-lint analyzers; today just the RNB-C lock-order witness)
LINT_KEYWORDS = ["lock_witness"]

#: keys a root 'netedge' object may carry (rnb_tpu.netedge)
NETEDGE_KEYWORDS = ["enabled", "listen", "connect", "beat_ms",
                    "io_timeout_ms", "max_retries", "backoff_ms",
                    "resend_window", "spawn"]

#: Ring slots per stage instance when a step omits 'num_shared_tensors'
#: (reference control.py:8). Lives here (not control.py) so validation
#: can check the effective slot count at parse time.
DEFAULT_NUM_SHARED_TENSORS = 10


def _effective_shared_tensors(num_shared_tensors: Optional[int]) -> int:
    """The one defaulting rule for ring depth — used by parse-time
    validation and by StepConfig.effective_shared_tensors (which
    ChannelFabric allocation reads)."""
    return (num_shared_tensors if num_shared_tensors is not None
            else DEFAULT_NUM_SHARED_TENSORS)

DEFAULT_QUEUE_SELECTOR = "rnb_tpu.selector.RoundRobinSelector"

#: the selector replica expansion swaps in for the default on the
#: producer side of a replica-expanded edge (least-loaded routing)
REPLICA_QUEUE_SELECTOR = "rnb_tpu.selector.ReplicaSelector"


class ConfigError(ValueError):
    """Malformed pipeline configuration."""


def _expect(cond: bool, message: str) -> None:
    if not cond:
        raise ConfigError(message)


@dataclasses.dataclass
class GroupConfig:
    """One queue group: replicas on `devices` sharing one in-queue and a
    selector-routed set of out-queues."""

    devices: List[DeviceSpec]
    in_queue: Optional[int]
    out_queues: List[int]
    queue_selector: str
    extras: Dict[str, Any]

    @property
    def num_instances(self) -> int:
        return len(self.devices)


@dataclasses.dataclass
class StepConfig:
    """One pipeline step: a stage-model class fanned out over groups."""

    model: str
    groups: List[GroupConfig]
    num_segments: int
    num_shared_tensors: Optional[int]
    extras: Dict[str, Any]
    #: publish outputs without blocking on device completion (timing
    #: then measures dispatch, not compute — see rnb_tpu.runner)
    async_dispatch: bool = False
    #: containment retry budget for *transient* errors escaping this
    #: step's model call (rnb_tpu.faults taxonomy): up to max_retries
    #: re-attempts with retry_backoff_ms of sleep between them; an
    #: exhausted budget degrades the request to a contained permanent
    #: failure. Default 0 = fail on first transient.
    max_retries: int = 0
    retry_backoff_ms: float = 10.0
    #: False opts this step out of the job's load-adaptive batching
    #: controller (root 'autotune' key, rnb_tpu.autotune); the step
    #: then keeps its static batching knobs exactly as configured
    autotune: bool = True
    #: set on replica-expanded steps (step key ``replicas: N`` or a
    #: placement-apply plan): the per-replica lane queue indices, in
    #: replica order. The launcher builds the shared
    #: rnb_tpu.handoff.InflightDepths over these so the upstream
    #: ReplicaSelector routes least-loaded (rnb_tpu.selector).
    replica_queues: Optional[tuple] = None
    #: hedged re-dispatch threshold for dispatches INTO this
    #: replica-expanded step (rnb_tpu.health.HedgeGovernor): a
    #: positive millisecond count, or "p95x" for the governor's own
    #: settle-latency p95 estimate. None = no hedging.
    hedge_ms: Optional[object] = None

    @property
    def effective_shared_tensors(self) -> int:
        """Ring slots per producer instance after defaulting."""
        return _effective_shared_tensors(self.num_shared_tensors)

    def kwargs_for_group(self, group_idx: int) -> Dict[str, Any]:
        """Model-constructor kwargs: step extras overridden by group extras
        (reference benchmark.py:241-246)."""
        merged = dict(self.extras)
        merged.update(self.groups[group_idx].extras)
        return merged


@dataclasses.dataclass
class PipelineConfig:
    video_path_iterator: str
    steps: List[StepConfig]
    raw: Dict[str, Any]
    #: "abort" (reference parity: a full queue kills the job) or
    #: "shed" (a full queue drops the NEW request with a counted shed
    #: outcome and the pipeline keeps serving)
    overload_policy: str = "abort"
    #: when False, even *classified* transient/permanent errors abort
    #: the job like any other exception — strict reference semantics
    fault_containment: bool = True
    #: validated fault-injection plan dict (rnb_tpu.faults), or None;
    #: the RNB_FAULT_PLAN env JSON overrides it at launch
    fault_plan: Optional[Dict[str, Any]] = None
    #: validated request-popularity spec ({"dist": "zipf", "s": ...,
    #: "universe": ...}), or None for the base iterator's own order;
    #: the client wraps the video-path iterator with
    #: rnb_tpu.video_path_provider.ZipfPathIterator when set
    popularity: Optional[Dict[str, Any]] = None
    #: validated load-adaptive batching spec ({"enabled": ..,
    #: "slo_ms": .., "ewma_alpha": .., "min_hold_ms": ..,
    #: "max_hold_ms": .., "buckets": [..]}), or None; the launcher
    #: builds rnb_tpu.autotune.AutotuneSettings from it and every
    #: batching stage not opted out gets a BatchController
    autotune: Optional[Dict[str, Any]] = None
    #: validated ragged row-pool dispatch spec ({"enabled": ..,
    #: "pool_rows": ..}), or None; when enabled the launcher injects
    #: ``ragged``/``ragged_pool_rows`` kwargs into every
    #: ``SUPPORTS_RAGGED`` stage (rnb_tpu.ops.ragged): stages dispatch
    #: a flat row pool at ONE compiled shape with a rows_valid scalar
    #: and per-request segment offsets instead of padding to buckets
    ragged: Optional[Dict[str, Any]] = None
    #: validated page-allocator spec ({"enabled": .., "page_rows": ..,
    #: "pool_mb": .., "feature_cache": ..}), or None; when enabled the
    #: launcher builds one rnb_tpu.pager.Pager (fixed-size device row
    #: pages under one slab per arena) shared by every
    #: ``SUPPORTS_PAGER`` stage: clip-cache entries become page
    #: reference lists gathered on device at the consumption seam
    #: (zero host memcpy on hits), and — with ``feature_cache`` true —
    #: post-stage activation rows are cached on feature pages so a
    #: repeat request skips the backbone. Requires ``ragged`` (the
    #: gather seam is the one pool shape). Absent => byte-stable logs.
    pager: Optional[Dict[str, Any]] = None
    #: validated device-resident handoff spec ({"enabled": ..,
    #: "mode": "device"|"host"}), or None for the pre-handoff edge
    #: semantics (stage models re-home their own inputs, no
    #: accounting, byte-stable logs) — rnb_tpu.handoff
    handoff: Optional[Dict[str, Any]] = None
    #: validated placement-planner spec ({"enabled": .., "mode":
    #: "plan"|"apply", "plan": {"step<i>": replicas}}), or None; when
    #: set the launcher measures per-stage dispatch costs and writes
    #: the Placement: log-meta plan line (rnb_tpu.placement); "apply"
    #: additionally expands the named steps' replica counts at parse
    #: time exactly like a hand-written ``replicas`` key
    placement: Optional[Dict[str, Any]] = None
    #: validated lane-health / circuit-breaker spec ({"enabled": ..,
    #: "suspect_after_ms": .., "open_after_ms": ..,
    #: "probe_interval_ms": ..}), or None; when set the launcher
    #: builds one rnb_tpu.health.LaneHealthBoard per replica-expanded
    #: step — the upstream ReplicaSelector stops routing to open
    #: lanes, evicted lanes drain onto siblings, and log-meta gains
    #: the Health:/Health lanes: lines
    health: Optional[Dict[str, Any]] = None
    #: validated deadline-propagation spec ({"enabled": ..,
    #: "budget_ms": ..}), or None; when set the client stamps every
    #: request with an absolute deadline (budget seeded from
    #: autotune.slo_ms when unset) and every stage boundary sheds
    #: expired requests (shed reason deadline_expired) instead of
    #: computing doomed work — rnb_tpu.health
    deadline: Optional[Dict[str, Any]] = None
    #: validated live-metrics spec ({"enabled": .., "interval_ms": ..,
    #: "flight_recorder": {..}}), or None; when enabled the launcher
    #: builds an rnb_tpu.metrics.MetricsRegistry + background flusher
    #: (metrics.jsonl / metrics.prom / flight-<n>.json in the job
    #: dir) and log-meta gains the Metrics:/Slo: lines. Absent => no
    #: registry, byte-stable logs.
    metrics: Optional[Dict[str, Any]] = None
    #: validated device-observability spec ({"enabled": ..,
    #: "capture_window_ms": .., "capture_on_trigger": ..,
    #: "max_captures": .., "capture_max_ops": .., "watermark_mb": ..,
    #: "sample_hz": ..}), or None; when enabled the launcher builds an
    #: rnb_tpu.devobs.DevObsPlane (bounded jax.profiler capture
    #: windows merged into trace.json as device tracks, per-stage
    #: compute meters feeding the Compute: line and compute.* series,
    #: and the rnb_tpu.memledger HBM footprint ledger behind the
    #: Memory: line and memory.* gauges). Absent => no plane,
    #: byte-stable logs.
    devobs: Optional[Dict[str, Any]] = None
    #: validated critical-path extraction spec ({"enabled": ..}), or
    #: None; when enabled the launcher recovers every completed
    #: request's blocking chain from its TimeCard stamps
    #: (rnb_tpu.critpath) and log-meta gains the Critpath:/Critpath
    #: stages: lines plus a `# critpath` table trailer. Absent =>
    #: byte-stable logs.
    critpath: Optional[Dict[str, Any]] = None
    #: validated what-if engine spec ({"enabled": ..}), or None; when
    #: enabled (requires `metrics` — the service histograms ARE the
    #: calibration data) the launcher calibrates a per-stage queueing
    #: model at teardown (rnb_tpu.whatif) and log-meta gains the
    #: Whatif: line. Absent => byte-stable logs.
    whatif: Optional[Dict[str, Any]] = None
    #: validated operator-plane spec ({"enabled": .., "port": ..,
    #: "allow_actions": .., "sample_hz": ..}), or None; when enabled
    #: the launcher binds the rnb_tpu.statusz introspection/control
    #: HTTP server on loopback (port 0 = ephemeral; bound address
    #: written to logs/<job>/operator.json) and — with sample_hz > 0 —
    #: runs the rnb_tpu.stacksampler wall-clock stack sampler
    #: (stacks.folded artifact, sampler tracks in trace.json, Stacks:
    #: line). POST actions (/flight, /capture) stay 403 unless
    #: allow_actions is true. Absent => no server, no sampler,
    #: byte-stable logs.
    operator: Optional[Dict[str, Any]] = None
    #: validated cross-host ingest-edge spec ({"enabled": ..,
    #: "listen": .., "connect": .., "beat_ms": ..,
    #: "io_timeout_ms": .., "max_retries": .., "backoff_ms": ..,
    #: "resend_window": .., "spawn": ..}), or None; when enabled the
    #: launcher interposes the rnb_tpu.netedge transport between the
    #: client and step 0: requests route over a checksummed TCP frame
    #: protocol to an ingest peer process (spawn: true launches it)
    #: with a local fallback path behind a LaneHealthBoard, and
    #: log-meta gains the Net:/Net errors: lines. Absent => in-process
    #: queues, byte-stable logs.
    netedge: Optional[Dict[str, Any]] = None
    #: validated lint-runtime spec ({"lock_witness": ..}), or None;
    #: with lock_witness true the launcher enables the
    #: rnb_tpu.lockwitness lock-order witness BEFORE pipeline
    #: construction (the witness wraps locks at creation), log-meta
    #: gains the Locks:/Lock edges: lines, and parse --check holds
    #: observed acquisition-order edges to a subset of the static
    #: RNB-C lock-order graph with zero violations. Absent or false
    #: => plain threading locks, byte-stable logs.
    lint: Optional[Dict[str, Any]] = None
    #: validated tracing spec ({"enabled": .., "sample_hz": ..,
    #: "max_events": ..}), or None; when enabled the launcher builds
    #: an rnb_tpu.trace.Tracer, every thread role emits named spans,
    #: a background sampler records queue/slot occupancy, and the job
    #: dir gains a Perfetto-loadable trace.json plus per-request
    #: phase attribution (Phases: line, `# phases` trailers). Absent
    #: => logs are byte-stable with the pre-trace schema.
    trace: Optional[Dict[str, Any]] = None

    @property
    def num_steps(self) -> int:
        return len(self.steps)

    @property
    def num_runners(self) -> int:
        return sum(g.num_instances for s in self.steps for g in s.groups)

    def all_devices(self) -> List[DeviceSpec]:
        return [d for s in self.steps for g in s.groups for d in g.devices]

    def check_devices(self) -> None:
        """Resolve every placement against the visible JAX devices."""
        from rnb_tpu.devices import check_devices
        check_devices(self.all_devices())


def _expand_replicas(pipeline: list, placement: Optional[Dict[str, Any]]
                     ) -> tuple:
    """Replica-sharded serving (PR 9): expand every step declaring
    ``replicas: N`` (or named by an apply-mode placement plan) into N
    queue groups — one per replica, each with its own fresh lane queue
    and an equal slice of the step's device list (the per-replica
    sub-mesh) — and rewire the upstream producers onto the lanes with
    the least-loaded ReplicaSelector swapped in for the default.

    Returns ``(expanded_pipeline, {step_idx: (lane queue indices)})``;
    the input list is never mutated (``config.raw`` keeps the
    as-written form). Expansion happens at parse time so everything
    downstream — fabric wiring, the static graph checker, the job-dir
    config copy — sees one canonical multi-group form.
    """
    import copy

    plan: Dict[int, int] = {}
    if placement is not None and placement.get("enabled", True) \
            and placement.get("mode", "plan") == "apply":
        for key, val in (placement.get("plan") or {}).items():
            plan[int(key[4:])] = int(val)

    wants: Dict[int, Any] = {}
    for step_idx, step in enumerate(pipeline):
        if not isinstance(step, dict):
            continue
        n = step.get("replicas")
        if n is None:
            # an explicit per-step ``replicas`` wins over the plan —
            # the plan is advice, the step key is the operator's word
            n = plan.get(step_idx)
        if n is not None:
            wants[step_idx] = n

    for step_idx, n in wants.items():
        _expect(isinstance(n, int) and not isinstance(n, bool)
                and n >= 1,
                "pipeline step %d: 'replicas' must be a positive "
                "integer, got %r" % (step_idx, n))
    if not wants:
        return pipeline, {}

    pipeline = copy.deepcopy(pipeline)
    used = set()
    for step in pipeline:
        if not isinstance(step, dict):
            continue
        for g in step.get("queue_groups") or []:
            if not isinstance(g, dict):
                continue
            if isinstance(g.get("in_queue"), int):
                used.add(g["in_queue"])
            for q in g.get("out_queues") or []:
                if isinstance(q, int):
                    used.add(q)
    next_q = max(used) + 1 if used else 0

    replica_queues: Dict[int, tuple] = {}
    for step_idx in sorted(wants):
        n = wants[step_idx]
        step = pipeline[step_idx]
        step.pop("replicas", None)
        # the structural constraints hold for EVERY declared replicas
        # key, n == 1 included — otherwise an operator iterating
        # replica counts would hit a "regression" at n=2 for a
        # topology that was invalid (but silently accepted) at n=1
        where = "pipeline step %d" % step_idx
        _expect(step_idx > 0,
                "%s: 'replicas' needs a routable in_queue; the first "
                "step reads the shared filename queue — replicate it "
                "by listing more devices instead" % where)
        _expect(step.get("num_segments", 1) == 1,
                "%s: 'replicas' cannot be combined with "
                "'num_segments' > 1 (segment siblings must reach one "
                "aggregator, which per-replica lanes cannot "
                "guarantee)" % where)
        groups = step.get("queue_groups")
        _expect(isinstance(groups, list) and len(groups) == 1
                and isinstance(groups[0], dict),
                "%s: 'replicas' requires exactly one queue group to "
                "expand" % where)
        g = groups[0]
        dev_key = ("devices" if "devices" in g
                   else "gpus" if "gpus" in g else None)
        _expect(dev_key is not None,
                "%s, queue group 0 needs a 'devices' list" % where)
        devices = g[dev_key]
        _expect(isinstance(devices, list) and devices
                and len(devices) % n == 0,
                "%s: 'replicas'=%d must evenly divide the %d-entry "
                "device list — each replica owns an equal sub-mesh"
                % (where, n, len(devices) if isinstance(devices, list)
                   else 0))
        orig_in = g.get("in_queue")
        _expect(isinstance(orig_in, int),
                "%s, queue group 0 needs an integer 'in_queue'" % where)
        if n == 1:
            # validated but structurally a no-op: the existing queue
            # IS the single lane, so no rewiring (and no selector
            # swap) happens
            continue

        lanes = list(range(next_q, next_q + n))
        next_q += n
        # the per-replica sub-mesh rule lives with the mesh factoring
        # (rnb_tpu.parallel.mesh): contiguous equal device slices
        from rnb_tpu.parallel.mesh import carve_replicas
        new_groups = []
        for lane, sub_mesh in zip(lanes, carve_replicas(devices, n)):
            ng = copy.deepcopy(g)
            ng[dev_key] = sub_mesh
            ng["in_queue"] = lane
            new_groups.append(ng)
        step["queue_groups"] = new_groups

        rewired = False
        for ug in pipeline[step_idx - 1].get("queue_groups") or []:
            if not isinstance(ug, dict):
                continue
            outs = list(ug.get("out_queues") or [])
            if orig_in not in outs:
                continue
            pos = outs.index(orig_in)
            ug["out_queues"] = outs[:pos] + lanes + outs[pos + 1:]
            if ug.get("queue_selector",
                      DEFAULT_QUEUE_SELECTOR) == DEFAULT_QUEUE_SELECTOR:
                ug["queue_selector"] = REPLICA_QUEUE_SELECTOR
            rewired = True
        _expect(rewired,
                "%s: no upstream queue group names out-queue %d, so "
                "the replica lanes cannot be wired" % (where, orig_in))
        replica_queues[step_idx] = tuple(lanes)
    return pipeline, replica_queues


def _expand_shard(pipeline: list) -> list:
    """Intra-stage tensor parallelism (rnb_tpu.parallel.shardplan):
    translate every step's ``shard: {degree, axis, hbm_budget_mb}``
    key into per-group constructor kwargs. Runs AFTER replica
    expansion, so the two compose replica-major: ``replicas: N``
    first carves the step's device list into N equal lane sub-meshes,
    then each lane's sub-mesh must be exactly ``degree`` devices —
    its shard ring. The group keeps ONE primary device (the executor
    spawns one instance per listed device; a shard ring is one
    executable over k devices, not k executors) and the full ring
    travels to the stage as ``shard_devices``.

    Returns the (possibly copied) pipeline; the input list is never
    mutated when a shard key is present (``config.raw`` keeps the
    as-written form).
    """
    import copy

    if not any(isinstance(step, dict) and step.get("shard") is not None
               for step in pipeline):
        return pipeline
    pipeline = copy.deepcopy(pipeline)
    for step_idx, step in enumerate(pipeline):
        if not isinstance(step, dict):
            continue
        shard = step.get("shard")
        if shard is None:
            continue
        where = "pipeline step %d" % step_idx
        _expect(isinstance(shard, dict),
                "%s: 'shard' must be an object" % where)
        unknown = sorted(set(shard) - set(SHARD_KEYWORDS))
        _expect(not unknown,
                "%s: 'shard' has unknown key(s) %s — keys are %s"
                % (where, unknown, SHARD_KEYWORDS))
        degree = shard.get("degree")
        _expect(isinstance(degree, int) and not isinstance(degree, bool)
                and degree >= 1,
                "%s: 'shard.degree' must be a positive integer, got %r"
                % (where, degree))
        axis = shard.get("axis", "tp")
        _expect(isinstance(axis, str) and axis,
                "%s: 'shard.axis' must be a non-empty string, got %r"
                % (where, axis))
        budget = shard.get("hbm_budget_mb")
        _expect(budget is None
                or (isinstance(budget, (int, float))
                    and not isinstance(budget, bool) and budget > 0),
                "%s: 'shard.hbm_budget_mb' must be a positive number, "
                "got %r" % (where, budget))
        _expect(step.get("num_segments", 1) == 1,
                "%s: 'shard' cannot be combined with 'num_segments' "
                "> 1 (segment siblings would each need their own "
                "ring)" % where)
        for group_idx, group in enumerate(step.get("queue_groups")
                                          or []):
            gwhere = "%s, queue group %d" % (where, group_idx)
            _expect(isinstance(group, dict),
                    "%s must be an object" % gwhere)
            dev_key = ("devices" if "devices" in group
                       else "gpus" if "gpus" in group else None)
            _expect(dev_key is not None,
                    "%s needs a 'devices' list" % gwhere)
            devices = group[dev_key]
            _expect(isinstance(devices, list)
                    and len(devices) == degree,
                    "%s: 'shard.degree'=%d needs exactly that many "
                    "devices per lane (got %d) — with 'replicas' the "
                    "step's device list must total replicas x degree"
                    % (gwhere, degree,
                       len(devices) if isinstance(devices, list)
                       else 0))
            _expect(all(d != -1 for d in devices),
                    "%s: 'shard' rings cannot include the host "
                    "(-1)" % gwhere)
            # one primary device -> one executor instance; the ring
            # rides the open kwargs passthrough to the stage
            group[dev_key] = devices[:1]
            group["shard_devices"] = list(devices)
            group["shard_degree"] = degree
            group["shard_axis"] = axis
            if budget is not None:
                group["shard_hbm_budget_mb"] = budget
    return pipeline


def load_config(path: str) -> PipelineConfig:
    with open(path, "r") as f:
        try:
            raw = json.load(f)
        except json.JSONDecodeError as e:
            raise ConfigError("config file %s is not valid JSON: %s"
                              % (path, e)) from e
    return parse_config(raw)


def parse_config(raw: Dict[str, Any]) -> PipelineConfig:
    _expect(isinstance(raw, dict), "config root must be a JSON object")
    _expect("video_path_iterator" in raw,
            "config is missing 'video_path_iterator'")
    _expect(isinstance(raw["video_path_iterator"], str),
            "'video_path_iterator' must be a class-path string")
    _expect("pipeline" in raw, "config is missing 'pipeline'")
    pipeline = raw["pipeline"]
    _expect(isinstance(pipeline, list) and pipeline,
            "'pipeline' must be a non-empty list of steps")

    unknown_root = sorted(set(raw) - set(ROOT_KEYWORDS))
    _expect(not unknown_root,
            "config has unknown root key(s) %s — root keys are %s"
            % (unknown_root, sorted(k for k in ROOT_KEYWORDS
                                    if k != "_comment")))

    overload_policy = raw.get("overload_policy", "abort")
    _expect(overload_policy in ("abort", "shed"),
            "'overload_policy' must be \"abort\" or \"shed\", got %r"
            % (overload_policy,))
    fault_containment = raw.get("fault_containment", True)
    _expect(isinstance(fault_containment, bool),
            "'fault_containment' must be a boolean")
    popularity = raw.get("popularity")
    if popularity is not None:
        _expect(isinstance(popularity, dict),
                "'popularity' must be an object")
        unknown_pop = sorted(set(popularity) - set(POPULARITY_KEYWORDS))
        _expect(not unknown_pop,
                "'popularity' has unknown key(s) %s — keys are %s"
                % (unknown_pop, POPULARITY_KEYWORDS))
        _expect(popularity.get("dist", "zipf") == "zipf",
                "'popularity.dist' must be \"zipf\" (the one supported "
                "distribution), got %r" % (popularity.get("dist"),))
        s = popularity.get("s", 1.0)
        _expect(isinstance(s, (int, float)) and not isinstance(s, bool)
                and s >= 0,
                "'popularity.s' must be a non-negative number, got %r"
                % (s,))
        universe = popularity.get("universe")
        _expect(universe is None
                or (isinstance(universe, int)
                    and not isinstance(universe, bool) and universe >= 1),
                "'popularity.universe' must be a positive integer, got %r"
                % (universe,))

    autotune = raw.get("autotune")
    if autotune is not None:
        _expect(isinstance(autotune, dict), "'autotune' must be an object")
        unknown_at = sorted(set(autotune) - set(AUTOTUNE_KEYWORDS))
        _expect(not unknown_at,
                "'autotune' has unknown key(s) %s — keys are %s"
                % (unknown_at, AUTOTUNE_KEYWORDS))
        _expect(isinstance(autotune.get("enabled", True), bool),
                "'autotune.enabled' must be a boolean")

        def _number(key, default, minimum, strict=False):
            val = autotune.get(key, default)
            ok = (isinstance(val, (int, float))
                  and not isinstance(val, bool)
                  and (val > minimum if strict else val >= minimum))
            _expect(ok, "'autotune.%s' must be a number %s %g, got %r"
                    % (key, ">" if strict else ">=", minimum, val))
            return float(val)

        _number("slo_ms", 50.0, 0, strict=True)
        alpha = _number("ewma_alpha", 0.2, 0, strict=True)
        _expect(alpha <= 1.0,
                "'autotune.ewma_alpha' must be in (0, 1], got %r"
                % (alpha,))
        min_hold = _number("min_hold_ms", 0.5, 0)
        max_hold = _number("max_hold_ms", max(min_hold, 50.0), 0)
        _expect(max_hold >= min_hold,
                "'autotune.max_hold_ms' (%g) must be >= "
                "'autotune.min_hold_ms' (%g)" % (max_hold, min_hold))
        buckets = autotune.get("buckets")
        if buckets is not None:
            _expect(isinstance(buckets, list) and buckets
                    and all(isinstance(b, int) and not isinstance(b, bool)
                            and b >= 1 for b in buckets)
                    and len(set(buckets)) == len(buckets),
                    "'autotune.buckets' must be a non-empty list of "
                    "distinct positive row counts, got %r" % (buckets,))

    trace = raw.get("trace")
    if trace is not None:
        _expect(isinstance(trace, dict), "'trace' must be an object")
        unknown_tr = sorted(set(trace) - set(TRACE_KEYWORDS))
        _expect(not unknown_tr,
                "'trace' has unknown key(s) %s — keys are %s"
                % (unknown_tr, TRACE_KEYWORDS))
        _expect(isinstance(trace.get("enabled", True), bool),
                "'trace.enabled' must be a boolean")
        sample_hz = trace.get("sample_hz", 20.0)
        _expect(isinstance(sample_hz, (int, float))
                and not isinstance(sample_hz, bool) and sample_hz >= 0,
                "'trace.sample_hz' must be a non-negative number "
                "(0 disables the occupancy sampler), got %r"
                % (sample_hz,))
        max_events = trace.get("max_events", 200000)
        _expect(isinstance(max_events, int)
                and not isinstance(max_events, bool) and max_events >= 1,
                "'trace.max_events' must be a positive integer, got %r"
                % (max_events,))

    ragged = raw.get("ragged")
    if ragged is not None:
        _expect(isinstance(ragged, dict), "'ragged' must be an object")
        unknown_rg = sorted(set(ragged) - set(RAGGED_KEYWORDS))
        _expect(not unknown_rg,
                "'ragged' has unknown key(s) %s — keys are %s"
                % (unknown_rg, RAGGED_KEYWORDS))
        _expect(isinstance(ragged.get("enabled", True), bool),
                "'ragged.enabled' must be a boolean")
        pool_rows = ragged.get("pool_rows")
        _expect(pool_rows is None
                or (isinstance(pool_rows, int)
                    and not isinstance(pool_rows, bool)
                    and pool_rows >= 1),
                "'ragged.pool_rows' must be a positive integer "
                "(the flat row pool's capacity), got %r" % (pool_rows,))
        if ragged.get("enabled", True):
            # the pool is ONE fixed shape; a row-split into segments
            # would need per-segment pool shapes — reject like the
            # row_buckets/segments combination above
            _expect(all(step.get("num_segments", 1) == 1
                        for step in pipeline if isinstance(step, dict)),
                    "'ragged' cannot be combined with 'num_segments' "
                    "> 1: the pool is one fixed dispatch shape")

    pager = raw.get("pager")
    if pager is not None:
        _expect(isinstance(pager, dict), "'pager' must be an object")
        unknown_pg = sorted(set(pager) - set(PAGER_KEYWORDS))
        _expect(not unknown_pg,
                "'pager' has unknown key(s) %s — keys are %s"
                % (unknown_pg, PAGER_KEYWORDS))
        _expect(isinstance(pager.get("enabled", True), bool),
                "'pager.enabled' must be a boolean")
        page_rows = pager.get("page_rows")
        _expect(page_rows is None
                or (isinstance(page_rows, int)
                    and not isinstance(page_rows, bool)
                    and page_rows >= 1),
                "'pager.page_rows' must be a positive integer (rows "
                "per fixed-size page), got %r" % (page_rows,))
        pool_mb = pager.get("pool_mb")
        _expect(pool_mb is None
                or (isinstance(pool_mb, (int, float))
                    and not isinstance(pool_mb, bool)
                    and pool_mb > 0),
                "'pager.pool_mb' must be a positive number (per-arena "
                "page budget; omit to size from the cache budget), "
                "got %r" % (pool_mb,))
        _expect(isinstance(pager.get("feature_cache", False), bool),
                "'pager.feature_cache' must be a boolean")
        if pager.get("enabled", True):
            # the gather-from-pages seam overlays rows onto the ONE
            # ragged pool shape after its transfer; bucketed emissions
            # have no single dispatch pool to gather into
            _expect(isinstance(ragged, dict)
                    and ragged.get("enabled", True),
                    "'pager' requires 'ragged': paged cache hits "
                    "gather into the ragged row pool at its one "
                    "compiled shape")

    handoff = raw.get("handoff")
    if handoff is not None:
        _expect(isinstance(handoff, dict), "'handoff' must be an object")
        unknown_ho = sorted(set(handoff) - set(HANDOFF_KEYWORDS))
        _expect(not unknown_ho,
                "'handoff' has unknown key(s) %s — keys are %s"
                % (unknown_ho, HANDOFF_KEYWORDS))
        _expect(isinstance(handoff.get("enabled", True), bool),
                "'handoff.enabled' must be a boolean")
        mode = handoff.get("mode", "device")
        _expect(mode in ("device", "host"),
                "'handoff.mode' must be \"device\" (device-resident "
                "edges) or \"host\" (the explicit host round-trip "
                "baseline arm), got %r" % (mode,))

    placement = raw.get("placement")
    if placement is not None:
        _expect(isinstance(placement, dict),
                "'placement' must be an object")
        unknown_pl = sorted(set(placement) - set(PLACEMENT_KEYWORDS))
        _expect(not unknown_pl,
                "'placement' has unknown key(s) %s — keys are %s"
                % (unknown_pl, PLACEMENT_KEYWORDS))
        _expect(isinstance(placement.get("enabled", True), bool),
                "'placement.enabled' must be a boolean")
        pl_mode = placement.get("mode", "plan")
        _expect(pl_mode in ("plan", "apply"),
                "'placement.mode' must be \"plan\" (report the "
                "measured-cost plan) or \"apply\" (apply 'plan' replica "
                "counts at launch), got %r" % (pl_mode,))
        plan = placement.get("plan")
        if pl_mode == "apply":
            _expect(isinstance(plan, dict) and plan,
                    "'placement.mode' \"apply\" needs a non-empty "
                    "'plan' object ({\"step<i>\": replicas})")
        if plan is not None:
            _expect(isinstance(plan, dict), "'placement.plan' must be "
                    "an object")
            for key, val in plan.items():
                ok_key = (isinstance(key, str) and key.startswith("step")
                          and key[4:].isdigit()
                          and int(key[4:]) < len(pipeline))
                _expect(ok_key,
                        "'placement.plan' keys must be \"step<i>\" with "
                        "i inside the pipeline (0..%d), got %r"
                        % (len(pipeline) - 1, key))
                _expect(isinstance(val, int)
                        and not isinstance(val, bool) and val >= 1,
                        "'placement.plan.%s' must be a positive integer "
                        "replica count, got %r" % (key, val))

    health = raw.get("health")
    if health is not None:
        _expect(isinstance(health, dict), "'health' must be an object")
        unknown_h = sorted(set(health) - set(HEALTH_KEYWORDS))
        _expect(not unknown_h,
                "'health' has unknown key(s) %s — keys are %s"
                % (unknown_h, HEALTH_KEYWORDS))
        _expect(isinstance(health.get("enabled", True), bool),
                "'health.enabled' must be a boolean")
        for key in ("suspect_after_ms", "open_after_ms",
                    "probe_interval_ms"):
            val = health.get(key)
            _expect(val is None
                    or (isinstance(val, (int, float))
                        and not isinstance(val, bool) and val > 0),
                    "'health.%s' must be a positive number, got %r"
                    % (key, val))
        if health.get("enabled", True):
            # the same defaulting the runtime applies — a config whose
            # thresholds invert must fail at parse time, not at launch
            try:
                from rnb_tpu.health import HealthSettings
                HealthSettings.from_config(health)
            except ValueError as e:
                raise ConfigError("invalid 'health': %s" % e) from e

    deadline = raw.get("deadline")
    if deadline is not None:
        _expect(isinstance(deadline, dict),
                "'deadline' must be an object")
        unknown_d = sorted(set(deadline) - set(DEADLINE_KEYWORDS))
        _expect(not unknown_d,
                "'deadline' has unknown key(s) %s — keys are %s"
                % (unknown_d, DEADLINE_KEYWORDS))
        _expect(isinstance(deadline.get("enabled", True), bool),
                "'deadline.enabled' must be a boolean")
        budget = deadline.get("budget_ms")
        _expect(budget is None
                or (isinstance(budget, (int, float))
                    and not isinstance(budget, bool) and budget > 0),
                "'deadline.budget_ms' must be a positive number "
                "(defaults to autotune.slo_ms when autotune is "
                "configured), got %r" % (budget,))

    metrics = raw.get("metrics")
    if metrics is not None:
        _expect(isinstance(metrics, dict), "'metrics' must be an object")
        unknown_m = sorted(set(metrics) - set(METRICS_KEYWORDS))
        _expect(not unknown_m,
                "'metrics' has unknown key(s) %s — keys are %s"
                % (unknown_m, METRICS_KEYWORDS))
        _expect(isinstance(metrics.get("enabled", True), bool),
                "'metrics.enabled' must be a boolean")
        interval = metrics.get("interval_ms", 250.0)
        _expect(isinstance(interval, (int, float))
                and not isinstance(interval, bool) and interval > 0,
                "'metrics.interval_ms' must be a positive number, "
                "got %r" % (interval,))
        fr = metrics.get("flight_recorder")
        if fr is not None and not isinstance(fr, bool):
            _expect(isinstance(fr, dict),
                    "'metrics.flight_recorder' must be a boolean or "
                    "an object")
            unknown_fr = sorted(set(fr) - set(FLIGHT_RECORDER_KEYWORDS))
            _expect(not unknown_fr,
                    "'metrics.flight_recorder' has unknown key(s) %s "
                    "— keys are %s" % (unknown_fr,
                                       FLIGHT_RECORDER_KEYWORDS))
            _expect(isinstance(fr.get("enabled", True), bool),
                    "'metrics.flight_recorder.enabled' must be a "
                    "boolean")
            for key in ("ring_events", "max_dumps"):
                val = fr.get(key)
                _expect(val is None
                        or (isinstance(val, int)
                            and not isinstance(val, bool) and val >= 1),
                        "'metrics.flight_recorder.%s' must be a "
                        "positive integer, got %r" % (key, val))
            for key in ("burn_threshold", "shed_spike_per_s",
                        "cooldown_s"):
                val = fr.get(key)
                _expect(val is None
                        or (isinstance(val, (int, float))
                            and not isinstance(val, bool) and val > 0),
                        "'metrics.flight_recorder.%s' must be a "
                        "positive number, got %r" % (key, val))
            sat = fr.get("queue_saturation")
            _expect(sat is None
                    or (isinstance(sat, (int, float))
                        and not isinstance(sat, bool)
                        and 0 < sat <= 1),
                    "'metrics.flight_recorder.queue_saturation' must "
                    "be a fraction in (0, 1], got %r" % (sat,))

    devobs = raw.get("devobs")
    if devobs is not None:
        _expect(isinstance(devobs, dict), "'devobs' must be an object")
        unknown_do = sorted(set(devobs) - set(DEVOBS_KEYWORDS))
        _expect(not unknown_do,
                "'devobs' has unknown key(s) %s — keys are %s"
                % (unknown_do, DEVOBS_KEYWORDS))
        _expect(isinstance(devobs.get("enabled", True), bool),
                "'devobs.enabled' must be a boolean")
        _expect(isinstance(devobs.get("capture_on_trigger", True),
                           bool),
                "'devobs.capture_on_trigger' must be a boolean")
        window = devobs.get("capture_window_ms")
        _expect(window is None
                or (isinstance(window, (int, float))
                    and not isinstance(window, bool) and window >= 0),
                "'devobs.capture_window_ms' must be a non-negative "
                "number (0 disables the configured window; forced/"
                "trigger captures still run), got %r" % (window,))
        for key in ("max_captures", "capture_max_ops"):
            val = devobs.get(key)
            _expect(val is None
                    or (isinstance(val, int)
                        and not isinstance(val, bool) and val >= 1),
                    "'devobs.%s' must be a positive integer, got %r"
                    % (key, val))
        for key in ("watermark_mb", "sample_hz"):
            val = devobs.get(key)
            _expect(val is None
                    or (isinstance(val, (int, float))
                        and not isinstance(val, bool) and val > 0),
                    "'devobs.%s' must be a positive number, got %r"
                    % (key, val))

    critpath = raw.get("critpath")
    if critpath is not None:
        _expect(isinstance(critpath, dict),
                "'critpath' must be an object")
        unknown_cp = sorted(set(critpath) - set(CRITPATH_KEYWORDS))
        _expect(not unknown_cp,
                "'critpath' has unknown key(s) %s — keys are %s"
                % (unknown_cp, CRITPATH_KEYWORDS))
        _expect(isinstance(critpath.get("enabled", True), bool),
                "'critpath.enabled' must be a boolean")

    whatif = raw.get("whatif")
    if whatif is not None:
        _expect(isinstance(whatif, dict), "'whatif' must be an object")
        unknown_wi = sorted(set(whatif) - set(WHATIF_KEYWORDS))
        _expect(not unknown_wi,
                "'whatif' has unknown key(s) %s — keys are %s"
                % (unknown_wi, WHATIF_KEYWORDS))
        _expect(isinstance(whatif.get("enabled", True), bool),
                "'whatif.enabled' must be a boolean")
        if whatif.get("enabled", True):
            _expect(isinstance(metrics, dict)
                    and metrics.get("enabled", True),
                    "'whatif' requires an enabled root 'metrics' key "
                    "— the per-stage service histograms streamed to "
                    "metrics.jsonl are the calibration data")

    operator = raw.get("operator")
    if operator is not None:
        _expect(isinstance(operator, dict),
                "'operator' must be an object")
        unknown_op = sorted(set(operator) - set(OPERATOR_KEYWORDS))
        _expect(not unknown_op,
                "'operator' has unknown key(s) %s — keys are %s"
                % (unknown_op, OPERATOR_KEYWORDS))
        _expect(isinstance(operator.get("enabled", True), bool),
                "'operator.enabled' must be a boolean")
        _expect(isinstance(operator.get("allow_actions", False), bool),
                "'operator.allow_actions' must be a boolean (false "
                "keeps POST /flight and /capture 403-gated)")
        port = operator.get("port", 0)
        _expect(isinstance(port, int) and not isinstance(port, bool)
                and 0 <= port <= 65535,
                "'operator.port' must be an integer in [0, 65535] "
                "(0 binds an ephemeral port, recorded in "
                "operator.json), got %r" % (port,))
        op_hz = operator.get("sample_hz")
        _expect(op_hz is None
                or (isinstance(op_hz, (int, float))
                    and not isinstance(op_hz, bool) and op_hz >= 0),
                "'operator.sample_hz' must be a non-negative number "
                "(0 disables the wall-clock stack sampler), got %r"
                % (op_hz,))

    netedge = raw.get("netedge")
    if netedge is not None:
        _expect(isinstance(netedge, dict),
                "'netedge' must be an object")
        unknown_ne = sorted(set(netedge) - set(NETEDGE_KEYWORDS))
        _expect(not unknown_ne,
                "'netedge' has unknown key(s) %s — keys are %s"
                % (unknown_ne, NETEDGE_KEYWORDS))
        _expect(isinstance(netedge.get("enabled", True), bool),
                "'netedge.enabled' must be a boolean")
        _expect(isinstance(netedge.get("spawn", False), bool),
                "'netedge.spawn' must be a boolean")
        for key in ("listen", "connect"):
            val = netedge.get(key)
            _expect(val is None or isinstance(val, str),
                    "'netedge.%s' must be a host:port string, got %r"
                    % (key, val))
        for key in ("beat_ms", "io_timeout_ms", "backoff_ms"):
            val = netedge.get(key)
            _expect(val is None
                    or (isinstance(val, (int, float))
                        and not isinstance(val, bool) and val >= 0),
                    "'netedge.%s' must be a non-negative number, "
                    "got %r" % (key, val))
        for key in ("max_retries", "resend_window"):
            val = netedge.get(key)
            _expect(val is None
                    or (isinstance(val, int)
                        and not isinstance(val, bool) and val >= 1),
                    "'netedge.%s' must be a positive integer, got %r"
                    % (key, val))
        if netedge.get("enabled", True):
            # the same defaulting the runtime applies — a timeout
            # shorter than the heartbeat, or neither connect nor
            # spawn, must fail at parse time, not at launch
            try:
                from rnb_tpu.netedge import NetEdgeSettings
                NetEdgeSettings.from_config(netedge)
            except ValueError as e:
                raise ConfigError("invalid 'netedge': %s" % e) from e

    lint = raw.get("lint")
    if lint is not None:
        _expect(isinstance(lint, dict), "'lint' must be an object")
        unknown_lint = sorted(set(lint) - set(LINT_KEYWORDS))
        _expect(not unknown_lint,
                "'lint' has unknown key(s) %s — keys are %s"
                % (unknown_lint, LINT_KEYWORDS))
        _expect(isinstance(lint.get("lock_witness", False), bool),
                "'lint.lock_witness' must be a boolean")

    fault_plan = raw.get("fault_plan")
    if fault_plan is not None:
        from rnb_tpu.faults import FaultPlan
        try:
            # structural validation + step indices against THIS
            # pipeline (a typo'd step would silently never fire)
            FaultPlan(fault_plan).check_steps(len(pipeline))
        except ValueError as e:
            raise ConfigError("invalid 'fault_plan': %s" % e) from e

    # replica-sharded serving: expand `replicas` steps (and an
    # apply-mode placement plan) into per-replica lane groups BEFORE
    # any wiring validation, so the expanded form is the one canonical
    # topology everything checks and builds
    pipeline, replica_queues = _expand_replicas(pipeline, placement)
    # intra-stage sharding composes replica-major: each replica lane's
    # equal device slice becomes that lane's shard ring
    pipeline = _expand_shard(pipeline)

    steps: List[StepConfig] = []
    prev_out_queues: Optional[set] = None
    for step_idx, step_raw in enumerate(pipeline):
        first = step_idx == 0
        final = step_idx == len(pipeline) - 1
        where = "pipeline step %d" % step_idx
        _expect(isinstance(step_raw, dict), "%s must be an object" % where)
        _expect(isinstance(step_raw.get("model"), str),
                "%s needs a 'model' class-path string" % where)
        groups_raw = step_raw.get("queue_groups")
        _expect(isinstance(groups_raw, list) and groups_raw,
                "%s needs a non-empty 'queue_groups' list" % where)

        num_segments = step_raw.get("num_segments", 1)
        _expect(isinstance(num_segments, int) and num_segments >= 1,
                "%s: 'num_segments' must be a positive integer" % where)
        _expect(not (final and num_segments != 1),
                "the last step may not have multiple segments")
        # variable bucketed row counts would make the per-segment split
        # shapes unpredictable — every first-seen shape is a silent XLA
        # recompile inside the measured window
        _expect(not (num_segments > 1 and "row_buckets" in step_raw),
                "%s: 'row_buckets' cannot be combined with "
                "'num_segments' > 1" % where)

        # transfer-pipeline knobs (rnb_tpu.staging) are open kwargs —
        # they flow to the stage constructor like any extra — but
        # their types are validated here so a typo'd value fails at
        # parse time, not as a mid-run constructor error
        staging_slots = step_raw.get("staging_slots")
        _expect(staging_slots is None
                or (isinstance(staging_slots, int)
                    and not isinstance(staging_slots, bool)
                    and staging_slots >= 0),
                "%s: 'staging_slots' must be a non-negative integer "
                "(0 disables zero-copy staging), got %r"
                % (where, staging_slots))
        transfer_async = step_raw.get("transfer_async")
        _expect(transfer_async is None or isinstance(transfer_async, bool),
                "%s: 'transfer_async' must be a boolean, got %r"
                % (where, transfer_async))
        fallback_threads = step_raw.get("fallback_decode_threads")
        _expect(fallback_threads is None
                or (isinstance(fallback_threads, int)
                    and not isinstance(fallback_threads, bool)
                    and fallback_threads >= 1),
                "%s: 'fallback_decode_threads' must be a positive "
                "integer, got %r" % (where, fallback_threads))

        num_shared_tensors = step_raw.get("num_shared_tensors")
        if num_shared_tensors is not None:
            _expect(isinstance(num_shared_tensors, int)
                    and num_shared_tensors >= 1,
                    "%s: 'num_shared_tensors' must be a positive integer"
                    % where)
            _expect(not final,
                    "the last step does not need shared output tensors")

        # A producer writes every segment of a batch into its own ring
        # slot before publishing any Signal (runner.py), so a ring with
        # fewer slots than segments blocks forever on a slot whose
        # consumer was never told about it — a silent self-deadlock the
        # 1800 s barrier timeout would otherwise be the first sign of.
        # Deliberately conservative: a ring-less step (output_shape None,
        # knowable only after loading the model class — which parse-time
        # validation must not do) cannot deadlock, but is still rejected
        # here; declare num_shared_tensors >= num_segments to get past
        # (harmless when no ring is allocated).
        effective_slots = _effective_shared_tensors(num_shared_tensors)
        _expect(num_segments <= effective_slots,
                "%s: 'num_segments' (%d) exceeds the shared-tensor ring "
                "size (%d%s) — the producer would deadlock waiting on a "
                "slot it has not yet published; raise 'num_shared_tensors'"
                % (where, num_segments, effective_slots,
                   "" if num_shared_tensors is not None
                   else ", the default"))

        groups: List[GroupConfig] = []
        for group_idx, group_raw in enumerate(groups_raw):
            gwhere = "%s, queue group %d" % (where, group_idx)
            _expect(isinstance(group_raw, dict),
                    "%s must be an object" % gwhere)
            dev_key = ("devices" if "devices" in group_raw
                       else "gpus" if "gpus" in group_raw else None)
            _expect(dev_key is not None,
                    "%s needs a 'devices' list" % gwhere)
            devices_raw = group_raw[dev_key]
            _expect(isinstance(devices_raw, list) and devices_raw,
                    "%s: '%s' must be a non-empty list" % (gwhere, dev_key))
            devices = [DeviceSpec(d) for d in devices_raw]

            in_queue = group_raw.get("in_queue")
            if first:
                _expect(in_queue is None,
                        "%s: the first step reads the filename queue and "
                        "may not declare 'in_queue'" % gwhere)
            else:
                _expect(isinstance(in_queue, int),
                        "%s needs an integer 'in_queue'" % gwhere)

            out_queues = group_raw.get("out_queues", [])
            if final:
                _expect(not out_queues,
                        "%s: the last step may not declare 'out_queues'"
                        % gwhere)
            else:
                _expect(isinstance(out_queues, list) and out_queues
                        and all(isinstance(q, int) for q in out_queues),
                        "%s needs a non-empty integer 'out_queues' list"
                        % gwhere)

            selector = group_raw.get("queue_selector",
                                     DEFAULT_QUEUE_SELECTOR)
            _expect(isinstance(selector, str),
                    "%s: 'queue_selector' must be a class-path string"
                    % gwhere)

            extras = {k: v for k, v in group_raw.items()
                      if k not in RESERVED_KEYWORDS}
            groups.append(GroupConfig(devices=devices, in_queue=in_queue,
                                      out_queues=list(out_queues),
                                      queue_selector=selector,
                                      extras=extras))

        # queue wiring: this step's in-queues must be exactly the previous
        # step's out-queues (reference benchmark.py:79-87)
        if not first:
            in_queues = {g.in_queue for g in groups}
            if in_queues != prev_out_queues:
                raise ConfigError(
                    "output queues of step %d %s do not match input queues "
                    "of step %d %s"
                    % (step_idx - 1, sorted(prev_out_queues),
                       step_idx, sorted(in_queues)))
        prev_out_queues = {q for g in groups for q in g.out_queues}

        async_dispatch = step_raw.get("async_dispatch", False)
        _expect(isinstance(async_dispatch, bool),
                "%s: 'async_dispatch' must be a boolean" % where)

        max_retries = step_raw.get("max_retries", 0)
        _expect(isinstance(max_retries, int) and max_retries >= 0,
                "%s: 'max_retries' must be a non-negative integer" % where)
        retry_backoff_ms = step_raw.get("retry_backoff_ms", 10.0)
        _expect(isinstance(retry_backoff_ms, (int, float))
                and retry_backoff_ms >= 0,
                "%s: 'retry_backoff_ms' must be a non-negative number"
                % where)

        step_autotune = step_raw.get("autotune", True)
        _expect(isinstance(step_autotune, bool),
                "%s: 'autotune' must be a boolean (false opts the step "
                "out of the root autotune controller)" % where)

        hedge_ms = step_raw.get("hedge_ms")
        if hedge_ms is not None:
            _expect(hedge_ms == "p95x"
                    or (isinstance(hedge_ms, (int, float))
                        and not isinstance(hedge_ms, bool)
                        and hedge_ms > 0),
                    "%s: 'hedge_ms' must be a positive millisecond "
                    "count or \"p95x\", got %r" % (where, hedge_ms))
            _expect(replica_queues.get(step_idx) is not None,
                    "%s: 'hedge_ms' needs replica lanes to re-dispatch "
                    "onto — declare 'replicas' >= 2 on this step"
                    % where)

        step_extras = {k: v for k, v in step_raw.items()
                       if k not in RESERVED_KEYWORDS}
        steps.append(StepConfig(model=step_raw["model"], groups=groups,
                                num_segments=num_segments,
                                num_shared_tensors=num_shared_tensors,
                                extras=step_extras,
                                async_dispatch=async_dispatch,
                                max_retries=max_retries,
                                retry_backoff_ms=float(retry_backoff_ms),
                                autotune=step_autotune,
                                replica_queues=replica_queues.get(
                                    step_idx),
                                hedge_ms=hedge_ms))

    if netedge is not None and netedge.get("enabled", True):
        # the remote peer serves step 0 and the receiver injects its
        # outputs into step 0's out-queue — both need a downstream
        # step to exist and the local/remote emission paths to be
        # interchangeable; features that break that symmetry are
        # rejected loudly rather than silently mis-accounted
        _expect(len(steps) >= 2,
                "'netedge' needs at least 2 pipeline steps: the peer "
                "serves step 0 remotely and injects into step 1's "
                "input edge")
        _expect(steps[0].num_segments == 1,
                "'netedge' cannot serve a segmented step 0: the "
                "remote path bypasses the runner's segment split")
        _expect(not (isinstance(trace, dict)
                     and trace.get("enabled", True)),
                "'netedge' cannot be combined with 'trace': remote "
                "emissions lack the trace-mode decode stamps, so the "
                "per-request timing tables would mix two schemas")
        _expect(not (isinstance(ragged, dict)
                     and ragged.get("enabled", True)),
                "'netedge' cannot be combined with 'ragged': the "
                "peer's row-pool accounting dies with the peer")
        _expect(all(s.replica_queues is None for s in steps),
                "'netedge' cannot be combined with replica-expanded "
                "steps (or hedging/apply-mode placement): injected "
                "remote emissions bypass the replica in-flight depth "
                "accounting")

    return PipelineConfig(video_path_iterator=raw["video_path_iterator"],
                          steps=steps, raw=raw,
                          overload_policy=overload_policy,
                          fault_containment=fault_containment,
                          fault_plan=fault_plan,
                          popularity=popularity,
                          autotune=autotune,
                          ragged=ragged,
                          pager=pager,
                          handoff=handoff,
                          placement=placement,
                          health=health,
                          deadline=deadline,
                          critpath=critpath,
                          whatif=whatif,
                          metrics=metrics,
                          devobs=devobs,
                          operator=operator,
                          netedge=netedge,
                          lint=lint,
                          trace=trace)
