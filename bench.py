"""Headline benchmark: videos/sec through the flagship pipeline.

Reproduces the reference's benchmark methodology (SURVEY.md §6) on this
framework: the 2-stage decode→R(2+1)D pipeline of
``configs/r2p1d-whole.json`` driven in bulk (max-throughput) mode —
the same topology behind the reference's only published number
(11.3 videos/s on one GPU, reference README.md:176-178).

Prints exactly ONE JSON line:
  {"metric": "videos_per_sec", "value": N, "unit": "videos/s",
   "vs_baseline": N / 11.3, "platform": "tpu", "num_devices": 1,
   "num_videos": 500, "config": "configs/r2p1d-whole.json"}
and on unrecoverable failure a structured error line instead:
  {"metric": "videos_per_sec", "value": null, "unit": "videos/s",
   "vs_baseline": null, "error": "..."}

``vs_baseline`` is only reported when the measured platform is the TPU
plugin — the reference number is a GPU-hardware number and comparing a
host-CPU run against it would be meaningless (and unauditable, since
round-2 review noted nothing *asserted* what was measured).

Backend resilience: the TPU in this environment is reached through a
tunnel that can be transiently unavailable (and, when wedged, makes
``jax.devices()`` *block* rather than raise). Before touching the
backend in-process we probe it in short-lived subprocesses — each with
an internal deadline that exits via ``os._exit`` (a process-initiated
exit; an external SIGKILL on a TPU-attached process is what wedges the
tunnel in the first place) — retrying with backoff within a time
budget.

Env knobs: RNB_BENCH_VIDEOS (default 500), RNB_BENCH_CONFIG,
RNB_BENCH_MEAN_INTERVAL_MS (default 0 = bulk), RNB_BENCH_PLATFORM
(e.g. "cpu" to force the CPU backend for smoke runs; skips the probe),
RNB_BENCH_INIT_BUDGET_S (default 600) total probe budget,
RNB_BENCH_PROBE_TIMEOUT_S (default 90) per-attempt deadline.
"""

from __future__ import annotations

import contextlib
import io
import json
import os
import subprocess
import sys
import time

#: reference README.md:176-178 — 500 videos / 44.249694 s on one GPU
BASELINE_VIDEOS_PER_SEC = 500.0 / 44.249694

#: run in a fresh interpreter; prints the device list on success and
#: self-exits (rc 3) if backend init blocks past the deadline.
_PROBE_SRC = r"""
import os, sys, threading
deadline = float(sys.argv[1])
def _watchdog():
    import time
    time.sleep(deadline)
    sys.stderr.write("probe: backend init still blocked after %.0fs\n"
                     % deadline)
    sys.stderr.flush()
    os._exit(3)
threading.Thread(target=_watchdog, daemon=True).start()
import jax
devs = jax.devices()
print("%d:%s" % (len(devs), devs[0].platform))
"""


def _probe_backend(budget_s: float, attempt_timeout_s: float) -> str:
    """Wait (with backoff) until a fresh interpreter can init the
    default JAX backend. Returns '' on success, else an error string.
    (The measured platform is reported from the live backend after the
    run, not from the probe — the tunnel could re-resolve in between.)

    Each attempt is a subprocess so a failed/hung init never poisons
    this process's backend cache; the subprocess exits on its own
    internal deadline — it is never killed externally. If even the
    internal watchdog fails (backend init holding the GIL so the daemon
    thread never runs), the child is ABANDONED, not killed: a SIGKILL
    on a TPU-attached process is exactly what wedges the tunnel. An
    abandoned child self-exits if its watchdog ever gets scheduled, and
    otherwise lingers harmlessly until the tunnel releases it.
    """
    start = time.monotonic()
    backoff, attempt, last = 15.0, 0, "no probe attempted"
    abandoned = []
    while True:
        attempt += 1
        proc = subprocess.Popen(
            [sys.executable, "-c", _PROBE_SRC, str(attempt_timeout_s)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        try:
            # generous soft stop: the internal watchdog fires first;
            # reaching this timeout means the watchdog itself is stuck
            out, errout = proc.communicate(timeout=attempt_timeout_s + 60)
        except subprocess.TimeoutExpired:
            abandoned.append(proc)  # never killed — see docstring
            last = ("probe watchdog failed; child pid %d abandoned "
                    "(not killed)" % proc.pid)
        else:
            if proc.returncode == 0:
                sys.stderr.write("bench: backend up (%s) after %d probe(s)\n"
                                 % (out.strip(), attempt))
                return ""
            tail = (errout or "").strip().splitlines()
            last = ("probe rc=%d: %s"
                    % (proc.returncode, tail[-1] if tail else "no output"))
        elapsed = time.monotonic() - start
        if elapsed + backoff > budget_s:
            return ("backend unavailable after %d probe(s) in %.0fs; last: %s"
                    % (attempt, elapsed, last))
        sys.stderr.write("bench: %s; retrying in %.0fs\n" % (last, backoff))
        time.sleep(backoff)
        backoff = min(backoff * 2, 120.0)


#: the real stdout, captured before any redirect_stdout so the one-line
#: JSON contract holds even when the watchdog fires mid-redirect
#: (round-2 advisor: the error line used to land in the discarded
#: StringIO and the process exited with empty stdout).
_REAL_STDOUT = sys.stdout


def _emit(payload: dict) -> None:
    _REAL_STDOUT.write(json.dumps(payload) + "\n")
    _REAL_STDOUT.flush()


def _emit_error(msg: str) -> int:
    _emit({
        "metric": "videos_per_sec",
        "value": None,
        "unit": "videos/s",
        "vs_baseline": None,
        "error": msg[:500],
    })
    return 1


def main() -> int:
    repo_dir = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, repo_dir)
    platform = os.environ.get("RNB_BENCH_PLATFORM")
    if platform:
        # env-var JAX_PLATFORMS alone is overridden by the site hook in
        # some containers; the config knob wins
        import jax
        jax.config.update("jax_platforms", platform)
    else:
        err = _probe_backend(
            float(os.environ.get("RNB_BENCH_INIT_BUDGET_S", "600")),
            float(os.environ.get("RNB_BENCH_PROBE_TIMEOUT_S", "90")))
        if err:
            return _emit_error(err)

    num_videos = int(os.environ.get("RNB_BENCH_VIDEOS", "500"))
    config = os.environ.get(
        "RNB_BENCH_CONFIG",
        os.path.join(repo_dir, "configs", "r2p1d-whole.json"))
    mean_interval = int(os.environ.get("RNB_BENCH_MEAN_INTERVAL_MS", "0"))

    from rnb_tpu.benchmark import run_benchmark

    # the probe leaves one gap: the tunnel can wedge *between* the
    # probe and run_benchmark's own backend init, hanging this process
    # with nothing on stdout. A daemon watchdog closes it: if the run
    # exceeds its budget the structured error line is printed and the
    # process self-exits (process-initiated; never an external SIGKILL,
    # which is what wedges the tunnel).
    import threading
    run_budget_s = float(os.environ.get("RNB_BENCH_RUN_BUDGET_S", "1800"))
    done = threading.Event()

    def _watchdog():
        if not done.wait(run_budget_s):
            _emit_error("benchmark did not finish within %.0fs "
                        "(backend hang?)" % run_budget_s)
            sys.stdout.flush()
            os._exit(1)

    threading.Thread(target=_watchdog, daemon=True).start()

    # everything the harness prints stays out of the one-line contract
    captured_err = io.StringIO()
    try:
        with contextlib.redirect_stdout(io.StringIO()), \
                contextlib.redirect_stderr(captured_err):
            result = run_benchmark(
                config_path=config,
                mean_interval_ms=mean_interval,
                num_videos=num_videos,
                log_base=os.environ.get("RNB_BENCH_LOG_BASE", "logs"),
                print_progress=False,
                seed=0,
            )
    except Exception as e:  # noqa: BLE001 — one-line contract on any failure
        done.set()
        sys.stderr.write(captured_err.getvalue())
        return _emit_error("%s: %s" % (type(e).__name__, e))
    done.set()

    # record what was actually measured: the live backend, not the
    # probe's claim (the tunnel could have re-resolved in between)
    import jax
    devs = jax.devices()
    measured_platform = devs[0].platform
    line = {
        "metric": "videos_per_sec",
        "value": round(result.throughput_vps, 3),
        "unit": "videos/s",
        "vs_baseline": None,
        "platform": measured_platform,
        "num_devices": len(devs),
        "num_videos": num_videos,
        "config": os.path.relpath(config, repo_dir),
    }
    if measured_platform == "tpu":
        line["vs_baseline"] = round(
            result.throughput_vps / BASELINE_VIDEOS_PER_SEC, 3)
    else:
        # the baseline is a GPU-hardware number; comparing a host run
        # against it would publish a meaningless ratio
        line["note"] = ("vs_baseline omitted: measured platform is %r, "
                        "not the TPU plugin" % measured_platform)
    _emit(line)
    if result.termination_flag != 0:
        sys.stderr.write(captured_err.getvalue())
        sys.stderr.write("bench: abnormal termination flag %d\n"
                         % result.termination_flag)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
