"""Headline benchmark: videos/sec through the flagship pipeline.

Reproduces the reference's benchmark methodology (SURVEY.md §6) on this
framework: the 2-stage decode→R(2+1)D pipeline of
``configs/r2p1d-whole.json`` driven in bulk (max-throughput) mode —
the same topology behind the reference's only published number
(11.3 videos/s on one GPU, reference README.md:176-178).

Prints exactly ONE JSON line:
  {"metric": "videos_per_sec", "value": N, "unit": "videos/s",
   "vs_baseline": N / 11.3}

Env knobs: RNB_BENCH_VIDEOS (default 500), RNB_BENCH_CONFIG,
RNB_BENCH_MEAN_INTERVAL_MS (default 0 = bulk), RNB_BENCH_PLATFORM
(e.g. "cpu" to force the CPU backend for smoke runs).
"""

from __future__ import annotations

import contextlib
import io
import json
import os
import sys

#: reference README.md:176-178 — 500 videos / 44.249694 s on one GPU
BASELINE_VIDEOS_PER_SEC = 500.0 / 44.249694


def main() -> int:
    repo_dir = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, repo_dir)
    platform = os.environ.get("RNB_BENCH_PLATFORM")
    if platform:
        # env-var JAX_PLATFORMS alone is overridden by the site hook in
        # some containers; the config knob wins
        import jax
        jax.config.update("jax_platforms", platform)
    num_videos = int(os.environ.get("RNB_BENCH_VIDEOS", "500"))
    config = os.environ.get(
        "RNB_BENCH_CONFIG",
        os.path.join(repo_dir, "configs", "r2p1d-whole.json"))
    mean_interval = int(os.environ.get("RNB_BENCH_MEAN_INTERVAL_MS", "0"))

    from rnb_tpu.benchmark import run_benchmark

    # everything the harness prints stays out of the one-line contract
    captured_err = io.StringIO()
    with contextlib.redirect_stdout(io.StringIO()), \
            contextlib.redirect_stderr(captured_err):
        result = run_benchmark(
            config_path=config,
            mean_interval_ms=mean_interval,
            num_videos=num_videos,
            log_base=os.environ.get("RNB_BENCH_LOG_BASE", "logs"),
            print_progress=False,
            seed=0,
        )

    value = result.throughput_vps
    print(json.dumps({
        "metric": "videos_per_sec",
        "value": round(value, 3),
        "unit": "videos/s",
        "vs_baseline": round(value / BASELINE_VIDEOS_PER_SEC, 3),
    }))
    if result.termination_flag != 0:
        sys.stderr.write(captured_err.getvalue())
        sys.stderr.write("bench: abnormal termination flag %d\n"
                         % result.termination_flag)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
