"""Headline benchmark: videos/sec through the flagship pipeline.

Reproduces the reference's benchmark methodology (SURVEY.md §6) on this
framework, driven in bulk (max-throughput) mode against the baseline
from the reference's only published number (11.3 videos/s on one GPU
over config/r2p1d-whole.json, reference README.md:176-178). The default
topology here is ``configs/rnb-fused-yuv-big.json`` — the reference's
Replicate & Batch idea collapsed into the loader: R2P1DFusingLoader
submits every request to the decode pool on receipt, harvests
completed decodes and ships one fused device batch straight to the
network stage, whose jit opens with the yuv420 ingest (packed 4:2:0
planes -> chroma upsample -> BT.601 -> normalize, rnb_tpu/ops/yuv.py).
Batching without the extra host stage that made the standalone Batcher
topology host-bound (rnb-1chip measured 481 vs 874-909 fused in round
4); the 2-stage ``r2p1d-whole-yuv`` and the reference-shaped
``rnb-1chip`` remain measured side-by-side in scripts/bench_matrix.py.
The ``-big`` variant (fuse 20 / 48-row cap, buckets [6,15,24,36,48])
exists because the tunnel's per-dispatch round-trip varies ~10x across
transport phases (RESULTS.md, 2026-07-30): with ~9ms effective per
dispatch the 15-row cap throttled the chip to 273 videos/s while the
identical code had measured 869-909 in the low-RTT phase; 48-row
fused dispatches recovered 2.1x (562) in the degraded phase and cost
nothing in the warm one (adaptive emission still sends small batches
the moment the pipeline idles).

**Real decode by default.** The reference's number includes real video
decode through NVVL (reference models/r2p1d/model.py:140-151), so this
bench decodes real files too: it generates (once, cached under
``data/bench_y4m``) a y4m dataset via scripts/make_dataset.py and runs
it through the native C++ decode pool. ``RNB_BENCH_DATASET=mjpeg``
switches to compressed MJPEG input (baseline-JPEG Huffman+IDCT per
frame in native/decode.cpp — real codec work, the role NVDEC filled
for the reference); ``RNB_BENCH_DATASET=synth`` restores the
synthetic-id mode for apples-to-apples comparison with rounds ≤3; the
emitted ``decode_backend`` key states which path was measured.

Prints exactly ONE JSON line with throughput plus the evidence keys the
perf claim needs to be auditable:
  {"metric": "videos_per_sec", "value": N, "unit": "videos/s",
   "vs_baseline": N / 11.3, "platform": "tpu", "decode_backend": "...",
   "p50_ms": N, "p99_ms": N, "clips_per_sec": N,
   "gflops_per_clip": 42.14, "tflops": N, "mfu": N, ...}
and on unrecoverable failure a structured error line instead:
  {"metric": "videos_per_sec", "value": null, "unit": "videos/s",
   "vs_baseline": null, "error": "..."}

``vs_baseline`` is only reported when the measured platform is the TPU
plugin — the reference number is a GPU-hardware number and comparing a
host-CPU run against it would be meaningless. ``mfu`` is analytic
conv+dense FLOPs (rnb_tpu/models/r2p1d/flops.py, cross-checked against
XLA cost_analysis in tests) divided by the device's spec-sheet bf16
peak; it is null on platforms with no known peak.

Backend resilience: the TPU in this environment is reached through a
tunnel that can be transiently unavailable (and, when wedged, makes
``jax.devices()`` *block* rather than raise). Before touching the
backend in-process we probe it in short-lived subprocesses — each with
an internal deadline that exits via ``os._exit`` (a process-initiated
exit; an external SIGKILL on a TPU-attached process is what wedges the
tunnel in the first place) — retrying with backoff within a time
budget.

Env knobs: RNB_BENCH_VIDEOS (default 10000: a >10s measured window at
the round-4 fused flagship's ~900 videos/s on
TPU), RNB_BENCH_CONFIG, RNB_BENCH_MEAN_INTERVAL_MS (default 0 = bulk),
RNB_BENCH_DATASET (y4m|mjpeg|synth, default y4m), RNB_TPU_DATA_ROOT (use an
existing dataset instead of generating), RNB_BENCH_PLATFORM (e.g.
"cpu" to force the CPU backend for smoke runs; skips the probe),
RNB_BENCH_INIT_BUDGET_S (default 600) total probe budget,
RNB_BENCH_PROBE_TIMEOUT_S (default 90) per-attempt deadline.
"""

from __future__ import annotations

import contextlib
import io
import json
import os
import subprocess
import sys
import time

#: reference README.md:176-178 — 500 videos / 44.249694 s on one GPU
BASELINE_VIDEOS_PER_SEC = 500.0 / 44.249694

#: run in a fresh interpreter; prints the device list on success and
#: self-exits (rc 3) if backend init blocks past the deadline.
_PROBE_SRC = r"""
import os, sys, threading
deadline = float(sys.argv[1])
def _watchdog():
    import time
    time.sleep(deadline)
    sys.stderr.write("probe: backend init still blocked after %.0fs\n"
                     % deadline)
    sys.stderr.flush()
    os._exit(3)
threading.Thread(target=_watchdog, daemon=True).start()
import jax
devs = jax.devices()
print("%d:%s" % (len(devs), devs[0].platform))
"""


def _probe_backend(budget_s: float, attempt_timeout_s: float) -> str:
    """Wait (with backoff) until a fresh interpreter can init the
    default JAX backend. Returns '' on success, else an error string.
    (The measured platform is reported from the live backend after the
    run, not from the probe — the tunnel could re-resolve in between.)

    Each attempt is a subprocess so a failed/hung init never poisons
    this process's backend cache; the subprocess exits on its own
    internal deadline — it is never killed externally. If even the
    internal watchdog fails (backend init holding the GIL so the daemon
    thread never runs), the child is ABANDONED, not killed: a SIGKILL
    on a TPU-attached process is exactly what wedges the tunnel. An
    abandoned child self-exits if its watchdog ever gets scheduled, and
    otherwise lingers harmlessly until the tunnel releases it.
    """
    start = time.monotonic()
    backoff, attempt, last = 15.0, 0, "no probe attempted"
    abandoned = []
    while True:
        attempt += 1
        proc = subprocess.Popen(
            [sys.executable, "-c", _PROBE_SRC, str(attempt_timeout_s)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        try:
            # generous soft stop: the internal watchdog fires first;
            # reaching this timeout means the watchdog itself is stuck
            out, errout = proc.communicate(timeout=attempt_timeout_s + 60)
        except subprocess.TimeoutExpired:
            abandoned.append(proc)  # never killed — see docstring
            last = ("probe watchdog failed; child pid %d abandoned "
                    "(not killed)" % proc.pid)
        else:
            if proc.returncode == 0:
                sys.stderr.write("bench: backend up (%s) after %d probe(s)\n"
                                 % (out.strip(), attempt))
                return ""
            tail = (errout or "").strip().splitlines()
            last = ("probe rc=%d: %s"
                    % (proc.returncode, tail[-1] if tail else "no output"))
        elapsed = time.monotonic() - start
        if elapsed + backoff > budget_s:
            return ("backend unavailable after %d probe(s) in %.0fs; last: %s"
                    % (attempt, elapsed, last))
        sys.stderr.write("bench: %s; retrying in %.0fs\n" % (last, backoff))
        time.sleep(backoff)
        backoff = min(backoff * 2, 120.0)


#: the real stdout, captured before any redirect_stdout so the one-line
#: JSON contract holds even when the watchdog fires mid-redirect
#: (round-2 advisor: the error line used to land in the discarded
#: StringIO and the process exited with empty stdout).
_REAL_STDOUT = sys.stdout


def _emit(payload: dict) -> None:
    _REAL_STDOUT.write(json.dumps(payload) + "\n")
    _REAL_STDOUT.flush()


def _emit_error(msg: str) -> int:
    _emit({
        "metric": "videos_per_sec",
        "value": None,
        "unit": "videos/s",
        "vs_baseline": None,
        "error": msg[:500],
    })
    return 1


def _dataset_spec():
    """Generated-dataset geometry (env-overridable for smoke tests):
    128 source frames so the sampler can place 15 non-overlapping
    8-frame clips (15*8=120 <= 128 keeps the reference's skewed [1,15]
    clip population intact), 192x256 source pixels so decode+resize
    does real work per frame. 4 labels x 11 videos is chosen because
    the per-id deterministic sampler locks each file's clip count to
    its path hash: this population lands at 4/44 large videos (9.1%)
    and 2.27 clips/video on average — matching the [1,15]@[10,1]
    weights the reference's sampler draws (a smaller set can skew to
    ~3% large and flatter the measured throughput). The share holds for
    the default data/bench_y4m root — ids are path-hashed, so custom
    RNB_TPU_DATA_ROOT datasets carry their own (still deterministic)
    mix."""
    e = os.environ.get
    return ("--labels", e("RNB_BENCH_DATASET_LABELS", "4"),
            "--videos-per-label", e("RNB_BENCH_DATASET_VPL", "11"),
            "--frames", e("RNB_BENCH_DATASET_FRAMES", "128"),
            "--size", e("RNB_BENCH_DATASET_SIZE", "192x256"),
            # 4:2:0 like real video — and decode is read-bandwidth
            # bound once the colourspace math runs on device, so the
            # stand-in for codec output should not double the bytes
            "--colorspace", e("RNB_BENCH_DATASET_COLORSPACE", "420"))


def _count_videos(root: str, exts=(".y4m",)) -> int:
    """Count videos using EXACTLY the pipeline iterator's scan rule
    (root/<label>/*<ext>, one level — R2P1DVideoPathIterator): a dataset
    this count accepts is a dataset the measured run actually consumes,
    so decode_backend can never claim real decode over a layout the
    iterator would silently skip (falling back to synth:// ids)."""
    if not os.path.isdir(root):
        return 0
    total = 0
    for label in os.listdir(root):
        label_dir = os.path.join(root, label)
        if os.path.isdir(label_dir):
            total += sum(1 for v in os.listdir(label_dir)
                         if v.endswith(tuple(exts)))
    return total


def _ensure_dataset(repo_dir: str):
    """Prepare the decode workload; -> (decode_backend, dataset_root).

    y4m mode (default): reuse RNB_TPU_DATA_ROOT if it already holds
    videos, else generate the procedural y4m tree once under
    data/bench_y4m; exports RNB_TPU_DATA_ROOT so the pipeline's path
    iterator and decode warm-up find it. synth mode: clears the root so
    the loader falls back to synth:// ids (rounds <=3 behavior).
    """
    mode = os.environ.get("RNB_BENCH_DATASET", "y4m")
    if mode == "synth":
        os.environ.pop("RNB_TPU_DATA_ROOT", None)
        return "synthetic", None
    if mode not in ("y4m", "mjpeg"):
        raise ValueError("RNB_BENCH_DATASET must be y4m, mjpeg or "
                         "synth, got %r" % mode)
    exts = (".y4m",) if mode == "y4m" else (".mjpg", ".mjpeg")
    user_root = os.environ.get("RNB_TPU_DATA_ROOT")
    root = user_root or os.path.join(repo_dir, "data", "bench_" + mode)
    spec = list(_dataset_spec())
    if mode == "mjpeg":
        # real codec work per frame: baseline-JPEG entropy decode +
        # IDCT (native/decode.cpp), the role NVDEC filled for the
        # reference (README.md:42-110)
        spec += ["--format", "mjpeg", "--quality",
                 os.environ.get("RNB_BENCH_MJPEG_QUALITY", "90")]
    spec_path = os.path.join(root, "DATASET_SPEC.json")
    spec_stale = False
    if not user_root and _count_videos(root, exts) > 0:
        # the generated cache is keyed by its spec: a geometry change
        # (e.g. the round-4 clip-mix fix) must regenerate, or the run
        # silently measures the old population while the evidence
        # describes the new one. User-supplied roots are never touched.
        try:
            with open(spec_path) as f:
                spec_stale = json.load(f) != spec
        except (OSError, ValueError):
            spec_stale = True
    if _count_videos(root, exts) == 0 or spec_stale:
        if spec_stale:
            import shutil
            sys.stderr.write("bench: regenerating %s (spec changed)\n"
                             % root)
            shutil.rmtree(root, ignore_errors=True)
        else:
            sys.stderr.write("bench: generating %s dataset under %s\n"
                             % (mode, root))
        subprocess.run(
            [sys.executable,
             os.path.join(repo_dir, "scripts", "make_dataset.py"),
             "--root", root, *spec],
            check=True, stdout=subprocess.DEVNULL)
        if _count_videos(root, exts) == 0:
            raise RuntimeError(
                "dataset generation produced no root/label/* videos "
                "under %s" % root)
        if not user_root:
            with open(spec_path, "w") as f:
                json.dump(spec, f)
    # the iterator consumes EVERY supported extension, so a root mixing
    # formats would measure a different population than decode_backend
    # claims — fail loud instead of publishing false evidence
    other_exts = (".mjpg", ".mjpeg") if mode == "y4m" else (".y4m",)
    n_other = _count_videos(root, other_exts)
    if n_other:
        raise RuntimeError(
            "dataset root %s holds %d %s video(s) alongside the %s "
            "dataset — the pipeline iterator would consume both and "
            "the decode_backend evidence key would lie; use a "
            "single-format root" % (root, n_other, other_exts, mode))
    os.environ["RNB_TPU_DATA_ROOT"] = root
    from rnb_tpu.decode.native import native_available
    native = native_available()
    if mode == "mjpeg":
        backend = "native-mjpeg" if native else "pil-mjpeg"
    else:
        backend = "native-y4m" if native else "numpy-y4m"
    return backend, root


def _config_stage_views(config: dict):
    """Shared with the devobs plane (rnb_tpu.devobs) — one merged-view
    rule so the published evidence and the runtime Compute:/Memory:
    accounting can never disagree on what a stage was configured as."""
    from rnb_tpu.devobs import config_stage_views
    return config_stage_views(config)


def _flops_per_clip_for_config(config: dict) -> float:
    """Analytic conv+dense FLOPs one clip costs across every network
    stage — delegated to rnb_tpu.devobs.flops_per_clip_for_config, the
    SAME config walk the device observability plane cross-foots its
    runtime ``compute_profile()`` seam against (``make devobs``), so
    the evidence line's gflops_per_clip and the Compute: log-meta line
    share one definition."""
    from rnb_tpu.devobs import flops_per_clip_for_config
    return flops_per_clip_for_config(config)


def _latency_semantics(config: dict) -> str:
    """\"completion\" when every stage blocks before stamping
    inference_finish; \"dispatch\" when any stage publishes async
    (async_dispatch step flag, or a mesh stage with sync_preds false) —
    the emitted p50/p99 then measure dispatch, and the evidence line
    must say so."""
    for step, views in _config_stage_views(config):
        for view in views:
            if view.get("async_dispatch"):
                return "dispatch"
            if (view.get("model", step.get("model", ""))
                    .endswith(".R2P1DMeshRunner")
                    and view.get("sync_preds") is False):
                return "dispatch"
    return "completion"


def _devices_used(config: dict) -> int:
    """Distinct accelerator devices the topology touches — delegated
    to rnb_tpu.devobs.devices_used, the same MFU denominator rule the
    Compute: log-meta line applies, so the two cross-foot by
    construction."""
    from rnb_tpu.devobs import devices_used
    return devices_used(config)


def main() -> int:
    repo_dir = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, repo_dir)

    try:
        decode_backend, dataset_root = _ensure_dataset(repo_dir)
    except Exception as e:  # noqa: BLE001 — one-line contract
        return _emit_error("dataset preparation failed: %s: %s"
                           % (type(e).__name__, e))

    platform = os.environ.get("RNB_BENCH_PLATFORM")
    if platform:
        # env-var JAX_PLATFORMS alone is overridden by the site hook in
        # some containers; the config knob wins
        import jax
        jax.config.update("jax_platforms", platform)
    else:
        err = _probe_backend(
            float(os.environ.get("RNB_BENCH_INIT_BUDGET_S", "600")),
            float(os.environ.get("RNB_BENCH_PROBE_TIMEOUT_S", "90")))
        if err:
            return _emit_error(err)

    num_videos = int(os.environ.get("RNB_BENCH_VIDEOS", "10000"))
    config = os.environ.get(
        "RNB_BENCH_CONFIG",
        os.path.join(repo_dir, "configs", "rnb-fused-yuv-big.json"))
    mean_interval = int(os.environ.get("RNB_BENCH_MEAN_INTERVAL_MS", "0"))

    # the probe leaves one gap: the tunnel can wedge *between* the
    # probe and run_benchmark's own backend init, hanging this process
    # with nothing on stdout. A daemon watchdog closes it: if the run
    # exceeds its budget the structured error line is printed and the
    # process self-exits (process-initiated; never an external SIGKILL,
    # which is what wedges the tunnel).
    import threading
    run_budget_s = float(os.environ.get("RNB_BENCH_RUN_BUDGET_S", "1800"))
    done = threading.Event()

    def _watchdog():
        if not done.wait(run_budget_s):
            _emit_error("benchmark did not finish within %.0fs "
                        "(backend hang?)" % run_budget_s)
            sys.stdout.flush()
            os._exit(1)

    threading.Thread(target=_watchdog, daemon=True).start()

    # everything the harness prints stays out of the one-line contract
    captured_err = io.StringIO()
    try:
        with contextlib.redirect_stdout(io.StringIO()), \
                contextlib.redirect_stderr(captured_err):
            line, termination_flag = measure(
                config, num_videos, mean_interval,
                decode_backend, dataset_root,
                log_base=os.environ.get("RNB_BENCH_LOG_BASE", "logs"))
    except Exception as e:  # noqa: BLE001 — one-line contract on any failure
        done.set()
        sys.stderr.write(captured_err.getvalue())
        return _emit_error("%s: %s" % (type(e).__name__, e))
    done.set()
    _emit(line)
    if termination_flag != 0:
        sys.stderr.write(captured_err.getvalue())
        sys.stderr.write("bench: abnormal termination flag %d\n"
                         % termination_flag)
        return 1
    return 0


def measure(config: str, num_videos: int, mean_interval: int,
            decode_backend: str, dataset_root, log_base: str = "logs",
            seed: int = 0):
    """Run one benchmark job; -> (evidence line dict, termination flag).

    Shared by the headline bench (one line to stdout) and
    scripts/bench_matrix.py (one row per config in the matrix artifact).
    """
    repo_dir = os.path.dirname(os.path.abspath(__file__))
    with open(config) as f:
        config_dict = json.load(f)
    from rnb_tpu.benchmark import run_benchmark
    result = run_benchmark(
        config_path=config,
        mean_interval_ms=mean_interval,
        num_videos=num_videos,
        log_base=log_base,
        print_progress=False,
        seed=seed,
    )

    # record what was actually measured: the live backend, not the
    # probe's claim (the tunnel could have re-resolved in between)
    import jax
    devs = jax.devices()
    measured_platform = devs[0].platform
    line = {
        "metric": "videos_per_sec",
        "value": round(result.throughput_vps, 3),
        "unit": "videos/s",
        "vs_baseline": None,
        "platform": measured_platform,
        "device_kind": devs[0].device_kind,
        "num_devices": len(devs),
        "devices_used": _devices_used(config_dict),
        "num_videos": num_videos,
        "mean_interval_ms": mean_interval,
        "config": os.path.relpath(config, repo_dir),
        "decode_backend": decode_backend,
        "dataset": (os.path.relpath(dataset_root, repo_dir)
                    if dataset_root else None),
        "measured_window_s": round(result.total_time_s, 3),
        "p50_ms": (round(result.p50_latency_ms, 3)
                   if result.p50_latency_ms is not None else None),
        "p99_ms": (round(result.p99_latency_ms, 3)
                   if result.p99_latency_ms is not None else None),
        "latency_semantics": _latency_semantics(config_dict),
        # host-core saturation over the measured window (1-core host:
        # ~1.0 means the host is the ceiling) — the quantitative leg
        # of any host-bound claim
        "host_cpu_frac": (round(result.host_cpu_s / result.total_time_s,
                                3)
                          if result.total_time_s > 0 else None),
    }
    # device-utilization evidence: analytic conv+dense FLOPs (see
    # rnb_tpu/models/r2p1d/flops.py) x measured clip rate vs spec peak
    from rnb_tpu.models.r2p1d.flops import peak_tflops_for
    flops_per_clip = _flops_per_clip_for_config(config_dict)
    clips_per_sec = (result.clips_completed / result.total_time_s
                     if result.total_time_s > 0 else 0.0)
    line["clips_per_sec"] = round(clips_per_sec, 3)
    line["gflops_per_clip"] = round(flops_per_clip / 1e9, 3)
    tflops = clips_per_sec * flops_per_clip / 1e12
    line["tflops"] = round(tflops, 3)
    peak = peak_tflops_for(devs[0].device_kind)
    line["peak_tflops_per_device"] = peak
    line["mfu"] = (round(tflops / (peak * line["devices_used"]), 4)
                   if peak else None)
    if result.compute_stages:
        # devobs-enabled runs surface the runtime compute plane's own
        # figures next to the analytic ones — the `make devobs` gate
        # holds them equal to the digit (tflops_milli vs
        # round(tflops, 3); mfu_e4 vs round(mfu, 4); -1 = no peak)
        line["compute_tflops_milli"] = result.compute_tflops_milli
        line["compute_mfu_e4"] = result.compute_mfu_e4
    if measured_platform == "tpu":
        line["vs_baseline"] = round(
            result.throughput_vps / BASELINE_VIDEOS_PER_SEC, 3)
    else:
        # the baseline is a GPU-hardware number; comparing a host run
        # against it would publish a meaningless ratio
        line["note"] = ("vs_baseline omitted: measured platform is %r, "
                        "not the TPU plugin" % measured_platform)
    return line, result.termination_flag


if __name__ == "__main__":
    sys.exit(main())
