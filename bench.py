"""Headline benchmark: videos/sec through the flagship pipeline.

Reproduces the reference's benchmark methodology (SURVEY.md §6) on this
framework: the 2-stage decode→R(2+1)D pipeline of
``configs/r2p1d-whole.json`` driven in bulk (max-throughput) mode —
the same topology behind the reference's only published number
(11.3 videos/s on one GPU, reference README.md:176-178).

Prints exactly ONE JSON line:
  {"metric": "videos_per_sec", "value": N, "unit": "videos/s",
   "vs_baseline": N / 11.3}
and on unrecoverable failure a structured error line instead:
  {"metric": "videos_per_sec", "value": null, "unit": "videos/s",
   "vs_baseline": null, "error": "..."}

Backend resilience: the TPU in this environment is reached through a
tunnel that can be transiently unavailable (and, when wedged, makes
``jax.devices()`` *block* rather than raise). Before touching the
backend in-process we probe it in short-lived subprocesses — each with
an internal deadline that exits via ``os._exit`` (a process-initiated
exit; an external SIGKILL on a TPU-attached process is what wedges the
tunnel in the first place) — retrying with backoff within a time
budget.

Env knobs: RNB_BENCH_VIDEOS (default 500), RNB_BENCH_CONFIG,
RNB_BENCH_MEAN_INTERVAL_MS (default 0 = bulk), RNB_BENCH_PLATFORM
(e.g. "cpu" to force the CPU backend for smoke runs; skips the probe),
RNB_BENCH_INIT_BUDGET_S (default 600) total probe budget,
RNB_BENCH_PROBE_TIMEOUT_S (default 90) per-attempt deadline.
"""

from __future__ import annotations

import contextlib
import io
import json
import os
import subprocess
import sys
import time

#: reference README.md:176-178 — 500 videos / 44.249694 s on one GPU
BASELINE_VIDEOS_PER_SEC = 500.0 / 44.249694

#: run in a fresh interpreter; prints the device list on success and
#: self-exits (rc 3) if backend init blocks past the deadline.
_PROBE_SRC = r"""
import os, sys, threading
deadline = float(sys.argv[1])
def _watchdog():
    import time
    time.sleep(deadline)
    sys.stderr.write("probe: backend init still blocked after %.0fs\n"
                     % deadline)
    sys.stderr.flush()
    os._exit(3)
threading.Thread(target=_watchdog, daemon=True).start()
import jax
devs = jax.devices()
print("%d:%s" % (len(devs), devs[0].platform))
"""


def _probe_backend(budget_s: float, attempt_timeout_s: float) -> str:
    """Wait (with backoff) until a fresh interpreter can init the
    default JAX backend. Returns '' on success, else an error string.

    Each attempt is a subprocess so a failed/hung init never poisons
    this process's backend cache; the subprocess exits on its own
    internal deadline — it is never killed externally.
    """
    start = time.monotonic()
    backoff, attempt, last = 15.0, 0, "no probe attempted"
    while True:
        attempt += 1
        try:
            proc = subprocess.run(
                [sys.executable, "-c", _PROBE_SRC, str(attempt_timeout_s)],
                capture_output=True, text=True,
                # generous hard stop: the internal watchdog fires first;
                # this outer guard only catches a watchdog failure
                timeout=attempt_timeout_s + 60)
        except subprocess.TimeoutExpired:
            last = "probe watchdog failed; outer timeout hit"
        else:
            if proc.returncode == 0:
                sys.stderr.write("bench: backend up (%s) after %d probe(s)\n"
                                 % (proc.stdout.strip(), attempt))
                return ""
            tail = (proc.stderr or "").strip().splitlines()
            last = ("probe rc=%d: %s"
                    % (proc.returncode, tail[-1] if tail else "no output"))
        elapsed = time.monotonic() - start
        if elapsed + backoff > budget_s:
            return ("backend unavailable after %d probe(s) in %.0fs; last: %s"
                    % (attempt, elapsed, last))
        sys.stderr.write("bench: %s; retrying in %.0fs\n" % (last, backoff))
        time.sleep(backoff)
        backoff = min(backoff * 2, 120.0)


def _emit_error(msg: str) -> int:
    print(json.dumps({
        "metric": "videos_per_sec",
        "value": None,
        "unit": "videos/s",
        "vs_baseline": None,
        "error": msg[:500],
    }))
    return 1


def main() -> int:
    repo_dir = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, repo_dir)
    platform = os.environ.get("RNB_BENCH_PLATFORM")
    if platform:
        # env-var JAX_PLATFORMS alone is overridden by the site hook in
        # some containers; the config knob wins
        import jax
        jax.config.update("jax_platforms", platform)
    else:
        err = _probe_backend(
            float(os.environ.get("RNB_BENCH_INIT_BUDGET_S", "600")),
            float(os.environ.get("RNB_BENCH_PROBE_TIMEOUT_S", "90")))
        if err:
            return _emit_error(err)

    num_videos = int(os.environ.get("RNB_BENCH_VIDEOS", "500"))
    config = os.environ.get(
        "RNB_BENCH_CONFIG",
        os.path.join(repo_dir, "configs", "r2p1d-whole.json"))
    mean_interval = int(os.environ.get("RNB_BENCH_MEAN_INTERVAL_MS", "0"))

    from rnb_tpu.benchmark import run_benchmark

    # the probe leaves one gap: the tunnel can wedge *between* the
    # probe and run_benchmark's own backend init, hanging this process
    # with nothing on stdout. A daemon watchdog closes it: if the run
    # exceeds its budget the structured error line is printed and the
    # process self-exits (process-initiated; never an external SIGKILL,
    # which is what wedges the tunnel).
    import threading
    run_budget_s = float(os.environ.get("RNB_BENCH_RUN_BUDGET_S", "1800"))
    done = threading.Event()

    def _watchdog():
        if not done.wait(run_budget_s):
            _emit_error("benchmark did not finish within %.0fs "
                        "(backend hang?)" % run_budget_s)
            sys.stdout.flush()
            os._exit(1)

    threading.Thread(target=_watchdog, daemon=True).start()

    # everything the harness prints stays out of the one-line contract
    captured_err = io.StringIO()
    try:
        with contextlib.redirect_stdout(io.StringIO()), \
                contextlib.redirect_stderr(captured_err):
            result = run_benchmark(
                config_path=config,
                mean_interval_ms=mean_interval,
                num_videos=num_videos,
                log_base=os.environ.get("RNB_BENCH_LOG_BASE", "logs"),
                print_progress=False,
                seed=0,
            )
    except Exception as e:  # noqa: BLE001 — one-line contract on any failure
        done.set()
        sys.stderr.write(captured_err.getvalue())
        return _emit_error("%s: %s" % (type(e).__name__, e))
    done.set()

    value = result.throughput_vps
    print(json.dumps({
        "metric": "videos_per_sec",
        "value": round(value, 3),
        "unit": "videos/s",
        "vs_baseline": round(value / BASELINE_VIDEOS_PER_SEC, 3),
    }))
    if result.termination_flag != 0:
        sys.stderr.write(captured_err.getvalue())
        sys.stderr.write("bench: abnormal termination flag %d\n"
                         % result.termination_flag)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
