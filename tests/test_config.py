"""Config parsing, schema validation, queue wiring, device resolution."""

import glob
import os

import pytest

from rnb_tpu.config import (ConfigError, PipelineConfig, load_config,
                            parse_config)
from rnb_tpu.devices import DeviceResolutionError, DeviceSpec

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _minimal(overrides=None, step1=None, step0=None):
    cfg = {
        "video_path_iterator": "rnb_tpu.video_path_provider.VideoPathIterator",
        "pipeline": [
            {"model": "m.A",
             "queue_groups": [{"devices": [0], "out_queues": [0]}],
             **(step0 or {})},
            {"model": "m.B",
             "queue_groups": [{"devices": [1], "in_queue": 0}],
             **(step1 or {})},
        ],
    }
    cfg.update(overrides or {})
    return cfg


def test_parse_minimal():
    pc = parse_config(_minimal())
    assert pc.num_steps == 2
    assert pc.num_runners == 2
    assert pc.steps[0].groups[0].out_queues == [0]
    assert pc.steps[1].groups[0].in_queue == 0
    assert pc.steps[0].num_segments == 1


def test_gpus_alias_accepted():
    raw = _minimal()
    raw["pipeline"][0]["queue_groups"][0] = {"gpus": [0], "out_queues": [0]}
    pc = parse_config(raw)
    assert pc.steps[0].groups[0].devices == [DeviceSpec(0)]


def test_kwargs_passthrough_step_and_group():
    raw = _minimal(step1={"start_index": 1, "end_index": 5})
    raw["pipeline"][1]["queue_groups"][0]["end_index"] = 3
    pc = parse_config(raw)
    kw = pc.steps[1].kwargs_for_group(0)
    assert kw == {"start_index": 1, "end_index": 3}  # group overrides step


def test_wiring_mismatch_rejected():
    raw = _minimal()
    raw["pipeline"][1]["queue_groups"][0]["in_queue"] = 5
    with pytest.raises(ConfigError, match="do not match"):
        parse_config(raw)


def test_last_step_constraints():
    with pytest.raises(ConfigError, match="last step may not have multiple"):
        parse_config(_minimal(step1={"num_segments": 2}))
    with pytest.raises(ConfigError, match="does not need shared output"):
        parse_config(_minimal(step1={"num_shared_tensors": 4}))
    raw = _minimal()
    raw["pipeline"][1]["queue_groups"][0]["out_queues"] = [0]
    with pytest.raises(ConfigError, match="may not declare 'out_queues'"):
        parse_config(raw)


def test_first_step_rejects_in_queue():
    raw = _minimal()
    raw["pipeline"][0]["queue_groups"][0]["in_queue"] = 0
    with pytest.raises(ConfigError, match="filename queue"):
        parse_config(raw)


def test_missing_fields_rejected():
    with pytest.raises(ConfigError, match="video_path_iterator"):
        parse_config({"pipeline": []})
    with pytest.raises(ConfigError, match="non-empty"):
        parse_config({"video_path_iterator": "x.Y", "pipeline": []})
    raw = _minimal()
    del raw["pipeline"][0]["model"]
    with pytest.raises(ConfigError, match="'model'"):
        parse_config(raw)
    raw = _minimal()
    raw["pipeline"][0]["queue_groups"][0].pop("devices")
    with pytest.raises(ConfigError, match="'devices'"):
        parse_config(raw)


def test_num_segments_validation():
    with pytest.raises(ConfigError, match="positive integer"):
        parse_config(_minimal(step0={"num_segments": 0}))
    with pytest.raises(ConfigError, match="positive integer"):
        parse_config(_minimal(step0={"num_segments": "3"}))


def test_segments_exceeding_ring_slots_rejected():
    # the producer fills one ring slot per segment before publishing any
    # Signal, so slots < segments would self-deadlock at runtime — this
    # must fail fast at parse time instead
    with pytest.raises(ConfigError, match="deadlock"):
        parse_config(_minimal(step0={"num_segments": 3,
                                     "num_shared_tensors": 2}))
    # default ring depth is 10: 11 segments must also be rejected even
    # when 'num_shared_tensors' is omitted
    with pytest.raises(ConfigError, match="the default"):
        parse_config(_minimal(step0={"num_segments": 11}))
    # boundary: exactly as many slots as segments is legal
    pc = parse_config(_minimal(step0={"num_segments": 3,
                                      "num_shared_tensors": 3}))
    assert pc.steps[0].num_segments == 3


def test_shard_key_expands_to_group_kwargs():
    raw = _minimal(step1={"shard": {"degree": 2,
                                    "hbm_budget_mb": 256}})
    raw["pipeline"][1]["queue_groups"][0]["devices"] = [1, 2]
    pc = parse_config(raw)
    # one primary device -> ONE executor instance; the full ring rides
    # the open kwargs passthrough to the stage constructor
    assert pc.steps[1].groups[0].devices == [DeviceSpec(1)]
    kw = pc.steps[1].kwargs_for_group(0)
    assert kw["shard_devices"] == [1, 2]
    assert kw["shard_degree"] == 2
    assert kw["shard_axis"] == "tp"
    assert kw["shard_hbm_budget_mb"] == 256
    # config.raw keeps the as-written form (the job-dir copy)
    assert raw["pipeline"][1]["queue_groups"][0]["devices"] == [1, 2]
    assert "shard_devices" not in raw["pipeline"][1]["queue_groups"][0]


def test_shard_composes_replica_major_with_replicas():
    # replicas: 2 carves [1,2,3,4] into two lanes first, then each
    # lane's sub-mesh is one degree-2 shard ring
    raw = _minimal(step1={"replicas": 2, "shard": {"degree": 2}})
    raw["pipeline"][1]["queue_groups"][0]["devices"] = [1, 2, 3, 4]
    pc = parse_config(raw)
    groups = pc.steps[1].groups
    assert len(groups) == 2
    rings = [pc.steps[1].kwargs_for_group(i)["shard_devices"]
             for i in range(2)]
    assert rings == [[1, 2], [3, 4]]
    assert [g.devices for g in groups] == [[DeviceSpec(1)],
                                           [DeviceSpec(3)]]


def test_shard_key_rejections():
    with pytest.raises(ConfigError, match="must be an object"):
        parse_config(_minimal(step1={"shard": 2}))
    with pytest.raises(ConfigError, match="unknown key"):
        parse_config(_minimal(step1={"shard": {"degree": 2,
                                               "deg": 2}}))
    with pytest.raises(ConfigError, match="positive integer"):
        parse_config(_minimal(step1={"shard": {"degree": 0}}))
    with pytest.raises(ConfigError, match="positive integer"):
        parse_config(_minimal(step1={"shard": {"degree": True}}))
    with pytest.raises(ConfigError, match="positive number"):
        parse_config(_minimal(step1={"shard": {"degree": 2,
                                               "hbm_budget_mb": 0}}))
    # the lane's device list IS the ring: its length must equal degree
    with pytest.raises(ConfigError, match="exactly that many"):
        parse_config(_minimal(step1={"shard": {"degree": 2}}))
    raw = _minimal(step1={"shard": {"degree": 2}})
    raw["pipeline"][1]["queue_groups"][0]["devices"] = [1, -1]
    with pytest.raises(ConfigError, match="host"):
        parse_config(raw)
    with pytest.raises(ConfigError, match="num_segments"):
        parse_config(_minimal(step0={"shard": {"degree": 1},
                                     "num_segments": 2}))


def test_all_shipped_configs_parse_and_resolve():
    for path in sorted(glob.glob(os.path.join(REPO_ROOT, "configs",
                                              "*.json"))):
        pc = load_config(path)
        assert isinstance(pc, PipelineConfig)
        # every shipped config must fit the 8-device test backend
        pc.check_devices()


def test_device_spec_resolution():
    import jax
    assert DeviceSpec(0).resolve() == jax.devices()[0]
    assert DeviceSpec(-1).is_host
    assert DeviceSpec(-1).resolve().platform == "cpu"
    assert DeviceSpec("cpu:1").resolve() == jax.devices("cpu")[1]
    assert DeviceSpec(-1).label == "host"
    with pytest.raises(DeviceResolutionError, match="only"):
        DeviceSpec(99).resolve()
    with pytest.raises(DeviceResolutionError):
        DeviceSpec("nope:0").resolve()
    with pytest.raises(DeviceResolutionError):
        DeviceSpec(2.5).resolve()


def test_probe_busy_devices():
    from rnb_tpu.devices import BUSY_BYTES_THRESHOLD, probe_busy_devices

    class FakeSpec:
        is_host = False

        def __init__(self, stats, label="tpu:0"):
            self._stats = stats
            self.label = label

        def resolve(self):
            return self

        def memory_stats(self):
            if isinstance(self._stats, Exception):
                raise self._stats
            return self._stats

    busy = FakeSpec({"bytes_in_use": BUSY_BYTES_THRESHOLD + 1})
    idle = FakeSpec({"bytes_in_use": 512 * 1024}, label="tpu:1")
    opaque = FakeSpec(None, label="tpu:2")
    broken = FakeSpec(RuntimeError("no stats"), label="tpu:3")
    host = FakeSpec({"bytes_in_use": 10 ** 12}, label="host")
    host.is_host = True

    warnings = probe_busy_devices([busy, idle, opaque, broken, host, busy])
    assert len(warnings) == 1  # busy flagged once despite appearing twice
    assert "tpu:0" in warnings[0] and "in use" in warnings[0]

    # real backend: must never raise, whatever the platform reports
    pc = parse_config(_minimal())
    assert isinstance(probe_busy_devices(pc.all_devices()), list)


def test_check_devices_over_config():
    raw = _minimal()
    raw["pipeline"][0]["queue_groups"][0]["devices"] = [42]
    pc = parse_config(raw)
    with pytest.raises(DeviceResolutionError):
        pc.check_devices()
