"""Self-healing replica serving (rnb_tpu.health, PR 10).

Tier-1 coverage of the lane-health/circuit-breaking, deadline-
propagation and hedged-re-dispatch contracts on the 8-virtual-device
CPU backend:

* the :class:`LaneHealthBoard` state machine, driven with explicit
  clocks — every transition path pinned against the legal automaton;
* the :class:`ReplicaSelector` health gate + the STABLE lowest-lane
  tie-break under eviction, with the routing sequence for a seeded
  kill schedule pinned exactly (chaos arms must replay identically);
* deadline settings/semantics (budget seeded from ``autotune.slo_ms``,
  fused batches shed only when every member expired);
* the :class:`HedgeGovernor` exactly-once claim ledger and p95x
  threshold gating;
* the new ``replica_crash``/``replica_stall``/``lane`` fault-plan
  schema;
* end-to-end: a mid-stream lane kill with eviction + redispatch and
  every request terminating exactly once; deadline expiry shedding
  under overload; hedged re-dispatch past a wedged lane with the
  hedge WINNING and the loser discarded by rid; per-lane shed-site
  accounting on a full replica lane queue; a contained decode failure
  inside a fused batch with downstream replicas — all with
  ``parse_utils --check`` green;
* the ``--check`` exit-code discipline (2 = parse failure, 1 =
  invariant violation) and violation fixtures for the new
  Health:/Deadline:/Hedge: invariants;
* log-meta byte-stability with every self-healing feature off.
"""

import json
import os
import sys
import tempfile

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "scripts"))

import parse_utils  # noqa: E402

from rnb_tpu.config import ConfigError, parse_config  # noqa: E402
from rnb_tpu.faults import (FaultPlan, LaneDeathError,  # noqa: E402
                            classify_error, validate_plan)
from rnb_tpu.handoff import InflightDepths  # noqa: E402
from rnb_tpu.health import (EVICTED, HALF_OPEN, HEALTHY,  # noqa: E402
                            LOSER, OPEN, SUSPECT, UNTRACKED, WINNER,
                            DeadlineSettings, HealthSettings,
                            HedgeGovernor, LaneHealthBoard, expired,
                            legal_path)
from rnb_tpu.selector import ReplicaSelector  # noqa: E402
from rnb_tpu.telemetry import TimeCard, TimeCardList  # noqa: E402


def _settings(suspect=100.0, open_=300.0, probe=200.0):
    return HealthSettings(suspect_after_ms=suspect,
                          open_after_ms=open_,
                          probe_interval_ms=probe)


# -- the lane state machine -------------------------------------------

def test_board_walks_the_full_circuit_and_recovers():
    board = LaneHealthBoard([4, 5], _settings())
    t0 = 1000.0
    board.note_enqueue(4, now=t0)
    # fresh dispatch: still healthy
    allowed, probe = board.route_filter([4, 5], now=t0 + 0.05)
    assert allowed == [4, 5] and probe is None
    # oldest in-flight item ages past suspect_after_ms
    allowed, _ = board.route_filter([4, 5], now=t0 + 0.15)
    assert board.state(4) == SUSPECT
    assert allowed == [4, 5], "suspect lanes still serve"
    # past open_after_ms the circuit opens: lane leaves the set
    allowed, _ = board.route_filter([4, 5], now=t0 + 0.35)
    assert board.state(4) == OPEN
    assert allowed == [5]
    # probe_interval later: half-open, exactly one probe is granted
    allowed, probe = board.route_filter([4, 5], now=t0 + 0.60)
    assert probe == 4 and board.state(4) == HALF_OPEN
    _, probe2 = board.route_filter([4, 5], now=t0 + 0.61)
    assert probe2 is None, "only one outstanding probe"
    # the probe settles: the lane heals
    board.note_settle(4)
    assert board.state(4) == HEALTHY
    snap = board.snapshot()
    assert snap["lane_detail"]["4"]["path"] == [
        HEALTHY, SUSPECT, OPEN, HALF_OPEN, HEALTHY]
    assert legal_path(snap["lane_detail"]["4"]["path"])
    assert snap["opens"] == 1 and snap["probes"] == 1
    assert snap["transitions"] == 4


def test_board_suspect_recovers_without_opening():
    board = LaneHealthBoard([1, 2], _settings())
    t0 = 50.0
    board.note_enqueue(1, now=t0)
    board.route_filter([1, 2], now=t0 + 0.15)
    assert board.state(1) == SUSPECT
    board.note_settle(1)  # the slow dispatch completed after all
    # recovery needs a suspect_after_ms dwell (anti-flap), so just
    # after the signal clears the lane stays suspect...
    board.route_filter([1, 2], now=t0 + 0.20)
    assert board.state(1) == SUSPECT
    # ...and heals once it has dwelled clean
    board.route_filter([1, 2], now=t0 + 0.30)
    assert board.state(1) == HEALTHY
    assert board.snapshot()["lane_detail"]["1"]["path"] == [
        HEALTHY, SUSPECT, HEALTHY]


def test_board_fast_failing_lane_trips_on_dead_letters():
    """A lane that fails every dispatch QUICKLY is low-distress (it
    beats and settles promptly) — the dead-letter count must trip the
    circuit anyway, and a still-failing lane must never heal."""
    from rnb_tpu.health import FAILURE_TRIP_THRESHOLD
    board = LaneHealthBoard([1, 2], _settings())
    t0 = 10.0
    for _ in range(FAILURE_TRIP_THRESHOLD):
        board.note_failure(1)
    board.beat(1, now=t0 + 0.05)
    assert board.state(1) == SUSPECT
    # fresh failures at the suspect rung escalate to open
    for _ in range(FAILURE_TRIP_THRESHOLD):
        board.note_failure(1)
    board.beat(1, now=t0 + 0.10)
    assert board.state(1) == OPEN
    snap = board.snapshot()
    assert snap["lane_detail"]["1"]["path"] == [HEALTHY, SUSPECT, OPEN]
    # a suspect lane that KEEPS failing cannot heal even past the
    # dwell window
    board2 = LaneHealthBoard([1, 2], _settings())
    for _ in range(FAILURE_TRIP_THRESHOLD):
        board2.note_failure(1)
    board2.beat(1, now=t0 + 0.05)
    assert board2.state(1) == SUSPECT
    board2.note_failure(1)
    board2.beat(1, now=t0 + 0.50)
    assert board2.state(1) == SUSPECT


def test_hedge_discard_counts_only_the_hedged_step_span():
    """Waste attribution: only the deepest inference span (the losing
    dispatch itself) counts — shared pre-fork spans were paid once by
    both copies, and an unfinished losing span counts 0."""
    gov = HedgeGovernor(5.0)
    tc = TimeCard(1)
    tc.record("inference0_start", at=10.0)
    tc.record("inference0_finish", at=10.08)   # shared 80 ms decode
    tc.record("inference1_start", at=10.10)
    tc.record("inference1_finish", at=10.15)   # the losing 50 ms
    gov.discard(tc)
    assert abs(gov.wasted_ms - 50.0) < 1.0, gov.wasted_ms
    # loser that never finished the hedged step: 0, not the shared 80
    gov2 = HedgeGovernor(5.0)
    tc2 = TimeCard(2)
    tc2.record("inference0_start", at=10.0)
    tc2.record("inference0_finish", at=10.08)
    tc2.record("inference1_start", at=10.10)   # failed mid-service
    gov2.discard(tc2)
    assert gov2.wasted_ms == 0.0


def test_board_beat_advances_the_clockwork():
    """A wedged lane's circuit must open even when the producer never
    routes again — sibling beats drive the evaluation."""
    board = LaneHealthBoard([1, 2], _settings())
    t0 = 10.0
    board.note_enqueue(1, now=t0)
    board.beat(2, now=t0 + 0.15)  # the SIBLING's liveness beat
    assert board.state(1) == SUSPECT
    board.beat(2, now=t0 + 0.35)
    assert board.state(1) == OPEN


def test_board_stale_beat_with_work_outstanding_is_distress():
    board = LaneHealthBoard([1], _settings())
    t0 = 5.0
    board.beat(1, now=t0)
    # items keep arriving but the executor stopped beating: the beat
    # staleness (not just item age) trips the circuit
    board.note_enqueue(1, now=t0 + 0.29)
    board.route_filter([1], now=t0 + 0.31)
    assert board.state(1) == SUSPECT
    # an IDLE lane (nothing in flight) is silent, not sick
    board2 = LaneHealthBoard([1], _settings())
    board2.beat(1, now=t0)
    board2.route_filter([1], now=t0 + 99.0)
    assert board2.state(1) == HEALTHY


def test_board_eviction_is_terminal_and_legal_from_any_state():
    for prep in (lambda b, t: None,                       # healthy
                 lambda b, t: (b.note_enqueue(1, now=t),  # open
                               b.route_filter([1], now=t + 0.5))):
        board = LaneHealthBoard([1, 2], _settings())
        prep(board, 1.0)
        board.evict(1, "replica-crash")
        assert board.state(1) == EVICTED
        board.evict(1, "again")  # idempotent
        snap = board.snapshot()
        assert snap["evictions"] == 1
        assert legal_path(snap["lane_detail"]["1"]["path"])
        allowed, probe = board.route_filter([1, 2], now=999.0)
        assert allowed == [2] and probe is None


def test_legal_path_rejects_illegal_walks():
    assert legal_path([HEALTHY])
    assert legal_path([HEALTHY, SUSPECT, OPEN, HALF_OPEN, OPEN,
                       HALF_OPEN, HEALTHY])
    assert not legal_path([SUSPECT, OPEN])          # must start healthy
    assert not legal_path([HEALTHY, OPEN])          # no skip to open
    assert not legal_path([HEALTHY, EVICTED, HEALTHY])  # terminal
    assert not legal_path([])


def test_routes_after_open_counts_violations_not_probes():
    board = LaneHealthBoard([1, 2], _settings())
    board.note_enqueue(1, now=0.0)
    # one transition hop per evaluation tick: suspect, then open
    board.route_filter([1, 2], now=0.15)
    board.route_filter([1, 2], now=0.5)
    assert board.state(1) == OPEN
    board.note_route(1)            # violation: sibling 2 was routable
    board.note_route(2)
    board.note_route(1, forced=True)  # exempt: no-sibling fallback
    snap = board.snapshot()
    assert snap["routes_after_open"] == 1


def test_drained_latch_covers_every_lane():
    board = LaneHealthBoard([1, 2], _settings())
    assert not board.all_drained()
    board.note_drained(1)
    assert not board.all_drained()
    board.note_drained(2)
    assert board.all_drained()


def test_health_settings_validation():
    with pytest.raises(ValueError):
        HealthSettings(suspect_after_ms=0)
    with pytest.raises(ValueError):
        HealthSettings(suspect_after_ms=500, open_after_ms=100)
    assert HealthSettings.from_config(None) is None
    assert HealthSettings.from_config({"enabled": False}) is None
    s = HealthSettings.from_config({"suspect_after_ms": 50})
    assert s.suspect_after_ms == 50.0


# -- selector: health gate + stable tie-break (seeded kill schedule) --

def _bound_selector(lanes, board=None):
    depths = InflightDepths(lanes)
    sel = ReplicaSelector(len(lanes))
    sel.bind_depths(depths, lanes)
    if board is not None:
        sel.bind_health(board)
    return sel, depths


def test_replica_selector_tie_break_is_stable_under_eviction():
    """The regression the seeded chaos arms rely on: with lanes
    excluded by eviction/circuit-open, the survivors keep their
    original relative order and the lowest-lane tie-break replays the
    identical routing sequence for the same depth sequence."""
    lanes = [3, 4, 5, 6]
    board = LaneHealthBoard(lanes, _settings())
    sel, depths = _bound_selector(lanes, board)

    def route():
        pos = sel.select(None, None, None)
        q = lanes[pos]
        depths.inc(q)
        return q

    # seeded kill schedule: 4 routes healthy, kill lane 4, 6 routes,
    # kill lane 3, 4 routes — the full sequence is pinned
    seq = [route() for _ in range(4)]
    assert seq == [3, 4, 5, 6], seq
    board.evict(4, "chaos-kill-1")
    seq2 = [route() for _ in range(6)]
    # lane 4 is skipped STABLY: survivors 3,5,6 in original order,
    # least-loaded with lowest-lane tie-break over equal depths
    assert seq2 == [3, 5, 6, 3, 5, 6], seq2
    board.evict(3, "chaos-kill-2")
    seq3 = [route() for _ in range(4)]
    assert seq3 == [5, 6, 5, 6], seq3
    # replay: a fresh selector fed the same schedule reproduces the
    # identical sequence (pure function of depths + board state)
    board_b = LaneHealthBoard(lanes, _settings())
    sel_b, depths_b = _bound_selector(lanes, board_b)

    def route_b():
        pos = sel_b.select(None, None, None)
        q = lanes[pos]
        depths_b.inc(q)
        return q

    replay = [route_b() for _ in range(4)]
    board_b.evict(4, "chaos-kill-1")
    replay += [route_b() for _ in range(6)]
    board_b.evict(3, "chaos-kill-2")
    replay += [route_b() for _ in range(4)]
    assert replay == seq + seq2 + seq3


def test_replica_selector_routes_probe_to_half_open_lane():
    lanes = [1, 2]
    board = LaneHealthBoard(lanes, _settings())
    sel, depths = _bound_selector(lanes, board)
    board.note_enqueue(1, now=0.0)
    board.route_filter(lanes, now=0.15)     # lane 1 -> suspect
    board.route_filter(lanes, now=0.5)      # lane 1 -> open
    assert board.state(1) == OPEN
    # wall clock >> probe deadline: the next select issues the probe
    pos = sel.select(None, None, None)
    assert lanes[pos] == 1 and board.state(1) == HALF_OPEN
    assert board.snapshot()["probes"] == 1
    assert board.snapshot()["routes_after_open"] == 0


def test_replica_selector_forced_route_when_everything_is_down():
    lanes = [1, 2]
    board = LaneHealthBoard(lanes, _settings())
    sel, depths = _bound_selector(lanes, board)
    board.evict(1, "x")
    board.evict(2, "y")
    pos = sel.select(None, None, None)
    assert lanes[pos] in lanes and sel.last_route_forced
    assert board.snapshot()["routes_after_open"] == 0  # forced exempt


# -- deadline settings + semantics ------------------------------------

def test_deadline_budget_seeds_from_autotune_slo():
    assert DeadlineSettings.from_config(None) is None
    assert DeadlineSettings.from_config({"enabled": False}) is None
    s = DeadlineSettings.from_config({}, {"slo_ms": 80.0})
    assert s.budget_ms == 80.0
    s = DeadlineSettings.from_config({"budget_ms": 30}, {"slo_ms": 80})
    assert s.budget_ms == 30.0
    s = DeadlineSettings.from_config({})
    assert s.budget_ms == DeadlineSettings.DEFAULT_BUDGET_MS


def test_expired_requires_every_fused_member_blown():
    a, b = TimeCard(1), TimeCard(2)
    a.deadline_s, b.deadline_s = 10.0, 20.0
    fused = TimeCardList([a, b])
    assert not expired(fused, now=15.0)  # b can still make it
    assert expired(fused, now=25.0)
    # undeadlined cards never expire (feature-off runs, exit markers)
    assert not expired(TimeCard(3), now=1e12)
    c = TimeCard(4)
    c.deadline_s = 1.0
    assert not expired(TimeCardList([a, c, TimeCard(5)]), now=1e12)


# -- hedge governor ----------------------------------------------------

def _tracked(gov, rid=7, lane=1, t=100.0):
    tc = TimeCard(rid)
    tc.record("enqueue_filename", at=1.0)
    gov.track(tc, lane, ("payload",), None, now=t)
    return tc


def test_hedge_claim_resolves_exactly_once_each_copy():
    gov = HedgeGovernor(5.0)
    tc = _tracked(gov)
    due = gov.poll(now=100.006)
    assert len(due) == 1 and due[0].lane == 1
    assert gov.begin_fire(due[0])
    assert gov.poll(now=100.1) == [], "a fired hedge never re-fires"
    # the hedge copy resolves first: WINNER, counted won
    assert gov.claim(due[0].card) == WINNER
    assert gov.claim(tc) == LOSER
    assert gov.claim(tc) == UNTRACKED
    snap = gov.snapshot()
    assert (snap["fired"], snap["won"], snap["lost"]) == (1, 1, 0)


def test_hedge_original_winning_counts_lost():
    gov = HedgeGovernor(5.0)
    tc = _tracked(gov)
    due = gov.poll(now=101.0)
    assert gov.begin_fire(due[0])
    assert gov.claim(tc) == WINNER          # original got there first
    assert gov.claim(due[0].card) == LOSER
    snap = gov.snapshot()
    assert (snap["fired"], snap["won"], snap["lost"]) == (1, 0, 1)


def test_hedge_unresolved_at_teardown_counts_lost():
    gov = HedgeGovernor(5.0)
    _tracked(gov)
    assert gov.begin_fire(gov.poll(now=200.0)[0])
    snap = gov.snapshot()
    assert snap["won"] + snap["lost"] == snap["fired"] == 1


def test_hedge_settled_dispatches_never_hedge():
    gov = HedgeGovernor(5.0)
    tc = _tracked(gov)
    gov.settle(tc, now=100.004)
    assert gov.poll(now=200.0) == []


def test_hedge_never_fires_for_an_already_resolved_dispatch():
    """The fire-after-resolve race: a dispatch that completed (claim
    ran, returned UNTRACKED) between the producer's poll() and its
    enqueue must NOT be hedged — begin_fire re-checks under the same
    lock claim() settles in, so the late copy can never claim WINNER
    and publish the request a second time."""
    gov = HedgeGovernor(5.0)
    tc = _tracked(gov)
    due = gov.poll(now=200.0)
    assert len(due) == 1
    # the consumer resolves the dispatch while the producer holds its
    # poll snapshot
    assert gov.claim(tc, now=200.0) == UNTRACKED
    assert gov.begin_fire(due[0]) is False
    snap = gov.snapshot()
    assert snap["fired"] == 0
    # and once resolved it never re-enters the poll window either
    assert gov.poll(now=300.0) == []


def test_hedge_begin_fire_is_exactly_once_and_cancelable():
    gov = HedgeGovernor(5.0)
    _tracked(gov)
    due = gov.poll(now=200.0)
    assert gov.begin_fire(due[0]) is True
    assert gov.begin_fire(due[0]) is False  # double-fire blocked
    gov2 = HedgeGovernor(5.0)
    _tracked(gov2)
    entry = gov2.poll(now=200.0)[0]
    assert gov2.begin_fire(entry) is True
    gov2.cancel_fire(entry)  # sibling queue was full: roll back
    assert gov2.snapshot()["fired"] == 0
    # the entry is hedgeable again on a later tick
    entry2 = gov2.poll(now=300.0)
    assert len(entry2) == 1


def test_hedge_p95x_needs_samples_then_tracks_latency():
    gov = HedgeGovernor("p95x")
    assert gov.threshold_ms() is None  # cold: never hedge
    for i in range(6):
        tc = TimeCard(i)
        gov.track(tc, 1, None, None, now=10.0 + i)
        gov.settle(tc, now=10.0 + i + 0.010)  # 10 ms settles
    thr = gov.threshold_ms()
    assert thr is not None and 10.0 <= thr < 50.0
    # an untracked rid claims UNTRACKED (no hedge was ever fired)
    assert gov.claim(TimeCard(99)) == UNTRACKED


def test_hedge_clone_is_stamp_complete_and_marked():
    from rnb_tpu.health import clone_cards
    tc = TimeCard(3)
    tc.record("enqueue_filename", at=1.0)
    tc.num_clips = 2
    clone = clone_cards(tc)
    assert clone.id == 3 and clone.hedge_copy
    assert clone.timings == tc.timings and clone.num_clips == 2
    clone.record("inference1_start", at=2.0)
    assert "inference1_start" not in tc.timings, "distinct objects"
    fused = TimeCardList([TimeCard(1), TimeCard(2)])
    cl = clone_cards(fused)
    assert [c.id for c in cl.time_cards] == [1, 2]
    assert all(c.hedge_copy for c in cl.time_cards)


# -- fault-plan schema for lane deaths --------------------------------

def test_fault_plan_accepts_and_fires_lane_kinds():
    plan = FaultPlan({"faults": [
        {"kind": "replica_crash", "step": 1, "lane": 3,
         "probability": 1.0}]})
    with pytest.raises(LaneDeathError) as e:
        plan.fire(1, [5], lane=3)
    assert e.value.fate == "crash"
    plan.fire(1, [5], lane=2)   # other lane: nothing fires
    plan.fire(0, [5], lane=3)   # other step: nothing fires
    plan.fire(1, [5], lane=3, attempt=1)  # retries never re-kill
    # a stall wedges then dies
    plan2 = FaultPlan({"faults": [
        {"kind": "replica_stall", "step": 1, "ms": 0,
         "probability": 1.0}]})
    with pytest.raises(LaneDeathError) as e2:
        plan2.fire(1, [5], lane=0)
    assert e2.value.fate == "stall"
    # LaneDeathError escaping to classification is FATAL (a chaos
    # plan aimed at a lane-less step must abort loudly)
    assert classify_error(e2.value) == "fatal"


def test_fault_plan_rejects_bad_lane_kind_specs():
    with pytest.raises(ValueError):
        validate_plan({"faults": [
            {"kind": "replica_crash", "probability": 1.0, "ms": 5}]})
    with pytest.raises(ValueError):
        validate_plan({"faults": [
            {"kind": "replica_stall", "probability": 1.0}]})  # no ms
    with pytest.raises(ValueError):
        validate_plan({"faults": [
            {"kind": "replica_crash", "probability": 1.0,
             "times": 2}]})
    with pytest.raises(ValueError):
        validate_plan({"faults": [
            {"kind": "replica_crash", "probability": 1.0,
             "lane": -1}]})
    validate_plan({"faults": [
        {"kind": "replica_stall", "ms": 10, "lane": 2,
         "request_ids": [1]}]})
    # ANY kind may be lane-addressed: a lane-scoped latency/stall is
    # the slow-lane chaos class, error kinds a lane-local fault domain
    validate_plan({"faults": [
        {"kind": "stall", "ms": 10, "lane": 1, "probability": 0.5},
        {"kind": "transient", "probability": 1.0, "lane": 1}]})


def test_lane_addressed_slow_lane_faults_fire_per_lane():
    plan = FaultPlan({"faults": [
        {"kind": "stall", "step": 1, "ms": 50, "lane": 2,
         "probability": 1.0}]})
    assert plan.stall_ms(1, [0], lane=2) == 50.0
    assert plan.stall_ms(1, [0], lane=3) == 0.0
    assert plan.stall_ms(1, [0]) == 0.0  # lane-less site never matches
    plan2 = FaultPlan({"faults": [
        {"kind": "permanent", "step": 1, "lane": 2,
         "probability": 1.0}]})
    plan2.fire(1, [0], lane=3)  # other lane: clean
    with pytest.raises(Exception):
        plan2.fire(1, [0], lane=2)


# -- config schema ----------------------------------------------------

def _cfg(step_extra=None, root_extra=None):
    cfg = {
        "video_path_iterator":
            "tests.pipeline_helpers.CountingPathIterator",
        "pipeline": [
            {"model": "tests.pipeline_helpers.TinyLoader",
             "queue_groups": [{"devices": [0], "out_queues": [0]}]},
            {"model": "tests.pipeline_helpers.TinySink",
             "replicas": 2,
             "queue_groups": [{"devices": [1, 2], "in_queue": 0}]},
        ],
    }
    if step_extra:
        cfg["pipeline"][1].update(step_extra)
    if root_extra:
        cfg.update(root_extra)
    return cfg


def test_config_accepts_and_rejects_health_deadline_hedge():
    cfg = parse_config(_cfg(
        step_extra={"hedge_ms": "p95x"},
        root_extra={"health": {"suspect_after_ms": 50},
                    "deadline": {"budget_ms": 100}}))
    assert cfg.health == {"suspect_after_ms": 50}
    assert cfg.deadline == {"budget_ms": 100}
    assert cfg.steps[1].hedge_ms == "p95x"
    with pytest.raises(ConfigError):
        parse_config(_cfg(root_extra={"health": {"bogus": 1}}))
    with pytest.raises(ConfigError):
        parse_config(_cfg(root_extra={
            "health": {"suspect_after_ms": 500,
                       "open_after_ms": 100}}))
    with pytest.raises(ConfigError):
        parse_config(_cfg(root_extra={"deadline": {"budget_ms": 0}}))
    with pytest.raises(ConfigError):
        parse_config(_cfg(step_extra={"hedge_ms": "p99x"}))
    with pytest.raises(ConfigError):
        parse_config(_cfg(step_extra={"hedge_ms": -5}))
    # hedge_ms needs replica lanes to re-dispatch onto
    bad = _cfg(step_extra={"hedge_ms": 5})
    del bad["pipeline"][1]["replicas"]
    bad["pipeline"][1]["queue_groups"][0]["devices"] = [1]
    with pytest.raises(ConfigError):
        parse_config(bad)


# -- end-to-end --------------------------------------------------------

def _run(cfg, videos=16, **kwargs):
    from rnb_tpu.benchmark import run_benchmark
    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "cfg.json")
        with open(path, "w") as f:
            json.dump(cfg, f)
        res = run_benchmark(path, mean_interval_ms=0,
                            num_videos=videos, queue_size=64,
                            log_base=tmp, print_progress=False,
                            seed=5, **kwargs)
        problems, parse_failed = parse_utils.check_job_detail(
            res.log_dir)
        with open(os.path.join(res.log_dir, "log-meta.txt")) as f:
            meta_text = f.read()
        res.parsed_meta = parse_utils.parse_meta(res.log_dir)
        return res, problems, parse_failed, meta_text


def test_e2e_lane_crash_contained_and_redispatched():
    """A replica lane crashing mid-stream: the in-service dispatch
    dead-letters, queued work moves to the healthy sibling, every
    request terminates exactly once, the selector never feeds the
    dead lane after eviction — and --check agrees."""
    cfg = _cfg(root_extra={
        "health": {"suspect_after_ms": 100, "open_after_ms": 300,
                   "probe_interval_ms": 200},
        "fault_plan": {"faults": [
            {"kind": "replica_crash", "step": 1, "lane": 1,
             "probability": 1.0},
            {"kind": "latency", "step": 1, "probability": 1.0,
             "ms": 30}]}})
    res, problems, _pf, meta_text = _run(cfg)
    assert problems == [], problems
    assert res.termination_flag == 0
    assert res.num_completed + res.num_failed + res.num_shed == 16
    assert res.num_failed >= 1
    assert res.failure_reasons.get("replica-crash") == res.num_failed
    assert res.health_evictions == 1
    assert res.health_lane_detail["1"]["state"] == EVICTED
    assert res.health_routes_after_open == 0
    assert "Health:" in meta_text and "Health lanes:" in meta_text


def test_e2e_multi_instance_lane_death_drains_after_last_instance():
    """A lane carrying TWO executor instances (a multi-device
    sub-mesh per replica): a lane-addressed kill takes both down —
    only the LAST death may drain the queue (the first dying instance
    must leave the survivor's work alone), and nothing strands."""
    cfg = {
        "video_path_iterator":
            "tests.pipeline_helpers.CountingPathIterator",
        "health": {"suspect_after_ms": 200, "open_after_ms": 600,
                   "probe_interval_ms": 400},
        "fault_plan": {"faults": [
            {"kind": "replica_crash", "step": 1, "lane": 1,
             "probability": 1.0},
            {"kind": "latency", "step": 1, "probability": 1.0,
             "ms": 30}]},
        "pipeline": [
            {"model": "tests.pipeline_helpers.TinyLoader",
             "queue_groups": [{"devices": [0], "out_queues": [0]}]},
            # replicas 2 over 4 devices -> 2 instances per lane
            {"model": "tests.pipeline_helpers.TinySink", "replicas": 2,
             "queue_groups": [{"devices": [1, 2, 3, 4],
                               "in_queue": 0}]},
        ],
    }
    res, problems, _pf, _meta = _run(cfg, videos=20)
    assert problems == [], problems
    assert res.termination_flag == 0
    # both instances of lane 1 die (one dead-letter each), everything
    # else terminates exactly once on the surviving lane
    assert res.num_completed + res.num_failed + res.num_shed == 20
    assert res.num_failed == 2
    assert res.failure_reasons == {"replica-crash": 2}
    assert res.health_evictions == 1
    assert res.health_lane_detail["1"]["state"] == EVICTED


def test_lane_faults_without_health_are_rejected_at_launch():
    """A lane death without the health layer cannot be contained (no
    eviction, no drain, no sibling linger) — the launcher must fail
    fast instead of letting the run hang to the barrier timeout."""
    cfg = _cfg(root_extra={"fault_plan": {"faults": [
        {"kind": "replica_crash", "step": 1, "lane": 1,
         "probability": 1.0}]}})
    from rnb_tpu.benchmark import run_benchmark
    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "cfg.json")
        with open(path, "w") as f:
            json.dump(cfg, f)
        with pytest.raises(ValueError, match="health"):
            run_benchmark(path, mean_interval_ms=0, num_videos=4,
                          queue_size=16, log_base=tmp,
                          print_progress=False, seed=1)


def test_e2e_deadline_sheds_expired_work_with_check_green():
    cfg = {
        "video_path_iterator":
            "tests.pipeline_helpers.CountingPathIterator",
        "deadline": {"budget_ms": 150},
        "overload_policy": "shed",
        "pipeline": [
            {"model": "tests.pipeline_helpers.TinyLoader",
             "queue_groups": [{"devices": [0], "out_queues": [0]}]},
            {"model": "tests.pipeline_helpers.TinySlowSink",
             "delay_s": 0.05,
             "queue_groups": [{"devices": [1], "in_queue": 0}]},
        ],
    }
    res, problems, _pf, meta_text = _run(cfg, videos=20)
    assert problems == [], problems
    assert res.termination_flag == 0
    assert res.deadline_expired > 0
    assert res.deadline_expired == res.num_shed
    assert sum(res.deadline_sites.values()) == res.deadline_expired
    assert all(site.endswith(":deadline_expired")
               for site in res.deadline_sites)
    # doomed work was dropped BEFORE service, not after: completions
    # + expiry sheds partition the stream
    assert res.num_completed + res.num_shed == 20
    assert "Deadline:" in meta_text and "Deadline sites:" in meta_text


def test_e2e_hedge_wins_past_a_wedged_lane():
    """One lane wedges on its first dispatch (a 'stall' fault, no
    death): the hedge re-issues that dispatch on the healthy sibling,
    the hedge copy WINS, the wedged original resolves later as the
    loser and is discarded by rid — every request still terminates
    exactly once and the waste is accounted."""
    cfg = {
        "video_path_iterator":
            "tests.pipeline_helpers.CountingPathIterator",
        "health": {"suspect_after_ms": 5000, "open_after_ms": 10000,
                   "probe_interval_ms": 5000},
        # the slow-lane chaos class: the stall is LANE-addressed, so
        # only lane 1's copy wedges — the hedge re-issued on lane 2
        # runs clean (an un-addressed stall would wedge both copies
        # and the hedge could never win)
        "fault_plan": {"faults": [
            {"kind": "stall", "step": 1, "ms": 1200, "lane": 1,
             "request_ids": [0]}]},
        "pipeline": [
            {"model": "tests.pipeline_helpers.TinyLoader",
             "queue_groups": [{"devices": [0], "out_queues": [0]}]},
            # a sink with real (50 ms) service so the discarded
            # loser's burned span is measurable in hedges_wasted_ms
            {"model": "tests.pipeline_helpers.TinySlowSink",
             "delay_s": 0.05, "replicas": 2, "hedge_ms": 100,
             "queue_groups": [{"devices": [1, 2], "in_queue": 0}]},
        ],
    }
    res, problems, _pf, meta_text = _run(cfg, videos=10)
    assert problems == [], problems
    assert res.termination_flag == 0
    assert res.num_completed == 10 and res.num_failed == 0
    assert res.hedges_fired >= 1
    assert res.hedges_won + res.hedges_lost == res.hedges_fired
    assert res.hedges_won >= 1, (
        "the wedged original should lose to the hedge copy")
    assert res.hedges_wasted_ms > 0
    assert "Hedge:" in meta_text


def test_e2e_full_replica_lane_queue_sheds_per_lane():
    """Satellite: shed-at-full-queue on a *replica* lane queue — the
    shed site names the lane, so per-lane accounting survives the
    replica expansion."""
    cfg = {
        "video_path_iterator":
            "tests.pipeline_helpers.CountingPathIterator",
        "overload_policy": "shed",
        "pipeline": [
            {"model": "tests.pipeline_helpers.TinyLoader",
             "queue_groups": [{"devices": [0], "out_queues": [0]}]},
            {"model": "tests.pipeline_helpers.TinySlowSink",
             "delay_s": 0.05, "replicas": 2,
             "queue_groups": [{"devices": [1, 2], "in_queue": 0}]},
        ],
    }
    from rnb_tpu.benchmark import run_benchmark
    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "cfg.json")
        with open(path, "w") as f:
            json.dump(cfg, f)
        # Poisson mode keeps the configured (tiny) queue size, so the
        # lane queues really fill; bulk mode would resize them
        res = run_benchmark(path, mean_interval_ms=1, num_videos=40,
                            queue_size=2, log_base=tmp,
                            print_progress=False, seed=5)
        problems = parse_utils.check_job(res.log_dir)
    assert problems == [], problems
    assert res.termination_flag == 0
    assert res.num_shed > 0
    lane_sites = [s for s in res.shed_sites
                  if s.startswith("step0_out_queue.lane")]
    assert lane_sites, ("replica-lane sheds must carry per-lane "
                        "sites, got %s" % res.shed_sites)


def _write_tiny_dataset(root):
    """3 valid 2-frame y4m videos + 1 corrupt one in a label subtree
    (the test_fault_containment fixture shape)."""
    import numpy as np
    from rnb_tpu.decode import write_y4m
    label = os.path.join(root, "label0")
    os.makedirs(label, exist_ok=True)
    rng = np.random.default_rng(0)
    for i in range(3):
        frames = rng.integers(0, 256, (4, 16, 16, 3), dtype=np.uint8)
        write_y4m(os.path.join(label, "ok%d.y4m" % i), frames,
                  colorspace="420")
    with open(os.path.join(label, "bad.y4m"), "wb") as f:
        f.write(b"NOT_A_Y4M_STREAM totally corrupt payload\n")


@pytest.mark.chaos
def test_e2e_contained_decode_failure_with_replica_siblings(
        tmp_path, monkeypatch):
    """Satellite: a REAL decode failure contained inside a fused
    batch (the loader's take_failed path, not an executor-level
    injection) while the surviving fused emissions route across two
    replica lanes — the corrupt video dead-letters, its batchmates
    complete on whichever lane they landed, --check green."""
    data_root = str(tmp_path / "data")
    _write_tiny_dataset(data_root)
    monkeypatch.setenv("RNB_TPU_DATA_ROOT", data_root)
    cfg = {
        "video_path_iterator":
            "rnb_tpu.models.r2p1d.model.R2P1DVideoPathIterator",
        "pipeline": [
            {"model": "rnb_tpu.models.r2p1d.model.R2P1DFusingLoader",
             "queue_groups": [{"devices": [0], "out_queues": [0]}],
             "max_clips": 2, "consecutive_frames": 2, "fuse": 2,
             "num_clips_population": [1], "weights": [1],
             "num_warmups": 0},
            {"model": "tests.pipeline_helpers.TinySink",
             "replicas": 2,
             "queue_groups": [{"devices": [1, 2], "in_queue": 0}]},
        ],
    }
    # 8 requests cycling 4 files (sorted: bad, ok0..ok2): the corrupt
    # video is fused into a batch exactly twice
    res, problems, _pf, _meta = _run(cfg, videos=8)
    assert problems == [], problems
    assert res.termination_flag == 0
    assert res.num_failed == 2
    assert res.failure_reasons == {"corrupt-video": 2}
    assert res.num_completed == 6


@pytest.mark.slow
@pytest.mark.chaos
def test_shipped_chaos_arm_contains_a_replica_loss():
    """The tier-1-adjacent gate as a registered chaos test: the
    shipped 4-replica chaos arm (`make chaos`) must contain a seeded
    mid-stream lane loss end-to-end."""
    import chaos_demo
    assert chaos_demo.main() == 0


def test_e2e_features_off_keeps_logs_byte_stable():
    res, problems, _pf, meta_text = _run(_cfg(), videos=8)
    assert problems == [], problems
    for line in ("Health", "Deadline", "Hedge"):
        assert line not in meta_text, line
    meta = res.parsed_meta
    assert "health_lanes" not in meta
    assert "deadline_expired" not in meta
    assert "hedges_fired" not in meta


# -- --check: violation fixtures + exit codes -------------------------

def _job(tmp_path, extra_meta="", table=True):
    job = tmp_path / "job"
    job.mkdir()
    (job / "log-meta.txt").write_text(
        "Args: Namespace(mean_interval_ms=0, batch_size=1, videos=1, "
        "queue_size=1, config_file_path='x.json')\n"
        "1.0 2.0\n"
        "Termination flag: 0\n"
        "Faults: num_failed=0 num_shed=0 num_retries=0\n"
        + extra_meta)
    if table:
        (job / "cpu0-group0-0.txt").write_text(
            "enqueue_filename inference1_finish device0\n"
            "1.0 1.5 ('cpu:0',)\n")
    return str(job)


def test_check_flags_illegal_lane_path(tmp_path):
    job = _job(tmp_path,
               "Health: lanes=1 transitions=1 opens=1 evictions=0 "
               "probes=0 redispatches=0 routes_after_open=0\n"
               'Health lanes: {"1": {"state": "open", "path": '
               '["healthy", "open"], "redispatched_from": 0, '
               '"routes_after_open": 0}}\n')
    problems = parse_utils.check_job(job)
    assert any("not a legal walk" in p for p in problems), problems


def test_check_flags_routes_after_open(tmp_path):
    job = _job(tmp_path,
               "Health: lanes=1 transitions=0 opens=0 evictions=0 "
               "probes=0 redispatches=0 routes_after_open=2\n"
               'Health lanes: {"1": {"state": "healthy", "path": '
               '["healthy"], "redispatched_from": 0, '
               '"routes_after_open": 2}}\n')
    problems = parse_utils.check_job(job)
    assert any("circuit containment violated" in p
               for p in problems), problems


def test_check_flags_redispatch_without_eviction(tmp_path):
    job = _job(tmp_path,
               "Health: lanes=1 transitions=0 opens=0 evictions=0 "
               "probes=0 redispatches=3 routes_after_open=0\n"
               'Health lanes: {"1": {"state": "healthy", "path": '
               '["healthy"], "redispatched_from": 3, '
               '"routes_after_open": 0}}\n')
    problems = parse_utils.check_job(job)
    assert any("never evicted" in p for p in problems), problems


def test_check_flags_deadline_site_mismatch(tmp_path):
    job = _job(tmp_path,
               "Shed sites: {\"step1_take:deadline_expired\": 2}\n"
               "Deadline: budget_ms=100 expired=3\n"
               "Deadline sites: {\"step1_take:deadline_expired\": "
               "3}\n")
    problems = parse_utils.check_job(job)
    assert any("disagrees with the shed ledger" in p
               for p in problems), problems


def test_check_flags_hedge_resolution_leak(tmp_path):
    job = _job(tmp_path,
               "Hedge: fired=3 won=1 lost=1 wasted_ms=4\n")
    problems = parse_utils.check_job(job)
    assert any("resolves exactly once" in p for p in problems), \
        problems


def test_check_flags_stranded_requests(tmp_path):
    job = _job(tmp_path,
               "Health: lanes=1 transitions=0 opens=0 evictions=0 "
               "probes=0 redispatches=0 routes_after_open=0\n"
               'Health lanes: {"1": {"state": "healthy", "path": '
               '["healthy"], "redispatched_from": 0, '
               '"routes_after_open": 0}}\n')
    # the Args line says videos=1 and the table holds 1 row, so the
    # run is complete; rewrite Args to claim 5 videos -> 4 stranded
    meta = open(os.path.join(job, "log-meta.txt")).read()
    with open(os.path.join(job, "log-meta.txt"), "w") as f:
        f.write(meta.replace("videos=1,", "videos=5,"))
    problems = parse_utils.check_job(job)
    assert any("stranded" in p for p in problems), problems


def test_check_exit_codes_distinguish_parse_from_invariant(tmp_path):
    # invariant violation over parsable artifacts -> exit 1
    bad = _job(tmp_path, "Hedge: fired=2 won=0 lost=1 wasted_ms=0\n")
    assert parse_utils.main(["--check", bad]) == 1
    # schema-parse failure (no log-meta at all) -> exit 2
    empty = tmp_path / "empty-job"
    empty.mkdir()
    assert parse_utils.main(["--check", str(empty)]) == 2
    # a clean job -> 0
    sub = tmp_path / "sub"
    sub.mkdir()
    ok = _job(sub, "")
    assert parse_utils.main(["--check", ok]) == 0
