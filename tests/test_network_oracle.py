"""Independent numerics validation of the Flax R(2+1)D network.

Drives the Flax modules and the pure-numpy oracle (oracle_r2p1d, no
Flax/XLA in its math) with identical parameter arrays and asserts
agreement — the check the reference got implicitly from running
pretrained torch weights through the submodule's blocks
(/root/reference/models/r2p1d/model.py:18,50-63). A padding, stride,
or factored-channel regression on the Flax side cannot hide here: the
oracle would diverge. A committed golden-logits fixture additionally
pins one seeded full-net forward against drift over time.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import oracle_r2p1d as oracle
from rnb_tpu.models.r2p1d.network import (LAYER_INPUT_SHAPES,
                                          R2Plus1DClassifier, R2Plus1DNet,
                                          SpatioTemporalConv,
                                          SpatioTemporalResBlock,
                                          factored_channels,
                                          range_output_shape)

GOLDEN_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "golden", "r2p1d_logits.npz")


def _randomize_dict(d, rng):
    """Non-trivial BN statistics and affine terms: init() gives
    mean=0/var=1/scale=1/bias=0, which would let a BN wiring bug pass
    as the identity."""
    out = {}
    for k, v in d.items():
        if hasattr(v, "items"):
            out[k] = _randomize_dict(v, rng)
        elif k == "mean":
            out[k] = rng.normal(0.0, 0.3, np.shape(v)).astype(np.float32)
        elif k == "var":
            out[k] = rng.uniform(0.5, 1.5, np.shape(v)).astype(np.float32)
        elif k == "scale":
            out[k] = rng.uniform(0.5, 1.5, np.shape(v)).astype(np.float32)
        elif k == "bias":
            out[k] = rng.normal(0.0, 0.3, np.shape(v)).astype(np.float32)
        else:
            out[k] = np.asarray(v)
    return out


def _prep(module, x, seed=0):
    """init on float32, randomize BN/affine leaves, return (flax_out,
    plain-numpy variables)."""
    variables = module.init(jax.random.PRNGKey(seed), x, train=False)
    plain = jax.tree_util.tree_map(np.asarray, variables)
    plain = {k: _randomize_dict(dict(v), np.random.default_rng(seed + 1))
             for k, v in dict(plain).items()}
    out = module.apply(plain, x, train=False)
    return np.asarray(out), plain


def test_conv3d_oracle_is_a_direct_conv():
    """The oracle itself, pinned on a hand-checkable case: 1-D identity
    kernel and a known sum."""
    x = np.arange(2 * 3 * 3 * 1, dtype=np.float64).reshape(1, 2, 3, 3, 1)
    w = np.ones((1, 2, 2, 1, 1))
    out = oracle.conv3d(x, w, (1, 1, 1), ((0, 0), (0, 0), (0, 0)))
    assert out.shape == (1, 2, 2, 2, 1)
    # top-left window of frame 0: 0+1+3+4
    assert out[0, 0, 0, 0, 0] == 8.0


def test_factored_channels_formula_pinned():
    """Hand-computed M_i values from the paper's parameter-matching
    formula, floor(t*d^2*Ni-1*No / (d^2*Ni-1 + t*No)) — literal
    expectations, not a comparison against a copy of the code."""
    assert factored_channels(3, 64, 3, 7) == 83      # stem
    assert factored_channels(64, 64, 3, 3) == 144    # layer 2 blocks
    assert factored_channels(64, 128, 3, 3) == 230   # layer 3 entry
    assert factored_channels(128, 256, 3, 3) == 460  # layer 4 entry
    assert factored_channels(256, 512, 3, 3) == 921  # layer 5 entry


@pytest.mark.parametrize("kernel,stride", [((3, 3), (1, 1)),
                                           ((3, 7), (1, 2)),
                                           ((3, 3), (2, 2)),
                                           ((1, 1), (2, 2))])
def test_spatiotemporal_conv_matches_oracle(kernel, stride):
    rng = np.random.default_rng(42)
    x = jnp.asarray(rng.normal(size=(2, 4, 6, 6, 3)).astype(np.float32))
    module = SpatioTemporalConv(5, kernel=kernel, stride=stride,
                                dtype=jnp.float32)
    flax_out, plain = _prep(module, x)
    ora = oracle.spatiotemporal_conv(plain, np.asarray(x), kernel, stride)
    assert flax_out.shape == ora.shape
    np.testing.assert_allclose(flax_out, ora, rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("downsample,factored",
                         [(False, False), (True, False), (True, True)])
def test_res_block_matches_oracle(downsample, factored):
    rng = np.random.default_rng(7)
    x = jnp.asarray(rng.normal(size=(1, 4, 6, 6, 4)).astype(np.float32))
    module = SpatioTemporalResBlock(4, downsample=downsample,
                                    factored_shortcut=factored,
                                    dtype=jnp.float32)
    flax_out, plain = _prep(module, x)
    ora = oracle.res_block(plain, np.asarray(x), downsample=downsample,
                           factored_shortcut=factored)
    assert flax_out.shape == ora.shape
    np.testing.assert_allclose(flax_out, ora, rtol=2e-4, atol=2e-4)


def test_full_net_matches_oracle():
    """The real R18 architecture (layer sizes 2,2,2,2) end to end on a
    spatially small input — stem padding, every stage's downsampling
    schedule, the factored widths, and the global pool all in play."""
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(size=(1, 8, 16, 16, 3)).astype(np.float32))
    module = R2Plus1DNet(dtype=jnp.float32)
    flax_out, plain = _prep(module, x)
    ora = oracle.r2plus1d_net(plain, np.asarray(x))
    assert flax_out.shape == (1, 512)
    np.testing.assert_allclose(flax_out, ora, rtol=5e-4, atol=5e-4)


@pytest.mark.parametrize("start,end", [(2, 2), (2, 4), (3, 5)])
def test_partial_ranges_match_oracle_and_shape_table(start, end):
    rng = np.random.default_rng(start * 10 + end)
    t, h, w, c = LAYER_INPUT_SHAPES[start]
    # small spatial extent, true channel count (channels drive the
    # factored widths); T matters for the stride-2 temporal path
    x = jnp.asarray(rng.normal(size=(1, t, 8, 8, c)).astype(np.float32))
    module = R2Plus1DNet(start=start, end=end, dtype=jnp.float32)
    flax_out, plain = _prep(module, x)
    ora = oracle.r2plus1d_net(plain, np.asarray(x), start=start, end=end)
    assert flax_out.shape == ora.shape
    np.testing.assert_allclose(flax_out, ora, rtol=5e-4, atol=5e-4)


def test_classifier_matches_oracle():
    rng = np.random.default_rng(11)
    x = jnp.asarray(rng.normal(size=(1, 8, 16, 16, 3)).astype(np.float32))
    module = R2Plus1DClassifier(num_classes=10, dtype=jnp.float32)
    flax_out, plain = _prep(module, x)
    ora = oracle.r2plus1d_classifier(plain, np.asarray(x))
    assert flax_out.shape == (1, 10)
    np.testing.assert_allclose(flax_out, ora, rtol=5e-4, atol=5e-4)


def test_golden_logits_fixture():
    """One seeded full-net float32 forward pinned to a committed
    fixture — catches silent numerical drift (padding defaults, BN
    epsilon, init changes) between rounds. Regenerate deliberately
    with scripts/make_golden_logits.py when the architecture changes
    on purpose.

    Provenance: regenerated 2026-08-04 for this image's flax/jax —
    the prior fixture's logits were UNCORRELATED with the current
    init at identical seeds (corr ~0.02, so flax changed how it
    folds the init RNG, not the math; a precision drift would keep
    the draws correlated). The network arithmetic itself is pinned
    independently of init by the numpy-oracle tests above, which
    feed IDENTICAL parameter arrays to both implementations."""
    golden = np.load(GOLDEN_PATH)
    rng = np.random.default_rng(int(golden["input_seed"]))
    x = jnp.asarray(
        rng.normal(size=tuple(golden["input_shape"])).astype(np.float32))
    module = R2Plus1DClassifier(dtype=jnp.float32)
    variables = module.init(jax.random.PRNGKey(int(golden["param_seed"])),
                            x, train=False)
    out = np.asarray(module.apply(variables, x, train=False))
    np.testing.assert_allclose(out, golden["logits"], rtol=1e-3, atol=1e-3)


def test_range_output_shape_agrees_with_oracle():
    """The runtime's ring-sizing shape table vs shapes the oracle
    actually produces (the reference hardcoded this and documented the
    partial case broken, TODO #69)."""
    for start, end in [(1, 1), (1, 2), (2, 4), (3, 4), (4, 4)]:
        t, h, w, c = LAYER_INPUT_SHAPES[start]
        rng = np.random.default_rng(1)
        x = jnp.asarray(rng.normal(size=(1, t, h, w, c)).astype(np.float32))
        module = R2Plus1DNet(start=start, end=end, dtype=jnp.float32)
        variables = module.init(jax.random.PRNGKey(0), x, train=False)
        out = module.apply(variables, x, train=False)
        expect = range_output_shape(start, end, consecutive_frames=t)
        assert tuple(out.shape[1:]) == expect, (start, end)
