"""Native C++ decoder vs numpy Y4MDecoder: bit parity + pool behavior.

The native backend must be indistinguishable from the numpy one (same
frames, same clamp-past-EOF semantics, same resize index map) so the
pipeline can switch between them freely.  Tests auto-build the library
if a toolchain is present and skip otherwise.
"""

import os
import subprocess

import numpy as np
import pytest

from rnb_tpu.decode import Y4MDecoder, write_y4m

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LIB = os.path.join(REPO, "native", "build", "librnb_decode.so")


def _ensure_lib():
    if not os.path.exists(LIB):
        try:
            subprocess.run(["make", "-C", os.path.join(REPO, "native")],
                           check=True, capture_output=True)
        except (OSError, subprocess.CalledProcessError):
            pytest.skip("native toolchain unavailable")
    from rnb_tpu.decode.native import native_available
    if not native_available():
        pytest.skip("native decode library failed to load")


@pytest.fixture(scope="module")
def native():
    _ensure_lib()
    from rnb_tpu.decode.native import NativeY4MDecoder
    return NativeY4MDecoder()


def _write_video(path, n=12, h=24, w=32, seed=0):
    rng = np.random.default_rng(seed)
    frames = rng.integers(0, 256, (n, h, w, 3), dtype=np.uint8)
    write_y4m(str(path), frames)
    return frames


def test_probe_matches_numpy(tmp_path, native):
    path = tmp_path / "a.y4m"
    _write_video(path, n=9)
    assert native.num_frames(str(path)) == 9
    assert Y4MDecoder().num_frames(str(path)) == 9


@pytest.mark.parametrize("geometry", [(24, 32, 16, 16), (24, 32, 24, 32),
                                      (16, 16, 20, 28)])
def test_decode_parity_with_numpy(tmp_path, native, geometry):
    h, w, out_h, out_w = geometry
    path = tmp_path / "b.y4m"
    _write_video(path, n=10, h=h, w=w, seed=1)
    starts = [0, 3, 7]
    got = native.decode_clips(str(path), starts, consecutive_frames=4,
                              width=out_w, height=out_h)
    want = Y4MDecoder().decode_clips(str(path), starts,
                                     consecutive_frames=4,
                                     width=out_w, height=out_h)
    assert got.shape == want.shape == (3, 4, out_h, out_w, 3)
    # float rounding at truncation boundaries may differ by 1
    diff = np.abs(got.astype(np.int16) - want.astype(np.int16))
    assert diff.max() <= 1, "max pixel delta %d" % diff.max()
    assert (diff > 0).mean() < 0.01


@pytest.mark.parametrize("colorspace", ["444", "420"])
@pytest.mark.parametrize("geometry", [(24, 32, 16, 16), (16, 16, 20, 28),
                                      (30, 42, 12, 18), (48, 20, 48, 20)])
def test_yuv_gather_parity_sweep(tmp_path, native, geometry, colorspace):
    """Packed-plane gathers are pure byte moves — the two backends must
    be BIT-exact across upscale/downscale/identity geometries and both
    source colourspaces."""
    h, w, out_h, out_w = geometry
    rng = np.random.default_rng(h * 1000 + w)
    frames = rng.integers(0, 256, (9, h, w, 3), dtype=np.uint8)
    path = tmp_path / ("c_%s.y4m" % colorspace)
    write_y4m(str(path), frames, colorspace=colorspace)
    # >= POOL_SPLIT_MIN_CLIPS so the native side exercises the POOLED
    # yuv fan-out (per-chunk slices of one packed batch buffer), not
    # just the synchronous path
    starts = [0, 2, 4, 6]
    got = native.decode_clips_yuv(str(path), starts,
                                  consecutive_frames=3,
                                  width=out_w, height=out_h)
    want = Y4MDecoder().decode_clips_yuv(str(path), starts,
                                         consecutive_frames=3,
                                         width=out_w, height=out_h)
    assert got.shape == want.shape == (4, 3, out_h * out_w * 3 // 2)
    np.testing.assert_array_equal(got, want)


def test_yuv_odd_geometry_rejected_numpy(tmp_path):
    # toolchain-independent: the numpy backend's check must hold even
    # where the native library cannot build
    path = tmp_path / "d.y4m"
    _write_video(path, n=4)
    with pytest.raises(ValueError):
        Y4MDecoder().decode_clips_yuv(str(path), [0], 2,
                                      width=15, height=16)


def test_yuv_odd_geometry_rejected_native(tmp_path, native):
    path = tmp_path / "d.y4m"
    _write_video(path, n=4)
    with pytest.raises(ValueError):
        native.decode_clips_yuv(str(path), [0], 2, width=15, height=16)


def test_clamp_past_eof_matches_numpy(tmp_path, native):
    path = tmp_path / "c.y4m"
    _write_video(path, n=5, seed=2)
    got = native.decode_clips(str(path), [3], consecutive_frames=6,
                              width=16, height=16)
    want = Y4MDecoder().decode_clips(str(path), [3], consecutive_frames=6,
                                     width=16, height=16)
    # frames past EOF repeat the last frame
    np.testing.assert_array_equal(got[0, 2], got[0, 5])
    diff = np.abs(got.astype(np.int16) - want.astype(np.int16))
    assert diff.max() <= 1


def test_negative_start_rejected_by_both_backends(tmp_path, native):
    path = tmp_path / "neg.y4m"
    _write_video(path, n=4, seed=3)
    with pytest.raises(ValueError):
        native.decode_clips(str(path), [-1], consecutive_frames=2,
                            width=16, height=16)
    with pytest.raises(ValueError):
        Y4MDecoder().decode_clips(str(path), [-1], consecutive_frames=2,
                                  width=16, height=16)


def test_errors_surface(tmp_path, native):
    bad = tmp_path / "bad.y4m"
    bad.write_bytes(b"not a y4m header\n")
    with pytest.raises(ValueError):
        native.num_frames(str(bad))
    with pytest.raises(ValueError):
        native.decode_clips(str(tmp_path / "missing.y4m"), [0])


def test_pool_concurrent_decodes(tmp_path, native):
    from rnb_tpu.decode.native import DecodePool
    paths, frames = [], []
    for i in range(6):
        p = tmp_path / ("v%d.y4m" % i)
        frames.append(_write_video(p, n=8, seed=10 + i))
        paths.append(str(p))
    pool = DecodePool(num_threads=3)
    try:
        tickets = [pool.submit(p, [0, 2], 3, 16, 16) for p in paths]
        sync = native
        for p, (ticket, out) in zip(paths, tickets):
            pool.wait(ticket, p)
            want = sync.decode_clips(p, [0, 2], consecutive_frames=3,
                                     width=16, height=16)
            np.testing.assert_array_equal(out, want)
    finally:
        pool.close()


def test_pool_double_wait_fails_fast(tmp_path, native):
    from rnb_tpu.decode.native import DecodePool
    p = tmp_path / "dw.y4m"
    _write_video(p, n=4)
    pool = DecodePool(num_threads=1)
    try:
        ticket, _ = pool.submit(str(p), [0], 2, 16, 16)
        pool.wait(ticket)
        with pytest.raises(ValueError):
            pool.wait(ticket)  # retired ticket must not hang
    finally:
        pool.close()


def test_get_decoder_prefers_native(tmp_path, native):
    from rnb_tpu.decode import get_decoder
    from rnb_tpu.decode.native import NativeY4MDecoder
    path = tmp_path / "d.y4m"
    _write_video(path, n=3)
    assert isinstance(get_decoder(str(path)), NativeY4MDecoder)
