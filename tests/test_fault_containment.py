"""Request-level fault containment, load shedding, and the
deterministic fault-injection harness (rnb_tpu.faults).

Covers the failure taxonomy (transient/permanent/fatal), the executor's
retry + dead-letter path, the "shed" overload policy at both overflow
sites, the fusing loader's internal containment, the extended summary
schema end-to-end through scripts/parse_utils, and — the acceptance
scenario — a 100-video chaos run that completes with exact fault
accounting while the fault-free run keeps reference-parity behavior.
"""

import json
import os

import numpy as np
import pytest

from rnb_tpu.benchmark import run_benchmark
from rnb_tpu.config import ConfigError, parse_config
from rnb_tpu.control import TerminationFlag
from rnb_tpu.faults import (FATAL, PERMANENT, TRANSIENT, CorruptVideoError,
                            FaultPlan, InjectedPermanentError,
                            InjectedTransientError, TransientDecodeError,
                            classify_error, fault_reason, validate_plan)

chaos = pytest.mark.chaos


def _write_config(tmp_path, cfg, name="pipeline.json"):
    path = os.path.join(str(tmp_path), name)
    with open(path, "w") as f:
        json.dump(cfg, f)
    return path


def _two_step(extra_root=None, extra_step0=None):
    cfg = {
        "video_path_iterator": "tests.pipeline_helpers.CountingPathIterator",
        "pipeline": [
            {"model": "tests.pipeline_helpers.TinyLoader",
             "queue_groups": [{"devices": [0], "out_queues": [0]}],
             "num_shared_tensors": 4},
            {"model": "tests.pipeline_helpers.TinySink",
             "queue_groups": [{"devices": [1], "in_queue": 0}]},
        ],
    }
    cfg.update(extra_root or {})
    cfg["pipeline"][0].update(extra_step0 or {})
    return cfg


# -- taxonomy ---------------------------------------------------------

def test_classify_error_taxonomy():
    assert classify_error(InjectedTransientError("x")) is TRANSIENT
    assert classify_error(TransientDecodeError("x")) is TRANSIENT
    assert classify_error(OSError("io blip")) is TRANSIENT
    assert classify_error(InjectedPermanentError("x")) is PERMANENT
    assert classify_error(CorruptVideoError("x")) is PERMANENT
    # deterministic OSErrors are verdicts, not blips: retrying an
    # open() of a missing file cannot succeed
    assert classify_error(FileNotFoundError("gone")) is PERMANENT
    assert classify_error(PermissionError("denied")) is PERMANENT
    assert fault_reason(FileNotFoundError("gone")) == "file-not-found"
    # anything unclassified stays fatal — containment must not paper
    # over genuine bugs
    assert classify_error(ValueError("bug")) is FATAL
    assert classify_error(AssertionError()) is FATAL
    assert classify_error(KeyError("k")) is FATAL
    # classified decode errors still read as ValueError for
    # pre-containment callers
    assert isinstance(CorruptVideoError("x"), ValueError)
    assert isinstance(TransientDecodeError("x"), ValueError)


def test_fault_reasons():
    assert fault_reason(CorruptVideoError("x")) == "corrupt-video"
    assert fault_reason(InjectedPermanentError("x")) == "injected-permanent"
    assert fault_reason(OSError("x")) == "os-error"
    e = InjectedTransientError("x")
    e.fault_reason = "custom"
    assert fault_reason(e) == "custom"


# -- plan validation + determinism ------------------------------------

def test_validate_plan_rejects_malformed():
    for bad in (
            [],                                          # not an object
            {"faults": "nope"},                          # faults not a list
            {"faults": [{"kind": "bogus",
                         "request_ids": [1]}]},          # unknown kind
            {"faults": [{"kind": "transient"}]},         # no selector
            {"faults": [{"kind": "transient", "request_ids": [1],
                         "probability": 0.5}]},          # both selectors
            {"faults": [{"kind": "latency",
                         "request_ids": [1]}]},          # latency needs ms
            {"faults": [{"kind": "transient", "request_ids": [1],
                         "times": 0}]},                  # times >= 1
            {"faults": [{"kind": "transient", "request_ids": [1],
                         "typo": True}]},                # unknown key
            {"faults": [{"kind": "transient", "request_ids": [1],
                         "ms": 100}]},                   # ms on error kind
            {"faults": [{"kind": "latency", "ms": 5, "request_ids": [1],
                         "times": 2}]},                  # times on delay
            {"seed": "x", "faults": []},                 # non-int seed
    ):
        with pytest.raises(ValueError):
            validate_plan(bad)
    validate_plan({"seed": 3, "faults": [
        {"step": 0, "kind": "permanent", "request_ids": [1]},
        {"kind": "transient", "probability": 0.25},
        {"step": 1, "kind": "latency", "ms": 5, "probability": 1.0},
        {"step": 0, "kind": "stall", "ms": 5, "request_ids": [2]},
    ]})


def test_plan_fire_and_determinism():
    spec = {"seed": 11, "faults": [
        {"step": 0, "kind": "transient", "request_ids": [4], "times": 2},
        {"step": 0, "kind": "permanent", "probability": 0.3},
    ]}
    plan_a, plan_b = FaultPlan(spec), FaultPlan(spec)
    # id-listed transient fires on the first `times` attempts only
    with pytest.raises(InjectedTransientError):
        plan_a.fire(0, 4, attempt=0)
    with pytest.raises(InjectedTransientError):
        plan_a.fire(0, 4, attempt=1)
    plan_a.fire(0, 4, attempt=2)  # budget spent: no raise
    plan_a.fire(1, 4, attempt=0)  # wrong step: no raise
    # probability draws are a pure function of (seed, site): two plan
    # instances agree on every request id
    for rid in range(200):
        hit_a = hit_b = False
        try:
            plan_a.fire(0, rid + 1000, attempt=0)
        except InjectedPermanentError:
            hit_a = True
        try:
            plan_b.fire(0, rid + 1000, attempt=0)
        except InjectedPermanentError:
            hit_b = True
        assert hit_a == hit_b
    # ~30% of draws hit (loose bounds; deterministic, so never flaky)
    hits = 0
    for rid in range(1000):
        try:
            plan_b.fire(0, rid + 1000, attempt=0)
        except InjectedPermanentError:
            hits += 1
    assert 200 < hits < 400


def test_plan_from_env(monkeypatch):
    monkeypatch.delenv("RNB_FAULT_PLAN", raising=False)
    assert FaultPlan.from_env() is None
    monkeypatch.setenv("RNB_FAULT_PLAN", json.dumps(
        {"faults": [{"kind": "permanent", "request_ids": [1]}]}))
    plan = FaultPlan.from_env()
    with pytest.raises(InjectedPermanentError):
        plan.fire(0, 1)
    monkeypatch.setenv("RNB_FAULT_PLAN", "{not json")
    with pytest.raises(ValueError):
        FaultPlan.from_env()


# -- config schema ----------------------------------------------------

def test_config_schema_robustness_keys():
    base = _two_step()
    cfg = parse_config(dict(base))
    assert cfg.overload_policy == "abort"
    assert cfg.fault_containment is True
    assert cfg.fault_plan is None
    assert cfg.steps[0].max_retries == 0

    rich = _two_step(
        extra_root={"overload_policy": "shed",
                    "fault_containment": True,
                    "fault_plan": {"faults": [
                        {"kind": "transient", "probability": 0.1}]}},
        extra_step0={"max_retries": 3, "retry_backoff_ms": 2})
    cfg = parse_config(rich)
    assert cfg.overload_policy == "shed"
    assert cfg.steps[0].max_retries == 3
    assert cfg.steps[0].retry_backoff_ms == 2.0
    assert cfg.steps[1].max_retries == 0
    # the retry knobs are schema, not model kwargs
    assert "max_retries" not in cfg.steps[0].extras

    for bad_root in ({"overload_policy": "drop"},
                     {"fault_containment": "yes"},
                     {"fault_plan": {"faults": [{"kind": "??"}]}},
                     {"overload_polcy": "shed"}):          # typo'd key
        with pytest.raises(ConfigError):
            parse_config(_two_step(extra_root=bad_root))
    for bad_step in ({"max_retries": -1}, {"max_retries": "2"},
                     {"retry_backoff_ms": -5}):
        with pytest.raises(ConfigError):
            parse_config(_two_step(extra_step0=bad_step))
    # a fault targeting a step the pipeline does not have would
    # silently never fire — rejected at parse time
    with pytest.raises(ConfigError):
        parse_config(_two_step(extra_root={"fault_plan": {"faults": [
            {"step": 2, "kind": "permanent", "request_ids": [1]}]}}))


def test_plan_check_steps():
    plan = FaultPlan({"faults": [
        {"step": 1, "kind": "permanent", "request_ids": [1]},
        {"kind": "transient", "probability": 0.1}]})  # step-less: any
    plan.check_steps(2)
    with pytest.raises(ValueError):
        plan.check_steps(1)


# -- the acceptance chaos run -----------------------------------------

@chaos
def test_chaos_acceptance_run(tmp_path):
    """100 videos, k=3 injected permanent decode failures plus a
    3-request transient burst: the run completes (no abort), reports
    exactly num_failed == k, the retried transients succeed and count
    in num_retries, and latency percentiles cover successes only —
    while the same pipeline without a plan behaves exactly like the
    pre-containment runtime."""
    plan = {"seed": 7, "faults": [
        {"step": 0, "kind": "permanent", "request_ids": [5, 25, 50]},
        {"step": 0, "kind": "transient", "request_ids": [10, 11, 12]},
        {"step": 1, "kind": "latency", "ms": 10, "request_ids": [7]},
        {"step": 0, "kind": "stall", "ms": 20, "request_ids": [60]},
    ]}
    cfg = _two_step(extra_root={"fault_plan": plan},
                    extra_step0={"max_retries": 2, "retry_backoff_ms": 1})
    path = _write_config(tmp_path, cfg)
    res = run_benchmark(path, mean_interval_ms=0, num_videos=100,
                        queue_size=500, log_base=str(tmp_path / "logs"),
                        print_progress=False)
    assert res.termination_flag == \
        TerminationFlag.TARGET_NUM_VIDEOS_REACHED
    assert res.num_failed == 3
    assert res.failure_reasons == {"injected-permanent": 3}
    assert res.num_retries == 3  # one retry per burst member, then ok
    assert res.num_shed == 0
    assert res.num_completed >= 97
    assert res.p99_latency_ms >= res.p50_latency_ms > 0
    # dead-letter record names the exact ids
    with open(os.path.join(res.log_dir, "failed-requests.txt")) as f:
        lines = [ln.split() for ln in f if not ln.startswith("#")]
    assert sorted(int(ln[0]) for ln in lines) == [5, 25, 50]
    assert all(ln[1] == "0" and ln[2] == "injected-permanent"
               for ln in lines)
    # meta carries the same accounting
    with open(os.path.join(res.log_dir, "log-meta.txt")) as f:
        meta_text = f.read()
    assert "Termination flag: 0" in meta_text
    assert "Faults: num_failed=3 num_shed=0 num_retries=3" in meta_text

    # reference parity: no plan, abort policy -> byte-compatible
    # fault-free schema (no '# faults' trailer, zero counters)
    parity = _write_config(tmp_path, _two_step(), name="parity.json")
    res2 = run_benchmark(parity, mean_interval_ms=0, num_videos=100,
                         queue_size=500,
                         log_base=str(tmp_path / "logs2"),
                         print_progress=False)
    assert res2.termination_flag == \
        TerminationFlag.TARGET_NUM_VIDEOS_REACHED
    assert (res2.num_failed, res2.num_shed, res2.num_retries) == (0, 0, 0)
    report = [f for f in os.listdir(res2.log_dir) if "group" in f][0]
    with open(os.path.join(res2.log_dir, report)) as f:
        text = f.read()
    assert "# faults" not in text
    assert not os.path.exists(
        os.path.join(res2.log_dir, "failed-requests.txt"))


@chaos
def test_transient_without_retry_budget_fails_request(tmp_path):
    """With max_retries=0 a transient fault degrades to a contained
    permanent failure with a 'retries-exhausted:' reason."""
    cfg = _two_step(extra_root={"fault_plan": {"faults": [
        {"step": 0, "kind": "transient", "request_ids": [3],
         "times": 99}]}})
    path = _write_config(tmp_path, cfg)
    res = run_benchmark(path, mean_interval_ms=0, num_videos=20,
                        queue_size=100, log_base=str(tmp_path / "logs"),
                        print_progress=False)
    assert res.termination_flag == \
        TerminationFlag.TARGET_NUM_VIDEOS_REACHED
    assert res.num_failed == 1
    assert res.failure_reasons == \
        {"retries-exhausted:injected-transient": 1}


@chaos
def test_containment_off_keeps_failfast(tmp_path):
    """fault_containment: false restores strict reference semantics —
    even a classified injected error aborts the job."""
    cfg = _two_step(
        extra_root={"fault_containment": False,
                    "fault_plan": {"faults": [
                        {"step": 0, "kind": "permanent",
                         "request_ids": [2]}]}})
    path = _write_config(tmp_path, cfg)
    res = run_benchmark(path, mean_interval_ms=0, num_videos=20,
                        queue_size=100, log_base=str(tmp_path / "logs"),
                        print_progress=False)
    assert res.termination_flag == TerminationFlag.INTERNAL_ERROR


@chaos
def test_segment_step_failure_stays_failfast(tmp_path):
    """A permanent fault at a stage consuming forked SEGMENT cards is
    not contained (dead-lettering one segment would strand its sibling
    in the aggregator and double-count the request) — the job aborts
    exactly as pre-containment. A fault at the forking step itself
    (before the fork) is contained normally."""
    def seg_cfg(fault_step):
        return {
            "video_path_iterator":
                "tests.pipeline_helpers.CountingPathIterator",
            "fault_plan": {"faults": [
                {"step": fault_step, "kind": "permanent",
                 "request_ids": [6]}]},
            "pipeline": [
                {"model": "tests.pipeline_helpers.TinyLoader",
                 "queue_groups": [{"devices": [0], "out_queues": [0]}],
                 "num_segments": 2, "num_shared_tensors": 8,
                 "rows_per_video": 4},
                {"model": "tests.pipeline_helpers.TinyDouble",
                 "queue_groups": [{"devices": [1, 2], "in_queue": 0,
                                   "out_queues": [1]}]},
                {"model": "rnb_tpu.models.r2p1d.model.R2P1DAggregator",
                 "queue_groups": [{"devices": [-1], "in_queue": 1}],
                 "aggregate": 2},
            ],
        }
    path = _write_config(tmp_path, seg_cfg(fault_step=1))
    res = run_benchmark(path, mean_interval_ms=0, num_videos=12,
                        queue_size=100, log_base=str(tmp_path / "logs"),
                        print_progress=False)
    assert res.termination_flag == TerminationFlag.INTERNAL_ERROR

    path = _write_config(tmp_path, seg_cfg(fault_step=0), name="fork.json")
    res = run_benchmark(path, mean_interval_ms=0, num_videos=12,
                        queue_size=100, log_base=str(tmp_path / "logs2"),
                        print_progress=False)
    assert res.termination_flag == \
        TerminationFlag.TARGET_NUM_VIDEOS_REACHED
    assert res.num_failed == 1  # once, not once per segment


@chaos
def test_env_plan_overrides_config(tmp_path, monkeypatch):
    monkeypatch.setenv("RNB_FAULT_PLAN", json.dumps(
        {"faults": [{"step": 0, "kind": "permanent",
                     "request_ids": [1, 2]}]}))
    path = _write_config(tmp_path, _two_step())
    res = run_benchmark(path, mean_interval_ms=0, num_videos=15,
                        queue_size=100, log_base=str(tmp_path / "logs"),
                        print_progress=False)
    assert res.termination_flag == \
        TerminationFlag.TARGET_NUM_VIDEOS_REACHED
    assert res.num_failed == 2


# -- shed overload policy ---------------------------------------------

@chaos
def test_shed_at_filename_queue(tmp_path):
    """Under "shed" a full filename queue drops new requests with a
    counted outcome and the run still terminates cleanly — the same
    topology under "abort" dies with FILENAME_QUEUE_FULL
    (test_pipeline.test_filename_queue_overflow_aborts)."""
    cfg = {
        "video_path_iterator": "tests.pipeline_helpers.CountingPathIterator",
        "overload_policy": "shed",
        "pipeline": [
            {"model": "tests.pipeline_helpers.TinySlowSink",
             "queue_groups": [{"devices": [-1]}], "delay_s": 0.1},
        ],
    }
    path = _write_config(tmp_path, cfg)
    res = run_benchmark(path, mean_interval_ms=1, num_videos=30,
                        queue_size=2, log_base=str(tmp_path / "logs"),
                        print_progress=False)
    assert res.termination_flag == \
        TerminationFlag.TARGET_NUM_VIDEOS_REACHED
    assert res.num_shed > 0
    assert res.num_failed == 0
    assert res.num_completed + res.num_shed >= 30
    assert res.shed_sites == {"filename_queue": res.num_shed}
    with open(os.path.join(res.log_dir, "log-meta.txt")) as f:
        meta_text = f.read()
    assert "num_shed=%d" % res.num_shed in meta_text
    assert '"filename_queue"' in meta_text  # per-site breakdown


@chaos
def test_shed_between_stages(tmp_path):
    """A full inter-stage queue under "shed" drops the new item at the
    producer instead of raising FRAME_QUEUE_FULL."""
    cfg = {
        "video_path_iterator": "tests.pipeline_helpers.CountingPathIterator",
        "overload_policy": "shed",
        "pipeline": [
            {"model": "tests.pipeline_helpers.TinyLoader",
             "queue_groups": [{"devices": [0], "out_queues": [0]}],
             "num_shared_tensors": 4},
            {"model": "tests.pipeline_helpers.TinySlowSink",
             "queue_groups": [{"devices": [1], "in_queue": 0}],
             "delay_s": 0.15},
        ],
    }
    path = _write_config(tmp_path, cfg)
    res = run_benchmark(path, mean_interval_ms=1, num_videos=25,
                        queue_size=2, log_base=str(tmp_path / "logs"),
                        print_progress=False)
    assert res.termination_flag == \
        TerminationFlag.TARGET_NUM_VIDEOS_REACHED
    assert res.num_shed > 0
    # the sheds happened somewhere (client or step 0); no aborts
    assert res.num_completed + res.num_shed >= 25


# -- malformed real inputs through the pipeline -----------------------

def _write_tiny_dataset(root, corrupt=True):
    """3 valid 2-frame y4m videos (+1 corrupt) in a label subtree."""
    from rnb_tpu.decode import write_y4m
    label = os.path.join(root, "label0")
    os.makedirs(label, exist_ok=True)
    rng = np.random.default_rng(0)
    for i in range(3):
        frames = rng.integers(0, 256, (4, 16, 16, 3), dtype=np.uint8)
        write_y4m(os.path.join(label, "ok%d.y4m" % i), frames,
                  colorspace="420")
    if corrupt:
        with open(os.path.join(label, "bad.y4m"), "wb") as f:
            f.write(b"NOT_A_Y4M_STREAM totally corrupt payload\n")


@chaos
def test_corrupt_y4m_contained_in_pipeline(tmp_path, monkeypatch):
    """A corrupt video among good ones: with containment on, every
    request for it is a contained failure — the run completes and the
    good videos' requests all succeed (satellite: malformed-input error
    paths end in a failed request, not an aborted run)."""
    data_root = str(tmp_path / "data")
    _write_tiny_dataset(data_root)
    monkeypatch.setenv("RNB_TPU_DATA_ROOT", data_root)
    cfg = {
        "video_path_iterator":
            "rnb_tpu.models.r2p1d.model.R2P1DVideoPathIterator",
        "pipeline": [
            {"model": "rnb_tpu.models.r2p1d.model.R2P1DLoader",
             "queue_groups": [{"devices": [0]}],
             "max_clips": 2, "consecutive_frames": 2,
             "num_clips_population": [1, 2], "weights": [1, 1],
             "num_warmups": 0},
        ],
    }
    path = _write_config(tmp_path, cfg)
    # 8 requests cycling 4 files (sorted: bad, ok0, ok1, ok2): the
    # corrupt video is requested exactly twice
    res = run_benchmark(path, mean_interval_ms=0, num_videos=8,
                        queue_size=50, log_base=str(tmp_path / "logs"),
                        print_progress=False)
    assert res.termination_flag == \
        TerminationFlag.TARGET_NUM_VIDEOS_REACHED
    assert res.num_failed == 2
    assert res.failure_reasons == {"corrupt-video": 2}
    assert res.num_completed >= 6
    # the final instance's report carries the '# faults' trailer (the
    # failures happened AT the final step) and parse_utils reads both
    # the trailer-bearing table and the extended meta
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "scripts"))
    import parse_utils
    meta, df = parse_utils.get_data(res.log_dir)
    assert meta["num_failed"] == 2
    assert meta["failure_reasons"] == {"corrupt-video": 2}
    assert len(df) >= 6  # successes only in the table
    letters = parse_utils.parse_dead_letters(res.log_dir)
    assert list(letters["reason"].unique()) == ["corrupt-video"]
    report = [f for f in os.listdir(res.log_dir) if "group" in f][0]
    with open(os.path.join(res.log_dir, report)) as f:
        assert "# faults num_failed=2" in f.read()


@chaos
def test_fusing_loader_strict_mode_aborts(tmp_path, monkeypatch):
    """fault_containment: false applies to stage-INTERNAL containment
    too: a corrupt video surfacing inside the fusing loader's batch
    assembly must abort the job, not quietly dead-letter — strict
    semantics cannot depend on which code path the error takes."""
    data_root = str(tmp_path / "data")
    _write_tiny_dataset(data_root)
    monkeypatch.setenv("RNB_TPU_DATA_ROOT", data_root)
    cfg = {
        "video_path_iterator":
            "rnb_tpu.models.r2p1d.model.R2P1DVideoPathIterator",
        "fault_containment": False,
        "pipeline": [
            {"model": "rnb_tpu.models.r2p1d.model.R2P1DFusingLoader",
             "queue_groups": [{"devices": [0]}],
             "max_clips": 2, "consecutive_frames": 2, "fuse": 2,
             "num_clips_population": [1], "weights": [1],
             "num_warmups": 0},
        ],
    }
    path = _write_config(tmp_path, cfg)
    res = run_benchmark(path, mean_interval_ms=0, num_videos=8,
                        queue_size=50, log_base=str(tmp_path / "logs"),
                        print_progress=False)
    assert res.termination_flag == TerminationFlag.INTERNAL_ERROR


def test_fusing_loader_transient_retry(monkeypatch):
    """A transient decode failure during fused-batch assembly honors
    the step's retry budget (synchronous re-decode) instead of being
    dead-lettered immediately."""
    import jax

    from rnb_tpu.models.r2p1d.model import R2P1DFusingLoader, _FuseRecord
    from rnb_tpu.telemetry import TimeCard

    loader = R2P1DFusingLoader(jax.devices()[0], max_clips=2,
                               consecutive_frames=2, num_warmups=0,
                               num_clips_population=[1], weights=[1])
    video = "synth://retry-test"
    tc = TimeCard(0)

    class BoomHandle:
        n = 1
        out = None
        error = None

        def wait(self, v):
            raise TransientDecodeError("rc -1")

    # no budget: transient is dead-lettered with the exhausted prefix
    loader.fault_retry_budget = (0, 0.0)
    assert loader._wait_contained(
        _FuseRecord(BoomHandle(), video, tc)) is False
    ((failed_tc, reason),) = loader.take_failed()
    assert failed_tc is tc
    assert reason == "retries-exhausted:decode-io"
    assert loader.take_retries() == 0

    # with budget: the synchronous re-decode succeeds on retry
    loader.fault_retry_budget = (2, 0.0)
    handle = BoomHandle()
    assert loader._wait_contained(_FuseRecord(handle, video, tc)) is True
    assert handle.out is not None and handle.out.shape[0] >= 1
    assert loader.take_retries() == 1
    assert loader.take_failed() == []


@chaos
def test_corrupt_y4m_contained_fusing_loader(tmp_path, monkeypatch):
    """The fusing loader excludes a corrupt video from its fused batch
    (internal containment via take_failed) — its batchmates complete."""
    data_root = str(tmp_path / "data")
    _write_tiny_dataset(data_root)
    monkeypatch.setenv("RNB_TPU_DATA_ROOT", data_root)
    cfg = {
        "video_path_iterator":
            "rnb_tpu.models.r2p1d.model.R2P1DVideoPathIterator",
        "pipeline": [
            {"model": "rnb_tpu.models.r2p1d.model.R2P1DFusingLoader",
             "queue_groups": [{"devices": [0]}],
             "max_clips": 2, "consecutive_frames": 2, "fuse": 2,
             "num_clips_population": [1], "weights": [1],
             "num_warmups": 0},
        ],
    }
    path = _write_config(tmp_path, cfg)
    res = run_benchmark(path, mean_interval_ms=0, num_videos=8,
                        queue_size=50, log_base=str(tmp_path / "logs"),
                        print_progress=False)
    assert res.termination_flag == \
        TerminationFlag.TARGET_NUM_VIDEOS_REACHED
    assert res.num_failed == 2
    assert res.failure_reasons == {"corrupt-video": 2}
    assert res.num_completed >= 6


@chaos
def test_injection_hits_fused_batches(tmp_path):
    """A fault targeting a step that consumes fused TimeCardList
    batches fires when ANY constituent matches, failing the whole
    dispatch (batch blast radius) — plans against downstream-of-batcher
    steps must not be silently inert."""
    cfg = {
        "video_path_iterator": "tests.pipeline_helpers.CountingPathIterator",
        "fault_plan": {"faults": [
            {"step": 2, "kind": "permanent", "request_ids": [2]}]},
        "pipeline": [
            {"model": "tests.pipeline_helpers.TinyLoader",
             "queue_groups": [{"devices": [0], "out_queues": [0]}],
             "num_shared_tensors": 4},
            {"model": "rnb_tpu.batcher.Batcher",
             "queue_groups": [{"devices": [1], "in_queue": 0,
                               "out_queues": [1]}],
             "batch": 2, "shapes": [[4, 2]]},
            {"model": "tests.pipeline_helpers.TinySink",
             "queue_groups": [{"devices": [2], "in_queue": 1}]},
        ],
    }
    path = _write_config(tmp_path, cfg)
    res = run_benchmark(path, mean_interval_ms=0, num_videos=12,
                        queue_size=100, log_base=str(tmp_path / "logs"),
                        print_progress=False)
    assert res.termination_flag == \
        TerminationFlag.TARGET_NUM_VIDEOS_REACHED
    # request 2's fused batch (requests 2 and 3) fails as a unit
    assert res.num_failed == 2
    assert res.failure_reasons == {"injected-permanent": 2}
    assert res.num_completed >= 10


@chaos
def test_prefetch_handle_retired_on_injected_fault(tmp_path, monkeypatch):
    """An injected fault can fire BEFORE a prefetched decode handle is
    completed; the executor must retire the abandoned handle or its
    native-pool tickets pin the decode buffers for the process's
    life."""
    data_root = str(tmp_path / "data")
    _write_tiny_dataset(data_root, corrupt=False)
    monkeypatch.setenv("RNB_TPU_DATA_ROOT", data_root)
    cfg = {
        "video_path_iterator":
            "rnb_tpu.models.r2p1d.model.R2P1DVideoPathIterator",
        "fault_plan": {"faults": [
            {"step": 0, "kind": "permanent", "request_ids": [1, 3]}]},
        "pipeline": [
            {"model": "rnb_tpu.models.r2p1d.model.R2P1DLoader",
             "queue_groups": [{"devices": [0]}],
             "max_clips": 2, "consecutive_frames": 2, "prefetch": 2,
             "num_clips_population": [1, 2], "weights": [1, 1],
             "num_warmups": 0},
        ],
    }
    path = _write_config(tmp_path, cfg)
    res = run_benchmark(path, mean_interval_ms=0, num_videos=8,
                        queue_size=50, log_base=str(tmp_path / "logs"),
                        print_progress=False)
    assert res.termination_flag == \
        TerminationFlag.TARGET_NUM_VIDEOS_REACHED
    assert res.num_failed == 2
    from rnb_tpu.decode.native import DecodePool, native_available
    if native_available() and DecodePool._shared is not None:
        # every submitted ticket was waited or discarded
        assert DecodePool._shared._pending == {}


# -- malformed inputs at the decoder layer ----------------------------

def _contained(exc_info):
    return classify_error(exc_info.value) is not FATAL


def test_numpy_y4m_malformed_errors(tmp_path):
    from rnb_tpu.decode import Y4MDecoder, write_y4m
    dec = Y4MDecoder()
    bad_magic = str(tmp_path / "bad.y4m")
    with open(bad_magic, "wb") as f:
        f.write(b"JUNKJUNKJUNK\n" * 4)
    with pytest.raises(CorruptVideoError):
        dec.num_frames(bad_magic)

    # truncated inside the first FRAME marker line
    good = str(tmp_path / "good.y4m")
    frames = np.zeros((2, 16, 16, 3), dtype=np.uint8)
    write_y4m(good, frames, colorspace="420")
    data = open(good, "rb").read()
    header_end = data.index(b"\n") + 1
    trunc = str(tmp_path / "trunc.y4m")
    with open(trunc, "wb") as f:
        f.write(data[:header_end + 3])  # "FRA"
    with pytest.raises(CorruptVideoError):
        dec.num_frames(trunc)

    # a header lying about geometry (payload shorter than one frame)
    lying = str(tmp_path / "lying.y4m")
    with open(lying, "wb") as f:
        f.write(b"YUV4MPEG2 W64 H64 C420\nFRAME\n")
        f.write(b"\x00" * (64 * 64 * 3 // 2))  # exactly one frame...
    data = open(lying, "rb").read()
    with open(lying, "wb") as f:
        f.write(data[:-100])  # ...now truncated mid-payload
    # count floors to 0; any requested clip start is an error path,
    # and whatever surfaces must be contained, never fatal
    with pytest.raises(Exception) as ei:
        dec.decode_clips(lying, [0], consecutive_frames=1,
                         width=16, height=16)
    assert _contained(ei)


def test_mjpeg_malformed_errors(tmp_path):
    from rnb_tpu.decode import MjpegPILDecoder, write_mjpeg
    dec = MjpegPILDecoder()
    garbage = str(tmp_path / "garbage.mjpg")
    with open(garbage, "wb") as f:
        f.write(b"\x00\x01\x02 not a jpeg at all" * 10)
    with pytest.raises(CorruptVideoError):
        dec.num_frames(garbage)

    # a single frame truncated mid-entropy: the scanner finds no
    # complete frame -> classified, not a PIL crash
    good = str(tmp_path / "good.mjpg")
    frames = np.random.default_rng(1).integers(
        0, 256, (1, 16, 16, 3), dtype=np.uint8)
    write_mjpeg(good, frames)
    data = open(good, "rb").read()
    trunc = str(tmp_path / "trunc.mjpg")
    with open(trunc, "wb") as f:
        f.write(data[: int(len(data) * 0.6)])
    with pytest.raises(CorruptVideoError):
        dec.num_frames(trunc)


def test_native_malformed_errors(tmp_path):
    from rnb_tpu.decode.native import NativeY4MDecoder, native_available
    if not native_available():
        pytest.skip("native decode library not built")
    dec = NativeY4MDecoder(use_pool=False)
    bad = str(tmp_path / "bad.y4m")
    with open(bad, "wb") as f:
        f.write(b"JUNKJUNKJUNK\n" * 4)
    with pytest.raises(Exception) as ei:
        dec.num_frames(bad)
    assert _contained(ei)
    # vanished file: the native probe's I/O failure is transient
    with pytest.raises(TransientDecodeError):
        dec.num_frames(str(tmp_path / "nope.y4m"))
    garbage_mjpg = str(tmp_path / "garbage.mjpg")
    with open(garbage_mjpg, "wb") as f:
        f.write(b"\x00\x01\x02 not a jpeg" * 16)
    with pytest.raises(Exception) as ei:
        dec.num_frames(garbage_mjpg)
    assert _contained(ei)


# -- TimeCard / summary plumbing --------------------------------------

def test_timecard_status_fork_merge():
    from rnb_tpu.telemetry import TimeCard
    tc = TimeCard(1)
    assert tc.status == "ok"
    tc.record("a")
    forks = [tc.fork(0), tc.fork(1)]
    forks[1].record("b")
    forks[0].record("b")
    forks[0].mark_failed("corrupt-video")
    merged = TimeCard.merge(forks)
    assert merged.status == "failed"
    assert merged.failure_reason == "corrupt-video"
    tc2 = TimeCard(2)
    tc2.mark_shed("filename_queue")
    assert tc2.status == "shed"


def test_summary_fault_counters_and_trailer():
    import io

    from rnb_tpu.telemetry import TimeCard, TimeCardSummary
    s = TimeCardSummary()
    tc = TimeCard(0)
    tc.record("a"); tc.record("b")  # noqa: E702
    tc.add_device("cpu:0")
    s.register(tc)
    assert s.faults_line() is None  # fault-free: byte-stable schema
    s.note_failure("corrupt-video")
    s.note_retries(2)
    s.note_shed()
    line = s.faults_line()
    assert line.startswith("# faults num_failed=1 num_shed=1 "
                           "num_retries=2")
    assert "reason:corrupt-video=1" in line
    buf = io.StringIO()
    s.save_full_report(buf)
    text = buf.getvalue()
    assert text.splitlines()[-1] == line
    # latencies exclude the faulted accounting entirely
    assert len(s.latencies_ms(0)) == 1
