"""Profiler bridge smoke test — the test_cupti.py equivalent.

Reference behavior (test_cupti.py:1-21 + README.md:194-212): run one
small op under the bridge, expect kernel records with plausible
timestamps from ``report()``.  Here: a jitted matmul under
initialize/flush/report; both the native parser and the pure-Python
fallback must see the same events.
"""

import os
import subprocess

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from rnb_tpu import profiler

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def captured(tmp_path_factory):
    trace_dir = str(tmp_path_factory.mktemp("xprof"))
    profiler.initialize(trace_dir)
    x = jnp.ones((128, 128), jnp.float32)
    jax.jit(lambda a: a @ a)(x).block_until_ready()
    profiler.flush()
    return trace_dir


def test_report_returns_intervals(captured):
    events = profiler.report(keep_trace=True)
    assert events, "no events captured"
    names = [n for n, _, _ in events]
    assert any(n for n in names), names
    for name, t0, t1 in events:
        assert isinstance(name, str)
        assert t1 >= t0 >= 0


def test_report_include_plane(captured):
    """include_plane=True appends the owning plane to every tuple and
    matches the 3-tuple form element-for-element (same parse, plane
    stripped vs kept)."""
    with_plane = profiler.report(keep_trace=True, include_plane=True)
    bare = profiler.report(keep_trace=True)
    assert with_plane and bare
    assert [(n, t0, t1) for n, t0, t1, _p in with_plane] == bare
    planes = {p for _n, _t0, _t1, p in with_plane}
    assert all(isinstance(p, str) and p for p in planes), planes


def test_native_and_python_parsers_agree(captured):
    files = profiler._xplane_files()
    assert files, "no xplane.pb produced"
    lib = profiler._xplane_lib()
    if lib is None:
        try:
            subprocess.run(["make", "-C", os.path.join(REPO, "native")],
                           check=True, capture_output=True)
        except (OSError, subprocess.CalledProcessError):
            pytest.skip("native toolchain unavailable")
        lib = profiler._xplane_lib()
        if lib is None:
            pytest.skip("native xplane library failed to load")
    for path in files:
        native = profiler._parse_native(lib, path, "")
        python = profiler._parse_python(path, "")
        assert native == python
        assert len(native) > 0


def test_python_parser_tolerates_truncated_file(tmp_path, captured):
    files = profiler._xplane_files()
    src = files[0]
    trunc = tmp_path / "trunc.xplane.pb"
    data = open(src, "rb").read()
    trunc.write_bytes(data[:len(data) // 3])
    # must not raise; partial (possibly empty) results are fine
    events = profiler._parse_python(str(trunc), "")
    assert isinstance(events, list)
    lib = profiler._xplane_lib()
    if lib is not None:
        assert isinstance(profiler._parse_native(lib, str(trunc), ""),
                          list)


def test_report_keeps_caller_supplied_dir(tmp_path):
    d = tmp_path / "run1"
    d.mkdir()
    (d / "precious.txt").write_text("keep me")
    profiler.initialize(str(d))
    import jax.numpy as jnp
    jnp.zeros((8,)).block_until_ready()
    profiler.flush()
    profiler.report()
    assert (d / "precious.txt").exists()


def test_double_initialize_rejected(tmp_path):
    profiler.initialize(str(tmp_path / "t"))
    try:
        with pytest.raises(RuntimeError):
            profiler.initialize(str(tmp_path / "t2"))
    finally:
        profiler.flush()
        profiler.report()  # drain


def test_report_drains_trace(tmp_path):
    profiler.initialize(str(tmp_path / "t"))
    jnp.zeros((8,)).block_until_ready()
    profiler.flush()
    first = profiler.report()
    assert profiler.report() == []
    assert isinstance(first, list)
