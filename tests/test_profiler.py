"""Profiler bridge smoke test — the test_cupti.py equivalent.

Reference behavior (test_cupti.py:1-21 + README.md:194-212): run one
small op under the bridge, expect kernel records with plausible
timestamps from ``report()``.  Here: a jitted matmul under
initialize/flush/report; both the native parser and the pure-Python
fallback must see the same events.
"""

import os
import subprocess

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from rnb_tpu import profiler

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def captured(tmp_path_factory):
    trace_dir = str(tmp_path_factory.mktemp("xprof"))
    profiler.initialize(trace_dir)
    x = jnp.ones((128, 128), jnp.float32)
    jax.jit(lambda a: a @ a)(x).block_until_ready()
    profiler.flush()
    return trace_dir


def test_report_returns_intervals(captured):
    events = profiler.report(keep_trace=True)
    assert events, "no events captured"
    names = [n for n, _, _ in events]
    assert any(n for n in names), names
    for name, t0, t1 in events:
        assert isinstance(name, str)
        assert t1 >= t0 >= 0


def test_report_include_plane(captured):
    """include_plane=True appends the owning plane to every tuple and
    matches the 3-tuple form element-for-element (same parse, plane
    stripped vs kept)."""
    with_plane = profiler.report(keep_trace=True, include_plane=True)
    bare = profiler.report(keep_trace=True)
    assert with_plane and bare
    assert [(n, t0, t1) for n, t0, t1, _p in with_plane] == bare
    planes = {p for _n, _t0, _t1, p in with_plane}
    assert all(isinstance(p, str) and p for p in planes), planes


def test_native_and_python_parsers_agree(captured):
    files = profiler._xplane_files()
    assert files, "no xplane.pb produced"
    lib = profiler._xplane_lib()
    if lib is None:
        try:
            subprocess.run(["make", "-C", os.path.join(REPO, "native")],
                           check=True, capture_output=True)
        except (OSError, subprocess.CalledProcessError):
            pytest.skip("native toolchain unavailable")
        lib = profiler._xplane_lib()
        if lib is None:
            pytest.skip("native xplane library failed to load")
    for path in files:
        native = profiler._parse_native(lib, path, "")
        python = profiler._parse_python(path, "")
        assert native == python
        assert len(native) > 0


def test_python_parser_tolerates_truncated_file(tmp_path, captured):
    files = profiler._xplane_files()
    src = files[0]
    trunc = tmp_path / "trunc.xplane.pb"
    data = open(src, "rb").read()
    trunc.write_bytes(data[:len(data) // 3])
    # must not raise; partial (possibly empty) results are fine
    events = profiler._parse_python(str(trunc), "")
    assert isinstance(events, list)
    lib = profiler._xplane_lib()
    if lib is not None:
        assert isinstance(profiler._parse_native(lib, str(trunc), ""),
                          list)


def test_report_keeps_caller_supplied_dir(tmp_path):
    d = tmp_path / "run1"
    d.mkdir()
    (d / "precious.txt").write_text("keep me")
    profiler.initialize(str(d))
    import jax.numpy as jnp
    jnp.zeros((8,)).block_until_ready()
    profiler.flush()
    profiler.report()
    assert (d / "precious.txt").exists()


def test_double_initialize_rejected(tmp_path):
    profiler.initialize(str(tmp_path / "t"))
    try:
        with pytest.raises(RuntimeError):
            profiler.initialize(str(tmp_path / "t2"))
    finally:
        profiler.flush()
        profiler.report()  # drain


def test_report_drains_trace(tmp_path):
    profiler.initialize(str(tmp_path / "t"))
    jnp.zeros((8,)).block_until_ready()
    profiler.flush()
    first = profiler.report()
    assert profiler.report() == []
    assert isinstance(first, list)


def test_benchmark_xprof_end_to_end(tmp_path):
    """run_benchmark(xprof=True) through the real runtime on the CPU
    backend: xprof-ops.txt carries the 4-column header, the epoch
    window line, and at least two window-marker events; device_busy
    reports a marker-delimited window on it."""
    import io
    import json
    import sys as _sys
    from contextlib import redirect_stdout

    import numpy as np

    from rnb_tpu.benchmark import run_benchmark
    from rnb_tpu.control import TerminationFlag
    from rnb_tpu.decode import write_y4m
    from rnb_tpu.models.r2p1d import checkpoint as ckpt

    root = os.path.join(str(tmp_path), "data")
    os.makedirs(os.path.join(root, "label0"))
    rng = np.random.default_rng(0)
    for i in range(3):
        write_y4m(os.path.join(root, "label0", "v%d.y4m" % i),
                  rng.integers(0, 256, (30, 64, 64, 3), dtype=np.uint8))
    os.environ["RNB_TPU_DATA_ROOT"] = root
    try:
        ckpt_path = os.path.join(str(tmp_path), "tiny.msgpack")
        ckpt.save_checkpoint(ckpt_path, ckpt.init_variables(
            seed=1, num_classes=8, layer_sizes=(1, 1, 1, 1)))
        cfg = {
            "video_path_iterator":
                "rnb_tpu.models.r2p1d.model.R2P1DVideoPathIterator",
            "pipeline": [
                {"model":
                    "rnb_tpu.models.r2p1d.model.R2P1DFusingLoader",
                 "queue_groups": [{"devices": [0], "out_queues": [0]}],
                 "num_shared_tensors": 10,
                 "fuse": 2, "max_clips": 4,
                 "num_clips_population": [2], "weights": [1],
                 "consecutive_frames": 2, "num_warmups": 0,
                 "pixel_path": "yuv420"},
                {"model": "rnb_tpu.models.r2p1d.model.R2P1DRunner",
                 "queue_groups": [{"devices": [0], "in_queue": 0}],
                 "start_index": 1, "end_index": 5, "num_classes": 8,
                 "layer_sizes": [1, 1, 1, 1], "max_rows": 4,
                 "consecutive_frames": 2, "num_warmups": 0,
                 "ckpt_path": ckpt_path, "pixel_path": "yuv420"},
            ],
        }
        cfg_path = os.path.join(str(tmp_path), "fused.json")
        with open(cfg_path, "w") as f:
            json.dump(cfg, f)
        log_base = os.path.join(str(tmp_path), "logs")
        res = run_benchmark(cfg_path, mean_interval_ms=0, num_videos=6,
                            log_base=log_base, print_progress=False,
                            xprof=True)
        assert res.termination_flag == \
            TerminationFlag.TARGET_NUM_VIDEOS_REACHED
        job = os.listdir(log_base)[0]
        trace = os.path.join(log_base, job, "xprof-ops.txt")
        with open(trace) as f:
            head = [f.readline(), f.readline()]
        assert head[0].startswith("# t0_ns t1_ns plane op_name")
        assert "window_epoch" in head[1] and "flush_epoch" in head[1]
        with open(trace) as f:
            n_markers = sum("rnb_window_marker" in line for line in f)
        assert n_markers >= 2, n_markers

        _sys.path.insert(0, os.path.join(REPO, "scripts"))
        import device_busy
        buf = io.StringIO()
        with redirect_stdout(buf):
            assert device_busy.main([trace]) == 0
        assert "measured window" in buf.getvalue()
    finally:
        os.environ.pop("RNB_TPU_DATA_ROOT", None)
