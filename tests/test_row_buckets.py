"""Row bucketing: pad to the smallest bucket >= clip count.

The sampler's skewed clip population ([1,15]@[10,1]) means max-shape
padding wastes ~15x transfer+compute on most videos; buckets keep
shapes static per bucket (one jit executable each). Checks the loader's
bucket selection, validation, and a bucketed end-to-end pipeline.
"""

import json
import os

import jax
import numpy as np
import pytest

from rnb_tpu.benchmark import run_benchmark
from rnb_tpu.control import TerminationFlag
from rnb_tpu.models.r2p1d.model import R2P1DLoader, R2P1DRunner
from rnb_tpu.telemetry import TimeCard


def _loader(**kw):
    return R2P1DLoader(jax.devices()[0], max_clips=4,
                       consecutive_frames=2,
                       num_clips_population=[1, 4], weights=[3, 1],
                       num_warmups=1, **kw)


def test_loader_bucket_selection():
    ld = _loader(row_buckets=[1, 2, 4])
    assert [ld._bucket_for(n) for n in (1, 2, 3, 4)] == [1, 2, 4, 4]
    # default: single max bucket
    assert _loader()._bucket_for(1) == 4


def test_loader_emits_bucket_shapes():
    ld = _loader(row_buckets=[1, 4])
    seen = set()
    for vid in range(30):
        (pb,), _, tc = ld(None, "synth://bucket-%d" % vid, TimeCard(vid))
        assert pb.data.shape[0] in (1, 4)
        assert pb.valid <= pb.data.shape[0]
        assert pb.data.shape[0] == ld._bucket_for(pb.valid)
        seen.add(pb.data.shape[0])
    assert seen == {1, 4}, "population [1,4] must hit both buckets"


def test_bad_buckets_rejected():
    with pytest.raises(ValueError):
        _loader(row_buckets=[1, 2])  # must end at max_clips
    with pytest.raises(ValueError):
        _loader(row_buckets=[0, 4])  # positive rows only
    with pytest.raises(ValueError):
        _loader(row_buckets=[2, 2, 4])  # distinct
    with pytest.raises(ValueError):
        R2P1DRunner(jax.devices()[0], num_classes=8,
                    layer_sizes=[1, 1, 1, 1], max_rows=2,
                    consecutive_frames=2, num_warmups=1,
                    row_buckets=[1, 3])  # must end at max_rows
    with pytest.raises(ValueError):
        # raw consumers shard a fixed clip axis over a mesh
        _loader(row_buckets=[1, 4], raw_output=True)


def test_buckets_with_segments_rejected(tmp_path):
    from rnb_tpu.config import ConfigError, load_config
    cfg = {
        "video_path_iterator":
            "rnb_tpu.models.r2p1d.model.R2P1DVideoPathIterator",
        "pipeline": [
            {"model": "rnb_tpu.models.r2p1d.model.R2P1DLoader",
             "queue_groups": [{"devices": [0], "out_queues": [0]}],
             "num_segments": 2, "row_buckets": [1, 2], "max_clips": 2},
            {"model": "rnb_tpu.models.r2p1d.model.R2P1DRunner",
             "queue_groups": [{"devices": [1], "in_queue": 0}]},
        ],
    }
    path = tmp_path / "bad.json"
    path.write_text(json.dumps(cfg))
    with pytest.raises(ConfigError):
        load_config(str(path))


def test_bucketed_pipeline_end_to_end(tmp_path):
    cfg = {
        "video_path_iterator":
            "rnb_tpu.models.r2p1d.model.R2P1DVideoPathIterator",
        "pipeline": [
            {"model": "rnb_tpu.models.r2p1d.model.R2P1DLoader",
             "queue_groups": [{"devices": [0], "out_queues": [0]}],
             "num_shared_tensors": 8,
             "max_clips": 2, "consecutive_frames": 2,
             "num_clips_population": [1, 2], "weights": [2, 1],
             "row_buckets": [1, 2], "num_warmups": 1},
            {"model": "rnb_tpu.models.r2p1d.model.R2P1DRunner",
             "queue_groups": [{"devices": [1], "in_queue": 0}],
             "start_index": 1, "end_index": 5,
             "num_classes": 8, "layer_sizes": [1, 1, 1, 1],
             "max_rows": 2, "consecutive_frames": 2,
             "row_buckets": [1, 2], "num_warmups": 1},
        ],
    }
    path = os.path.join(str(tmp_path), "bucketed.json")
    with open(path, "w") as f:
        json.dump(cfg, f)
    res = run_benchmark(path, mean_interval_ms=0, num_videos=12,
                        log_base=str(tmp_path / "logs"),
                        print_progress=False, seed=0)
    assert res.termination_flag == TerminationFlag.TARGET_NUM_VIDEOS_REACHED
    assert res.throughput_vps > 0
