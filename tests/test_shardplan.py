"""Intra-stage tensor parallelism (rnb_tpu/parallel/shardplan.py).

Contract under test, on the 8-virtual-device CPU backend:

  * the weight-gathered sharded forward is logit-BIT-identical to the
    unsharded forward at degrees 2 and 4, on both production pixel
    paths (yuv420 + dct), padded and whole-pool ragged dispatch, with
    exactly ONE compiled signature per stage per arm;
  * a head stage's merge collective is host-timed into shard_stats
    (gathers / collective_ms / rows foot the calls), a mid-pipeline
    range needs no merge at all;
  * the plan math — sharded-vs-replicated byte split, the per-device
    HBM projection, the min feasible degree — and the launch-time
    gates: over-budget projection REJECTS construction, invalid
    degrees / device rings / chunked-ragged combinations are refused
    up front, never discovered mid-run.
"""

import numpy as np
import pytest

from rnb_tpu.stage import PaddedBatch, RaggedBatch
from rnb_tpu.telemetry import TimeCard

LS = (1, 1, 1, 1)  # minimal layer sizes: fast compile, full topology


# -- plan math --------------------------------------------------------

def test_shardable_widths_and_validate_degree():
    from rnb_tpu.parallel.shardplan import (shardable_widths,
                                            validate_degree)
    # the full range ends the network, so the head rides along
    assert shardable_widths(1, 5, 8) == [64, 64, 128, 256, 512, 8]
    # a mid-pipeline range has no head column axis
    assert shardable_widths(2, 4, 400) == [64, 128, 256]
    validate_degree(4, 1, 5, 8)
    validate_degree(1, 1, 5, 400)
    with pytest.raises(ValueError, match="does not divide"):
        validate_degree(3, 1, 5, 8)  # 64 % 3
    with pytest.raises(ValueError, match="does not divide"):
        validate_degree(16, 1, 5, 8)  # classes 8 % 16
    with pytest.raises(ValueError, match=">= 1"):
        validate_degree(0, 1, 5, 8)


def test_is_sharded_param_picks_temporal_and_head_only():
    from rnb_tpu.parallel.shardplan import is_sharded_param
    assert is_sharded_param(("layer1", "block0", "temporal", "kernel"))
    assert is_sharded_param(("classifier", "linear", "kernel"))
    assert is_sharded_param(("classifier", "linear", "bias"))
    assert not is_sharded_param(("layer1", "block0", "spatial",
                                 "kernel"))
    assert not is_sharded_param(("layer1", "block0", "temporal",
                                 "bias"))
    assert not is_sharded_param(("bn", "scale"))


def test_split_bytes_projection_and_min_degree():
    from rnb_tpu.parallel.shardplan import (min_feasible_degree,
                                            projected_device_mb,
                                            split_param_bytes)
    variables = {"params": {
        "temporal": {"kernel": np.zeros((3, 4, 8), np.float32)},
        "spatial": {"kernel": np.zeros((3, 3, 4), np.float32)},
        "linear": {"kernel": np.zeros((8, 8), np.float32),
                   "bias": np.zeros((8,), np.float32)}}}
    rep, sh = split_param_bytes(variables)
    assert sh == (3 * 4 * 8 + 8 * 8 + 8) * 4
    assert rep == 3 * 3 * 4 * 4
    # one formula for gate and planner: replicated + sharded/k + pool
    mib = 1 << 20
    assert projected_device_mb(2 * mib, 8 * mib, mib, 1) \
        == pytest.approx(11.0)
    assert projected_device_mb(2 * mib, 8 * mib, mib, 4) \
        == pytest.approx(5.0)
    # 11 MiB at d1, 7 at d2, 5 at d4: a 6 MiB budget first fits at 4
    assert min_feasible_degree(2 * mib, 8 * mib, mib, 6.0) == 4
    assert min_feasible_degree(2 * mib, 8 * mib, mib, 7.0) == 2
    assert min_feasible_degree(2 * mib, 8 * mib, mib, 64.0) == 1
    # the replicated half alone exceeds the budget: NO degree saves it
    assert min_feasible_degree(2 * mib, 8 * mib, mib, 2.5) is None


def test_build_shard_mesh_wants_exactly_degree_devices():
    import jax
    from rnb_tpu.parallel.shardplan import build_shard_mesh
    devs = jax.devices()
    mesh = build_shard_mesh(devs[:2], 2)
    assert int(mesh.shape["tp"]) == 2
    with pytest.raises(ValueError, match="exactly degree"):
        build_shard_mesh(devs[:3], 2)


# -- golden-logit bit parity ------------------------------------------

def _runner(pixel_path, **extra):
    import jax
    from rnb_tpu.models.r2p1d.model import R2P1DRunner
    kw = dict(start_index=1, end_index=5, num_classes=8,
              layer_sizes=LS, max_rows=2, consecutive_frames=2,
              num_warmups=1, pixel_path=pixel_path)
    kw.update(extra)
    return R2P1DRunner(jax.devices()[0], **kw)


def _yuv_pool(rows=2, seed=13):
    from rnb_tpu.ops.yuv import packed_frame_bytes
    pk = packed_frame_bytes(112, 112)
    return np.random.RandomState(seed).randint(
        0, 256, (rows, 2, pk), np.uint8)


def _dct_pool(rows=2):
    from rnb_tpu.decode import SyntheticDecoder
    return SyntheticDecoder().decode_clips_dct(
        "synth://shard-parity", list(range(0, 8 * rows, 8)), 2,
        112, 112)


@pytest.mark.parametrize("pixel_path", ["yuv420", "dct"])
def test_sharded_forward_is_bitwise_unsharded_both_pixel_paths(
        pixel_path):
    import jax.numpy as jnp
    pool = _yuv_pool() if pixel_path == "yuv420" else _dct_pool()
    base = _runner(pixel_path)
    (want,), _, _ = base((PaddedBatch(jnp.asarray(pool), 2),), None,
                         TimeCard(0))
    for degree in (2, 4):
        sharded = _runner(pixel_path, shard_degree=degree)
        sharded.bind_shard_step(1)
        (got,), _, _ = sharded((PaddedBatch(jnp.asarray(pool), 2),),
                               None, TimeCard(1))
        # BIT-identical: the gathered kernel is bitwise the unsharded
        # one and the op graph is structurally identical, so XLA's
        # bf16 excess-precision elisions land in the same places
        assert np.array_equal(np.asarray(got.data),
                              np.asarray(want.data)), \
            (pixel_path, degree)
        # the merge collective was host-timed into the accounting
        stats = sharded.shard_stats
        assert stats["gathers"] == 1
        assert stats["collective_ms"] > 0.0
        assert stats["rows"] == 2
        # one compiled signature per stage per arm: the parity call
        # above reused the warmup executable, and a repeat adds none
        sharded.compiles.freeze()
        sharded((PaddedBatch(jnp.asarray(pool), 2),), None,
                TimeCard(2))
        snap = sharded.compiles.snapshot()
        assert snap["warmup"] == 1 and snap["steady_new"] == 0


def test_sharded_ragged_whole_pool_is_bitwise_unsharded():
    import jax.numpy as jnp
    pool = _yuv_pool(rows=2, seed=17)
    # the unsharded twin must pin chunk 0 (whole-pool apply): chunked
    # dispatch changes the op graph and is NOT bitwise-comparable
    base = _runner("yuv420", ragged=True, ragged_pool_rows=2,
                   ragged_chunk_rows=0)
    sharded = _runner("yuv420", ragged=True, ragged_pool_rows=2,
                      shard_degree=2)
    assert sharded.ragged_chunk_rows == 0  # auto-chunk collapsed
    for valid in (1, 2):
        (want,), _, _ = base(
            (RaggedBatch(jnp.asarray(pool), valid, (0, valid)),),
            None, TimeCard(0))
        (got,), _, _ = sharded(
            (RaggedBatch(jnp.asarray(pool), valid, (0, valid)),),
            None, TimeCard(1))
        assert isinstance(got, RaggedBatch)
        assert np.array_equal(np.asarray(got.data)[:valid],
                              np.asarray(want.data)[:valid]), valid
    # the ragged pool is ONE signature regardless of valid
    sharded.compiles.freeze()
    sharded((RaggedBatch(jnp.asarray(pool), 2, (0, 2)),), None,
            TimeCard(2))
    snap = sharded.compiles.snapshot()
    assert snap["warmup"] == 1 and snap["steady_new"] == 0


def test_mid_pipeline_shard_has_no_merge_and_matches():
    import jax.numpy as jnp
    pool = _yuv_pool(rows=2, seed=19)
    base = _runner("yuv420", end_index=4)
    sharded = _runner("yuv420", end_index=4, shard_degree=2)
    # no head -> the last temporal gather already reassembled the
    # activation: nothing left to merge, nothing to host-time
    assert sharded._merge is None
    sharded.bind_shard_step(1)  # protocol call is a no-op here
    (want,), _, _ = base((PaddedBatch(jnp.asarray(pool), 2),), None,
                         TimeCard(0))
    (got,), _, _ = sharded((PaddedBatch(jnp.asarray(pool), 2),), None,
                           TimeCard(1))
    assert np.array_equal(np.asarray(got.data), np.asarray(want.data))
    assert sharded.shard_stats["gathers"] == 0


# -- launch-time gates ------------------------------------------------

def test_over_budget_projection_rejects_launch():
    from rnb_tpu.parallel.shardplan import (projected_device_mb,
                                            split_param_bytes)
    with pytest.raises(ValueError, match="shard launch rejected"):
        _runner("yuv420", shard_degree=2, shard_hbm_budget_mb=0.001)
    # a budget between the d1 and d2 projections: degree 1 is the
    # headline's launch-rejected arm, degree 2 fits
    probe = _runner("yuv420", shard_degree=2,
                    shard_hbm_budget_mb=10_000.0)
    stats = probe.shard_stats
    rep, sh = stats["replicated_bytes"], stats["sharded_bytes"]
    pool = stats["pool_bytes"]
    d1 = projected_device_mb(rep, sh, pool, 1)
    d2 = projected_device_mb(rep, sh, pool, 2)
    assert d2 < d1
    budget = (d1 + d2) / 2.0
    with pytest.raises(ValueError, match="shard launch rejected"):
        _runner("yuv420", shard_degree=1, shard_hbm_budget_mb=budget)
    fits = _runner("yuv420", shard_degree=2,
                   shard_hbm_budget_mb=budget)
    assert fits.shard_stats["min_degree"] == 2
    # the stats' byte split is the real variables tree's
    assert (rep, sh) == split_param_bytes(fits._variables)


def test_shard_construction_rejections():
    import jax
    with pytest.raises(ValueError, match="does not divide"):
        _runner("yuv420", shard_degree=3)
    with pytest.raises(ValueError, match="shard_degree must be"):
        _runner("yuv420", shard_degree=0)
    with pytest.raises(ValueError, match="exactly that many devices"):
        _runner("yuv420", shard_degree=2,
                shard_devices=[0, 1, 2])
    with pytest.raises(ValueError, match="cannot be combined"):
        _runner("yuv420", ragged=True, ragged_pool_rows=2,
                ragged_chunk_rows=2, shard_degree=2)
    # declared degree 1 arms the accounting without a mesh
    one = _runner("yuv420", shard_degree=1)
    assert one.shard_declared and one._shard_mesh is None
    assert one.shard_stats["degree"] == 1
    del jax
