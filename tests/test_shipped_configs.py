"""Every shipped config must have EXECUTED end-to-end at least once.

The reference shipped config/r2p1d-segment.json broken for years
because its sanity_check only parsed. Here scripts/run_shipped_configs.py
runs each configs/*.json through run_benchmark on the 8-virtual-device
CPU backend and records one row per config in MULTICHIP_CONFIGS.json;
this test pins the committed artifact to the shipped set, so adding a
config without ever executing it (or committing a failing sweep) fails
the suite. Re-run the sweep — full, or ``--only <new-config>.json`` to
merge one row — whenever configs change.
"""

import glob
import json
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ARTIFACT = os.path.join(REPO, "MULTICHIP_CONFIGS.json")


def test_every_shipped_config_validates_under_extended_schema():
    """Every configs/*.json parses under the full schema including the
    robustness keys (overload_policy, fault_containment, fault_plan,
    per-step retry knobs) — and the shipped set exercises the "shed"
    overload policy at least once so the non-default path cannot rot
    unvalidated."""
    from rnb_tpu.config import load_config
    policies = set()
    for path in sorted(glob.glob(os.path.join(REPO, "configs",
                                              "*.json"))):
        cfg = load_config(path)  # raises ConfigError on any violation
        assert cfg.overload_policy in ("abort", "shed")
        policies.add(cfg.overload_policy)
        for step in cfg.steps:
            assert step.max_retries >= 0
            assert step.retry_backoff_ms >= 0
    assert "shed" in policies, (
        "no shipped config exercises overload_policy: \"shed\" — keep "
        "configs/r2p1d-tiny-shed.json (or an equivalent) in the tree")


def test_every_shipped_config_has_an_ok_execution_row():
    assert os.path.exists(ARTIFACT), (
        "MULTICHIP_CONFIGS.json missing — run "
        "scripts/run_shipped_configs.py")
    with open(ARTIFACT) as f:
        artifact = json.load(f)
    rows = {r["config"]: r for r in artifact["configs"]}
    shipped = sorted(
        os.path.relpath(p, REPO)
        for p in glob.glob(os.path.join(REPO, "configs", "*.json")))
    missing = [c for c in shipped if c not in rows]
    assert not missing, (
        "configs never executed end-to-end: %s — run "
        "scripts/run_shipped_configs.py --only '<name>.json'" % missing)
    failed = [c for c in shipped if not rows[c].get("ok")]
    assert not failed, (
        "configs whose last end-to-end execution failed: %s (see "
        "MULTICHIP_CONFIGS.json for the error rows)" % failed)
    assert artifact["all_ok"] is True


def test_scaleout_arms_ship_executed_and_scale():
    """The PR 9 replica/handoff arms must land in BOTH configs/ and
    the matrix (the two-way sync tests above enforce the general
    rule; this pins the specific pair), and the committed execution
    rows must back the headline claim: the 4-replica arm >= 2.5x the
    single-replica same-workload arm. A re-sweep that drops below the
    floor invalidates the headline and must fail here, not silently
    rot in the artifact (`make multichip` asserts the same bound
    end-to-end with --check)."""
    arms = ("configs/rnb-scaleout-r1.json",
            "configs/rnb-scaleout-r4.json")
    for rel in arms:
        assert os.path.exists(os.path.join(REPO, rel)), rel
        from rnb_tpu.config import load_config
        cfg = load_config(os.path.join(REPO, rel))
        # both arms declare device-resident handoff + the planner
        assert cfg.handoff and cfg.handoff.get("mode") == "device"
        assert cfg.placement is not None
    # the apply arm really expands to 4 replica lanes
    r4_cfg = load_config(os.path.join(REPO, arms[1]))
    assert r4_cfg.steps[1].replica_queues is not None
    assert len(r4_cfg.steps[1].replica_queues) == 4
    with open(ARTIFACT) as f:
        rows = {r["config"]: r
                for r in json.load(f)["configs"]}
    for rel in arms:
        assert rows[rel].get("ok"), rel
    ratio = (rows[arms[1]]["videos_per_sec"]
             / rows[arms[0]]["videos_per_sec"])
    assert ratio >= 2.5, (
        "committed scale-out rows show only %.2fx (4-replica vs "
        "1-replica); the headline requires >= 2.5x — re-run "
        "scripts/run_shipped_configs.py --only 'rnb-scaleout-*' on an "
        "idle host or retune the arms" % ratio)


def test_chaos_arm_ships_executed_with_the_full_healing_layer():
    """The replica-loss chaos arm (PR 10 self-healing) must land in
    BOTH configs/ and the matrix with an ok execution row, and must
    actually declare the whole healing surface — lane health, a
    lane-addressed replica_stall kill, p95x hedging on the replicated
    step — so `make chaos` exercises circuit breaking + eviction +
    redispatch, not a watered-down arm."""
    rel = "configs/rnb-scaleout-r4-chaos.json"
    path = os.path.join(REPO, rel)
    assert os.path.exists(path), rel
    from rnb_tpu.config import load_config
    cfg = load_config(path)
    assert cfg.health is not None
    assert cfg.steps[1].replica_queues is not None
    assert len(cfg.steps[1].replica_queues) == 4
    assert cfg.steps[1].hedge_ms == "p95x"
    kinds = {f["kind"] for f in cfg.fault_plan["faults"]}
    assert "replica_stall" in kinds, (
        "the chaos arm must kill a lane mid-stream (replica_stall/"
        "replica_crash), got fault kinds %s" % sorted(kinds))
    lane_faults = [f for f in cfg.fault_plan["faults"]
                   if f["kind"] == "replica_stall"]
    assert lane_faults[0]["lane"] in cfg.steps[1].replica_queues
    with open(ARTIFACT) as f:
        rows = {r["config"]: r for r in json.load(f)["configs"]}
    assert rel in rows and rows[rel].get("ok"), (
        "the chaos arm has no ok execution row — run "
        "scripts/run_shipped_configs.py --only "
        "'rnb-scaleout-r4-chaos.json'")


def test_metrics_arm_ships_executed_with_overhead_in_the_noise():
    """The live-metrics headline cell (PR 11) must land in BOTH
    configs/ and the matrix with an ok execution row, must actually
    declare the root ``metrics`` key over the same topology as
    rnb-fused-yuv-staged, and the committed pair must back the
    overhead claim: the metrics arm's videos/s within the noise of
    the staged baseline (>= 0.85x). A re-sweep that drops below the
    floor invalidates the 'overhead in the noise' headline and must
    fail here, not silently rot in the artifact."""
    rel = "configs/rnb-fused-yuv-metrics.json"
    base = "configs/rnb-fused-yuv-staged.json"
    path = os.path.join(REPO, rel)
    assert os.path.exists(path), rel
    from rnb_tpu.config import load_config
    cfg = load_config(path)
    assert cfg.metrics is not None and cfg.metrics.get("enabled", True)
    base_cfg = load_config(os.path.join(REPO, base))
    # same topology as the staged baseline: the pair differs by the
    # metrics key alone, so the committed ratio IS the overhead
    assert [s.model for s in cfg.steps] \
        == [s.model for s in base_cfg.steps]
    with open(ARTIFACT) as f:
        rows = {r["config"]: r for r in json.load(f)["configs"]}
    assert rel in rows and rows[rel].get("ok"), (
        "the metrics arm has no ok execution row — run "
        "scripts/run_shipped_configs.py --only "
        "'rnb-fused-yuv-metrics.json'")
    ratio = rows[rel]["videos_per_sec"] / rows[base]["videos_per_sec"]
    assert ratio >= 0.85, (
        "metrics arm runs at %.2fx the staged baseline — the live "
        "plane's overhead is no longer in the noise; profile the "
        "flusher/bridge before re-executing the row" % ratio)


def test_operator_arm_ships_executed_with_overhead_in_the_noise():
    """The operator-plane headline cell (PR 15) must land in BOTH
    configs/ and the matrix with an ok execution row, must declare the
    root ``operator`` key (actions gated OFF per the honesty policy,
    sampler ON) over the same topology as rnb-fused-yuv-metrics, and
    the committed pair must back the overhead claim: serving the
    operator server + continuous stack sampler costs videos/s within
    the noise of the metrics baseline (>= 0.85x)."""
    rel = "configs/rnb-fused-yuv-operator.json"
    base = "configs/rnb-fused-yuv-metrics.json"
    path = os.path.join(REPO, rel)
    assert os.path.exists(path), rel
    from rnb_tpu.config import load_config
    cfg = load_config(path)
    assert cfg.operator is not None \
        and cfg.operator.get("enabled", True)
    assert cfg.operator.get("allow_actions") is False, (
        "the shipped operator arm must keep actuation opt-in "
        "(allow_actions false) — introspection ships, control does "
        "not")
    assert cfg.operator.get("sample_hz", 1) > 0, (
        "the shipped arm carries the always-on sampler (the overhead "
        "claim covers it)")
    base_cfg = load_config(os.path.join(REPO, base))
    # same topology as the metrics baseline: the pair differs by the
    # operator key alone, so the committed ratio IS the overhead
    assert [s.model for s in cfg.steps] \
        == [s.model for s in base_cfg.steps]
    with open(ARTIFACT) as f:
        rows = {r["config"]: r for r in json.load(f)["configs"]}
    assert rel in rows and rows[rel].get("ok"), (
        "the operator arm has no ok execution row — run "
        "scripts/run_shipped_configs.py --only "
        "'rnb-fused-yuv-operator.json'")
    ratio = rows[rel]["videos_per_sec"] / rows[base]["videos_per_sec"]
    assert ratio >= 0.85, (
        "operator arm runs at %.2fx the metrics baseline — the "
        "server/sampler overhead is no longer in the noise; profile "
        "the sampler cadence before re-executing the row" % ratio)


def test_dct_arm_ships_executed_with_half_the_wire_bytes():
    """The DCT-domain ingest headline cell (PR 12) must land in BOTH
    configs/ and the matrix with an ok execution row, must be the
    same topology as rnb-fused-yuv-ragged differing by the pixel path
    alone, must declare wire rows at <= HALF the yuv420 arm's
    bytes/frame (the byte headline, computed from the stages' own
    declarations), and the committed pair must back the 'no slower'
    claim within host noise (>= 0.9x — `make dct` asserts the strict
    byte bound and logit parity end-to-end)."""
    rel = "configs/rnb-fused-dct-ragged.json"
    base = "configs/rnb-fused-yuv-ragged.json"
    path = os.path.join(REPO, rel)
    assert os.path.exists(path), rel
    from rnb_tpu.config import load_config
    from rnb_tpu.utils.class_utils import load_class
    cfg = load_config(path)
    base_cfg = load_config(os.path.join(REPO, base))
    assert [s.model for s in cfg.steps] \
        == [s.model for s in base_cfg.steps]
    assert cfg.ragged == base_cfg.ragged
    kw = cfg.steps[0].kwargs_for_group(0)
    base_kw = base_cfg.steps[0].kwargs_for_group(0)
    assert kw["pixel_path"] == "dct"
    assert base_kw["pixel_path"] == "yuv420"
    # the wire-byte headline, from the loader's own declarations
    loader_cls = load_class(cfg.steps[0].model)
    dct_shape = loader_cls.output_shape_for(**kw)[0]
    yuv_shape = loader_cls.output_shape_for(**base_kw)[0]
    dct_bytes = dct_shape[-1] * 2   # int16 coefficient rows
    yuv_bytes = yuv_shape[-1]       # u8 packed planes
    assert loader_cls.output_dtype_for(**kw) == "int16"
    assert dct_bytes * 2 <= yuv_bytes, (
        "the dct wire row (%d B/frame) must stay <= half the yuv420 "
        "row (%d B/frame)" % (dct_bytes, yuv_bytes))
    with open(ARTIFACT) as f:
        rows = {r["config"]: r for r in json.load(f)["configs"]}
    assert rel in rows and rows[rel].get("ok"), (
        "the dct arm has no ok execution row — run "
        "scripts/run_shipped_configs.py --only "
        "'rnb-fused-dct-ragged.json'")
    ratio = rows[rel]["videos_per_sec"] / rows[base]["videos_per_sec"]
    assert ratio >= 0.9, (
        "dct arm runs at %.2fx the yuv420 ragged baseline — the "
        "fused on-device ingest should be throughput-neutral on the "
        "CPU harness (and a win on real TPUs, where the wire is the "
        "bottleneck); profile the unpack/IDCT before re-executing "
        "the row" % ratio)


def test_netedge_arms_ship_executed_with_loopback_near_in_process():
    """The disaggregated-ingest cells (PR 16) must land in BOTH
    configs/ and the matrix with ok execution rows. The loopback
    headline cell serves the DCT loader from a real second process
    over the netedge wire: its frame payload must be the PR 12 packed
    row exactly (9408 B/frame at the default budget, computed from
    the loader's own declarations — the wire ships valid rows only,
    so bytes/frame IS the packed row size), and its committed
    throughput must hold >= 0.85x its in-process twin
    rnb-netedge-off (byte-identical pipeline, netedge disabled,
    executed back-to-back by the same sweep) — the only variable
    between the two rows is the process boundary, so the committed
    ratio IS the wire overhead. The honesty policy forbids comparing
    either netedge cell against the fused/ragged rows: fusing is
    unavailable over the wire by construction (single-request
    emissions keep the dedup ledger's exactly-once claim sound), so
    those rows measure a different workload. The chaos arm must
    declare the full network fault surface `make netchaos` exercises
    — a non-fatal reset, a silent wedge, a fatal peer kill — against
    a liveness circuit tight enough to beat its io timeout."""
    rel = "configs/rnb-netedge-loopback.json"
    base = "configs/rnb-netedge-off.json"
    chaos = "configs/rnb-netedge-chaos.json"
    from rnb_tpu.config import load_config
    from rnb_tpu.utils.class_utils import load_class
    for p in (rel, base, chaos):
        assert os.path.exists(os.path.join(REPO, p)), p
    cfg = load_config(os.path.join(REPO, rel))
    assert cfg.netedge is not None and cfg.netedge.get("enabled")
    assert cfg.netedge.get("spawn"), (
        "the shipped loopback cell must dial a REAL spawned peer "
        "process — an in-process shortcut would not measure the wire")
    # the wire carries single-request emissions only (seq <-> request
    # 1:1 is what keeps the dedup ledger's exactly-once claim sound),
    # so the disaggregated arm is the plain non-fusing twin: same
    # pixel path as the dct headline arm, no ragged pooling
    kw = cfg.steps[0].kwargs_for_group(0)
    assert kw["pixel_path"] == "dct"
    assert cfg.ragged is None
    loader_cls = load_class(cfg.steps[0].model)
    frame_bytes = loader_cls.output_shape_for(**kw)[0][-1] * 2
    assert loader_cls.output_dtype_for(**kw) == "int16"
    assert frame_bytes == 9408, (
        "the loopback cell's wire row is %d B/frame — the PR 12 "
        "packed-DCT pin is 9408 (dct_rows_per_frame x budget x "
        "int16); a drifted row size silently changes the headline's "
        "meaning" % frame_bytes)
    # the denominator must stay the loopback cell's true twin: same
    # pipeline verbatim, netedge block differing ONLY in the enabled
    # switch — otherwise the committed ratio stops meaning "the wire"
    with open(os.path.join(REPO, rel)) as f:
        rel_raw = json.load(f)
    with open(os.path.join(REPO, base)) as f:
        base_raw = json.load(f)
    assert not base_raw["netedge"]["enabled"]
    assert dict(base_raw["netedge"], enabled=True) == rel_raw["netedge"]
    assert base_raw["pipeline"] == rel_raw["pipeline"], (
        "rnb-netedge-off.json drifted from the loopback pipeline — "
        "the wire-cost ratio is only honest between byte-identical "
        "twins")
    chaos_cfg = load_config(os.path.join(REPO, chaos))
    assert chaos_cfg.netedge is not None \
        and chaos_cfg.netedge.get("enabled")
    assert chaos_cfg.health is not None
    kinds = [f["kind"] for f in chaos_cfg.fault_plan["faults"]]
    assert "net_reset" in kinds and "net_timeout" in kinds, (
        "the net chaos arm must stage both a reset and a silent "
        "wedge, got %s" % sorted(set(kinds)))
    assert any(f.get("fatal") for f in chaos_cfg.fault_plan["faults"]
               if f["kind"] == "net_reset"), (
        "the net chaos arm must kill the peer process outright "
        "(fatal net_reset) — eviction + local fallback is the "
        "scenario win")
    # the open-before-timeout claim needs the circuit strictly
    # tighter than the io timeout
    assert chaos_cfg.health["open_after_ms"] \
        < chaos_cfg.netedge["io_timeout_ms"]
    with open(ARTIFACT) as f:
        rows = {r["config"]: r for r in json.load(f)["configs"]}
    for p in (rel, base, chaos):
        assert p in rows and rows[p].get("ok"), (
            "%s has no ok execution row — run "
            "scripts/run_shipped_configs.py --only '%s'"
            % (p, os.path.basename(p)))
    ratio = rows[rel]["videos_per_sec"] / rows[base]["videos_per_sec"]
    assert ratio >= 0.85, (
        "loopback netedge cell runs at %.2fx its in-process twin "
        "(rnb-netedge-off) — crossing a process boundary should cost "
        "noise (serialization + loopback memcpy), not throughput; "
        "profile the wire before re-executing the rows back-to-back"
        % ratio)


def test_paged_zipf_arm_ships_executed_and_beats_its_blob_twin():
    """The paged-memory headline pair (PR 17) must land in BOTH
    configs/ and the matrix with ok execution rows, and the committed
    rows must back the headline claim: the paged + feature-pages cell
    >= 1.15x the blob-cache twin under the same seeded Zipf workload,
    executed back-to-back by the same sweep. The twins cannot be
    byte-identical pipelines — the pager requires the ragged plane by
    construction — so the honesty anchor is the WORKLOAD: the same
    popularity block, the same fusing shape, the same cache budget.
    What the ratio then measures is the paged seam itself (page-slab
    hits gathered on-device instead of host-copied blobs, plus
    feature pages answering repeats before any decode). A re-sweep
    that drops below the floor invalidates the headline and must fail
    here (`make pages` asserts the numerics contract end-to-end)."""
    rel = "configs/rnb-fused-yuv-paged-zipf.json"
    base = "configs/rnb-fused-yuv-zipf-cache.json"
    from rnb_tpu.config import load_config
    for p in (rel, base):
        assert os.path.exists(os.path.join(REPO, p)), p
    cfg = load_config(os.path.join(REPO, rel))
    assert cfg.pager is not None and cfg.pager.get("enabled")
    assert cfg.pager.get("feature_cache"), (
        "the headline cell must exercise feature pages — without them "
        "the row only measures the clip-page gather")
    assert cfg.ragged is not None and cfg.ragged.get("enabled"), (
        "pager requires the ragged plane (page gathers land in the "
        "ragged pool)")
    base_cfg = load_config(os.path.join(REPO, base))
    assert cfg.pager.get("page_rows", 0) >= 1
    assert base_cfg.pager is None, (
        "the blob twin must not enable the pager — the ratio stops "
        "meaning 'the paged seam' otherwise")
    # same seeded Zipf workload and the same cache budget on both
    # arms: the only intended deltas are the pager + ragged planes
    with open(os.path.join(REPO, rel)) as f:
        rel_raw = json.load(f)
    with open(os.path.join(REPO, base)) as f:
        base_raw = json.load(f)
    assert rel_raw["popularity"] == base_raw["popularity"], (
        "the twins drifted apart on the popularity block — the ratio "
        "is only honest over identical traffic")
    rel_kw = cfg.steps[0].kwargs_for_group(0)
    base_kw = base_cfg.steps[0].kwargs_for_group(0)
    for key in ("cache_mb", "fuse", "max_clips", "pixel_path"):
        assert rel_kw.get(key) == base_kw.get(key), (
            "twins differ on loader %r — re-align the arms before "
            "trusting the committed ratio" % key)
    with open(ARTIFACT) as f:
        rows = {r["config"]: r for r in json.load(f)["configs"]}
    for p in (rel, base):
        assert p in rows and rows[p].get("ok"), (
            "%s has no ok execution row — run "
            "scripts/run_shipped_configs.py --only '%s'"
            % (p, os.path.basename(p)))
    ratio = rows[rel]["videos_per_sec"] / rows[base]["videos_per_sec"]
    assert ratio >= 1.15, (
        "paged Zipf cell runs at %.2fx its blob-cache twin — the "
        "headline floor is 1.15x (committed pair: 1.21x). Re-execute "
        "BOTH rows back-to-back on one idle host "
        "(scripts/run_shipped_configs.py --only "
        "'rnb-fused-yuv-*zipf*') before concluding a regression; if "
        "it reproduces, profile the gather path (`make pages`) "
        "before touching the floor" % ratio)


def test_shard_arms_ship_executed_and_pin_the_feasibility_headline():
    """The intra-stage sharding pair (PR 19) must land in BOTH
    configs/ and the matrix with ok execution rows. The headline is a
    FEASIBILITY claim, not a speed claim — weight-gathered sharding
    never divides compute — so the pin is analytic: project the d2
    arm's per-device bytes from the abstract parameter tree
    (jax.eval_shape — no weight is ever materialized) plus the
    declared ragged pool, and assert the shipped 120 MiB budget
    strictly separates degree 1 (launch-rejected, ~129.6 MiB) from
    degree 2 (runs, ~112.1 MiB). A checkpoint/pool-geometry change
    that collapses the separation invalidates the headline and must
    fail here, not silently rot in the config comments (`make shard`
    asserts the reject + bit parity end-to-end on a reduced net)."""
    rel = "configs/rnb-shard-d2.json"
    base = "configs/rnb-shard-d1.json"
    from rnb_tpu.config import load_config
    for p in (rel, base):
        assert os.path.exists(os.path.join(REPO, p)), p
    cfg = load_config(os.path.join(REPO, rel))
    base_cfg = load_config(os.path.join(REPO, base))
    kw = cfg.steps[1].kwargs_for_group(0)
    base_kw = base_cfg.steps[1].kwargs_for_group(0)
    assert kw["shard_degree"] == 2
    assert len(kw["shard_devices"]) == 2
    budget = kw["shard_hbm_budget_mb"]
    assert budget == 120.0
    # the baseline arm declares degree 1 (telemetry armed, no mesh),
    # ships WITHOUT the budget (it could not launch under it), and
    # pins whole-pool apply — the only program shape the sharded arm
    # is bitwise-comparable against
    assert base_kw["shard_degree"] == 1
    assert "shard_hbm_budget_mb" not in base_kw
    assert base_kw["ragged_chunk_rows"] == 0
    # same workload on both arms: the pair differs by the runner's
    # devices + shard key alone
    with open(os.path.join(REPO, rel)) as f:
        rel_raw = json.load(f)
    with open(os.path.join(REPO, base)) as f:
        base_raw = json.load(f)
    assert rel_raw["pipeline"][0] == base_raw["pipeline"][0]
    assert rel_raw["ragged"] == base_raw["ragged"]
    # the analytic feasibility pin: abstract init (eval_shape) of the
    # shipped network -> split by the shard partitioning rule -> the
    # per-device projection the launch gate enforces
    import jax
    import numpy as np
    from rnb_tpu.models.r2p1d.network import (LAYER_INPUT_SHAPES,
                                              R2Plus1DClassifier)
    from rnb_tpu.ops.yuv import packed_frame_bytes
    from rnb_tpu.parallel.shardplan import (min_feasible_degree,
                                            projected_device_mb,
                                            split_param_bytes)
    model = R2Plus1DClassifier(
        start=cfg.steps[1].kwargs_for_group(0).get("start_index", 1),
        end=5, num_classes=400)
    dummy = jax.ShapeDtypeStruct(
        (1, 2, 14, 14, LAYER_INPUT_SHAPES[1][-1]), np.float32)
    abstract = jax.eval_shape(
        lambda k, x: model.init(k, x, train=False),
        jax.random.key(0), dummy)
    rep, sh = split_param_bytes(abstract)
    pool_bytes = (rel_raw["ragged"]["pool_rows"] * 8
                  * packed_frame_bytes(112, 112))
    d1_mb = projected_device_mb(rep, sh, pool_bytes, 1)
    d2_mb = projected_device_mb(rep, sh, pool_bytes, 2)
    assert d2_mb <= budget < d1_mb, (
        "the shipped 120 MiB budget no longer separates the arms "
        "(d1 projects %.1f MiB, d2 %.1f) — the feasibility headline "
        "is void; re-derive the budget from the current network"
        % (d1_mb, d2_mb))
    assert min_feasible_degree(rep, sh, pool_bytes, budget,
                               (1, 2, 4)) == 2
    with open(ARTIFACT) as f:
        rows = {r["config"]: r for r in json.load(f)["configs"]}
    for p in (rel, base):
        assert p in rows and rows[p].get("ok"), (
            "%s has no ok execution row — run "
            "scripts/run_shipped_configs.py --only '%s'"
            % (p, os.path.basename(p)))


def test_every_executed_config_is_still_shipped():
    """The reverse direction: MULTICHIP_CONFIGS.json and configs/ stay
    in sync BOTH ways. A row for a config that no longer ships is a
    stale execution claim — it reads as coverage for a topology the
    tree no longer contains (delete the row when retiring a config, or
    restore the config)."""
    with open(ARTIFACT) as f:
        artifact = json.load(f)
    shipped = {
        os.path.relpath(p, REPO)
        for p in glob.glob(os.path.join(REPO, "configs", "*.json"))}
    stale = sorted({r["config"] for r in artifact["configs"]} - shipped)
    assert not stale, (
        "MULTICHIP_CONFIGS.json rows for configs that no longer ship: "
        "%s — prune the rows (scripts/run_shipped_configs.py rewrites "
        "the artifact) or restore the configs" % stale)
