"""Tiny stage models + path iterators used by runtime tests.

These play the role of the reference's CPU fallback (`gpus: [-1]`) as a
poor man's fake backend (SURVEY.md §4): minimal stages that exercise the
pipeline machinery without heavyweight models.
"""

from __future__ import annotations

import numpy as np

from rnb_tpu.stage import PaddedBatch, StageModel
from rnb_tpu.video_path_provider import VideoPathIterator

SHAPE = (4, 2)  # (max_rows, feature)


class TinyLoader(StageModel):
    """First stage: turns a request id string into a small batch."""

    def __init__(self, device, rows_per_video=2, **kwargs):
        super().__init__(device)
        self.rows_per_video = int(rows_per_video)

    @staticmethod
    def output_shape():
        return (SHAPE,)

    def __call__(self, tensors, non_tensors, time_card):
        vid = int(str(non_tensors).rsplit("-", 1)[-1])
        rows = np.full((self.rows_per_video, SHAPE[1]), float(vid),
                       dtype=np.float32)
        return (PaddedBatch.from_rows(rows, SHAPE[0]),), vid, time_card


class TinyDouble(StageModel):
    """Middle stage: doubles the payload."""

    def input_shape(self):
        return (SHAPE,)

    @staticmethod
    def output_shape():
        return (SHAPE,)

    def __call__(self, tensors, non_tensors, time_card):
        pb = tensors[0]
        return (PaddedBatch(np.asarray(pb.data) * 2.0, pb.valid),), \
            non_tensors, time_card


class TinySink(StageModel):
    """Final stage: no tensor outputs (output_shape None => no rings)."""

    def __init__(self, device, **kwargs):
        super().__init__(device)
        self.seen = []

    @staticmethod
    def output_shape():
        return None

    def __call__(self, tensors, non_tensors, time_card):
        if tensors is not None:
            self.seen.append(np.asarray(tensors[0].data).copy())
        return None, non_tensors, time_card


class TinyRoutedLoader(TinyLoader):
    """Loader stamping num_clips: every 4th video 'large' (15 clips)."""

    def __call__(self, tensors, non_tensors, time_card):
        out = super().__call__(tensors, non_tensors, time_card)
        vid = int(str(non_tensors).rsplit("-", 1)[-1])
        time_card.num_clips = 15 if vid % 4 == 3 else 1
        return out


class TinySlowSink(StageModel):
    """Final stage that sleeps per item — forces upstream overflow."""

    def __init__(self, device, delay_s=0.2, **kwargs):
        super().__init__(device)
        self.delay_s = float(delay_s)

    @staticmethod
    def output_shape():
        return None

    def __call__(self, tensors, non_tensors, time_card):
        import time
        time.sleep(self.delay_s)
        return None, non_tensors, time_card


class HoardingSink(StageModel):
    """Final stage that swallows EVERY item and releases them only at
    end-of-stream, one per flush() call — a deterministic stand-in for
    accumulator stages holding many pending batches at drain time."""

    def __init__(self, device, **kwargs):
        super().__init__(device)
        self._held = []

    @staticmethod
    def output_shape():
        return None

    def __call__(self, tensors, non_tensors, time_card):
        time_card.num_clips = 1  # completions show in clips_completed
        self._held.append((non_tensors, time_card))
        return None, None, None

    def flush(self):
        if not self._held:
            return None
        non_tensors, time_card = self._held.pop(0)
        return None, non_tensors, time_card


class TinyComputeSink(StageModel):
    """Final stage with a tiny jitted matmul plus the devobs compute/
    memory seam (compute_profile): the declared per-row FLOPs are the
    matmul's 2*F*F MACs, the 'params' footprint is the weight matrix,
    so test_devobs can check MFU/ledger arithmetic against hand
    computation while the jit guarantees a capture window sees XLA
    ops."""

    FLOPS_PER_ROW = 2 * SHAPE[1] * SHAPE[1]

    def __init__(self, device, **kwargs):
        super().__init__(device)
        import jax
        self._w = jax.device_put(
            np.eye(SHAPE[1], dtype=np.float32))
        self._apply = jax.jit(lambda x, w: x @ w)
        jax.block_until_ready(
            self._apply(np.zeros(SHAPE, np.float32), self._w))
        self.seen = []

    def compute_profile(self):
        return {
            "flops_per_row": self.FLOPS_PER_ROW,
            "devices": 1,
            "bytes_per_row": float(SHAPE[1] * 4 * 2),
            "params_key": ("tiny-w", SHAPE[1]),
            "params_bytes": int(self._w.nbytes),
            "pool_bytes": 0,
        }

    @staticmethod
    def output_shape():
        return None

    def __call__(self, tensors, non_tensors, time_card):
        if tensors is not None:
            import jax
            out = self._apply(
                np.asarray(tensors[0].data, np.float32), self._w)
            self.seen.append(np.asarray(jax.block_until_ready(out)))
        return None, non_tensors, time_card


class CountingPathIterator(VideoPathIterator):
    """Yields synthetic request ids forever: video-0, video-1, ..."""

    def __iter__(self):
        i = 0
        while True:
            yield "video-%d" % i
            i += 1
