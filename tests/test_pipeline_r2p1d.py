"""End-to-end run of the real R(2+1)D stages (reduced geometry).

One bounded integration test: Poisson client -> R2P1DLoader (synthetic
decode, 2-frame clips) -> R2P1DRunner (1-block layers, 8 classes) ->
logs, on two virtual devices. Uses the shared jit/param caches, so cost
is one compile for the whole test session.
"""

import json
import os


from rnb_tpu.benchmark import run_benchmark
from rnb_tpu.control import TerminationFlag


def test_r2p1d_whole_pipeline(tmp_path):
    cfg = {
        "video_path_iterator":
            "rnb_tpu.models.r2p1d.model.R2P1DVideoPathIterator",
        "pipeline": [
            {"model": "rnb_tpu.models.r2p1d.model.R2P1DLoader",
             "queue_groups": [{"devices": [0], "out_queues": [0]}],
             "num_shared_tensors": 8,
             "max_clips": 2, "consecutive_frames": 2,
             "num_clips_population": [1, 2], "weights": [3, 1],
             "num_warmups": 1},
            {"model": "rnb_tpu.models.r2p1d.model.R2P1DRunner",
             "queue_groups": [{"devices": [1], "in_queue": 0}],
             "start_index": 1, "end_index": 5,
             "num_classes": 8, "layer_sizes": [1, 1, 1, 1],
             "max_rows": 2, "consecutive_frames": 2, "num_warmups": 1},
        ],
    }
    path = os.path.join(str(tmp_path), "whole.json")
    with open(path, "w") as f:
        json.dump(cfg, f)
    res = run_benchmark(path, mean_interval_ms=0, num_videos=4,
                        queue_size=20, log_base=str(tmp_path / "logs"),
                        print_progress=False)
    assert res.termination_flag == TerminationFlag.TARGET_NUM_VIDEOS_REACHED
    reports = [f for f in os.listdir(res.log_dir) if "group" in f]
    with open(os.path.join(res.log_dir, reports[0])) as f:
        lines = f.read().strip().split("\n")
    header = lines[0].split()
    assert "inference0_finish" in header  # loader stage timed
    assert "inference1_finish" in header  # net stage timed
    assert len(lines) - 1 >= 4


def test_r2p1d_layer_split_pipeline(tmp_path):
    """Inter-layer partitioning end-to-end: loader -> conv1-4 -> conv5.

    The mid-pipeline feature-map hand-off the reference could never wire
    (its TODO #69: output shapes hardcoded to full-range logits); here
    the conv1-4 stage declares its exact shape via output_shape_for and
    the runtime sizes its ring from it.
    """
    tiny = {"num_classes": 8, "layer_sizes": [1, 1, 1, 1],
            "consecutive_frames": 2, "num_warmups": 1}
    cfg = {
        "video_path_iterator":
            "rnb_tpu.models.r2p1d.model.R2P1DVideoPathIterator",
        "pipeline": [
            {"model": "rnb_tpu.models.r2p1d.model.R2P1DLoader",
             "queue_groups": [{"devices": [0], "out_queues": [0]}],
             "num_shared_tensors": 8,
             "max_clips": 2, "consecutive_frames": 2,
             "num_clips_population": [1, 2], "weights": [3, 1],
             "num_warmups": 1},
            {"model": "rnb_tpu.models.r2p1d.model.R2P1DRunner",
             "queue_groups": [{"devices": [1], "in_queue": 0,
                               "out_queues": [0]}],
             "start_index": 1, "end_index": 4, "max_rows": 2, **tiny},
            {"model": "rnb_tpu.models.r2p1d.model.R2P1DRunner",
             "queue_groups": [{"devices": [2], "in_queue": 0}],
             "start_index": 5, "end_index": 5, "max_rows": 2, **tiny},
        ],
    }
    path = os.path.join(str(tmp_path), "split.json")
    with open(path, "w") as f:
        json.dump(cfg, f)
    res = run_benchmark(path, mean_interval_ms=0, num_videos=4,
                        queue_size=20, log_base=str(tmp_path / "logs"),
                        print_progress=False)
    assert res.termination_flag == TerminationFlag.TARGET_NUM_VIDEOS_REACHED
    reports = [f for f in os.listdir(res.log_dir) if "group" in f]
    with open(os.path.join(res.log_dir, reports[0])) as f:
        header = f.readline().split()
    assert "inference2_finish" in header  # all three stages timed


def test_split_range_logits_match_whole_range(tmp_path):
    """conv1-4 -> conv5 staged inference must reproduce the whole-range
    logits when both load the same checkpoint (weight-sharing via
    explicit ckpt_path, checkpoint.load_or_init)."""
    import jax.numpy as jnp
    import numpy as np

    from rnb_tpu.models.r2p1d import checkpoint as ckpt
    from rnb_tpu.models.r2p1d.model import R2P1DRunner
    from rnb_tpu.stage import PaddedBatch
    from rnb_tpu.telemetry import TimeCard

    tiny = dict(num_classes=8, layer_sizes=(1, 1, 1, 1), max_rows=2,
                consecutive_frames=2, num_warmups=1)
    path = os.path.join(str(tmp_path), "tiny.msgpack")
    ckpt.save_checkpoint(path, ckpt.init_variables(
        seed=3, num_classes=8, layer_sizes=(1, 1, 1, 1)))

    import jax
    dev = jax.devices()[0]
    stage_a = R2P1DRunner(dev, start_index=1, end_index=4,
                          ckpt_path=path, **tiny)
    stage_b = R2P1DRunner(dev, start_index=5, end_index=5,
                          ckpt_path=path, **tiny)
    whole = R2P1DRunner(dev, start_index=1, end_index=5,
                        ckpt_path=path, **tiny)

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((2, 2, 112, 112, 3)),
                    jnp.bfloat16)
    pb = PaddedBatch(x, 2)
    (feat,), _, tc = stage_a((pb,), None, TimeCard(0))
    (split_logits,), _, tc = stage_b((feat,), None, tc)
    (whole_logits,), _, _ = whole((pb,), None, TimeCard(1))
    np.testing.assert_allclose(np.asarray(split_logits.data),
                               np.asarray(whole_logits.data),
                               rtol=0, atol=0.05)
    assert split_logits.valid == whole_logits.valid == 2
