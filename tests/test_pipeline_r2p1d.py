"""End-to-end run of the real R(2+1)D stages (reduced geometry).

One bounded integration test: Poisson client -> R2P1DLoader (synthetic
decode, 2-frame clips) -> R2P1DRunner (1-block layers, 8 classes) ->
logs, on two virtual devices. Uses the shared jit/param caches, so cost
is one compile for the whole test session.
"""

import json
import os


from rnb_tpu.benchmark import run_benchmark
from rnb_tpu.control import TerminationFlag


def test_r2p1d_whole_pipeline(tmp_path):
    cfg = {
        "video_path_iterator":
            "rnb_tpu.models.r2p1d.model.R2P1DVideoPathIterator",
        "pipeline": [
            {"model": "rnb_tpu.models.r2p1d.model.R2P1DLoader",
             "queue_groups": [{"devices": [0], "out_queues": [0]}],
             "num_shared_tensors": 8,
             "max_clips": 2, "consecutive_frames": 2,
             "num_clips_population": [1, 2], "weights": [3, 1],
             "num_warmups": 1},
            {"model": "rnb_tpu.models.r2p1d.model.R2P1DRunner",
             "queue_groups": [{"devices": [1], "in_queue": 0}],
             "start_index": 1, "end_index": 5,
             "num_classes": 8, "layer_sizes": [1, 1, 1, 1],
             "max_rows": 2, "consecutive_frames": 2, "num_warmups": 1},
        ],
    }
    path = os.path.join(str(tmp_path), "whole.json")
    with open(path, "w") as f:
        json.dump(cfg, f)
    res = run_benchmark(path, mean_interval_ms=0, num_videos=4,
                        queue_size=20, log_base=str(tmp_path / "logs"),
                        print_progress=False)
    assert res.termination_flag == TerminationFlag.TARGET_NUM_VIDEOS_REACHED
    reports = [f for f in os.listdir(res.log_dir) if "group" in f]
    with open(os.path.join(res.log_dir, reports[0])) as f:
        lines = f.read().strip().split("\n")
    header = lines[0].split()
    assert "inference0_finish" in header  # loader stage timed
    assert "inference1_finish" in header  # net stage timed
    assert len(lines) - 1 >= 4
