"""Direct coverage for pieces only exercised transitively elsewhere:
the fused no-pipelining baseline stage (R2P1DSingleStep), the Poisson
client through the real runtime, and the argparse validators."""

import json
import os

import numpy as np
import pytest

from rnb_tpu.arg_utils import nonnegative_int, positive_int
from rnb_tpu.decode import write_y4m
from rnb_tpu.telemetry import TimeCard


def test_arg_validators():
    assert positive_int("3") == 3
    assert nonnegative_int("0") == 0
    import argparse
    for fn, bad in ((positive_int, "0"), (positive_int, "-2"),
                    (nonnegative_int, "-1")):
        with pytest.raises(argparse.ArgumentTypeError):
            fn(bad)
    # non-numeric input raises ValueError, which argparse also treats
    # as an invalid-value signal (reference arg_utils.py behavior)
    with pytest.raises(ValueError):
        positive_int("x")


@pytest.mark.parametrize("pixel_path", ["rgb", "yuv420"])
def test_single_step_end_to_end(tmp_path, pixel_path):
    """The fused decode+net baseline: one call, one class id out, no
    tensor outputs — in both pixel paths."""
    import jax
    from rnb_tpu.models.r2p1d import checkpoint as ckpt
    from rnb_tpu.models.r2p1d.model import R2P1DSingleStep

    frames = np.random.default_rng(0).integers(
        0, 256, (30, 64, 64, 3), dtype=np.uint8)
    path = os.path.join(str(tmp_path), "v.y4m")
    write_y4m(path, frames, colorspace="420")
    ckpt_path = os.path.join(str(tmp_path), "tiny.msgpack")
    ckpt.save_checkpoint(ckpt_path, ckpt.init_variables(
        seed=2, num_classes=8, layer_sizes=(1, 1, 1, 1)))

    step = R2P1DSingleStep(jax.devices()[0], num_classes=8,
                           layer_sizes=(1, 1, 1, 1), max_clips=2,
                           consecutive_frames=2, num_warmups=0,
                           ckpt_path=ckpt_path,
                           num_clips_population=[2], weights=[1],
                           pixel_path=pixel_path)
    assert step.output_shape() is None
    tensors, pred, tc = step(None, path, TimeCard(0))
    assert tensors is None
    assert 0 <= int(pred) < 8
    # deterministic: same video, same prediction
    _, pred2, _ = step(None, path, TimeCard(1))
    assert int(pred2) == int(pred)


def test_poisson_client_pipeline(tmp_path):
    """Poisson arrivals through the real runtime: the client is
    unbounded (reference semantics) and stops when the final stage
    reaches the target."""
    from rnb_tpu.benchmark import run_benchmark
    from rnb_tpu.control import TerminationFlag

    cfg = {
        "video_path_iterator":
            "tests.pipeline_helpers.CountingPathIterator",
        "pipeline": [
            {"model": "tests.pipeline_helpers.TinyLoader",
             "queue_groups": [{"devices": [-1], "out_queues": [0]}]},
            {"model": "tests.pipeline_helpers.TinySink",
             "queue_groups": [{"devices": [-1], "in_queue": 0}]},
        ],
    }
    cfg_path = os.path.join(str(tmp_path), "poisson.json")
    with open(cfg_path, "w") as f:
        json.dump(cfg, f)
    res = run_benchmark(cfg_path, mean_interval_ms=1, num_videos=12,
                        log_base=os.path.join(str(tmp_path), "logs"),
                        print_progress=False, seed=3)
    assert res.termination_flag == \
        TerminationFlag.TARGET_NUM_VIDEOS_REACHED
    assert res.p50_latency_ms is not None
