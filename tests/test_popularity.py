"""Zipf popularity sampling (rnb_tpu.video_path_provider) and the
``popularity`` config key: seeded determinism, the s=0 uniform
degenerate case, universe clamping, and client wiring."""

import itertools
import queue
import threading
from collections import Counter

import numpy as np
import pytest

from rnb_tpu.config import ConfigError, parse_config
from rnb_tpu.video_path_provider import (DEFAULT_UNIVERSE,
                                         VideoPathIterator,
                                         ZipfPathIterator,
                                         zipf_probabilities)


class _TenVideos(VideoPathIterator):
    def __init__(self, n=10):
        super().__init__()
        self._videos = ["video-%02d" % i for i in range(n)]

    def dataset(self):
        return list(self._videos)

    def __iter__(self):
        return itertools.cycle(self._videos)


def _draw(it, n):
    return list(itertools.islice(iter(it), n))


def test_same_seed_identical_request_sequence():
    a = _draw(ZipfPathIterator(_TenVideos(), s=1.2, seed=42), 200)
    b = _draw(ZipfPathIterator(_TenVideos(), s=1.2, seed=42), 200)
    assert a == b
    c = _draw(ZipfPathIterator(_TenVideos(), s=1.2, seed=43), 200)
    assert a != c  # a different seed reorders the stream


def test_s_zero_degenerates_to_uniform():
    probs = zipf_probabilities(8, 0.0)
    np.testing.assert_allclose(probs, np.full(8, 1.0 / 8))
    # and the drawn stream covers the universe ~evenly
    counts = Counter(_draw(ZipfPathIterator(_TenVideos(), s=0.0,
                                            seed=1), 5000))
    assert len(counts) == 10
    assert max(counts.values()) < 2 * min(counts.values())


def test_positive_s_skews_toward_head_ranks():
    counts = Counter(_draw(ZipfPathIterator(_TenVideos(), s=1.5,
                                            seed=7), 2000))
    assert counts["video-00"] > counts.get("video-09", 0) * 5
    # rank assignment is the dataset order
    probs = zipf_probabilities(10, 1.5)
    assert probs[0] == max(probs) and probs[-1] == min(probs)


def test_universe_clamps_to_dataset_size():
    z = ZipfPathIterator(_TenVideos(), s=1.0, universe=999, seed=0)
    assert len(z.dataset()) == 10
    z = ZipfPathIterator(_TenVideos(), s=1.0, universe=3, seed=0)
    assert z.dataset() == ["video-00", "video-01", "video-02"]
    assert set(_draw(z, 300)) <= set(z.dataset())


def test_fallback_universe_from_cycling_iterator():
    # a base iterator without dataset(): the wrapper materializes the
    # first distinct items from the endless cycle
    z = ZipfPathIterator(itertools.cycle(["a", "b", "c"]), s=1.0,
                         universe=2, seed=0)
    assert z.dataset() == ["a", "b"]
    z = ZipfPathIterator(itertools.cycle(["a", "b", "c"]), s=1.0, seed=0)
    assert z.dataset() == ["a", "b", "c"]  # cycle detected < DEFAULT
    assert len(z.dataset()) <= DEFAULT_UNIVERSE


def test_invalid_parameters_rejected():
    with pytest.raises(ValueError):
        zipf_probabilities(0, 1.0)
    with pytest.raises(ValueError):
        zipf_probabilities(5, -0.5)
    with pytest.raises(ValueError):
        ZipfPathIterator(_TenVideos(0), s=1.0)  # empty universe


# -- config schema ----------------------------------------------------

def _cfg(popularity):
    return {
        "video_path_iterator": "tests.test_popularity._TenVideos",
        "popularity": popularity,
        "pipeline": [
            {"model": "tests.pipeline_helpers.TinyLoader",
             "queue_groups": [{"devices": [0]}]},
        ],
    }


def test_config_accepts_and_carries_popularity():
    cfg = parse_config(_cfg({"dist": "zipf", "s": 1.1, "universe": 8}))
    assert cfg.popularity == {"dist": "zipf", "s": 1.1, "universe": 8}
    assert parse_config(_cfg({"s": 0})).popularity == {"s": 0}
    # absent key stays None (no popularity wrapping)
    base = _cfg({})
    del base["popularity"]
    assert parse_config(base).popularity is None


def test_config_rejects_malformed_popularity():
    for bad in ("zipf",                     # not an object
                {"dist": "pareto"},         # unsupported distribution
                {"s": -1},                  # negative skew
                {"s": True},                # boolean masquerading
                {"universe": 0},            # non-positive universe
                {"universe": 2.5},          # non-integer universe
                {"typo": 1}):               # unknown key
        with pytest.raises(ConfigError):
            parse_config(_cfg(bad))


# -- client wiring ----------------------------------------------------

def test_client_wraps_iterator_with_popularity():
    from rnb_tpu.client import bulk_client
    from rnb_tpu.control import TerminationState

    def run(popularity, seed):
        q = queue.Queue(maxsize=1000)
        termination = TerminationState()
        sta = threading.Barrier(1)
        fin = threading.Barrier(1)
        bulk_client("tests.test_popularity._TenVideos", q, 50,
                    termination, sta, fin, seed=seed, num_markers=1,
                    popularity=popularity)
        paths = []
        while True:
            item = q.get_nowait()
            if item is None:
                break
            paths.append(item[1])
        return paths

    pop = {"dist": "zipf", "s": 1.4, "universe": 4}
    a = run(pop, seed=9)
    b = run(pop, seed=9)
    assert a == b                      # seeded: identical stream
    assert len(a) == 50
    assert set(a) <= {"video-%02d" % i for i in range(4)}  # universe
    counts = Counter(a)
    assert counts["video-00"] == max(counts.values())  # head-heavy
    plain = run(None, seed=9)
    assert plain[:10] == ["video-%02d" % i for i in range(10)]  # cycle
