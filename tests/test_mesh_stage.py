"""R2P1DMeshRunner: clip-sharded stage over a sub-mesh.

Checks (a) prediction parity between the mesh stage and a plain
single-device forward over the same clips, and (b) the full pipeline
topology loader(raw uint8) -> mesh stage with on-device psum
aggregation, end to end.
"""

import json
import os

import numpy as np
import pytest

from rnb_tpu.benchmark import run_benchmark
from rnb_tpu.control import TerminationFlag

TINY = dict(max_clips=2, consecutive_frames=2, num_classes=8,
            layer_sizes=[1, 1, 1, 1], num_warmups=1)


def _mesh_config(tmp_path, mesh_devices, pixel_path="rgb"):
    cfg = {
        "video_path_iterator":
            "rnb_tpu.models.r2p1d.model.R2P1DVideoPathIterator",
        "pipeline": [
            {"model": "rnb_tpu.models.r2p1d.model.R2P1DLoader",
             "queue_groups": [{"devices": [0], "out_queues": [0]}],
             "num_shared_tensors": 8,
             "raw_output": True,
             "pixel_path": pixel_path,
             "max_clips": TINY["max_clips"],
             "consecutive_frames": TINY["consecutive_frames"],
             "num_clips_population": [1, 2],
             "weights": [3, 1],
             "num_warmups": 1},
            {"model": "rnb_tpu.models.r2p1d.model.R2P1DMeshRunner",
             "queue_groups": [{"devices": [mesh_devices[0]],
                               "in_queue": 0}],
             "mesh_devices": mesh_devices,
             "pixel_path": pixel_path,
             **TINY},
        ],
    }
    path = tmp_path / "mesh.json"
    path.write_text(json.dumps(cfg))
    return str(path)


def test_mesh_stage_matches_single_device():
    import jax
    from rnb_tpu.models.r2p1d.model import R2P1DMeshRunner
    from rnb_tpu.models.r2p1d import checkpoint as ckpt
    from rnb_tpu.models.r2p1d.network import (R2Plus1DClassifier,
                                              normalize_u8)
    from rnb_tpu.stage import PaddedBatch
    from rnb_tpu.telemetry import TimeCard

    stage = R2P1DMeshRunner(device=jax.devices()[0],
                            mesh_devices=[0, 1], **TINY)
    rng = np.random.default_rng(0)
    clips = rng.integers(
        0, 256, (TINY["max_clips"], TINY["consecutive_frames"], 112, 112,
                 3), dtype=np.uint8)
    for valid in (1, 2):
        pb = PaddedBatch(jax.numpy.asarray(clips), valid)
        _, pred, _ = stage((pb,), None, TimeCard(0))

        model = R2Plus1DClassifier(num_classes=TINY["num_classes"],
                                   layer_sizes=tuple(TINY["layer_sizes"]))
        variables = ckpt.load_or_init(
            1, 5, TINY["num_classes"], tuple(TINY["layer_sizes"]))
        logits = model.apply(variables, normalize_u8(clips[:valid]),
                             train=False)
        want = int(np.asarray(logits, np.float32).sum(axis=0).argmax())
        assert pred == want, "valid=%d" % valid


def test_mesh_pipeline_end_to_end(tmp_path):
    cfg = _mesh_config(tmp_path, mesh_devices=[1, 2])
    res = run_benchmark(cfg, mean_interval_ms=0, num_videos=6,
                        log_base=str(tmp_path / "logs"),
                        print_progress=False, seed=0)
    assert res.termination_flag == TerminationFlag.TARGET_NUM_VIDEOS_REACHED
    assert res.throughput_vps > 0
    reports = [f for f in os.listdir(res.log_dir) if "group" in f]
    assert len(reports) == 1


def test_mesh_pipeline_yuv_pixel_path(tmp_path):
    """loader(raw packed 4:2:0) -> mesh stage whose sharded program
    runs the fused yuv ingest — the pixel path composes with dp x sp
    sharding end to end."""
    cfg = _mesh_config(tmp_path, mesh_devices=[1, 2],
                       pixel_path="yuv420")
    res = run_benchmark(cfg, mean_interval_ms=0, num_videos=6,
                        log_base=str(tmp_path / "logs"),
                        print_progress=False, seed=0)
    assert res.termination_flag == TerminationFlag.TARGET_NUM_VIDEOS_REACHED
    assert res.throughput_vps > 0


def test_mesh_stage_rejects_pixel_path_mismatch():
    """A loader/mesh pixel_path disagreement must fail with a clear
    error naming pixel_path, not a shape error inside shard_map."""
    import jax
    from rnb_tpu.models.r2p1d.model import R2P1DMeshRunner
    from rnb_tpu.stage import PaddedBatch
    from rnb_tpu.telemetry import TimeCard

    stage = R2P1DMeshRunner(device=jax.devices()[0],
                            mesh_devices=[0, 1], pixel_path="yuv420",
                            **TINY)
    rgb = np.zeros((TINY["max_clips"], TINY["consecutive_frames"],
                    112, 112, 3), np.uint8)
    with pytest.raises(ValueError, match="pixel_path"):
        stage((PaddedBatch(rgb, 1),), None, TimeCard(0))


def test_mesh_stage_pads_indivisible_clip_axis():
    """sp=3 does not divide max_clips=2: the step pads the clip axis to
    3 inside the compiled program (masked rows), so every mesh core is
    used and predictions still match a plain single-device forward —
    this is what lets an 8-core mesh serve the 15-clip flagship."""
    import jax
    from rnb_tpu.models.r2p1d.model import R2P1DMeshRunner
    from rnb_tpu.models.r2p1d import checkpoint as ckpt
    from rnb_tpu.models.r2p1d.network import (R2Plus1DClassifier,
                                              normalize_u8)
    from rnb_tpu.stage import PaddedBatch
    from rnb_tpu.telemetry import TimeCard

    stage = R2P1DMeshRunner(device=jax.devices()[0],
                            mesh_devices=[0, 1, 2], **TINY)
    assert stage._si.padded_clips == 3
    rng = np.random.default_rng(7)
    clips = rng.integers(
        0, 256, (TINY["max_clips"], TINY["consecutive_frames"], 112, 112,
                 3), dtype=np.uint8)
    model = R2Plus1DClassifier(num_classes=TINY["num_classes"],
                               layer_sizes=tuple(TINY["layer_sizes"]))
    variables = ckpt.load_or_init(
        1, 5, TINY["num_classes"], tuple(TINY["layer_sizes"]))
    for valid in (1, 2):
        pb = PaddedBatch(jax.numpy.asarray(clips), valid)
        _, pred, _ = stage((pb,), None, TimeCard(0))
        logits = model.apply(variables, normalize_u8(clips[:valid]),
                             train=False)
        want = int(np.asarray(logits, np.float32).sum(axis=0).argmax())
        assert pred == want, "valid=%d" % valid


def test_mesh_pipeline_dp_batched(tmp_path):
    """dp=2 x sp=2: two queued videos fuse into one sharded dispatch;
    async device preds; flush handles an odd video count."""
    cfg = {
        "video_path_iterator":
            "rnb_tpu.models.r2p1d.model.R2P1DVideoPathIterator",
        "pipeline": [
            {"model": "rnb_tpu.models.r2p1d.model.R2P1DLoader",
             "queue_groups": [{"devices": [0], "out_queues": [0]}],
             "num_shared_tensors": 8,
             "raw_output": True,
             "max_clips": TINY["max_clips"],
             "consecutive_frames": TINY["consecutive_frames"],
             "num_clips_population": [1, 2],
             "weights": [3, 1],
             "num_warmups": 1},
            {"model": "rnb_tpu.models.r2p1d.model.R2P1DMeshRunner",
             "queue_groups": [{"devices": [1], "in_queue": 0}],
             "mesh_devices": [0, 1, 2, 3],
             "dp": 2,
             **TINY},
        ],
    }
    path = tmp_path / "mesh-dp.json"
    path.write_text(json.dumps(cfg))
    # 7 % dp != 0: the last video completes only through flush()
    res = run_benchmark(str(path), mean_interval_ms=0, num_videos=7,
                        log_base=str(tmp_path / "logs"),
                        print_progress=False)
    assert res.termination_flag == TerminationFlag.TARGET_NUM_VIDEOS_REACHED
    reports = [f for f in os.listdir(res.log_dir) if "group" in f]
    with open(os.path.join(res.log_dir, reports[0])) as f:
        lines = f.read().strip().split("\n")
    assert len(lines) - 1 >= 7
