"""Device-resident decoded-clip cache + in-flight coalescing
(rnb_tpu.cache): lookup/eviction accounting, hit/miss bit-identical
serving through both loaders, coalesced TimeCard fan-out, and the
fault interaction (a failed decode is never inserted).

The fast tests here are the tier-1 unit suite for the subsystem; the
end-to-end Zipf+cache pipeline run is ``slow``-marked.
"""

import json
import os
import threading

import numpy as np
import pytest

from rnb_tpu.cache import (ClipCache, InflightTable, aggregate_snapshots,
                           content_key)
from rnb_tpu.telemetry import TimeCard, TimeCardList


def _entry(mb: float, fill: int = 0) -> np.ndarray:
    return np.full((int(mb * (1 << 20)),), fill, dtype=np.uint8)


# -- ClipCache unit logic (no jax needed: any .nbytes array works) ----

def test_lookup_miss_then_hit_counts():
    cache = ClipCache(1)
    key = ("v", (-1, -1), "cfg")
    assert cache.lookup(key) is None
    assert cache.insert_device(key, _entry(0.25), 3)
    entry = cache.lookup(key)
    assert entry is not None and entry.valid == 3
    snap = cache.snapshot()
    assert (snap["hits"], snap["misses"], snap["inserts"]) == (1, 1, 1)
    assert snap["bytes_resident"] == entry.nbytes
    assert snap["entries"] == 1


def test_lru_eviction_stays_within_budget():
    cache = ClipCache(1)  # 1 MiB budget
    for i in range(5):
        assert cache.insert_device(("v%d" % i, (-1, -1), "c"),
                                   _entry(0.3), 1)
    snap = cache.snapshot()
    assert snap["bytes_resident"] <= cache.capacity_bytes
    assert snap["entries"] == 3
    assert snap["evictions"] == 2
    # LRU order: the two oldest are gone, the three newest resident
    assert cache.lookup(("v0", (-1, -1), "c")) is None
    assert cache.lookup(("v4", (-1, -1), "c")) is not None


def test_lookup_refreshes_recency():
    cache = ClipCache(1)
    for i in range(3):
        cache.insert_device(("v%d" % i, (-1, -1), "c"), _entry(0.3), 1)
    assert cache.lookup(("v0", (-1, -1), "c")) is not None  # touch LRU
    cache.insert_device(("v3", (-1, -1), "c"), _entry(0.3), 1)
    # v1 (now the least recent) was evicted, the touched v0 survived
    assert cache.lookup(("v0", (-1, -1), "c")) is not None
    assert cache.lookup(("v1", (-1, -1), "c")) is None


def test_oversize_entry_skipped_not_inserted():
    cache = ClipCache(0.5)
    assert not cache.insert_device(("big", (-1, -1), "c"), _entry(1.0), 1)
    snap = cache.snapshot()
    assert snap["oversize"] == 1
    assert snap["entries"] == 0 and snap["bytes_resident"] == 0


def test_duplicate_insert_is_noop():
    cache = ClipCache(1)
    key = ("v", (-1, -1), "c")
    assert cache.insert_device(key, _entry(0.1, fill=1), 2)
    assert not cache.insert_device(key, _entry(0.1, fill=9), 5)
    entry = cache.lookup(key)
    assert entry.valid == 2 and entry.batch[0] == 1  # first writer wins
    assert cache.snapshot()["inserts"] == 1


def test_zero_budget_rejected():
    with pytest.raises(ValueError):
        ClipCache(0)


def test_content_key_tracks_file_identity(tmp_path):
    path = str(tmp_path / "v.y4m")
    with open(path, "wb") as f:
        f.write(b"AAAA")
    k1 = content_key(path, "cfg")
    assert content_key(path, "cfg") == k1
    with open(path, "wb") as f:
        f.write(b"BBBBBBBB")  # different size (and mtime)
    assert content_key(path, "cfg") != k1
    # config fingerprint is part of the key
    assert content_key(path, "other-cfg") != content_key(path, "cfg")
    # non-file ids get the constant signature (content is procedural)
    assert content_key("synth://a", "cfg") == content_key("synth://a",
                                                          "cfg")


def test_aggregate_snapshots_sums():
    a = ClipCache(1)
    a.insert_device(("x", (-1, -1), "c"), _entry(0.1), 1)
    a.lookup(("x", (-1, -1), "c"))
    b = ClipCache(1)
    b.lookup(("y", (-1, -1), "c"))
    b.note_coalesced(2)
    total = aggregate_snapshots([a.snapshot(), b.snapshot()])
    assert total["hits"] == 1 and total["misses"] == 1
    assert total["inserts"] == 1 and total["coalesced"] == 2


def test_inflight_table_basic():
    table = InflightTable()
    table.put("k", "rec")
    assert table.get("k") == "rec"
    table.pop("k")
    table.pop("k")      # idempotent
    table.pop(None)     # no-op
    assert table.get("k") is None and len(table) == 0


# -- loader integration (8-virtual-device CPU backend, conftest) ------

def _plain_loader(**kw):
    import jax
    from rnb_tpu.models.r2p1d.model import R2P1DLoader
    kw.setdefault("num_warmups", 0)
    kw.setdefault("num_clips_population", [2])
    kw.setdefault("weights", [1])
    kw.setdefault("consecutive_frames", 2)
    return R2P1DLoader(jax.devices()[0], **kw)


def _fusing_loader(**kw):
    import jax
    from rnb_tpu.models.r2p1d.model import R2P1DFusingLoader
    kw.setdefault("num_warmups", 0)
    kw.setdefault("num_clips_population", [1])
    kw.setdefault("weights", [1])
    kw.setdefault("consecutive_frames", 2)
    kw.setdefault("max_hold_ms", 10000.0)
    kw.setdefault("depth", 50)
    return R2P1DFusingLoader(jax.devices()[0], **kw)


def test_plain_loader_hit_is_bit_identical_and_stamped():
    loader = _plain_loader(cache_mb=16)
    video = "synth://kinetics/video-0042"
    tc_miss, tc_hit = TimeCard(0), TimeCard(1)
    (pb_miss,), _, _ = loader(None, video, tc_miss)
    (pb_hit,), _, _ = loader(None, video, tc_hit)
    assert tc_miss.cache_hit is False and tc_hit.cache_hit is True
    assert pb_hit.valid == pb_miss.valid == tc_hit.num_clips
    np.testing.assert_array_equal(np.asarray(pb_miss.data),
                                  np.asarray(pb_hit.data))
    snap = loader.cache.snapshot()
    assert snap["hits"] == 1 and snap["misses"] == 1
    assert snap["inserts"] == 1


def test_hit_and_miss_logits_bit_identical_through_network():
    """The golden-logit acceptance check at stage level: the cached
    device batch feeds the identical jitted preprocess+network path a
    miss feeds, so per-request logits match bit-for-bit."""
    import jax
    from rnb_tpu.models.r2p1d.model import R2P1DRunner
    loader = _plain_loader(cache_mb=16)
    net = R2P1DRunner(jax.devices()[0], start_index=1, end_index=5,
                      num_classes=8, layer_sizes=[1, 1, 1, 1],
                      max_rows=2, consecutive_frames=2, num_warmups=0)
    video = "synth://kinetics/video-0007"
    (pb_miss,), _, _ = loader(None, video, TimeCard(0))
    (logits_miss,), _, _ = net((pb_miss,), None, TimeCard(0))
    tc = TimeCard(1)
    (pb_hit,), _, _ = loader(None, video, tc)
    assert tc.cache_hit is True
    (logits_hit,), _, _ = net((pb_hit,), None, tc)
    np.testing.assert_array_equal(np.asarray(logits_miss.data),
                                  np.asarray(logits_hit.data))


def test_plain_loader_eviction_under_forced_overflow():
    # max_clips=2 so the padded bucket is 2x2x112x112x3 = ~147 KiB;
    # a 0.2 MiB budget holds exactly one entry
    loader = _plain_loader(cache_mb=0.2, max_clips=2)
    for i in range(4):
        loader(None, "synth://kinetics/video-%04d" % i, TimeCard(i))
    snap = loader.cache.snapshot()
    assert snap["bytes_resident"] <= loader.cache.capacity_bytes
    assert snap["evictions"] == 3 and snap["entries"] == 1


def test_prefetch_submit_coalesces_inflight_duplicates():
    loader = _plain_loader(cache_mb=16, prefetch=4)
    video = "synth://kinetics/video-0005"
    tc_lead, tc_follow = TimeCard(0), TimeCard(1)
    lead = loader.submit(video, tc_lead)
    follow = loader.submit(video, tc_follow)
    assert follow.leader is lead
    assert tc_follow.cache_coalesced is True
    assert tc_follow.num_clips == tc_lead.num_clips
    out_lead = loader.complete(lead, video, tc_lead)
    out_follow = loader.complete(follow, video, tc_follow)
    np.testing.assert_array_equal(np.asarray(out_lead[0][0].data),
                                  np.asarray(out_follow[0][0].data))
    snap = loader.cache.snapshot()
    assert snap["coalesced"] == 1
    assert snap["inserts"] == 1  # only the leader inserted
    # the in-flight window is drained and a fresh request now hits
    tc3 = TimeCard(2)
    h3 = loader.submit(video, tc3)
    assert h3.cached is not None and tc3.cache_hit is True


def test_fusing_loader_hit_emits_immediately_bit_identical():
    loader = _fusing_loader(cache_mb=16, fuse=3)
    video = "synth://kinetics/video-0009"
    emitted = []
    out = loader(None, video, TimeCard(0))
    if out[2] is not None:
        emitted.append(out)
    while True:
        out = loader.flush()
        if out is None:
            break
        emitted.append(out)
    assert len(emitted) == 1
    (pb_miss,), _, cards_miss = emitted[0]
    assert len(cards_miss) == 1
    # second request for the same video: an immediate standalone hit
    tc = TimeCard(1)
    tensors, _, cards = loader(None, video, tc)
    assert cards is not None and isinstance(cards, TimeCardList)
    assert tc.cache_hit is True
    (pb_hit,) = tensors
    assert pb_hit.valid == pb_miss.valid
    np.testing.assert_array_equal(np.asarray(pb_miss.data),
                                  np.asarray(pb_hit.data))
    assert loader.flush() is None  # the hit left no pending state


def test_fusing_loader_coalesces_concurrent_same_key_requests():
    """Two concurrent requests for one video share one decode and ride
    one fused emission: every card is stamped via the TimeCardList
    fan-out (the machinery a follower reuses instead of re-decoding)."""
    loader = _fusing_loader(cache_mb=16, fuse=10)
    gate = threading.Event()
    real_decode = loader._decode_sync

    def gated_decode(decoder, video, starts):
        gate.wait(10.0)
        return real_decode(decoder, video, starts)

    loader._decode_sync = gated_decode
    video = "synth://kinetics/video-0011"
    tc_lead, tc_follow = TimeCard(0), TimeCard(1)
    out = loader(None, video, tc_lead)
    assert out[2] is None          # decode gated: nothing emitted
    out = loader(None, video, tc_follow)
    assert out[2] is None          # coalesced, no second decode
    assert tc_follow.cache_coalesced is True
    assert loader.cache.snapshot()["coalesced"] == 1
    assert len(loader._inflight) == 1  # ONE decode for two requests
    gate.set()
    out = loader.flush()
    assert out is not None
    (pb,), _, cards = out
    assert isinstance(cards, TimeCardList) and len(cards) == 2
    assert {tc.id for tc in cards.time_cards} == {0, 1}
    assert pb.valid == tc_lead.num_clips  # rows appear ONCE
    assert loader.flush() is None
    # the shared decode was inserted; a third request hits
    tc3 = TimeCard(2)
    tensors, _, cards3 = loader(None, video, tc3)
    assert cards3 is not None and tc3.cache_hit is True


def test_failed_decode_never_inserted_and_fails_followers():
    """PR-1 fault composition: a decode failing with a classified
    error inside fused assembly parks every rider (leader + coalesced
    followers) on the take_failed() queue and never touches the
    cache."""
    from rnb_tpu.faults import CorruptVideoError
    loader = _fusing_loader(cache_mb=16, fuse=10)
    calls = {"n": 0}
    gate = threading.Event()

    def broken_decode(decoder, video, starts):
        calls["n"] += 1
        gate.wait(10.0)  # hold the decode in flight so a follower can
        raise CorruptVideoError("injected corrupt payload")  # coalesce

    loader._decode_sync = broken_decode
    video = "synth://kinetics/video-0013"
    tc_lead, tc_follow = TimeCard(0), TimeCard(1)
    loader(None, video, tc_lead)
    loader(None, video, tc_follow)
    assert tc_follow.cache_coalesced is True
    gate.set()
    assert loader.flush() is None  # the whole batch failed
    failed = loader.take_failed()
    assert sorted(tc.id for tc, _ in failed) == [0, 1]
    assert all(reason == "corrupt-video" for _, reason in failed)
    snap = loader.cache.snapshot()
    assert snap["inserts"] == 0 and snap["entries"] == 0
    # the coalescing window is closed: the next request re-decodes
    # (fresh miss) rather than parking on a dead record
    before = calls["n"]
    loader(None, video, TimeCard(2))
    loader.flush()
    loader.take_failed()
    assert calls["n"] > before
    assert snap["hits"] == 0


def test_cache_composes_with_row_buckets():
    loader = _fusing_loader(cache_mb=16, fuse=3, max_clips=15,
                            row_buckets=[6, 15],
                            num_clips_population=[2], weights=[1])
    video = "synth://kinetics/video-0021"
    emitted = []
    # a fast decode can emit from __call__ itself (the internal poll),
    # so the return value must be captured like any flush() emission
    out = loader(None, video, TimeCard(0))
    if out[2] is not None:
        emitted.append(out)
    while True:
        out = loader.flush()
        if out is None:
            break
        emitted.append(out)
    # 2 valid rows pad to the 6-bucket on the miss...
    assert emitted[0][0][0].data.shape[0] == 6
    tensors, _, cards = loader(None, video, TimeCard(1))
    # ...and the hit serves the identical bucket shape
    assert tensors[0].data.shape[0] == 6
    np.testing.assert_array_equal(np.asarray(emitted[0][0][0].data),
                                  np.asarray(tensors[0].data))


# -- end-to-end: Zipf workload through the full pipeline --------------

@pytest.mark.slow
def test_zipf_cache_pipeline_end_to_end(tmp_path, monkeypatch):
    """Acceptance scenario: a seeded Zipf workload over a real y4m
    dataset with the cache enabled completes on the CPU backend with
    hit-rate > 0, stamps every coalesced/hit request's completed
    TimeCard, and reports consistent cache stats in BenchmarkResult,
    log-meta.txt and `parse_utils --check`."""
    import sys

    from rnb_tpu.benchmark import run_benchmark
    from rnb_tpu.control import TerminationFlag
    from rnb_tpu.decode import write_y4m

    data_root = str(tmp_path / "data")
    label = os.path.join(data_root, "label0")
    os.makedirs(label)
    rng = np.random.default_rng(3)
    for i in range(6):
        write_y4m(os.path.join(label, "v%02d.y4m" % i),
                  rng.integers(0, 256, (6, 16, 16, 3), dtype=np.uint8),
                  colorspace="420")
    monkeypatch.setenv("RNB_TPU_DATA_ROOT", data_root)

    cfg = {
        "video_path_iterator":
            "rnb_tpu.models.r2p1d.model.R2P1DVideoPathIterator",
        "popularity": {"dist": "zipf", "s": 1.3, "universe": 4},
        "pipeline": [
            {"model": "rnb_tpu.models.r2p1d.model.R2P1DFusingLoader",
             "queue_groups": [{"devices": [0], "out_queues": [0]}],
             "num_shared_tensors": 30, "fuse": 3, "depth": 2,
             "max_clips": 2, "consecutive_frames": 2,
             "num_clips_population": [1, 2],
             "weights": [1, 1], "num_warmups": 0, "cache_mb": 32},
            {"model": "rnb_tpu.models.r2p1d.model.R2P1DRunner",
             "queue_groups": [{"devices": [1], "in_queue": 0}],
             "start_index": 1, "end_index": 5, "num_classes": 8,
             "layer_sizes": [1, 1, 1, 1], "max_rows": 2,
             "consecutive_frames": 2, "num_warmups": 1},
        ],
    }
    cfg_path = os.path.join(str(tmp_path), "pipeline.json")
    with open(cfg_path, "w") as f:
        json.dump(cfg, f)

    res = run_benchmark(cfg_path, mean_interval_ms=0, num_videos=60,
                        queue_size=200, log_base=str(tmp_path / "logs"),
                        print_progress=False, seed=11)
    assert res.termination_flag == \
        TerminationFlag.TARGET_NUM_VIDEOS_REACHED
    assert res.num_completed == 60
    # 60 requests over a 4-video Zipf universe: the cache must serve
    # most of them
    assert res.cache_hits > 0
    assert res.cache_misses >= 4
    # every request is exactly one lookup; coalesced followers are the
    # subset of misses that shared an in-flight decode
    assert res.cache_hits + res.cache_misses == 60
    assert res.cache_coalesced <= res.cache_misses
    assert res.cache_inserts <= res.cache_misses
    assert res.cache_bytes_resident > 0

    # log-meta carries the same counters
    with open(os.path.join(res.log_dir, "log-meta.txt")) as f:
        meta_text = f.read()
    assert ("Cache: hits=%d misses=%d inserts=%d evictions=%d "
            "coalesced=%d" % (res.cache_hits, res.cache_misses,
                              res.cache_inserts, res.cache_evictions,
                              res.cache_coalesced)) in meta_text

    # every request — hits, misses and coalesced followers — received
    # a completed, cache-stamped TimeCard in the final table
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "scripts"))
    import parse_utils
    meta, df = parse_utils.get_data(res.log_dir)
    assert meta["cache_hits"] == res.cache_hits
    assert meta["cache_coalesced"] == res.cache_coalesced
    assert len(df) == 60
    report = [f for f in os.listdir(res.log_dir) if "group" in f][0]
    trailers = parse_utils.parse_table_trailers(
        os.path.join(res.log_dir, report))
    assert trailers["cache"]["num_tracked"] == 60
    assert trailers["cache"]["num_hits"] > 0

    # the consistency checker agrees end-to-end
    assert parse_utils.check_job(res.log_dir) == []
    assert parse_utils.main(["--check", res.log_dir]) == 0


@pytest.mark.slow
def test_zipf_same_seed_same_results(tmp_path, monkeypatch):
    """Determinism of the benchmark cell: same seed => identical
    request stream => identical cache accounting."""
    from rnb_tpu.benchmark import run_benchmark

    cfg = {
        "video_path_iterator":
            "rnb_tpu.models.r2p1d.model.R2P1DVideoPathIterator",
        "popularity": {"dist": "zipf", "s": 1.0, "universe": 8},
        "pipeline": [
            {"model": "rnb_tpu.models.r2p1d.model.R2P1DLoader",
             "queue_groups": [{"devices": [0], "out_queues": [0]}],
             "num_shared_tensors": 20, "max_clips": 2,
             "consecutive_frames": 2, "num_clips_population": [2],
             "weights": [1], "num_warmups": 0, "cache_mb": 32},
            {"model": "rnb_tpu.models.r2p1d.model.R2P1DRunner",
             "queue_groups": [{"devices": [1], "in_queue": 0}],
             "start_index": 1, "end_index": 5, "num_classes": 8,
             "layer_sizes": [1, 1, 1, 1], "max_rows": 2,
             "consecutive_frames": 2, "num_warmups": 1},
        ],
    }
    cfg_path = os.path.join(str(tmp_path), "pipeline.json")
    with open(cfg_path, "w") as f:
        json.dump(cfg, f)
    results = []
    for run in range(2):
        res = run_benchmark(cfg_path, mean_interval_ms=0, num_videos=30,
                            queue_size=100,
                            log_base=str(tmp_path / ("logs%d" % run)),
                            print_progress=False, seed=5)
        results.append((res.cache_hits, res.cache_misses,
                        res.cache_inserts, res.num_completed))
    assert results[0] == results[1]
    assert results[0][0] > 0
