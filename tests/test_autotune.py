"""Load-adaptive batching controller (rnb_tpu.autotune).

Contract under test:

* decisions are a deterministic pure function of the observed stamp
  stream (a seeded workload replays to identical decisions);
* monotone in arrival rate — faster arrivals never shrink the target
  bucket;
* ``slo_ms`` binds — a held decision's predicted residual-fill wait
  plus predicted service never exceeds the budget;
* min/max hold clamps are respected;
* decisions are restricted to warmed buckets (an ``autotune.buckets``
  restriction naming an un-warmed bucket is rejected at build time);
* the accounting invariants ``parse_utils --check`` enforces hold on
  every path (decisions >= emissions, verdicts partition decisions);
* the slow-marked Poisson e2e A/B: autotune beats the static
  ``max_hold_ms`` baseline on mean and p99 latency at low rate.
"""

import json
import math
import os
import sys
import time

import numpy as np
import pytest

from rnb_tpu.autotune import (AUTOTUNE_DEFAULTS, AutotuneSettings,
                              BatchController, aggregate_snapshots)

SETTINGS = AutotuneSettings.from_config({"enabled": True, "slo_ms": 40.0})


def _controller(candidates=(3, 6, 15), max_rows=15, **over):
    raw = {"enabled": True, "slo_ms": 40.0}
    raw.update(over)
    return BatchController.for_stage(AutotuneSettings.from_config(raw),
                                     candidates, max_rows)


def _feed_constant(ctrl, ia_s, rows=1, n=50, service=None):
    """Constant-interval stream: the EWMAs converge to the constants,
    so predicted waits are exactly computable in the assertions."""
    for i in range(n):
        ctrl.observe_enqueue(i * ia_s)
    ctrl.observe_rows(rows)
    for bucket, s in (service or {}).items():
        ctrl.observe_service(bucket, s)


# -- settings / construction ------------------------------------------

def test_settings_from_config_defaults_and_disabled():
    assert AutotuneSettings.from_config(None) is None
    assert AutotuneSettings.from_config(
        {"enabled": False, "slo_ms": 10.0}) is None
    s = AutotuneSettings.from_config({"enabled": True})
    assert s.slo_ms == AUTOTUNE_DEFAULTS["slo_ms"]
    assert s.ewma_alpha == AUTOTUNE_DEFAULTS["ewma_alpha"]
    assert s.min_hold_ms == AUTOTUNE_DEFAULTS["min_hold_ms"]
    assert s.max_hold_ms == AUTOTUNE_DEFAULTS["max_hold_ms"]
    assert s.buckets is None
    s2 = AutotuneSettings.from_config({"buckets": [15, 6]})
    assert s2.buckets == (6, 15)
    # an omitted max_hold_ms tracks min_hold_ms (matching config-time
    # validation) — a flat 50.0 default would silently invert the
    # clamp pair and cap every hold below the configured minimum
    s3 = AutotuneSettings.from_config({"min_hold_ms": 80.0})
    assert s3.max_hold_ms == 80.0
    with pytest.raises(ValueError, match="max_hold_ms"):
        AutotuneSettings.from_config({"min_hold_ms": 80.0,
                                      "max_hold_ms": 20.0})


def test_for_stage_rejects_unwarmed_bucket_restriction():
    s = AutotuneSettings.from_config({"buckets": [5]})
    with pytest.raises(ValueError, match="never warms"):
        BatchController.for_stage(s, (6, 15), 15)
    # a warmed subset is accepted and becomes the candidate set
    s2 = AutotuneSettings.from_config({"buckets": [6]})
    ctrl = BatchController.for_stage(s2, (6, 15), 15)
    assert ctrl.candidates == (6,)
    with pytest.raises(ValueError):
        BatchController(SETTINGS, (), 15)


def test_decisions_restricted_to_warmed_candidates():
    ctrl = _controller()
    _feed_constant(ctrl, 0.002, service={6: 0.004, 15: 0.008})
    for rows in range(1, 16):
        dec = ctrl.decide(rows, rows, 0.0)
        assert dec.bucket in ctrl.candidates
        assert dec.target_rows in ctrl.candidates
    assert ctrl.bucket_for(2) == 3
    assert ctrl.bucket_for(7) == 15
    assert ctrl.bucket_for(99) == 15  # hard cap applies upstream


# -- the decision -----------------------------------------------------

def test_unknown_arrival_rate_dispatches_immediately():
    # no inter-arrival estimate yet: holding can never be justified
    dec = _controller().decide(1, 2, 0.0)
    assert dec.immediate and dec.hold_s == 0.0


def test_slow_arrivals_collapse_to_immediate_dispatch():
    ctrl = _controller()
    _feed_constant(ctrl, 1.0)  # 1 req/s against a 40 ms budget
    dec = ctrl.decide(1, 1, 0.0)
    assert dec.immediate and dec.hold_s == 0.0


def test_fast_arrivals_grow_to_the_largest_feasible_bucket():
    ctrl = _controller()
    _feed_constant(ctrl, 0.001, service={6: 0.004, 15: 0.008})
    dec = ctrl.decide(1, 1, 0.0)
    assert not dec.immediate
    assert dec.target_rows == 15


def test_decisions_deterministic_under_fixed_seed():
    def run(seed):
        rng = np.random.default_rng(seed)
        ctrl = _controller()
        decisions = []
        t = 0.0
        for _ in range(200):
            t += rng.exponential(0.004)
            ctrl.observe_enqueue(t)
            ctrl.observe_rows(int(rng.integers(1, 4)))
            if rng.random() < 0.2:
                ctrl.observe_service(int(rng.choice([3, 6, 15])),
                                     rng.exponential(0.003))
            decisions.append(ctrl.decide(
                int(rng.integers(1, 4)), int(rng.integers(1, 10)),
                rng.random() * 0.01))
        return decisions
    assert run(7) == run(7)
    assert run(7) != run(8)  # the stream, not the clock, drives it


def test_monotone_in_arrival_rate():
    # faster arrivals must never shrink the chosen target bucket
    targets = []
    for ia in (0.5, 0.05, 0.01, 0.004, 0.002, 0.0005):
        ctrl = _controller()
        _feed_constant(ctrl, ia, service={3: 0.002, 6: 0.003,
                                          15: 0.005})
        targets.append(ctrl.decide(1, 2, 0.0).target_rows)
    assert targets == sorted(targets), targets


def test_slo_binds_on_every_held_decision():
    # constant stream -> the EWMAs equal the constants, so the
    # predicted wait+service of the chosen target is exactly checkable
    for ia in (0.002, 0.005, 0.012, 0.03):
        for rows_ready in (1, 2, 5, 8):
            ctrl = _controller()
            _feed_constant(ctrl, ia, rows=1,
                           service={3: 0.004, 6: 0.01, 15: 0.02})
            wait0 = 0.003
            dec = ctrl.decide(rows_ready, rows_ready, wait0)
            if dec.immediate:
                continue
            assert dec.target_rows > rows_ready
            extra = math.ceil(dec.target_rows - rows_ready)
            predicted = (wait0 + extra * ia
                         + ctrl.service_for(dec.target_rows))
            assert predicted <= ctrl.slo_ms / 1000.0 + 1e-9, \
                (ia, rows_ready, dec, predicted)


def test_hold_clamps_respected():
    # service ~ budget => raw hold ~ 0, clamped up to min_hold_ms
    # (fill wait to 3 rows = 2 * 0.5 ms; 38.5 + 1 <= 40 is feasible
    # but the leftover hold 40 - 38.5 = 1.5 ms sits under the clamp)
    ctrl = _controller(min_hold_ms=2.0, max_hold_ms=8.0)
    _feed_constant(ctrl, 0.0005, service={15: 0.0385})
    dec = ctrl.decide(1, 1, 0.0)
    assert not dec.immediate
    assert dec.hold_s == pytest.approx(0.002)
    # cheap service => raw hold ~ budget, clamped down to max_hold_ms
    ctrl2 = _controller(min_hold_ms=2.0, max_hold_ms=8.0)
    _feed_constant(ctrl2, 0.001, service={15: 0.0001})
    dec2 = ctrl2.decide(1, 1, 0.0)
    assert not dec2.immediate
    assert dec2.hold_s == pytest.approx(0.008)
    # an expired hold turns the verdict immediate
    dec3 = ctrl2.decide(1, 1, 0.009)
    assert dec3.immediate


def test_observe_service_keys_by_actual_shipped_rows():
    # a narrowed candidate set must not round a smaller warmed
    # bucket's sample up into a larger candidate's EWMA — the stage's
    # static pad rule can legally emit below the candidate set
    ctrl = _controller(candidates=(15,), buckets=[15])
    ctrl.observe_service(6, 0.002)   # warmed-but-not-candidate bucket
    ctrl.observe_service(15, 0.020)
    assert ctrl.service_for(15) == pytest.approx(0.020)
    assert ctrl.service_for(6) == pytest.approx(0.002)


def test_service_for_falls_back_to_nearest_observed_bucket():
    ctrl = _controller()
    assert ctrl.service_for(6) == 0.0  # optimistic until observed
    ctrl.observe_service(15, 0.01)
    assert ctrl.service_for(6) == pytest.approx(0.01)  # larger first
    ctrl.observe_service(3, 0.002)
    assert ctrl.service_for(6) == pytest.approx(0.01)
    assert ctrl.service_for(2) == pytest.approx(0.002)


def test_out_of_order_enqueue_stamps_clamp_to_zero_gap():
    ctrl = _controller()
    ctrl.observe_enqueue(1.0)
    ctrl.observe_enqueue(0.5)  # fused upstream emission interleaving
    assert ctrl._ia_s == 0.0


# -- accounting invariants (the ones --check enforces) ----------------

def test_note_emission_backfills_missing_decision():
    ctrl = _controller()
    ctrl.note_emission(6)  # forced drain: no decide() preceded
    snap = ctrl.snapshot()
    assert snap["decisions"] == 1 and snap["immediate"] == 1
    assert snap["emissions"] == 1
    assert snap["bucket_counts"] == {"6": 1}


def test_peek_matches_decide_without_accounting():
    ctrl = _controller()
    _feed_constant(ctrl, 0.001, service={15: 0.001})
    before = ctrl.snapshot()
    peeked = ctrl.peek(1, 1, 0.0)
    assert ctrl.snapshot() == before, \
        "deadline queries must not charge decisions"
    assert ctrl.decide(1, 1, 0.0) == peeked
    assert ctrl.snapshot()["decisions"] == before["decisions"] + 1


def test_snapshot_invariants_over_a_random_stream():
    rng = np.random.default_rng(3)
    ctrl = _controller()
    t = 0.0
    for _ in range(300):
        t += rng.exponential(0.003)
        ctrl.observe_enqueue(t)
        ctrl.observe_rows(int(rng.integers(1, 4)))
        dec = ctrl.decide(1, int(rng.integers(1, 12)),
                          rng.random() * 0.05)
        if rng.random() < 0.5:
            ctrl.note_emission(dec.bucket)
    snap = ctrl.snapshot()
    assert snap["immediate"] + snap["held"] == snap["decisions"]
    assert snap["emissions"] <= snap["decisions"]
    assert sum(snap["bucket_counts"].values()) == snap["emissions"]
    if snap["held"]:
        assert snap["deadline_us_min"] <= snap["deadline_us_max"]
        assert (snap["held"] * snap["deadline_us_min"]
                <= snap["deadline_us_sum"]
                <= snap["held"] * snap["deadline_us_max"])


def test_aggregate_snapshots():
    a = _controller()
    _feed_constant(a, 0.001, service={15: 0.001})
    a.decide(1, 1, 0.0)
    a.note_emission(6)
    b = _controller()
    b.decide(1, 1, 0.0)  # immediate (no estimate): held stays 0
    b.note_emission(6)
    agg = aggregate_snapshots([a.snapshot(), b.snapshot()])
    assert agg["decisions"] == 2 and agg["emissions"] == 2
    assert agg["bucket_counts"] == {"6": 2}
    # the min ignores instances that never held
    assert agg["deadline_us_min"] == a.snapshot()["deadline_us_min"]


# -- config schema ----------------------------------------------------

def _cfg(autotune=None, step_extra=None):
    step = {"model": "rnb_tpu.models.r2p1d.model.R2P1DLoader",
            "queue_groups": [{"devices": [0]}]}
    step.update(step_extra or {})
    raw = {"video_path_iterator":
           "rnb_tpu.models.r2p1d.model.R2P1DVideoPathIterator",
           "pipeline": [step]}
    if autotune is not None:
        raw["autotune"] = autotune
    return raw


def test_config_accepts_and_defaults_autotune():
    from rnb_tpu.config import parse_config
    cfg = parse_config(_cfg({"enabled": True, "slo_ms": 30.0,
                             "buckets": [6, 15]}))
    assert cfg.autotune["slo_ms"] == 30.0
    assert cfg.steps[0].autotune is True
    cfg2 = parse_config(_cfg({"enabled": True},
                             step_extra={"autotune": False}))
    assert cfg2.steps[0].autotune is False
    assert parse_config(_cfg()).autotune is None


def test_config_rejects_bad_autotune():
    from rnb_tpu.config import ConfigError, parse_config
    bad = [{"slo_ms": 0}, {"slo_ms": -1.0}, {"ewma_alpha": 0},
           {"ewma_alpha": 1.5}, {"min_hold_ms": -0.1},
           {"min_hold_ms": 5.0, "max_hold_ms": 1.0},
           {"buckets": []}, {"buckets": [0]}, {"buckets": [3, 3]},
           {"buckets": [True]}, {"enabled": "yes"}, {"slo_typo": 1},
           "not-an-object"]
    for raw in bad:
        with pytest.raises(ConfigError):
            parse_config(_cfg(raw))
    with pytest.raises(ConfigError, match="'autotune' must be a "
                                          "boolean"):
        parse_config(_cfg({"enabled": True},
                          step_extra={"autotune": "no"}))


# -- Batcher integration ----------------------------------------------

def _batcher(batch=4, **kw):
    from rnb_tpu.batcher import Batcher
    from rnb_tpu.devices import DeviceSpec
    return Batcher(DeviceSpec(0), batch=batch, max_rows=8,
                   consecutive_frames=2, frame_hw=16, **kw)


def _item(rows, vid):
    from rnb_tpu.stage import PaddedBatch
    from rnb_tpu.telemetry import TimeCard
    data = np.full((rows, 2, 16, 16, 3), vid, dtype=np.uint8)
    return (PaddedBatch.from_rows(data, 8),), TimeCard(vid)


def test_batcher_static_semantics_unchanged_without_autotune():
    b = _batcher(batch=3)
    for vid in range(2):
        tensors, tc = _item(1, vid)
        assert b(tensors, None, tc)[2] is None
    assert b.next_deadline_s() is None
    assert b.poll() is None  # static mode: accumulate-to-batch only
    tensors, tc = _item(1, 2)
    out = b(tensors, None, tc)
    assert out[2] is not None and len(out[2].time_cards) == 3


def test_batcher_autotune_emits_early_at_low_rate():
    b = _batcher(batch=4, row_buckets=[2, 8])
    ctrl = b.enable_autotune(SETTINGS)
    assert ctrl.candidates == (2, 8)
    # slow stream: the controller sees 1 req/s -> immediate dispatch
    for i in range(20):
        ctrl.observe_enqueue(float(i))
    tensors, tc = _item(1, 0)
    out = b(tensors, None, tc)
    assert out[2] is not None, \
        "low-rate arrivals must not wait for the static batch count"
    assert out[0][0].data.shape[0] == 2  # padded to a candidate bucket
    snap = ctrl.snapshot()
    assert snap["emissions"] == 1
    assert snap["decisions"] >= snap["emissions"]


def test_batcher_autotune_holds_then_poll_emits_on_deadline():
    b = _batcher(batch=4, row_buckets=[2, 8])
    ctrl = b.enable_autotune(AutotuneSettings.from_config(
        {"enabled": True, "slo_ms": 40.0, "max_hold_ms": 10.0}))
    # fast stream (1 kHz): growth to 8 rows is predicted feasible
    for i in range(50):
        ctrl.observe_enqueue(i * 0.001)
    ctrl.observe_rows(1)
    tensors, tc = _item(1, 0)
    assert b(tensors, None, tc)[2] is None  # held for batchmates
    deadline = b.next_deadline_s()
    assert deadline is not None and deadline <= 0.040
    assert b.poll() is None  # deadline not reached yet
    time.sleep(deadline + 0.002)
    out = b.poll()  # the executor's idle tick fires the hold expiry
    assert out is not None and out[2] is not None
    assert b.next_deadline_s() is None  # accumulator drained


def test_batcher_autotune_respects_static_batch_ceiling():
    b = _batcher(batch=2, row_buckets=[2, 8])
    ctrl = b.enable_autotune(SETTINGS)
    for i in range(50):
        ctrl.observe_enqueue(i * 0.001)  # fast: would hold for more
    t0, tc0 = _item(1, 0)
    b(t0, None, tc0)
    t1, tc1 = _item(1, 1)
    out = b(t1, None, tc1)
    assert out[2] is not None, "the static fuse count stays a ceiling"


def test_batcher_deadline_queries_do_not_count_decisions():
    b = _batcher(batch=4, row_buckets=[2, 8])
    ctrl = b.enable_autotune(AutotuneSettings.from_config(
        {"enabled": True, "slo_ms": 40.0, "max_hold_ms": 10.0}))
    for i in range(50):
        ctrl.observe_enqueue(i * 0.001)  # fast: the batch is held
    tensors, tc = _item(1, 0)
    assert b(tensors, None, tc)[2] is None
    held = ctrl.snapshot()
    for _ in range(25):  # the executor polls the deadline every tick
        assert b.next_deadline_s() is not None
    assert ctrl.snapshot() == held, \
        "poll-frequency must not inflate the Autotune: counters"


def test_batcher_rows_per_request_splits_fused_emissions():
    from rnb_tpu.telemetry import TimeCard, TimeCardList
    b = _batcher(batch=4, row_buckets=[2, 8])
    ctrl = b.enable_autotune(SETTINGS)
    # one upstream FUSED emission carrying 4 requests' rows: the rows
    # EWMA must read ~1 row per client request (the inter-arrival EWMA
    # is fed per constituent card), not 4 rows per "arrival"
    tensors, _ = _item(4, 0)
    cards = TimeCardList([TimeCard(i) for i in range(4)])
    b(tensors, None, cards)
    assert ctrl._rows_per_req == pytest.approx(1.0)


# -- fusing-loader integration ---------------------------------------

def test_fusing_loader_controller_uses_warmed_buckets():
    jax = pytest.importorskip("jax")
    from rnb_tpu.models.r2p1d.model import R2P1DFusingLoader
    loader = R2P1DFusingLoader(jax.devices("cpu")[0], fuse=3,
                               num_clips_population=[1], weights=[1],
                               num_warmups=0, row_buckets=[6, 15])
    ctrl = loader.enable_autotune(SETTINGS)
    assert ctrl.candidates == (6, 15)
    assert ctrl.max_rows == loader.max_clips
    with pytest.raises(ValueError, match="never warms"):
        loader.enable_autotune(AutotuneSettings.from_config(
            {"enabled": True, "buckets": [5]}))


def test_fusing_loader_self_reports_service_span():
    jax = pytest.importorskip("jax")
    from rnb_tpu.models.r2p1d.model import R2P1DFusingLoader
    # the executor's stamp-based feed never sees transfer_async
    # emissions (they surface via take_ready, not a __call__ return),
    # so the loader reports its own close->ready span and the runner
    # must skip its TimeCard-stamp feed for this stage
    assert R2P1DFusingLoader.AUTOTUNE_SELF_SERVICE
    loader = R2P1DFusingLoader(jax.devices("cpu")[0], fuse=3,
                               num_clips_population=[1], weights=[1],
                               num_warmups=0, row_buckets=[6, 15])
    ctrl = loader.enable_autotune(SETTINGS)
    emission = (("tensors",), None, "cards")
    loader._push_ready(emission, bucket=6, service_s=0.004)
    assert loader._pop_ready() is emission
    assert ctrl.service_for(6) == pytest.approx(0.004)


# -- Poisson e2e A/B --------------------------------------------------

@pytest.mark.slow
def test_poisson_ab_autotune_beats_static_hold(tmp_path):
    """Poisson A/B through the real runtime, in the regime the round-5
    matrix flagged: arrivals overlap decode spans often enough that
    the static loader holds ready requests for batchmates (the
    ``max_hold_ms=100`` / ``fuse=6`` baseline), while autotune
    (slo_ms=15) sees that growing the batch cannot meet the budget
    and collapses to near-immediate dispatch — mean AND p99
    end-to-end latency must drop. Same seed, same dataset, same mesh.
    Also round-trips the ``Autotune:`` telemetry through
    ``parse_utils --check``. Loader-only pipeline: the batching knob
    under test lives in the loader, and the tiny R2P1D network's
    ~1 s/call CPU cost would otherwise saturate any test-sized
    arrival rate."""
    from rnb_tpu.benchmark import run_benchmark
    from rnb_tpu.control import TerminationFlag
    from rnb_tpu.decode import write_y4m

    root = os.path.join(str(tmp_path), "data")
    os.makedirs(os.path.join(root, "label0"))
    rng = np.random.default_rng(11)
    for i in range(6):
        write_y4m(os.path.join(root, "label0", "v%d.y4m" % i),
                  rng.integers(0, 256, (64, 144, 192, 3),
                               dtype=np.uint8))
    os.environ["RNB_TPU_DATA_ROOT"] = root
    try:
        def cfg(autotune):
            raw = {
                "video_path_iterator":
                    "rnb_tpu.models.r2p1d.model.R2P1DVideoPathIterator",
                "pipeline": [
                    {"model":
                        "rnb_tpu.models.r2p1d.model.R2P1DFusingLoader",
                     "queue_groups": [{"devices": [0]}],
                     "fuse": 6, "max_clips": 6, "depth": 12,
                     "max_hold_ms": 100.0,
                     "num_clips_population": [1], "weights": [1],
                     "consecutive_frames": 2, "num_warmups": 0,
                     "pixel_path": "yuv420"},
                ],
            }
            if autotune:
                raw["autotune"] = {"enabled": True, "slo_ms": 15.0}
            path = os.path.join(
                str(tmp_path), "ab-%s.json" % ("auto" if autotune
                                               else "static"))
            with open(path, "w") as f:
                json.dump(raw, f)
            return path

        results = {}
        for name, autotune in (("static", False), ("auto", True)):
            results[name] = run_benchmark(
                cfg(autotune), mean_interval_ms=6, num_videos=150,
                log_base=os.path.join(str(tmp_path), "logs-" + name),
                print_progress=False, seed=1234)
            assert results[name].termination_flag == \
                TerminationFlag.TARGET_NUM_VIDEOS_REACHED

        auto, static = results["auto"], results["static"]
        assert auto.autotune_decisions >= auto.autotune_emissions > 0
        assert static.autotune_decisions == 0
        assert auto.p50_latency_ms < static.p50_latency_ms, \
            (auto.p50_latency_ms, static.p50_latency_ms)
        assert auto.p99_latency_ms < static.p99_latency_ms, \
            (auto.p99_latency_ms, static.p99_latency_ms)

        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "scripts"))
        try:
            import parse_utils
        finally:
            sys.path.pop(0)
        meta = parse_utils.parse_meta(auto.log_dir)
        assert meta["autotune_decisions"] == auto.autotune_decisions
        assert meta["autotune_emissions"] == auto.autotune_emissions
        assert parse_utils.main(["--check", auto.log_dir]) == 0
        assert parse_utils.main(["--check", static.log_dir]) == 0
        with open(os.path.join(static.log_dir, "log-meta.txt")) as f:
            assert "Autotune:" not in f.read()  # schema byte-stable
    finally:
        os.environ.pop("RNB_TPU_DATA_ROOT", None)
