"""The yuv420 pixel path: packed-plane decode backends + on-device
colourspace conversion.

Contract under test (rnb_tpu/ops/yuv.py docstring):
  * numpy vs native packed-plane gathers are BIT-EXACT;
  * the jnp converter matches the numpy oracle within 1 u8 LSB (XLA
    may contract mul+add into FMA);
  * luma is bit-exact with the RGB pixel path (same index map);
  * the loader's yuv420 mode ships packed u8 and the network stage's
    fused ingest produces the same predictions as the rgb path.
"""

import os

import numpy as np
import pytest

from rnb_tpu.decode import (SyntheticDecoder, Y4MDecoder, get_decoder,
                            write_y4m)
from rnb_tpu.ops.yuv import (packed_frame_bytes, yuv420_to_rgb_numpy,
                             yuv420_to_rgb_u8)


def _make_y4m(tmp_path, name="vid.y4m", frames=24, h=96, w=128, seed=7):
    rng = np.random.default_rng(seed)
    data = rng.integers(0, 256, (frames, h, w, 3), dtype=np.uint8)
    path = os.path.join(str(tmp_path), name)
    write_y4m(path, data)
    return path


def test_packed_frame_bytes():
    assert packed_frame_bytes(112, 112) == 112 * 112 * 3 // 2
    with pytest.raises(ValueError):
        packed_frame_bytes(111, 112)


def test_numpy_yuv_matches_rgb_exactly_when_chroma_constant(tmp_path):
    """The two pixel paths differ ONLY in chroma index choice, so on a
    video with exactly constant chroma planes (U=V=128 raw) re-deriving
    RGB from the packed planes must be bit-exact with the direct RGB
    decode. (write_y4m's RGB->YUV roundtrip would leave ±1 chroma
    residue, so the 4:2:0 payload is written directly.)"""
    rng = np.random.default_rng(3)
    h, w, n = 96, 128, 20
    path = os.path.join(str(tmp_path), "gray.y4m")
    with open(path, "wb") as f:
        f.write(b"YUV4MPEG2 W%d H%d F25:1 Ip A1:1 C420\n" % (w, h))
        for _ in range(n):
            f.write(b"FRAME\n")
            f.write(rng.integers(0, 256, h * w, dtype=np.uint8)
                    .tobytes())
            f.write(np.full((h // 2) * (w // 2) * 2, 128,
                            np.uint8).tobytes())
    dec = Y4MDecoder()
    packed = dec.decode_clips_yuv(path, [0, 5], consecutive_frames=4,
                                  width=56, height=48)
    assert packed.shape == (2, 4, packed_frame_bytes(48, 56))
    assert packed.dtype == np.uint8
    rgb = dec.decode_clips(path, [0, 5], consecutive_frames=4,
                           width=56, height=48)
    re_rgb = yuv420_to_rgb_numpy(packed, 48, 56)
    np.testing.assert_array_equal(re_rgb, rgb)


def _smooth_frames(n=12, h=96, w=128):
    """Real-video-like moving gradients (noise frames would make the
    rgb-vs-yuv420 chroma index difference look maximal; real chroma is
    locally smooth)."""
    yy, xx = np.mgrid[0:h, 0:w].astype(np.float32)
    t = np.arange(n, dtype=np.float32)[:, None, None]
    frames = np.empty((n, h, w, 3), np.uint8)
    for c in range(3):
        frames[..., c] = (127.5 * (1 + np.sin(
            2 * np.pi * (yy / h + xx / w) + 0.3 * c + 0.1 * t))
        ).astype(np.uint8)
    return frames


def test_numpy_yuv_close_on_smooth_content(tmp_path):
    """On smooth (real-video-like) content the half-res chroma map
    stays within a few LSB of the rgb path everywhere."""
    frames = _smooth_frames()
    path = os.path.join(str(tmp_path), "smooth.y4m")
    write_y4m(path, frames)
    dec = Y4MDecoder()
    packed = dec.decode_clips_yuv(path, [0], consecutive_frames=8,
                                  width=56, height=48)
    rgb = dec.decode_clips(path, [0], consecutive_frames=8,
                           width=56, height=48)
    re_rgb = yuv420_to_rgb_numpy(packed, 48, 56)
    diff = np.abs(re_rgb.astype(int) - rgb.astype(int))
    # the chroma sample position can shift by ~1 source pixel in each
    # axis; on this gradient that is a handful of LSB
    assert np.percentile(diff, 50) <= 2
    assert np.percentile(diff, 99) <= 12
    assert diff.max() <= 24


def test_numpy_vs_native_yuv_bit_exact(tmp_path):
    from rnb_tpu.decode.native import NativeY4MDecoder, native_available
    if not native_available():
        pytest.skip("native decoder not built")
    path = _make_y4m(tmp_path, frames=30, h=120, w=160)
    a = Y4MDecoder().decode_clips_yuv(path, [0, 3, 25],
                                      consecutive_frames=8,
                                      width=112, height=112)
    b = NativeY4MDecoder(use_pool=False).decode_clips_yuv(
        path, [0, 3, 25], consecutive_frames=8, width=112, height=112)
    np.testing.assert_array_equal(a, b)


def test_native_pool_yuv_bit_exact(tmp_path):
    from rnb_tpu.decode.native import (DecodePool, NativeY4MDecoder,
                                       native_available)
    from rnb_tpu.decode.native import PIX_YUV420
    if not native_available():
        pytest.skip("native decoder not built")
    path = _make_y4m(tmp_path, frames=16, h=96, w=128)
    want = Y4MDecoder().decode_clips_yuv(path, [0, 8],
                                         consecutive_frames=8,
                                         width=112, height=112)
    pool = DecodePool(num_threads=2)
    try:
        out = np.empty_like(want)
        t = pool.submit_into(path, [0, 8], 8, out, pixfmt=PIX_YUV420)
        pool.wait(t, path)
        np.testing.assert_array_equal(out, want)
    finally:
        pool.close()


def test_write_y4m_420_roundtrip(tmp_path):
    """4:2:0 dataset files decode through both pixel paths, and the
    numpy/native backends stay bit-exact on them."""
    frames = _smooth_frames(n=10, h=64, w=96)
    path = os.path.join(str(tmp_path), "v420.y4m")
    write_y4m(path, frames, colorspace="420")
    dec = Y4MDecoder()
    assert dec.num_frames(path) == 10
    assert dec._parse_header(path)["subsample"] == 2
    rgb = dec.decode_clips(path, [0], 4, width=48, height=32)
    assert rgb.shape == (1, 4, 32, 48, 3)
    # the numpy yuv gather of the production (4:2:0) format must hold
    # regardless of whether the native library is built
    a = dec.decode_clips_yuv(path, [0, 3], 4, width=48, height=32)
    assert a.shape == (2, 4, packed_frame_bytes(32, 48))
    re_rgb = yuv420_to_rgb_numpy(a, 32, 48)
    got = dec.decode_clips(path, [0, 3], 4, width=48, height=32)
    assert np.abs(re_rgb.astype(int) - got.astype(int)).max() <= 24
    from rnb_tpu.decode.native import NativeY4MDecoder, native_available
    if native_available():
        b = NativeY4MDecoder(use_pool=False).decode_clips_yuv(
            path, [0, 3], 4, width=48, height=32)
        np.testing.assert_array_equal(a, b)
        c = NativeY4MDecoder(use_pool=False).decode_clips(
            path, [0, 3], 4, width=48, height=32)
        d = dec.decode_clips(path, [0, 3], 4, width=48, height=32)
        np.testing.assert_array_equal(c, d)


def test_write_y4m_rejects_bad_colorspace(tmp_path):
    with pytest.raises(ValueError):
        write_y4m(os.path.join(str(tmp_path), "x.y4m"),
                  np.zeros((1, 4, 4, 3), np.uint8), colorspace="422")
    with pytest.raises(ValueError):
        write_y4m(os.path.join(str(tmp_path), "x.y4m"),
                  np.zeros((1, 5, 4, 3), np.uint8), colorspace="420")


def test_synthetic_yuv_deterministic():
    dec = SyntheticDecoder()
    a = dec.decode_clips_yuv("synth://v1", [0, 10], 8, 112, 112)
    b = dec.decode_clips_yuv("synth://v1", [0, 10], 8, 112, 112)
    assert a.shape == (2, 8, packed_frame_bytes(112, 112))
    np.testing.assert_array_equal(a, b)
    c = dec.decode_clips_yuv("synth://v2", [0, 10], 8, 112, 112)
    assert not np.array_equal(a, c)


def test_device_converter_matches_numpy_oracle(tmp_path):
    import jax
    path = _make_y4m(tmp_path, frames=12, h=96, w=128)
    packed = Y4MDecoder().decode_clips_yuv(path, [0], 8, 112, 112)
    want = yuv420_to_rgb_numpy(packed, 112, 112)
    got = np.asarray(jax.jit(
        lambda x: yuv420_to_rgb_u8(x, 112, 112))(packed))
    assert np.abs(got.astype(int) - want.astype(int)).max() <= 1


def test_normalize_yuv420_range():
    import jax.numpy as jnp
    from rnb_tpu.ops.yuv import normalize_yuv420
    rng = np.random.default_rng(0)
    packed = rng.integers(0, 256, (2, 4, packed_frame_bytes(112, 112)),
                          dtype=np.uint8)
    out = normalize_yuv420(packed, 112, 112)
    assert out.shape == (2, 4, 112, 112, 3)
    assert out.dtype == jnp.bfloat16
    f = np.asarray(out, dtype=np.float32)
    assert f.min() >= -1.0 and f.max() <= 1.0


def test_loader_yuv_output_shape_and_pipeline_parity(tmp_path):
    """yuv420 loader ships packed u8; a start_index=1 runner configured
    for yuv420 accepts it and its logits track the rgb path's."""
    import jax
    from rnb_tpu.models.r2p1d.model import (R2P1DLoader, R2P1DRunner,
                                            FRAME_HW)
    shape = R2P1DLoader.output_shape_for(max_clips=15,
                                         consecutive_frames=8,
                                         pixel_path="yuv420")
    assert shape == ((15, 8, packed_frame_bytes(FRAME_HW, FRAME_HW)),)

    frames = _smooth_frames(n=40)
    path = os.path.join(str(tmp_path), "vid.y4m")
    write_y4m(path, frames)
    dev = jax.devices()[0]
    fixed = dict(num_clips_population=[2], weights=[1], max_clips=2,
                 num_warmups=0)
    loader = R2P1DLoader(dev, pixel_path="yuv420", **fixed)
    (pb,), _, tc = loader(None, path, _card(path))
    assert pb.data.shape == (2, 8, packed_frame_bytes(FRAME_HW,
                                                      FRAME_HW))
    assert str(pb.data.dtype) == "uint8"

    net = dict(start_index=1, end_index=5, num_warmups=0,
               layer_sizes=(1, 1, 1, 1), max_rows=2, num_classes=16)
    runner = R2P1DRunner(dev, pixel_path="yuv420", **net)
    (logits,), _, _ = runner((pb,), None, tc)
    assert logits.data.shape == (2, 16)

    # rgb reference prediction on the same video, same weights
    loader_rgb = R2P1DLoader(dev, **fixed)
    runner_rgb = R2P1DRunner(dev, **net)
    (pb2,), _, tc2 = loader_rgb(None, path, _card(path))
    (logits2,), _, _ = runner_rgb((pb2,), None, tc2)
    a = np.asarray(logits.data, dtype=np.float32)
    b = np.asarray(logits2.data, dtype=np.float32)
    # the pixel paths differ by <=1 chroma source pixel on smooth
    # content; logits must track closely (bf16 activations)
    np.testing.assert_allclose(a, b, atol=0.05 * np.abs(b).max())


def test_runner_yuv_requires_layer1():
    import jax
    from rnb_tpu.models.r2p1d.model import R2P1DRunner
    with pytest.raises(ValueError):
        R2P1DRunner(jax.devices()[0], start_index=2, end_index=5,
                    num_warmups=0, layer_sizes=(1, 1, 1, 1),
                    pixel_path="yuv420")


def _card(video):
    from rnb_tpu.telemetry import TimeCard
    return TimeCard(0)
