"""Loader decode prefetch (NVVL parity, reference README.md:46-110).

Covers the submit()/complete() protocol directly (numerics identical to
the synchronous path on both the native-y4m and synthetic backends) and
through the executor (a prefetching pipeline completes with every
record intact).
"""

import json
import os

import numpy as np

from rnb_tpu.benchmark import run_benchmark
from rnb_tpu.control import TerminationFlag
from rnb_tpu.devices import DeviceSpec
from rnb_tpu.telemetry import TimeCard


def _loader(**kw):
    from rnb_tpu.models.r2p1d.model import R2P1DLoader
    defaults = dict(max_clips=2, consecutive_frames=2, num_warmups=1,
                    num_clips_population=[1, 2], weights=[1, 1])
    defaults.update(kw)
    return R2P1DLoader(DeviceSpec(0), **defaults)


def test_submit_complete_matches_call_synthetic():
    loader = _loader(prefetch=2)
    video = "synth://prefetch/video-7"
    tc_a, tc_b = TimeCard(0), TimeCard(1)
    handle = loader.submit(video, tc_a)
    (pb_async,), _, _ = loader.complete(handle, video, tc_a)
    (pb_sync,), _, _ = loader(None, video, tc_b)
    assert tc_a.num_clips == tc_b.num_clips
    np.testing.assert_array_equal(np.asarray(pb_async.data),
                                  np.asarray(pb_sync.data))


def test_submit_complete_matches_call_y4m(tmp_path):
    from rnb_tpu.decode import write_y4m

    rng = np.random.default_rng(5)
    path = os.path.join(str(tmp_path), "clip.y4m")
    write_y4m(path, rng.integers(0, 256, (40, 64, 48, 3), dtype=np.uint8))

    loader = _loader(prefetch=2)
    tc_a, tc_b = TimeCard(0), TimeCard(1)
    handle = loader.submit(path, tc_a)
    (pb_async,), _, _ = loader.complete(handle, path, tc_a)
    (pb_sync,), _, _ = loader(None, path, tc_b)
    np.testing.assert_array_equal(np.asarray(pb_async.data),
                                  np.asarray(pb_sync.data))


def test_overlapped_submits_fill_disjoint_buffers(tmp_path):
    """Several decodes in flight at once (the actual prefetch pattern)
    must land each video's pixels in its own buffer."""
    from rnb_tpu.decode import write_y4m

    rng = np.random.default_rng(6)
    paths, frames = [], []
    for i in range(4):
        p = os.path.join(str(tmp_path), "v%d.y4m" % i)
        f = rng.integers(0, 256, (24, 32, 32, 3), dtype=np.uint8)
        write_y4m(p, f)
        paths.append(p)
        frames.append(f)

    loader = _loader(prefetch=4)
    cards = [TimeCard(i) for i in range(4)]
    handles = [loader.submit(p, tc) for p, tc in zip(paths, cards)]
    outs = [loader.complete(h, p, tc)[0][0]
            for h, p, tc in zip(handles, paths, cards)]
    syncs = [loader(None, p, TimeCard(10 + i))[0][0]
             for i, p in enumerate(paths)]
    for got, want in zip(outs, syncs):
        np.testing.assert_array_equal(np.asarray(got.data),
                                      np.asarray(want.data))


def test_prefetching_pipeline_end_to_end(tmp_path):
    cfg = {
        "video_path_iterator":
            "rnb_tpu.models.r2p1d.model.R2P1DVideoPathIterator",
        "pipeline": [
            {"model": "rnb_tpu.models.r2p1d.model.R2P1DLoader",
             "queue_groups": [{"devices": [0], "out_queues": [0]}],
             "num_shared_tensors": 8,
             "max_clips": 2, "consecutive_frames": 2,
             "num_clips_population": [1, 2], "weights": [3, 1],
             "num_warmups": 1, "prefetch": 3},
            {"model": "rnb_tpu.models.r2p1d.model.R2P1DRunner",
             "queue_groups": [{"devices": [1], "in_queue": 0}],
             "start_index": 1, "end_index": 5,
             "num_classes": 8, "layer_sizes": [1, 1, 1, 1],
             "max_rows": 2, "consecutive_frames": 2, "num_warmups": 1},
        ],
    }
    path = os.path.join(str(tmp_path), "prefetch.json")
    with open(path, "w") as f:
        json.dump(cfg, f)
    res = run_benchmark(path, mean_interval_ms=0, num_videos=12,
                        queue_size=40, log_base=str(tmp_path / "logs"),
                        print_progress=False)
    assert res.termination_flag == TerminationFlag.TARGET_NUM_VIDEOS_REACHED
    # every completion registered, with its clip stamp intact
    assert res.clips_completed >= 12
    reports = [f for f in os.listdir(res.log_dir) if "group" in f]
    with open(os.path.join(res.log_dir, reports[0])) as f:
        lines = f.read().strip().split("\n")
    # '#'-prefixed trailers (e.g. '# padding') are not table rows
    rows = [line for line in lines[1:] if not line.startswith("#")]
    assert len(rows) >= 12
    # timestamps stay monotonic per record even when decode ran ahead
    header_len = len(lines[0].split()) - 2  # minus device columns
    for line in rows:
        row = list(map(float, line.split()[:header_len]))
        assert row == sorted(row)
