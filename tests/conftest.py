"""Test harness: force an 8-virtual-device CPU JAX backend.

Multi-core placement, sharding and mesh logic all run on a simulated
8-device CPU platform so the suite never needs TPU hardware — the
idiomatic JAX substitute for a fake backend (SURVEY.md §4). Must run
before anything imports jax.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
