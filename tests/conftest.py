"""Test harness: force an 8-virtual-device CPU JAX backend.

Multi-core placement, sharding and mesh logic all run on a simulated
8-device CPU platform so the suite never needs TPU hardware — the
idiomatic JAX substitute for a fake backend (SURVEY.md §4).

Note: setting the JAX_PLATFORMS env var is NOT sufficient in this
environment — a site hook registers the TPU-tunnel PJRT plugin at
interpreter startup and overrides the platform list via jax.config, so
the config must be forced back to "cpu" before the first backend
initialization or every jax.devices() call blocks on the TPU tunnel.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax
import pytest

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


@pytest.fixture(autouse=True)
def _lock_witness():
    """Run every test under the runtime lock-order witness: any pool,
    cache, pager, health or netedge object a test constructs gets
    witnessed locks, so lock-order inversions and ``*_locked``
    convention breaches surface as recorded violations wherever a test
    (or the races gate) chooses to assert on them. The fixture itself
    never asserts — a test that wants the discipline checked reads
    ``lockwitness.summary()`` explicitly."""
    from rnb_tpu import lockwitness
    lockwitness.enable()
    lockwitness.reset()
    yield
    lockwitness.reset()
