"""The runtime lock-order witness (rnb_tpu.lockwitness).

* disabled path: plain factory locks, None summary — byte-stable
* enabled path: acquisition counting, order-edge recording, inversion
  / release / require() violation detection, reentrancy, Condition
  compatibility, cross-thread merging, the violation cap
* integration: the staging pool's claim-then-confirm protocol keeps
  the device sync outside the pool lock (the PR's headline RNB-C005
  fix), and the cache->pager nesting lands exactly on the static
  graph's declared edge
"""

import json
import threading

import numpy as np
import pytest

from rnb_tpu import lockwitness


@pytest.fixture
def witness():
    """Fresh enabled witness; restores the prior enabled state (the
    suite-wide autouse fixture keeps it on between tests)."""
    was_enabled = lockwitness.enabled()
    lockwitness.enable()
    lockwitness.reset()
    yield lockwitness
    lockwitness.reset()
    if not was_enabled:
        lockwitness.disable()


# -- disabled path ----------------------------------------------------

def test_disabled_returns_plain_factory_lock():
    was_enabled = lockwitness.enabled()
    lockwitness.disable()
    try:
        plain = lockwitness.lock("X._lock")
        assert not isinstance(plain, lockwitness.WitnessLock)
        assert type(plain) is type(threading.Lock())
        rlock = lockwitness.lock("X.rlock", threading.RLock)
        assert not isinstance(rlock, lockwitness.WitnessLock)
        assert lockwitness.summary() is None
        # require/holds are free no-ops off
        lockwitness.require("X._lock")
        assert not lockwitness.holds("X._lock")
    finally:
        if was_enabled:
            lockwitness.enable()


# -- edges + counters -------------------------------------------------

def test_nested_acquisition_records_one_edge(witness):
    a = witness.lock("A._lock")
    b = witness.lock("B._lock")
    with a:
        assert witness.holds("A._lock")
        with b:
            pass
    snap = witness.summary()
    assert snap["locks"] == 2
    assert snap["acquires"] == 2
    assert snap["edges"] == [("A._lock", "B._lock")]
    assert snap["violations"] == []


def test_order_inversion_is_a_violation(witness):
    a = witness.lock("A._lock")
    b = witness.lock("B._lock")
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    snap = witness.summary()
    assert len(snap["violations"]) == 1
    assert "order inversion" in snap["violations"][0]


def test_release_without_hold_is_a_violation(witness):
    a = witness.lock("A._lock")
    a._inner.acquire()  # hold the inner lock so release() is legal
    a.release()
    snap = witness.summary()
    assert any("does not hold" in v for v in snap["violations"])


def test_require_flags_the_locked_convention(witness):
    a = witness.lock("A._lock")
    witness.require("A._lock")  # not held -> violation
    with a:
        witness.require("A._lock")  # held -> clean
    snap = witness.summary()
    assert len(snap["violations"]) == 1
    assert "required but not held" in snap["violations"][0]


def test_reentrant_rlock_records_no_self_edge(witness):
    r = witness.lock("P.lock", threading.RLock)
    with r:
        with r:
            pass
    snap = witness.summary()
    assert snap["edges"] == []
    assert snap["violations"] == []
    assert snap["acquires"] == 2


def test_condition_on_witness_lock_waits_and_notifies(witness):
    inner = witness.lock("S._lock")
    cond = threading.Condition(inner)
    ready = []

    def waiter():
        with cond:
            while not ready:
                cond.wait(timeout=5.0)

    t = threading.Thread(target=waiter)
    t.start()
    with cond:
        ready.append(1)
        cond.notify_all()
    t.join(timeout=5.0)
    assert not t.is_alive()
    assert witness.summary()["violations"] == []


def test_cross_thread_edges_merge(witness):
    a = witness.lock("A._lock")
    b = witness.lock("B._lock")

    def nest():
        with a:
            with b:
                pass

    threads = [threading.Thread(target=nest) for _ in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    snap = witness.summary()
    assert snap["edges"] == [("A._lock", "B._lock")]
    assert snap["violations"] == []
    assert snap["acquires"] == 4


def test_violation_list_is_capped(witness):
    for _ in range(lockwitness.MAX_VIOLATIONS + 20):
        witness.require("never.held")
    snap = witness.summary()
    assert len(snap["violations"]) == lockwitness.MAX_VIOLATIONS


def test_format_edges_is_sorted_json(witness):
    a = witness.lock("A._lock")
    b = witness.lock("B._lock")
    with a:
        with b:
            pass
    snap = witness.summary()
    payload = json.loads(witness.format_edges(snap))
    assert payload["edges"] == [["A._lock", "B._lock"]]
    assert payload["violations"] == []


# -- integration: the fixed subsystems under the witness --------------

def test_staging_confirm_runs_outside_the_pool_lock(witness):
    """Regression for the RNB-C005 true positive this PR fixed: the
    lazy transfer confirmation used to block_until_ready under the
    pool lock. The claim/confirm split must sync the device OUTSIDE
    it — proven by probing the witness's held-stack from inside the
    sync itself."""
    from rnb_tpu.staging import StagingPool

    held_during_sync = []

    class Probe:
        def block_until_ready(self):
            held_during_sync.append(
                lockwitness.holds("StagingPool._lock"))
            return self

        def unsafe_buffer_pointer(self):
            return 0  # never aliases the slot buffer

    pool = StagingPool([(2, 4)], 1)
    slot = pool.try_acquire((2, 4))
    assert slot is not None
    pool.begin_transfer(slot)
    pool.finish_transfer(slot, Probe())  # lazy confirm: parks the probe
    slot2 = pool.try_acquire((2, 4))     # claim processes the probe
    assert slot2 is slot
    assert held_during_sync == [False], \
        "device sync ran under the pool lock"
    assert witness.summary()["violations"] == []


def test_cache_pager_nesting_matches_the_static_graph(witness):
    """The one real cross-class nesting: a paged cache hit pins pages
    under ClipCache._lock -> Pager.lock. The witness must observe
    exactly the edge the static analyzer declares — the subset
    invariant parse_utils --check enforces on real runs."""
    import jax.numpy as jnp
    from rnb_tpu.analysis.concurrency import static_lock_order_edges
    from rnb_tpu.cache import ClipCache
    from rnb_tpu.ops.pages import _page_writer_jit
    from rnb_tpu.pager import Pager, PagerSettings

    pager = Pager(PagerSettings(page_rows=1))
    arena = pager.create_arena("clips", (16,), np.float32,
                               budget_bytes=128)
    cache = ClipCache(1.0)
    cache.attach_arena(arena)
    pool = jnp.zeros((2, 16), jnp.float32)
    try:
        assert cache.insert_pages(("vid",), pool, 0, 2)
        plan = cache.acquire(("vid",))
        assert plan is not None
        plan.release()
    finally:
        # this insert compiles the memoized page writer for a shape
        # test_pager's single-signature pin never uses — hand that
        # test a fresh writer
        _page_writer_jit.cache_clear()

    snap = witness.summary()
    observed = {tuple(e) for e in snap["edges"]}
    assert ("ClipCache._lock", "Pager.lock") in observed
    assert snap["violations"] == []
    declared = static_lock_order_edges()
    assert observed <= declared, observed - declared
