"""Pure-numpy oracle for the R(2+1)D network — no Flax, no XLA.

An independent re-derivation of the factored (2+1)D math from the
paper's definition (Tran et al., CVPR'18; reference consumes it via
the R2Plus1D-PyTorch submodule, /root/reference/models/r2p1d/
network.py:9-60): direct sliding-window 3-D convolution in float64,
inference-mode batch norm, the factored-channel formula, residual
blocks, and the layer-range composition. Tests drive the Flax modules
(rnb_tpu.models.r2p1d.network) and this oracle with the SAME parameter
arrays and assert the outputs agree — catching padding/stride/
factorization regressions that Flax-vs-Flax tests cannot (they would
agree with their own bug).

The only things taken from the Flax side are the parameter *values*
(plain numpy arrays pulled out of the variables pytree) and the
architecture hyperparameters; every floating-point operation here is
numpy on float64.
"""

from __future__ import annotations

import numpy as np

BN_EPS = 1e-5  # flax.linen.BatchNorm default epsilon

R18_LAYER_SIZES = (2, 2, 2, 2)


def conv3d(x, w, strides, padding):
    """Direct sliding-window 3-D convolution, NDHWC x (kt,kh,kw,ci,co).

    ``padding`` is ((pt0,pt1),(ph0,ph1),(pw0,pw1)). Accumulates one
    kernel tap at a time over the strided input view — deliberately
    the textbook formulation, not an im2col/FFT restatement of what a
    conv library would do.
    """
    x = np.asarray(x, np.float64)
    w = np.asarray(w, np.float64)
    x = np.pad(x, ((0, 0),) + tuple(padding) + ((0, 0),))
    st, sh, sw = strides
    kt, kh, kw, cin, cout = w.shape
    n, t, h, wd, c = x.shape
    assert c == cin, (c, cin)
    ot = (t - kt) // st + 1
    oh = (h - kh) // sh + 1
    ow = (wd - kw) // sw + 1
    out = np.zeros((n, ot, oh, ow, cout), np.float64)
    for a in range(kt):
        for b in range(kh):
            for d in range(kw):
                view = x[:, a:a + st * ot:st, b:b + sh * oh:sh,
                         d:d + sw * ow:sw, :]
                out += np.einsum("nthwc,co->nthwo", view, w[a, b, d])
    return out


def batchnorm(x, scale, bias, mean, var):
    """Inference-mode batch norm over the channel axis."""
    x = np.asarray(x, np.float64)
    return ((x - mean) / np.sqrt(np.asarray(var, np.float64) + BN_EPS)
            * scale + bias)


def relu(x):
    return np.maximum(x, 0.0)


def _bn_args(params, stats):
    return (params["scale"], params["bias"], stats["mean"], stats["var"])


def spatiotemporal_conv(var, x, kernel, stride=(1, 1)):
    """The factored conv: spatial (1,d,d) conv, BN, ReLU, temporal
    (t,1,1) conv. ``var`` is the module's {"params", "batch_stats"}
    subtree."""
    t, d = kernel
    st, sd = stride
    p, s = var["params"], var.get("batch_stats", {})
    x = conv3d(x, p["spatial"]["kernel"], (1, sd, sd),
               ((0, 0), (d // 2, d // 2), (d // 2, d // 2)))
    x = batchnorm(x, *_bn_args(p["bn"], s["bn"]))
    x = relu(x)
    x = conv3d(x, p["temporal"]["kernel"], (st, 1, 1),
               ((t // 2, t // 2), (0, 0), (0, 0)))
    return x


def _sub(var, name):
    return {"params": var["params"][name],
            "batch_stats": var.get("batch_stats", {}).get(name, {})}


def res_block(var, x, downsample=False, factored_shortcut=False):
    stride = 2 if downsample else 1
    p, s = var["params"], var.get("batch_stats", {})
    res = spatiotemporal_conv(_sub(var, "conv1"), x, (3, 3),
                              (stride, stride))
    res = batchnorm(res, *_bn_args(p["bn1"], s["bn1"]))
    res = relu(res)
    res = spatiotemporal_conv(_sub(var, "conv2"), res, (3, 3))
    res = batchnorm(res, *_bn_args(p["bn2"], s["bn2"]))
    if downsample:
        if factored_shortcut:
            x = spatiotemporal_conv(_sub(var, "shortcut"), x, (1, 1),
                                    (2, 2))
        else:
            x = conv3d(x, p["shortcut"]["kernel"], (2, 2, 2),
                       ((0, 0), (0, 0), (0, 0)))
        x = batchnorm(x, *_bn_args(p["shortcut_bn"], s["shortcut_bn"]))
    return relu(x + res)


def res_layer(var, x, num_blocks, downsample=False,
              factored_shortcut=False):
    x = res_block(_sub(var, "block0"), x, downsample=downsample,
                  factored_shortcut=factored_shortcut)
    for i in range(1, num_blocks):
        x = res_block(_sub(var, "block%d" % i), x)
    return x


def r2plus1d_net(var, x, start=1, end=5, layer_sizes=R18_LAYER_SIZES,
                 factored_shortcut=False):
    """The layer-range network: stem (+BN+ReLU) when layer 1 is in
    range, residual stages 2..5, global spatiotemporal mean pool when
    the range reaches layer 5."""
    p, s = var["params"], var.get("batch_stats", {})
    for layer in range(start, end + 1):
        if layer == 1:
            x = spatiotemporal_conv(_sub(var, "conv1"), x, (3, 7), (1, 2))
            x = batchnorm(x, *_bn_args(p["stem_bn"], s["stem_bn"]))
            x = relu(x)
        else:
            x = res_layer(_sub(var, "conv%d" % layer), x,
                          num_blocks=layer_sizes[layer - 2],
                          downsample=(layer >= 3),
                          factored_shortcut=factored_shortcut)
    if end == 5:
        x = x.mean(axis=(1, 2, 3))
    return x


def r2plus1d_classifier(var, x, start=1, end=5,
                        layer_sizes=R18_LAYER_SIZES,
                        factored_shortcut=False):
    x = r2plus1d_net(_sub(var, "net"), x, start=start, end=end,
                     layer_sizes=layer_sizes,
                     factored_shortcut=factored_shortcut)
    if end == 5:
        p = var["params"]["linear"]
        x = x @ np.asarray(p["kernel"], np.float64) \
            + np.asarray(p["bias"], np.float64)
    return x
