"""Tier-1 gate for the static analyzer (scripts/rnb_lint.py).

Three layers:

* fixture pairs per rule — every ``bad_*`` fixture triggers exactly
  its rule id, the ``good*`` fixtures trigger nothing;
* the repo itself (rnb_tpu/ + every shipped config) is lint-clean
  modulo the checked-in baseline, via the real CLI under
  ``JAX_PLATFORMS=cpu`` with no device or dataset;
* the schema checker's cross-checks fire on synthetic drift
  (unparsed registry entries, BenchmarkResult counter drift).
"""

import glob
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "lint_fixtures")


def _fixture(name):
    return os.path.join(FIXTURES, name)


# -- pipeline graph checker -------------------------------------------

GRAPH_CASES = [
    ("bad_g001_parse.json", "RNB-G001"),
    ("bad_g002_class.json", "RNB-G002"),
    ("bad_g003_shape.json", "RNB-G003"),
    ("bad_g004_selector.json", "RNB-G004"),
    ("bad_g005_key.json", "RNB-G005"),
    ("bad_g006_buckets.json", "RNB-G006"),
    ("bad_g006_autotune.json", "RNB-G006"),
    ("bad_g007_cache.json", "RNB-G007"),
    ("bad_g008_dtype.json", "RNB-G008"),
    ("bad_g008_dct.json", "RNB-G008"),
    ("bad_g009_ragged.json", "RNB-G009"),
    ("bad_g010_degree.json", "RNB-G010"),
    ("bad_g010_no_spec.json", "RNB-G010"),
]


def test_good_config_fixture_is_clean():
    from rnb_tpu.analysis.graph import check_config
    assert check_config(_fixture("good.json")) == []


def test_good_autotune_fixture_is_clean():
    # the root 'autotune' key and the reserved per-step opt-out are
    # consumed by the checker: no RNB-G005 "unconsumed key", and an
    # in-warmed-set bucket restriction passes RNB-G006
    from rnb_tpu.analysis.graph import check_config
    assert check_config(_fixture("good_autotune.json")) == []


def test_good_dct_fixture_is_clean():
    # pixel_path "dct": the checker derives the loader's packed
    # coefficient row shape/dtype ((15, 8, nb + 2*C), int16) from the
    # stage classmethods and matches it against the runner's dct
    # ingest declaration — no RNB-G001/G003/G005/G008, and
    # dct_coeffs_per_frame is a consumed constructor key on both
    # stages
    from rnb_tpu.analysis.graph import check_config
    findings = check_config(_fixture("good_dct.json"))
    assert findings == [], [f.render() for f in findings]


def test_good_shard_fixture_is_clean():
    # degree 2 divides every declared channel width of [1..5] and the
    # ring is 2 distinct devices on a SUPPORTS_SHARD class — nothing
    # fires (in particular no RNB-G005: the parse-time shard_* wiring
    # keys are not user config typos)
    from rnb_tpu.analysis.graph import check_config
    findings = check_config(_fixture("good_shard.json"))
    assert findings == [], [f.render() for f in findings]


def test_good_ragged_fixture_is_clean():
    # the root 'ragged' key is consumed (no RNB-G001/G005), a matching
    # pool_rows passes RNB-G009, and an autotune.buckets restriction
    # naming counts the bucketed rule never warms (4, 10) passes
    # RNB-G006 — legal only under ragged, where the candidate set is
    # continuous up to the pool capacity
    from rnb_tpu.analysis.graph import check_config
    findings = check_config(_fixture("good_ragged.json"))
    assert findings == [], [f.render() for f in findings]


def test_ragged_pool_mismatch_across_stages_triggers_g006():
    # omitted ragged.pool_rows: each stage resolves its OWN declared
    # max, so a loader pool (15) feeding a bigger runner pool (30)
    # would be a mid-run recompile — the edge check must treat the
    # ragged consumer's warmed set as exactly its pool, not its
    # counterfactual row_buckets
    import json
    import os as _os
    import tempfile
    from rnb_tpu.analysis.graph import check_config
    raw = {
        "video_path_iterator":
            "rnb_tpu.models.r2p1d.model.R2P1DVideoPathIterator",
        "ragged": {"enabled": True},
        "pipeline": [
            {"model": "rnb_tpu.models.r2p1d.model.R2P1DFusingLoader",
             "queue_groups": [{"devices": [0], "out_queues": [0]}],
             "fuse": 6},
            {"model": "rnb_tpu.models.r2p1d.model.R2P1DRunner",
             "queue_groups": [{"devices": [0], "in_queue": 0}],
             "max_rows": 30, "row_buckets": [15, 30]}],
    }
    with tempfile.TemporaryDirectory() as tmp:
        path = _os.path.join(tmp, "pool_mismatch.json")
        with open(path, "w") as f:
            json.dump(raw, f)
        findings = check_config(path)
    assert {f.rule for f in findings} == {"RNB-G006"}, \
        [f.render() for f in findings]


def test_ragged_buckets_without_ragged_still_trigger_g006():
    # the same out-of-warmed-set restriction WITHOUT the ragged key
    # must keep firing — the relaxation is scoped to ragged configs
    import json
    from rnb_tpu.analysis.graph import check_config
    with open(_fixture("good_ragged.json")) as f:
        raw = json.load(f)
    del raw["ragged"]
    import tempfile, os as _os
    with tempfile.TemporaryDirectory() as tmp:
        path = _os.path.join(tmp, "no_ragged.json")
        with open(path, "w") as f:
            json.dump(raw, f)
        findings = check_config(path)
    assert {f.rule for f in findings} == {"RNB-G006"}, \
        [f.render() for f in findings]


@pytest.mark.parametrize("name,rule", GRAPH_CASES)
def test_bad_config_fixture_triggers_exactly_its_rule(name, rule):
    from rnb_tpu.analysis.graph import check_config
    findings = check_config(_fixture(name))
    assert findings, "expected a %s finding for %s" % (rule, name)
    assert {f.rule for f in findings} == {rule}, \
        "expected only %s, got: %s" % (
            rule, [f.render() for f in findings])


def test_every_shipped_config_passes_the_graph_checker():
    from rnb_tpu.analysis.graph import check_configs
    paths = sorted(glob.glob(os.path.join(REPO, "configs", "*.json")))
    assert paths
    findings = check_configs(paths)
    assert findings == [], "\n".join(f.render() for f in findings)


# -- hot-path AST lint ------------------------------------------------

HOTPATH_CASES = [
    ("bad_h001_jit.py", "RNB-H001"),
    ("bad_h002_import.py", "RNB-H002"),
    ("bad_h003_loop_put.py", "RNB-H003"),
    ("bad_h004_random.py", "RNB-H004"),
    ("bad_h005_shed.py", "RNB-H005"),
    ("bad_h006_sync.py", "RNB-H006"),
    ("bad_h007_alloc.py", "RNB-H007"),
    ("bad_h008_handoff.py", "RNB-H008"),
    ("bad_h009_block.py", "RNB-H009"),
    ("bad_h009_socket.py", "RNB-H009"),
    ("bad_h010_device_alloc.py", "RNB-H010"),
]


def test_good_hotpath_fixture_is_clean():
    from rnb_tpu.analysis.hotpath import check_file
    assert check_file(_fixture("good_hot.py"), root=FIXTURES) == []


def test_good_h009_fixture_is_clean():
    # timeout-bounded waits with a liveness re-check each lap are the
    # sanctioned shape (the runner's own queue polls); RNB-H009 must
    # stay quiet on them — including on a wait-named leaf method
    from rnb_tpu.analysis.hotpath import check_file
    assert check_file(_fixture("good_h009_wait.py"),
                      root=FIXTURES) == []


def test_good_h009_socket_fixture_is_clean():
    # the socket face of RNB-H009: settimeout-ing the sockets you
    # block on, or gettimeout-guarding a handed-in one (the
    # wire.recv_exact idiom), are the sanctioned shapes
    from rnb_tpu.analysis.hotpath import check_file
    assert check_file(_fixture("good_h009_socket.py"),
                      root=FIXTURES) == []


def test_good_h010_fixture_is_clean():
    # pool-shaped device memory allocated once at stage init and
    # reused per emission is the sanctioned shape; RNB-H010 must stay
    # quiet on it
    from rnb_tpu.analysis.hotpath import check_file
    assert check_file(_fixture("good_h010_device_alloc.py"),
                      root=FIXTURES) == []


def test_good_handoff_fixture_is_clean():
    # host materialization confined to the '*host*'-named path of a
    # Handoff class is the sanctioned shape (rnb_tpu.handoff's own
    # _take_host); RNB-H008 must stay quiet on it
    from rnb_tpu.analysis.hotpath import check_file
    assert check_file(_fixture("good_handoff.py"), root=FIXTURES) == []


@pytest.mark.parametrize("name,rule", HOTPATH_CASES)
def test_bad_hotpath_fixture_triggers_exactly_its_rule(name, rule):
    from rnb_tpu.analysis.hotpath import check_file
    findings = check_file(_fixture(name), root=FIXTURES)
    assert findings, "expected a %s finding for %s" % (rule, name)
    assert {f.rule for f in findings} == {rule}, \
        "expected only %s, got: %s" % (
            rule, [f.render() for f in findings])


# -- telemetry schema checker -----------------------------------------

def _parse_utils_src():
    with open(os.path.join(REPO, "scripts", "parse_utils.py")) as f:
        return f.read()


def test_registered_stamps_fixture_is_clean():
    from rnb_tpu.analysis.schema import check_stamps
    findings = check_stamps([_fixture("stamps_registered.py")],
                            _parse_utils_src(), root=FIXTURES)
    assert findings == [], [f.render() for f in findings]


def test_unregistered_stamp_triggers_t001():
    from rnb_tpu.analysis.schema import check_stamps
    findings = check_stamps([_fixture("bad_t001_stamp.py")],
                            _parse_utils_src(), root=FIXTURES)
    assert {f.rule for f in findings} == {"RNB-T001"}
    assert findings[0].anchor == "mystery_stamp"


def test_unregistered_content_stamp_triggers_t007():
    from rnb_tpu.analysis.schema import check_content_stamps
    findings = check_content_stamps([_fixture("bad_t007_content.py")],
                                    root=FIXTURES)
    assert {(f.rule, f.anchor) for f in findings} \
        == {("RNB-T007", "mystery_attr")}


def test_trace_event_fixture_is_clean():
    from rnb_tpu.analysis.schema import check_trace_events
    from rnb_tpu.telemetry import StampSpec
    registry = (StampSpec("good.event", "f", "instant"),
                StampSpec("good.gauge", "f", "counter"),
                StampSpec("good.e{step}.depth", "f", "span via name"))
    findings = check_trace_events([_fixture("good_t008_trace.py")],
                                  root=FIXTURES, registry=registry)
    assert findings == [], [f.render() for f in findings]


def test_unregistered_trace_event_triggers_t008():
    from rnb_tpu.analysis.schema import check_trace_events
    from rnb_tpu.telemetry import StampSpec
    registry = (StampSpec("good.event", "f", "instant"),
                StampSpec("good.gauge", "f", "counter"),
                StampSpec("good.e{step}.depth", "f", "span via name"))
    findings = check_trace_events([_fixture("bad_t008_trace.py")],
                                  root=FIXTURES, registry=registry)
    assert {(f.rule, f.anchor) for f in findings} \
        == {("RNB-T008", "mystery.event")}


def test_dead_trace_registry_entry():
    # a registered trace event no site emits is an RNB-T003 dead entry
    from rnb_tpu.analysis.schema import check_trace_events
    from rnb_tpu.telemetry import StampSpec
    registry = (StampSpec("good.event", "f", "instant"),
                StampSpec("good.gauge", "f", "counter"),
                StampSpec("good.e{step}.depth", "f", "span via name"),
                StampSpec("ghost.event", "nowhere", "never emitted"))
    findings = check_trace_events([_fixture("good_t008_trace.py")],
                                  root=FIXTURES, registry=registry)
    assert {(f.rule, f.anchor) for f in findings} \
        == {("RNB-T003", "ghost.event")}


_T009_REGISTRY = None


def _t009_registry():
    from rnb_tpu.telemetry import MetricSpec
    return (MetricSpec("good.requests", "counter", "site", "f"),
            MetricSpec("good.depth", "gauge", "site", "f"),
            MetricSpec("good.latency", "histogram", "site", "f"),
            MetricSpec("good.arrivals", "rate", "site", "f"),
            MetricSpec("good.e{step}.depth", "gauge", "site", "f"))


def test_metric_fixture_is_clean():
    from rnb_tpu.analysis.schema import check_metric_names
    findings = check_metric_names([_fixture("good_t009_metrics.py")],
                                  root=FIXTURES,
                                  registry=_t009_registry())
    assert findings == [], [f.render() for f in findings]


def test_unregistered_metric_triggers_t009():
    from rnb_tpu.analysis.schema import check_metric_names
    findings = check_metric_names([_fixture("bad_t009_metrics.py")],
                                  root=FIXTURES,
                                  registry=_t009_registry())
    assert {(f.rule, f.anchor) for f in findings} \
        == {("RNB-T009", "mystery.series")}


def test_dead_site_metric_registry_entry():
    # a registered SITE-sourced metric no call site emits is an
    # RNB-T003 dead entry; bridge/poll/derived entries have no call
    # sites by design and must NOT be flagged
    from rnb_tpu.analysis.schema import check_metric_names
    from rnb_tpu.telemetry import MetricSpec
    registry = _t009_registry() + (
        MetricSpec("ghost.series", "counter", "site", "never emitted"),
        MetricSpec("bridged.series", "histogram", "bridge", "no site"),
        MetricSpec("polled.series", "counter", "poll", "no site"),
        MetricSpec("derived.series", "gauge", "derived", "no site"))
    findings = check_metric_names([_fixture("good_t009_metrics.py")],
                                  root=FIXTURES, registry=registry)
    assert {(f.rule, f.anchor) for f in findings} \
        == {("RNB-T003", "ghost.series")}


def _devobs_metric_registry():
    from rnb_tpu.telemetry import MetricSpec
    return (MetricSpec("compute.s{step}.tflops", "gauge", "poll", "f"),
            MetricSpec("compute.s{step}.rows", "counter", "poll", "f"),
            MetricSpec("memory.total_bytes", "gauge", "poll", "f"),
            MetricSpec("memory.cache_bytes", "gauge", "poll", "f"))


def test_devobs_metric_fixture_is_clean():
    # the RNB-T009 family covers the compute.*/memory.* vocabulary:
    # the good fixture emits exactly the declared devobs series
    from rnb_tpu.analysis.schema import check_metric_names
    findings = check_metric_names([_fixture("good_t009_devobs.py")],
                                  root=FIXTURES,
                                  registry=_devobs_metric_registry())
    assert findings == [], [f.render() for f in findings]


def test_unregistered_devobs_metric_triggers_t009():
    from rnb_tpu.analysis.schema import check_metric_names
    findings = check_metric_names([_fixture("bad_t009_devobs.py")],
                                  root=FIXTURES,
                                  registry=_devobs_metric_registry())
    assert {(f.rule, f.anchor) for f in findings} \
        == {("RNB-T009", "compute.s0.mystery")}


def test_repo_metric_names_all_registered():
    # the real tree: every emitted metric series name is declared and
    # every declared site-sourced name is still emitted somewhere
    from rnb_tpu.analysis.findings import package_py_files
    from rnb_tpu.analysis.schema import check_metric_names
    findings = check_metric_names(
        package_py_files(os.path.join(REPO, "rnb_tpu")), root=REPO)
    assert findings == [], [f.render() for f in findings]


def test_repo_trace_events_all_registered():
    # the real tree: every emitted trace event name is declared and
    # every declared name is still emitted somewhere
    from rnb_tpu.analysis.findings import package_py_files
    from rnb_tpu.analysis.schema import check_trace_events
    findings = check_trace_events(
        package_py_files(os.path.join(REPO, "rnb_tpu")), root=REPO)
    assert findings == [], [f.render() for f in findings]


def test_dead_and_unparsed_registry_stamp(tmp_path):
    # a registered stamp nothing records and parse_utils never read:
    # both directions of the cross-check fire
    from rnb_tpu.analysis.schema import check_stamps
    from rnb_tpu.telemetry import STAMP_REGISTRY, StampSpec
    registry = STAMP_REGISTRY + (
        StampSpec("ghost_stamp", "nowhere", "never produced"),)
    findings = check_stamps([_fixture("stamps_registered.py")],
                            _parse_utils_src(), root=FIXTURES,
                            registry=registry)
    assert {(f.rule, f.anchor) for f in findings} == {
        ("RNB-T003", "ghost_stamp"), ("RNB-T002", "ghost_stamp")}


def test_unregistered_meta_line_triggers_t004(tmp_path):
    from rnb_tpu.analysis.schema import check_meta_lines
    bench = tmp_path / "bench_like.py"
    bench.write_text('f.write("Args: %s\\n" % args)\n'
                     'f.write("Termination flag: %d\\n" % flag)\n'
                     'f.write("Faults: num_failed=%d\\n" % n)\n'
                     'f.write("Failure reasons: %s\\n" % r)\n'
                     'f.write("Shed sites: %s\\n" % s)\n'
                     'f.write("Queue overflows: %s\\n" % q)\n'
                     'f.write("Cache: hits=%d\\n" % h)\n'
                     'f.write("Staging: slots=%d\\n" % s)\n'
                     'f.write("Autotune: decisions=%d\\n" % d)\n'
                     'f.write("Autotune buckets: %s\\n" % b)\n'
                     'f.write("Trace: events=%d\\n" % t)\n'
                     'f.write("Phases: %s\\n" % p)\n'
                     'f.write("Ragged: pool_rows=%d\\n" % r)\n'
                     'f.write("Padding: pad_rows=%d\\n" % pd)\n'
                     'f.write("Handoff: edges=%d\\n" % ho)\n'
                     'f.write("Handoff edges: %s\\n" % he)\n'
                     'f.write("Placement: %s\\n" % pl)\n'
                     'f.write("Health: lanes=%d\\n" % hl)\n'
                     'f.write("Health lanes: %s\\n" % hd)\n'
                     'f.write("Deadline: budget_ms=%d\\n" % dl)\n'
                     'f.write("Deadline sites: %s\\n" % ds)\n'
                     'f.write("Hedge: fired=%d\\n" % hg)\n'
                     'f.write("Compiles: %s\\n" % c)\n'
                     'f.write("Warmup: %s\\n" % w)\n'
                     'f.write("Metrics: snapshots=%d\\n" % ms)\n'
                     'f.write("Slo: tracked=%d\\n" % sl)\n'
                     'f.write("Compute: stages=%d\\n" % cp)\n'
                     'f.write("Compute stages: %s\\n" % cs)\n'
                     'f.write("Memory: owners=%d\\n" % mb)\n'
                     'f.write("Memory owners: %s\\n" % mo)\n'
                     'f.write("Critpath: requests=%d\\n" % cr)\n'
                     'f.write("Critpath stages: %s\\n" % ct)\n'
                     'f.write("Whatif: stages=%d\\n" % wi)\n'
                     'f.write("Operator: scrapes=%d\\n" % op)\n'
                     'f.write("Stacks: samples=%d\\n" % st)\n'
                     'f.write("Net: frames_sent=%d\\n" % nt)\n'
                     'f.write("Net errors: total=%d\\n" % ne)\n'
                     'f.write("Pages: allocs=%d\\n" % pg)\n'
                     'f.write("Shard: steps=%d\\n" % sh)\n'
                     'f.write("Shard steps: %s\\n" % ss)\n'
                     'f.write("Locks: tracked=%d\\n" % lk)\n'
                     'f.write("Lock edges: %s\\n" % le)\n'
                     'f.write("Bogus line: %s\\n" % b)\n')
    findings = check_meta_lines(str(bench), _parse_utils_src(),
                                root=str(tmp_path))
    assert {(f.rule, f.anchor) for f in findings} \
        == {("RNB-T004", "Bogus line:")}


def test_unparsed_meta_line_triggers_t005(tmp_path):
    from rnb_tpu.analysis.schema import check_meta_lines
    from rnb_tpu.telemetry import META_LINE_REGISTRY, StampSpec
    bench = tmp_path / "bench_like.py"
    bench.write_text('f.write("Ghost: %s\\n" % g)\n')
    registry = (StampSpec("Ghost:", "here", "written, never parsed"),)
    findings = check_meta_lines(str(bench), "startswith nothing",
                                root=str(tmp_path), registry=registry)
    assert {(f.rule, f.anchor) for f in findings} \
        == {("RNB-T005", "Ghost:")}


#: every key=value counter family a benchmark-like module writes,
#: shared by the RNB-T006 tests below (the devobs lines ride on top)
REPO_BENCH_LIKE = (
        'f.write("Faults: num_failed=%d num_shed=%d num_retries=%d '
        '\\n" % x)\n'
        'f.write("Cache: hits=%d misses=%d inserts=%d evictions=%d '
        'coalesced=%d oversize=%d bytes_resident=%d\\n" % y)\n'
        'f.write("Staging: slots=%d slot_bytes=%d acquires=%d '
        'acquire_waits=%d staged_batches=%d copied_batches=%d '
        'reallocs=%d\\n" % z)\n'
        'f.write("Autotune: decisions=%d immediate=%d held=%d '
        'emissions=%d deadline_us_min=%d deadline_us_max=%d '
        'deadline_us_sum=%d\\n" % w)\n'
        'f.write("Trace: events=%d dropped=%d\\n" % v)\n'
        'f.write("Ragged: pool_rows=%d emissions=%d rows=%d '
        'pad_rows_eliminated=%d cache_hit_rows=%d\\n" % r)\n'
        'f.write("Padding: pad_rows=%d total_rows=%d '
        'pad_emissions=%d\\n" % p)\n'
        'f.write("Handoff: edges=%d d2d_edges=%d host_edges=%d '
        'd2d_bytes=%d host_bytes=%d\\n" % h)\n'
        'f.write("Health: lanes=%d transitions=%d opens=%d '
        'evictions=%d probes=%d redispatches=%d '
        'routes_after_open=%d\\n" % hl)\n'
        'f.write("Deadline: budget_ms=%d expired=%d\\n" % dl)\n'
        'f.write("Hedge: fired=%d won=%d lost=%d wasted_ms=%d\\n" '
        '% hg)\n'
        'f.write("Metrics: snapshots=%d series=%d dumps=%d '
        'triggers=%d\\n" % ms)\n'
        'f.write("Slo: tracked=%d within=%d missed=%d '
        'burn_max_milli=%d\\n" % sl)\n'
        'f.write("Compute: stages=%d dispatches=%d rows=%d '
        'flops_total=%d window_us=%d tflops_milli=%d mfu_e4=%d '
        'captures=%d\\n" % cp)\n'
        'f.write("Memory: owners=%d devices=%d total_bytes=%d '
        'peak_bytes=%d watermark_bytes=%d watermark_hits=%d '
        'live_bytes=%d reconciled=%d\\n" % mm)\n'
        'f.write("Critpath: requests=%d segments=%d '
        'residual_us_max=%d hedged=%d redispatched=%d bound_step=%d '
        'bound_vps_milli=%d\\n" % cr)\n'
        'f.write("Whatif: stages=%d calibrated=%d pred_vps_milli=%d '
        'bottleneck_step=%d\\n" % wi)\n'
        'f.write("Operator: scrapes=%d actions=%d denied=%d '
        'errors=%d\\n" % op)\n'
        'f.write("Stacks: samples=%d threads=%d folded=%d '
        'total=%d\\n" % st)\n'
        'f.write("Net: frames_sent=%d frames_acked=%d '
        'resent_pending=%d resends=%d beats=%d reconnects=%d '
        'remote=%d local=%d dedup_drops=%d dup_arrivals=%d '
        'wire_bytes=%d frame_bytes=%d window_stranded=%d '
        'open_before_timeout=%d\\n" % nt)\n'
        'f.write("Net errors: total=%d refused=%d reset=%d '
        'timeout=%d partial_frame=%d corrupt=%d\\n" % ne)\n'
        'f.write("Shard: steps=%d max_degree=%d gathers=%d '
        'collective_us=%d rows=%d\\n" % sh)\n'
        'f.write("Locks: tracked=%d acquires=%d edges=%d '
        'violations=%d\\n" % lk)\n')


def test_benchmark_result_counter_drift_triggers_t006(tmp_path):
    from rnb_tpu.analysis.schema import check_benchmark_result
    bench = tmp_path / "bench_like.py"
    bench.write_text(REPO_BENCH_LIKE.replace(
        'num_retries=%d \\n', 'num_retries=%d num_bogus=%d\\n'))
    findings = check_benchmark_result(str(bench), root=str(tmp_path))
    assert {(f.rule, f.anchor) for f in findings} \
        == {("RNB-T006", "num_bogus")}


def test_compute_memory_counter_drift_triggers_t006(tmp_path):
    """The RNB-T006 family covers the devobs lines: a Compute:/Memory:
    counter with no BenchmarkResult twin is drift, and a compute_/
    memory_ result field nothing writes is invisible offline."""
    from rnb_tpu.analysis.schema import check_benchmark_result
    bench = tmp_path / "bench_like.py"
    # bogus keys added to both devobs lines on top of the complete
    # legitimate families, so exactly the two bogus fields surface
    src = (REPO_BENCH_LIKE
           .replace('captures=%d\\n', 'captures=%d bogus_flops=%d\\n')
           .replace('reconciled=%d\\n',
                    'reconciled=%d bogus_bytes=%d\\n'))
    bench.write_text(src)
    findings = check_benchmark_result(str(bench), root=str(tmp_path))
    anchors = {f.anchor for f in findings if f.rule == "RNB-T006"}
    assert "compute_bogus_flops" in anchors
    assert "memory_bogus_bytes" in anchors


def test_critpath_whatif_counter_drift_triggers_t006(tmp_path):
    """The RNB-T006 family covers the explanation-plane lines: the
    good fixture (REPO_BENCH_LIKE, which writes the full Critpath:/
    Whatif: counter sets) is clean, and a bogus counter on either
    line surfaces as exactly its drifted field."""
    from rnb_tpu.analysis.schema import check_benchmark_result
    good = tmp_path / "good_bench_like.py"
    good.write_text(REPO_BENCH_LIKE)
    assert check_benchmark_result(str(good), root=str(tmp_path)) == []
    bad = tmp_path / "bad_bench_like.py"
    bad.write_text(REPO_BENCH_LIKE
                   .replace('bound_vps_milli=%d\\n',
                            'bound_vps_milli=%d bogus_chain=%d\\n')
                   .replace('bottleneck_step=%d\\n',
                            'bottleneck_step=%d bogus_pred=%d\\n'))
    findings = check_benchmark_result(str(bad), root=str(tmp_path))
    anchors = {f.anchor for f in findings if f.rule == "RNB-T006"}
    assert "critpath_bogus_chain" in anchors
    assert "whatif_bogus_pred" in anchors


def test_operator_stacks_counter_drift_triggers_t006(tmp_path):
    """The RNB-T006 family covers the operator-plane lines: the good
    fixture (REPO_BENCH_LIKE, which writes the full Operator:/Stacks:
    counter sets) is clean, and a bogus counter on either line
    surfaces as exactly its drifted field."""
    from rnb_tpu.analysis.schema import check_benchmark_result
    good = tmp_path / "good_bench_like.py"
    good.write_text(REPO_BENCH_LIKE)
    assert check_benchmark_result(str(good), root=str(tmp_path)) == []
    bad = tmp_path / "bad_bench_like.py"
    bad.write_text(REPO_BENCH_LIKE
                   .replace('errors=%d\\n',
                            'errors=%d bogus_gets=%d\\n')
                   .replace('total=%d\\n',
                            'total=%d bogus_ticks=%d\\n'))
    findings = check_benchmark_result(str(bad), root=str(tmp_path))
    anchors = {f.anchor for f in findings if f.rule == "RNB-T006"}
    assert "operator_bogus_gets" in anchors
    assert "stacks_bogus_ticks" in anchors


def test_net_counter_drift_triggers_t006(tmp_path):
    """The RNB-T006 family covers the cross-host ingest lines: the
    good fixture (REPO_BENCH_LIKE, which writes the full Net:/Net
    errors: counter sets) is clean — which is also the reverse
    direction, since every net_* BenchmarkResult field must map to a
    written counter for that assert to hold — and a bogus counter on
    either line surfaces as exactly its drifted field."""
    from rnb_tpu.analysis.schema import check_benchmark_result
    good = tmp_path / "good_bench_like.py"
    good.write_text(REPO_BENCH_LIKE)
    assert check_benchmark_result(str(good), root=str(tmp_path)) == []
    bad = tmp_path / "bad_bench_like.py"
    bad.write_text(REPO_BENCH_LIKE
                   .replace('open_before_timeout=%d\\n',
                            'open_before_timeout=%d bogus_frames=%d'
                            '\\n')
                   .replace('partial_frame=%d corrupt=%d\\n',
                            'partial_frame=%d corrupt=%d '
                            'bogus_class=%d\\n'))
    findings = check_benchmark_result(str(bad), root=str(tmp_path))
    anchors = {f.anchor for f in findings if f.rule == "RNB-T006"}
    assert "net_bogus_frames" in anchors
    assert "net_err_bogus_class" in anchors


def test_schema_checker_clean_on_repo():
    from rnb_tpu.analysis.schema import check_repo
    findings = check_repo(REPO)
    assert findings == [], "\n".join(f.render() for f in findings)


# -- concurrency contracts + lock discipline --------------------------

CONCURRENCY_CASES = [
    ("bad_c001_unguarded.py", "RNB-C001"),
    ("bad_c002_role_write.py", "RNB-C002"),
    ("bad_c003_undeclared.py", "RNB-C003"),
    ("bad_c004_cycle.py", "RNB-C004"),
    ("bad_c005_block.py", "RNB-C005"),
]


@pytest.mark.parametrize("name", ["good_c001_guarded.py",
                                  "good_c002_role_read.py",
                                  "good_c003_declared.py",
                                  "good_c004_order.py",
                                  "good_c005_outside.py"])
def test_good_concurrency_fixture_is_clean(name):
    from rnb_tpu.analysis.concurrency import check_file
    findings = check_file(_fixture(name), root=FIXTURES)
    assert findings == [], [f.render() for f in findings]


@pytest.mark.parametrize("name,rule", CONCURRENCY_CASES)
def test_bad_concurrency_fixture_triggers_exactly_its_rule(name, rule):
    from rnb_tpu.analysis.concurrency import check_file
    findings = check_file(_fixture(name), root=FIXTURES)
    assert findings, "expected a %s finding for %s" % (rule, name)
    assert {f.rule for f in findings} == {rule}, \
        "expected only %s, got: %s" % (
            rule, [f.render() for f in findings])


def test_concurrency_checker_clean_on_repo_modulo_baseline():
    """The analyzer over the real package yields nothing beyond the
    justified baseline (the health/hedge/pager/staging/netedge sweep
    is fixed or documented, not ignored)."""
    from rnb_tpu.analysis.concurrency import check_package
    from rnb_tpu.analysis.findings import Baseline, apply_baseline
    findings = check_package(os.path.join(REPO, "rnb_tpu"), root=REPO)
    baseline = Baseline.load(os.path.join(REPO, "rnb-lint-baseline.txt"))
    active, _, _ = apply_baseline(findings, baseline)
    assert active == [], [f.render() for f in active]


def test_static_lock_order_edges_cover_the_cache_pager_nesting():
    """The exported static graph carries the one real cross-class
    nesting the runtime witness will observe: the clip cache takes the
    pager's lock inside its own (acquire/insert_pages page pinning)."""
    from rnb_tpu.analysis.concurrency import static_lock_order_edges
    edges = static_lock_order_edges()
    assert ("ClipCache._lock", "Pager.lock") in edges
    # and the reverse order is never declared — the graph is acyclic
    assert ("Pager.lock", "ClipCache._lock") not in edges


def test_contract_registry_names_the_core_classes():
    from rnb_tpu.analysis.concurrency import contract_registry
    classes = {cls for _, cls, _, _ in contract_registry()}
    for expected in ("ClipCache", "StagingPool", "HedgeGovernor",
                     "LaneHealthBoard", "Pager", "MetricsRegistry"):
        assert expected in classes, expected


def test_rnb_lint_concurrency_family_runs_without_jax(tmp_path):
    """Acceptance: `--family concurrency` must not import jax (the
    analyzer is pure-AST, budgeted at seconds not minutes) — a
    poisoned jax shim on PYTHONPATH proves the import never happens."""
    (tmp_path / "jax.py").write_text(
        'raise AssertionError("the concurrency family imported jax")\n')
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env["PYTHONPATH"] = "%s%s%s" % (tmp_path, os.pathsep,
                                    env.get("PYTHONPATH", ""))
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "rnb_lint.py"),
         "--family", "concurrency"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "OK" in proc.stdout


def test_rnb_lint_stamps_prints_contract_registry():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "rnb_lint.py"),
         "--stamps"],
        capture_output=True, text=True, cwd=REPO, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    for needle in ("guarded by", "ClipCache", "StagingPool"):
        assert needle in proc.stdout


# -- the real CLI over the real repo ----------------------------------

def test_rnb_lint_cli_clean_on_repo_and_shipped_configs():
    """Acceptance: `python scripts/rnb_lint.py` exits 0 on the repo +
    all shipped configs, with no JAX device and no dataset."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("RNB_TPU_DATA_ROOT", None)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "rnb_lint.py")],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=600)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "OK" in proc.stdout


def test_rnb_lint_cli_fails_on_bad_config_with_rule_id():
    """Acceptance: non-zero exit on a bad fixture, naming its rule."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "rnb_lint.py"),
         "--family", "graph",
         "--config", _fixture("bad_g006_buckets.json")],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=600)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "RNB-G006" in proc.stdout


def test_parse_utils_stamps_reference():
    proc = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "scripts", "parse_utils.py"), "--stamps"],
        capture_output=True, text=True, cwd=REPO, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    for needle in ("runner{step}_start", "inference{step}_finish",
                   "Cache:", "# <kind>"):
        assert needle in proc.stdout


def test_baseline_file_parses_and_documents_every_entry():
    from rnb_tpu.analysis.findings import Baseline
    baseline = Baseline.load(os.path.join(REPO, "rnb-lint-baseline.txt"))
    assert not baseline.empty()
    for key, justification in baseline.entries.items():
        assert justification, "baseline entry %r needs a justification" \
            % (key,)
