"""bench.py driver contract: exactly one JSON line on stdout.

The round driver runs ``python bench.py`` and records the single JSON
line; this test pins the schema (metric/value/unit/vs_baseline) and the
exit code using the reduced-geometry config via env overrides.
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_bench_prints_one_json_line(tmp_path):
    env = dict(os.environ)
    env.update({
        "RNB_BENCH_VIDEOS": "6",
        "RNB_BENCH_CONFIG": os.path.join(REPO, "configs",
                                         "r2p1d-tiny.json"),
        "RNB_BENCH_LOG_BASE": str(tmp_path / "logs"),
        "RNB_BENCH_PLATFORM": "cpu",
        "RNB_BENCH_DATASET": "synth",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
    })
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True, text=True, env=env, timeout=600)
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1, "stdout must be exactly one line: %r" % lines
    payload = json.loads(lines[0])
    # the driver contract plus the round-4 evidence keys (p50/p99, clip
    # rate, analytic FLOPs, MFU, decode backend)
    assert set(payload) >= {"metric", "value", "unit", "vs_baseline",
                            "platform", "num_devices", "num_videos",
                            "config", "note", "decode_backend", "p50_ms",
                            "p99_ms", "clips_per_sec", "gflops_per_clip",
                            "tflops", "mfu", "measured_window_s",
                            "device_kind", "devices_used"}
    assert payload["metric"] == "videos_per_sec"
    assert payload["unit"] == "videos/s"
    assert payload["value"] > 0
    # the baseline ratio is only published for real-TPU measurements;
    # this forced-CPU run must refuse the comparison and say why
    assert payload["platform"] == "cpu"
    assert payload["vs_baseline"] is None
    assert "not the TPU plugin" in payload["note"]
    assert payload["num_devices"] >= 1
    assert payload["num_videos"] == 6
    assert payload["config"].endswith("r2p1d-tiny.json")
    assert payload["decode_backend"] == "synthetic"
    assert payload["mfu"] is None  # no spec peak for the CPU backend


def test_bench_y4m_mode_uses_real_decode(tmp_path):
    """Default dataset mode decodes real files: a fresh dataset root is
    populated once and the emitted line says which backend ran."""
    env = dict(os.environ)
    env.update({
        "RNB_BENCH_VIDEOS": "6",
        "RNB_BENCH_CONFIG": os.path.join(REPO, "configs",
                                         "r2p1d-tiny.json"),
        "RNB_BENCH_LOG_BASE": str(tmp_path / "logs"),
        "RNB_BENCH_PLATFORM": "cpu",
        "RNB_TPU_DATA_ROOT": str(tmp_path / "data"),
        "RNB_BENCH_DATASET_LABELS": "2",
        "RNB_BENCH_DATASET_VPL": "4",
        "RNB_BENCH_DATASET_FRAMES": "24",
        "RNB_BENCH_DATASET_SIZE": "64x64",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
    })
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True, text=True, env=env, timeout=900)
    assert proc.returncode == 0, proc.stderr[-2000:]
    payload = json.loads(proc.stdout.strip())
    assert payload["decode_backend"] in ("native-y4m", "numpy-y4m")
    assert payload["value"] > 0
    # the dataset generator ran against the requested root
    found = []
    for _dir, _sub, files in os.walk(str(tmp_path / "data")):
        found += [f for f in files if f.endswith(".y4m")]
    assert len(found) >= 8
