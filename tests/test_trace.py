"""Unified pipeline tracing (rnb_tpu.trace): spans/counters/export,
deterministic phase attribution, trace-off byte-stability, and the
hostprof thread-role dimension.

Unit coverage runs without JAX; the e2e cases drive the tiny test
pipeline (tests.pipeline_helpers) through run_benchmark with the root
``trace`` config key on and off.
"""

import json
import os
import threading

import pytest

from rnb_tpu import trace
from rnb_tpu.trace import (TraceSettings, Tracer, attribute_phases,
                           phase_of, phase_stats, sorted_phases,
                           track_names, validate_trace)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _no_active_tracer():
    """Unit tests must never leak a module-global tracer into later
    tests (benchmark.py owns install/clear in real runs)."""
    trace.ACTIVE = None
    yield
    trace.ACTIVE = None


# -- settings / config validation -------------------------------------

def test_settings_from_config():
    assert TraceSettings.from_config(None) is None
    assert TraceSettings.from_config({"enabled": False}) is None
    s = TraceSettings.from_config({})
    assert s is not None and s.sample_hz == trace.DEFAULT_SAMPLE_HZ \
        and s.max_events == trace.DEFAULT_MAX_EVENTS
    s = TraceSettings.from_config({"sample_hz": 0, "max_events": 7})
    assert s.sample_hz == 0.0 and s.max_events == 7


def _cfg(trace_value):
    return {
        "video_path_iterator":
            "tests.pipeline_helpers.CountingPathIterator",
        "trace": trace_value,
        "pipeline": [
            {"model": "tests.pipeline_helpers.TinyLoader",
             "queue_groups": [{"devices": [0], "out_queues": [0]}],
             "num_shared_tensors": 4},
            {"model": "tests.pipeline_helpers.TinySink",
             "queue_groups": [{"devices": [1], "in_queue": 0}]},
        ],
    }


def test_config_accepts_valid_trace_key():
    from rnb_tpu.config import parse_config
    cfg = parse_config(_cfg({"enabled": True, "sample_hz": 5,
                             "max_events": 1000}))
    assert cfg.trace == {"enabled": True, "sample_hz": 5,
                        "max_events": 1000}


@pytest.mark.parametrize("bad", [
    "yes",                          # not an object
    {"enable": True},               # unknown key
    {"enabled": 1},                 # non-bool enabled
    {"sample_hz": -1},              # negative rate
    {"sample_hz": True},            # bool masquerading as number
    {"max_events": 0},              # cap must be positive
    {"max_events": 2.5},            # cap must be an int
])
def test_config_rejects_bad_trace_key(bad):
    from rnb_tpu.config import ConfigError, parse_config
    with pytest.raises(ConfigError):
        parse_config(_cfg(bad))


# -- collector + export -----------------------------------------------

def test_disabled_module_hooks_are_noops():
    # no tracer installed: span returns the shared null context, the
    # instant/counter hooks return without recording anything
    with trace.span("exec0.queue_get") as s:
        assert s is None
    trace.instant("client.enqueue", rid=1)
    trace.counter("client.enqueued", 1)


def test_tracer_export_valid_and_flow_linked(tmp_path):
    tracer = Tracer(TraceSettings(sample_hz=0))
    trace.ACTIVE = tracer
    with trace.span("exec0.model_call", rid=7):
        pass
    trace.instant("client.enqueue", rid=7)
    trace.instant("client.enqueue", rid=8)  # single-event rid: no flow
    trace.counter("client.enqueued", 2)

    def other_thread():
        with trace.span("exec1.model_call", rid=7):
            pass

    t = threading.Thread(target=other_thread, name="runner-s1-g0-i0")
    t.start()
    t.join()
    path = str(tmp_path / "trace.json")
    written = tracer.export(path, "job-x")
    assert written == tracer.num_events() == 5
    assert validate_trace(path) == []
    with open(path) as f:
        doc = json.load(f)
    assert doc["otherData"]["num_events"] == 5
    assert doc["otherData"]["dropped_events"] == 0
    # rid 7 has 3 correlated events across 2 threads -> one flow chain
    assert doc["otherData"]["num_flows"] == 1
    flows = [ev for ev in doc["traceEvents"] if ev.get("cat") == "request"]
    assert [ev["ph"] for ev in flows] == ["s", "t", "f"]
    assert {ev["id"] for ev in flows} == {7}
    # one named track per thread role
    assert "runner-s1-g0-i0" in track_names(path)
    # every non-meta event carries ts/tid/ph; spans carry dur
    for ev in doc["traceEvents"]:
        for key in ("ph", "ts", "tid", "pid"):
            assert key in ev
        if ev["ph"] == "X":
            assert ev["dur"] >= 0


def test_max_events_cap_counts_drops(tmp_path):
    tracer = Tracer(TraceSettings(max_events=3, sample_hz=0))
    trace.ACTIVE = tracer
    for i in range(10):
        trace.instant("client.enqueue", rid=i)
    assert tracer.num_events() == 3
    assert tracer.dropped == 7
    path = str(tmp_path / "trace.json")
    tracer.export(path, "job-cap")
    with open(path) as f:
        doc = json.load(f)
    assert doc["otherData"]["dropped_events"] == 7


def test_sampler_polls_counter_sources(tmp_path):
    tracer = Tracer(TraceSettings(sample_hz=200))
    tracer.add_counter_source("queue.e0.depth", lambda: 3)
    tracer.add_counter_source("queue.e1.depth",
                              lambda: (_ for _ in ()).throw(
                                  RuntimeError("dying probe")))
    tracer.start_sampler()
    import time
    deadline = time.monotonic() + 2.0
    while tracer.num_events() < 2 and time.monotonic() < deadline:
        time.sleep(0.01)
    tracer.stop_sampler()
    assert tracer.num_events() >= 2  # dying probe killed neither loop
    path = str(tmp_path / "trace.json")
    tracer.export(path, "job-s")
    with open(path) as f:
        doc = json.load(f)
    counters = [ev for ev in doc["traceEvents"]
                if ev.get("ph") == "C"]
    assert counters and all(ev["name"] == "queue.e0.depth"
                            and ev["args"]["value"] == 3
                            for ev in counters)


def test_validate_trace_reports_structural_problems(tmp_path):
    path = str(tmp_path / "bad.json")
    with open(path, "w") as f:
        json.dump({"traceEvents": [
            {"name": "x", "ph": "X", "pid": 1, "tid": 1, "ts": 0},
            {"name": "request", "ph": "s", "id": 4, "pid": 1,
             "tid": 1, "ts": 0},
        ]}, f)
    problems = validate_trace(path)
    assert any("dur" in p for p in problems)
    assert any("flow id 4" in p for p in problems)
    assert validate_trace(str(tmp_path / "missing.json"))


# -- deterministic phase attribution ----------------------------------

def test_phase_of_classification():
    assert phase_of("enqueue_filename", "runner0_start") == "client_queue"
    assert phase_of("runner0_start", "inference0_start") == "client_queue"
    assert phase_of("inference0_start", "decode0_done") == "decode"
    assert phase_of("decode0_done", "transfer0_start") == "hold"
    assert phase_of("transfer0_start", "transfer0_done") == "transfer"
    assert phase_of("transfer0_done", "inference0_finish") == "drain"
    assert phase_of("inference0_finish", "runner1_start") \
        == "inter_stage_queue"
    assert phase_of("runner1_start", "inference1_start") \
        == "inter_stage_queue"
    assert phase_of("inference1_start", "inference1_finish") \
        == "inference1"
    # un-refined past logs: the whole loader span reports as decode
    assert phase_of("inference0_start", "inference0_finish") == "decode"
    # merged segment cards: the -{sub_id} suffix is ignored
    assert phase_of("inference1_start-0", "inference1_finish-0") \
        == "inference1"


def test_attribute_phases_partitions_end_to_end():
    t0 = 1000.0
    timings = {
        "enqueue_filename": t0,
        "runner0_start": t0 + 0.010,
        "inference0_start": t0 + 0.011,
        "decode0_done": t0 + 0.020,
        "transfer0_start": t0 + 0.024,
        "transfer0_done": t0 + 0.030,
        "inference0_finish": t0 + 0.031,
        "runner1_start": t0 + 0.033,
        "inference1_start": t0 + 0.034,
        "inference1_finish": t0 + 0.040,
    }
    phases = attribute_phases(timings)
    assert phases["decode"] == pytest.approx(9.0, abs=1e-6)
    assert phases["hold"] == pytest.approx(4.0, abs=1e-6)
    assert phases["transfer"] == pytest.approx(6.0, abs=1e-6)
    assert phases["drain"] == pytest.approx(1.0, abs=1e-6)
    assert phases["inference1"] == pytest.approx(6.0, abs=1e-6)
    assert sum(phases.values()) == pytest.approx(40.0, abs=1e-6)
    # deterministic: same stamps -> same decomposition, dict order
    # irrelevant (attribution sorts by time)
    shuffled = dict(reversed(list(timings.items())))
    assert attribute_phases(shuffled) == phases


def test_attribute_phases_drops_nans_and_handles_tiny_cards():
    assert attribute_phases({}) == {}
    assert attribute_phases({"enqueue_filename": 1.0}) == {}
    phases = attribute_phases({"enqueue_filename": 1.0,
                               "runner0_start": float("nan"),
                               "inference0_finish": 1.5})
    assert phases == {"decode": pytest.approx(500.0)}


def test_phase_stats_and_sort_order():
    stats = phase_stats({"inference1": [2.0, 4.0], "decode": [1.0],
                         "client_queue": [0.5], "empty": []})
    assert "empty" not in stats
    assert stats["inference1"]["mean_ms"] == pytest.approx(3.0)
    assert stats["inference1"]["count"] == 2
    assert sorted_phases(stats) == ["client_queue", "decode",
                                    "inference1"]


def test_record_clamped_keeps_cards_time_ordered():
    from rnb_tpu.models.r2p1d.model import _record_clamped
    from rnb_tpu.telemetry import TimeCard
    tc = TimeCard(1)
    tc.record("inference0_start", at=100.0)
    _record_clamped(tc, "decode0_done", 99.0)  # earlier: clamps to 100
    _record_clamped(tc, "transfer0_start", 100.5)
    assert tc.timings["decode0_done"] == 100.0
    assert tc.timings["transfer0_start"] == 100.5
    assert attribute_phases(tc.timings)["decode"] == 0.0


# -- e2e: traced and un-traced tiny pipeline runs ----------------------

def _run(tmp_path, name, trace_value, videos=30, interval_ms=1):
    from rnb_tpu.benchmark import run_benchmark
    cfg = _cfg(trace_value)
    if trace_value is None:
        del cfg["trace"]
    path = os.path.join(str(tmp_path), "%s.json" % name)
    with open(path, "w") as f:
        json.dump(cfg, f)
    return run_benchmark(path, mean_interval_ms=interval_ms,
                         num_videos=videos, queue_size=50,
                         log_base=os.path.join(str(tmp_path),
                                               "logs-%s" % name),
                         print_progress=False)


def test_traced_run_end_to_end(tmp_path):
    res = _run(tmp_path, "traced",
               {"enabled": True, "sample_hz": 200, "max_events": 50000})
    assert res.termination_flag == 0
    assert res.trace_events > 0 and res.trace_dropped == 0
    # the tracer is cleared after export: nothing leaks into later runs
    assert trace.ACTIVE is None

    trace_path = os.path.join(res.log_dir, "trace.json")
    assert os.path.isfile(trace_path)
    assert validate_trace(trace_path) == []
    # distinct thread-role tracks: client + one executor per stage
    tracks = set(track_names(trace_path))
    assert {"client", "runner-s0-g0-i0", "runner-s1-g0-i0"} <= tracks
    with open(trace_path) as f:
        doc = json.load(f)
    names = {ev.get("name") for ev in doc["traceEvents"]}
    # deterministic event vocabulary for this topology
    assert {"client.enqueue", "client.enqueued", "exec0.model_call",
            "exec1.model_call", "exec0.publish"} <= names
    # sampled counter tracks (inter-stage queue + client queue): the
    # 1 ms Poisson client keeps the run alive >= a few sampler ticks
    assert {"queue.filename.depth", "queue.e0.depth"} <= names
    # flow-linked request chains across stages
    assert any(ev.get("ph") == "s" and ev.get("cat") == "request"
               for ev in doc["traceEvents"])

    # per-request attribution surfaced everywhere
    assert res.phases and "client_queue" in res.phases
    total = sum(s["mean_ms"] for s in res.phases.values())
    with open(os.path.join(res.log_dir, "log-meta.txt")) as f:
        meta_text = f.read()
    assert "Trace: events=%d dropped=0\n" % res.trace_events in meta_text
    assert "Phases: " in meta_text
    tables = [n for n in os.listdir(res.log_dir) if "group" in n]
    assert tables
    with open(os.path.join(res.log_dir, tables[0])) as f:
        report = f.read()
    assert "# phases n=" in report

    # offline tooling agrees with the online summaries
    import sys
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        import parse_utils
        assert parse_utils.check_job(res.log_dir) == []
        stats = parse_utils.attribute_job(res.log_dir)
        assert set(stats) == set(res.phases)
        for phase in stats:
            assert stats[phase]["mean_ms"] == pytest.approx(
                res.phases[phase]["mean_ms"], abs=1e-6)
        # mean phase components sum to the mean end-to-end latency
        assert total == pytest.approx(
            sum(s["mean_ms"] for s in stats.values()), abs=1e-6)
        assert parse_utils.print_attribution(
            res.log_dir, out=open(os.devnull, "w")) == 0
    finally:
        sys.path.remove(os.path.join(REPO, "scripts"))


def test_untraced_run_stays_byte_stable(tmp_path):
    res = _run(tmp_path, "plain", None)
    assert res.termination_flag == 0
    assert res.trace_events == 0 and res.trace_dropped == 0
    assert res.phases == {}
    assert not os.path.isfile(os.path.join(res.log_dir, "trace.json"))
    with open(os.path.join(res.log_dir, "log-meta.txt")) as f:
        meta_text = f.read()
    assert "Trace:" not in meta_text and "Phases:" not in meta_text
    tables = [n for n in os.listdir(res.log_dir) if "group" in n]
    with open(os.path.join(res.log_dir, tables[0])) as f:
        report = f.read()
    assert "# phases" not in report
    # the stamp schema is exactly the pre-trace set: no refinement
    # columns leak into untraced tables
    header = report.split("\n", 1)[0].split()
    assert header == ["enqueue_filename", "runner0_start",
                      "inference0_start", "inference0_finish",
                      "runner1_start", "inference1_start",
                      "inference1_finish", "device0", "device1"]


def test_trace_overhead_is_bounded(tmp_path):
    # guard, not a benchmark: a traced bulk run of the tiny pipeline
    # must complete promptly (the disabled path is separately pinned
    # to a single None test by rnb-lint's hot-path discipline)
    import time
    t0 = time.monotonic()
    res = _run(tmp_path, "overhead",
               {"enabled": True, "sample_hz": 20}, videos=50,
               interval_ms=0)
    assert res.termination_flag == 0
    assert time.monotonic() - t0 < 60.0


# -- hostprof thread-role dimension (satellite) ------------------------

def test_hostprof_role_split_and_rollup():
    from rnb_tpu import hostprof
    hostprof.reset()
    try:
        hostprof.add("loader.cache_insert", 0.5, role="runner-s0-g0-i0")
        hostprof.add("loader.cache_insert", 0.25, role="rnb-transfer")
        hostprof.add("loader.cache_insert", 0.25, role="rnb-transfer")
        hostprof.add("exec0.queue_get", 1.0, role="runner-s0-g0-i0")
        # role-less view folds roles per section (historical schema)
        snap = hostprof.snapshot()
        assert snap["loader.cache_insert"] == (1.0, 3)
        by_role = hostprof.snapshot_by_role()
        assert by_role[("loader.cache_insert", "rnb-transfer")] \
            == (0.5, 2)
        assert hostprof.totals("loader.") == (1.0, 3)
        assert hostprof.totals("loader.", role="rnb-transfer") \
            == (0.5, 2)
        lines = hostprof.report_lines(10.0)
        text = "\n".join(lines)
        # the multi-role section gets per-role breakdown rows; the
        # single-role one does not
        assert "loader.cache_insert @rnb-transfer" in text
        assert "exec0.queue_get @" not in text
    finally:
        hostprof.reset()


def test_hostprof_add_defaults_to_current_thread_name():
    from rnb_tpu import hostprof
    hostprof.reset()
    try:
        result = {}

        def work():
            hostprof.add("loader.emit_wait", 0.125)

        t = threading.Thread(target=work, name="runner-s9-g0-i0")
        t.start()
        t.join()
        assert hostprof.snapshot_by_role()[
            ("loader.emit_wait", "runner-s9-g0-i0")] == (0.125, 1)
    finally:
        hostprof.reset()
