"""R2P1DFusingLoader: loader-side dynamic batching.

Contract: every request is submitted to the decode pool on receipt;
completed decodes are harvested FIFO and emitted as one fused padded
batch with a TimeCardList; partial batches emit when nothing is in
flight, on hold-timeout, or at end-of-stream (flush). Backpressure
blocks on the oldest decode once `depth` requests are pending.
"""

import os

import numpy as np
import pytest

from rnb_tpu.decode import write_y4m
from rnb_tpu.telemetry import TimeCard, TimeCardList


def _dataset(tmp_path, n=12, frames=40, h=64, w=96):
    rng = np.random.default_rng(5)
    paths = []
    for i in range(n):
        p = os.path.join(str(tmp_path), "v%02d.y4m" % i)
        write_y4m(p, rng.integers(0, 256, (frames, h, w, 3),
                                  dtype=np.uint8))
        paths.append(p)
    return paths


def _loader(fuse=3, **kw):
    import jax
    from rnb_tpu.models.r2p1d.model import R2P1DFusingLoader
    kw.setdefault("num_clips_population", [1])
    kw.setdefault("weights", [1])
    kw.setdefault("num_warmups", 0)
    return R2P1DFusingLoader(jax.devices()[0], fuse=fuse, **kw)


def test_fuses_to_target(tmp_path):
    paths = _dataset(tmp_path)
    loader = _loader(fuse=3, max_hold_ms=10000.0, depth=50)
    emitted = []
    for i, p in enumerate(paths[:9]):
        out = loader(None, p, TimeCard(i))
        if out[2] is not None:
            emitted.append(out)
    # 9 requests x 1 clip, fuse=3 -> 3 fused batches once decodes land
    # (timing-dependent: the early calls may swallow while decodes run,
    # so drain the rest through flush and count totals)
    while True:
        out = loader.flush()
        if out is None:
            break
        emitted.append(out)
    total = sum(len(tc) for _, _, tc in emitted)
    assert total == 9
    for (pb,), _, cards in emitted:
        assert isinstance(cards, TimeCardList)
        assert pb.valid == len(cards)  # 1 clip per request here
        assert pb.data.shape[0] in (3, 6, 15)  # row buckets or max


def test_emit_partial_when_idle(tmp_path):
    """The nothing-in-flight rule: once decode catches up and no later
    request is pending, a sub-fuse batch must emit rather than wait
    for a fill that may never come. Driven through poll() (the
    executor's idle tick) so the assertion does not depend on decode
    finishing faster than the next submit."""
    import time
    paths = _dataset(tmp_path, n=2)
    loader = _loader(fuse=5, max_hold_ms=10000.0)
    got = 0
    for i, p in enumerate(paths):
        out = loader(None, p, TimeCard(i))
        if out[2] is not None:
            got += len(out[2])
    deadline = time.time() + 10
    while got < 2 and time.time() < deadline:
        time.sleep(0.01)
        out = loader.poll()  # fires the nothing-in-flight rule
        if out is not None and out[2] is not None:
            got += len(out[2])
    assert got == 2
    assert loader.flush() is None


def test_flush_drains_everything(tmp_path):
    paths = _dataset(tmp_path, n=7)
    loader = _loader(fuse=100, max_hold_ms=1e9, depth=100)
    seen = 0
    for i, p in enumerate(paths):
        out = loader(None, p, TimeCard(i))
        if out[2] is not None:
            # "nothing in flight" emissions are legal mid-stream when
            # decode outruns arrivals — count them too
            seen += len(out[2])
    while True:
        out = loader.flush()
        if out is None:
            break
        seen += len(out[2])
    assert seen == 7
    assert loader.flush() is None


def test_backpressure_blocks_and_emits(tmp_path):
    paths = _dataset(tmp_path, n=6)
    loader = _loader(fuse=100, max_hold_ms=1e9, depth=2)
    emitted = []
    for i, p in enumerate(paths):
        out = loader(None, p, TimeCard(i))
        if out[2] is not None:
            emitted.append(out)
    # depth=2: by request 3 the loader must start retiring decodes
    assert emitted, "backpressure never forced an emission"
    total = sum(len(tc) for _, _, tc in emitted)
    while True:
        out = loader.flush()
        if out is None:
            break
        total += len(out[2])
    assert total == 6


def test_idle_poll_emits_on_hold_timeout(tmp_path):
    """The executor's idle tick must release a held batch once
    max_hold_ms expires — without waiting for the next arrival."""
    import time
    paths = _dataset(tmp_path, n=3)
    loader = _loader(fuse=100, max_hold_ms=30.0, depth=100)
    got = 0
    for i, p in enumerate(paths[:2]):
        out = loader(None, p, TimeCard(i))
        if out[2] is not None:
            got += len(out[2])
    # no further arrivals: only the executor's idle poll can release
    # what is still held — it must fire within ~max_hold_ms
    deadline = time.time() + 10
    while got < 2 and time.time() < deadline:
        time.sleep(0.01)
        out = loader.poll()
        if out is not None and out[2] is not None:
            got += len(out[2])
    assert got == 2
    assert loader.flush() is None


def test_next_deadline_drives_poll_timeout(tmp_path):
    """The stage's deadline hook and the executor's timeout clamp: a
    held batch's hold expiry must shrink the queue-poll window (the
    round-5 frontier measured the fixed 50 ms poll as the light-load
    p99 floor)."""
    import time

    from rnb_tpu.runner import MIN_POLL_S, QUEUE_POLL_S, poll_timeout
    paths = _dataset(tmp_path, n=3)
    loader = _loader(fuse=100, max_hold_ms=30.0, depth=100)
    assert loader.next_deadline_s() is None  # no work held
    assert poll_timeout(loader) == QUEUE_POLL_S
    out = loader(None, paths[0], TimeCard(0))
    if out[2] is None:  # swallowed (the usual case: decode in flight)
        # decode in flight or already ready: the deadline must be at
        # most the harvest tick / the remaining hold — far below the
        # 50 ms poll
        deadline = loader.next_deadline_s()
        assert deadline is not None and deadline <= 0.031
        assert MIN_POLL_S <= poll_timeout(loader) <= 0.031
        # once the decode lands and the hold expires, the deadline
        # collapses to zero (generous cap: slow CI host)
        cap = time.time() + 10
        while loader.next_deadline_s() != 0.0 and time.time() < cap:
            time.sleep(0.005)
        assert loader.next_deadline_s() == 0.0
        assert poll_timeout(loader) == MIN_POLL_S
        assert loader.poll() is not None  # and the poll emits
    assert loader.next_deadline_s() is None
    # stages without the hook keep the coarse default
    assert poll_timeout(object()) == QUEUE_POLL_S


def test_discard_pending_retires_all_tickets(tmp_path):
    """Abort path: every submitted decode (in flight AND harvested but
    unemitted) must be retired so the shared pool pins no buffers."""
    from rnb_tpu.decode.native import DecodePool, native_available
    if not native_available():
        pytest.skip("native decoder not built")
    paths = _dataset(tmp_path, n=4)
    loader = _loader(fuse=100, max_hold_ms=1e9, depth=100)
    for i, p in enumerate(paths):
        out = loader(None, p, TimeCard(i))
        assert out[2] is None or len(out[2])  # swallow or emit
    loader._harvest()  # some land in _ready with live tickets
    loader.discard_pending()
    assert not loader._inflight and not loader._ready
    assert not DecodePool.shared()._pending


def test_rejects_prefetch_kwarg():
    import jax
    from rnb_tpu.models.r2p1d.model import R2P1DFusingLoader
    with pytest.raises(ValueError):
        R2P1DFusingLoader(jax.devices()[0], prefetch=4, num_warmups=0)


def test_drain_survives_more_batches_than_exit_markers(tmp_path):
    """EOS drain regression: a stage holding MORE pending batches than
    NUM_EXIT_MARKERS must still complete every request. The old drain
    consumed one exit marker per flush() emission and broke the hot
    loop after the first, stranding the tail (UNSET termination).
    Driven with a deterministic hoarding stage that swallows every item
    and releases exactly one per flush() call."""
    import json

    from rnb_tpu.benchmark import run_benchmark
    from rnb_tpu.control import NUM_EXIT_MARKERS, TerminationFlag

    n = NUM_EXIT_MARKERS + 5  # strictly more flushes than markers
    cfg = {
        "video_path_iterator":
            "tests.pipeline_helpers.CountingPathIterator",
        "pipeline": [
            {"model": "tests.pipeline_helpers.HoardingSink",
             "queue_groups": [{"devices": [-1]}]},
        ],
    }
    cfg_path = os.path.join(str(tmp_path), "drain.json")
    with open(cfg_path, "w") as f:
        json.dump(cfg, f)
    res = run_benchmark(cfg_path, mean_interval_ms=0, num_videos=n,
                        log_base=os.path.join(str(tmp_path), "logs"),
                        print_progress=False)
    assert res.termination_flag == \
        TerminationFlag.TARGET_NUM_VIDEOS_REACHED
    # completion-derived evidence (BenchmarkResult.num_videos merely
    # echoes the request): every held card was registered at drain
    assert res.clips_completed == n


def test_fused_pipeline_end_to_end(tmp_path):
    """Client -> FusingLoader -> net through the real runtime."""
    import json

    from rnb_tpu.benchmark import run_benchmark
    from rnb_tpu.control import TerminationFlag
    from rnb_tpu.models.r2p1d import checkpoint as ckpt

    root = os.path.join(str(tmp_path), "data")
    os.makedirs(os.path.join(root, "label0"))
    rng = np.random.default_rng(0)
    for i in range(4):
        write_y4m(os.path.join(root, "label0", "v%d.y4m" % i),
                  rng.integers(0, 256, (30, 64, 64, 3), dtype=np.uint8))
    os.environ["RNB_TPU_DATA_ROOT"] = root
    try:
        ckpt_path = os.path.join(str(tmp_path), "tiny.msgpack")
        ckpt.save_checkpoint(ckpt_path, ckpt.init_variables(
            seed=1, num_classes=8, layer_sizes=(1, 1, 1, 1)))
        cfg = {
            "video_path_iterator":
                "rnb_tpu.models.r2p1d.model.R2P1DVideoPathIterator",
            "pipeline": [
                {"model":
                    "rnb_tpu.models.r2p1d.model.R2P1DFusingLoader",
                 "queue_groups": [{"devices": [0], "out_queues": [0]}],
                 "num_shared_tensors": 10,
                 "fuse": 2, "max_clips": 4,
                 "num_clips_population": [2], "weights": [1],
                 "consecutive_frames": 2, "num_warmups": 0,
                 "pixel_path": "yuv420"},
                {"model": "rnb_tpu.models.r2p1d.model.R2P1DRunner",
                 "queue_groups": [{"devices": [0], "in_queue": 0}],
                 "start_index": 1, "end_index": 5, "num_classes": 8,
                 "layer_sizes": [1, 1, 1, 1], "max_rows": 4,
                 "consecutive_frames": 2, "num_warmups": 0,
                 "ckpt_path": ckpt_path, "pixel_path": "yuv420"},
            ],
        }
        cfg_path = os.path.join(str(tmp_path), "fused.json")
        with open(cfg_path, "w") as f:
            json.dump(cfg, f)
        res = run_benchmark(cfg_path, mean_interval_ms=0, num_videos=9,
                            log_base=os.path.join(str(tmp_path), "logs"),
                            print_progress=False)
        assert res.termination_flag == \
            TerminationFlag.TARGET_NUM_VIDEOS_REACHED
        assert res.num_videos == 9
    finally:
        os.environ.pop("RNB_TPU_DATA_ROOT", None)


def test_wide_caps_bucket_and_conserve(tmp_path):
    """Wide-dispatch caps (configs/rnb-fused-yuv-big/-mid): fused rows
    never exceed max_clips, every emission pads to the smallest bucket
    that fits, and no request/clip is lost. Emission *sizes* here are
    timing-dependent (decode may outrun the submit loop and trigger
    nothing-in-flight partial emits), so this test asserts only the
    invariants that hold for every emission; the deterministic
    per-size cases live in test_flush_take_hits_exact_buckets."""
    paths = _dataset(tmp_path, n=15)
    loader = _loader(fuse=12, max_hold_ms=1e9, depth=100,
                     max_clips=36, row_buckets=[6, 15, 24, 36],
                     num_clips_population=[3], weights=[1])
    emitted = []
    for i, p in enumerate(paths):
        out = loader(None, p, TimeCard(i))
        if out[2] is not None:
            emitted.append(out)
    while True:
        out = loader.flush()
        if out is None:
            break
        emitted.append(out)
    total_reqs = sum(len(tc) for _, _, tc in emitted)
    total_rows = sum(pb.valid for (pb,), _, tc in emitted)
    assert total_reqs == 15
    assert total_rows == 45  # 15 requests x 3 clips, none lost
    for (pb,), _, cards in emitted:
        assert pb.valid <= 36  # cap respected
        assert pb.data.shape[0] in (6, 15, 24, 36)  # a real bucket
        # smallest bucket that fits the valid rows — no over-padding
        fitting = [b for b in (6, 15, 24, 36) if b >= pb.valid]
        assert pb.data.shape[0] == fitting[0], (pb.valid,
                                                pb.data.shape[0])


def test_flush_take_hits_exact_buckets(tmp_path):
    """Deterministic bucket selection for wide caps. Submits bypass
    __call__ (whose poll can emit early whenever decode outruns the
    loop) and go straight into the in-flight window, so flush() —
    which retires every decode, then takes exactly ``fuse`` requests
    per call — produces known emission sizes. The case this pins: a
    24-row fusion must ship the 24-row bucket, not the 36-row cap."""
    paths = _dataset(tmp_path, n=15)
    for fuse, want in ((8, [(24, 24), (21, 24)]),
                       (12, [(36, 36), (9, 15)])):
        loader = _loader(fuse=fuse, max_hold_ms=1e9, depth=100,
                         max_clips=36, row_buckets=[6, 15, 24, 36],
                         num_clips_population=[3], weights=[1])
        from rnb_tpu.models.r2p1d.model import _FuseRecord
        for i, p in enumerate(paths):
            tc = TimeCard(i)
            handle = loader.submit(p, tc)
            loader._inflight.append(_FuseRecord(handle, p, tc))
        got = []
        while True:
            out = loader.flush()
            if out is None:
                break
            (pb,), _, cards = out
            got.append((pb.valid, pb.data.shape[0]))
        # fuse=8: takes of 8, 7(=15-8) requests x 3 clips + remainder
        # rows 24->bucket 24 (NOT 36), 21->24, 9->15
        assert got == want, (fuse, got)
