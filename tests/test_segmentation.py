"""Segment splitting arithmetic + the content-routed/batched topology."""

import json
import os

import numpy as np

from rnb_tpu.benchmark import run_benchmark
from rnb_tpu.control import TerminationFlag
from rnb_tpu.runner import split_segments
from rnb_tpu.stage import PaddedBatch


def _pb(valid, max_rows=15, features=4):
    data = np.zeros((max_rows, features), np.float32)
    data[:valid] = np.arange(1, valid + 1, dtype=np.float32)[:, None]
    return PaddedBatch(data, valid)


def test_split_remainder_from_front():
    # 11 valid rows over 3 segments -> 4, 4, 3 (reference runner.py:140-154)
    segs = split_segments((_pb(11, max_rows=15),), 3)
    assert [s[0].valid for s in segs] == [4, 4, 3]
    # segment max rows = ceil(15/3) = 5
    assert all(s[0].data.shape == (5, 4) for s in segs)
    # values partition in order: rows 1..4 | 5..8 | 9..11
    np.testing.assert_array_equal(np.asarray(segs[0][0].valid_data())[:, 0],
                                  [1, 2, 3, 4])
    np.testing.assert_array_equal(np.asarray(segs[1][0].valid_data())[:, 0],
                                  [5, 6, 7, 8])
    np.testing.assert_array_equal(np.asarray(segs[2][0].valid_data())[:, 0],
                                  [9, 10, 11])
    # padding rows are zero
    np.testing.assert_array_equal(np.asarray(segs[2][0].data)[3:],
                                  np.zeros((2, 4), np.float32))


def test_split_fewer_rows_than_segments():
    segs = split_segments((_pb(1, max_rows=6),), 3)
    assert [s[0].valid for s in segs] == [1, 0, 0]
    assert all(s[0].data.shape == (2, 4) for s in segs)


def test_split_single_segment_identity():
    pb = _pb(5)
    [seg] = split_segments((pb,), 1)
    assert seg[0] is pb


def test_split_multiple_tensors_independent():
    a, b = _pb(6, max_rows=6), _pb(3, max_rows=9)
    segs = split_segments((a, b), 3)
    assert [s[0].valid for s in segs] == [2, 2, 2]
    assert [s[1].valid for s in segs] == [1, 1, 1]
    assert segs[0][0].data.shape == (2, 4)
    assert segs[0][1].data.shape == (3, 4)


def test_rnb_topology_routing_and_batching(tmp_path):
    """The rnb.json idea on tiny stages: LargeSmall routing into a
    batched small lane + passthrough large lane, re-merging downstream
    (reference config/rnb.json)."""
    cfg = {
        "video_path_iterator": "tests.pipeline_helpers.CountingPathIterator",
        "pipeline": [
            {"model": "tests.pipeline_helpers.TinyRoutedLoader",
             "queue_groups": [
                 {"devices": [0, 1], "out_queues": [0, 1],
                  "queue_selector":
                      "rnb_tpu.models.r2p1d.model.LargeSmallSelector"}],
             "num_shared_tensors": 10, "rows_per_video": 1},
            {"model": "rnb_tpu.batcher.Batcher",
             "queue_groups": [
                 {"devices": [2], "in_queue": 0, "out_queues": [0],
                  "batch": 3},
                 {"devices": [3], "in_queue": 1, "out_queues": [0]}],
             "num_shared_tensors": 10, "shapes": [[4, 2]]},
            {"model": "tests.pipeline_helpers.TinySink",
             "queue_groups": [{"devices": [-1], "in_queue": 0}]},
        ],
    }
    path = os.path.join(str(tmp_path), "rnb.json")
    with open(path, "w") as f:
        json.dump(cfg, f)
    res = run_benchmark(path, mean_interval_ms=1, num_videos=16,
                        queue_size=200, log_base=str(tmp_path / "logs"),
                        print_progress=False)
    assert res.termination_flag == TerminationFlag.TARGET_NUM_VIDEOS_REACHED
    # the fused lane produces TimeCardLists; every constituent request
    # is counted, so the target is reachable only if batching + routing
    # both worked
    reports = [f for f in os.listdir(res.log_dir) if "group" in f]
    assert len(reports) == 1


def test_rnb_topology_flushes_partial_batch_at_eos(tmp_path):
    """num_videos not divisible by the batch size must still complete:
    the executor flushes the batcher's partial batch on the exit marker
    (the reference's batcher stranded those requests)."""
    cfg = {
        "video_path_iterator": "tests.pipeline_helpers.CountingPathIterator",
        "pipeline": [
            {"model": "tests.pipeline_helpers.TinyLoader",
             "queue_groups": [{"devices": [0], "out_queues": [0]}],
             "num_shared_tensors": 10, "rows_per_video": 1},
            {"model": "rnb_tpu.batcher.Batcher",
             "queue_groups": [
                 {"devices": [1], "in_queue": 0, "out_queues": [0],
                  "batch": 4}],
             "num_shared_tensors": 10, "shapes": [[4, 2]]},
            {"model": "tests.pipeline_helpers.TinySink",
             "queue_groups": [{"devices": [-1], "in_queue": 0}]},
        ],
    }
    path = os.path.join(str(tmp_path), "rnb-flush.json")
    with open(path, "w") as f:
        json.dump(cfg, f)
    # 10 % 4 == 2: without the flush the last 2 requests never complete
    res = run_benchmark(path, mean_interval_ms=0, num_videos=10,
                        queue_size=100, log_base=str(tmp_path / "logs"),
                        print_progress=False)
    assert res.termination_flag == TerminationFlag.TARGET_NUM_VIDEOS_REACHED
