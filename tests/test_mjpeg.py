"""MJPEG (baseline JPEG) decode: native C++ decoder vs the PIL oracle.

The native decoder (native/decode.cpp) implements baseline JPEG from
the spec — Huffman, dequant, IDCT, 4:2:0/4:4:4 — with no libjpeg. PIL
(libjpeg) writes the fixtures and serves as the independent oracle:
luma must match within IDCT rounding (+-2), 4:4:4 RGB within
conversion rounding, and smooth-content round trips within
quantization error. This is the compressed-decode capability the
reference got from NVVL/NVDEC (reference README.md:42-110, consumed at
models/r2p1d/model.py:123-145).
"""

import io
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts"))

from make_dataset import synth_frames  # noqa: E402

from rnb_tpu.decode import (MjpegPILDecoder, get_decoder,  # noqa: E402
                            scan_mjpeg_frames, write_mjpeg)
from rnb_tpu.decode.native import (NativeY4MDecoder,  # noqa: E402
                                   native_available)

# only tests that touch the C++ decoder need the build — the PIL
# fallback/dispatch/iterator tests must keep running without it (that
# no-native configuration is exactly what the fallback exists for)
needs_native = pytest.mark.skipif(not native_available(),
                                  reason="native library not built")

H, W = 64, 96


@pytest.fixture(scope="module")
def mjpg(tmp_path_factory):
    frames = synth_frames(6, H, W, seed=[5, 1, 2])
    path = str(tmp_path_factory.mktemp("mjpeg") / "v.mjpg")
    write_mjpeg(path, frames, quality=90)
    return path, frames


def _pil_ycbcr(path, idx):
    from PIL import Image
    with open(path, "rb") as f:
        data = f.read()
    off, length = scan_mjpeg_frames(data)[idx]
    with Image.open(io.BytesIO(data[off:off + length])) as im:
        im.draft("YCbCr", im.size)
        return np.asarray(im.convert("YCbCr"))


@needs_native
def test_probe_and_frame_index(mjpg):
    path, frames = mjpg
    nd = NativeY4MDecoder()
    assert nd.num_frames(path) == len(frames)
    with open(path, "rb") as f:
        scanned = scan_mjpeg_frames(f.read())
    assert len(scanned) == len(frames)
    # frames are wall-to-wall: offsets partition the file exactly
    assert scanned[0][0] == 0
    for (o1, l1), (o2, _l2) in zip(scanned, scanned[1:]):
        assert o1 + l1 == o2


@needs_native
def test_luma_matches_libjpeg_within_idct_rounding(mjpg):
    path, _frames = mjpg
    nd = NativeY4MDecoder()
    for idx in (0, 3):
        out = nd.decode_clips_yuv(path, [idx], 1, width=W, height=H)
        y_native = out[0, 0][:H * W].reshape(H, W).astype(int)
        y_pil = _pil_ycbcr(path, idx)[..., 0].astype(int)
        assert np.abs(y_native - y_pil).max() <= 2


@needs_native
def test_chroma_matches_stored_samples_loosely(mjpg):
    """PIL only exposes chroma AFTER its triangle ('fancy') upsample,
    so the stored samples the native gather returns differ from PIL's
    filtered values by the neighbourhood spread — bounded, not exact."""
    path, _frames = mjpg
    nd = NativeY4MDecoder()
    out = nd.decode_clips_yuv(path, [0], 1, width=W, height=H)[0, 0]
    u_native = out[H * W:H * W + (H // 2) * (W // 2)].astype(int)
    ycc = _pil_ycbcr(path, 0)
    u_pil = ycc[::2, ::2, 1].ravel().astype(int)
    assert np.abs(u_native - u_pil).mean() <= 8
    assert np.abs(u_native - u_pil).max() <= 48


@needs_native
def test_444_rgb_matches_pil_within_conversion_rounding(tmp_path):
    from PIL import Image
    frames = synth_frames(2, H, W, seed=[7, 7, 7])
    path = str(tmp_path / "v444.mjpg")
    with open(path, "wb") as f:
        for i in range(2):
            buf = io.BytesIO()
            Image.fromarray(frames[i], "RGB").save(
                buf, "JPEG", quality=95, subsampling=0)  # 4:4:4
            f.write(buf.getvalue())
    nd = NativeY4MDecoder()
    assert nd.num_frames(path) == 2
    out = nd.decode_clips(path, [0], 1, width=W, height=H)[0, 0]
    with open(path, "rb") as f:
        data = f.read()
    off, length = scan_mjpeg_frames(data)[0]
    pil_rgb = np.asarray(Image.open(io.BytesIO(data[off:off + length]))
                         .convert("RGB"))
    # no subsampling -> chroma path is exercised end to end with no
    # upsample ambiguity; only IDCT + BT.601 rounding remain
    assert np.abs(out.astype(int) - pil_rgb.astype(int)).max() <= 4


@needs_native
def test_smooth_round_trip_within_quantization(tmp_path):
    yy, xx = np.mgrid[0:H, 0:W].astype(np.float32)
    smooth = np.stack([127 + 60 * np.sin(yy / 20),
                       127 + 60 * np.cos(xx / 25),
                       127 + 50 * np.sin((xx + yy) / 30)],
                      axis=-1).astype(np.uint8)[None]
    path = str(tmp_path / "s.mjpg")
    write_mjpeg(path, smooth, quality=95)
    nd = NativeY4MDecoder()
    out = nd.decode_clips(path, [0], 1, width=W, height=H)[0, 0]
    assert np.abs(out.astype(int) - smooth[0].astype(int)).max() <= 8


@needs_native
def test_clamp_past_end_repeats_last_frame(mjpg):
    path, frames = mjpg
    nd = NativeY4MDecoder()
    out = nd.decode_clips(path, [len(frames) - 1], 3, width=W, height=H)
    assert np.array_equal(out[0, 0], out[0, 1])
    assert np.array_equal(out[0, 1], out[0, 2])


@needs_native
def test_pool_fanout_matches_direct(mjpg):
    path, _frames = mjpg
    nd = NativeY4MDecoder(use_pool=False)
    np_ = NativeY4MDecoder(use_pool=True)
    starts = [0, 1, 2, 3, 4]  # >= POOL_SPLIT_MIN_CLIPS -> fans out
    direct = nd.decode_clips(path, starts, 2, width=48, height=32)
    pooled = np_.decode_clips(path, starts, 2, width=48, height=32)
    assert np.array_equal(direct, pooled)
    d_yuv = nd.decode_clips_yuv(path, starts, 2, width=48, height=32)
    p_yuv = np_.decode_clips_yuv(path, starts, 2, width=48, height=32)
    assert np.array_equal(d_yuv, p_yuv)


@needs_native
def test_resize_matches_pil_fallback_loosely(mjpg):
    """Native nearest-gather resize vs the PIL fallback backend (which
    shares the index maps but decodes through libjpeg): luma-dominated
    smooth content keeps the two within a few LSB on average."""
    path, _frames = mjpg
    native = NativeY4MDecoder().decode_clips(path, [1], 2,
                                             width=112, height=112)
    fallback = MjpegPILDecoder().decode_clips(path, [1], 2,
                                              width=112, height=112)
    assert native.shape == fallback.shape
    diff = np.abs(native.astype(int) - fallback.astype(int))
    assert diff.mean() <= 4.0


def test_pil_fallback_contract(mjpg):
    path, frames = mjpg
    dec = MjpegPILDecoder()
    assert dec.num_frames(path) == len(frames)
    out = dec.decode_clips(path, [0, 2], 2, width=56, height=48)
    assert out.shape == (2, 2, 48, 56, 3)
    yuv = dec.decode_clips_yuv(path, [0], 2, width=56, height=48)
    assert yuv.shape == (1, 2, 48 * 56 * 3 // 2)
    with pytest.raises(ValueError, match="even geometry"):
        dec.decode_clips_yuv(path, [0], 2, width=55, height=48)


def test_get_decoder_dispatch(mjpg, monkeypatch):
    path, _frames = mjpg
    if native_available():
        assert isinstance(get_decoder(path), NativeY4MDecoder)
    # without the native library the PIL fallback carries the contract
    import rnb_tpu.decode.native as native_mod
    monkeypatch.setattr(native_mod, "_lib", None)
    monkeypatch.setattr(native_mod, "_lib_checked", True)
    monkeypatch.setenv("RNB_DISABLE_NATIVE", "1")
    assert isinstance(get_decoder(path), MjpegPILDecoder)


@needs_native
def test_unsupported_jpegs_fail_cleanly(tmp_path):
    from PIL import Image
    frames = synth_frames(1, H, W, seed=[9, 9, 9])
    nd = NativeY4MDecoder()
    # 4:2:2 sampling: outside the y4m-compatible plane model
    p422 = str(tmp_path / "v422.mjpg")
    buf = io.BytesIO()
    Image.fromarray(frames[0], "RGB").save(buf, "JPEG", quality=90,
                                           subsampling=1)  # 4:2:2
    with open(p422, "wb") as f:
        f.write(buf.getvalue())
    with pytest.raises(ValueError, match="colourspace|sampling"):
        nd.decode_clips(p422, [0], 1, width=W, height=H)
    # progressive: baseline decoder must refuse, not corrupt
    pprog = str(tmp_path / "vprog.mjpg")
    buf = io.BytesIO()
    Image.fromarray(frames[0], "RGB").save(buf, "JPEG", quality=90,
                                           subsampling=2,
                                           progressive=True)
    with open(pprog, "wb") as f:
        f.write(buf.getvalue())
    with pytest.raises(ValueError):
        nd.decode_clips(pprog, [0], 1, width=W, height=H)


@needs_native
def test_restart_markers_decode_and_scan(tmp_path):
    """DRI/RSTn streams: the decoder must resynchronize at restart
    intervals (byte-align, reset DC predictors) and the scanner must
    step over in-entropy RST markers — luma still matches libjpeg."""
    from PIL import Image
    frames = synth_frames(2, H, W, seed=[6, 6, 6])
    path = str(tmp_path / "rst.mjpg")
    with open(path, "wb") as f:
        for i in range(2):
            buf = io.BytesIO()
            Image.fromarray(frames[i], "RGB").save(
                buf, "JPEG", quality=90, subsampling=2,
                restart_marker_blocks=4)
            b = buf.getvalue()
            assert b"\xff\xdd" in b  # DRI present
            f.write(b)
    with open(path, "rb") as f:
        data = f.read()
    assert sum(data.count(bytes([0xFF, 0xD0 + i]))
               for i in range(8)) >= 2  # real RSTs in the streams
    assert len(scan_mjpeg_frames(data)) == 2
    nd = NativeY4MDecoder()
    assert nd.num_frames(path) == 2
    for idx in (0, 1):
        out = nd.decode_clips_yuv(path, [idx], 1, width=W, height=H)
        y_native = out[0, 0][:H * W].reshape(H, W).astype(int)
        y_pil = _pil_ycbcr(path, idx)[..., 0].astype(int)
        assert np.abs(y_native - y_pil).max() <= 2


def test_app_segment_with_embedded_eoi_not_split(tmp_path):
    """An APPn payload may legally contain FF D9 (e.g. an EXIF
    thumbnail's end-of-image); the scanner must skip segments by their
    length fields, not split at the first raw FF D9."""
    from PIL import Image
    frames = synth_frames(2, H, W, seed=[4, 4, 4])
    blobs = []
    for i in range(2):
        buf = io.BytesIO()
        Image.fromarray(frames[i], "RGB").save(buf, "JPEG", quality=90,
                                               subsampling=2)
        b = buf.getvalue()
        # inject an APP1 right after SOI whose payload embeds FFD8+FFD9
        payload = b"Exif\x00\x00" + b"\xff\xd8" + b"A" * 10 + b"\xff\xd9"
        app1 = b"\xff\xe1" + (len(payload) + 2).to_bytes(2, "big") + payload
        blobs.append(b[:2] + app1 + b[2:])
    path = str(tmp_path / "exif.mjpg")
    with open(path, "wb") as f:
        f.write(b"".join(blobs))
    with open(path, "rb") as f:
        scanned = scan_mjpeg_frames(f.read())
    assert len(scanned) == 2
    assert scanned[0][1] == len(blobs[0])
    if native_available():
        nd = NativeY4MDecoder()
        assert nd.num_frames(path) == 2
        out = nd.decode_clips(path, [0], 2, width=W, height=H)
        assert out.shape == (1, 2, H, W, 3)
    # the PIL fallback consumes the same boundaries
    assert MjpegPILDecoder().num_frames(path) == 2


def test_path_iterator_picks_up_mjpg(tmp_path, monkeypatch):
    from rnb_tpu.models.r2p1d.model import R2P1DVideoPathIterator
    label = tmp_path / "label000"
    label.mkdir()
    frames = synth_frames(2, 16, 16, seed=[1, 1, 1])
    write_mjpeg(str(label / "video0000.mjpg"), frames)
    monkeypatch.setenv("RNB_TPU_DATA_ROOT", str(tmp_path))
    it = R2P1DVideoPathIterator()
    first = next(iter(it))
    assert first.endswith("video0000.mjpg")
