"""The dct pixel path: packed dequantized-coefficient decode backends
+ the fused on-device IDCT/upsample/convert/normalize ingest.

Contract under test (rnb_tpu/ops/dct.py, rnb_tpu/decode/jpeg_dct.py):
  * the wire format round-trips, and the default budget is half the
    packed-yuv420 frame bytes;
  * the Pallas kernel body (interpret=True) is BIT-identical to the
    masked jnp twin tier-1 exercises, pad rows exactly zero;
  * the native C++ coefficient decode is bit-exact with the
    independent pure-Python entropy decoder (the fallback oracle);
  * reconstructed pixels match the yuv420 pixel path within float-IDCT
    rounding, and reduced R(2+1)D logits agree across
    dct / yuv420 / rgb on the same video;
  * ragged and bucketed dct dispatches are bit-identical on valid
    rows with exactly ONE compiled signature;
  * a mid-pool decode failure on the dct path is contained without
    poisoning pool-mates.
"""

import io
import os

import numpy as np
import pytest

from rnb_tpu.decode import (MjpegPILDecoder, SyntheticDecoder,
                            Y4MDecoder, write_mjpeg, write_y4m)
from rnb_tpu.faults import CorruptVideoError
from rnb_tpu.ops.dct import (coeffs_from_elems, dct_frame_elems,
                             dct_rows_to_rgb_numpy, default_dct_coeffs,
                             num_dct_blocks, pack_frame_dct,
                             ragged_normalize_dct,
                             unpack_frame_dct_numpy)
from rnb_tpu.ops.yuv import packed_frame_bytes, yuv420_to_rgb_numpy
from rnb_tpu.telemetry import TimeCard

LS = (1, 1, 1, 1)  # minimal layer sizes: fast compile, full topology


def _smooth_frames(n=8, hw=112, seed=5):
    """Real-video-like moving gradients (JPEG-sparse spectrum, smooth
    chroma — the content class the bytes-per-frame headline assumes;
    pure noise would blow the coefficient budget by design)."""
    rng = np.random.default_rng(seed)
    phase = rng.uniform(0, 2 * np.pi, size=3)
    yy, xx = np.mgrid[0:hw, 0:hw].astype(np.float32)
    t = np.arange(n, dtype=np.float32)[:, None, None]
    frames = np.empty((n, hw, hw, 3), np.uint8)
    for c in range(3):
        frames[..., c] = (127.5 * (1 + np.sin(
            2 * np.pi * (yy / hw + xx / hw) + phase[c] + 0.1 * t))
        ).astype(np.uint8)
    return frames


def _mjpg(tmp_path, name="v.mjpg", n=12, quality=85, seed=5):
    path = os.path.join(str(tmp_path), name)
    write_mjpeg(path, _smooth_frames(n, seed=seed), quality=quality)
    return path


def _rand_wire(rng, rows, frames, hw=32, max_per_block=5):
    """A random sparse coefficient pool (well-formed wire rows)."""
    nb = num_dct_blocks(hw, hw)
    elems = dct_frame_elems(hw, hw)
    pool = np.zeros((rows, frames, elems), np.int16)
    for r in range(rows):
        for f in range(frames):
            zz = np.zeros((nb, 64), np.int16)
            for b in range(nb):
                k = rng.integers(1, max_per_block + 1)
                pos = np.sort(rng.choice(64, size=k, replace=False))
                zz[b, pos] = rng.integers(-900, 900, k).astype(np.int16)
            pool[r, f] = pack_frame_dct(zz, hw, hw)
    return pool


# -- wire format ------------------------------------------------------

def test_wire_format_roundtrip_and_default_budget():
    hw = 112
    assert num_dct_blocks(hw, hw) == 294
    elems = dct_frame_elems(hw, hw)
    # the headline: a packed int16 frame is at most HALF the packed
    # yuv420 frame at the default budget
    assert elems * 2 <= packed_frame_bytes(hw, hw) // 2
    assert coeffs_from_elems(hw, hw, elems) == default_dct_coeffs(hw, hw)
    rng = np.random.default_rng(0)
    zz = np.zeros((294, 64), np.int16)
    for b in range(294):
        pos = np.sort(rng.choice(64, size=4, replace=False))
        zz[b, pos] = rng.integers(-2000, 2000, 4).astype(np.int16)
    wire = pack_frame_dct(zz, hw, hw)
    np.testing.assert_array_equal(unpack_frame_dct_numpy(wire, hw, hw),
                                  zz)
    with pytest.raises(ValueError):
        pack_frame_dct(zz, hw, hw, coeffs=100)  # over-budget spectrum
    with pytest.raises(ValueError):
        num_dct_blocks(100, 112)  # not divisible by 16
    with pytest.raises(ValueError):
        coeffs_from_elems(hw, hw, 295)  # odd remainder


# -- the fused primitive ----------------------------------------------

def test_pallas_interpret_matches_jnp_twin_bit_exact():
    # the TPU kernel body itself (grid skip via pl.when, scalar-
    # prefetched rows_valid) runs under interpret=True and must be
    # bit-identical to the masked jnp twin tier-1 exercises
    import jax.numpy as jnp
    pool = _rand_wire(np.random.default_rng(1), rows=4, frames=2)
    for valid in (0, 1, 3, 4):
        a = np.asarray(ragged_normalize_dct(
            jnp.asarray(pool), valid, 32, 32, dtype=jnp.float32))
        b = np.asarray(ragged_normalize_dct(
            jnp.asarray(pool), valid, 32, 32, dtype=jnp.float32,
            interpret=True))
        assert np.array_equal(a, b), valid
        assert not a[valid:].any()
        assert a.shape == (4, 2, 32, 32, 3)


def test_unpack_is_garbage_tolerant():
    # an uninitialized ragged pool tail must never trap or corrupt
    # valid rows: absurd counts/positions clamp/drop deterministically
    import jax.numpy as jnp
    pool = _rand_wire(np.random.default_rng(2), rows=3, frames=1)
    garbage = pool.copy()
    garbage[1:] = np.random.default_rng(3).integers(
        -32768, 32768, garbage[1:].shape).astype(np.int16)
    a = np.asarray(ragged_normalize_dct(
        jnp.asarray(pool), 1, 32, 32, dtype=jnp.float32))
    b = np.asarray(ragged_normalize_dct(
        jnp.asarray(garbage), 1, 32, 32, dtype=jnp.float32))
    assert np.array_equal(a[:1], b[:1])
    assert not b[1:].any()
    assert np.isfinite(b).all()


def test_conversion_matches_pixel_path_within_idct_rounding(tmp_path):
    """The on-device direct-basis IDCT and the host AAN IDCT are two
    float implementations of one transform: reconstructed u8 frames
    from the SAME JPEG must agree within 1 LSB (round boundaries)
    against the yuv420 pixel path."""
    import jax.numpy as jnp
    from rnb_tpu.ops.dct import normalize_dct
    path = _mjpg(tmp_path)
    dec = MjpegPILDecoder()
    wire = dec.decode_clips_dct(path, [0], 4, width=112, height=112)
    # the pure-numpy oracle first
    rgb_dct = dct_rows_to_rgb_numpy(wire, 112, 112)
    packed = dec.decode_clips_yuv(path, [0], 4, width=112, height=112)
    rgb_yuv = yuv420_to_rgb_numpy(packed, 112, 112)
    # PIL's decode_clips_yuv resamples chroma AFTER libjpeg's triangle
    # upsample, so allow its known few-LSB spread (same bound class as
    # tests/test_mjpeg.py's chroma tests); the tight <=1 LSB claim is
    # asserted against the native AAN decoder below, where both sides
    # read the STORED chroma samples
    diff = np.abs(rgb_dct.astype(int) - rgb_yuv.astype(int))
    assert np.percentile(diff, 99) <= 16
    assert diff.max() <= 32
    # the jittable twin agrees with its numpy oracle within 1 u8 LSB
    out = np.asarray(normalize_dct(jnp.asarray(wire), 112, 112,
                                   dtype=jnp.float32))
    out_u8 = (out * 255.0 + 255.0) / 2.0
    assert np.abs(out_u8 - rgb_dct.astype(np.float32)).max() <= 1.0


# -- decode backends --------------------------------------------------

def test_synthetic_dct_deterministic_and_well_formed():
    dec = SyntheticDecoder()
    a = dec.decode_clips_dct("synth://v1", [0, 10], 4, 112, 112)
    b = dec.decode_clips_dct("synth://v1", [0, 10], 4, 112, 112)
    assert a.shape == (2, 4, dct_frame_elems(112, 112))
    assert a.dtype == np.int16
    np.testing.assert_array_equal(a, b)
    c = dec.decode_clips_dct("synth://v2", [0, 10], 4, 112, 112)
    assert not np.array_equal(a, c)
    # rows are valid wire: counts sum within budget, roundtrip clean
    nb = num_dct_blocks(112, 112)
    counts = a[0, 0, :nb]
    assert (counts >= 1).all()
    assert counts.sum() <= default_dct_coeffs(112, 112)
    unpack_frame_dct_numpy(a[0, 0], 112, 112)


def test_y4m_rejects_dct_as_classified_permanent(tmp_path):
    path = os.path.join(str(tmp_path), "v.y4m")
    write_y4m(path, _smooth_frames(4))
    with pytest.raises(CorruptVideoError):
        Y4MDecoder().decode_clips_dct(path, [0], 2, 112, 112)


def test_pil_dct_geometry_and_budget_rejections(tmp_path):
    dec = MjpegPILDecoder()
    path = _mjpg(tmp_path)
    with pytest.raises(CorruptVideoError):
        # no resize exists in the coefficient domain
        dec.decode_clips_dct(path, [0], 1, width=96, height=96)
    with pytest.raises(CorruptVideoError):
        # over-budget spectrum is permanent, not silently truncated
        dec.decode_clips_dct(path, [0], 1, width=112, height=112,
                             coeffs=50)


needs_native = pytest.mark.skipif(
    not __import__("rnb_tpu.decode.native",
                   fromlist=["native_available"]).native_available(),
    reason="native library not built")


@needs_native
def test_native_matches_python_oracle_bit_exact(tmp_path):
    """The C++ entropy decoder and the independent pure-Python parser
    must produce IDENTICAL dequantized coefficients — the oracle
    parity that lets tier-1 trust either backend on the dct path."""
    from rnb_tpu.decode.native import (DecodePool, NativeY4MDecoder,
                                       PIX_DCT)
    path = _mjpg(tmp_path, n=10)
    nd = NativeY4MDecoder(use_pool=False)
    a = nd.decode_clips_dct(path, [0, 3, 8], 3, width=112, height=112)
    b = MjpegPILDecoder().decode_clips_dct(path, [0, 3, 8], 3,
                                           width=112, height=112)
    np.testing.assert_array_equal(a, b)
    # the pool path writes the same bytes into a caller buffer
    out = np.empty_like(a)
    pool = DecodePool(num_threads=2)
    try:
        t = pool.submit_into(path, [0, 3, 8], 3, out, pixfmt=PIX_DCT,
                             width=112, height=112)
        pool.wait(t, path)
    finally:
        pool.close()
    np.testing.assert_array_equal(out, a)


@needs_native
def test_reconstruction_within_one_lsb_of_native_pixels(tmp_path):
    """Against the native backend both pipelines read the SAME stored
    chroma samples, so the only difference is AAN-float vs
    direct-basis-float IDCT rounding: a plane sample can round 1 LSB
    apart at a .5 boundary, which the BT.601 matrix can stretch to 2
    RGB LSB — and nothing more."""
    from rnb_tpu.decode.native import NativeY4MDecoder
    path = _mjpg(tmp_path, n=8, seed=13)
    nd = NativeY4MDecoder(use_pool=False)
    wire = nd.decode_clips_dct(path, [0], 4, width=112, height=112)
    rgb_dct = dct_rows_to_rgb_numpy(wire, 112, 112)
    packed = nd.decode_clips_yuv(path, [0], 4, width=112, height=112)
    rgb_yuv = yuv420_to_rgb_numpy(packed, 112, 112)
    diff = np.abs(rgb_dct.astype(int) - rgb_yuv.astype(int))
    assert diff.max() <= 2
    assert (diff == 0).mean() >= 0.99


@needs_native
def test_native_dct_classified_errors(tmp_path):
    from rnb_tpu.decode.native import NativeY4MDecoder
    nd = NativeY4MDecoder(use_pool=False)
    path = _mjpg(tmp_path)
    with pytest.raises(CorruptVideoError):
        nd.decode_clips_dct(path, [0], 1, width=112, height=112,
                            coeffs=50)  # over budget
    y4m = os.path.join(str(tmp_path), "v.y4m")
    write_y4m(y4m, _smooth_frames(4))
    with pytest.raises(CorruptVideoError):
        nd.decode_clips_dct(y4m, [0], 1, width=112, height=112)


# -- stage wiring -----------------------------------------------------

def test_loader_runner_declarations():
    from rnb_tpu.models.r2p1d.model import R2P1DLoader, R2P1DRunner
    elems = dct_frame_elems(112, 112)
    assert R2P1DLoader.output_shape_for(
        max_clips=15, consecutive_frames=8,
        pixel_path="dct") == ((15, 8, elems),)
    assert R2P1DLoader.output_dtype_for(pixel_path="dct") == "int16"
    assert R2P1DRunner.input_shape_for(
        max_rows=15, consecutive_frames=8,
        pixel_path="dct") == ((15, 8, elems),)
    assert R2P1DRunner.input_dtype_for(pixel_path="dct") == "int16"
    custom = dct_frame_elems(112, 112, 1000)
    assert R2P1DLoader.output_shape_for(
        max_clips=2, consecutive_frames=2, pixel_path="dct",
        dct_coeffs_per_frame=1000) == ((2, 2, custom),)


def test_stage_validation_rejections():
    import jax
    from rnb_tpu.models.r2p1d.model import R2P1DLoader, R2P1DRunner
    dev = jax.devices()[0]
    with pytest.raises(ValueError):
        R2P1DLoader(dev, pixel_path="dct", raw_output=True,
                    num_warmups=0)
    with pytest.raises(ValueError):
        R2P1DLoader(dev, pixel_path="rgb", dct_coeffs_per_frame=100,
                    num_warmups=0)
    with pytest.raises(ValueError):
        R2P1DRunner(dev, start_index=2, end_index=5, num_warmups=0,
                    layer_sizes=LS, pixel_path="dct")
    with pytest.raises(ValueError):
        R2P1DRunner(dev, start_index=1, end_index=5, num_warmups=0,
                    layer_sizes=LS, pixel_path="rgb",
                    dct_coeffs_per_frame=100)


def test_golden_logit_parity_dct_vs_yuv_vs_rgb(tmp_path):
    """The headline numerics claim: the same video through all three
    pixel paths lands on the same prediction through a real reduced
    R(2+1)D stage, with dct-vs-yuv420 logits inside float-IDCT
    rounding and both inside the documented chroma tolerance of the
    rgb path."""
    import jax
    from rnb_tpu.models.r2p1d.model import R2P1DLoader, R2P1DRunner
    path = _mjpg(tmp_path, n=30, seed=11)
    dev = jax.devices()[0]
    fixed = dict(num_clips_population=[2], weights=[1], max_clips=2,
                 num_warmups=0, consecutive_frames=4)
    net = dict(start_index=1, end_index=5, num_warmups=0,
               layer_sizes=LS, max_rows=2, num_classes=16,
               consecutive_frames=4)
    logits = {}
    for arm in ("rgb", "yuv420", "dct"):
        loader = R2P1DLoader(dev, pixel_path=arm, **fixed)
        runner = R2P1DRunner(dev, pixel_path=arm, **net)
        (pb,), _, tc = loader(None, path, TimeCard(0))
        if arm == "dct":
            assert pb.data.shape == (2, 4, dct_frame_elems(112, 112))
            assert str(pb.data.dtype) == "int16"
        (lg,), _, _ = runner((pb,), None, tc)
        logits[arm] = np.asarray(lg.data, np.float32)
    ref = logits["yuv420"]
    assert np.array_equal(logits["dct"].argmax(-1), ref.argmax(-1))
    # dct vs yuv420: same chroma semantics, only float-IDCT rounding
    np.testing.assert_allclose(logits["dct"], ref,
                               atol=0.02 * np.abs(ref).max())
    # vs rgb: the documented <=1-chroma-pixel pixel-path tolerance
    np.testing.assert_allclose(logits["dct"], logits["rgb"],
                               atol=0.05 * np.abs(logits["rgb"]).max())


def test_ragged_bucketed_dct_bit_parity_one_signature(tmp_path):
    import jax
    import jax.numpy as jnp
    from rnb_tpu.models.r2p1d.model import R2P1DRunner
    from rnb_tpu.stage import PaddedBatch, RaggedBatch
    dev = jax.devices()[0]
    net = dict(start_index=1, end_index=5, num_classes=8,
               layer_sizes=LS, max_rows=4, consecutive_frames=2,
               num_warmups=1, pixel_path="dct")
    bucketed = R2P1DRunner(dev, **net)
    ragged = R2P1DRunner(dev, ragged=True, ragged_pool_rows=4,
                         ragged_chunk_rows=2, **net)
    pool = SyntheticDecoder().decode_clips_dct(
        "synth://parity", [0, 8, 16, 24], 2, 112, 112)
    for valid in (1, 3, 4):
        masked = pool.copy()
        masked[valid:] = 0  # bucketed pads are zero wire rows
        (rg,), _, _ = ragged(
            (RaggedBatch(jnp.asarray(pool), valid, (0, valid)),),
            None, TimeCard(0))
        (bk,), _, _ = bucketed(
            (PaddedBatch(jnp.asarray(masked), valid),), None,
            TimeCard(1))
        assert np.array_equal(np.asarray(rg.data)[:valid],
                              np.asarray(bk.data)[:valid]), valid
    ragged.compiles.freeze()
    ragged((RaggedBatch(jnp.asarray(pool), 2, (0, 2)),), None,
           TimeCard(2))
    snap = ragged.compiles.snapshot()
    assert snap["warmup"] == 1 and snap["steady_new"] == 0


def test_fusing_loader_dct_pool_and_contained_failure(tmp_path):
    """The dct path through the fusing loader's ragged pool: good
    requests fuse into one int16 pool emission; a mid-pool permanent
    decode failure (an over-budget frame) is contained via
    take_failed() without poisoning pool-mates, and the shipped
    segment table still partitions the surviving rows."""
    import jax
    from rnb_tpu.models.r2p1d.model import R2P1DFusingLoader
    from rnb_tpu.stage import RaggedBatch
    good = [_mjpg(tmp_path, "g%d.mjpg" % i, n=10, seed=20 + i)
            for i in range(3)]
    # same geometry, but pure-noise frames at q95: a spectrum far past
    # the default budget — a real over-budget permanent failure
    noisy = np.random.default_rng(9).integers(
        0, 256, (6, 112, 112, 3), np.uint8)
    bad = os.path.join(str(tmp_path), "bad.mjpg")
    write_mjpeg(bad, noisy, quality=95)
    loader = R2P1DFusingLoader(
        jax.devices()[0], fuse=4, max_hold_ms=10000.0, depth=50,
        pixel_path="dct", ragged=True, max_clips=4,
        consecutive_frames=2, num_clips_population=[1], weights=[1],
        num_warmups=0)
    emitted = []
    cards = [TimeCard(i) for i in range(4)]
    for card, p in zip(cards, [good[0], good[1], bad, good[2]]):
        out = loader(None, p, card)
        if out[2] is not None:
            emitted.append(out)
    while True:
        out = loader.flush()
        if out is None:
            break
        emitted.append(out)
    failed = loader.take_failed()
    assert [tc.id for tc, _ in failed] == [2]
    assert failed[0][1] == "corrupt-video"
    survivors = sorted(tc.id for _, _, tcl in emitted
                       for tc in tcl.time_cards)
    assert survivors == [0, 1, 3]
    for (pb,), _, tcl in emitted:
        assert isinstance(pb, RaggedBatch)
        assert str(pb.data.dtype) == "int16"
        assert pb.data.shape[0] == 4  # the one pool shape
        assert pb.segment_offsets[-1] == pb.valid
        assert pb.num_segments == len(tcl)


def test_dct_cache_rows_roundtrip(tmp_path):
    """Ragged clip-cache entries on the dct path are host int16 row
    extents; a hit fills pool rows bit-identically to the decode it
    skipped."""
    import jax
    from rnb_tpu.models.r2p1d.model import R2P1DFusingLoader
    path = _mjpg(tmp_path, n=10, seed=31)
    loader = R2P1DFusingLoader(
        jax.devices()[0], fuse=1, max_hold_ms=10000.0, depth=50,
        pixel_path="dct", ragged=True, cache_mb=16, max_clips=2,
        consecutive_frames=2, num_clips_population=[1], weights=[1],
        num_warmups=0)
    emitted = []
    out = loader(None, path, TimeCard(0))
    if out[2] is not None:
        emitted.append(out)
    while True:
        o = loader.flush()
        if o is None:
            break
        emitted.append(o)
    assert loader.cache.snapshot()["inserts"] == 1
    first = np.asarray(emitted[0][0][0].data)
    assert first.dtype == np.int16
    hit_card = TimeCard(1)
    out = loader(None, path, hit_card)
    if out[2] is None:
        emitted2 = []
        while True:
            o = loader.flush()
            if o is None:
                break
            emitted2.append(o)
        out = emitted2[0]
    assert hit_card.cache_hit is True
    valid = out[0][0].valid
    np.testing.assert_array_equal(np.asarray(out[0][0].data)[:valid],
                                  first[:valid])
