"""Live metrics plane (rnb_tpu.metrics): registry semantics, flusher,
SLO burn-rate math, flight recorder, config validation, disabled-path
no-ops, and the metrics-off byte-stability contract.

Unit coverage runs without JAX; the e2e cases drive the tiny test
pipeline (tests.pipeline_helpers) through run_benchmark with the root
``metrics`` config key on and off.
"""

import json
import os
import sys
import threading
import time

import pytest

from rnb_tpu import metrics, trace
from rnb_tpu.metrics import (MetricsRegistry, MetricsSettings,
                             SpanBridge, hist_bucket,
                             hist_upper_bounds)
from rnb_tpu.telemetry import TimeCard
from rnb_tpu.trace import Tracer, TraceSettings, validate_trace

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _no_active_registry():
    """Unit tests must never leak a module-global registry/tracer into
    later tests (benchmark.py owns install/clear in real runs)."""
    metrics.ACTIVE = None
    trace.ACTIVE = None
    yield
    metrics.ACTIVE = None
    trace.ACTIVE = None


# -- settings / config validation -------------------------------------

def test_settings_from_config():
    assert MetricsSettings.from_config(None) is None
    assert MetricsSettings.from_config({"enabled": False}) is None
    s = MetricsSettings.from_config({})
    assert s is not None
    assert s.interval_ms == metrics.DEFAULT_INTERVAL_MS
    assert s.flight_enabled
    assert s.ring_events == metrics.DEFAULT_RING_EVENTS
    s = MetricsSettings.from_config(
        {"interval_ms": 25,
         "flight_recorder": {"enabled": False}})
    assert s.interval_ms == 25.0 and not s.flight_enabled
    s = MetricsSettings.from_config(
        {"flight_recorder": {"ring_events": 16, "max_dumps": 2,
                             "burn_threshold": 1.5}})
    assert s.ring_events == 16 and s.max_dumps == 2
    assert s.burn_threshold == 1.5


def _cfg(metrics_value, extra=None):
    cfg = {
        "video_path_iterator":
            "tests.pipeline_helpers.CountingPathIterator",
        "metrics": metrics_value,
        "pipeline": [
            {"model": "tests.pipeline_helpers.TinyLoader",
             "queue_groups": [{"devices": [0], "out_queues": [0]}],
             "num_shared_tensors": 4},
            {"model": "tests.pipeline_helpers.TinySink",
             "queue_groups": [{"devices": [1], "in_queue": 0}]},
        ],
    }
    if extra:
        cfg.update(extra)
    return cfg


def test_config_accepts_valid_metrics_key():
    from rnb_tpu.config import parse_config
    cfg = parse_config(_cfg({"enabled": True, "interval_ms": 50,
                             "flight_recorder": {"ring_events": 256}}))
    assert cfg.metrics == {"enabled": True, "interval_ms": 50,
                           "flight_recorder": {"ring_events": 256}}
    # boolean shorthand for the recorder
    parse_config(_cfg({"flight_recorder": False}))


@pytest.mark.parametrize("bad", [
    "yes",                                  # not an object
    {"enable": True},                       # unknown key
    {"enabled": 1},                         # non-bool enabled
    {"interval_ms": 0},                     # non-positive interval
    {"interval_ms": True},                  # bool as number
    {"flight_recorder": 3},                 # recorder not bool/object
    {"flight_recorder": {"rings": 4}},      # unknown recorder key
    {"flight_recorder": {"ring_events": 0}},
    {"flight_recorder": {"max_dumps": 1.5}},
    {"flight_recorder": {"burn_threshold": 0}},
    {"flight_recorder": {"queue_saturation": 1.5}},
])
def test_config_rejects_bad_metrics_key(bad):
    from rnb_tpu.config import ConfigError, parse_config
    with pytest.raises(ConfigError):
        parse_config(_cfg(bad))


# -- disabled-path no-ops ---------------------------------------------

def test_disabled_module_hooks_are_noops():
    metrics.counter("client.requests")
    metrics.gauge("queue.filename.depth", 3)
    metrics.observe("exec0.model_call", 1.5)
    metrics.mark("client.arrivals")
    metrics.trigger("circuit_open")
    metrics.completions([TimeCard(1)])
    metrics.register_stage(object())


# -- registry semantics -----------------------------------------------

def test_counter_gauge_rate_histogram_semantics():
    reg = MetricsRegistry(MetricsSettings())
    reg.inc_counter("client.requests", 2)
    reg.inc_counter("client.requests")
    reg.set_gauge("queue.filename.depth", 7)
    reg.set_gauge("queue.filename.depth", 4)
    reg.mark_rate("client.arrivals", 5, now=1000.0)
    reg.observe_ms("exec0.model_call", 3.0)
    reg.observe_ms("exec0.model_call", 100.0)
    snap = reg.snapshot(now=1000.5)
    assert snap["counters"]["client.requests"] == 3
    assert snap["gauges"]["queue.filename.depth"] == 4.0
    assert snap["rates"]["client.arrivals"] == pytest.approx(
        5 / metrics.RATE_WINDOW_S)
    hist = snap["histograms"]["exec0.model_call"]
    assert hist["count"] == 2 and sum(hist["buckets"]) == 2
    assert hist["sum_ms"] == pytest.approx(103.0)


def test_undeclared_metric_name_raises():
    reg = MetricsRegistry(MetricsSettings())
    with pytest.raises(ValueError, match="not declared"):
        reg.inc_counter("mystery.series")
    with pytest.raises(ValueError, match="not declared"):
        reg.set_gauge("mystery.series", 1.0)


def test_histogram_bucket_placement_and_bounds():
    bounds = hist_upper_bounds()
    assert len(bounds) == metrics.HIST_NUM_BUCKETS
    assert bounds[0] == 2.0 ** metrics.HIST_LOG2_MIN
    assert bounds[-1] == float("inf")
    # everything at or below the first bound lands in bucket 0
    assert hist_bucket(0.0) == 0
    assert hist_bucket(0.125) == 0
    # each observation lands in the first bucket whose bound covers it
    for ms in (0.2, 1.0, 7.0, 500.0, 1e9):
        b = hist_bucket(ms)
        assert ms <= bounds[b]
        if b > 0:
            assert ms > bounds[b - 1]


def test_rate_window_prunes_and_bounds_memory():
    reg = MetricsRegistry(MetricsSettings())
    for sec in range(100):
        reg.mark_rate("client.arrivals", 1, now=1000.0 + sec)
    rate = reg._rates["client.arrivals"]
    # bounded: only cells inside the window survive
    assert len(rate.cells) <= metrics.RATE_WINDOW_S + 1
    # 11 one-per-second cells survive (closed interval fencepost)
    assert rate.per_second(1099.0) == pytest.approx(
        11 / metrics.RATE_WINDOW_S)
    # far in the future the window is empty but lifetime total holds
    assert rate.per_second(5000.0) == 0.0
    assert rate.total == 100


def test_series_cardinality_is_bounded():
    reg = MetricsRegistry(MetricsSettings())
    for idx in range(metrics.MAX_SERIES + 50):
        reg.set_gauge("queue.e%d.depth" % idx, 1.0)
    assert len(reg._gauges) == metrics.MAX_SERIES
    snap = reg.snapshot(now=1.0)
    assert snap["series_overflowed"] >= 50


def test_counters_monotone_across_snapshots():
    reg = MetricsRegistry(MetricsSettings())
    values = []
    for step in range(4):
        reg.inc_counter("client.requests", step + 1)
        values.append(
            reg.snapshot(now=float(step))["counters"]
            ["client.requests"])
    assert values == sorted(values)


# -- poll sources -----------------------------------------------------

def test_poll_sources_sum_across_instances():
    reg = MetricsRegistry(MetricsSettings())
    a = {"hits": 3, "misses": 1}
    b = {"hits": 2, "misses": 5}
    reg.add_poll(metrics.snapshot_poll("cache", lambda: a,
                                       counters=("hits", "misses")))
    reg.add_poll(metrics.snapshot_poll("cache", lambda: b,
                                       counters=("hits", "misses")))
    snap = reg.snapshot(now=1.0)
    assert snap["counters"]["cache.hits"] == 5
    assert snap["counters"]["cache.misses"] == 6
    a["hits"] = 10  # sources advance; the polled sum follows
    assert reg.snapshot(now=2.0)["counters"]["cache.hits"] == 12


def test_register_stage_bridges_cache_and_staging():
    class FakeCache:
        def snapshot(self):
            return {"hits": 4, "misses": 2, "inserts": 2,
                    "evictions": 0, "coalesced": 1, "oversize": 0,
                    "bytes_resident": 128, "entries": 2}

    class FakeStaging:
        def snapshot(self):
            return {"slots": 3, "acquires": 9, "acquire_waits": 1,
                    "staged_batches": 7, "copied_batches": 2,
                    "reallocs": 0}

    class FakeModel:
        cache = FakeCache()
        staging = FakeStaging()

    reg = MetricsRegistry(MetricsSettings())
    metrics.ACTIVE = reg
    metrics.register_stage(FakeModel())
    snap = reg.snapshot(now=1.0)
    assert snap["counters"]["cache.hits"] == 4
    assert snap["counters"]["staging.staged_batches"] == 7
    assert snap["gauges"]["cache.bytes_resident"] == 128.0
    assert snap["gauges"]["staging.slots"] == 3.0


def test_gauge_source_probed_each_tick():
    reg = MetricsRegistry(MetricsSettings())
    depth = {"v": 2}
    reg.add_gauge_source("queue.filename.depth",
                         lambda: depth["v"], capacity=100)
    assert reg.snapshot(now=1.0)["gauges"]["queue.filename.depth"] \
        == 2.0
    depth["v"] = 9
    assert reg.snapshot(now=2.0)["gauges"]["queue.filename.depth"] \
        == 9.0


# -- SLO layer --------------------------------------------------------

def _card(rid, t0, t1, deadline_s=None):
    tc = TimeCard(rid)
    tc.record("enqueue_filename", at=t0)
    tc.record("inference1_finish", at=t1)
    if deadline_s is not None:
        tc.deadline_s = deadline_s
    return tc


def test_slo_verdicts_from_deadline_stamp_and_budget():
    reg = MetricsRegistry(MetricsSettings(), slo_budget_ms=100.0)
    # deadline stamp wins when present
    reg.note_completions([_card(1, 0.0, 5.0, deadline_s=6.0)],
                         finish_s=1000.0)   # within its deadline
    reg.note_completions([_card(2, 0.0, 5.0, deadline_s=4.0)],
                         finish_s=1000.0)   # past its deadline
    # no stamp: the job budget applies to the end-to-end span
    reg.note_completions([_card(3, 0.0, 0.05)], finish_s=1000.0)
    reg.note_completions([_card(4, 0.0, 0.5)], finish_s=1000.0)
    assert (reg.slo_tracked, reg.slo_within, reg.slo_missed) \
        == (4, 2, 2)


def test_slo_without_any_budget_counts_all_within():
    reg = MetricsRegistry(MetricsSettings(), slo_budget_ms=None)
    reg.note_completions([_card(1, 0.0, 99.0)], finish_s=1000.0)
    assert (reg.slo_tracked, reg.slo_within, reg.slo_missed) \
        == (1, 1, 0)


def test_burn_rate_matches_hand_computed_window():
    reg = MetricsRegistry(MetricsSettings(), slo_budget_ms=100.0)
    now = 1000.0
    # 8 within + 2 late completions inside one window
    for rid in range(8):
        reg.note_completions([_card(rid, 0.0, 0.01)], finish_s=now)
    for rid in range(8, 10):
        reg.note_completions([_card(rid, 0.0, 5.0)], finish_s=now)
    snap = reg.snapshot(now=now + 0.5)
    # hand-computed: good 0.8/s, miss 0.2/s over the 10 s window;
    # miss fraction 0.2 against the 1% error budget => burn 20
    assert snap["rates"]["slo.good"] == pytest.approx(0.8)
    assert snap["rates"]["slo.miss"] == pytest.approx(0.2)
    assert snap["gauges"]["slo.goodput_vps"] == pytest.approx(0.8)
    assert snap["gauges"]["slo.burn_rate"] == pytest.approx(
        (0.2 / 1.0) / (1.0 - metrics.SLO_TARGET))
    assert reg.burn_max == pytest.approx(
        snap["gauges"]["slo.burn_rate"])
    # the ledger counters partition
    c = snap["counters"]
    assert c["slo.tracked"] == c["slo.within"] + c["slo.missed"] == 10


def test_sheds_count_into_burn_via_slo_miss():
    reg = MetricsRegistry(MetricsSettings(), slo_budget_ms=100.0)
    now = 1000.0
    for rid in range(9):
        reg.note_completions([_card(rid, 0.0, 0.01)], finish_s=now)
    # a shed request (control.FaultStats bridge) is an SLO violation
    reg.mark_rate("slo.miss", 1, now=now)
    reg.mark_rate("faults.sheds", 1, now=now)
    snap = reg.snapshot(now=now + 0.1)
    assert snap["gauges"]["slo.burn_rate"] == pytest.approx(
        (0.1 / 1.0) / (1.0 - metrics.SLO_TARGET))


# -- flight recorder --------------------------------------------------

def _armed_registry(tmp_path, ring_events=64, max_dumps=2,
                    cooldown_s=100.0):
    settings = MetricsSettings(
        flight_recorder={"ring_events": ring_events,
                         "max_dumps": max_dumps,
                         "cooldown_s": cooldown_s})
    reg = MetricsRegistry(settings, job_dir=str(tmp_path),
                          job_id="flight-test")
    reg.bridge = SpanBridge(reg, ring_events=settings.ring_events)
    return reg


def test_ring_evicts_oldest_and_dump_validates(tmp_path):
    reg = _armed_registry(tmp_path, ring_events=4)
    trace.ACTIVE = reg.bridge
    for idx in range(10):
        with trace.span("exec0.model_call", rid=idx):
            pass
    events = reg.bridge.ring_events()
    assert len(events) == 4
    assert [e[5] for e in events] == [6, 7, 8, 9]  # oldest evicted
    reg.request_dump("forced", {"why": "test"})
    reg.tick(now=time.time())
    path = str(tmp_path / "flight-0.json")
    assert os.path.isfile(path)
    assert validate_trace(path) == []
    with open(path) as f:
        doc = json.load(f)
    assert doc["otherData"]["flight_trigger"] == "forced"
    assert doc["otherData"]["metric_window"]  # snapshots embedded
    # a truncated ring must read as truncated: the 6 evicted events
    # surface as the dump's dropped count, never as a complete window
    assert doc["otherData"]["dropped_events"] == 6
    assert reg.num_dumps == 1


def test_dump_budget_and_cooldown(tmp_path):
    reg = _armed_registry(tmp_path, max_dumps=2, cooldown_s=1000.0)
    trace.ACTIVE = reg.bridge
    with trace.span("exec0.model_call", rid=1):
        pass
    # same-kind triggers inside the cooldown collapse to one dump
    reg.request_dump("circuit_open", {"lane": 1})
    reg.request_dump("circuit_open", {"lane": 2})
    # a different kind dumps, further kinds hit the budget
    reg.request_dump("shed_spike")
    reg.request_dump("slo_burn")
    reg.tick()
    names = sorted(p for p in os.listdir(str(tmp_path))
                   if p.startswith("flight-"))
    assert names == ["flight-0.json", "flight-1.json"]
    assert reg.num_dumps == 2
    assert reg.num_triggers == 4


def test_burn_threshold_trigger_fires_from_flusher(tmp_path):
    reg = _armed_registry(tmp_path)
    reg.settings.burn_threshold = 2.0
    reg.slo_budget_ms = 100.0
    trace.ACTIVE = reg.bridge
    with trace.span("exec0.model_call", rid=1):
        pass
    now = 1000.0
    for rid in range(10):  # all late: burn = 100x the budget
        reg.note_completions([_card(rid, 0.0, 5.0)], finish_s=now)
    reg.tick(now=now + 0.1)
    doc = json.load(open(str(tmp_path / "flight-0.json")))
    assert doc["otherData"]["flight_trigger"] == "slo_burn"


def test_queue_saturation_trigger(tmp_path):
    reg = _armed_registry(tmp_path)
    trace.ACTIVE = reg.bridge
    with trace.span("exec0.model_call", rid=1):
        pass
    reg.add_gauge_source("queue.filename.depth", lambda: 95,
                         capacity=100)
    reg.tick(now=1000.0)
    doc = json.load(open(str(tmp_path / "flight-0.json")))
    assert doc["otherData"]["flight_trigger"] == "queue_saturation"
    assert doc["otherData"]["flight_detail"]["queue"] \
        == "queue.filename.depth"


def test_recorder_off_keeps_triggers_inert(tmp_path):
    settings = MetricsSettings(flight_recorder={"enabled": False})
    reg = MetricsRegistry(settings, job_dir=str(tmp_path))
    reg.bridge = SpanBridge(reg, ring_events=0)
    reg.request_dump("circuit_open")
    reg.tick()
    assert not [p for p in os.listdir(str(tmp_path))
                if p.startswith("flight-")]


# -- span bridge ------------------------------------------------------

def test_span_bridge_feeds_histograms_and_forwards():
    reg = MetricsRegistry(MetricsSettings())
    tracer = Tracer(TraceSettings(sample_hz=0))
    bridge = SpanBridge(reg, forward=tracer, ring_events=8)
    reg.bridge = bridge
    trace.ACTIVE = bridge
    with trace.span("exec0.model_call", rid=3):
        pass
    trace.instant("health.lane_state", args={"lane": 1})
    trace.instant("client.enqueue", rid=3)  # not a declared metric
    snap = reg.snapshot(now=1.0)
    assert snap["histograms"]["exec0.model_call"]["count"] == 1
    assert snap["counters"]["health.lane_state"] == 1
    assert "client.enqueue" not in snap["counters"]
    # the real tracer saw everything, bridged or not
    assert tracer.num_events() == 3


def test_bridge_cache_does_not_launder_undeclared_site_names():
    reg = MetricsRegistry(MetricsSettings())
    # seen first through the bridge (silently skipped there) ...
    reg.bridge_event("client.enqueue", "i", 0.0)
    # ... a direct call-site use of the same undeclared name still
    # fails loudly
    with pytest.raises(ValueError, match="not declared"):
        reg.inc_counter("client.enqueue")


# -- flusher thread ---------------------------------------------------

def test_flusher_streams_snapshots_and_stops(tmp_path):
    reg = MetricsRegistry(MetricsSettings(interval_ms=20),
                          job_dir=str(tmp_path), job_id="flush-test")
    metrics.ACTIVE = reg
    reg.start()
    deadline = time.monotonic() + 5.0
    while reg.seq < 3 and time.monotonic() < deadline:
        time.sleep(0.01)
    reg.stop()
    metrics.ACTIVE = None
    lines = [json.loads(line) for line in
             open(str(tmp_path / "metrics.jsonl"))
             if line.strip()]
    assert len(lines) >= 3
    assert [rec["seq"] for rec in lines] \
        == sorted(rec["seq"] for rec in lines)
    # bounded memory: the in-registry window never exceeds its cap
    assert len(reg._recent) <= 8
    assert os.path.isfile(str(tmp_path / "metrics.prom"))


def test_forced_dump_env_hook(tmp_path, monkeypatch):
    reg = _armed_registry(tmp_path)
    trace.ACTIVE = reg.bridge
    with trace.span("exec0.model_call", rid=1):
        pass
    monkeypatch.setenv(metrics.FORCE_DUMP_ENV, "1")
    reg.start()
    reg.stop()
    assert os.path.isfile(str(tmp_path / "flight-0.json"))
    assert validate_trace(str(tmp_path / "flight-0.json")) == []


def test_exposition_format(tmp_path):
    reg = MetricsRegistry(MetricsSettings(), job_dir=str(tmp_path))
    reg.inc_counter("client.requests", 5)
    reg.set_gauge("queue.filename.depth", 3)
    reg.observe_ms("exec0.model_call", 4.0)
    reg.snapshot(now=1.0)
    reg._write_exposition(str(tmp_path / "metrics.prom"))
    text = open(str(tmp_path / "metrics.prom")).read()
    assert "# TYPE rnb_client_requests counter\n" \
           "rnb_client_requests 5\n" in text
    assert "rnb_queue_filename_depth 3" in text
    assert 'rnb_exec0_model_call_ms_bucket{le="+Inf"} 1' in text
    assert "rnb_exec0_model_call_ms_count 1" in text


# -- e2e: metrics-enabled and metrics-off tiny pipeline runs ----------

def _run(tmp_path, run_name, metrics_value, extra=None, videos=40,
         interval_ms=1):
    from rnb_tpu.benchmark import run_benchmark
    cfg = _cfg(metrics_value, extra)
    if metrics_value is None:
        del cfg["metrics"]
    path = os.path.join(str(tmp_path), "%s.json" % run_name)
    with open(path, "w") as f:
        json.dump(cfg, f)
    return run_benchmark(path, mean_interval_ms=interval_ms,
                         num_videos=videos, queue_size=50,
                         log_base=os.path.join(str(tmp_path),
                                               "logs-%s" % run_name),
                         print_progress=False)


def _parse_utils():
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    import parse_utils
    return parse_utils


def test_metrics_deadline_run_end_to_end(tmp_path):
    res = _run(tmp_path, "live",
               {"enabled": True, "interval_ms": 20},
               extra={"deadline": {"budget_ms": 500}}, videos=60)
    assert res.termination_flag == 0
    assert res.metrics_snapshots >= 3
    assert res.slo_tracked >= res.slo_within > 0
    assert res.slo_within + res.slo_missed == res.slo_tracked
    # the module hook is cleared: nothing leaks into later runs
    assert metrics.ACTIVE is None and trace.ACTIVE is None

    jsonl = os.path.join(res.log_dir, "metrics.jsonl")
    assert os.path.isfile(jsonl)
    lines = [json.loads(line) for line in open(jsonl) if line.strip()]
    assert len(lines) == res.metrics_snapshots
    final = lines[-1]["counters"]
    # the footing contract: the final snapshot equals the ledgers
    assert final["faults.num_failed"] == res.num_failed
    assert final["faults.num_shed"] == res.num_shed
    assert final["deadline.expired"] == res.deadline_expired
    assert final["slo.tracked"] == res.slo_tracked
    # >=, not ==: the open-loop poisson client may legally create one
    # request past the target before it observes termination
    assert final["client.requests"] >= 60
    # bridged histograms from the existing executor spans
    hists = lines[-1]["histograms"]
    assert hists["exec0.model_call"]["count"] > 0
    assert hists["exec1.model_call"]["count"] > 0
    assert os.path.isfile(os.path.join(res.log_dir, "metrics.prom"))

    with open(os.path.join(res.log_dir, "log-meta.txt")) as f:
        meta_text = f.read()
    assert "Metrics: snapshots=%d" % res.metrics_snapshots in meta_text
    assert "Slo: tracked=%d" % res.slo_tracked in meta_text

    parse_utils = _parse_utils()
    try:
        assert parse_utils.check_job(res.log_dir) == []
    finally:
        sys.path.remove(os.path.join(REPO, "scripts"))


def test_metrics_and_trace_compose(tmp_path):
    # both planes on: the bridge forwards to the real tracer, so the
    # trace artifact stays complete AND the metrics plane streams
    res = _run(tmp_path, "both",
               {"enabled": True, "interval_ms": 20},
               extra={"trace": {"enabled": True, "sample_hz": 100}})
    assert res.termination_flag == 0
    assert res.trace_events > 0
    assert res.metrics_snapshots >= 1
    assert validate_trace(os.path.join(res.log_dir,
                                       "trace.json")) == []
    parse_utils = _parse_utils()
    try:
        assert parse_utils.check_job(res.log_dir) == []
    finally:
        sys.path.remove(os.path.join(REPO, "scripts"))


def test_check_catches_metrics_drift(tmp_path):
    res = _run(tmp_path, "drift", {"enabled": True, "interval_ms": 20})
    assert res.termination_flag == 0
    jsonl = os.path.join(res.log_dir, "metrics.jsonl")
    lines = open(jsonl).read().splitlines()
    final = json.loads(lines[-1])
    final["counters"]["faults.num_failed"] += 7  # cook the books
    with open(jsonl, "w") as f:
        f.write("\n".join(lines[:-1]
                          + [json.dumps(final, sort_keys=True)]) + "\n")
    parse_utils = _parse_utils()
    try:
        problems = parse_utils.check_job(res.log_dir)
    finally:
        sys.path.remove(os.path.join(REPO, "scripts"))
    assert any("does not foot" in p for p in problems)


def test_metrics_off_run_stays_byte_stable(tmp_path):
    res = _run(tmp_path, "plain", None)
    assert res.termination_flag == 0
    assert res.metrics_snapshots == 0 and res.slo_tracked == 0
    for artifact in ("metrics.jsonl", "metrics.prom", "flight-0.json"):
        assert not os.path.isfile(os.path.join(res.log_dir, artifact))
    with open(os.path.join(res.log_dir, "log-meta.txt")) as f:
        meta_text = f.read()
    assert "Metrics:" not in meta_text and "Slo:" not in meta_text
    tables = [n for n in os.listdir(res.log_dir) if "group" in n]
    with open(os.path.join(res.log_dir, tables[0])) as f:
        report = f.read()
    # the stamp schema is exactly the pre-metrics set
    header = report.split("\n", 1)[0].split()
    assert header == ["enqueue_filename", "runner0_start",
                      "inference0_start", "inference0_finish",
                      "runner1_start", "inference1_start",
                      "inference1_finish", "device0", "device1"]
