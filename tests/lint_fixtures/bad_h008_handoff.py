"""RNB-H008: host materialization on a device-resident handoff path."""

import numpy as np


class DemoEdgeHandoff:
    def __init__(self, device):
        self.device = device

    def take(self, payload):
        out = []
        for pb in payload:
            host = np.asarray(pb)  # host bounce on the d2d path
            out.append(host)
        return tuple(out)
