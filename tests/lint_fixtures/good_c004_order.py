"""RNB-C004 good fixture: both nesting sites acquire in the same
global order (Outer._a_lock before Inner._b_lock) — an order graph
with edges but no cycle."""

import threading


class Outer:
    def __init__(self, inner):
        self._a_lock = threading.Lock()
        self.inner = inner

    def one(self):
        with self._a_lock:
            with self.inner._b_lock:
                pass


class Inner:
    def __init__(self):
        self._b_lock = threading.Lock()
        self.outer = None

    def two(self):
        with self.outer._a_lock:
            with self._b_lock:
                pass
