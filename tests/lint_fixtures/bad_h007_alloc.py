"""RNB-H007: bucket-shaped host allocation per emission."""

import numpy as np


class Stage:
    def _batch_shape(self, rows):
        return (rows, 8, 112, 112, 3)

    def __call__(self, tensors, non_tensors, time_card):
        out = np.empty(self._batch_shape(4), dtype=np.uint8)
        out[:] = 0
        return (out,), non_tensors, time_card
