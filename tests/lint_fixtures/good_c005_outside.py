"""RNB-C005 good fixture: the blocking queue pop happens before the
lock; only the bounded ledger update runs under it. ``d.get(key)``
(a dict probe with positional args) must also stay quiet."""

import threading


class Worker:
    GUARDED_BY = {"_jobs": "_lock", "_last": "_lock"}

    def __init__(self, q):
        self._lock = threading.Lock()
        self._q = q
        self._jobs = {}
        self._last = None

    def take(self, key):
        item = self._q.get()
        with self._lock:
            self._jobs[key] = item
            self._last = self._jobs.get(key)
            return item
