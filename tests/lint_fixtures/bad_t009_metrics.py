"""RNB-T009: emits an unregistered metric series name (plus the
registered ones, so no dead-registry finding muddies the fixture)."""

from rnb_tpu import metrics


def emit(step, value, ms):
    metrics.counter("good.requests")
    metrics.gauge("good.depth", value)
    metrics.observe("good.latency", ms)
    metrics.mark("good.arrivals")
    metrics.gauge(metrics.name("good.e%d.depth", step), value)
    metrics.counter("mystery.series")
