"""Bad fixture: socket verbs with no configured timeout in sight
(RNB-H009, socket face) — a silently dead peer blocks this thread
forever instead of classifying as ``net_timeout``. The socket's
timeout cannot ride the call like a queue wait's ``timeout=`` kwarg,
so the function that blocks must be the one seen bounding it."""


def serve_forever(lsock):
    conn, _ = lsock.accept()            # RNB-H009: no settimeout
    head = conn.recv(28)                # RNB-H009: no settimeout
    return head


def dial(sock, addr):
    sock.connect(addr)                  # RNB-H009: no settimeout
    return sock
