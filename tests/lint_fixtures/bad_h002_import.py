"""RNB-H002: import inside a per-request hot path."""


class Stage:
    def __call__(self, tensors, non_tensors, time_card):
        import json
        return json.dumps({}), non_tensors, time_card
