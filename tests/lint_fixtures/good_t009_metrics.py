"""Schema fixture: emits exactly the (test-local) registered metric
series names through every rnb_tpu.metrics entry-point shape the
extractor must see."""

from rnb_tpu import metrics


def emit(step, value, ms):
    metrics.counter("good.requests")
    metrics.gauge("good.depth", value)
    metrics.observe("good.latency", ms)
    metrics.mark("good.arrivals")
    metrics.gauge(metrics.name("good.e%d.depth", step), value)
