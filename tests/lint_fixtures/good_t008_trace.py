"""Schema fixture: emits exactly the (test-local) registered trace
event names through every rnb_tpu.trace entry-point shape the
extractor must see."""

from rnb_tpu import trace


def emit(step, value):
    trace.instant("good.event")
    trace.counter("good.gauge", value)
    with trace.span(trace.name("good.e%d.depth", step)):
        pass
