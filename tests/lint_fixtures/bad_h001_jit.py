"""RNB-H001: host-sync calls inside jitted functions — both the
module-level shape and the factory-nested shape every real jit site
in the tree uses (`fn = jax.jit(apply)` inside a builder)."""

import jax
import numpy as np


def apply_fn(variables, x):
    return np.asarray(x) + 1


apply = jax.jit(apply_fn)


def make_apply(model):
    def apply_nested(variables, x):
        return float(x) + 1

    return jax.jit(apply_nested)
