"""Schema fixture: emits exactly the (test-local) registered devobs
metric series — the compute.*/memory.* vocabulary the device
observability plane streams — through the entry-point shapes the
extractor must see."""

from rnb_tpu import metrics


def emit(step, tflops, nbytes):
    metrics.gauge(metrics.name("compute.s%d.tflops", step), tflops)
    metrics.counter(metrics.name("compute.s%d.rows", step))
    metrics.gauge("memory.total_bytes", nbytes)
    metrics.gauge("memory.cache_bytes", nbytes)
