"""RNB-T008: emits an unregistered trace event name (plus the
registered ones, so no dead-registry finding muddies the fixture)."""

from rnb_tpu import trace


def emit(step, value):
    trace.instant("good.event")
    trace.counter("good.gauge", value)
    with trace.span(trace.name("good.e%d.depth", step)):
        pass
    trace.instant("mystery.event")
