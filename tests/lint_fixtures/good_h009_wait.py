"""Good fixture: every blocking wait on the hot path is bounded with
a timeout and re-checks liveness each lap (the RNB-H009 discipline)."""

import queue


class BoundedStage:
    def __init__(self, device, in_queue, done_event, termination):
        self.in_queue = in_queue
        self.done_event = done_event
        self.termination = termination

    def __call__(self, tensors, non_tensors, time_card):
        while not self.termination.terminated:
            try:
                item = self.in_queue.get(timeout=0.05)
            except queue.Empty:
                continue
            while not self.done_event.wait(timeout=0.05):
                if self.termination.terminated:
                    return None, None, None
            return item, non_tensors, time_card
        return None, None, None

    def wait(self):
        # a wait-named leaf is in H009 scope too: bounded is clean
        self.done_event.wait(timeout=1.0)
