"""RNB-H004: unseeded RNG in fault-injection code."""

import random


class MyFaultPlan:
    def draw(self, step_idx, request_id):
        return random.random()
