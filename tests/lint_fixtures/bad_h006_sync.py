"""RNB-H006: host sync on a per-request hot path."""


class Stage:
    def __call__(self, tensors, non_tensors, time_card):
        tensors[0].data.block_until_ready()
        return tensors, non_tensors, time_card
