"""RNB-C005 bad fixture: a blocking queue pop while holding the
lock — every other thread touching the ledger stalls behind IO."""

import threading


class Worker:
    GUARDED_BY = {"_jobs": "_lock"}

    def __init__(self, q):
        self._lock = threading.Lock()
        self._q = q
        self._jobs = {}

    def take(self, key):
        with self._lock:
            item = self._q.get()
            self._jobs[key] = item
            return item
