"""RNB-T007: stamps an attribute CONTENT_STAMPS does not declare."""


def stamp(time_card):
    time_card.mystery_attr = 1
