"""RNB-C003 bad fixture: a lock-owning class mutates an undeclared
attribute after __init__ (no GUARDED_BY/UNGUARDED_OK entry)."""

import threading


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0

    def bump(self):
        with self._lock:
            self._n += 1
