"""Clean RNB-H010 fixture: pool-shaped device memory allocated once
at stage init and reused per emission — no rule fires."""

import jax.numpy as jnp


class Stage:
    def _batch_shape(self, rows):
        return (rows, 8, 112, 112, 3)

    def __init__(self):
        # init-path preallocation is the sanctioned shape: one device
        # zero pool, reused by every emission (__init__ is not a hot
        # root)
        self._zero_pool = jnp.zeros(self._batch_shape(4), jnp.uint8)

    def __call__(self, tensors, non_tensors, time_card):
        pool = self._zero_pool
        return (pool,), non_tensors, time_card
