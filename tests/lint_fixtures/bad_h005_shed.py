"""RNB-H005: ring-slot write precedes the shed decision."""


def publish(ctx, payload, time_card, summary, full):
    ctx.output_ring.slots[0].write(payload)
    if full:
        _shed_item(ctx, time_card, summary)
