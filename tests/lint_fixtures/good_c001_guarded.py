"""RNB-C001 good fixture: every GUARDED_BY access holds the lock —
via the with block, or via the *_locked callee convention."""

import threading


class Ledger:
    GUARDED_BY = {"_entries": "_lock", "_total": "_lock"}

    def __init__(self):
        self._lock = threading.Lock()
        self._entries = {}
        self._total = 0

    def add(self, key, n):
        with self._lock:
            self._entries[key] = n
            self._total += n

    def total(self):
        with self._lock:
            return self._total

    def _drain_locked(self):
        out, self._entries = self._entries, {}
        self._total = 0
        return out

    def drain(self):
        with self._lock:
            return self._drain_locked()
