"""Schema fixture: records exactly the registered stamp patterns."""


def stamp_all(tc, step):
    tc.record("enqueue_filename")
    tc.record("runner%d_start" % step)
    tc.record("inference%d_start" % step)
    tc.record("inference%d_finish" % step)
    tc.record("decode%d_done" % step)
    tc.record("transfer%d_start" % step)
    tc.record("transfer%d_done" % step)
