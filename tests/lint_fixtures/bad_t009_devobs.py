"""RNB-T009: emits an unregistered compute.* series next to the
registered devobs vocabulary (so no dead-registry finding muddies the
fixture)."""

from rnb_tpu import metrics


def emit(step, tflops, nbytes):
    metrics.gauge(metrics.name("compute.s%d.tflops", step), tflops)
    metrics.counter(metrics.name("compute.s%d.rows", step))
    metrics.gauge("memory.total_bytes", nbytes)
    metrics.gauge("memory.cache_bytes", nbytes)
    metrics.gauge("compute.s0.mystery", tflops)
