"""Bad fixture: unbounded blocking waits on a stage hot path
(RNB-H009) — a dead producer hangs this consumer forever."""


class BlockingStage:
    def __init__(self, device, in_queue, done_event):
        self.in_queue = in_queue
        self.done_event = done_event

    def __call__(self, tensors, non_tensors, time_card):
        item = self.in_queue.get()          # RNB-H009: no timeout
        self.done_event.wait()              # RNB-H009: no timeout
        return item, non_tensors, time_card
