"""RNB-C001 bad fixture: a GUARDED_BY attribute read outside the
declared lock (the writes are disciplined, so only C001 fires)."""

import threading


class Ledger:
    GUARDED_BY = {"_entries": "_lock", "_total": "_lock"}

    def __init__(self):
        self._lock = threading.Lock()
        self._entries = {}
        self._total = 0

    def add(self, key, n):
        with self._lock:
            self._entries[key] = n
            self._total += n

    def total(self):
        return self._total
