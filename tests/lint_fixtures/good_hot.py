"""Clean hot-path fixture: no rule fires."""

import math


def helper(x):
    return math.sqrt(x)


class Stage:
    def __call__(self, tensors, non_tensors, time_card):
        total = 0
        for pb in tensors:
            total += helper(pb)
        return tensors, non_tensors, time_card
