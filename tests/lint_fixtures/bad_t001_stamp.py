"""RNB-T001: records an unregistered stamp (plus all registered ones,
so no dead-registry finding muddies the fixture)."""


def stamp_all(tc, step):
    tc.record("enqueue_filename")
    tc.record("runner%d_start" % step)
    tc.record("inference%d_start" % step)
    tc.record("inference%d_finish" % step)
    tc.record("decode%d_done" % step)
    tc.record("transfer%d_start" % step)
    tc.record("transfer%d_done" % step)
    tc.record("mystery_stamp")
