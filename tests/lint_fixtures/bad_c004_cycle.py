"""RNB-C004 bad fixture: Outer nests its lock around Inner's while
Inner nests the other way — a two-lock order cycle."""

import threading


class Outer:
    def __init__(self, inner):
        self._a_lock = threading.Lock()
        self.inner = inner

    def one(self):
        with self._a_lock:
            with self.inner._b_lock:
                pass


class Inner:
    def __init__(self):
        self._b_lock = threading.Lock()
        self.outer = None

    def two(self):
        with self._b_lock:
            with self.outer._a_lock:
                pass
