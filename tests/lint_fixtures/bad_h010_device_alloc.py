"""RNB-H010: pool/bucket-shaped DEVICE allocation per emission."""

import jax
import jax.numpy as jnp


def make_host(shape):
    return shape


class Stage:
    def _batch_shape(self, rows):
        return (rows, 8, 112, 112, 3)

    def __call__(self, tensors, non_tensors, time_card):
        # a fresh pool-shaped device array per emission (the HBM
        # fragmentation the page allocator exists to delete)
        pool = jnp.zeros(self._batch_shape(4), jnp.uint8)
        return (pool,), non_tensors, time_card

    def submit(self, video):
        # the device_put spelling of the same bug
        dev = jax.devices()[0]
        return jax.device_put(make_host(self._batch_shape(8)), dev)
