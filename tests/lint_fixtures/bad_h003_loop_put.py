"""RNB-H003: device_put inside a per-request loop."""


class Stage:
    def __call__(self, tensors, non_tensors, time_card):
        out = []
        for pb in tensors:
            out.append(jax.device_put(pb, self.device))
        return tuple(out), non_tensors, time_card
