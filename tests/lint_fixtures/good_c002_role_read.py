"""RNB-C002 good fixture: the declared read-only poll thread only
reads under the lock; mutation lives on an un-roled method."""

import threading


class Poller:
    GUARDED_BY = {"_seen": "_lock"}

    READ_ONLY_ROLES = {"rnb-poll": "the poll thread observes, the "
                                   "caller thread mutates"}

    def __init__(self):
        self._lock = threading.Lock()
        self._seen = 0
        self._thread = threading.Thread(target=self._poll_loop,
                                        name="rnb-poll_1")

    def _poll_loop(self):
        with self._lock:
            return self._seen

    def bump(self):
        with self._lock:
            self._seen += 1
