"""RNB-C003 good fixture: the lock-owning class declares every
attribute it mutates after __init__."""

import threading


class Counter:
    GUARDED_BY = {"_n": "_lock"}

    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0

    def bump(self):
        with self._lock:
            self._n += 1
