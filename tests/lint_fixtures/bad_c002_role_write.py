"""RNB-C002 bad fixture: the thread entry point whose name declares
the read-only ``rnb-poll`` role writes shared state (locked, so C001
stays quiet; declared, so C003 stays quiet — only C002 fires)."""

import threading


class Poller:
    GUARDED_BY = {"_seen": "_lock"}

    READ_ONLY_ROLES = {"rnb-poll": "the poll thread observes, the "
                                   "caller thread mutates"}

    def __init__(self):
        self._lock = threading.Lock()
        self._seen = 0
        self._thread = threading.Thread(target=self._poll_loop,
                                        name="rnb-poll_1")

    def _poll_loop(self):
        with self._lock:
            self._seen += 1
