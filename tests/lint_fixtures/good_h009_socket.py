"""Good fixture: socket verbs with in-function timeout discipline
(the RNB-H009 socket face stays quiet). Two sanctioned shapes: the
configuring function ``settimeout``s the sockets it blocks on, and a
leaf read helper ``gettimeout``-guards a socket it was handed (the
``rnb_tpu.ops.wire.recv_exact`` idiom — refuse an unbounded socket
rather than trust every caller)."""


def serve_once(lsock, io_timeout_s):
    lsock.settimeout(1.0)
    conn, _ = lsock.accept()
    conn.settimeout(io_timeout_s)
    return conn.recv(28)


def recv_exact(sock, n):
    if sock.gettimeout() is None:
        raise ValueError("socket needs a configured timeout")
    return sock.recv(n)
