"""Clean RNB-H008 fixture: host materialization confined to the
designated host-mode path of a handoff class."""


class DemoEdgeHandoff:
    def __init__(self, device):
        self.device = device

    def take(self, payload):
        # device-resident path: adopt/reshard only, no host bounce
        out = []
        for pb in payload:
            out.append(self._rehome(pb))
        return tuple(out)

    def _rehome(self, pb):
        import jax
        return jax.device_put(pb, self.device)

    def _take_host(self, payload):
        # the designated host-mode arm: bouncing is its whole job
        import numpy as np
        return tuple(np.asarray(pb) for pb in payload)
