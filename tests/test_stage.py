"""PaddedBatch, Batcher fusion, selectors and dynamic class loading."""

import numpy as np
import pytest

from rnb_tpu.batcher import Batcher
from rnb_tpu.selector import RoundRobinSelector
from rnb_tpu.stage import PaddedBatch
from rnb_tpu.telemetry import TimeCard, TimeCardList
from rnb_tpu.utils.class_utils import load_class


def test_padded_batch_pads_and_slices():
    rows = np.arange(6, dtype=np.float32).reshape(2, 3)
    pb = PaddedBatch.from_rows(rows, max_rows=5)
    assert pb.data.shape == (5, 3)
    assert pb.valid == 2
    assert pb.max_rows == 5
    np.testing.assert_array_equal(pb.valid_data(), rows)
    np.testing.assert_array_equal(pb.data[2:], np.zeros((3, 3), np.float32))


def test_padded_batch_exact_fit_and_overflow():
    rows = np.ones((4, 2), np.float32)
    pb = PaddedBatch.from_rows(rows, max_rows=4)
    assert pb.valid == 4
    with pytest.raises(ValueError):
        PaddedBatch.from_rows(rows, max_rows=3)


def _clip_batch(n_clips, fill):
    data = np.full((n_clips, 3, 8, 112, 112), fill, dtype=np.float32)
    return (PaddedBatch.from_rows(data, max_rows=15),)


def test_batcher_accumulates_then_fuses():
    b = Batcher(device=None, batch=3)
    out = b(_clip_batch(1, 1.0), None, TimeCard(0))
    assert out == (None, None, None)
    out = b(_clip_batch(2, 2.0), None, TimeCard(1))
    assert out == (None, None, None)
    tensors, non_tensors, card = b(_clip_batch(1, 3.0), "meta-2", TimeCard(2))
    assert non_tensors is None  # fused metadata is unattributable
    assert isinstance(card, TimeCardList)
    assert len(card) == 3
    fused = tensors[0]
    assert fused.valid == 4
    assert fused.data.shape == (15, 3, 8, 112, 112)
    np.testing.assert_array_equal(
        fused.valid_data()[:, 0, 0, 0, 0], [1.0, 2.0, 2.0, 3.0])
    # internal state resets for the next fused batch
    assert b(_clip_batch(1, 9.0), None, TimeCard(3)) == (None, None, None)


def test_batcher_passthrough_when_batch_leq_one():
    b = Batcher(device=None, batch=1)
    tensors = _clip_batch(2, 5.0)
    tc = TimeCard(0)
    out = b(tensors, "meta", tc)
    assert out == (tensors, "meta", tc)


def test_batcher_overflow_raises_and_recovers():
    b = Batcher(device=None, batch=2)
    b(_clip_batch(8, 1.0), None, TimeCard(0))
    with pytest.raises(ValueError):
        b(_clip_batch(8, 2.0), None, TimeCard(1))
    # the oversized request was rejected without wedging the accumulator:
    # a small follow-up request completes the fused batch
    tensors, _, card = b(_clip_batch(2, 3.0), None, TimeCard(2))
    assert tensors[0].valid == 10
    assert len(card) == 2


def test_round_robin_selector_cycles():
    s = RoundRobinSelector(3)
    picks = [s.select(None, None, None) for _ in range(7)]
    assert picks == [0, 1, 2, 0, 1, 2, 0]


def test_load_class_roundtrip():
    cls = load_class("rnb_tpu.selector.RoundRobinSelector")
    assert cls is RoundRobinSelector
    with pytest.raises(ValueError):
        load_class("NoDots")
    with pytest.raises(ImportError):
        load_class("rnb_tpu.selector.DoesNotExist")


def test_validate_payload_contract():
    import numpy as np
    import pytest
    from rnb_tpu.runner import validate_payload
    from rnb_tpu.stage import PaddedBatch

    declared = ((4, 2),)
    ok = (PaddedBatch(np.zeros((4, 2), np.float32), 3),)
    validate_payload(declared, ok, "step")
    # smaller row axis is legal (row bucketing)
    validate_payload(declared, (PaddedBatch(np.zeros((2, 2)), 1),), "step")
    # trailing-dim mismatch: the exact rot the NCFHW batcher declaration
    # had in round 1 — must be caught, not silently parked
    with pytest.raises(ValueError):
        validate_payload(declared, (PaddedBatch(np.zeros((4, 3)), 1),),
                         "step")
    # larger row axis than declared
    with pytest.raises(ValueError):
        validate_payload(declared, (PaddedBatch(np.zeros((5, 2)), 1),),
                         "step")
    # tensor-count mismatch
    with pytest.raises(ValueError):
        validate_payload(declared, ok * 2, "step")
    # None declaration forbids tensor output; empty payload is fine
    validate_payload(None, None, "step")
    validate_payload(None, (), "step")
    with pytest.raises(ValueError):
        validate_payload(None, ok, "step")
