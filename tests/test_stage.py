"""PaddedBatch, Batcher fusion, selectors and dynamic class loading."""

import numpy as np
import pytest

from rnb_tpu.batcher import Batcher
from rnb_tpu.selector import RoundRobinSelector
from rnb_tpu.stage import PaddedBatch
from rnb_tpu.telemetry import TimeCard, TimeCardList
from rnb_tpu.utils.class_utils import load_class


def test_padded_batch_pads_and_slices():
    rows = np.arange(6, dtype=np.float32).reshape(2, 3)
    pb = PaddedBatch.from_rows(rows, max_rows=5)
    assert pb.data.shape == (5, 3)
    assert pb.valid == 2
    assert pb.max_rows == 5
    np.testing.assert_array_equal(pb.valid_data(), rows)
    np.testing.assert_array_equal(pb.data[2:], np.zeros((3, 3), np.float32))


def test_padded_batch_exact_fit_and_overflow():
    rows = np.ones((4, 2), np.float32)
    pb = PaddedBatch.from_rows(rows, max_rows=4)
    assert pb.valid == 4
    with pytest.raises(ValueError):
        PaddedBatch.from_rows(rows, max_rows=3)


def _clip_batch(n_clips, fill):
    data = np.full((n_clips, 3, 8, 112, 112), fill, dtype=np.float32)
    return (PaddedBatch.from_rows(data, max_rows=15),)


def test_batcher_accumulates_then_fuses():
    b = Batcher(device=None, batch=3)
    out = b(_clip_batch(1, 1.0), None, TimeCard(0))
    assert out == (None, None, None)
    out = b(_clip_batch(2, 2.0), None, TimeCard(1))
    assert out == (None, None, None)
    tensors, non_tensors, card = b(_clip_batch(1, 3.0), "meta-2", TimeCard(2))
    assert non_tensors is None  # fused metadata is unattributable
    assert isinstance(card, TimeCardList)
    assert len(card) == 3
    fused = tensors[0]
    assert fused.valid == 4
    assert fused.data.shape == (15, 3, 8, 112, 112)
    np.testing.assert_array_equal(
        fused.valid_data()[:, 0, 0, 0, 0], [1.0, 2.0, 2.0, 3.0])
    # internal state resets for the next fused batch
    assert b(_clip_batch(1, 9.0), None, TimeCard(3)) == (None, None, None)


def test_batcher_passthrough_when_batch_leq_one():
    b = Batcher(device=None, batch=1)
    tensors = _clip_batch(2, 5.0)
    tc = TimeCard(0)
    out = b(tensors, "meta", tc)
    assert out == (tensors, "meta", tc)


def test_batcher_emits_early_when_request_would_overflow():
    # 8+8 > 15: the second request closes the window early — the
    # pending batch is emitted and the new request starts the next one
    # (one mid-sized video must not abort the run)
    b = Batcher(device=None, batch=2)
    assert b(_clip_batch(8, 1.0), None, TimeCard(0)) == (None, None, None)
    tensors, _, card = b(_clip_batch(8, 2.0), None, TimeCard(1))
    assert tensors[0].valid == 8
    assert len(card) == 1
    np.testing.assert_array_equal(
        tensors[0].valid_data()[:, 0, 0, 0, 0], [1.0] * 8)
    # the displaced request is pending; a follow-up completes its batch
    tensors, _, card = b(_clip_batch(2, 3.0), None, TimeCard(2))
    assert tensors[0].valid == 10
    assert len(card) == 2


def test_batcher_input_shape_follows_constructor_args():
    # regression: input_shape() used to hardcode the flagship
    # (MAX_ROWS, 8, 112, 112, 3) shape regardless of the
    # shapes/max_rows/consecutive_frames/frame_hw the instance was
    # built with, so declared-vs-actual payload validation was wrong
    # for every non-flagship topology
    b = Batcher(device=None, batch=2, max_rows=4, consecutive_frames=2,
                frame_hw=16)
    assert b.input_shape() == ((4, 2, 16, 16, 3),)
    assert b.input_shape() == b.output_shape_for(
        max_rows=4, consecutive_frames=2, frame_hw=16)
    b = Batcher(device=None, batch=2, shapes=[[6, 3], [6, 5]])
    assert b.input_shape() == ((6, 3), (6, 5))
    # default construction keeps the flagship shape
    assert Batcher(device=None, batch=2).input_shape() == \
        ((15, 8, 112, 112, 3),)


def test_batcher_early_emission_non_flagship_window():
    # regression (previously untested): a MID-SIZED request closing a
    # pending window on a non-flagship declared shape — the pending
    # batch must emit with only its own cards and the displaced
    # request must seed the next window intact
    b = Batcher(device=None, batch=3, shapes=[[4, 2]])

    def req(rows, fill):
        return (PaddedBatch.from_rows(
            np.full((rows, 2), fill, dtype=np.float32), max_rows=4),)

    assert b(req(2, 1.0), None, TimeCard(0)) == (None, None, None)
    # 2 pending + 3 incoming > 4 declared: early emission fires
    tensors, non_tensors, card = b(req(3, 2.0), None, TimeCard(1))
    assert non_tensors is None
    assert isinstance(card, TimeCardList) and len(card) == 1
    assert tensors[0].valid == 2
    assert tensors[0].data.shape == (4, 2)
    np.testing.assert_array_equal(tensors[0].valid_data()[:, 0],
                                  [1.0, 1.0])
    # the displaced mid-sized request is the next window's seed
    flushed = b.flush()
    assert flushed is not None
    assert flushed[0][0].valid == 3
    assert len(flushed[2]) == 1
    np.testing.assert_array_equal(flushed[0][0].valid_data()[:, 0],
                                  [2.0, 2.0, 2.0])


def test_batcher_rejects_single_oversized_request():
    # a lone request beyond the DECLARED capacity is a topology error
    b = Batcher(device=None, batch=2, shapes=[[4, 3, 8, 112, 112]])
    with pytest.raises(ValueError):
        b(_clip_batch(8, 1.0), None, TimeCard(0))
    # fail-fast left the accumulator intact
    assert b(_clip_batch(2, 2.0), None, TimeCard(1)) == (None, None, None)
    tensors, _, card = b(_clip_batch(2, 3.0), None, TimeCard(2))
    assert tensors[0].valid == 4


def test_round_robin_selector_cycles():
    s = RoundRobinSelector(3)
    picks = [s.select(None, None, None) for _ in range(7)]
    assert picks == [0, 1, 2, 0, 1, 2, 0]


def test_load_class_roundtrip():
    cls = load_class("rnb_tpu.selector.RoundRobinSelector")
    assert cls is RoundRobinSelector
    with pytest.raises(ValueError):
        load_class("NoDots")
    with pytest.raises(ImportError):
        load_class("rnb_tpu.selector.DoesNotExist")


def test_validate_payload_contract():
    import numpy as np
    import pytest
    from rnb_tpu.runner import validate_payload
    from rnb_tpu.stage import PaddedBatch

    declared = ((4, 2),)
    ok = (PaddedBatch(np.zeros((4, 2), np.float32), 3),)
    validate_payload(declared, ok, "step")
    # smaller row axis is legal (row bucketing)
    validate_payload(declared, (PaddedBatch(np.zeros((2, 2)), 1),), "step")
    # trailing-dim mismatch: the exact rot the NCFHW batcher declaration
    # had in round 1 — must be caught, not silently parked
    with pytest.raises(ValueError):
        validate_payload(declared, (PaddedBatch(np.zeros((4, 3)), 1),),
                         "step")
    # larger row axis than declared
    with pytest.raises(ValueError):
        validate_payload(declared, (PaddedBatch(np.zeros((5, 2)), 1),),
                         "step")
    # tensor-count mismatch
    with pytest.raises(ValueError):
        validate_payload(declared, ok * 2, "step")
    # None declaration forbids tensor output; empty payload is fine
    validate_payload(None, None, "step")
    validate_payload(None, (), "step")
    with pytest.raises(ValueError):
        validate_payload(None, ok, "step")


def test_batcher_row_buckets_pad_to_bucket():
    b = Batcher(device=None, batch=3, row_buckets=[4, 15])
    b(_clip_batch(1, 1.0), None, TimeCard(0))
    b(_clip_batch(1, 2.0), None, TimeCard(1))
    tensors, _, card = b(_clip_batch(1, 3.0), None, TimeCard(2))
    # 3 valid rows pad to the 4 bucket, not the 15 max shape
    assert tensors[0].valid == 3
    assert tensors[0].data.shape[0] == 4
    # an oversized fuse still pads to the max shape
    b2 = Batcher(device=None, batch=2, row_buckets=[4, 15])
    b2(_clip_batch(4, 1.0), None, TimeCard(0))
    tensors, _, _ = b2(_clip_batch(4, 2.0), None, TimeCard(1))
    assert tensors[0].data.shape[0] == 15


def test_batcher_flush_emits_partial_batch():
    b = Batcher(device=None, batch=4, row_buckets=[4, 15])
    assert b.flush() is None  # nothing pending
    b(_clip_batch(1, 1.0), None, TimeCard(0))
    b(_clip_batch(1, 2.0), None, TimeCard(1))
    tensors, non_tensors, card = b.flush()
    assert len(card) == 2
    assert tensors[0].valid == 2
    assert tensors[0].data.shape[0] == 4
    np.testing.assert_array_equal(
        tensors[0].valid_data()[:, 0, 0, 0, 0], [1.0, 2.0])
    assert b.flush() is None  # state reset


def test_batcher_fuses_on_device_without_host_bounce():
    """Device-array constituents fuse into a device array on the same
    device — the fused batch must not round-trip through the host
    (through a TPU tunnel that bounce costs a transfer per request)."""
    import jax
    import jax.numpy as jnp

    dev = jax.devices()[1]
    b = Batcher(device=None, batch=2, row_buckets=[4, 15])

    def dev_batch(n, fill):
        data = jnp.full((n, 3, 8, 16, 16), fill, jnp.bfloat16)
        data = jax.device_put(
            jnp.concatenate([data, jnp.zeros((15 - n,) + data.shape[1:],
                                             data.dtype)]), dev)
        return (PaddedBatch(data, n),)

    b(dev_batch(1, 1.0), None, TimeCard(0))
    tensors, _, card = b(dev_batch(2, 2.0), None, TimeCard(1))
    fused = tensors[0]
    assert isinstance(fused.data, jax.Array)
    assert fused.data.devices() == {dev}
    assert fused.valid == 3
    assert fused.data.shape[0] == 4  # padded to the bucket on device
    got = np.asarray(fused.data[:, 0, 0, 0, 0], np.float32)
    np.testing.assert_array_equal(got, [1.0, 2.0, 2.0, 0.0])
