"""The cross-host ingest edge (rnb_tpu.netedge + rnb_tpu.ops.wire).

Unit coverage for the frame codec and its fault classification, the
seeded reconnect backoff, both dedup ledgers (exactly-once under ack
loss), the health-board binding, receive-boundary deadline shedding —
plus a fault-injected two-process end-to-end run held to ``parse_utils
--check`` and the netedge-off byte-stability contract.
"""

import json
import os
import queue
import socket
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "scripts"))

from rnb_tpu.control import (FaultStats, InferenceCounter,  # noqa: E402
                             TerminationState)
from rnb_tpu.faults import (NetCorruptFrameError,  # noqa: E402
                            NetPartialFrameError, NetRefusedError,
                            NetResetError, NetTimeoutError,
                            PermanentError, TransientError)
from rnb_tpu.health import (DeadlineStats, HealthSettings,  # noqa: E402
                            LaneHealthBoard, deadline_site)
from rnb_tpu.netedge import (NET_LANE, BACKOFF_CAP_MS,  # noqa: E402
                             JITTER_FRAC, NetEdgeClient,
                             NetEdgeSettings, NetStats,
                             backoff_schedule_ms, parse_addr)
from rnb_tpu.ops import wire  # noqa: E402
from rnb_tpu.stage import PaddedBatch  # noqa: E402
from rnb_tpu.telemetry import TimeCard  # noqa: E402


# -- frame codec ------------------------------------------------------

def _pair():
    """A socketpair with configured timeouts — the wire layer REFUSES
    an unbounded socket (a silent peer must surface as net_timeout,
    never as a forever-blocked recv)."""
    a, b = socket.socketpair()
    a.settimeout(5.0)
    b.settimeout(5.0)
    return a, b


def test_recv_refuses_an_unbounded_socket():
    a, b = socket.socketpair()   # deliberately no settimeout
    try:
        with pytest.raises(ValueError, match="configured timeout"):
            wire.read_frame(b)
    finally:
        a.close()
        b.close()


def test_frame_roundtrip_over_a_real_socket():
    a, b = _pair()
    try:
        card = TimeCard(7)
        card.record("enqueue_filename")
        payload = wire.encode_req("video-7", card)
        a.sendall(wire.encode_frame(wire.REQ, payload, seq=42,
                                    deadline=123.5, depth=3))
        ftype, flags, depth, seq, deadline, got = wire.read_frame(b)
        assert (ftype, flags, depth, seq, deadline) \
            == (wire.REQ, 0, 3, 42, 123.5)
        path, card2 = wire.decode_req(got)
        assert path == "video-7" and card2.id == 7
        assert list(card2.timings) == ["enqueue_filename"]
    finally:
        a.close()
        b.close()


def test_corrupt_frame_classifies_and_carries_seq():
    a, b = _pair()
    try:
        frame = bytearray(wire.encode_frame(wire.DATA, b"payload",
                                            seq=9))
        frame[-1] ^= 0xff   # flip a payload byte AFTER the crc stamp
        a.sendall(bytes(frame))
        with pytest.raises(NetCorruptFrameError) as exc_info:
            wire.read_frame(b)
        assert exc_info.value.seq == 9
        # framing survived: the next frame on the same connection reads
        a.sendall(wire.encode_frame(wire.BEAT, depth=1))
        assert wire.read_frame(b)[0] == wire.BEAT
    finally:
        a.close()
        b.close()


def test_partial_frame_vs_reset_classification():
    # EOF mid-frame -> partial; EOF at a frame boundary -> reset
    a, b = _pair()
    frame = wire.encode_frame(wire.DATA, b"x" * 64, seq=1)
    a.sendall(frame[:len(frame) // 2])
    a.close()
    with pytest.raises(NetPartialFrameError):
        wire.read_frame(b)
    b.close()

    a, b = _pair()
    a.close()
    with pytest.raises(NetResetError):
        wire.read_frame(b)
    b.close()


def test_io_error_classification_taxonomy():
    assert isinstance(wire.classify_io_error(socket.timeout()),
                      NetTimeoutError)
    assert isinstance(wire.classify_io_error(ConnectionRefusedError()),
                      NetRefusedError)
    assert isinstance(wire.classify_io_error(ConnectionResetError()),
                      NetResetError)
    assert isinstance(wire.classify_io_error(BrokenPipeError()),
                      NetResetError)
    assert wire.classify_io_error(ValueError()) is None
    # the taxonomy split: only corruption is permanent
    for cls in (NetRefusedError, NetResetError, NetTimeoutError,
                NetPartialFrameError):
        assert issubclass(cls, TransientError), cls
    assert issubclass(NetCorruptFrameError, PermanentError)


def test_data_codec_ships_valid_rows_and_repads():
    rows = np.arange(2 * 3, dtype=np.float32).reshape(2, 3)
    batch = PaddedBatch.from_rows(rows, 5)
    card = TimeCard(3)
    card.num_clips = 2
    payload = wire.encode_data(batch, 3, card)
    out, non_tensors, card2, row_bytes = wire.decode_data(payload)
    assert row_bytes == rows.nbytes   # ONLY the valid rows crossed
    assert non_tensors == 3 and card2.id == 3
    assert out.valid == 2 and out.max_rows == 5
    np.testing.assert_array_equal(np.asarray(out.data)[:2], rows)
    assert not np.asarray(out.data)[2:].any()   # re-padded with zeros


def test_data_codec_rejects_fused_emissions():
    from rnb_tpu.telemetry import TimeCardList
    batch = PaddedBatch.from_rows(np.zeros((1, 2), np.float32), 2)
    cards = TimeCardList([TimeCard(0), TimeCard(1)])
    with pytest.raises(ValueError, match="single-request"):
        wire.encode_data(batch, None, cards)


# -- reconnect backoff ------------------------------------------------

def test_backoff_schedule_is_seeded_and_capped():
    a = backoff_schedule_ms(50, 6, seed=17)
    b = backoff_schedule_ms(50, 6, seed=17)
    assert a == b                      # replayable byte-for-byte
    assert a != backoff_schedule_ms(50, 6, seed=18)
    assert len(a) == 6
    for i, delay in enumerate(a):
        base = min(50.0 * 2 ** i, BACKOFF_CAP_MS)
        assert base <= delay <= base * (1 + JITTER_FRAC)
    # exponential growth until the cap
    assert a[0] < a[1] < a[2]


def test_parse_addr():
    assert parse_addr("127.0.0.1:80") == ("127.0.0.1", 80)
    with pytest.raises(ValueError):
        parse_addr("no-port")


# -- client-side dedup / deadline / board binding ---------------------

def _client(num_videos=4, health=None, deadline_stats=None):
    settings = NetEdgeSettings(connect="127.0.0.1:1", beat_ms=20,
                               io_timeout_ms=100, max_retries=1,
                               backoff_ms=1, resend_window=4)
    stats = NetStats()
    board = LaneHealthBoard((NET_LANE,), health or HealthSettings())
    client = NetEdgeClient(
        settings, board=board, stats=stats, fault_plan=None,
        fault_stats=FaultStats(), deadline_stats=deadline_stats,
        counter=InferenceCounter(), num_videos=num_videos,
        termination=TerminationState(), filename_queue=queue.Queue(),
        local_queue=queue.Queue(), inject_queue=queue.Queue(),
        num_markers=1, seed=11)
    return client


def _window_entry(client, seq, rid, deadline_s=None):
    card = TimeCard(rid)
    if deadline_s is not None:
        card.deadline_s = deadline_s
    frame = wire.encode_frame(wire.REQ,
                              wire.encode_req("video-%d" % rid, card),
                              seq=seq)
    from rnb_tpu.netedge import _WindowEntry
    client._window[seq] = _WindowEntry(seq, "video-%d" % rid, card,
                                       frame)
    client.board.note_enqueue(NET_LANE)
    return card


def _data_payload(rid, deadline_s=None):
    rows = np.full((1, 2), float(rid), np.float32)
    card = TimeCard(rid)
    if deadline_s is not None:
        card.deadline_s = deadline_s
    return wire.encode_data(PaddedBatch.from_rows(rows, 2), rid, card)


def test_resend_dedup_dispatches_exactly_once():
    """Ack lost -> resend -> the response arrives twice; the second
    copy hits the dedup ledger, never the inject queue."""
    client = _client()
    _window_entry(client, seq=1, rid=0)
    payload = _data_payload(0)
    client._on_data(1, payload)           # first arrival: dispatched
    client._on_data(1, payload)           # resend's twin: dropped
    assert client.inject_queue.qsize() == 1
    snap = client.stats.snapshot()
    assert snap["dup_arrivals"] == 1
    assert snap["dedup_drops"] == 1
    assert client._finalizing == 0        # drain gate fully released
    # dispatched work completes (and counts) DOWNSTREAM — the edge
    # itself disposes nothing on the success path
    assert client.counter.value == 0


def test_ack_then_data_settles_once():
    client = _client()
    _window_entry(client, seq=5, rid=2)
    client._on_ack(5)
    client._on_ack(5)                     # duplicate ack: counted once
    assert client.stats.snapshot()["frames_acked"] == 1
    client._on_data(5, _data_payload(2))
    assert client.inject_queue.qsize() == 1
    assert client.stats.snapshot()["dup_arrivals"] == 0


def test_deadline_expiry_sheds_at_the_netedge_site():
    """A response whose every constituent deadline has passed is shed
    at the receive boundary — site 'netedge:deadline_expired' — and
    still terminates exactly once (disposed, never injected)."""
    deadline_stats = DeadlineStats()
    client = _client(deadline_stats=deadline_stats)
    past = time.time() - 10.0
    _window_entry(client, seq=1, rid=0, deadline_s=past)
    client._on_data(1, _data_payload(0, deadline_s=past))
    assert client.inject_queue.qsize() == 0
    site = deadline_site("netedge")
    assert site == "netedge:deadline_expired"
    assert deadline_stats.snapshot()["sites"] == {site: 1}
    assert client.fault_stats.snapshot()["shed_sites"] == {site: 1}
    assert client.counter.value == 1
    # an unexpired response on the same run dispatches normally
    future = time.time() + 60.0
    _window_entry(client, seq=2, rid=1, deadline_s=future)
    client._on_data(2, _data_payload(1, deadline_s=future))
    assert client.inject_queue.qsize() == 1


def test_beat_staleness_walks_the_board_to_open():
    """In-flight work + a silent peer: the dispatcher's idle ticks
    (route_filter consults, NEVER beat()) walk the lane
    healthy -> suspect -> open on staleness alone."""
    client = _client(health=HealthSettings(suspect_after_ms=30,
                                           open_after_ms=80,
                                           probe_interval_ms=60))
    client.board.beat(NET_LANE)
    _window_entry(client, seq=1, rid=0)   # in-flight, then... silence
    assert client.board.state(NET_LANE) == "healthy"
    deadline = time.monotonic() + 2.0
    while client.board.state(NET_LANE) != "suspect" \
            and time.monotonic() < deadline:
        client._tick()
        time.sleep(0.01)
    assert client.board.state(NET_LANE) == "suspect"
    while client.board.state(NET_LANE) != "open" \
            and time.monotonic() < deadline:
        client._tick()
        time.sleep(0.01)
    assert client.board.state(NET_LANE) == "open"
    assert client.stats.snapshot()["open_before_timeout"] == 0  # pre-finalize
    # a settle while open is still honored (the response dispatches)
    client._on_data(1, _data_payload(0))
    assert client.inject_queue.qsize() == 1


def test_dead_letter_fails_the_request_exactly_once():
    client = _client()
    card = _window_entry(client, seq=3, rid=1)
    client._dead_letter(3)
    assert card.status == "failed"
    assert card.failure_reason == "net_corrupt"
    assert client.fault_stats.snapshot()["failure_reasons"] \
        == {"net_corrupt": 1}
    assert client.counter.value == 1
    client._dead_letter(3)                # idempotent on unknown seq
    assert client.counter.value == 1


# -- two-process end-to-end with injected faults ----------------------

def _netedge_config(extra_root=None, netedge=None):
    cfg = {
        "video_path_iterator":
            "tests.pipeline_helpers.CountingPathIterator",
        "netedge": dict({
            "enabled": True, "spawn": True, "beat_ms": 100,
            "io_timeout_ms": 2000, "max_retries": 3,
            "backoff_ms": 20, "resend_window": 4,
        }, **(netedge or {})),
        "pipeline": [
            {"model": "tests.pipeline_helpers.TinyLoader",
             "queue_groups": [{"devices": [0], "out_queues": [0]}],
             "num_shared_tensors": 8},
            {"model": "tests.pipeline_helpers.TinySink",
             "queue_groups": [{"devices": [0], "in_queue": 0}]},
        ],
    }
    cfg.update(extra_root or {})
    return cfg


def test_two_process_e2e_with_injected_net_faults(tmp_path,
                                                  monkeypatch):
    """net_corrupt dead-letters exactly one request on the wire;
    net_timeout wedges the peer briefly (beats pause, the io timeout
    classifies it); every request still terminates exactly once and
    the offline --check invariants hold."""
    monkeypatch.setenv("PYTHONPATH", REPO)
    from rnb_tpu.benchmark import run_benchmark
    cfg = _netedge_config(extra_root={"fault_plan": {
        "seed": 5,
        "faults": [
            {"kind": "net_corrupt", "request_ids": [3]},
            {"kind": "net_timeout", "request_ids": [6], "ms": 2500},
        ],
    }})
    path = os.path.join(str(tmp_path), "chaos.json")
    with open(path, "w") as f:
        json.dump(cfg, f)
    res = run_benchmark(path, mean_interval_ms=0, num_videos=10,
                        queue_size=50, log_base=str(tmp_path / "logs"),
                        print_progress=False, seed=5)
    assert res.termination_flag == 0
    assert res.net_err_corrupt == 1
    assert res.num_failed == 1            # the corrupt frame's request
    assert res.net_err_timeout >= 1       # the wedge was classified
    assert res.net_window_stranded == 0
    assert res.net_frames_sent \
        == res.net_frames_acked + res.net_resent_pending
    assert res.net_dedup_drops == res.net_dup_arrivals
    import parse_utils
    assert parse_utils.check_job(res.log_dir) == []


def test_net_faults_without_netedge_are_rejected(tmp_path):
    from rnb_tpu.benchmark import run_benchmark
    cfg = _netedge_config(extra_root={
        "netedge": {"enabled": False},
        "fault_plan": {"seed": 1, "faults": [
            {"kind": "net_reset", "request_ids": [0]}]},
    })
    path = os.path.join(str(tmp_path), "bad.json")
    with open(path, "w") as f:
        json.dump(cfg, f)
    with pytest.raises(ValueError, match="net"):
        run_benchmark(path, mean_interval_ms=0, num_videos=2,
                      queue_size=10, log_base=str(tmp_path / "logs"),
                      print_progress=False)


# -- netedge-off byte-stability ---------------------------------------

def test_netedge_off_keeps_logs_byte_stable(tmp_path):
    from rnb_tpu.benchmark import run_benchmark
    cfg = _netedge_config()
    del cfg["netedge"]
    path = os.path.join(str(tmp_path), "plain.json")
    with open(path, "w") as f:
        json.dump(cfg, f)
    res = run_benchmark(path, mean_interval_ms=0, num_videos=8,
                        queue_size=50, log_base=str(tmp_path / "logs"),
                        print_progress=False)
    assert res.termination_flag == 0
    assert res.net_frames_sent == 0 and res.net_err_total == 0
    with open(os.path.join(res.log_dir, "log-meta.txt")) as f:
        meta_text = f.read()
    assert "Net:" not in meta_text
    assert "Net errors:" not in meta_text
    # the stamp schema is exactly the pre-netedge set
    tables = [n for n in os.listdir(res.log_dir) if "group" in n]
    with open(os.path.join(res.log_dir, tables[0])) as f:
        header = f.readline().split()
    assert header == ["enqueue_filename", "runner0_start",
                      "inference0_start", "inference0_finish",
                      "runner1_start", "inference1_start",
                      "inference1_finish", "device0", "device1"]
