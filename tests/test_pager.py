"""Paged device memory (rnb_tpu/pager.py + rnb_tpu/ops/pages.py + the
paged ClipCache mode + feature pages).

Contract under test: the gather-from-pages Pallas kernel body is
bit-identical to its masked-jnp twin under ``interpret=True``; the
donated page writer publishes exact rows (clamp-padded tails landing
in dead page rows); the page allocator's accounting foots (``allocs ==
frees + live`` at every quiescent point); eviction under a pinned
gather parks pages in limbo and never recycles them under the plan;
the paged clip cache round-trips entries with no oversize skips below
arena size; feature-page hits are bit-identical to re-running the
forward (they ARE the original forward's rows); and the
insert-after-success rule holds on both fault paths — a contained
mid-pool decode failure and a deadline-expired shed never insert
feature pages and leak no pins.
"""

import os

import numpy as np
import pytest

from rnb_tpu.pager import (DEFAULT_ARENA_MB, Pager, PagerSettings)
from rnb_tpu.telemetry import TimeCard, TimeCardList

LS = (1, 1, 1, 1)


def _pager(page_rows=2, pool_mb=None, feature=False):
    return Pager(PagerSettings(page_rows=page_rows, pool_mb=pool_mb,
                               feature_cache=feature))


# -- the primitives (ops/pages.py) ------------------------------------

def test_gather_rows_interpret_matches_reference():
    # the TPU kernel body itself (scalar-prefetched source table,
    # pl.when slab-vs-passthrough) runs under interpret=True and must
    # be bit-identical to the masked jnp twin tier-1 exercises
    import jax.numpy as jnp
    from rnb_tpu.ops.pages import gather_rows, gather_rows_reference
    rng = np.random.RandomState(0)
    pool = rng.randint(0, 256, (5, 3, 128), np.uint8)   # 384 = 3*128
    slab = rng.randint(0, 256, (12, 3, 128), np.uint8)
    for src in ([-1, -1, -1, -1, -1],      # all-miss: pure passthrough
                [0, 1, 2, 3, 4],           # all-hit
                [7, -1, 0, -1, 11],        # mixed, unordered sources
                [3, 3, -1, 3, -1]):        # repeated source rows
        src = np.asarray(src, np.int32)
        ref = np.asarray(gather_rows_reference(
            jnp.asarray(pool), jnp.asarray(slab), src))
        out = np.asarray(gather_rows(
            jnp.asarray(pool), jnp.asarray(slab), src, interpret=True))
        assert np.array_equal(out, ref), src
        # the contract in plain numpy: byte moves, never arithmetic
        want = pool.copy()
        for i, s in enumerate(src):
            if s >= 0:
                want[i] = slab[s]
        assert np.array_equal(out, want), src


def test_gather_rows_non_lane_divisible_takes_reference_path():
    # per-row bytes not divisible by 128 lanes: the jitted masked-jnp
    # reference serves the identical contract
    import jax.numpy as jnp
    from rnb_tpu.ops.pages import gather_rows
    rng = np.random.RandomState(1)
    pool = rng.standard_normal((4, 7)).astype(np.float32)
    slab = rng.standard_normal((6, 7)).astype(np.float32)
    src = np.asarray([5, -1, 0, -1], np.int32)
    out = np.asarray(gather_rows(jnp.asarray(pool), jnp.asarray(slab),
                                 src))
    want = pool.copy()
    want[0], want[2] = slab[5], slab[0]
    assert np.array_equal(out, want)


def test_write_rows_page_publishes_exact_rows():
    import jax.numpy as jnp
    from rnb_tpu.ops.pages import write_rows_page
    rng = np.random.RandomState(2)
    slab = jnp.zeros((8, 16), jnp.float32)
    src_pool = jnp.asarray(rng.standard_normal((5, 16)), jnp.float32)
    # page_rows=4 write of 3 valid rows starting at pool row 1: the
    # index vector is clamp-padded to fixed length, the padded tail
    # repeats the last valid row (dead page rows no gather references)
    idx = np.minimum(1 + np.arange(4), 1 + 3 - 1).astype(np.int32)
    slab = write_rows_page(slab, src_pool, idx, 4)
    got = np.asarray(slab)
    assert np.array_equal(got[4:7], np.asarray(src_pool)[1:4])
    assert np.array_equal(got[7], np.asarray(src_pool)[3])  # clamp pad
    assert not got[:4].any()                 # other pages untouched


def test_page_writer_is_one_jit_signature():
    # the compilestats discipline: however entries are sized, the
    # (slab, pool) shape pair compiles exactly once — the index
    # vector's fixed page_rows length is what makes that true
    from rnb_tpu.ops.pages import _page_writer_jit
    import jax.numpy as jnp
    slab = jnp.zeros((8, 16), jnp.float32)
    pool = jnp.ones((5, 16), jnp.float32)
    writer = _page_writer_jit()
    for dst, idx in ((0, [0, 0, 0, 0]), (4, [1, 2, 3, 4])):
        slab = write_stable = writer(slab, pool,
                                     np.asarray(idx, np.int32),
                                     np.int32(dst))
    assert writer._cache_size() == 1


# -- settings / sizing ------------------------------------------------

def test_pager_settings_from_config():
    assert PagerSettings.from_config(None) is None
    assert PagerSettings.from_config({}) is None
    assert PagerSettings.from_config({"enabled": False}) is None
    s = PagerSettings.from_config({"enabled": True})
    assert s.page_rows == 4 and s.pool_mb is None \
        and not s.feature_cache
    s = PagerSettings.from_config(
        {"enabled": True, "page_rows": 2, "pool_mb": 1.5,
         "feature_cache": True})
    assert s.page_rows == 2 and s.pool_mb == 1.5 and s.feature_cache
    with pytest.raises(ValueError):
        PagerSettings.from_config({"enabled": True, "page_rows": 0})
    with pytest.raises(ValueError):
        PagerSettings.from_config({"enabled": True, "pool_mb": 0})


def test_resolve_budget_precedence():
    # explicit pool_mb > caller's figure > ledger size hint > default
    p = _pager(pool_mb=2)
    assert p.resolve_budget(123) == 2 << 20
    p = _pager()
    assert p.resolve_budget(123) == 123
    p.size_hint(456)
    assert p.resolve_budget() == 456
    assert _pager().resolve_budget() == DEFAULT_ARENA_MB << 20


# -- allocator accounting ---------------------------------------------

def test_arena_alloc_free_foots():
    p = _pager(page_rows=2)
    # 16-byte rows, 2-row pages: a 128-byte budget is 4 pages
    a = p.create_arena("clips", (16,), np.uint8, budget_bytes=128)
    assert a.num_pages == 4 and a.page_bytes == 32
    assert a.pages_needed(1) == 1 and a.pages_needed(3) == 2
    with p.lock:
        pg1 = a.alloc_locked(2)
        pg2 = a.alloc_locked(2)
        assert a.alloc_locked(1) is None       # exhausted: counted
        a.free_locked(pg1)
        pg3 = a.alloc_locked(1)
    assert pg1 is not None and pg2 is not None and pg3 is not None
    snap = p.snapshot()
    assert snap["alloc_fails"] == 1
    # the --check invariant, at a quiescent point: every allocated
    # page is either freed or live
    assert snap["allocs"] == snap["frees"] + snap["live"]
    assert snap["limbo"] == 0


def test_flat_rows_addressing():
    p = _pager(page_rows=2)
    a = p.create_arena("clips", (16,), np.uint8, budget_bytes=128)
    # entry rows 0..2 over pages (3, 1): rows 0,1 in page 3, row 2 in
    # page 1 — flat slab rows 6, 7, 2
    assert a.flat_rows((3, 1), 3).tolist() == [6, 7, 2]
    assert a.flat_rows((0,), 1).tolist() == [0]


def test_eviction_under_pinned_gather_parks_pages_in_limbo():
    # the crash the pin/limbo discipline prevents: an entry is evicted
    # WHILE a hit's gather is in flight; its pages must not re-enter
    # the free list (and so can never be rewritten) until the plan
    # releases
    import jax.numpy as jnp
    from rnb_tpu.cache import ClipCache
    p = _pager(page_rows=1)
    a = p.create_arena("clips", (16,), np.float32, budget_bytes=256)
    assert a.num_pages == 4               # two 2-page entries fill it
    cache = ClipCache(1.0)
    cache.attach_arena(a)
    rng = np.random.RandomState(3)
    pool_a = jnp.asarray(rng.standard_normal((2, 16)), jnp.float32)
    pool_x = jnp.asarray(rng.standard_normal((2, 16)), jnp.float32)
    pool_b = jnp.asarray(rng.standard_normal((2, 16)), jnp.float32)
    assert cache.insert_pages(("va",), pool_a, 0, 2)
    assert cache.insert_pages(("vx",), pool_x, 0, 2)
    plan = cache.acquire(("va",))              # pinned hit in flight
    assert plan is not None and plan.valid == 2
    cache.acquire(("vx",)).release()           # vx is now MRU: the
    assert cache.num_hits == 2                 # pinned va is LRU
    # pressure: vb's insert evicts va first — its pages are pinned so
    # they park in limbo, the loop moves on to vx whose pages free
    assert cache.insert_pages(("vb",), pool_b, 0, 2)
    snap = p.snapshot()
    assert snap["limbo"] == 2                  # parked, not recycled
    assert cache.num_evictions == 2
    # the in-flight gather still reads va's exact bytes: vb could not
    # have reused those slab rows
    dest = jnp.zeros((2, 16), jnp.float32)
    out = np.asarray(a.gather(dest, plan.src_rows))
    assert np.array_equal(out, np.asarray(pool_a))
    # release: limbo pages re-enter the free list, accounting foots
    plan.release()
    snap = p.snapshot()
    assert snap["limbo"] == 0
    assert snap["allocs"] == snap["frees"] + snap["live"]
    # and the freed pages are genuinely reusable now
    assert cache.insert_pages(("vc",), pool_b, 0, 2)
    assert cache.acquire(("vc",)).release() is None


def test_eviction_pressure_with_pins_skips_insert_never_blocks():
    # every page pinned (directly or in limbo): an insert skips
    # (False) instead of blocking or stealing pinned pages
    import jax.numpy as jnp
    from rnb_tpu.cache import ClipCache
    p = _pager(page_rows=1)
    a = p.create_arena("clips", (16,), np.float32, budget_bytes=128)
    assert a.num_pages == 2
    cache = ClipCache(1.0)
    cache.attach_arena(a)
    pool = jnp.zeros((2, 16), jnp.float32)
    assert cache.insert_pages(("va",), pool, 0, 2)
    plan = cache.acquire(("va",))
    # vb's insert evicts va (collateral of the pressure loop) but its
    # pinned pages only reach limbo — no free page appears, so the
    # insert is skipped rather than blocked
    assert not cache.insert_pages(("vb",), pool, 0, 2)
    assert not cache.contains(("va",))
    assert p.snapshot()["limbo"] == 2
    plan.release()
    snap = p.snapshot()
    assert snap["limbo"] == 0
    assert snap["allocs"] == snap["frees"] + snap["live"]
    assert cache.insert_pages(("vc",), pool, 0, 2)


def test_paged_clipcache_roundtrip_and_counters():
    import jax.numpy as jnp
    from rnb_tpu.cache import ClipCache
    p = _pager(page_rows=2)
    a = p.create_arena("clips", (16,), np.float32, budget_bytes=512)
    cache = ClipCache(1.0)
    cache.attach_arena(a)
    assert cache.paged and cache.capacity_bytes == a.nbytes
    rng = np.random.RandomState(4)
    pool = jnp.asarray(rng.standard_normal((4, 16)), jnp.float32)
    # 3 valid rows -> 2 pages, 1 dead tail row
    assert cache.insert_pages(("v0",), pool, 1, 3)
    assert not cache.insert_pages(("v0",), pool, 1, 3)  # first writer
    assert cache.insert_pages(("v1",), pool, 0, 1)
    assert cache.num_inserts == 2
    assert cache.resident_bytes == 3 * a.page_bytes
    plan = cache.acquire(("v0",))
    assert plan is not None and plan.valid == 3
    dest = jnp.zeros((4, 16), jnp.float32)
    src = np.full((4,), -1, np.int32)
    src[:3] = plan.src_rows
    out = np.asarray(a.gather(dest, src))
    assert np.array_equal(out[:3], np.asarray(pool)[1:4])
    assert not out[3:].any()
    plan.release()
    assert cache.acquire(("nope",)) is None
    assert cache.num_hits == 1 and cache.num_misses == 1
    snap = p.snapshot()
    assert snap["gathers"] == 1 and snap["gather_rows"] == 3
    assert snap["allocs"] == snap["frees"] + snap["live"]


def test_paged_insert_oversize_is_counted_and_skipped():
    import jax.numpy as jnp
    from rnb_tpu.cache import ClipCache
    p = _pager(page_rows=2)
    a = p.create_arena("clips", (16,), np.float32, budget_bytes=128)
    cache = ClipCache(1.0)
    cache.attach_arena(a)
    pool = jnp.zeros((8, 16), jnp.float32)
    # 5 rows need 3 pages; the whole arena holds 1: the ONLY size an
    # entry can still exceed — no contiguity requirement remains
    assert not cache.insert_pages(("big",), pool, 0, 5)
    assert cache.num_oversize == 1
    assert p.snapshot()["allocs"] == 0       # nothing allocated for it


# -- feature pages ----------------------------------------------------

def test_feature_cache_roundtrip_fingerprint_and_lru():
    import jax.numpy as jnp
    p = _pager(page_rows=2, feature=True)
    a = p.create_arena("features", (16,), np.float32,
                       budget_bytes=128,
                       gather_keys=("feature_gathers",
                                    "feature_gather_rows"))
    assert not p.feature.ready
    assert p.feature.acquire(("v0",)) is None   # counted, pre-attach
    p.feature.attach(a, ("fp", 1))
    rng = np.random.RandomState(5)
    out_a = jnp.asarray(rng.standard_normal((2, 16)), jnp.float32)
    out_b = jnp.asarray(rng.standard_normal((2, 16)), jnp.float32)
    assert p.feature.insert(("v0",), out_a, 0, 2)
    assert not p.feature.insert(("v0",), out_a, 0, 2)  # first writer
    plan = p.feature.acquire(("v0",))
    assert plan is not None
    got = np.asarray(a.gather(jnp.zeros((2, 16), jnp.float32),
                              plan.src_rows))
    assert np.array_equal(got, np.asarray(out_a))   # the exact rows
    plan.release()
    # LRU pressure: the 1-entry arena evicts v0 for v1
    assert p.feature.insert(("v1",), out_b, 0, 2)
    assert not p.feature.contains(("v0",))
    assert p.feature.contains(("v1",))
    snap = p.snapshot()
    assert snap["feature_lookups"] == 2
    assert snap["feature_hits"] == 1
    assert snap["feature_inserts"] == 2
    assert snap["feature_evictions"] == 1
    assert snap["feature_gathers"] == 1
    assert snap["feature_gather_rows"] == 2
    assert snap["feature_inserts"] == (snap["feature_entries"]
                                       + snap["feature_evictions"])
    assert snap["allocs"] == snap["frees"] + snap["live"]


def test_feature_hit_logits_bit_identical_to_forward(monkeypatch):
    # the golden-logit gate: a feature-page hit gathers the EXACT
    # rows the original forward produced — bit parity, not tolerance
    import jax
    import jax.numpy as jnp
    from rnb_tpu.models.r2p1d.model import R2P1DRunner
    from rnb_tpu.pager import GatherPlan
    from rnb_tpu.stage import RaggedBatch
    runner = R2P1DRunner(jax.devices()[0], start_index=1, end_index=5,
                         num_classes=8, layer_sizes=LS, max_rows=4,
                         consecutive_frames=2, num_warmups=1,
                         pixel_path="rgb", ragged=True,
                         ragged_pool_rows=4, ragged_chunk_rows=2)
    pager = _pager(page_rows=2, feature=True)
    runner.enable_pager(pager)
    assert pager.feature.ready
    rng = np.random.RandomState(6)
    pool = jnp.asarray(rng.standard_normal(
        (4, 2, 112, 112, 3)).astype(np.float32), jnp.bfloat16)
    # miss: the forward runs; the loader-side stamp triggers the
    # insert-after-success publish
    tc = TimeCard(0)
    tc.feature_insert = (("vid0", "cfg"), 0, 3)
    (miss,), _, _ = runner((RaggedBatch(pool, 3, (0, 3)),), None, tc)
    assert getattr(tc, "feature_insert", None) is None  # consumed
    assert pager.feature.contains(("vid0", "cfg"))
    # hit: a stub pool rides in; the runner gathers the cached rows
    # over its preallocated zero pool and skips the forward entirely
    plan = pager.feature.acquire(("vid0", "cfg"))
    tc2 = TimeCard(1)
    tc2.feature_hit = True
    tc2.feature_plan = plan
    stub = jnp.zeros_like(pool)
    (hit,), _, _ = runner((RaggedBatch(stub, 3, (0, 3)),), None, tc2)
    assert getattr(tc2, "feature_plan", None) is None   # consumed
    assert np.array_equal(np.asarray(hit.data)[:3],
                          np.asarray(miss.data)[:3])
    assert not np.asarray(hit.data)[3:].any()   # zero pool tail
    assert hit.valid == 3
    snap = pager.snapshot()
    assert snap["feature_gathers"] == 1
    assert snap["feature_gather_rows"] == 3
    assert snap["allocs"] == snap["frees"] + snap["live"]
    assert snap["limbo"] == 0                   # plan released


def test_feature_cache_requires_final_stage_and_ragged():
    import jax
    from rnb_tpu.models.r2p1d.model import R2P1DRunner
    mid = R2P1DRunner(jax.devices()[0], start_index=1, end_index=4,
                      num_classes=8, layer_sizes=LS, max_rows=4,
                      consecutive_frames=2, num_warmups=0,
                      ragged=True, ragged_pool_rows=4)
    with pytest.raises(ValueError):
        mid.enable_pager(_pager(feature=True))
    bucketed = R2P1DRunner(jax.devices()[0], start_index=1,
                           end_index=5, num_classes=8, layer_sizes=LS,
                           max_rows=4, consecutive_frames=2,
                           num_warmups=0)
    with pytest.raises(ValueError):
        bucketed.enable_pager(_pager(feature=True))


# -- fault paths: insert-after-success --------------------------------

def _write_y4m_dataset(tmp_path, n=6, frames=8):
    from rnb_tpu.decode import write_y4m
    rng = np.random.default_rng(7)
    paths = []
    for i in range(n):
        p = os.path.join(str(tmp_path), "v%02d.y4m" % i)
        write_y4m(p, rng.integers(0, 256, (frames, 32, 32, 3),
                                  dtype=np.uint8))
        paths.append(p)
    return paths


def _paged_loader(pager, **kw):
    import jax
    from rnb_tpu.models.r2p1d.model import R2P1DFusingLoader
    kw.setdefault("num_clips_population", [1])
    kw.setdefault("weights", [1])
    kw.setdefault("num_warmups", 0)
    kw.setdefault("max_clips", 4)
    kw.setdefault("consecutive_frames", 2)
    kw.setdefault("ragged", True)
    kw.setdefault("cache_mb", 4)
    loader = R2P1DFusingLoader(jax.devices()[0], **kw)
    loader.enable_pager(pager)
    if pager.feature is not None and not pager.feature.ready:
        # stand in for the consuming stage: a tiny logit arena so the
        # loader-side probe/stamp machinery is live
        arena = pager.create_arena(
            "features", (8,), np.float32, budget_bytes=1 << 12,
            gather_keys=("feature_gathers", "feature_gather_rows"))
        pager.feature.attach(arena, ("test-fingerprint",))
    return loader


def _drain(loader, emitted):
    while True:
        out = loader.flush()
        if out is None:
            return
        emitted.append(out)


def test_decode_failure_never_inserts_feature_pages(tmp_path):
    # a contained mid-pool decode failure is parked (take_failed) and
    # must neither stamp a feature insert nor leak page pins; its
    # pool-mates' stamps survive
    import time as _time
    from rnb_tpu.faults import CorruptVideoError
    from rnb_tpu.models.r2p1d.model import _FuseRecord
    paths = _write_y4m_dataset(tmp_path, n=4)
    pager = _pager(page_rows=2, feature=True)
    loader = _paged_loader(pager, fuse=5, max_hold_ms=10000.0,
                           depth=50)
    emitted = []
    cards = [TimeCard(i) for i in range(5)]
    for card, p in zip(cards[:2], paths[:2]):
        out = loader(None, p, card)
        if out[2] is not None:
            emitted.append(out)

    class BoomHandle:
        n = 1
        out = None
        error = None
        slot = None
        row0 = 0
        ready = True
        gather_plan = None
        feature_plan = None
        cached = None

        def wait(self, v):
            raise CorruptVideoError("mid-pool corruption")

    boom = _FuseRecord(BoomHandle(), "boom.y4m", cards[2])
    boom.t_ready = _time.monotonic()
    loader._inflight.append(boom)
    for card, p in zip(cards[3:], paths[2:]):
        out = loader(None, p, card)
        if out[2] is not None:
            emitted.append(out)
    _drain(loader, emitted)
    failed = loader.take_failed()
    assert [tc.id for tc, _r in failed] == [2]
    # the failed card carries NO insert obligation — only cards whose
    # transfer succeeded are stamped (insert-after-success)
    assert getattr(cards[2], "feature_insert", None) is None
    assert not pager.feature.contains(("boom.y4m",))
    survivors = [tc for _, _, tcl in emitted for tc in tcl.time_cards]
    assert sorted(tc.id for tc in survivors) == [0, 1, 3, 4]
    for tc in survivors:
        job = getattr(tc, "feature_insert", None)
        if job is not None:
            key, row0, n = job
            assert n >= 1
    # no pin leaked: the allocator foots at quiescence
    snap = pager.snapshot()
    assert snap["limbo"] == 0
    assert snap["allocs"] == snap["frees"] + snap["live"]


def test_deadline_shed_releases_plans_and_never_inserts(tmp_path):
    # a feature-page hit whose card expires in the hold window is shed
    # BEFORE its gather dispatches: the plan's pin is released (no
    # limbo leak), no feature insert fires, and the counters keep
    # feature_gathers <= feature_hits
    import jax.numpy as jnp
    paths = _write_y4m_dataset(tmp_path, n=2)
    pager = _pager(page_rows=2, feature=True)
    loader = _paged_loader(pager, fuse=4, max_hold_ms=10000.0,
                           depth=50)
    # seed the feature cache with an entry for paths[0] under the
    # loader's own content key
    from rnb_tpu.cache import content_key
    fkey = content_key(paths[0], loader._cache_cfg)
    rows = jnp.asarray(np.random.RandomState(8)
                       .standard_normal((2, 8)).astype(np.float32))
    assert pager.feature.insert(fkey, rows, 0, 1)
    # a feature hit emits standalone and never enters the hold window,
    # so exercise the shed on the PLAN-carrying record directly: stamp
    # an already-expired deadline, then submit the hit
    tc = TimeCard(0)
    tc.deadline_s = 1e-9          # epoch-anchored: long expired
    out = loader(None, paths[0], tc)
    if out[2] is not None:
        # the standalone feature-hit emission happened before any
        # deadline check — the runner-side shed covers that leg; what
        # must hold HERE is that the plan rode the card, pinned
        assert getattr(tc, "feature_hit", False)
        plan = tc.feature_plan
        assert plan is not None
        # the executor's shed path releases plans via card drop — the
        # plan release must be idempotent and return pages to freelist
        plan.release()
        tc.feature_plan = None
    snap = pager.snapshot()
    assert snap["feature_hits"] == 1
    assert snap["feature_gathers"] == 0     # shed before dispatch
    assert snap["limbo"] == 0
    assert snap["allocs"] == snap["frees"] + snap["live"]
    # the paged-hit hold-window shed: a clip-cache paged hit parked in
    # _ready with every card expired is dropped; _release_handle_plan
    # unpins, so counted hit rows bound gather rows from above
    import time as _time
    loader2 = _paged_loader(pager2 := _pager(page_rows=2,
                                             feature=False),
                            fuse=4, max_hold_ms=10000.0, depth=50)
    emitted = []
    tc0 = TimeCard(0)
    out = loader2(None, paths[1], tc0)
    if out[2] is not None:
        emitted.append(out)
    _drain(loader2, emitted)      # decode+emit: inserts pages
    assert sum(len(tcl) for _, _, tcl in emitted) == 1
    tc1 = TimeCard(1)
    tc1.deadline_s = 1e-9
    out = loader2(None, paths[1], tc1)   # paged hit, expired card
    assert getattr(tc1, "cache_hit", False)
    # force the hold-window sweep without emitting
    loader2._drop_expired_ready()
    shed = loader2.take_shed()
    assert [tc.id for tc, _site in shed] == [1]
    assert getattr(tc1, "feature_insert", None) is None
    snap2 = pager2.snapshot()
    assert snap2["limbo"] == 0                 # pin released on shed
    assert snap2["gathers"] == 0               # never dispatched
    assert snap2["allocs"] == snap2["frees"] + snap2["live"]
