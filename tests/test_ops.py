"""Pallas ingest-preprocess kernel: numerics parity with the jnp path.

The CPU test backend cannot run compiled TPU kernels, so the kernel
body itself is exercised through the Pallas interpreter and must match
``normalize_u8_reference`` bit-for-bit; the dispatching wrapper is
checked to fall back cleanly off-TPU.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.experimental import pallas as pl

from rnb_tpu.ops import normalize_u8
from rnb_tpu.ops.preprocess import (LANES, _normalize_kernel,
                                    normalize_u8_reference)


def _run_interpret(x, dtype, block_rows):
    flat = x.reshape(-1, LANES)
    rows = flat.shape[0]
    out = pl.pallas_call(
        _normalize_kernel,
        out_shape=jax.ShapeDtypeStruct((rows, LANES), dtype),
        grid=(pl.cdiv(rows, block_rows),),
        in_specs=[pl.BlockSpec((block_rows, LANES), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((block_rows, LANES), lambda i: (i, 0)),
        interpret=True,
    )(flat)
    return out.reshape(x.shape)


@pytest.mark.parametrize("shape", [(2, 2, 16, 16, 3), (15, 8, 112, 8, 2)])
def test_kernel_matches_reference(shape):
    rng = np.random.default_rng(0)
    x = rng.integers(0, 256, shape, dtype=np.uint8)
    got = _run_interpret(jnp.asarray(x), jnp.float32, block_rows=8)
    want = normalize_u8_reference(jnp.asarray(x), jnp.float32)
    # FMA contraction inside the kernel may differ by 1 ulp
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=0, atol=2e-7)


def test_kernel_ragged_final_block():
    # rows not divisible by the block: Pallas masks the tail block
    rng = np.random.default_rng(1)
    x = rng.integers(0, 256, (40, LANES), dtype=np.uint8)  # 40 = 8*5
    got = _run_interpret(jnp.asarray(x), jnp.float32, block_rows=16)
    want = normalize_u8_reference(jnp.asarray(x), jnp.float32)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=0, atol=2e-7)


def test_range_endpoints():
    x = jnp.asarray([[0] * LANES, [255] * LANES], dtype=jnp.uint8)
    y = np.asarray(_run_interpret(x, jnp.float32, block_rows=8))
    assert y.min() == pytest.approx(-1.0)
    assert y.max() == pytest.approx(1.0)


def test_kernel_matches_reference_bf16():
    # parity at the PRODUCTION dtype: both paths must round to bf16
    # exactly once, from the same f32 intermediate
    x = jnp.arange(256, dtype=jnp.uint8).reshape(2, LANES)
    got = _run_interpret(x, jnp.bfloat16, block_rows=8)
    want = normalize_u8_reference(x, jnp.bfloat16)
    np.testing.assert_array_equal(np.asarray(got, np.float32),
                                  np.asarray(want, np.float32))


def test_empty_input_dispatch():
    x = jnp.zeros((0, 8, LANES), dtype=jnp.uint8)
    y = normalize_u8(x)
    assert y.shape == (0, 8, LANES) and y.dtype == jnp.bfloat16


def test_dispatch_off_tpu_falls_back():
    # On the CPU test backend the wrapper must take the jnp path and
    # still produce the contract numerics in bf16.
    x = np.full((4, LANES), 128, dtype=np.uint8)
    y = normalize_u8(jnp.asarray(x))
    assert y.dtype == jnp.bfloat16
    want = normalize_u8_reference(jnp.asarray(x))
    np.testing.assert_array_equal(np.asarray(y, np.float32),
                                  np.asarray(want, np.float32))


def test_network_normalize_delegates():
    from rnb_tpu.models.r2p1d.network import normalize_u8 as net_norm
    x = np.full((2, LANES), 255, dtype=np.uint8)
    np.testing.assert_array_equal(
        np.asarray(net_norm(jnp.asarray(x)), np.float32),
        np.asarray(normalize_u8(jnp.asarray(x)), np.float32))
